"""Intra-entry restore overlap: single-large-array restore wall vs the
serial read+consume sum.

The buffered restore path only overlaps storage reads with consumption
ACROSS entries — within one entry, the full blob lands in memory before
the first byte is hashed, decompressed, or copied to device, so a single
large array's critical path is read + consume. The streaming read path
(sub-chunk pipeline, scheduler._ReadPipeline._stream_read_and_consume)
overlaps the two WITHIN the entry: the consumer verifies/decodes chunk N
while the plugin is already fetching N+1, collapsing the wall toward
max(read, consume).

Two legs:

- **throttled**: storage read latency is simulated (per-window sleep at
  a configured GB/s — the network-filesystem regime) and consume cost is
  simulated the same way (per-chunk sleep standing in for a slow
  hash/decompress pass, the dist_verify gate's slow-hasher regime). Both
  components are sleeps, so they genuinely overlap even on a 1-core CI
  box; the leg ASSERTS overlap_ratio >= 1.25 with a bit-exact restored
  array. This is the design claim, measured.
- **tmpfs**: real end-to-end ``Snapshot`` restore, streamed vs buffered
  (``TORCHSNAPSHOT_TPU_STREAM_READS=0``) on tmpfs, p50 over trials, with
  bit-exact checks — the restore-path counterpart of BENCH_r06's save
  legs, persisted as BENCH_r08.json by ``--emit``.

Usage: JAX_PLATFORMS=cpu python benchmarks/restore_overlap.py [mb] [sim_gbps] [--emit]
Emits one JSON line per leg; ``--emit`` also writes BENCH_r08.json.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    args = [a for a in sys.argv[1:] if a != "--emit"]
    emit = "--emit" in sys.argv[1:]
    mb = float(args[0]) if len(args) > 0 else 256.0
    # Slow enough that simulated transport/verify latency dominates the
    # real memcpy work even on a 1-core host — the overlap claim is
    # about hiding LATENCY, and the copies can't parallelize there.
    sim_gbps = float(args[1]) if len(args) > 1 else 0.4

    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer
    from torchsnapshot_tpu.io_types import ReadIO, ReadReq, ReadStream
    from torchsnapshot_tpu.manifest import ArrayEntry
    from torchsnapshot_tpu.scheduler import execute_read_reqs
    from torchsnapshot_tpu.serialization import dtype_to_string
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    nbytes = int(mb * 1e6)
    rows = nbytes // (1024 * 4)
    arr = np.arange(rows * 1024, dtype=np.float32).reshape(rows, 1024)

    read_bps = sim_gbps * 1e9
    consume_bps = sim_gbps * 1e9  # symmetric: max theoretical ratio 2x

    class ThrottledFS(FSStoragePlugin):
        """Simulated storage read latency proportional to bytes moved —
        the component a streamed restore hides under consumption."""

        def _pread_exact(self, fd, lo, hi):  # streamed windows
            time.sleep((hi - lo) / read_bps)  # executor thread: off the loop
            return FSStoragePlugin._pread_exact(fd, lo, hi)

        async def read(self, read_io):  # buffered whole-entry read
            await super().read(read_io)
            await asyncio.sleep(memoryview(read_io.buf).nbytes / read_bps)

    class ThrottledConsumer(ArrayBufferConsumer):
        """Simulated consume cost (slow verify/decompress regime):
        per-chunk sleep in the consumer's executor, so streamed consume
        overlaps the plugin's read-ahead exactly like real CRC work."""

        async def consume_buffer(self, buf, executor=None):
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                executor, time.sleep, memoryview(buf).nbytes / consume_bps
            )
            await super().consume_buffer(buf, executor)

        async def consume_stream(self, stream, executor=None):
            loop = asyncio.get_running_loop()

            async def throttled(chunks):
                async for chunk in chunks:
                    await loop.run_in_executor(
                        executor,
                        time.sleep,
                        memoryview(chunk).nbytes / consume_bps,
                    )
                    yield chunk

            await super().consume_stream(
                ReadStream(
                    path=stream.path, nbytes=stream.nbytes, chunks=throttled(stream.chunks)
                ),
                executor,
            )

    def mk_req(dst):
        # A real destination keeps the comparison honest: with a
        # callback-only consumer the buffered mmap path never faults the
        # payload's pages, so its "consume" would be artificially free.
        entry = ArrayEntry(
            location="payload",
            serializer="buffer_protocol",
            dtype=dtype_to_string(arr.dtype),
            shape=list(arr.shape),
            replicated=False,
        )
        consumer = ThrottledConsumer(entry, dst_view=dst)
        return ReadReq(path="payload", buffer_consumer=consumer)

    reps = int(os.environ.get("RESTORE_OVERLAP_REPS", "3"))
    tmp = tempfile.mkdtemp(prefix="restore_overlap_")
    results = {}
    try:
        loop = asyncio.new_event_loop()
        plugin = ThrottledFS(tmp)
        from torchsnapshot_tpu.io_types import WriteIO
        from torchsnapshot_tpu.scheduler import io_governor

        loop.run_until_complete(
            plugin.write(WriteIO(path="payload", buf=arr.tobytes()))
        )
        # Seed the governor with the simulated link's rate — in
        # production the telemetry bus feeds this from prior restores;
        # the auto policy then streams full-retention consumers on this
        # latency-bound "storage".
        io_governor().record_read("ThrottledFS", nbytes, nbytes / read_bps)

        # -- serial reference: full read, then full consume -------------
        read_s = consume_s = float("inf")
        for _ in range(reps):
            dst = np.zeros_like(arr)
            req = mk_req(dst)
            read_io = ReadIO(path="payload")
            t0 = time.perf_counter()
            loop.run_until_complete(plugin.read(read_io))
            read_s = min(read_s, time.perf_counter() - t0)
            t0 = time.perf_counter()
            loop.run_until_complete(
                req.buffer_consumer.consume_buffer(read_io.buf)
            )
            consume_s = min(consume_s, time.perf_counter() - t0)
            assert np.array_equal(dst, arr)
            del read_io
        serial_s = read_s + consume_s

        # -- streamed: one entry through the streaming read pipeline ----
        # 16 MB windows: enough chunks for a real pipeline, few enough
        # that per-chunk dispatch overhead stays well under the
        # simulated latency being hidden.
        streamed_s = float("inf")
        os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"] = str(16 << 20)
        try:
            for _ in range(reps):
                dst = np.zeros_like(arr)
                t0 = time.perf_counter()
                loop.run_until_complete(
                    execute_read_reqs([mk_req(dst)], plugin, 1 << 31, rank=0)
                )
                streamed_s = min(streamed_s, time.perf_counter() - t0)
                assert np.array_equal(dst, arr), "not bit-exact"
        finally:
            del os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"]

        overlap_ratio = serial_s / max(streamed_s, 1e-9)
        results["throttled"] = {
            "benchmark": "restore_overlap/throttled",
            "state_mb": mb,
            "sim_storage_gbps": sim_gbps,
            "read_s": round(read_s, 3),
            "consume_s": round(consume_s, 3),
            "serial_sum_s": round(serial_s, 3),
            "streamed_s": round(streamed_s, 3),
            "overlap_ratio": round(overlap_ratio, 2),
            "bit_exact": True,
        }
        print(json.dumps(results["throttled"]), flush=True)
        assert overlap_ratio >= 1.25, (
            f"read/consume overlap ratio {overlap_ratio:.2f} < 1.25 "
            f"(streamed {streamed_s:.2f}s vs serial {serial_s:.2f}s)"
        )
        loop.close()

        # -- tmpfs end-to-end: streamed vs buffered restore p50 ---------
        state = {"m": StateDict(w=arr)}
        base = "/dev/shm" if os.path.isdir("/dev/shm") else None
        tmp2 = tempfile.mkdtemp(prefix="restore_e2e_", dir=base)
        try:
            save_trials = []
            for _ in range(reps):
                shutil.rmtree(f"{tmp2}/snap", ignore_errors=True)
                t0 = time.perf_counter()
                Snapshot.take(f"{tmp2}/snap", state)
                save_trials.append(time.perf_counter() - t0)

            def restore_trials(mode):
                if mode is None:
                    os.environ.pop("TORCHSNAPSHOT_TPU_STREAM_READS", None)
                else:
                    os.environ["TORCHSNAPSHOT_TPU_STREAM_READS"] = mode
                trials = []
                try:
                    for _ in range(reps):
                        dst = {"m": StateDict(w=np.zeros_like(arr))}
                        t0 = time.perf_counter()
                        Snapshot(f"{tmp2}/snap").restore(dst)
                        trials.append(time.perf_counter() - t0)
                        assert np.array_equal(dst["m"]["w"], arr)
                finally:
                    os.environ.pop("TORCHSNAPSHOT_TPU_STREAM_READS", None)
                return trials

            # auto is what users get: on memcpy-speed tmpfs it keeps
            # full-retention consumers buffered (streaming only where it
            # wins); always/never bracket the two mechanisms.
            auto_trials = restore_trials(None)
            streamed_trials = restore_trials("always")
            buffered_trials = restore_trials("never")

            p50_auto = statistics.median(auto_trials)
            p50_streamed = statistics.median(streamed_trials)
            p50_buffered = statistics.median(buffered_trials)
            p50_save = statistics.median(save_trials)
            results["tmpfs"] = {
                "benchmark": "restore_overlap/tmpfs_restore",
                "state_mb": mb,
                "auto_restore_s": [round(t, 3) for t in auto_trials],
                "streamed_restore_s": [round(t, 3) for t in streamed_trials],
                "buffered_restore_s": [round(t, 3) for t in buffered_trials],
                "restore_p50_gbps": round(nbytes / 1e9 / p50_auto, 3),
                "streamed_restore_p50_gbps": round(
                    nbytes / 1e9 / p50_streamed, 3
                ),
                "buffered_restore_p50_gbps": round(
                    nbytes / 1e9 / p50_buffered, 3
                ),
                "save_p50_gbps": round(nbytes / 1e9 / p50_save, 3),
                "bit_exact": True,
            }
            print(json.dumps(results["tmpfs"]), flush=True)
        finally:
            shutil.rmtree(tmp2, ignore_errors=True)

        if emit:
            doc = {
                "metric": "snapshot_restore_throughput_1chip",
                "value": results["tmpfs"]["restore_p50_gbps"],
                "unit": "GB/s",
                "restore_p50_gbps": results["tmpfs"]["restore_p50_gbps"],
                "streamed_restore_p50_gbps": results["tmpfs"][
                    "streamed_restore_p50_gbps"
                ],
                "buffered_restore_p50_gbps": results["tmpfs"][
                    "buffered_restore_p50_gbps"
                ],
                "save_p50_gbps": results["tmpfs"]["save_p50_gbps"],
                "overlap_ratio_throttled": results["throttled"]["overlap_ratio"],
                "state_mb": mb,
                "platform": "cpu",
            }
            out_path = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "BENCH_r08.json",
            )
            with open(out_path, "w") as f:
                json.dump(doc, f)
                f.write("\n")
            print(f"wrote {out_path}", flush=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
