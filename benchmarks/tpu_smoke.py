"""Real-TPU hardware smoke: compile + run + finite-grad every op family.

The test suite forces a virtual CPU mesh (tests/conftest.py), and Pallas
interpret mode plus CPU lowering hide real-TPU type/lowering issues (a
vma mismatch in ring-flash's scan carries was only catchable on the
chip). This script validates the hardware paths in a few minutes:

- transformer forward+grad through the auto -> flash kernel route;
- flash / ring-flash / zigzag-flash vs the dense oracle (bf16);
- SSM LM forward+grad (associative-scan mixing);
- MoE einsum and sort dispatch paths (values must agree);
- a Snapshot round-trip of device arrays.

Run on a machine with a TPU: ``python benchmarks/tpu_smoke.py``.
Exits nonzero on any failure; prints one OK line per family.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> int:
    if jax.default_backend() != "tpu":
        print(f"not a TPU backend ({jax.default_backend()}); nothing to smoke")
        return 2

    # --- attention kernels vs dense oracle (bf16) ----------------------
    from jax.sharding import Mesh

    from torchsnapshot_tpu.ops import (
        dense_attention,
        flash_attention,
        ring_flash_attention_sharded,
        zigzag_ring_flash_attention_sharded,
    )

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (
        jax.random.normal(kk, (2, 512, 4, 64), jnp.bfloat16) for kk in ks
    )
    ref = dense_attention(q, k, v, causal=True).astype(jnp.float32)
    # All local devices: on a multi-chip host the ring actually rotates
    # K/V over ICI ppermute (S=512 divides 2/4/8-way rings); a single
    # chip still validates kernels + shard_map + custom VJP lowering.
    mesh1 = Mesh(np.array(jax.devices()), ("seq",))
    for name, fn in (
        ("flash", lambda q, k, v: flash_attention(q, k, v, causal=True)),
        ("ring_flash", lambda q, k, v: ring_flash_attention_sharded(q, k, v, mesh1)),
        ("zigzag_flash",
         lambda q, k, v: zigzag_ring_flash_attention_sharded(q, k, v, mesh1)),
    ):
        out = fn(q, k, v).astype(jnp.float32)
        err = float(jnp.max(jnp.abs(out - ref)))
        assert err < 0.05, (name, err)
        grads = jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2),
        )(q, k, v)
        for gname, g in zip("qkv", grads):
            assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32)))), (name, gname)
        print(f"OK attention/{name} (max_err {err:.4f})")

    # --- transformer auto route ----------------------------------------
    from torchsnapshot_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=1024, d_model=256, n_heads=4, n_layers=2, d_ff=512,
        max_seq_len=512,
    )
    params = T.init_params(jax.random.PRNGKey(1), cfg)
    tokens = jnp.ones((2, 512), jnp.int32)
    loss, grads = jax.jit(
        jax.value_and_grad(
            lambda p: jnp.mean(T.forward(p, tokens, cfg).astype(jnp.float32) ** 2)
        )
    )(params)
    assert all(
        bool(jnp.all(jnp.isfinite(x.astype(jnp.float32))))
        for x in jax.tree.leaves(grads)
    )
    print(f"OK transformer/auto->flash (loss {float(loss):.4f})")

    # --- SSM LM ---------------------------------------------------------
    from torchsnapshot_tpu.models import ssm_lm as M

    scfg = M.SSMConfig(vocab_size=512, d_model=128, d_state=8, n_layers=2, d_ff=256)
    sp = M.init_params(jax.random.PRNGKey(2), scfg)
    stoks = jnp.ones((2, 256), jnp.int32)

    def sloss(p):
        return jnp.mean(M.forward(p, stoks, scfg).astype(jnp.float32) ** 2)

    sl, sg = jax.jit(jax.value_and_grad(sloss))(sp)
    assert all(
        bool(jnp.all(jnp.isfinite(x.astype(jnp.float32))))
        for x in jax.tree.leaves(sg)
    )
    print(f"OK ssm_lm (loss {float(sl):.4f})")

    # --- MoE dispatch paths agree ---------------------------------------
    from torchsnapshot_tpu.ops import moe_ffn
    from torchsnapshot_tpu.ops.moe import init_moe_params

    mp = init_moe_params(jax.random.PRNGKey(3), d_model=128, d_ff=256, n_experts=4)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 64, 128), jnp.float32)
    outs = {}
    for dispatch in ("einsum", "sort"):
        y, aux = jax.jit(
            lambda mp, dispatch=dispatch: moe_ffn(mp, x, dispatch=dispatch)
        )(mp)
        outs[dispatch] = np.asarray(y)
    np.testing.assert_allclose(outs["einsum"], outs["sort"], atol=1e-5)
    print("OK moe (einsum == sort dispatch)")

    # --- snapshot round-trip of device arrays ---------------------------
    from torchsnapshot_tpu import Snapshot, StateDict

    with tempfile.TemporaryDirectory() as d:
        w = jax.random.normal(jax.random.PRNGKey(5), (256, 256), jnp.bfloat16)
        Snapshot.take(f"{d}/s", {"app": StateDict(w=w)})
        dst = StateDict(w=jnp.zeros((256, 256), jnp.bfloat16))
        Snapshot(f"{d}/s").restore({"app": dst})
        np.testing.assert_array_equal(
            np.asarray(dst["w"], np.float32), np.asarray(w, np.float32)
        )
    print("OK snapshot round-trip (device arrays)")
    print("TPU SMOKE: ALL OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
