"""Row-wise sharded embedding checkpoint benchmark
(reference: benchmarks/torchrec/main.py:54-231 — DLRM row-wise sharded
embedding tables; sync vs async save with the caller-blocked interval and
peak RSS measured).

Usage:
  python benchmarks/embedding_save.py [--gb 1.0] [--tables 8] [--cpu-devices 8]
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=0.5, help="total table size, decimal GB")
    ap.add_argument("--tables", type=int, default=8)
    ap.add_argument("--cpu-devices", type=int, default=0)
    args = ap.parse_args()

    from bench_utils import force_cpu_devices, report, timed_rss

    if args.cpu_devices:
        force_cpu_devices(args.cpu_devices)
    import jax
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import embedding as E
    from torchsnapshot_tpu.parallel import make_mesh

    mesh = make_mesh()
    dim = 64
    rows = int(args.gb * 1e9 / args.tables / dim / 4)
    # rows must tile over all devices for the row-wise layout
    n_dev = len(jax.devices())
    rows -= rows % max(n_dev, 1)
    cfg = E.EmbeddingConfig(n_tables=args.tables, rows_per_table=rows, dim=dim)
    import optax

    tx = optax.adagrad(1e-2)  # DLRM-style sparse-friendly optimizer
    state = E.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    jax.block_until_ready(state)
    nbytes = sum(x.nbytes for x in jax.tree.leaves(state))

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(dir=base, prefix="bench_embedding_")
    try:
        app_state = {"train": StateDict(**state)}

        res: dict = {"param_count": cfg.param_count, "rows_per_table": rows}
        with timed_rss(res):
            Snapshot.take(f"{tmp}/sync", app_state)
        report("embedding_save/sync", res, nbytes)

        # Cold = first async_take of the process, with the staging pool
        # pre-faulted by warmup_staging (the production recipe: warm up
        # once after building state, off the training-loop critical path).
        from torchsnapshot_tpu import warmup_staging

        res = {}
        t0 = time.perf_counter()
        res["warmup_mb"] = round(warmup_staging(app_state) / 1e6, 1)
        res["warmup_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        pending = Snapshot.async_take(f"{tmp}/async", app_state)
        res["caller_blocked_s"] = round(time.perf_counter() - t0, 3)
        pending.wait()
        res["total_s"] = round(time.perf_counter() - t0, 3)
        # Steady state: a training loop checkpoints repeatedly; from the
        # second async_take the staging-buffer pool recycles, so warm
        # numbers are the production caller-blocked cost.
        shutil.rmtree(f"{tmp}/async", ignore_errors=True)
        time.sleep(1.0)
        t0 = time.perf_counter()
        pending = Snapshot.async_take(f"{tmp}/async", app_state)
        res["warm_caller_blocked_s"] = round(time.perf_counter() - t0, 3)
        pending.wait()
        res["warm_total_s"] = round(time.perf_counter() - t0, 3)
        report("embedding_save/async", res, nbytes)

        fresh = E.init_state(jax.random.PRNGKey(1), cfg, tx, mesh=mesh)
        dst = {"train": StateDict(**fresh)}
        res = {}
        with timed_rss(res):
            Snapshot(f"{tmp}/sync").restore(dst)
        report("embedding_save/restore", res, nbytes)

        a = np.asarray(jax.device_get(state["params"]["tables"]["table_0"]))
        b = np.asarray(jax.device_get(dst["train"]["params"]["tables"]["table_0"]))
        assert a.tobytes() == b.tobytes(), "restore not bit-exact"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
