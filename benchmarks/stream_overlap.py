"""Intra-entry streaming overlap: single-large-array save wall vs the
serial stage+write sum.

The buffered write path only overlaps staging with I/O ACROSS entries —
within one entry, staging fully completes before the first byte hits
storage, so a single large array's critical path is stage + write. The
streaming path (sub-chunk pipeline, scheduler.stream_write) overlaps the
two WITHIN the entry: sub-chunk N writes while N+1 stages, collapsing
the wall toward max(stage, write).

Two legs:

- **throttled**: storage latency is simulated (per-chunk sleep at a
  configured GB/s, the network-filesystem regime BASELINE.json targets).
  On any host — including 1-core CI boxes where two memcpy-bound phases
  can't parallelize — the sleep component genuinely overlaps staging, so
  this leg ASSERTS wall_streamed < stage_s + write_s and reports the
  overlap ratio. This is the design claim, measured.
- **tmpfs**: real end-to-end `Snapshot.take` streamed vs buffered on
  tmpfs, with a bit-exact restore check. Reported without an overlap
  assertion: on a 1-core host both phases are memory-bandwidth-bound and
  overlap cannot manifest; on multi-core hosts this leg shows the real
  gain.

Usage: JAX_PLATFORMS=cpu python benchmarks/stream_overlap.py [mb] [sim_gbps]
Emits one JSON line per leg.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    mb = float(sys.argv[1]) if len(sys.argv) > 1 else 512.0
    sim_gbps = float(sys.argv[2]) if len(sys.argv) > 2 else 1.0

    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager
    from torchsnapshot_tpu.io_types import WriteReq
    from torchsnapshot_tpu.manifest import ArrayEntry
    from torchsnapshot_tpu.scheduler import execute_write_reqs
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    nbytes = int(mb * 1e6)
    rows = nbytes // (1024 * 4)
    arr = np.arange(rows * 1024, dtype=np.float32).reshape(rows, 1024)

    sim_bps = sim_gbps * 1e9

    class ThrottledFS(FSStoragePlugin):
        """Adds simulated storage latency proportional to bytes written
        — the component that genuinely overlaps with staging."""

        def _pwrite_all(self, fd, buf, offset):  # streamed sub-chunks
            n = memoryview(buf).nbytes
            time.sleep(n / sim_bps)  # executor thread: off the loop
            return FSStoragePlugin._pwrite_all(fd, buf, offset)

        async def write(self, write_io):  # buffered whole-entry write
            await asyncio.sleep(memoryview(write_io.buf).nbytes / sim_bps)
            await super().write(write_io)

    def mk_req():
        entry = ArrayEntry(
            location="payload",
            serializer="buffer_protocol",
            dtype="float32",
            shape=list(arr.shape),
            replicated=False,
        )
        return WriteReq(path="payload", buffer_stager=ArrayBufferStager(arr, entry))

    tmp = tempfile.mkdtemp(prefix="stream_overlap_")
    try:
        loop = asyncio.new_event_loop()
        plugin = ThrottledFS(tmp)

        # Best-of-N legs: single measurements on a noisy 1-core host can
        # invert a real ~25% gap; the minimum of each leg is the
        # contention-free number the pipeline comparison is about.
        reps = int(os.environ.get("STREAM_OVERLAP_REPS", "3"))

        # -- serial reference: full stage, then full write --------------
        from concurrent.futures import ThreadPoolExecutor

        from torchsnapshot_tpu.io_types import WriteIO

        stage_s = write_s = float("inf")
        for _ in range(reps):
            req = mk_req()
            with ThreadPoolExecutor(2) as pool:
                t0 = time.perf_counter()
                buf = loop.run_until_complete(
                    req.buffer_stager.stage_buffer(pool)
                )
                stage_s = min(stage_s, time.perf_counter() - t0)
            t0 = time.perf_counter()
            loop.run_until_complete(
                plugin.write(WriteIO(path="serial", buf=buf))
            )
            write_s = min(write_s, time.perf_counter() - t0)
            del buf
        serial_s = stage_s + write_s

        # -- streamed: one entry through the streaming pipeline ---------
        streamed_s = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            pending = loop.run_until_complete(
                execute_write_reqs(
                    [mk_req()], plugin, 1 << 31, rank=0, allow_streaming=True
                )
            )
            pending.sync_complete(loop)
            streamed_s = min(streamed_s, time.perf_counter() - t0)

        ok = streamed_s < serial_s
        print(
            json.dumps(
                {
                    "benchmark": "stream_overlap/throttled",
                    "state_mb": mb,
                    "sim_storage_gbps": sim_gbps,
                    "stage_s": round(stage_s, 3),
                    "write_s": round(write_s, 3),
                    "serial_sum_s": round(serial_s, 3),
                    "streamed_s": round(streamed_s, 3),
                    "overlap_ratio": round(serial_s / max(streamed_s, 1e-9), 2),
                    "wall_below_serial_sum": ok,
                }
            ),
            flush=True,
        )
        assert ok, (
            f"no intra-entry overlap: streamed {streamed_s:.2f}s >= "
            f"serial {serial_s:.2f}s"
        )
        loop.close()

        # -- tmpfs end-to-end: streamed vs buffered take + bit-exact ----
        state = {"m": StateDict(w=arr)}
        base = "/dev/shm" if os.path.isdir("/dev/shm") else None
        tmp2 = tempfile.mkdtemp(prefix="stream_e2e_", dir=base)
        try:
            t0 = time.perf_counter()
            Snapshot.take(f"{tmp2}/streamed", state)
            streamed_take_s = time.perf_counter() - t0
            os.environ["TORCHSNAPSHOT_TPU_STREAM_WRITES"] = "0"
            t0 = time.perf_counter()
            Snapshot.take(f"{tmp2}/buffered", state)
            buffered_take_s = time.perf_counter() - t0
            del os.environ["TORCHSNAPSHOT_TPU_STREAM_WRITES"]

            dst = {"m": StateDict(w=np.zeros_like(arr))}
            Snapshot(f"{tmp2}/streamed").restore(dst)
            bit_exact = dst["m"]["w"].tobytes() == arr.tobytes()
            print(
                json.dumps(
                    {
                        "benchmark": "stream_overlap/tmpfs_take",
                        "state_mb": mb,
                        "streamed_take_s": round(streamed_take_s, 3),
                        "buffered_take_s": round(buffered_take_s, 3),
                        "streamed_gbps": round(nbytes / 1e9 / streamed_take_s, 3),
                        "buffered_gbps": round(nbytes / 1e9 / buffered_take_s, 3),
                        "bit_exact": bit_exact,
                    }
                ),
                flush=True,
            )
            assert bit_exact, "streamed snapshot restore not bit-exact"
        finally:
            shutil.rmtree(tmp2, ignore_errors=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
