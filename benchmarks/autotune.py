"""Closed-loop autotune leg (ISSUE 19): cold-start convergence and
warm-start parity vs a hand-tuned static election, on latency-bound
storage.

The regime the tuner exists for: a storage tier where every request
pays a fixed latency on top of bandwidth (object stores, NFS round
trips). The governor's measured-rate heuristic sizes sub-chunks at
~50 ms of measured bandwidth — and on latency-dominated storage that
backfires: low achieved bandwidth -> small sub-chunks -> MORE requests
-> more latency -> lower bandwidth still. A hand-tuned operator pins
``TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES`` at the leaf size and moves on;
the closed loop (autotune.py) should discover the same thing by
perturb-and-read — and remember it across processes.

Storage writes are throttled with a per-request latency + bandwidth
model (LATENCY_S + nbytes/THROTTLE_BPS per buffered write or stream
sub-chunk), charged through one rate lock per event loop — the same
single-simulated-pipe discipline as coop_restore.py / lazy_restore.py,
plus the request-latency term this leg is ABOUT.

Gate metrics are wall-clock throughput per take. The checkpoint root
sits on tmpfs so real writes are memcpy and the synthetic throttle
dominates every wall — on the disk-backed /tmp, ext4 writeback stalls
2-10x a take's modeled time were measuring the host, not the tuner.
The modeled service time the throttle charged per take is reported
alongside (``model_gbps``) as a deterministic diagnostic of the
elections in effect; it is NOT the gate, because it ignores the
latency the streamed path genuinely hides behind overlapped staging
(the fused-span residual accounting in telemetry/critpath.py measures
that overlap, which is why the tuner can legitimately settle on a
sub-leaf sub-chunk whose wall matches the hand-tuned pin).

Four legs, same 256 MiB state (4 x 64 MiB leaves). I/O concurrency is
pinned and the native engine disabled on EVERY leg, so sub-chunk size
is the one experimental dimension (under the shared-pipe model the
other dims are flat — trials on them would only spend takes learning
"no difference"):

- hand-tuned: AUTOTUNE=never, SUB_CHUNK_BYTES=64 MiB — the static
  optimum an operator would pin. Its p50 is the reference.
- heuristic: AUTOTUNE=never, no pin — the measured-rate default. On
  this storage it converges DOWN (the pathology), so the gap to
  hand-tuned is what the tuner must close.
- cold-start: fresh governor, AUTOTUNE=fresh — two discarded
  ``never`` warmups feed the rate tables (so learning starts at the
  heuristic's true operating point, not the rate-free default), then
  N takes with learning on. GATE: throughput within 10% of the
  hand-tuned p50, sustained from some take <= 8, and the converged
  profile persisted to the root's history journal.
- warm-start: governor reset again (a "new process"), AUTOTUNE=auto —
  the first take loads the persisted profile and must land >= 0.9x the
  hand-tuned p50 immediately (no relearning).

Emits one JSON line per leg plus ``autotune/summary`` (bench.py's
``_autotune_leg`` persists that to BENCH_r16.json).

Usage: JAX_PLATFORMS=cpu python benchmarks/autotune.py
"""

from __future__ import annotations

import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

#: Simulated storage: every request pays LATENCY_S, bytes move at
#: THROTTLE_BPS through one shared pipe. 25 ms / 800 MB/s puts the
#: optimal sub-chunk at the leaf size (one request per leaf) and makes
#: the heuristic's ~50 ms-of-bandwidth sizing land 3-8x too small.
LATENCY_S = 0.025
THROTTLE_BPS = 800e6

N_LEAVES = 4
LEAF_BYTES = 64 << 20  # float32 elems below
PAYLOAD_BYTES = N_LEAVES * LEAF_BYTES

HAND_SUB_CHUNK = str(LEAF_BYTES)
PINNED_IO_CONCURRENCY = "8"

TAKES_PER_LEG = 5
COLD_TAKES = 10
CONVERGE_WITHIN = 8  # gate: sustained >=90% of hand-tuned from take <= 8
CONVERGE_FRAC = 0.90
WARM_FLOOR = 0.90  # gate: warm-start first take >= 0.9x hand-tuned p50

#: Modeled service time charged by the throttle, cumulative. Each
#: take reports PAYLOAD / (charged delta) as ``model_gbps`` — the
#: deterministic per-request cost of the elections the governor made
#: on that take, before streaming's stage/write overlap hides any of
#: it. Diagnostic only; the gates use wall throughput.
_CHARGED = [0.0]


def _throttle():
    """Charge LATENCY_S + n/THROTTLE_BPS for every payload write
    request (buffered write, or each sub-chunk of a streamed write),
    through one rate lock per event loop. Telemetry/manifest artifacts
    (any dot-prefixed path component) ride free — they are not the
    storage tier under test and their tiny transfers would poison the
    governor's measured-rate EWMA."""
    import asyncio

    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    rate_lock: list = [None, None]

    def _is_payload(path: str) -> bool:
        return not any(p.startswith(".") for p in path.split(os.sep))

    async def _pay(n: int) -> None:
        loop = asyncio.get_running_loop()
        if rate_lock[1] is not loop:
            rate_lock[0] = asyncio.Lock()
            rate_lock[1] = loop
        charge = LATENCY_S + n / THROTTLE_BPS
        _CHARGED[0] += charge
        async with rate_lock[0]:
            await asyncio.sleep(charge)

    orig_write = FSStoragePlugin.write

    async def slow_write(self, write_io, _orig=orig_write):
        await _orig(self, write_io)
        if _is_payload(write_io.path):
            await _pay(memoryview(write_io.buf).nbytes)

    orig_stream = FSStoragePlugin.write_stream

    async def slow_stream(self, stream, _orig=orig_stream):
        if not _is_payload(stream.path):
            await _orig(self, stream)
            return
        inner = stream.chunks

        async def chunks():
            async for c in inner:
                await _pay(memoryview(c).nbytes)
                yield c

        stream.chunks = chunks()
        await _orig(self, stream)

    FSStoragePlugin.write = slow_write
    FSStoragePlugin.write_stream = slow_stream


def _build_state(np):
    from torchsnapshot_tpu import StateDict

    rng = np.random.default_rng(19)
    return {
        "model": StateDict(
            **{
                f"p{i}": rng.standard_normal(LEAF_BYTES // 4).astype(
                    np.float32
                )
                for i in range(N_LEAVES)
            }
        )
    }


class _Env:
    """Scoped env overrides (restore on exit)."""

    def __init__(self, **kv):
        self._kv = {k: v for k, v in kv.items()}
        self._saved = {}

    def __enter__(self):
        for k, v in self._kv.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def _timed_take(Snapshot, root, name, state):
    """One take; returns (wall_gbps, model_gbps).

    wall_gbps is the gate metric — real end-to-end rate, stable on the
    tmpfs root. model_gbps divides the payload by the service time the
    throttle charged for this take's requests: the deterministic
    request-count consequence of the sub-chunk elections in effect,
    reported as a diagnostic (see module docstring for why it is not
    the gate).
    """
    path = os.path.join(root, name)
    c0 = _CHARGED[0]
    t0 = time.perf_counter()
    Snapshot.take(path, state)
    wall = time.perf_counter() - t0
    charged = _CHARGED[0] - c0
    shutil.rmtree(path, ignore_errors=True)
    # Settle: the rmtree's reclaim otherwise lands inside the NEXT
    # take's attribution windows and skews what the governor learns.
    time.sleep(0.2)
    return (
        PAYLOAD_BYTES / wall / 1e9,
        PAYLOAD_BYTES / charged / 1e9 if charged > 0 else float("nan"),
    )


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from torchsnapshot_tpu import Snapshot, telemetry
    from torchsnapshot_tpu.scheduler import io_governor, reset_io_governor

    _throttle()
    telemetry.set_enabled(True)
    state = _build_state(np)
    # Prefer tmpfs: real writes become memcpy, so the synthetic
    # throttle dominates every measurement AND the governor's learning
    # signal — /tmp here is disk-backed and its writeback stalls were
    # drowning both.
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    base = tempfile.mkdtemp(prefix="autotune_bench_", dir=shm)
    # The cold leg persists its learned profile into this root's
    # journal; the warm leg saves under the SAME root so its governor
    # warm-starts from it.
    root = os.path.join(base, "ckpts")
    os.makedirs(root)

    pin_io = {
        "TORCHSNAPSHOT_TPU_IO_CONCURRENCY": PINNED_IO_CONCURRENCY,
        "TORCHSNAPSHOT_TPU_DISABLE_NATIVE": "1",
        # Preverify hashing overlaps the streamed writes, and its
        # windows are subtracted from the storage residual the governor
        # scores trials by (fused-span accounting) — a confounder that
        # biases the learned sub-chunk away from the wall optimum. Off
        # on every leg: this bench isolates sub-chunk size against a
        # latency-bound storage model, nothing else.
        "TORCHSNAPSHOT_TPU_PREVERIFY": "never",
    }
    try:
        # -------- leg 1: hand-tuned static pin (the reference) --------
        reset_io_governor()
        with _Env(
            TORCHSNAPSHOT_TPU_AUTOTUNE="never",
            TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES=HAND_SUB_CHUNK,
            **pin_io,
        ):
            _timed_take(Snapshot, root, "warm_hand", state)  # discarded
            hand = [
                _timed_take(Snapshot, root, f"hand_{i}", state)
                for i in range(TAKES_PER_LEG)
            ]
        hand_p50 = statistics.median(g for g, _ in hand)
        report(
            "autotune/hand",
            {
                "sub_chunk_mib": LEAF_BYTES >> 20,
                "takes_gbps": [round(g, 4) for g, _ in hand],
                "takes_model_gbps": [round(m, 4) for _, m in hand],
                "p50_gbps": round(hand_p50, 4),
            },
            data_bytes=PAYLOAD_BYTES,
        )

        # -------- leg 2: measured-rate heuristic (the pathology) ------
        reset_io_governor()
        with _Env(TORCHSNAPSHOT_TPU_AUTOTUNE="never", **pin_io):
            _timed_take(Snapshot, root, "warm_heur", state)  # feeds rates
            heur = [
                _timed_take(Snapshot, root, f"heur_{i}", state)
                for i in range(TAKES_PER_LEG)
            ]
        heur_p50 = statistics.median(g for g, _ in heur)
        report(
            "autotune/heuristic",
            {
                "takes_gbps": [round(g, 4) for g, _ in heur],
                "takes_model_gbps": [round(m, 4) for _, m in heur],
                "p50_gbps": round(heur_p50, 4),
                "vs_hand": round(heur_p50 / hand_p50, 4),
            },
            data_bytes=PAYLOAD_BYTES,
        )

        # -------- leg 3: cold-start learning --------------------------
        reset_io_governor()
        cold = []
        with _Env(TORCHSNAPSHOT_TPU_AUTOTUNE="never", **pin_io):
            # Two discarded warmups feed the rate tables so learning
            # starts at the heuristic's real (bad) operating point.
            _timed_take(Snapshot, root, "warm_cold0", state)
            _timed_take(Snapshot, root, "warm_cold1", state)
        with _Env(TORCHSNAPSHOT_TPU_AUTOTUNE="fresh", **pin_io):
            for i in range(COLD_TAKES):
                gbps, model_gbps = _timed_take(
                    Snapshot, root, f"cold_{i}", state
                )
                profs = io_governor().profiles()
                settings = {}
                for rec in profs.values():
                    settings.update(rec.get("settings") or {})
                cold.append(
                    {
                        "take": i + 1,
                        "gbps": round(gbps, 4),
                        "model_gbps": round(model_gbps, 4),
                        "vs_hand": round(gbps / hand_p50, 4),
                        "settings": settings,
                    }
                )
        ratios = [c["vs_hand"] for c in cold]
        # Converged at the first take that ITSELF clears 90% of
        # hand-tuned AND whose remaining takes hold a median above it:
        # the median keeps an isolated dip (a trial probing away from
        # the optimum, or a residual host stall) from un-converging a
        # settled profile, while the point condition stops a lucky
        # early take from claiming convergence the tail doesn't sustain.
        converged_take = next(
            (
                i + 1
                for i in range(len(ratios))
                if ratios[i] >= CONVERGE_FRAC
                and statistics.median(ratios[i:]) >= CONVERGE_FRAC
            ),
            None,
        )
        report(
            "autotune/cold",
            {
                "takes": cold,
                "converged_take": converged_take,
                "budget_takes": CONVERGE_WITHIN,
                "profiles": io_governor().profiles(),
            },
            data_bytes=PAYLOAD_BYTES,
        )

        # -------- leg 4: warm start (a "new process") -----------------
        # Three independent "new processes": each iteration resets the
        # governor and measures its true FIRST take (profiles loaded
        # from the journal at op entry, no learning before the take).
        # The gate is the median of the three first-takes — a single
        # host stall must not flunk a correct warm start.
        warm_firsts = []
        with _Env(TORCHSNAPSHOT_TPU_AUTOTUNE="auto", **pin_io):
            for i in range(3):
                reset_io_governor()
                warm_firsts.append(
                    _timed_take(Snapshot, root, f"warm_{i}", state)
                )
        warm_p50 = statistics.median(g for g, _ in warm_firsts)
        warm_first_ratio = warm_p50 / hand_p50
        report(
            "autotune/warm",
            {
                "first_takes_gbps": [round(g, 4) for g, _ in warm_firsts],
                "first_takes_model_gbps": [
                    round(m, 4) for _, m in warm_firsts
                ],
                "first_p50_gbps": round(warm_p50, 4),
                "first_vs_hand_p50": round(warm_first_ratio, 4),
                "floor": WARM_FLOOR,
            },
            data_bytes=PAYLOAD_BYTES,
        )

        summary = {
            "payload_mib": PAYLOAD_BYTES >> 20,
            "latency_ms": LATENCY_S * 1e3,
            "throttle_mb_s": THROTTLE_BPS / 1e6,
            "hand_p50_gbps": round(hand_p50, 4),
            "heuristic_p50_gbps": round(heur_p50, 4),
            "heuristic_vs_hand": round(heur_p50 / hand_p50, 4),
            "cold_takes_gbps": [c["gbps"] for c in cold],
            "cold_converged_take": converged_take,
            "cold_budget_takes": CONVERGE_WITHIN,
            "warm_first_p50_gbps": round(warm_p50, 4),
            "warm_first_vs_hand_p50": round(warm_first_ratio, 4),
            "warm_floor": WARM_FLOOR,
        }
        report("autotune/summary", summary, data_bytes=PAYLOAD_BYTES)

        assert converged_take is not None and converged_take <= CONVERGE_WITHIN, (
            f"cold start did not converge to within 10% of hand-tuned "
            f"within {CONVERGE_WITHIN} takes (sustained from "
            f"{converged_take}; ratios {ratios})"
        )
        assert warm_first_ratio >= WARM_FLOOR, (
            f"warm-start first take {warm_p50:.3f} GB/s is "
            f"{warm_first_ratio:.2f}x the hand-tuned p50 "
            f"{hand_p50:.3f} GB/s (floor {WARM_FLOOR}x)"
        )
    finally:
        telemetry.set_enabled(False)
        reset_io_governor()
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
