"""Distributed digest verification vs full re-read, real 2-process world.

The serving/hot-reload steady state for a destination whose layout cuts
saved pieces ACROSS process boundaries: without device digests every
reload re-reads the full state; with them, the processes exchange
16-byte partial fingerprint lanes per piece (fingerprint additivity,
device_digest.py) and move ZERO payload bytes when nothing changed.

Measures, at a given state size:
- cold restore (full read) wall time,
- unchanged reload WITHOUT digests (full read again),
- unchanged reload WITH digests (distributed verification),
and reports the reload speedup plus the MEASURED payload bytes each
reload consumed from storage — the verify leg's must be exactly 0 (the
benchmark asserts it, so a silent fallback to reads can never
masquerade as verification).

Usage: JAX_PLATFORMS=cpu python benchmarks/dist_verify.py [mb_total]
Emits one JSON line (rank 0's timings).
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(rank, world_size, root, port, mb_total):
    import numpy as np

    from torchsnapshot_tpu.test_utils import init_pod_world

    jax = init_pod_world(rank, world_size, port, local_devices=2)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    rows = max(8, int(mb_total * 1e6 / 4 / 1024))
    rows -= rows % 8  # divisible by every mesh-axis tiling used below
    shape = (rows, 1024)

    mesh = Mesh(np.array(jax.devices()).reshape(world_size, 2), ("proc", "local"))

    def mk(spec):
        def cb(index):
            # Content is a function of the GLOBAL cell coordinates, so
            # every layout holds identical values (load-bearing: the
            # digest comparison must see genuinely unchanged data).
            r = np.arange(*index[0].indices(shape[0]), dtype=np.float32)
            c = np.arange(*index[1].indices(shape[1]), dtype=np.float32)
            return r[:, None] * 3.0 + c[None, :]

        return jax.make_array_from_callback(shape, NamedSharding(mesh, spec), cb)

    # Saved: column pieces replicated over procs; destination: row boxes
    # -> every piece is cut across both processes.
    src = mk(P(None, "local"))
    Snapshot.take(root, {"m": StateDict(w=src)}, device_digests=True)

    consumed_bytes = [0]
    orig_consume = _ShardScatterConsumer._consume_sync

    def counting(self, buf, _orig=orig_consume):
        consumed_bytes[0] += len(buf)
        return _orig(self, buf)

    _ShardScatterConsumer._consume_sync = counting

    def timed_restore(device_digests):
        dst = StateDict(w=mk(P("proc", None)))
        consumed_bytes[0] = 0
        t0 = time.perf_counter()
        Snapshot(root).restore({"m": dst}, device_digests=device_digests)
        return time.perf_counter() - t0, consumed_bytes[0]

    cold_s, cold_bytes = timed_restore(False)
    full_s, full_bytes = timed_restore(False)
    # First digest reload pays one XLA compile per distinct region shape
    # (a training/serving loop pays it once); the second is steady state.
    verify_first_s, verify_first_bytes = timed_restore(True)
    verify_s, verify_bytes = timed_restore(True)
    _ShardScatterConsumer._consume_sync = orig_consume
    assert verify_bytes == 0, (
        f"verification fell back to reads: {verify_bytes} bytes consumed"
    )
    assert full_bytes > 0
    return {
        "cold_s": cold_s,
        "reload_full_read_s": full_s,
        "reload_full_read_bytes": full_bytes,
        "reload_dist_verify_first_s": verify_first_s,
        "reload_dist_verify_s": verify_s,
        "reload_dist_verify_bytes": verify_bytes,
    }


def main() -> int:
    mb_total = float(sys.argv[1]) if len(sys.argv) > 1 else 64.0
    import json

    from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

    tmp = tempfile.mkdtemp(prefix="dist_verify_")
    try:
        results = run_with_subprocesses(
            _worker, 2, os.path.join(tmp, "snap"), _find_free_port(), mb_total,
            timeout=600.0,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    r = results[0]
    print(
        json.dumps(
            {
                "benchmark": "dist_verify/unchanged_reload",
                "state_mb": mb_total,
                "world": "2 procs x 2 devices",
                "cold_restore_s": round(r["cold_s"], 3),
                "reload_full_read_s": round(r["reload_full_read_s"], 3),
                "reload_full_read_bytes": r["reload_full_read_bytes"],
                "reload_dist_verify_first_s": round(
                    r["reload_dist_verify_first_s"], 3
                ),
                "reload_dist_verify_s": round(r["reload_dist_verify_s"], 3),
                "reload_dist_verify_bytes": r["reload_dist_verify_bytes"],
                "speedup": round(
                    r["reload_full_read_s"] / max(r["reload_dist_verify_s"], 1e-9), 2
                ),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
