"""Distributed digest verification vs full re-read, real 2-process world.

The serving/hot-reload steady state for a destination whose layout cuts
saved pieces ACROSS process boundaries: without device digests every
reload re-reads the full state; with them, the processes exchange
16-byte partial fingerprint lanes per piece (fingerprint additivity,
device_digest.py) and move ZERO payload bytes when nothing changed.

Measures, at a given state size:
- cold restore (full read) wall time,
- unchanged reload WITHOUT digests (full read again),
- unchanged reload WITH digests (distributed verification),
and reports the reload speedup plus the MEASURED payload bytes each
reload consumed from storage — the verify leg's must be exactly 0 (the
benchmark asserts it, so a silent fallback to reads can never
masquerade as verification).

**Gate legs** (VERDICT r5 item 6): with digests enabled AMBIENTLY (env,
not an explicit argument) the restore consults the I/O governor's
measured hash-vs-read economics before committing to the verification
pass. Two extra worlds demonstrate both regimes:
- ``gate_fast``: real tmpfs storage — reads measure GB/s while this
  host's hasher runs ~0.6 GB/s, so the gate PICKS READS (consumed
  bytes > 0) and skips the fingerprint pass;
- ``gate_slow``: reads throttled to ~40 MB/s (network-storage regime) —
  hashing is clearly cheaper, so the gate VERIFIES (consumed bytes ==
  0). The leg asserts this.
Each leg reports the rank-0 governor rates the decision was made from.

Usage: JAX_PLATFORMS=cpu python benchmarks/dist_verify.py [mb_total]
Emits one JSON line per leg (rank 0's timings).
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(rank, world_size, root, port, mb_total):
    import numpy as np

    from torchsnapshot_tpu.test_utils import init_pod_world

    jax = init_pod_world(rank, world_size, port, local_devices=2)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    rows = max(8, int(mb_total * 1e6 / 4 / 1024))
    rows -= rows % 8  # divisible by every mesh-axis tiling used below
    shape = (rows, 1024)

    mesh = Mesh(np.array(jax.devices()).reshape(world_size, 2), ("proc", "local"))

    def mk(spec):
        def cb(index):
            # Content is a function of the GLOBAL cell coordinates, so
            # every layout holds identical values (load-bearing: the
            # digest comparison must see genuinely unchanged data).
            r = np.arange(*index[0].indices(shape[0]), dtype=np.float32)
            c = np.arange(*index[1].indices(shape[1]), dtype=np.float32)
            return r[:, None] * 3.0 + c[None, :]

        return jax.make_array_from_callback(shape, NamedSharding(mesh, spec), cb)

    # Saved: column pieces replicated over procs; destination: row boxes
    # -> every piece is cut across both processes.
    src = mk(P(None, "local"))
    Snapshot.take(root, {"m": StateDict(w=src)}, device_digests=True)

    consumed_bytes = [0]
    orig_consume = _ShardScatterConsumer._consume_sync

    def counting(self, buf, _orig=orig_consume):
        consumed_bytes[0] += len(buf)
        return _orig(self, buf)

    _ShardScatterConsumer._consume_sync = counting

    def timed_restore(device_digests):
        dst = StateDict(w=mk(P("proc", None)))
        consumed_bytes[0] = 0
        t0 = time.perf_counter()
        Snapshot(root).restore({"m": dst}, device_digests=device_digests)
        return time.perf_counter() - t0, consumed_bytes[0]

    cold_s, cold_bytes = timed_restore(False)
    full_s, full_bytes = timed_restore(False)
    # First digest reload pays one XLA compile per distinct region shape
    # (a training/serving loop pays it once); the second is steady state.
    verify_first_s, verify_first_bytes = timed_restore(True)
    verify_s, verify_bytes = timed_restore(True)
    _ShardScatterConsumer._consume_sync = orig_consume
    assert verify_bytes == 0, (
        f"verification fell back to reads: {verify_bytes} bytes consumed"
    )
    assert full_bytes > 0
    return {
        "cold_s": cold_s,
        "reload_full_read_s": full_s,
        "reload_full_read_bytes": full_bytes,
        "reload_dist_verify_first_s": verify_first_s,
        "reload_dist_verify_s": verify_s,
        "reload_dist_verify_bytes": verify_bytes,
    }


def _gate_worker(rank, world_size, root, port, mb_total, throttle_read_bps):
    """Ambient-digest reload with the governor's economic gate live.

    Saves with digests, cold-restores (teaching the governor this
    process's real — or throttled — read bandwidth), then reloads with
    digests enabled via ENV ONLY, so the gate is free to pick the
    cheaper path. Returns the decision, measured bytes, walls, and the
    rates the decision was made from."""
    import numpy as np

    # Ambient enablement: the gate applies only when digests come from
    # the environment, never when the caller explicitly asked to verify.
    os.environ["TORCHSNAPSHOT_TPU_DEVICE_DIGESTS"] = "1"
    os.environ.pop("TORCHSNAPSHOT_TPU_PREVERIFY", None)

    from torchsnapshot_tpu.test_utils import init_pod_world

    jax = init_pod_world(rank, world_size, port, local_devices=2)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer
    from torchsnapshot_tpu.scheduler import io_governor
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    if throttle_read_bps:
        orig_read = FSStoragePlugin.read

        async def slow_read(self, read_io, _orig=orig_read):
            await _orig(self, read_io)
            import asyncio

            nbytes = len(memoryview(read_io.buf))
            await asyncio.sleep(nbytes / throttle_read_bps)

        FSStoragePlugin.read = slow_read

    rows = max(8, int(mb_total * 1e6 / 4 / 1024))
    rows -= rows % 8
    shape = (rows, 1024)
    mesh = Mesh(np.array(jax.devices()).reshape(world_size, 2), ("proc", "local"))

    def mk(spec):
        def cb(index):
            r = np.arange(*index[0].indices(shape[0]), dtype=np.float32)
            c = np.arange(*index[1].indices(shape[1]), dtype=np.float32)
            return r[:, None] * 3.0 + c[None, :]

        return jax.make_array_from_callback(shape, NamedSharding(mesh, spec), cb)

    src = mk(P(None, "local"))
    Snapshot.take(root, {"m": StateDict(w=src)}, device_digests=True)

    consumed_bytes = [0]
    orig_consume = _ShardScatterConsumer._consume_sync

    def counting(self, buf, _orig=orig_consume):
        consumed_bytes[0] += len(buf)
        return _orig(self, buf)

    _ShardScatterConsumer._consume_sync = counting

    def timed_reload():
        dst = StateDict(w=mk(P("proc", None)))
        consumed_bytes[0] = 0
        t0 = time.perf_counter()
        # device_digests resolved from env: the economic gate applies.
        Snapshot(root).restore({"m": dst})
        return time.perf_counter() - t0, consumed_bytes[0]

    # Cold reload with digests OFF: a full payload read that teaches the
    # governor this storage's real (or throttled) read bandwidth — with
    # digests ambient, even a first reload would verify-and-skip and the
    # gate would never learn the read side of its crossover.
    os.environ["TORCHSNAPSHOT_TPU_DEVICE_DIGESTS"] = "0"
    cold_s, cold_bytes = timed_reload()
    os.environ["TORCHSNAPSHOT_TPU_DEVICE_DIGESTS"] = "1"
    warm_s, warm_bytes = timed_reload()  # first gated reload (jit warm)
    gated_s, gated_bytes = timed_reload()  # steady state
    _ShardScatterConsumer._consume_sync = orig_consume
    gov = io_governor()
    return {
        "cold_s": cold_s,
        "cold_bytes": cold_bytes,
        "gated_s": gated_s,
        "gated_bytes": gated_bytes,
        "verified": gated_bytes == 0,
        "read_bps": gov.read_bps(),
        "hash_bps": gov.hash_bps(),
    }


def main() -> int:
    mb_total = float(sys.argv[1]) if len(sys.argv) > 1 else 64.0
    import json

    from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

    tmp = tempfile.mkdtemp(prefix="dist_verify_")
    try:
        results = run_with_subprocesses(
            _worker, 2, os.path.join(tmp, "snap"), _find_free_port(), mb_total,
            timeout=600.0,
        )
        gate_runs = {}
        gate_all_ranks = {}
        for leg, throttle in (("gate_fast", 0), ("gate_slow", 40e6)):
            ranks = run_with_subprocesses(
                _gate_worker,
                2,
                os.path.join(tmp, f"snap_{leg}"),
                _find_free_port(),
                mb_total,
                throttle,
                timeout=600.0,
            )
            gate_runs[leg] = ranks[0]
            gate_all_ranks[leg] = ranks
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    r = results[0]
    print(
        json.dumps(
            {
                "benchmark": "dist_verify/unchanged_reload",
                "state_mb": mb_total,
                "world": "2 procs x 2 devices",
                "cold_restore_s": round(r["cold_s"], 3),
                "reload_full_read_s": round(r["reload_full_read_s"], 3),
                "reload_full_read_bytes": r["reload_full_read_bytes"],
                "reload_dist_verify_first_s": round(
                    r["reload_dist_verify_first_s"], 3
                ),
                "reload_dist_verify_s": round(r["reload_dist_verify_s"], 3),
                "reload_dist_verify_bytes": r["reload_dist_verify_bytes"],
                "speedup": round(
                    r["reload_full_read_s"] / max(r["reload_dist_verify_s"], 1e-9), 2
                ),
            }
        ),
        flush=True,
    )
    for leg, g in gate_runs.items():
        print(
            json.dumps(
                {
                    "benchmark": f"dist_verify/{leg}",
                    "state_mb": mb_total,
                    "cold_restore_s": round(g["cold_s"], 3),
                    "gated_reload_s": round(g["gated_s"], 3),
                    "gated_reload_bytes": g["gated_bytes"],
                    "gate_verified": g["verified"],
                    "read_gbps": round((g["read_bps"] or 0) / 1e9, 3),
                    "hash_gbps": round((g["hash_bps"] or 0) / 1e9, 3),
                }
            ),
            flush=True,
        )
    # The throttled leg is deterministic: at ~0.04 GB/s reads vs this
    # host's ~0.6 GB/s hasher, verification is clearly cheaper and the
    # gate MUST take it (zero payload bytes).
    assert gate_runs["gate_slow"]["verified"], (
        "gate read payload bytes on slow storage: "
        f"{gate_runs['gate_slow']}"
    )
    # The fast leg's decision must MATCH its measured economics (on
    # tmpfs that is overwhelmingly read-bound, but the assertion is
    # rate-relative so a host with a fast hasher still passes). The
    # observed decision is the AND of BOTH ranks' local verdicts, so
    # only assert when every rank's rates point the same way — near the
    # 1.25x crossover the ranks may legitimately split, and the agreed
    # flag then correctly degrades to reads.
    expects = [
        r["read_bps"] <= r["hash_bps"] * 1.25
        for r in gate_all_ranks["gate_fast"].values()
        if r["read_bps"] and r["hash_bps"]
    ]
    if expects and len(set(expects)) == 1:
        gf = gate_runs["gate_fast"]
        assert gf["verified"] == expects[0], f"gate fought its rates: {gf}"
    return 0


if __name__ == "__main__":
    sys.exit(main())
