"""Sharded training-state save/restore benchmark
(reference analogues: benchmarks/fsdp/main.py:36-103 — sharded Transformer
state — and benchmarks/torchrec/main.py:136-151 — sync vs async save with
the caller-blocked interval measured separately).

Builds the flagship transformer with GSPMD-sharded params/optimizer state
on a device mesh, then measures:
  - sync Snapshot.take
  - Snapshot.async_take: caller-blocked time (staging) vs total time to
    commit — the async-stall metric from BASELINE.json
  - restore into a freshly-initialized sharded state

Usage:
  python benchmarks/sharded_save.py [--layers 4] [--d-model 512] [--cpu-devices 8]
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help=">0: run on N virtual CPU devices")
    args = ap.parse_args()

    from bench_utils import force_cpu_devices, report, timed_rss

    if args.cpu_devices:
        force_cpu_devices(args.cpu_devices)
    import jax
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import transformer as T
    from torchsnapshot_tpu.parallel import make_mesh

    mesh = make_mesh()
    cfg = T.TransformerConfig(
        vocab_size=8192,
        d_model=args.d_model,
        n_heads=8,
        n_layers=args.layers,
        d_ff=4 * args.d_model,
        max_seq_len=256,
    )
    tx = T.make_optimizer()
    state = T.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    jax.block_until_ready(state)
    nbytes = sum(x.nbytes for x in jax.tree.leaves(state))

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(dir=base, prefix="bench_sharded_")
    try:
        app_state = {"train": StateDict(**state)}

        res: dict = {"param_count": cfg.param_count}
        with timed_rss(res):
            Snapshot.take(f"{tmp}/sync", app_state)
        report("sharded_save/sync", res, nbytes)

        res = {}
        t0 = time.perf_counter()
        pending = Snapshot.async_take(f"{tmp}/async", app_state)
        res["caller_blocked_s"] = round(time.perf_counter() - t0, 3)
        pending.wait()
        res["total_s"] = round(time.perf_counter() - t0, 3)
        res["io_overlap_frac"] = round(
            1 - res["caller_blocked_s"] / max(res["total_s"], 1e-9), 3
        )
        # Steady state (staging-buffer pool warm), the production cost of
        # a periodic checkpoint in a training loop.
        shutil.rmtree(f"{tmp}/async", ignore_errors=True)
        time.sleep(1.0)
        t0 = time.perf_counter()
        pending = Snapshot.async_take(f"{tmp}/async", app_state)
        res["warm_caller_blocked_s"] = round(time.perf_counter() - t0, 3)
        pending.wait()
        res["warm_total_s"] = round(time.perf_counter() - t0, 3)
        report("sharded_save/async", res, nbytes)

        fresh = T.init_state(jax.random.PRNGKey(1), cfg, tx, mesh=mesh)
        dst = {"train": StateDict(**fresh)}
        res = {}
        with timed_rss(res):
            Snapshot(f"{tmp}/sync").restore(dst)
        report("sharded_save/restore", res, nbytes)

        a = np.asarray(jax.device_get(state["params"]["embed"]))
        b = np.asarray(jax.device_get(dst["train"]["params"]["embed"]))
        assert a.tobytes() == b.tobytes(), "restore not bit-exact"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
