"""Chaos soak: randomized (seeded) fault schedules against real takes,
plus the disabled-injector overhead leg.

Two legs:

``--soak`` (default; ``--iterations N``, default 40)
    Generates N seeded fault plans over the write-path sites — random
    site, trigger hit, action, and corruption offsets drawn from ONE
    seeded RNG, so a failing iteration replays from its printed plan
    string — runs a real SIGKILL-capable take under each in a
    subprocess, and asserts the crash-consistency invariant every time:
    the run either commits a bit-exact restorable snapshot or leaves
    the previous snapshot restorable and fsck-clean (and a committed
    snapshot that does NOT restore bit-exact must be fsck-dirty).
    This is the open-ended complement to the deterministic tier-1
    matrix (tests/test_chaos_matrix.py): same invariant, unbounded
    schedule space.

``--overhead``
    The acceptance gate for the injector's disabled hot path: times a
    ~2 GiB save with the injector disabled (one module-global flag
    check per site hit — the shipping configuration) against the same
    save with the shim bypassed entirely (site/mutate monkeypatched to
    raw no-ops), and ASSERTS the best-vs-best delta is under 1% (with a
    50 ms absolute floor — bench.py's recipe for this bimodal host).
    Also gates the coordination store's disabled-path overhead: with
    replication off, the failover machinery's per-op bookkeeping
    (idempotency stamps, dedup table) must stay under 1% of the KV
    round-trip time (5 ms floor over 3000 mixed ops).
    And gates the flight recorder's ALWAYS-ON cost (ISSUE 7): the same
    2 GiB save with the recorder enabled (the shipping default — ring
    appends on every phase/fence/progress event) vs hard-disabled
    (``record`` monkeypatched to a raw no-op), best-vs-best < 1% with
    the same 50 ms floor. The recorder records tens of events per save,
    never per-sub-chunk samples, so the gate has enormous margin — it
    exists to keep that invariant pinned.
    And gates the hang watchdog's ALWAYS-ON cost (ISSUE 13): the same
    2 GiB save with the stall-forensics watchdog armed (the shipping
    default — a daemon thread sampling every thread's stack twice a
    second plus duration-ring bookkeeping at every storage guard) vs
    ``forensics.set_enabled(False)``, best-vs-best < 1% with the 50 ms
    floor. Sampling is O(threads) every half second, off the hot path
    entirely.
    And gates the latency-histogram instrument (ISSUE 8): the same
    2 GiB save with the telemetry bus ENABLED and the histograms fully
    wired (per-sub-chunk and per-entry observations recording) vs the
    same enabled bus with ``histogram_observe`` bypassed to a raw
    no-op, best-vs-best < 1% with the 50 ms floor — the marginal cost
    of the distribution metric on top of the already-gated bus must be
    bucket math plus one uncontended lock, nothing more. (The DISABLED
    path needs no new gate: with the bus off every observation site is
    one flag check, the exact shape the injector gate above pins.)
    And gates the native I/O election (ISSUE 9): the 2 GiB save with
    the io_uring engine elected vs ``TORCHSNAPSHOT_TPU_NATIVE_IO=never``
    — electing the native engine may win but can never cost more than
    the 1% budget with the 50 ms floor.
    And gates the delta journal's DISABLED path (ISSUE 14): the same
    2 GiB save through CheckpointManager with journaling off (the
    shipping default — ``_journal_seed`` runs one env check per
    committed save and returns) vs that hook bypassed entirely,
    best-vs-best < 1% with the 50 ms floor. The enabled path's cost is
    measured, not gated, by the bench.py journal leg (BENCH_r12.json).
    And gates the fleet seeding tier's DISABLED path (ISSUE 16): a
    2 GiB RESTORE with ``TORCHSNAPSHOT_TPU_SEED_RESTORE`` unset (the
    shipping default — ``maybe_wrap_restore`` is one env check) vs that
    hook bypassed to a raw passthrough, best-vs-best < 1% with the
    50 ms floor. The enabled path's win is measured by bench.py's
    fleet-distribution leg (BENCH_r13.json).
    And gates the geo-replication tier's DISABLED path (ISSUE 20): a
    2 GiB CheckpointManager save with ``TORCHSNAPSHOT_TPU_GEOREP``
    unset (the shipping default — one ``remote_url`` env check at
    construction, one attribute check per commit) vs that env check
    bypassed to a raw ``None``, best-vs-best < 1% with the 50 ms floor.
    The ARMED shipper's foreground cost is gated separately by
    bench.py's georep leg (BENCH_r17.json).

Usage::

    python benchmarks/chaos_soak.py --soak --iterations 40 --seed 7
    python benchmarks/chaos_soak.py --overhead
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.bench_utils import report  # noqa: E402

# Write-path sites a soak take can hit (read sites are covered by the
# deterministic matrix; the soak's focus is commit-protocol integrity).
_SOAK_SITES = [
    "fs.write", "fs.pwrite", "scheduler.stage", "commit.metadata",
]
_SOAK_ACTIONS = [
    "transient", "permanent", "kill", "corrupt", "truncate:0.5",
    "delay:0.01",
]

_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict, faultinject

root, plan = sys.argv[1], sys.argv[2]

def state(seed):
    rng = np.random.default_rng(seed)
    return {"model": StateDict(
        **{f"p{i}": rng.standard_normal(400_000).astype(np.float32)
           for i in range(4)}
    )}

if plan:
    faultinject.configure(plan)
Snapshot.take(os.path.join(root, "cur"), state(1))
"""


def _expected_state(seed: int):
    import numpy as np

    rng = np.random.default_rng(seed)
    return {
        f"p{i}": rng.standard_normal(400_000).astype(np.float32)
        for i in range(4)
    }


def _random_plan(rng: random.Random) -> str:
    site = rng.choice(_SOAK_SITES)
    action = rng.choice(_SOAK_ACTIONS)
    hit = rng.randint(1, 8)
    trigger = f"{hit}+" if rng.random() < 0.3 else str(hit)
    return f"{site}@{trigger}={action};seed={rng.randint(0, 2**31)}"


def _run_soak_iteration(root: str, plan: str) -> str:
    """One seeded schedule; returns the outcome label. Raises on any
    invariant violation."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.cli import run_fsck

    cur = os.path.join(root, "cur")
    shutil.rmtree(cur, ignore_errors=True)
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, root, plan],
        capture_output=True,
        text=True,
        timeout=300,
    )
    killed = r.returncode == -signal.SIGKILL
    # Aborts must trace back to the plan, not to an unrelated crash.
    # Downstream consequences count: a corrupted/truncated fence write
    # surfaces as StaleCommitError (the commit refusing to trust a fence
    # it can no longer read) — that IS the protocol working.
    fault_signature = any(
        s in r.stderr
        for s in ("Injected", "fault injection", "StaleCommitError")
    )
    if not killed and r.returncode != 0 and not fault_signature:
        raise AssertionError(
            f"plan {plan!r}: child failed outside the injector "
            f"(rc={r.returncode}):\n{r.stderr[-2000:]}"
        )

    committed = os.path.exists(os.path.join(cur, ".snapshot_metadata"))
    expected = _expected_state(1)
    if committed:
        dst = {
            "model": StateDict(
                **{k: np.zeros_like(v) for k, v in expected.items()}
            )
        }
        exact = False
        try:
            Snapshot(cur).restore(dst)
            exact = all(
                np.array_equal(dst["model"][k], expected[k]) for k in expected
            )
        except Exception:  # noqa: BLE001
            exact = False
        if exact:
            return "committed"
        code, _ = run_fsck(cur, echo=lambda *a, **k: None)
        if code == 0:
            raise AssertionError(
                f"plan {plan!r}: committed, not bit-exact restorable, fsck "
                "clean — SILENT CORRUPTION"
            )
        return "committed-detectable"
    # Nothing committed: prev must be restorable + fsck-clean.
    prev = os.path.join(root, "prev")
    prev_expected = _expected_state(0)
    dst = {
        "model": StateDict(
            **{k: np.zeros_like(v) for k, v in prev_expected.items()}
        )
    }
    Snapshot(prev).restore(dst)
    assert all(
        np.array_equal(dst["model"][k], prev_expected[k])
        for k in prev_expected
    ), f"plan {plan!r}: previous snapshot damaged"
    code, _ = run_fsck(prev, echo=lambda *a, **k: None)
    assert code == 0, f"plan {plan!r}: previous snapshot not fsck-clean"
    if os.path.isdir(cur):
        code, _ = run_fsck(cur, echo=lambda *a, **k: None)
        assert code in (1, 2), f"plan {plan!r}: rubble fsck'd clean"
    return "killed" if killed else "aborted"


def soak(iterations: int, seed: int) -> None:
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix="chaos_soak_")
    try:
        Snapshot.take(
            os.path.join(root, "prev"),
            {
                "model": StateDict(
                    **{k: v for k, v in _expected_state(0).items()}
                )
            },
        )
        outcomes: dict = {}
        t0 = time.perf_counter()
        for it in range(iterations):
            plan = _random_plan(rng)
            outcome = _run_soak_iteration(root, plan)
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
            print(
                json.dumps({"iter": it, "plan": plan, "outcome": outcome}),
                flush=True,
            )
        report(
            "chaos_soak",
            {
                "iterations": iterations,
                "seed": seed,
                "outcomes": outcomes,
                "wall_s": round(time.perf_counter() - t0, 3),
            },
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


def overhead(trials: int = 5) -> None:
    """Disabled-injector overhead on a ~2 GiB save: flag-check shim vs
    bypassed shim. Asserts best-vs-best delta < 1% with a 50 ms floor
    (ISSUE 5 acceptance)."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, faultinject

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    try:
        import psutil
    except ImportError:  # pragma: no cover - baked into the image
        psutil = None
    proc = psutil.Process() if psutil is not None else None

    def timed_save() -> tuple:
        """One save's (wall, cpu/wall ratio). The save is CPU-bound on
        tmpfs (memcpy + CRC), so a clean trial's process CPU time ~=
        wall; when the host steals the core or reclaims pages mid-window
        wall inflates while CPU time doesn't — same DURING-trial
        contention detector bench.py uses."""
        root = tempfile.mkdtemp(prefix="chaos_overhead_")
        try:
            cpu0 = proc.cpu_times() if proc is not None else None
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            wall = time.perf_counter() - t0
            if cpu0 is None:
                return wall, 1.0
            cpu1 = proc.cpu_times()
            busy = (cpu1.user - cpu0.user) + (cpu1.system - cpu0.system)
            return wall, busy / max(wall, 1e-9)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def bypassed(fn):
        saved = (faultinject.site, faultinject.mutate)
        faultinject.site = lambda name: None
        faultinject.mutate = lambda name, buf: buf
        try:
            return fn()
        finally:
            faultinject.site, faultinject.mutate = saved

    # One discarded warmup save: the FIRST take of a process pays the
    # staging-pool first-touch faults and page-cache population — ~30x a
    # warm save — which would otherwise land entirely on one leg.
    faultinject.disable()
    timed_save()
    # Paired trials with ALTERNATING leg order: the second save of a
    # back-to-back pair periodically eats a multi-second page-reclaim
    # stall from the first save's 2 GiB rmtree (measured 0.8 s vs 5.8 s
    # on this lazily-backed VM). A fixed order pins that stall to one
    # leg and measures the host, not the shim; alternating cancels the
    # positional bias, and contended pairs (either leg's cpu/wall below
    # the bench.py 0.6 threshold) are discarded and retried, bounded.
    bypass_walls, shim_walls = [], []
    contended = []
    # Best-vs-best with an absolute floor and early stop — bench.py's
    # telemetry-leg recipe for exactly this host: bimodal trials (reclaim
    # stalls, hypervisor steals) only ever INFLATE a wall time, so each
    # leg's min is the honest estimate of its intrinsic cost, and one
    # shim trial landing within budget of the bypass best already proves
    # the flag check is cheap. The 50 ms floor keeps the gate meaningful
    # when a contended host drags both legs around: 236 shim calls per
    # 2 GiB save cost microseconds, not percents.
    max_pairs = 2 * trials
    for pair in range(max_pairs):
        if pair % 2 == 0:
            byp, byp_ratio = bypassed(timed_save)
            faultinject.disable()
            shim, shim_ratio = timed_save()
        else:
            faultinject.disable()
            shim, shim_ratio = timed_save()
            byp, byp_ratio = bypassed(timed_save)
        # cpu/wall ratio is the DURING-trial contention detector (the
        # save is CPU-bound on tmpfs); flagged trials still count into
        # the mins — noise can only make the gate pessimistic — but are
        # recorded for audit.
        if proc is not None and min(byp_ratio, shim_ratio) < 0.6:
            contended.append(
                {"bypass_s": round(byp, 3), "shim_s": round(shim, 3)}
            )
        bypass_walls.append(byp)
        shim_walls.append(shim)
        budget_s = max(0.01 * min(bypass_walls), 0.05)
        if pair + 1 >= trials and (
            min(shim_walls) - min(bypass_walls)
        ) < budget_s:
            break
    bypass_best = min(bypass_walls)
    shim_best = min(shim_walls)
    budget_s = max(0.01 * bypass_best, 0.05)
    delta = (shim_best - bypass_best) / bypass_best
    report(
        "chaos_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(bypass_walls),
            "bypass_trials_s": [round(t, 3) for t in bypass_walls],
            "shim_trials_s": [round(t, 3) for t in shim_walls],
            "bypass_best_s": round(bypass_best, 3),
            "shim_best_s": round(shim_best, 3),
            "overhead_pct": round(delta * 100, 3),
            "contended_pairs": contended,
        },
        data_bytes=nbytes,
    )
    assert (shim_best - bypass_best) < budget_s, (
        f"disabled-injector overhead {delta * 100:.2f}% over the 1% budget "
        f"(bypass best {bypass_best:.3f}s vs shim best {shim_best:.3f}s, "
        f"floor 50 ms)"
    )


def flightrec_overhead(trials: int = 5) -> None:
    """Always-on flight-recorder overhead on a ~2 GiB save: the shipping
    default (recorder enabled, ring appends at every phase/fence/
    progress event) vs hard-disabled (``record`` monkeypatched to a raw
    no-op — no flag check, no append). Asserts best-vs-best delta < 1%
    with a 50 ms floor (ISSUE 7 acceptance; same paired/alternating
    recipe as the injector gate above — bimodal-host noise only ever
    inflates a wall time, so each leg's min is its honest cost)."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.telemetry import flightrec

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    def timed_save() -> float:
        root = tempfile.mkdtemp(prefix="flightrec_overhead_")
        try:
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def disabled(fn):
        saved = flightrec.record
        flightrec.record = lambda event, **args: None
        try:
            return fn()
        finally:
            flightrec.record = saved

    flightrec.set_enabled(True)  # the shipping default, made explicit
    timed_save()  # discarded warmup (staging-pool first-touch faults)
    on_walls, off_walls = [], []
    max_pairs = 2 * trials
    for pair in range(max_pairs):
        if pair % 2 == 0:
            off = disabled(timed_save)
            on = timed_save()
        else:
            on = timed_save()
            off = disabled(timed_save)
        on_walls.append(on)
        off_walls.append(off)
        budget_s = max(0.01 * min(off_walls), 0.05)
        if pair + 1 >= trials and (min(on_walls) - min(off_walls)) < budget_s:
            break
    off_best, on_best = min(off_walls), min(on_walls)
    budget_s = max(0.01 * off_best, 0.05)
    delta = (on_best - off_best) / off_best
    report(
        "flightrec_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(on_walls),
            "disabled_trials_s": [round(t, 3) for t in off_walls],
            "enabled_trials_s": [round(t, 3) for t in on_walls],
            "disabled_best_s": round(off_best, 3),
            "enabled_best_s": round(on_best, 3),
            "overhead_pct": round(delta * 100, 3),
            "ring_events_total": flightrec.recorded_total(),
        },
        data_bytes=nbytes,
    )
    assert (on_best - off_best) < budget_s, (
        f"always-on flight-recorder overhead {delta * 100:.2f}% over the 1% "
        f"budget (disabled best {off_best:.3f}s vs enabled best "
        f"{on_best:.3f}s, floor 50 ms)"
    )


def forensics_overhead(trials: int = 5) -> None:
    """Always-on hang-watchdog overhead on a ~2 GiB save: the shipping
    default (watchdog armed per op, stack sampler ticking on its own
    daemon thread, storage guards feeding the per-kind duration rings)
    vs hard-disabled (``forensics.set_enabled(False)`` — ``arm``
    returns ``None``, no thread, guards fall through). Asserts
    best-vs-best delta < 1% with a 50 ms floor (ISSUE 13 acceptance;
    same paired/alternating bimodal-host recipe as the legs above)."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.telemetry import forensics

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    def timed_save() -> float:
        root = tempfile.mkdtemp(prefix="forensics_overhead_")
        try:
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def disabled(fn):
        forensics.set_enabled(False)
        try:
            return fn()
        finally:
            forensics.set_enabled(True)

    forensics.set_enabled(True)  # the shipping default, made explicit
    timed_save()  # discarded warmup (staging-pool first-touch faults)
    on_walls, off_walls = [], []
    max_pairs = 2 * trials
    for pair in range(max_pairs):
        if pair % 2 == 0:
            off = disabled(timed_save)
            on = timed_save()
        else:
            on = timed_save()
            off = disabled(timed_save)
        on_walls.append(on)
        off_walls.append(off)
        budget_s = max(0.01 * min(off_walls), 0.05)
        if pair + 1 >= trials and (min(on_walls) - min(off_walls)) < budget_s:
            break
    off_best, on_best = min(off_walls), min(on_walls)
    budget_s = max(0.01 * off_best, 0.05)
    delta = (on_best - off_best) / off_best
    report(
        "forensics_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(on_walls),
            "sample_cadence_s": forensics.sample_cadence_s(),
            "disabled_trials_s": [round(t, 3) for t in off_walls],
            "enabled_trials_s": [round(t, 3) for t in on_walls],
            "disabled_best_s": round(off_best, 3),
            "enabled_best_s": round(on_best, 3),
            "overhead_pct": round(delta * 100, 3),
        },
        data_bytes=nbytes,
    )
    assert (on_best - off_best) < budget_s, (
        f"always-on hang-watchdog overhead {delta * 100:.2f}% over the 1% "
        f"budget (disabled best {off_best:.3f}s vs enabled best "
        f"{on_best:.3f}s, floor 50 ms)"
    )


def histogram_overhead(trials: int = 5) -> None:
    """Histogram-instrument overhead on a ~2 GiB save with the telemetry
    bus ENABLED (the configuration where the instruments actually fire):
    fully wired (shipping ``histogram_observe`` — bucket math + one
    uncontended lock per observation, per sub-chunk and per entry) vs
    the same enabled bus with the instrument bypassed to a raw no-op.
    Asserts best-vs-best delta < 1% with a 50 ms floor (ISSUE 8
    acceptance; same paired/alternating bimodal-host recipe as the legs
    above — noise only ever inflates a wall time, so each leg's min is
    its honest cost)."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    observed = [0]

    def timed_save() -> float:
        root = tempfile.mkdtemp(prefix="hist_overhead_")
        try:
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)
            observed[0] = max(
                observed[0],
                sum(
                    h["count"]
                    for by_key in telemetry.histograms().values()
                    for h in by_key.values()
                ),
            )
            telemetry.reset()  # drop the op's events between trials

    def bypassed(fn):
        # Call sites resolve ``telemetry.histogram_observe`` at call
        # time, so patching the package attribute bypasses every wired
        # instrument (scheduler, retry tier, pg_wrapper) at once.
        saved = telemetry.histogram_observe
        telemetry.histogram_observe = lambda name, seconds, key=None: None
        try:
            return fn()
        finally:
            telemetry.histogram_observe = saved

    telemetry.set_enabled(True)
    try:
        timed_save()  # discarded warmup (staging-pool first-touch faults)
        on_walls, off_walls = [], []
        max_pairs = 2 * trials
        for pair in range(max_pairs):
            if pair % 2 == 0:
                off = bypassed(timed_save)
                on = timed_save()
            else:
                on = timed_save()
                off = bypassed(timed_save)
            on_walls.append(on)
            off_walls.append(off)
            budget_s = max(0.01 * min(off_walls), 0.05)
            if pair + 1 >= trials and (
                min(on_walls) - min(off_walls)
            ) < budget_s:
                break
        n_observations = observed[0]
    finally:
        telemetry.set_enabled(False)
        telemetry.reset()
    off_best, on_best = min(off_walls), min(on_walls)
    budget_s = max(0.01 * off_best, 0.05)
    delta = (on_best - off_best) / off_best
    report(
        "histogram_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(on_walls),
            "bypassed_trials_s": [round(t, 3) for t in off_walls],
            "wired_trials_s": [round(t, 3) for t in on_walls],
            "bypassed_best_s": round(off_best, 3),
            "wired_best_s": round(on_best, 3),
            "overhead_pct": round(delta * 100, 3),
            "observations_last_save": n_observations,
        },
        data_bytes=nbytes,
    )
    assert (on_best - off_best) < budget_s, (
        f"histogram-instrument overhead {delta * 100:.2f}% over the 1% "
        f"budget (bypassed best {off_best:.3f}s vs wired best "
        f"{on_best:.3f}s, floor 50 ms)"
    )


def native_io_overhead(trials: int = 5) -> None:
    """Elected-native vs never-forced on the ~2 GiB save (ISSUE 9
    acceptance): with the io_uring engine elected (the shipping auto
    election on a host where the probe succeeds), the save must never be
    SLOWER than the forced Python path beyond the 1% budget with the
    50 ms floor — the engine may win, but electing it can never cost.
    Same paired/alternating bimodal-host recipe as the legs above.
    Skips (reported, not failed) when the engine probe fails — there is
    no native leg to measure on such a host."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, native_io

    if native_io.engine_kind() is None:
        report("native_io_overhead", {"skipped": "no native engine"})
        return

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    def timed_save(mode: str) -> float:
        os.environ["TORCHSNAPSHOT_TPU_NATIVE_IO"] = mode
        root = tempfile.mkdtemp(prefix="native_overhead_")
        try:
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)

    saved_mode = os.environ.get("TORCHSNAPSHOT_TPU_NATIVE_IO")
    try:
        timed_save("never")  # discarded warmup (pool + page-cache faults)
        native_walls, python_walls = [], []
        max_pairs = 2 * trials
        for pair in range(max_pairs):
            if pair % 2 == 0:
                py = timed_save("never")
                nat = timed_save("always")
            else:
                nat = timed_save("always")
                py = timed_save("never")
            native_walls.append(nat)
            python_walls.append(py)
            budget_s = max(0.01 * min(python_walls), 0.05)
            if pair + 1 >= trials and (
                min(native_walls) - min(python_walls)
            ) < budget_s:
                break
    finally:
        if saved_mode is None:
            os.environ.pop("TORCHSNAPSHOT_TPU_NATIVE_IO", None)
        else:
            os.environ["TORCHSNAPSHOT_TPU_NATIVE_IO"] = saved_mode
    python_best, native_best = min(python_walls), min(native_walls)
    budget_s = max(0.01 * python_best, 0.05)
    delta = (native_best - python_best) / python_best
    report(
        "native_io_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(native_walls),
            "python_trials_s": [round(t, 3) for t in python_walls],
            "native_trials_s": [round(t, 3) for t in native_walls],
            "python_best_s": round(python_best, 3),
            "native_best_s": round(native_best, 3),
            "native_vs_python_pct": round(delta * 100, 3),
        },
        data_bytes=nbytes,
    )
    assert (native_best - python_best) < budget_s, (
        f"elected-native save {delta * 100:.2f}% slower than the Python "
        f"path (python best {python_best:.3f}s vs native best "
        f"{native_best:.3f}s, 1% budget with 50 ms floor)"
    )


def store_overhead(trials: int = 5, ops: int = 3000) -> None:
    """Disabled-path overhead of the store replication tier (ISSUE 6
    acceptance): with replication OFF (no replicas joined — the shipping
    single-host configuration), the client's (client_id, seq) stamp is
    ALREADY skipped by design (it only arms once a failover target is
    known), so the residual per-op cost is the server's log/dedup
    bookkeeping and role/registry checks. Times ``ops`` mixed KV round
    trips as shipped vs with that server bookkeeping bypassed
    (``_MUTATING_OPS`` emptied — read per call), and asserts
    best-vs-best delta < 1% with a 5 ms absolute floor (same
    bimodal-host recipe as the injector gate above: loopback RTT noise
    only ever inflates). The stamped path's cost is intentionally NOT
    gated here — it only runs in replicated deployments, where one
    extra µs per metadata op is noise against real network RTTs."""
    from torchsnapshot_tpu import dist_store

    store = dist_store.TCPStore("127.0.0.1", is_server=True, timeout=30.0)

    def timed() -> float:
        t0 = time.perf_counter()
        for i in range(ops // 4):
            k = f"k{i & 255}"
            store.set(k, b"v")
            store.add("ctr", 1)
            store.check(k)
            store.get(k)
        return time.perf_counter() - t0

    def bypassed(fn):
        saved = dist_store._MUTATING_OPS
        dist_store._MUTATING_OPS = frozenset()
        try:
            return fn()
        finally:
            dist_store._MUTATING_OPS = saved

    try:
        timed()  # warmup: connection buffers, dict growth, allocator
        shipped_walls, bypass_walls = [], []
        for pair in range(trials):
            if pair % 2 == 0:
                byp = bypassed(timed)
                shp = timed()
            else:
                shp = timed()
                byp = bypassed(timed)
            bypass_walls.append(byp)
            shipped_walls.append(shp)
        bypass_best = min(bypass_walls)
        shipped_best = min(shipped_walls)
        budget_s = max(0.01 * bypass_best, 0.005)
        delta = (shipped_best - bypass_best) / bypass_best
        report(
            "store_overhead",
            {
                "ops": ops,
                "pairs": len(bypass_walls),
                "bypass_trials_s": [round(t, 4) for t in bypass_walls],
                "shipped_trials_s": [round(t, 4) for t in shipped_walls],
                "bypass_best_s": round(bypass_best, 4),
                "shipped_best_s": round(shipped_best, 4),
                "overhead_pct": round(delta * 100, 3),
                "per_op_us": round(shipped_best / ops * 1e6, 2),
            },
        )
        assert (shipped_best - bypass_best) < budget_s, (
            f"disabled-path store overhead {delta * 100:.2f}% over the 1% "
            f"budget (bypass best {bypass_best:.4f}s vs shipped best "
            f"{shipped_best:.4f}s, floor 5 ms)"
        )
    finally:
        store.close()


def journal_overhead(trials: int = 5) -> None:
    """Disabled-path overhead of the delta journal (ISSUE 14): a ~2 GiB
    CheckpointManager save with journaling off (the shipping default —
    ``_journal_seed`` runs one ``enabled_by_env`` check after the commit
    and returns) vs that hook bypassed to a raw no-op. Best-vs-best < 1%
    with the 50 ms floor, same bimodal-host recipe as the injector gate.
    The ENABLED path (fingerprinting, appends) is a measured trade-off,
    not a gate — see bench.py's journal leg / BENCH_r12.json."""
    import numpy as np

    from torchsnapshot_tpu import CheckpointManager, StateDict
    from torchsnapshot_tpu import manager as manager_mod

    os.environ.pop("TORCHSNAPSHOT_TPU_JOURNAL", None)

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    try:
        import psutil
    except ImportError:  # pragma: no cover - baked into the image
        psutil = None
    proc = psutil.Process() if psutil is not None else None

    def timed_save() -> tuple:
        root = tempfile.mkdtemp(prefix="journal_overhead_")
        try:
            mgr = CheckpointManager(root, save_interval_steps=1)
            cpu0 = proc.cpu_times() if proc is not None else None
            t0 = time.perf_counter()
            mgr.save(0, state)
            wall = time.perf_counter() - t0
            if cpu0 is None:
                return wall, 1.0
            cpu1 = proc.cpu_times()
            busy = (cpu1.user - cpu0.user) + (cpu1.system - cpu0.system)
            return wall, busy / max(wall, 1e-9)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def bypassed(fn):
        saved = manager_mod.CheckpointManager._journal_seed
        manager_mod.CheckpointManager._journal_seed = (
            lambda self, step, app_state: None
        )
        try:
            return fn()
        finally:
            manager_mod.CheckpointManager._journal_seed = saved

    timed_save()  # warmup: staging-pool first touch, page cache
    bypass_walls, shim_walls = [], []
    contended = []
    max_pairs = 2 * trials
    for pair in range(max_pairs):
        if pair % 2 == 0:
            byp, byp_ratio = bypassed(timed_save)
            shim, shim_ratio = timed_save()
        else:
            shim, shim_ratio = timed_save()
            byp, byp_ratio = bypassed(timed_save)
        if proc is not None and min(byp_ratio, shim_ratio) < 0.6:
            contended.append(
                {"bypass_s": round(byp, 3), "shim_s": round(shim, 3)}
            )
        bypass_walls.append(byp)
        shim_walls.append(shim)
        budget_s = max(0.01 * min(bypass_walls), 0.05)
        if pair + 1 >= trials and (
            min(shim_walls) - min(bypass_walls)
        ) < budget_s:
            break
    bypass_best = min(bypass_walls)
    shim_best = min(shim_walls)
    budget_s = max(0.01 * bypass_best, 0.05)
    delta = (shim_best - bypass_best) / bypass_best
    report(
        "journal_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(bypass_walls),
            "bypass_trials_s": [round(t, 3) for t in bypass_walls],
            "shim_trials_s": [round(t, 3) for t in shim_walls],
            "bypass_best_s": round(bypass_best, 3),
            "shim_best_s": round(shim_best, 3),
            "overhead_pct": round(delta * 100, 3),
            "contended_pairs": contended,
        },
        data_bytes=nbytes,
    )
    assert (shim_best - bypass_best) < budget_s, (
        f"disabled-journal overhead {delta * 100:.2f}% over the 1% budget "
        f"(bypass best {bypass_best:.3f}s vs shipping best "
        f"{shim_best:.3f}s, floor 50 ms)"
    )


def distrib_overhead(trials: int = 5) -> None:
    """Disabled-path overhead of the fleet seeding tier (ISSUE 16): a
    ~2 GiB restore with seeding off (the shipping default —
    ``maybe_wrap_restore`` runs one env check and returns the storage
    untouched) vs that hook bypassed to a raw passthrough lambda.
    Best-vs-best < 1% with the 50 ms floor, same bimodal-host recipe as
    the legs above. The ENABLED path (registry lookups, peer fetches) is
    a measured trade-off on throttled storage, not a gate — see
    bench.py's fleet-distribution leg / BENCH_r13.json."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, distrib

    os.environ.pop("TORCHSNAPSHOT_TPU_SEED_RESTORE", None)

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }
    root = tempfile.mkdtemp(prefix="distrib_overhead_")
    snap = os.path.join(root, "s")
    dst = {
        "model": StateDict(
            **{k: np.zeros_like(v) for k, v in state["model"].items()}
        )
    }

    def timed_restore() -> float:
        t0 = time.perf_counter()
        Snapshot(snap).restore(dst)
        return time.perf_counter() - t0

    def bypassed(fn):
        # snapshot.py resolves the hook as a distrib attribute at call
        # time, so patching the module function bypasses the env check
        # entirely — the honest zero-cost floor.
        saved = distrib.maybe_wrap_restore
        distrib.maybe_wrap_restore = (
            lambda storage, path, pg_wrapper=None: (storage, None)
        )
        try:
            return fn()
        finally:
            distrib.maybe_wrap_restore = saved

    try:
        Snapshot.take(snap, state)
        timed_restore()  # discarded warmup (page cache, pool first touch)
        bypass_walls, shim_walls = [], []
        max_pairs = 2 * trials
        for pair in range(max_pairs):
            if pair % 2 == 0:
                byp = bypassed(timed_restore)
                shim = timed_restore()
            else:
                shim = timed_restore()
                byp = bypassed(timed_restore)
            bypass_walls.append(byp)
            shim_walls.append(shim)
            budget_s = max(0.01 * min(bypass_walls), 0.05)
            if pair + 1 >= trials and (
                min(shim_walls) - min(bypass_walls)
            ) < budget_s:
                break
    finally:
        shutil.rmtree(root, ignore_errors=True)
    bypass_best = min(bypass_walls)
    shim_best = min(shim_walls)
    budget_s = max(0.01 * bypass_best, 0.05)
    delta = (shim_best - bypass_best) / bypass_best
    report(
        "distrib_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(bypass_walls),
            "bypass_trials_s": [round(t, 3) for t in bypass_walls],
            "shim_trials_s": [round(t, 3) for t in shim_walls],
            "bypass_best_s": round(bypass_best, 3),
            "shim_best_s": round(shim_best, 3),
            "overhead_pct": round(delta * 100, 3),
        },
        data_bytes=nbytes,
    )
    assert (shim_best - bypass_best) < budget_s, (
        f"disabled-seeding restore overhead {delta * 100:.2f}% over the 1% "
        f"budget (bypass best {bypass_best:.3f}s vs shipping best "
        f"{shim_best:.3f}s, floor 50 ms)"
    )


def tenancy_overhead(trials: int = 5) -> None:
    """Disabled-path overhead of the multi-tenant plane (ISSUE 17): a
    ~2 GiB save with no tenant configured (the shipping default —
    ``tenancy_admission.maybe_arm`` runs one contextvar read + one env
    check and returns None; the scheduler's admission getattr misses)
    vs the arm/disarm hooks bypassed to raw no-op lambdas. Best-vs-best
    < 1% with the 50 ms floor, same bimodal-host recipe as the legs
    above. The ENABLED path (namespacing, quota, pacing) is a measured
    trade-off — see bench.py's tenancy leg / BENCH_r14.json."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, snapshot
    from torchsnapshot_tpu.tenancy import TENANT_ENV_VAR

    os.environ.pop(TENANT_ENV_VAR, None)

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    def timed_save() -> float:
        root = tempfile.mkdtemp(prefix="tenancy_overhead_")
        try:
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def bypassed(fn):
        # snapshot.py resolves the hooks as module attributes at call
        # time, so patching them bypasses even the env check — the
        # honest zero-cost floor.
        saved_arm = snapshot.tenancy_admission.maybe_arm
        saved_disarm = snapshot.tenancy_admission.disarm
        snapshot.tenancy_admission.maybe_arm = (
            lambda op, storage=None, pg_wrapper=None, tenant=None: None
        )
        snapshot.tenancy_admission.disarm = lambda storage, session: None
        try:
            return fn()
        finally:
            snapshot.tenancy_admission.maybe_arm = saved_arm
            snapshot.tenancy_admission.disarm = saved_disarm

    timed_save()  # discarded warmup (staging-pool first-touch faults)
    bypass_walls, shim_walls = [], []
    max_pairs = 2 * trials
    for pair in range(max_pairs):
        if pair % 2 == 0:
            byp = bypassed(timed_save)
            shim = timed_save()
        else:
            shim = timed_save()
            byp = bypassed(timed_save)
        bypass_walls.append(byp)
        shim_walls.append(shim)
        budget_s = max(0.01 * min(bypass_walls), 0.05)
        if pair + 1 >= trials and (
            min(shim_walls) - min(bypass_walls)
        ) < budget_s:
            break
    bypass_best = min(bypass_walls)
    shim_best = min(shim_walls)
    budget_s = max(0.01 * bypass_best, 0.05)
    delta = (shim_best - bypass_best) / bypass_best
    report(
        "tenancy_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(bypass_walls),
            "bypass_trials_s": [round(t, 3) for t in bypass_walls],
            "shim_trials_s": [round(t, 3) for t in shim_walls],
            "bypass_best_s": round(bypass_best, 3),
            "shim_best_s": round(shim_best, 3),
            "overhead_pct": round(delta * 100, 3),
        },
        data_bytes=nbytes,
    )
    assert (shim_best - bypass_best) < budget_s, (
        f"disabled-tenancy save overhead {delta * 100:.2f}% over the 1% "
        f"budget (bypass best {bypass_best:.3f}s vs shipping best "
        f"{shim_best:.3f}s, floor 50 ms)"
    )


def autotune_overhead(trials: int = 5) -> None:
    """Closed-loop autotune overhead on a ~2 GiB save: the shipping
    default (``TORCHSNAPSHOT_TPU_AUTOTUNE=auto``) vs hard-disabled
    (``=never``, one env check per election). Telemetry is enabled on
    BOTH legs so the attribution verdict exists — the delta isolates
    the tuner machinery, not the bus. The governor is reset before
    EVERY save (both legs): each save models a fresh process's FIRST
    take, which on the auto leg walks the full plane — mode parse,
    profile probe against the root journal, election resolution,
    post-commit verdict scoring, and the profile journal append —
    while excluding cross-save learning drift (on this host's
    page-cache-noisy disk the walls swing 20x for identical settings;
    what the tuner LEARNS from such a signal is benchmarks/autotune.py's
    problem, gated there under a deterministic storage model — this
    gate prices the machinery). Asserts best-vs-best delta < 1% with a
    50 ms floor (ISSUE 19 acceptance; same paired/alternating recipe
    as the gates above)."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry
    from torchsnapshot_tpu.scheduler import reset_io_governor

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    def timed_save() -> float:
        reset_io_governor()  # every save is a fresh process's first take
        root = tempfile.mkdtemp(prefix="autotune_overhead_")
        try:
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(root, "s"), state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def leg(mode: str) -> float:
        saved = os.environ.get("TORCHSNAPSHOT_TPU_AUTOTUNE")
        os.environ["TORCHSNAPSHOT_TPU_AUTOTUNE"] = mode
        try:
            return timed_save()
        finally:
            if saved is None:
                os.environ.pop("TORCHSNAPSHOT_TPU_AUTOTUNE", None)
            else:
                os.environ["TORCHSNAPSHOT_TPU_AUTOTUNE"] = saved

    telemetry.set_enabled(True)
    try:
        # Fresh governor + discarded warmup (staging-pool first touch).
        reset_io_governor()
        leg("never")
        off_walls, auto_walls = [], []
        max_pairs = 2 * trials
        for pair in range(max_pairs):
            if pair % 2 == 0:
                off = leg("never")
                auto = leg("auto")
            else:
                auto = leg("auto")
                off = leg("never")
            off_walls.append(off)
            auto_walls.append(auto)
            budget_s = max(0.01 * min(off_walls), 0.05)
            if pair + 1 >= trials and (
                min(auto_walls) - min(off_walls)
            ) < budget_s:
                break
    finally:
        telemetry.set_enabled(False)
        reset_io_governor()
    off_best = min(off_walls)
    auto_best = min(auto_walls)
    budget_s = max(0.01 * off_best, 0.05)
    delta = (auto_best - off_best) / off_best
    report(
        "autotune_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(off_walls),
            "never_trials_s": [round(t, 3) for t in off_walls],
            "auto_trials_s": [round(t, 3) for t in auto_walls],
            "never_best_s": round(off_best, 3),
            "auto_best_s": round(auto_best, 3),
            "overhead_pct": round(delta * 100, 3),
        },
        data_bytes=nbytes,
    )
    assert (auto_best - off_best) < budget_s, (
        f"autotune overhead {delta * 100:.2f}% over the 1% budget "
        f"(never best {off_best:.3f}s vs auto best {auto_best:.3f}s, "
        f"floor 50 ms)"
    )


def georep_overhead(trials: int = 5) -> None:
    """Disabled-path overhead of the geo-replication tier (ISSUE 20): a
    ~2 GiB CheckpointManager save with no remote configured (the
    shipping default — one ``remote_url`` env check at construction,
    one attribute check after the commit) vs that env check bypassed to
    a raw ``None``. Best-vs-best < 1% with the 50 ms floor, same
    bimodal-host recipe as the injector gate. The ENABLED path's cost
    (WAN shipping) is measured, not gated — see bench.py's georep leg /
    BENCH_r17.json and its foreground gate for the armed shipper."""
    import numpy as np

    from torchsnapshot_tpu import CheckpointManager, StateDict
    from torchsnapshot_tpu import georep as georep_mod

    os.environ.pop("TORCHSNAPSHOT_TPU_GEOREP", None)

    nbytes = 2 << 30
    n_arrays = 8
    per = nbytes // n_arrays // 4
    state = {
        "model": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(per)
                .astype(np.float32)
                for i in range(n_arrays)
            }
        )
    }

    def timed_save() -> float:
        root = tempfile.mkdtemp(prefix="georep_overhead_")
        try:
            mgr = CheckpointManager(root, save_interval_steps=1)
            t0 = time.perf_counter()
            mgr.save(0, state)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def bypassed(fn):
        saved = georep_mod.remote_url
        georep_mod.remote_url = lambda: None
        try:
            return fn()
        finally:
            georep_mod.remote_url = saved

    timed_save()  # warmup: staging-pool first touch, page cache
    bypass_walls, shim_walls = [], []
    max_pairs = 2 * trials
    for pair in range(max_pairs):
        if pair % 2 == 0:
            byp = bypassed(timed_save)
            shim = timed_save()
        else:
            shim = timed_save()
            byp = bypassed(timed_save)
        bypass_walls.append(byp)
        shim_walls.append(shim)
        budget_s = max(0.01 * min(bypass_walls), 0.05)
        if pair + 1 >= trials and (
            min(shim_walls) - min(bypass_walls)
        ) < budget_s:
            break
    bypass_best = min(bypass_walls)
    shim_best = min(shim_walls)
    budget_s = max(0.01 * bypass_best, 0.05)
    delta = (shim_best - bypass_best) / bypass_best
    report(
        "georep_overhead",
        {
            "gib": round(nbytes / (1 << 30), 2),
            "pairs": len(bypass_walls),
            "bypass_trials_s": [round(t, 3) for t in bypass_walls],
            "shim_trials_s": [round(t, 3) for t in shim_walls],
            "bypass_best_s": round(bypass_best, 3),
            "shim_best_s": round(shim_best, 3),
            "overhead_pct": round(delta * 100, 3),
        },
        data_bytes=nbytes,
    )
    assert (shim_best - bypass_best) < budget_s, (
        f"disabled-georep overhead {delta * 100:.2f}% over the 1% budget "
        f"(bypass best {bypass_best:.3f}s vs shipping best "
        f"{shim_best:.3f}s, floor 50 ms)"
    )


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--soak", action="store_true")
    parser.add_argument("--overhead", action="store_true")
    parser.add_argument("--iterations", type=int, default=40)
    parser.add_argument("--seed", type=int, default=0xC4A05)
    parser.add_argument("--trials", type=int, default=5)
    args = parser.parse_args()
    if not (args.soak or args.overhead):
        args.soak = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.soak:
        soak(args.iterations, args.seed)
    if args.overhead:
        overhead(args.trials)
        flightrec_overhead(args.trials)
        forensics_overhead(args.trials)
        histogram_overhead(args.trials)
        native_io_overhead(args.trials)
        store_overhead(args.trials)
        journal_overhead(args.trials)
        distrib_overhead(args.trials)
        tenancy_overhead(args.trials)
        autotune_overhead(args.trials)
        georep_overhead(args.trials)


if __name__ == "__main__":
    main()
