"""Delta-journal RPO leg (ISSUE 14): recoverable-state interval and
append throughput vs the full-save cadence, on throttled storage.

The RPO model (docs/source/fault_tolerance.rst): with a sustained
checkpoint-overhead budget ``f`` (the fraction of wall time a training
loop will spend inside checkpointing), durability can occur at most
every ``cost / f`` seconds — that interval IS the recovery point
objective, the training time a crash can lose. A full snapshot of an
``N``-byte state pays ``N`` bytes of storage bandwidth no matter how
little changed; a journal epoch pays one in-memory fingerprint scan
plus storage bandwidth for the DIRTY bytes only. At EQUAL sustained
overhead the RPO ratio is ``T_full / T_epoch`` — the quantity this leg
measures and gates (>= 10x, the ISSUE 14 acceptance).

Storage is throttled to THROTTLE_BPS with the same single-rate-lock
model as coop_restore.py/reshard_throughput.py (the shared-filer regime
journaling exists for — on tmpfs a "write" is a memcpy and every
checkpoint scheme is equally free). The throttle is applied
symmetrically: the fs plugin's payload writes AND the journal's segment
appends both pay transfer time for the bytes they push, so the ratio
measures bytes-moved, not which code path moved them. The journal
side's fingerprint scan runs at memory bandwidth and is measured, not
modeled.

The workload is the scenario journaling exists for: a mostly-frozen
state (large base arrays) with a small hot set mutating every step —
embedding rows, a fine-tuned head, optimizer scalars — including MANY
SMALL ARRAYS, the append path's worst case (per-record framing + CRC
dominates when payloads are tiny). Both legs are best-of-N on the same
root; the journal leg re-arms on the same committed base every trial,
so it measures a steady-state epoch, not a first-touch.

Emits one JSON line per leg plus a ``journal_rpo/summary`` line
(bench.py's ``_journal_leg`` persists that to BENCH_r12.json).

Usage: JAX_PLATFORMS=cpu python benchmarks/journal_rpo.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

# Simulated per-host storage write bandwidth. In family with the other
# throttled legs (coop_restore 40 MB/s, reshard_throughput 20 MB/s):
# a contended shared filer's per-host share, the regime where cadence
# is bandwidth-bound and the journal's bytes-not-moved are the win.
THROTTLE_BPS = 50e6

# Sustained-overhead budget used to EXPRESS costs as RPO seconds. The
# ratio is budget-independent; 1% is the fleet-typical checkpoint
# overhead BENCHMARKS.md quotes.
OVERHEAD_BUDGET = 0.01
FULL_TRIALS = 2
EPOCH_TRIALS = 3


def _throttle_writes():
    """Charge THROTTLE_BPS transfer time for every payload byte written
    to storage, through one rate lock per pipe (concurrent writers share
    the simulated bandwidth — independent sleeps would let I/O
    concurrency multiply it away). Patches the fs plugin's buffered and
    streaming payload writes AND the journal's segment append, so both
    cadence schemes pay for exactly the bytes they move."""
    import asyncio
    import threading

    from torchsnapshot_tpu import journal as journal_mod
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    # Each save spins up its own event loop, so the pipe lock is rebuilt
    # per loop (a Lock is bound to the loop that created it).
    async_lock: list = [None, None]

    async def _pay_async(n: int) -> None:
        loop = asyncio.get_running_loop()
        if async_lock[1] is not loop:
            async_lock[0] = asyncio.Lock()
            async_lock[1] = loop
        async with async_lock[0]:
            await asyncio.sleep(n / THROTTLE_BPS)

    def _is_payload(path: str) -> bool:
        # Manager-layout payload paths are "<rank>/<key>_<i>"; control
        # files (.snapshot_fence/.snapshot_metadata/...) are dotfiles.
        return not os.path.basename(path).startswith(".")

    orig_write = FSStoragePlugin.write

    async def slow_write(self, write_io, _orig=orig_write):
        await _orig(self, write_io)
        if _is_payload(write_io.path):
            await _pay_async(memoryview(write_io.buf).nbytes)

    FSStoragePlugin.write = slow_write

    # Streaming sub-chunks are payload by construction; _pwrite_all runs
    # in executor threads, so its share of the pipe is a thread lock.
    thread_lock = threading.Lock()
    orig_pwrite = FSStoragePlugin.__dict__["_pwrite_all"].__func__

    def slow_pwrite(fd, buf, offset, _orig=orig_pwrite):
        written = _orig(fd, buf, offset)
        with thread_lock:
            time.sleep(written / THROTTLE_BPS)
        return written

    FSStoragePlugin._pwrite_all = staticmethod(slow_pwrite)

    orig_append = journal_mod.DeltaJournal._append_records

    def slow_append(self, epoch, gen, pending, _orig=orig_append):
        out = _orig(self, epoch, gen, pending)
        nbytes = sum(len(payload) for _, _, payload, _ in pending)
        with thread_lock:
            time.sleep(nbytes / THROTTLE_BPS)
        return out

    journal_mod.DeltaJournal._append_records = slow_append


def _build_state(np):
    """~256 MiB frozen bulk + a hot set of one 2 MiB array and 64 small
    (16 KiB) arrays — the leaves journal epochs will carry."""
    from torchsnapshot_tpu import StateDict

    frozen = {
        f"frozen_{i}": np.random.default_rng(i)
        .standard_normal((64 << 20) // 4)
        .astype(np.float32)
        for i in range(4)
    }
    hot = {"head": np.zeros((2 << 20) // 4, dtype=np.float32)}
    for i in range(64):
        hot[f"emb_{i}"] = np.zeros(4096, dtype=np.float32)
    state = StateDict(**frozen, **hot, step=0)
    hot_bytes = sum(v.nbytes for k, v in hot.items())
    total_bytes = hot_bytes + sum(v.nbytes for v in frozen.values())
    return {"model": state}, total_bytes, hot_bytes


def _mutate_hot(app_state, np, step: int) -> None:
    st = app_state["model"]
    st["head"] = np.full_like(st["head"], float(step))
    for i in range(64):
        st[f"emb_{i}"] = np.full_like(st[f"emb_{i}"], float(step + i))
    st["step"] = step


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["TORCHSNAPSHOT_TPU_JOURNAL"] = "1"
    # The throttle patches the Python fs paths; the io_uring engine
    # would bypass them (and a simulated 50 MB/s pipe has nothing to say
    # about engine choice anyway).
    os.environ["TORCHSNAPSHOT_TPU_NATIVE_IO"] = "never"
    import numpy as np

    from torchsnapshot_tpu import CheckpointManager

    app_state, total_bytes, hot_bytes = _build_state(np)

    root = tempfile.mkdtemp(prefix="journal_rpo_")
    try:
        mgr = CheckpointManager(root, save_interval_steps=1)
        mgr.save(0, app_state)  # unthrottled warmup: staging, page cache
        shutil.rmtree(mgr.path_for(0))
        _throttle_writes()

        # Full-save leg: best-of-N cost of making the WHOLE state
        # durable (what the manager does at every cadence point without
        # a journal, regardless of how little changed).
        full_walls = []
        for t in range(FULL_TRIALS):
            step = 100 + t
            _mutate_hot(app_state, np, step)
            t0 = time.perf_counter()
            mgr.save(step, app_state, force=True)
            full_walls.append(time.perf_counter() - t0)
            if t < FULL_TRIALS - 1:
                shutil.rmtree(mgr.path_for(step))
        t_full = min(full_walls)
        report(
            "journal_rpo/full_save",
            {
                "state_mib": round(total_bytes / (1 << 20), 1),
                "throttle_mb_s": THROTTLE_BPS / 1e6,
                "trials_s": [round(w, 4) for w in full_walls],
                "wall_s": round(t_full, 4),
            },
            data_bytes=total_bytes,
        )

        # Journal leg: best-of-N cost of one epoch carrying only the hot
        # set. Each trial mutates the same leaves again, so every epoch
        # carries the same dirty footprint (steady state). The dominant
        # real cost is the full-state fingerprint scan — measured, not
        # throttled (it moves no storage bytes).
        epoch_walls = []
        base_step = 100 + FULL_TRIALS - 1
        for t in range(EPOCH_TRIALS):
            step = 200 + t
            _mutate_hot(app_state, np, step)
            t0 = time.perf_counter()
            assert mgr.journal_step(step, app_state)
            epoch_walls.append(time.perf_counter() - t0)
        t_epoch = min(epoch_walls)
        jdir = os.path.join(mgr.path_for(base_step), ".journal")
        seg_bytes = sum(
            os.path.getsize(os.path.join(jdir, n))
            for n in os.listdir(jdir)
            if n.endswith(".seg")
        )
        report(
            "journal_rpo/epoch_append",
            {
                "hot_mib": round(hot_bytes / (1 << 20), 2),
                "hot_arrays": 65,
                "trials_s": [round(w, 4) for w in epoch_walls],
                "wall_s": round(t_epoch, 4),
                "segment_bytes_total": seg_bytes,
            },
            data_bytes=hot_bytes,
        )

        # Replay-cost sanity: restoring base + the full epoch chain must
        # stay in the same ballpark as a plain restore (bounded replay;
        # reads are unthrottled — the model only prices writes).
        from torchsnapshot_tpu import StateDict

        dst = {
            "model": StateDict(
                **{
                    k: np.zeros_like(np.asarray(v))
                    for k, v in app_state["model"].items()
                }
            )
        }
        t0 = time.perf_counter()
        restored = mgr.restore(dst)
        t_replay = time.perf_counter() - t0
        assert restored == base_step
        np.testing.assert_array_equal(
            dst["model"]["head"], app_state["model"]["head"]
        )
        report(
            "journal_rpo/restore_with_replay",
            {"epochs": EPOCH_TRIALS, "wall_s": round(t_replay, 4)},
            data_bytes=total_bytes,
        )

        rpo_reduction = t_full / t_epoch
        summary = {
            "benchmark": "journal_rpo/summary",
            "state_mib": round(total_bytes / (1 << 20), 1),
            "hot_mib": round(hot_bytes / (1 << 20), 2),
            "throttle_mb_s": THROTTLE_BPS / 1e6,
            "full_save_s": round(t_full, 4),
            "epoch_append_s": round(t_epoch, 4),
            "append_throughput_mib_s": round(
                hot_bytes / (1 << 20) / t_epoch, 1
            ),
            "overhead_budget": OVERHEAD_BUDGET,
            "rpo_full_save_s": round(t_full / OVERHEAD_BUDGET, 1),
            "rpo_journal_s": round(t_epoch / OVERHEAD_BUDGET, 1),
            "rpo_reduction_x": round(rpo_reduction, 1),
            "restore_with_replay_s": round(t_replay, 4),
        }
        print(json.dumps(summary), flush=True)
        assert rpo_reduction >= 10.0, (
            f"RPO reduction {rpo_reduction:.1f}x < 10x at equal sustained "
            f"overhead (full save {t_full:.3f}s vs epoch {t_epoch:.3f}s)"
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
