"""Device-resident change detection on REAL TPU hardware.

An unchanged incremental resave through the host dedup path costs a full
DtoH transfer + SHA-256 before discovering nothing changed; with
``device_digests=True`` the array is fingerprinted ON DEVICE
(device_digest.py) and only 16 bytes cross to the host. This measures
both paths over the same state, warm (fingerprint jits compiled — the
steady state of a training loop saving every N steps):

- ``device_dedup/unchanged_resave``: wall time of an incremental
  ``Snapshot.take`` whose payloads are all unchanged, host vs device
  detection, best of ``trials``. The speedup scales with state size:
  the host path is DtoH-bandwidth-bound, the device path is one pass at
  HBM bandwidth plus fixed relay roundtrips.

Usage: python benchmarks/device_dedup.py [state_mb] [trials]
Emits one JSON line; exits 2 (no JSON) off-TPU.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from bench_utils import report

    if jax.default_backend() != "tpu":
        print(
            f"not a TPU backend ({jax.default_backend()}); this measures "
            "real DtoH avoidance only",
            file=sys.stderr,
        )
        return 2

    from torchsnapshot_tpu import Snapshot, StateDict

    state_mb = float(sys.argv[1]) if len(sys.argv) > 1 else 8.0
    trials = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    n = int(state_mb * 1e6 / 2 / 2)  # two bf16 arrays

    def fresh(seed):
        # Fresh buffers each trial: jax caches fetched host copies on the
        # Array, which would let the host path skip its DtoH.
        k = jax.random.PRNGKey(seed)
        s = StateDict(
            w=jax.random.normal(k, (n,), jnp.bfloat16),
            b=jax.random.normal(jax.random.fold_in(k, 1), (n,), jnp.bfloat16),
        )
        jax.block_until_ready(list(s.values()))
        return s

    tmp = tempfile.mkdtemp(prefix="device_dedup_")
    try:
        st = fresh(0)
        nbytes = sum(v.nbytes for v in st.values())
        # Base take with device digests compiles the fingerprint jits.
        Snapshot.take(os.path.join(tmp, "base"), {"m": st}, device_digests=True)
        legs = {}
        # host leg pins device_digests=False: with the env opt-in set,
        # kwarg None would resolve to the env and turn the control leg
        # into a second device leg (speedup ~1.0, meaningless).
        for name, kw in (
            ("host", {"device_digests": False}),
            ("device", {"device_digests": True}),
        ):
            times = []
            for trial in range(trials + 1):
                s2 = fresh(0)
                t0 = time.perf_counter()
                Snapshot.take(
                    os.path.join(tmp, f"incr_{name}_{trial}"),
                    {"m": s2},
                    incremental_base=os.path.join(tmp, "base"),
                    **kw,
                )
                times.append(time.perf_counter() - t0)
            legs[name] = times[1:]  # drop the per-leg warm-up trial
        t_host, t_dev = min(legs["host"]), min(legs["device"])
        report(
            "device_dedup/unchanged_resave",
            {
                "state_mb": round(nbytes / 1e6, 1),
                "host_dedup_s": round(t_host, 3),
                "device_dedup_s": round(t_dev, 3),
                "speedup": round(t_host / max(t_dev, 1e-9), 1),
                "platform": "tpu",
            },
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
