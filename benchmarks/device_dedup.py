"""Device-resident change detection on REAL TPU hardware.

An unchanged incremental resave through the host dedup path costs a full
DtoH transfer + SHA-256 before discovering nothing changed; with
``device_digests=True`` the array is fingerprinted ON DEVICE
(device_digest.py) and only 16 bytes cross to the host. This measures
both paths over the same state, warm (fingerprint jits compiled — the
steady state of a training loop saving every N steps):

- ``device_dedup/unchanged_resave``: wall time of an incremental
  ``Snapshot.take`` whose payloads are all unchanged, host vs device
  detection, best of ``trials``. The speedup scales with state size:
  the host path is DtoH-bandwidth-bound, the device path is one pass at
  HBM bandwidth plus fixed relay roundtrips.
- ``device_dedup/chain_reload_restore``: the serving-reload story — a
  process holding step N's state restores step N+1 (incremental on N,
  one small payload changed). Plain restore re-reads + re-transfers
  everything; ``restore(..., device_digests=True)`` fingerprints the
  destination and reads only the changed payload. Timed through
  ``block_until_ready`` on the destination (device_put is async; an
  un-drained plain restore looks artificially instant).

Usage: python benchmarks/device_dedup.py [state_mb] [trials]
Emits one JSON line per leg; exits 2 (no JSON) off-TPU.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from bench_utils import report

    if jax.default_backend() != "tpu":
        print(
            f"not a TPU backend ({jax.default_backend()}); this measures "
            "real DtoH avoidance only",
            file=sys.stderr,
        )
        return 2

    from torchsnapshot_tpu import Snapshot, StateDict

    state_mb = float(sys.argv[1]) if len(sys.argv) > 1 else 8.0
    trials = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    n = int(state_mb * 1e6 / 2 / 2)  # two bf16 arrays

    def fresh(seed):
        # Fresh buffers each trial: jax caches fetched host copies on the
        # Array, which would let the host path skip its DtoH.
        k = jax.random.PRNGKey(seed)
        s = StateDict(
            w=jax.random.normal(k, (n,), jnp.bfloat16),
            b=jax.random.normal(jax.random.fold_in(k, 1), (n,), jnp.bfloat16),
        )
        jax.block_until_ready(list(s.values()))
        return s

    tmp = tempfile.mkdtemp(prefix="device_dedup_")
    try:
        st = fresh(0)
        nbytes = sum(v.nbytes for v in st.values())
        # Base take with device digests compiles the fingerprint jits.
        Snapshot.take(os.path.join(tmp, "base"), {"m": st}, device_digests=True)
        legs = {}
        # host leg pins device_digests=False: with the env opt-in set,
        # kwarg None would resolve to the env and turn the control leg
        # into a second device leg (speedup ~1.0, meaningless).
        for name, kw in (
            ("host", {"device_digests": False}),
            ("device", {"device_digests": True}),
        ):
            times = []
            for trial in range(trials + 1):
                s2 = fresh(0)
                t0 = time.perf_counter()
                Snapshot.take(
                    os.path.join(tmp, f"incr_{name}_{trial}"),
                    {"m": s2},
                    incremental_base=os.path.join(tmp, "base"),
                    **kw,
                )
                times.append(time.perf_counter() - t0)
            legs[name] = times[1:]  # drop the per-leg warm-up trial
        t_host, t_dev = min(legs["host"]), min(legs["device"])
        report(
            "device_dedup/unchanged_resave",
            {
                "state_mb": round(nbytes / 1e6, 1),
                "host_dedup_s": round(t_host, 3),
                "device_dedup_s": round(t_dev, 3),
                "speedup": round(t_host / max(t_dev, 1e-9), 1),
                "platform": "tpu",
            },
        )

        # ---- restore side: reload step N+1 while holding step N -------
        # The skip trades ~one relay roundtrip per array (fingerprint
        # dispatch + 16-byte fetch) against the payload's read + HtoD.
        # Through this tunnel the roundtrip is ~70 ms, so the leg uses a
        # 3x state to sit clearly past breakeven; on non-tunneled links
        # (RTT ~0.1 ms, HtoD GB/s) breakeven is ~1 MB per array.
        def fresh_big(seed):
            k = jax.random.PRNGKey(seed)
            s = StateDict(
                w=jax.random.normal(k, (3 * n,), jnp.bfloat16),
                b=jax.random.normal(jax.random.fold_in(k, 1), (3 * n,), jnp.bfloat16),
            )
            jax.block_until_ready(list(s.values()))
            return s

        st = fresh_big(0)
        restore_nbytes = sum(v.nbytes for v in st.values())
        adapter = jax.random.normal(jax.random.PRNGKey(7), (64, 64), jnp.float32)
        s0, s1 = os.path.join(tmp, "r0"), os.path.join(tmp, "r1")
        Snapshot.take(
            s0, {"m": StateDict(**st, a=adapter)}, device_digests=True
        )
        Snapshot.take(
            s1,
            {"m": StateDict(**{k: v + 0 for k, v in st.items()}, a=adapter * 2)},
            incremental_base=s0,
            device_digests=True,
        )
        restore_legs = {}
        # plain leg pins device_digests=False for the same reason as the
        # take-side host leg: the env opt-in must not contaminate the
        # control.
        for name, kw in (
            ("plain", {"device_digests": False}),
            ("digest", {"device_digests": True}),
        ):
            times = []
            for trial in range(trials + 1):
                dst = {
                    "m": StateDict(
                        **{k: v + 0 for k, v in st.items()}, a=adapter + 0
                    )
                }
                jax.block_until_ready(list(dst["m"].values()))
                t0 = time.perf_counter()
                Snapshot(s1).restore(dst, **kw)
                jax.block_until_ready(list(dst["m"].values()))
                times.append(time.perf_counter() - t0)
            restore_legs[name] = min(times[1:])
        report(
            "device_dedup/chain_reload_restore",
            {
                "state_mb": round(restore_nbytes / 1e6, 1),
                "plain_restore_s": round(restore_legs["plain"], 3),
                "digest_restore_s": round(restore_legs["digest"], 3),
                "speedup": round(
                    restore_legs["plain"] / max(restore_legs["digest"], 1e-9), 1
                ),
                "platform": "tpu",
            },
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
