"""Head-to-head: torchsnapshot_tpu vs orbax (the incumbent JAX checkpointer).

Saves and restores the same pytree of bf16 arrays with both libraries on
the same storage and reports wall time + GB/s each way. Sizes default to
1 GiB; pass GiB as argv[1].

Usage: JAX_PLATFORMS=cpu python benchmarks/vs_orbax.py [gib]
Emits one JSON line per (library, direction) via bench_utils.report.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench_utils import report

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    gib = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    total = int(gib * (1 << 30))
    n_arrays = 16
    side = int((total / n_arrays / 2) ** 0.5)
    key = jax.random.PRNGKey(0)
    state = {}
    for i in range(n_arrays):
        key, sub = jax.random.split(key)
        state[f"param_{i}"] = jax.random.normal(sub, (side, side), jnp.bfloat16)
    jax.block_until_ready(state)
    nbytes = sum(a.nbytes for a in state.values())
    print(f"[vs_orbax] state {nbytes / 1e9:.2f} GB", file=sys.stderr, flush=True)

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(prefix="tsnap_vs_orbax_", dir=base)
    try:
        results = {}

        # --- torchsnapshot_tpu ------------------------------------------
        from torchsnapshot_tpu import Snapshot, StateDict

        t0 = time.perf_counter()
        Snapshot.take(f"{tmp}/tsnap", {"m": StateDict(**state)})
        results["tsnap_save"] = time.perf_counter() - t0

        dst = StateDict(**{k: jnp.zeros_like(v) for k, v in state.items()})
        t0 = time.perf_counter()
        Snapshot(f"{tmp}/tsnap").restore({"m": dst})
        results["tsnap_restore"] = time.perf_counter() - t0

        # --- torchsnapshot_tpu incremental (no orbax counterpart) -------
        # The frozen-backbone pattern: second save where only 1/16 of the
        # state changed. Orbax rewrites everything every save; this is the
        # capability gap the dedup layer exists for.
        Snapshot.take(
            f"{tmp}/tsnap_base", {"m": StateDict(**state)}, record_digests=True
        )
        state_inc = dict(state)
        state_inc["param_0"] = state["param_0"] + jnp.bfloat16(1.0)
        jax.block_until_ready(state_inc["param_0"])
        t0 = time.perf_counter()
        Snapshot.take(
            f"{tmp}/tsnap_inc",
            {"m": StateDict(**state_inc)},
            incremental_base=f"{tmp}/tsnap_base",
        )
        results["tsnapincr_save"] = time.perf_counter() - t0

        # --- orbax ------------------------------------------------------
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            t0 = time.perf_counter()
            ckptr.save(f"{tmp}/orbax", dict(state))
            results["orbax_save"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            restored = ckptr.restore(f"{tmp}/orbax")
            results["orbax_restore"] = time.perf_counter() - t0

        # sanity: every reported save restores bit-exactly
        import numpy as np

        for k, src in state.items():
            ref = np.asarray(src, np.float32)
            np.testing.assert_array_equal(np.asarray(dst[k], np.float32), ref)
            np.testing.assert_array_equal(np.asarray(restored[k], np.float32), ref)

        inc_dst = StateDict(**{k: jnp.zeros_like(v) for k, v in state_inc.items()})
        Snapshot(f"{tmp}/tsnap_inc").restore({"m": inc_dst})
        for k, src in state_inc.items():
            np.testing.assert_array_equal(
                np.asarray(inc_dst[k], np.float32), np.asarray(src, np.float32)
            )

        for name, dt in results.items():
            lib, direction = name.split("_")
            other_lib = "orbax" if lib.startswith("tsnap") else "tsnap"
            other = results.get(f"{other_lib}_{direction}")
            report(
                f"vs_orbax_{name}",
                {
                    "platform": jax.default_backend(),
                    "bytes": nbytes,
                    "wall_s": round(dt, 3),
                    "speedup_vs_other": round(other / dt, 2) if other else None,
                },
                data_bytes=nbytes,
            )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
