"""Shared benchmark helpers: timing, RSS tracking, result printing.

Reference analogues: benchmarks/*/main.py print wall times and peak RSS
(e.g. benchmarks/torchrec/main.py:212,231); here every benchmark emits one
JSON object per measured configuration so results are machine-comparable.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Dict, Generator, List


@contextlib.contextmanager
def timed_rss(result: Dict[str, Any]) -> Generator[None, None, None]:
    """Populate result with wall_s and peak_rss_delta_mb for the body."""
    from torchsnapshot_tpu.rss_profiler import RSSProfiler

    prof = RSSProfiler(interval_s=0.05)
    t0 = time.perf_counter()
    with prof:
        yield
    result["wall_s"] = round(time.perf_counter() - t0, 3)
    result["peak_rss_delta_mb"] = round(prof.peak_delta_bytes / 1e6, 1)


def report(name: str, result: Dict[str, Any], data_bytes: int | None = None) -> None:
    out = {"benchmark": name, **result}
    if data_bytes is not None and result.get("wall_s"):
        out["gbps"] = round(data_bytes / 1e9 / result["wall_s"], 3)
    # flush: completed legs must survive a later leg being killed at a
    # timeout (block-buffered stdout to a pipe/file would lose them all).
    print(json.dumps(out), flush=True)


def force_cpu_devices(n: int = 8) -> None:
    """Run on N virtual CPU devices (must be called before first JAX use)."""
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")


def payload_bytes(root: str, include_metadata: bool = False) -> int:
    """Total on-disk bytes under a snapshot root. By default counts only
    payload files (dotfiles — .snapshot_metadata — excluded), so byte-
    reduction claims measure data, not metadata."""
    import os

    total = 0
    for r, _, files in os.walk(root):
        for f in files:
            if include_metadata or not f.startswith("."):
                total += os.path.getsize(os.path.join(r, f))
    return total
