"""Manifest scalability at 70B-GSPMD cardinality.

A 70B model sharded over a pod is ~1000 named parameters × an optimizer
triplet (param, Adam mu/nu) × tens of shards each — ~50k shard entries
in the global manifest. The metadata serialize/parse sits on the commit
and restore critical paths (rank 0 writes ``.snapshot_metadata`` last;
every restoring rank parses it first), and ``_propagate_checksums`` does
a full manifest scan at gather time. YAML (the format's original
carrier, fine at the reference's ~100-entry scale) emits this in ~10 s
and parses in ~15 s; the round-4 JSON emission (valid YAML — old
readers keep working) is ~50x faster on both sides.

Usage: python benchmarks/manifest_scale.py [n_params] [n_ranks]
Emits one JSON line with all legs.

``--columnar`` runs the million-entry leg instead (ISSUE 17): the
binary struct-of-arrays TSCM codec (colmanifest.py) over ~1M shard
leaves — build / encode / decode / restore-plan walls, each bounded.
JSON at this cardinality is the motivating wall; TSCM must hold the
whole leg inside 60 s. Usage:
``python benchmarks/manifest_scale.py --columnar [n_params] [n_ranks]``
(defaults 20834 x 16 ranks x 3 tensors/param = ~1,000,032 leaves).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

from torchsnapshot_tpu.manifest import (  # noqa: E402
    ArrayEntry,
    Shard,
    ShardedArrayEntry,
    SnapshotMetadata,
)
from torchsnapshot_tpu.snapshot import _propagate_checksums  # noqa: E402


def build_manifest(n_params: int, n_ranks: int) -> dict:
    manifest = {}
    for i in range(n_params):
        for kind in ("param", "mu", "nu"):
            shards = [
                Shard(
                    offsets=[r * 512, 0],
                    sizes=[512, 8192],
                    array=ArrayEntry(
                        location=f"sharded/model.layers.{i}.{kind}_{r}",
                        serializer="buffer_protocol",
                        dtype="bfloat16",
                        shape=[512, 8192],
                        byte_range=None,
                        replicated=False,
                        checksum=f"crc32c:{(i * 37 + r) & 0xFFFFFFFF:08x}",
                    ),
                )
                for r in range(n_ranks)
            ]
            manifest[f"0/model/layers.{i}.{kind}"] = ShardedArrayEntry(
                dtype="bfloat16", shape=[512 * n_ranks, 8192], shards=shards
            )
    return manifest


def columnar_main(argv: list) -> int:
    """Million-entry columnar-manifest leg (ISSUE 17 acceptance)."""
    n_params = int(argv[0]) if argv else 20834
    n_ranks = int(argv[1]) if len(argv) > 1 else 16

    from torchsnapshot_tpu import colmanifest
    from torchsnapshot_tpu.manifest import get_available_entries

    t0 = time.perf_counter()
    manifest = build_manifest(n_params, n_ranks)
    t_build = time.perf_counter() - t0
    n_shards = sum(len(e.shards) for e in manifest.values())

    md = SnapshotMetadata(version="bench", world_size=n_ranks, manifest=manifest)
    t0 = time.perf_counter()
    raw = colmanifest.encode_metadata(md)
    t_encode = time.perf_counter() - t0

    t0 = time.perf_counter()
    md2 = colmanifest.decode_metadata(raw)
    t_decode = time.perf_counter() - t0
    assert len(md2.manifest) == len(manifest)

    # Restore-plan wall: what every restoring rank does with the parsed
    # manifest before any byte moves. (The reshard planner leg stays on
    # the 50k default run — its cost is per-plan-unit geometry, not
    # manifest-plane serialization, and 1M units is a different study.)
    t0 = time.perf_counter()
    avail = get_available_entries(md2.manifest, rank=3)
    t_plan = time.perf_counter() - t0
    assert len(avail) == len(manifest)

    total = t_build + t_encode + t_decode + t_plan
    assert total < 60.0, (
        f"columnar leg took {total:.1f}s over {n_shards} shard leaves — "
        "the manifest plane fell onto the commit/restore critical path"
    )

    json_len = len(md.to_yaml())
    report(
        "manifest_scale_columnar",
        {
            "entries": len(manifest),
            "shard_leaves": n_shards,
            "columnar_mb": round(len(raw) / 1e6, 2),
            "json_mb": round(json_len / 1e6, 2),
            "compaction_x": round(json_len / len(raw), 1),
            "build_s": round(t_build, 3),
            "encode_s": round(t_encode, 3),
            "decode_s": round(t_decode, 3),
            "plan_s": round(t_plan, 3),
            "total_s": round(total, 3),
        },
    )
    return 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--columnar":
        return columnar_main(sys.argv[2:])
    n_params = int(sys.argv[1]) if len(sys.argv) > 1 else 1050
    n_ranks = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    manifest = build_manifest(n_params, n_ranks)
    n_shards = sum(len(e.shards) for e in manifest.values())

    t0 = time.perf_counter()
    _propagate_checksums(manifest)
    t_prop = time.perf_counter() - t0

    md = SnapshotMetadata(version="bench", world_size=n_ranks, manifest=manifest)
    t0 = time.perf_counter()
    text = md.to_yaml()
    t_emit = time.perf_counter() - t0

    t0 = time.perf_counter()
    md2 = SnapshotMetadata.from_yaml(text)
    t_parse = time.perf_counter() - t0
    assert len(md2.manifest) == len(manifest)

    from torchsnapshot_tpu.manifest import get_available_entries

    t0 = time.perf_counter()
    avail = get_available_entries(manifest, rank=3)
    t_avail = time.perf_counter() - t0
    assert len(avail) == len(manifest)

    # Commit-shaped write+read through a real temp file (page-cache I/O).
    import tempfile

    with tempfile.NamedTemporaryFile("w+", suffix=".snapshot_metadata") as f:
        t0 = time.perf_counter()
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
        t_write = time.perf_counter() - t0
        f.seek(0)
        t0 = time.perf_counter()
        SnapshotMetadata.from_yaml(f.read())
        t_read = time.perf_counter() - t0

    # Reshard plan-time leg (ISSUE 12): the minimal-movement planner over
    # the full-size manifest — a tp16 -> col-parallel world-32 cross-cut
    # where every saved shard overlaps every destination strip (the
    # worst-case unit count: one planned unit per shard). The plan is
    # pure geometry on the manifest; it must stay far off the restore
    # critical path even at ~50k shards.
    from torchsnapshot_tpu.layout import LayoutSpec
    from torchsnapshot_tpu.reshard import plan_entry_transfers

    dst = LayoutSpec([("x", 32)])
    t0 = time.perf_counter()
    total_units = 0
    for entry in manifest.values():
        boxes = dst.boxes_by_rank(entry.shape, [(), ("x",)], 32)
        total_units += len(plan_entry_transfers(entry, boxes))
    t_plan = time.perf_counter() - t0
    assert total_units == n_shards, (total_units, n_shards)
    assert t_plan < 60.0, (
        f"planning {n_shards} shards took {t_plan:.1f}s — the planner "
        "fell onto the restore critical path"
    )

    report(
        "manifest_scale",
        {
            "entries": len(manifest),
            "shard_leaves": n_shards,
            "metadata_mb": round(len(text) / 1e6, 2),
            "propagate_checksums_s": round(t_prop, 3),
            "emit_s": round(t_emit, 3),
            "parse_s": round(t_parse, 3),
            "commit_write_s": round(t_write, 3),
            "restore_read_s": round(t_read, 3),
            "available_entries_s": round(t_avail, 3),
            "reshard_plan_s": round(t_plan, 3),
            "reshard_planned_units": total_units,
        },
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
