"""DtoH DMA-staging overlap on REAL TPU hardware.

The TPU-native staging design's core claim is that ``copy_to_host_async``
lets DtoH transfers overlap — with each other and with on-chip compute —
where a serial ``device_get`` loop strictly alternates. This measures
both claims at tiny sizes, so the tunneled device relay's fixed
bandwidth (single-digit MB/s in this environment) is the per-transfer
cost being overlapped, not a bottleneck being hidden:

0. ``dma_overlap/ceiling``: the MEASURED link/host ceilings every other
   number is normalized against — raw ``device_get`` bandwidth on one
   large buffer (= what the DtoH path can possibly deliver through this
   relay/link) and single-thread host memcpy bandwidth (= what the host
   pipeline can possibly deliver). Achieved-%-of-ceiling is the honest
   headline on tunneled hardware: absolute MB/s measures the tunnel.
1. ``dma_overlap/stage``: N device arrays fetched serially
   (``np.asarray`` one by one) vs all DMAs kicked first via
   ``copy_to_host_async`` then drained. overlap_ratio = serial/async
   wall; > 1 means the copies genuinely ran concurrently.
2. ``dma_overlap/async_take``: a jitted on-chip train step timed bare,
   then with ``Snapshot.async_take`` of a small device state in flight
   — step_inflation shows how much staging+I/O steals from compute.
3. ``dma_overlap/sync_take``: a warm-machinery ``Snapshot.take`` over
   FRESH device arrays (uncached DtoH) with a bit-exact restore —
   the end-to-end on-chip checkpoint number, sized from the measured
   ceiling to a ~40 s transfer budget (a faster link automatically
   gets a bigger, more credible absolute datapoint).

Usage: python benchmarks/dma_overlap.py [n_arrays] [mb_per_array]
Emits one JSON line per leg; exits 2 (no JSON) off-TPU.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    if "--cpu" in sys.argv:
        # In-process CPU forcing (the JAX_PLATFORMS env var can be
        # pre-empted by a TPU sitecustomize): used to smoke the script's
        # own logic off-hardware — it still exits 2, measuring nothing.
        sys.argv.remove("--cpu")
        from bench_utils import force_cpu_devices

        force_cpu_devices(1)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_utils import report

    if jax.default_backend() != "tpu":
        print(
            f"not a TPU backend ({jax.default_backend()}); this measures "
            "real DMA overlap only",
            file=sys.stderr,
        )
        return 2

    n_arrays = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    mb = float(sys.argv[2]) if len(sys.argv) > 2 else 2.0
    n_elem = int(mb * 1e6 / 2)  # bf16

    # --- leg 0: measured ceilings ------------------------------------
    # DtoH ceiling: one large uncached device_get. Two probes — a small
    # one sizes the big one so a slow tunnel doesn't eat the budget.
    small = jax.random.normal(jax.random.PRNGKey(7), (1 << 21,), jnp.bfloat16)
    jax.block_until_ready(small)
    t0 = time.perf_counter()
    np.asarray(small)
    small_mbps = (small.nbytes / 1e6) / max(time.perf_counter() - t0, 1e-9)
    # Size the real probe to ~10 s of transfer at the observed rate,
    # clamped to [4 MB, 512 MB].
    probe_mb = max(4.0, min(512.0, small_mbps * 10.0))
    big = jax.random.normal(
        jax.random.PRNGKey(8), (int(probe_mb * 1e6 / 2),), jnp.bfloat16
    )
    jax.block_until_ready(big)
    t0 = time.perf_counter()
    np.asarray(big)
    dtoh_ceiling_mbps = (big.nbytes / 1e6) / max(time.perf_counter() - t0, 1e-9)
    del big

    # Host ceiling: single-thread memcpy on a 256 MB buffer (the save
    # pipeline's floor cost is one pass over the bytes on the host).
    src = np.ones(256 * 1024 * 1024, np.uint8)
    dst_buf = np.empty_like(src)
    np.copyto(dst_buf, src)  # fault pages
    t0 = time.perf_counter()
    np.copyto(dst_buf, src)
    host_memcpy_gbps = (src.nbytes / 1e9) / max(time.perf_counter() - t0, 1e-9)
    del src, dst_buf

    report(
        "dma_overlap/ceiling",
        {
            "dtoh_probe_mb": round(probe_mb, 1),
            "dtoh_ceiling_mbps": round(dtoh_ceiling_mbps, 2),
            "host_memcpy_gbps": round(host_memcpy_gbps, 2),
            "platform": "tpu",
        },
    )

    # jax caches the fetched host copy on the Array (_npy_value), and
    # copy_to_host_async early-returns once it is set — each leg must
    # fetch FRESH device arrays or it times cache hits, not transfers.
    def build(seed):
        key = jax.random.PRNGKey(seed)
        arrs = []
        for _ in range(n_arrays):
            key, sub = jax.random.split(key)
            arrs.append(jax.random.normal(sub, (n_elem,), jnp.bfloat16))
        jax.block_until_ready(arrs)
        return arrs

    serial_arrs = build(0)
    async_arrs = build(0)  # same seed: same values, distinct buffers

    # Warm the relay/transfer channel on a throwaway array.
    warm = jax.random.normal(jax.random.PRNGKey(99), (n_elem,), jnp.bfloat16)
    np.asarray(warm)

    # --- serial device_get -------------------------------------------
    t0 = time.perf_counter()
    hosts = [np.asarray(a) for a in serial_arrs]
    t_serial = time.perf_counter() - t0

    # --- kick all DMAs, then drain -----------------------------------
    t0 = time.perf_counter()
    for a in async_arrs:
        a.copy_to_host_async()
    hosts2 = [np.asarray(a) for a in async_arrs]
    t_async = time.perf_counter() - t0

    for h1, h2 in zip(hosts, hosts2):
        np.testing.assert_array_equal(h1, h2)

    total_mb = n_arrays * mb
    report(
        "dma_overlap/stage",
        {
            "n_arrays": n_arrays,
            "mb_per_array": mb,
            "serial_s": round(t_serial, 3),
            "async_s": round(t_async, 3),
            "overlap_ratio": round(t_serial / max(t_async, 1e-9), 2),
            "serial_mbps": round(total_mb / max(t_serial, 1e-9), 2),
            "async_mbps": round(total_mb / max(t_async, 1e-9), 2),
            # Overlapped staging vs what the link can possibly deliver.
            "async_pct_of_ceiling": round(
                100.0
                * (total_mb / max(t_async, 1e-9))
                / max(dtoh_ceiling_mbps, 1e-9),
                1,
            ),
            "platform": "tpu",
        },
    )

    # --- async_take overlapping an on-chip step ----------------------
    from torchsnapshot_tpu import Snapshot, StateDict

    d = 1024
    w = jax.random.normal(jax.random.PRNGKey(1), (d, d), jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(2), (64, d), jnp.bfloat16)

    n_inner = 512

    @jax.jit
    def step(w, x):
        def body(carry, _):
            h = jnp.tanh(carry @ w)
            return h, None

        out, _ = jax.lax.scan(body, x, None, length=n_inner)
        return jnp.float32(out.sum())

    float(step(w, x))  # compile
    t0 = time.perf_counter()
    float(step(w, x))
    t_step = time.perf_counter() - t0

    state = {"m": StateDict(w=w)}
    tmp = tempfile.mkdtemp(prefix="dma_overlap_")
    try:
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(tmp, "snap"), state)
        blocked = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(step(w, x))  # compute while staging I/O drains
        t_overlap = time.perf_counter() - t0
        pending.wait()
        total = time.perf_counter() - t0 + blocked
        report(
            "dma_overlap/async_take",
            {
                "state_mb": round(w.nbytes / 1e6, 1),
                "bare_step_s": round(t_step, 3),
                "overlapped_step_s": round(t_overlap, 3),
                "step_inflation": round(t_overlap / max(t_step, 1e-9), 2),
                "caller_blocked_s": round(blocked, 3),
                "commit_total_s": round(total, 3),
                "platform": "tpu",
            },
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # --- timed sync take over fresh (uncached) device state ----------
    # Warm the snapshot machinery on one state, then time a take over
    # FRESH device arrays so the DtoH is real, not an _npy_value hit.
    # SIZE FROM THE MEASURED CEILING: the leg pays TWO full transfers of
    # the state (the timed take's DtoH + the bit-exact verification
    # fetch), so each gets half the budget (clamped to [8 MB, 2 GB]). A
    # faster relay automatically yields a larger, more credible absolute
    # datapoint; a slow tunnel stays inside the side-leg deadline.
    take_budget_s = float(os.environ.get("BENCH_SYNC_TAKE_BUDGET_S", "40"))
    state_mb_target = max(
        8.0, min(2048.0, dtoh_ceiling_mbps * take_budget_s / 2.0)
    )
    cols = max(1, int(state_mb_target * 1e6 / 4 / (2 * d)))  # two bf16 arrays

    def build_state(seed, cols_n=None):
        cols_n = cols if cols_n is None else cols_n
        k = jax.random.PRNGKey(seed)
        s = StateDict(
            w=jax.random.normal(k, (2 * d, cols_n), jnp.bfloat16),
            b=jax.random.normal(
                jax.random.fold_in(k, 1), (2 * d, cols_n), jnp.bfloat16
            ),
        )
        jax.block_until_ready(list(s.values()))
        return s

    tmp = tempfile.mkdtemp(prefix="tpu_take_")
    try:
        # Warm the machinery (jits, pools, event loop) on a SMALL state:
        # warmth is about code paths, not bytes — a full-size warm take
        # would double the leg's transfer bill for nothing.
        Snapshot.take(os.path.join(tmp, "warm"), {"m": build_state(3, 1024)})
        st = build_state(4)
        nbytes = sum(v.nbytes for v in st.values())
        t0 = time.perf_counter()
        snap = Snapshot.take(os.path.join(tmp, "timed"), {"m": st})
        t_take = time.perf_counter() - t0
        dst = {
            "m": StateDict(
                w=np.zeros((2 * d, cols), np.float32),
                b=np.zeros((2 * d, cols), np.float32),
            )
        }
        t0 = time.perf_counter()
        snap.restore(dst)
        t_restore = time.perf_counter() - t0
        ok = np.array_equal(
            np.asarray(st["w"], np.float32), dst["m"]["w"]
        ) and np.array_equal(np.asarray(st["b"], np.float32), dst["m"]["b"])
        take_mbps = nbytes / 1e6 / max(t_take, 1e-9)
        report(
            "dma_overlap/sync_take",
            {
                "state_mb": round(nbytes / 1e6, 1),
                "take_s": round(t_take, 2),
                "take_mbps": round(take_mbps, 2),
                # The headline on tunneled hardware: fraction of what the
                # measured link could possibly deliver (end-to-end take =
                # DtoH + serialize + checksum + write).
                "take_pct_of_ceiling": round(
                    100.0 * take_mbps / max(dtoh_ceiling_mbps, 1e-9), 1
                ),
                "ceiling_mbps": round(dtoh_ceiling_mbps, 2),
                "restore_s": round(t_restore, 2),
                "bit_exact": ok,
                "platform": "tpu",
            },
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
