"""Attention kernel benchmark: Pallas flash vs XLA blockwise, fwd+bwd.

Compute-only (scalar outputs), so it is meaningful on a real TPU chip even
when host<->device bandwidth is poor. Reports per-step wall time for a
train-shaped loss (forward + backward through attention) and the flash/
blockwise speedup. The reference has no attention code at all (SURVEY.md
§5.7) — this benchmarks the beyond-parity kernel path.

Usage: python benchmarks/attention_bench.py [B S H D] (default 4 2048 8 128)
Emits one JSON line per kernel via bench_utils.report.
"""

from __future__ import annotations

import os
import statistics
import sys
import time


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench_utils import report

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from torchsnapshot_tpu.ops import blockwise_attention, flash_attention

    args = [int(a) for a in sys.argv[1:5]]
    B, S, H, D = args + [4, 2048, 8, 128][len(args):]
    platform = jax.default_backend()
    print(f"[attention_bench] platform={platform} B={B} S={S} H={H} D={D}",
          file=sys.stderr, flush=True)

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16) for kk in ks)

    def bench(name, attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32))

        grad = jax.grad(loss, argnums=(0, 1, 2))

        @jax.jit
        def step(q, k, v):
            # Reduce grads to one scalar: fetching it (4-byte DtoH) forces
            # the whole computation to finish — block_until_ready alone can
            # report early through a device relay.
            dq, dk, dv = grad(q, k, v)
            return (
                jnp.sum(dq.astype(jnp.float32))
                + jnp.sum(dk.astype(jnp.float32))
                + jnp.sum(dv.astype(jnp.float32))
            )

        float(step(q, k, v))  # compile + warm
        times = []
        for _ in range(10):
            t0 = time.perf_counter()
            float(step(q, k, v))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    # Dispatch + scalar-fetch roundtrip overhead (can dominate through a
    # tunneled device relay): time a near-empty step and subtract it.
    @jax.jit
    def _noop(q):
        return jnp.sum(q[0, 0].astype(jnp.float32))

    float(_noop(q))
    overhead = statistics.median(
        [(lambda t0: (float(_noop(q)), time.perf_counter() - t0)[1])(time.perf_counter())
         for _ in range(10)]
    )
    print(f"[attention_bench] roundtrip overhead {overhead*1e3:.1f} ms",
          file=sys.stderr, flush=True)

    t_block = bench(
        "blockwise",
        lambda q, k, v: blockwise_attention(q, k, v, block_size=512, causal=True),
    )
    t_flash = bench(
        "flash",
        lambda q, k, v: flash_attention(q, k, v, causal=True),
    )

    # Ring-flash on a 1-device ring: measures the ring harness overhead
    # (shard_map + custom VJP + lse merge) over the bare kernel — on a
    # multi-chip mesh the same code path adds only the ppermute hops.
    import numpy as np
    from jax.sharding import Mesh

    from torchsnapshot_tpu.ops import ring_flash_attention_sharded

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("seq",))
    t_ring = bench(
        "ring_flash(ring=1)",
        lambda q, k, v: ring_flash_attention_sharded(q, k, v, mesh1, causal=True),
    )

    # Causal attention FLOPs (fwd 2 matmuls + bwd 5) ≈ 3.5 * 4 * B*H*S^2*D / 2.
    flops = 3.5 * 2 * B * H * S * S * D
    cb = max(t_block - overhead, 1e-9)
    cf = max(t_flash - overhead, 1e-9)
    cr = max(t_ring - overhead, 1e-9)
    for name, t, c in (
        ("blockwise", t_block, cb),
        ("flash", t_flash, cf),
        ("ring_flash", t_ring, cr),
    ):
        report(
            f"attention_fwdbwd_{name}",
            {
                "platform": platform,
                "shape": [B, S, H, D],
                "step_s": round(t, 5),
                "compute_s": round(c, 5),
                "tflops": round(flops / c / 1e12, 2),
                "speedup_vs_blockwise": round(cb / c, 2),
            },
        )


if __name__ == "__main__":
    main()
