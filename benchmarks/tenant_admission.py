"""Admission-control drill: a bulk save must not starve a restore.

Two tenants share one bandwidth-throttled bucket (every plugin instance
drains through ONE rate gate — the storage tier's aggregate ceiling).
Tenant ``batch`` (priority 1) saves in a loop; tenant ``serving``
(priority 4) restores. Without admission the saver's writes saturate
the shared gate and the restore's wall degrades toward the contended
fair-share floor; with admission the saver is paced to its priority
share at the scheduler's I/O-slot boundary and the restore keeps most
of the pipe.

Acceptance (ISSUE 17): the contended restore p50 stays <= 2x the solo
restore p50. An informative no-admission contended leg is also
reported (not asserted — it documents what admission is buying).

Usage: python benchmarks/tenant_admission.py [mb] [bandwidth_mbps]
Emits one JSON line per leg plus a summary line.
"""

from __future__ import annotations

import asyncio
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

REPS = 5


class SharedGate:
    """One serial service queue for ALL storage traffic: each request
    reserves ``nbytes / bps`` of exclusive pipe time and sleeps until
    its slot has drained. Thread-safe across event loops (saves and
    restores run on different scheduler loops)."""

    def __init__(self, bps: float) -> None:
        self.bps = bps
        self._lock = threading.Lock()
        self._free_at = 0.0

    def reserve(self, nbytes: int) -> float:
        with self._lock:
            now = time.perf_counter()
            start = max(now, self._free_at)
            self._free_at = start + nbytes / self.bps
            return self._free_at - now


def _throttled_fs(gate: SharedGate):
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    class SharedThrottledFS(FSStoragePlugin):
        # Buffered-only both ways: the slow-storage election would
        # otherwise route reads through read_stream(), skipping the gate.
        supports_streaming = False
        supports_streaming_reads = False

        async def write(self, write_io):
            nbytes = memoryview(write_io.buf).nbytes
            await super().write(write_io)
            await asyncio.sleep(gate.reserve(nbytes))

        async def read(self, read_io):
            await super().read(read_io)
            await asyncio.sleep(gate.reserve(memoryview(read_io.buf).nbytes))

    return SharedThrottledFS


def main() -> int:
    mb = float(sys.argv[1]) if len(sys.argv) > 1 else 24.0
    bandwidth = (
        float(sys.argv[2]) if len(sys.argv) > 2 else 80.0
    ) * 1e6  # bytes/s

    import numpy as np

    import torchsnapshot_tpu.storage_plugins.fs as fs_mod
    from torchsnapshot_tpu import StateDict
    from torchsnapshot_tpu.manager import CheckpointManager
    from torchsnapshot_tpu.tenancy import Tenant

    gate = SharedGate(bandwidth)
    orig_plugin = fs_mod.FSStoragePlugin
    fs_mod.FSStoragePlugin = _throttled_fs(gate)
    try:
        import tempfile

        root = tempfile.mkdtemp(prefix="tsnap_admission_")
        rows = int(mb * 1e6) // (1024 * 4)
        payload = np.arange(rows * 1024, dtype=np.float32).reshape(rows, 1024)
        batch = CheckpointManager(
            root, tenant=Tenant(id="batch", priority=1), keep_last=2
        )
        serving = CheckpointManager(
            root, tenant=Tenant(id="serving", priority=4), keep_last=2
        )

        def serving_state():
            return {"model": StateDict(w=np.zeros_like(payload))}

        # Seed both tenants' snapshots AND the governor's measured
        # write/read rates (admission pacing needs a measured rate; the
        # first op is the measurement).
        batch.save(0, {"model": StateDict(w=payload)})
        serving.save(0, {"model": StateDict(w=payload)})
        serving.restore(serving_state())

        def restore_p50() -> float:
            walls = []
            for _ in range(REPS):
                t0 = time.perf_counter()
                serving.restore(serving_state())
                walls.append(time.perf_counter() - t0)
            return statistics.median(walls)

        solo_p50 = restore_p50()
        report(
            "tenant_admission/solo",
            {"restore_p50_s": round(solo_p50, 3), "reps": REPS},
        )

        def contended_p50() -> float:
            stop = threading.Event()
            step = [1]

            def saver() -> None:
                while not stop.is_set():
                    step[0] += 1
                    batch.save(step[0], {"model": StateDict(w=payload)})

            t = threading.Thread(target=saver, daemon=True)
            t.start()
            time.sleep(0.2)  # let the first contended save enter I/O
            try:
                return restore_p50()
            finally:
                stop.set()
                t.join(timeout=120)

        contended = contended_p50()
        report(
            "tenant_admission/contended",
            {"restore_p50_s": round(contended, 3), "reps": REPS},
        )

        # Informative control: same contention with admission disabled.
        os.environ["TORCHSNAPSHOT_TPU_ADMISSION"] = "0"
        try:
            unpaced = contended_p50()
        finally:
            os.environ.pop("TORCHSNAPSHOT_TPU_ADMISSION", None)
        report(
            "tenant_admission/contended_no_admission",
            {"restore_p50_s": round(unpaced, 3), "reps": REPS},
        )

        ratio = contended / solo_p50
        summary = {
            "payload_mb": mb,
            "bandwidth_mbps": bandwidth / 1e6,
            "solo_p50_s": round(solo_p50, 3),
            "contended_p50_s": round(contended, 3),
            "no_admission_p50_s": round(unpaced, 3),
            "degradation_x": round(ratio, 2),
            "no_admission_degradation_x": round(unpaced / solo_p50, 2),
        }
        report("tenant_admission/summary", summary)
        assert ratio <= 2.0, (
            f"contended restore p50 {contended:.2f}s is {ratio:.2f}x solo "
            f"{solo_p50:.2f}s — admission failed to protect the "
            "high-priority tenant"
        )
        import shutil

        shutil.rmtree(root, ignore_errors=True)
    finally:
        fs_mod.FSStoragePlugin = orig_plugin
    return 0


if __name__ == "__main__":
    sys.exit(main())
