"""Cooperative restore fan-out vs N direct reads, real multi-process worlds.

The restore-side mirror of the save path's replicated striping: direct
restores read every replicated payload on EVERY rank (storage-read
amplification ~world×), cooperative restores partition the read work
across ranks and redistribute verified sub-chunks over the peer channel
(fanout.py), so the fleet reads each byte ~once.

For world sizes 1/2/4, on THROTTLED storage (per-read/per-window sleeps
at a simulated network-storage bandwidth — the regime the election's
bandwidth gate targets), this measures:

- aggregate restore throughput: world × payload / slowest-rank wall,
- storage-read amplification: fleet payload bytes served by storage /
  payload bytes (counted inside the fs plugin, so a silent fallback to
  direct reads cannot masquerade as cooperation),

for COOP_RESTORE=never (direct) and =always (cooperative), asserting at
world ≥ 2 that cooperation holds amplification ≤ 1.2× (direct measures
~world×) and improves aggregate throughput ≥ 1.5× — the r09 acceptance
criteria — with bit-exact payloads on every rank.

Usage: JAX_PLATFORMS=cpu python benchmarks/coop_restore.py [mb_total]
Emits one JSON line per (world, mode) leg plus a final summary line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

THROTTLE_BPS = 40e6  # ~40 MB/s: shared-filer / modest object-store regime
SUB_CHUNK = 4 << 20


def _state(mb_total: float):
    import numpy as np

    n_arrays = 8
    elems = int(mb_total * 1e6 / n_arrays / 4)
    rng = np.random.default_rng(42)
    return {
        f"w{i}": rng.standard_normal(elems).astype(np.float32)
        for i in range(n_arrays)
    }


def _throttle_and_count():
    """Model a per-host storage bandwidth cap at THROTTLE_BPS: every
    payload read/window pays its transfer time through ONE rate lock per
    process, so concurrent reads SHARE the simulated pipe (independent
    per-read sleeps would let I/O concurrency multiply the 'bandwidth'
    and the throttle would measure nothing). Counts payload bytes served
    (replicated/ and sharded/ locations only, so metadata reads don't
    pollute the amplification ratio)."""
    import asyncio

    from torchsnapshot_tpu.io_types import ReadStream
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    counts = {"payload": 0}
    rate_lock: list = [None]

    def _is_payload(path: str) -> bool:
        return "replicated/" in path or "sharded/" in path

    async def _pay(n: int) -> None:
        counts["payload"] += n
        if rate_lock[0] is None:
            rate_lock[0] = asyncio.Lock()
        async with rate_lock[0]:
            await asyncio.sleep(n / THROTTLE_BPS)

    orig_read = FSStoragePlugin.read

    async def slow_read(self, read_io, _orig=orig_read):
        await _orig(self, read_io)
        if _is_payload(read_io.path):
            await _pay(memoryview(read_io.buf).nbytes)

    orig_stream = FSStoragePlugin.read_stream

    async def slow_stream(self, read_io, sub_chunk, _orig=orig_stream):
        inner = await _orig(self, read_io, sub_chunk)
        path = read_io.path

        async def chunks():
            async for c in inner.chunks:
                if _is_payload(path):
                    await _pay(memoryview(c).nbytes)
                yield c

        return ReadStream(path=inner.path, nbytes=inner.nbytes, chunks=chunks())

    FSStoragePlugin.read = slow_read
    FSStoragePlugin.read_stream = slow_stream
    return counts


def _worker(rank, world_size, root, mb_total, mode):
    import numpy as np

    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = mode
    os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"] = str(SUB_CHUNK)
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "120"

    from torchsnapshot_tpu import Snapshot, StateDict

    state = _state(mb_total)
    app = {"model": StateDict(**state)}
    # The take is collective (every rank participates); each leg gets its
    # own snapshot dir. The throttle installs AFTER, so only the timed
    # restore pays it.
    Snapshot.take(root, app, replicated=["model/**"])
    counts = _throttle_and_count()

    dst = {"model": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    t0 = time.perf_counter()
    Snapshot(root).restore(dst)
    wall = time.perf_counter() - t0
    for k, v in state.items():
        assert dst["model"][k].tobytes() == v.tobytes(), f"{k} not bit-exact"
    return {"wall_s": wall, "payload_read": counts["payload"]}


def main() -> int:
    mb_total = float(sys.argv[1]) if len(sys.argv) > 1 else 64.0

    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    payload = sum(v.nbytes for v in _state(mb_total).values())
    legs = {}
    tmp = tempfile.mkdtemp(prefix="coop_restore_")
    try:
        for world in (1, 2, 4):
            for mode in ("never", "always"):
                root = os.path.join(tmp, f"snap_w{world}_{mode}")
                ranks = run_with_subprocesses(
                    _worker, world, root, mb_total, mode, timeout=600.0
                )
                wall = max(r["wall_s"] for r in ranks.values())
                fleet_read = sum(r["payload_read"] for r in ranks.values())
                leg = {
                    "benchmark": f"coop_restore/w{world}_{mode}",
                    "world": world,
                    "mode": mode,
                    "payload_mb": round(payload / 1e6, 1),
                    "slowest_rank_wall_s": round(wall, 3),
                    "aggregate_gbps": round(world * payload / 1e9 / wall, 3),
                    "storage_read_amplification": round(fleet_read / payload, 3),
                }
                legs[(world, mode)] = leg
                print(json.dumps(leg), flush=True)
                shutil.rmtree(root, ignore_errors=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    summary = {
        "benchmark": "coop_restore/summary",
        "payload_mb": round(payload / 1e6, 1),
        "throttle_mbps": THROTTLE_BPS / 1e6,
        "worlds": {},
    }
    for world in (1, 2, 4):
        direct, coop = legs[(world, "never")], legs[(world, "always")]
        summary["worlds"][str(world)] = {
            "direct_gbps": direct["aggregate_gbps"],
            "coop_gbps": coop["aggregate_gbps"],
            "speedup": round(
                coop["aggregate_gbps"] / max(direct["aggregate_gbps"], 1e-9), 2
            ),
            "direct_amplification": direct["storage_read_amplification"],
            "coop_amplification": coop["storage_read_amplification"],
        }
    print(json.dumps(summary), flush=True)

    # r09 acceptance criteria, asserted on the multi-process worlds.
    for world in (2, 4):
        w = summary["worlds"][str(world)]
        assert w["coop_amplification"] <= 1.2, (
            f"world {world}: cooperative amplification "
            f"{w['coop_amplification']}x > 1.2x"
        )
        assert w["direct_amplification"] >= 0.8 * world, (
            f"world {world}: direct amplification "
            f"{w['direct_amplification']}x unexpectedly low — the baseline "
            "being measured is not N direct reads"
        )
        assert w["speedup"] >= 1.5, (
            f"world {world}: cooperative speedup {w['speedup']}x < 1.5x "
            "on throttled storage"
        )
    # world 1: cooperation must never engage; amplification stays ~1.
    w1 = summary["worlds"]["1"]
    assert w1["coop_amplification"] <= 1.2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
