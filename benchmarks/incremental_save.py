"""Incremental-snapshot benchmark: frozen-backbone fine-tuning pattern.

Models the dominant real-world case for checkpoint dedup — LoRA/adapter
fine-tuning, where the backbone (most of the bytes) is frozen and only a
small trainable fraction changes between snapshots. Measures a full save,
then an incremental save against it, and reports wall time, bytes actually
written, and the speedup. No reference analogue: the reference rewrites
every byte on every save.

Usage: python benchmarks/incremental_save.py [total_MiB] [trainable_pct]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile

import numpy as np

from bench_utils import payload_bytes, report, timed_rss


def _disk_bytes(root: str) -> int:
    return payload_bytes(root, include_metadata=True)


def main() -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    total_mib = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    trainable_pct = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0

    total = total_mib * (1 << 20) // 4  # float32 elements
    n_train = max(1, int(total * trainable_pct / 100))
    n_frozen = total - n_train
    rng = np.random.default_rng(0)
    frozen = rng.standard_normal(n_frozen, dtype=np.float32)
    trainable = rng.standard_normal(n_train, dtype=np.float32)

    def state():
        return StateDict(backbone=frozen, adapter=trainable, step=0)

    with tempfile.TemporaryDirectory() as d:
        base, inc = os.path.join(d, "base"), os.path.join(d, "inc")

        full = {}
        with timed_rss(full):
            Snapshot.take(base, {"app": state()}, record_digests=True)
        full["written_mb"] = round(_disk_bytes(base) / 1e6, 1)
        report("full_save", full, data_bytes=total * 4)

        trainable += 0.01  # the training step: only the adapter moves
        inc_res = {}
        with timed_rss(inc_res):
            Snapshot.take(inc, {"app": state()}, incremental_base=base)
        inc_res["written_mb"] = round(_disk_bytes(inc) / 1e6, 1)
        inc_res["speedup_vs_full"] = round(full["wall_s"] / inc_res["wall_s"], 2)
        inc_res["bytes_reduction"] = round(
            full["written_mb"] / max(inc_res["written_mb"], 1e-9), 1
        )
        report("incremental_save", inc_res, data_bytes=total * 4)

        # compressed full save of the same state (zstd): honest numbers —
        # random fp32 mantissas bound the ratio; structured real states
        # (zero-heavy optimizer slots, embeddings, int arrays) do better.
        comp = os.path.join(d, "comp")
        try:
            import zstandard  # noqa: F401

            codec = "zstd"
        except ImportError:
            codec = "zlib"
        comp_res = {"codec": codec}
        with timed_rss(comp_res):
            Snapshot.take(comp, {"app": state()}, compression=codec)
        comp_res["written_mb"] = round(_disk_bytes(comp) / 1e6, 1)
        comp_res["bytes_reduction_vs_raw"] = round(
            full["written_mb"] / max(comp_res["written_mb"], 1e-9), 2
        )
        report("compressed_save", comp_res, data_bytes=total * 4)

        # restore correctness spot check
        dst = StateDict(
            backbone=np.zeros_like(frozen), adapter=np.zeros_like(trainable), step=1
        )
        restore = {}
        with timed_rss(restore):
            Snapshot(inc).restore({"app": dst})
        np.testing.assert_array_equal(dst["backbone"], frozen)
        np.testing.assert_array_equal(dst["adapter"], trainable)
        report("incremental_restore", restore, data_bytes=total * 4)


if __name__ == "__main__":
    main()
