"""Headline benchmark: Snapshot save throughput for device state.

Mirrors the reference's DDP benchmark (benchmarks/ddp/main.py: save a model
of N x 100MB params, report wall time). Reference baseline on comparable
1-worker hardware: 18 GB in ~45 s => 0.40 GB/s (benchmarks/ddp/README.md:15,
reproduced in BASELINE.md). We report save throughput in GB/s on one chip;
vs_baseline is the ratio against that 0.40 GB/s figure.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N,
   "p50_gbps": N, "restore_gbps": N, "platform": ...,
   "tpu_hw": {...}}   # optional — only when a TPU was reachable
value is best-of-4 save throughput; p50_gbps the median of the same
trials (run variance check); restore_gbps the best timed restore of the
same state. All diagnostics go to stderr.

Robustness: backend init is probed in a subprocess with a single generous
timeout (the experimental TPU platform in this environment can hang at
init, and killing a TPU client repeatedly can wedge the device relay) and
falls back to the CPU backend so a number is always recorded.

When the probe sees a live TPU — even one whose tunneled DtoH bandwidth
is below the floor that moves the main leg onto the cpu backend — a
bounded hardware side-leg (benchmarks/dma_overlap.py) runs first and its
summary is embedded under the JSON's "tpu_hw" key: DMA overlap ratio,
train-step inflation under an in-flight async_take, an on-chip sync-take
with bit-exact restore, and (when benchmarks/device_dedup.py also lands)
the device-resident change-detection resave speedup.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import statistics
import subprocess
import sys
import tempfile
import time

REFERENCE_SAVE_GBPS = 18.0 / 45.0  # benchmarks/ddp/README.md:15 (1 worker)

# The probe also measures DtoH bandwidth: in this environment the TPU is
# reached through a loopback relay whose DtoH path can run at single-digit
# MB/s — an environment artifact that would measure the tunnel, not the
# snapshot pipeline. Below this floor the benchmark runs on the CPU backend
# instead (recorded in the JSON's "platform" field).
_MIN_DTOH_GBPS = 0.05

_PROBE_CODE = """
import time
import jax, jax.numpy as jnp, numpy as np
x = jnp.ones((1 << 23,), jnp.bfloat16)  # 16 MB
jax.block_until_ready(x)
t0 = time.perf_counter()
np.asarray(x)
dt = time.perf_counter() - t0
print(jax.default_backend(), len(jax.devices()), f"{16e-3 / max(dt, 1e-9):.4f}")
"""


def _log(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


class _SubprocResult:
    def __init__(self, returncode, stdout, stderr, killed, pgid=None):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr
        self.killed = killed
        self.pgid = pgid  # the child-led process group (== child pid)


def _run_in_own_group(cmd, timeout):
    """subprocess.run, but the child leads its OWN process group and a
    timeout kills the WHOLE group — then verifies no orphan survived.

    The r05 driver artifact regressed 4.7x because two timed-out TPU
    probes left relay-side children competing for this host's single
    core during the timed saves: ``subprocess.run(timeout=...)`` kills
    only the direct child, not whatever the JAX TPU client forked. A
    wedged group member that survives SIGKILL (unkillable D-state) is
    loudly reported so the caller can annotate the run as contaminated.
    """
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,  # child = leader of a fresh process group
    )
    killed = False
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        killed = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        stdout, stderr = proc.communicate()
    if killed:
        _verify_group_dead(proc.pid)
    return _SubprocResult(
        proc.returncode, stdout or "", stderr or "", killed, pgid=proc.pid
    )


def _verify_group_dead(pgid, wait_s: float = 5.0) -> bool:
    """Poll until no process remains in ``pgid``; log loudly if one
    survives (it will contaminate subsequent timing windows)."""
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        try:
            os.killpg(pgid, 0)
        except ProcessLookupError:
            return True  # whole group reaped
        except PermissionError:
            break  # exists but not ours — report below
        time.sleep(0.2)
    _log(
        f"WARNING: process group {pgid} still has live members after "
        f"SIGKILL + {wait_s}s; the host may be contaminated for timing"
    )
    return False


# Floor for the memcpy self-calibration: all bench state fits in RAM and
# the pipeline is memory-bandwidth-bound, so a host that can't stream
# copies at this rate is either contended or misconfigured — the timed
# window would measure the contention, not the snapshot pipeline.
_MEMCPY_FLOOR_GBPS = float(os.environ.get("BENCH_MEMCPY_FLOOR_GBPS", "1.0"))


def _host_calibration():
    """Measure the host BEFORE opening the timed window: 1-minute load
    average and achieved memcpy bandwidth (3x 256 MB, best-of). A wedged
    relay day (r05) showed up as orphaned probe children stealing the
    core — this check makes that visible in the artifact instead of
    silently costing the round its headline. Returns a dict embedded in
    the JSON under "host_calibration" with a ``contaminated`` verdict."""
    import numpy as np

    cpu_count = os.cpu_count() or 1
    try:
        load1 = os.getloadavg()[0]
    except OSError:  # pragma: no cover
        load1 = 0.0
    src = np.empty(256 << 20, np.uint8)
    src[::4096] = 1  # fault the pages outside the timed copies
    dst = np.empty_like(src)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        best = max(best, src.nbytes / max(time.perf_counter() - t0, 1e-9))
    del src, dst
    memcpy_gbps = best / 1e9
    contaminated = load1 > 1.5 * cpu_count or memcpy_gbps < _MEMCPY_FLOOR_GBPS
    cal = {
        "load1": round(load1, 2),
        "cpu_count": cpu_count,
        "memcpy_gbps": round(memcpy_gbps, 2),
        "contaminated": contaminated,
    }
    if contaminated:
        cal["reason"] = (
            f"load1={load1:.2f} vs {cpu_count} cpu(s)"
            if load1 > 1.5 * cpu_count
            else f"memcpy {memcpy_gbps:.2f} GB/s < {_MEMCPY_FLOOR_GBPS} GB/s floor"
        )
    _log(f"host calibration: {cal}")
    return cal


def _probe_backend() -> "tuple[str, bool]":
    """Probe backend init in a subprocess (so a hang can be timed out).

    Returns ``(platform_to_use, tpu_reachable)``: the second element is
    True whenever the probe saw a live non-cpu backend, even if its DtoH
    bandwidth is below the floor that forces the main benchmark leg onto
    the cpu backend — a reachable chip still gets the hardware side-leg
    (see ``_tpu_hw_leg``). The device relay in this environment
    has INTERMITTENT outages (observed across rounds: init hangs, or a
    clean UNAVAILABLE after minutes), so the probe retries within a total
    time budget instead of giving up on the first failure. Clean failures
    (the probe process exited on its own) retry after a short pause; a
    timed-out probe was killed mid-init — which can wedge the relay — so
    those retry after a longer cool-down. Falls back to "cpu" when the
    budget is exhausted, so the benchmark always lands a number (round-1
    failure mode: dying at backend init).
    """
    if os.environ.get("BENCH_FORCE_CPU"):
        _log("BENCH_FORCE_CPU set; using cpu backend")
        return "cpu", False
    per_attempt = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "420"))
    total_budget = int(os.environ.get("BENCH_PROBE_TOTAL_S", "900"))
    begin = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        remaining = total_budget - (time.monotonic() - begin)
        if attempt > 1 and remaining <= 30:
            break
        deadline = min(per_attempt, max(30, int(remaining)))
        t0 = time.perf_counter()
        r = _run_in_own_group([sys.executable, "-c", _PROBE_CODE], deadline)
        killed = r.killed
        dt = time.perf_counter() - t0
        if killed:
            _log(f"probe attempt {attempt} timed out after {deadline}s "
                 "(process group killed)")
        else:
            if r.returncode == 0 and r.stdout.strip():
                try:
                    # Last line: libraries may print banners above it.
                    platform, n_dev, dtoh_s = (
                        r.stdout.strip().splitlines()[-1].split()[:3]
                    )
                    dtoh = float(dtoh_s)
                except (ValueError, IndexError):
                    _log(f"probe output unparseable: {r.stdout.strip()[-300:]!r}")
                else:
                    _log(
                        f"backend probe ok (attempt {attempt}, {dt:.1f}s): "
                        f"platform={platform} devices={n_dev} DtoH={dtoh} GB/s"
                    )
                    if platform != "cpu" and dtoh < _MIN_DTOH_GBPS:
                        _log(
                            f"DtoH {dtoh} GB/s is below the {_MIN_DTOH_GBPS} "
                            "GB/s floor (tunneled device relay); benchmarking "
                            "the host pipeline on the cpu backend instead"
                        )
                        return "cpu", True
                    return platform, platform != "cpu"
            else:
                _log(
                    f"probe attempt {attempt} rc={r.returncode} "
                    f"stderr={r.stderr.strip()[-500:]!r}"
                )
        remaining = total_budget - (time.monotonic() - begin)
        # A killed probe may have wedged the relay; cool down longer.
        pause = 120 if killed else 30
        if remaining <= pause + 30:
            break
        _log(f"retrying backend probe in {pause}s ({remaining:.0f}s budget left)")
        time.sleep(pause)
    _log("default backend unusable within the probe budget; falling back to cpu")
    return "cpu", False


def _json_records(stdout: str) -> "dict[str, dict]":
    """Parse a subprocess's stdout into {benchmark_name: record} from its
    one-JSON-object-per-line output, skipping banners/noise."""
    legs = {}
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            legs[rec.get("benchmark", "?")] = rec
    return legs


def _tpu_hw_leg() -> "tuple[dict | None, bool]":
    """Run benchmarks/dma_overlap.py against the reachable chip.

    Returns ``(summary, killed)``: a compact summary of the hardware legs
    (DMA overlap ratio, train-step inflation under an in-flight
    async_take, on-chip sync-take throughput + bit-exactness, and — when
    the optional device-dedup leg lands — its resave speedup) for
    embedding in the main JSON line, or None if the PRIMARY
    (dma_overlap) leg fails/times out; the optional second leg failing
    leaves the primary summary intact, so ``killed=True`` can coexist
    with a populated summary. ``killed`` is True when either subprocess
    was killed at its timeout — killing a TPU client mid-operation can
    wedge the device relay, so the caller must NOT then initialize the
    TPU backend in-process (no timeout there); it falls back to cpu
    instead. The
    relay-bound absolute MB/s measures the tunnel, but the RATIOS are the
    design claims (see BENCHMARKS.md "DMA-staging overlap").
    """
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "dma_overlap.py"
    )
    deadline = int(os.environ.get("BENCH_TPU_LEG_TIMEOUT_S", "420"))
    _log(f"running TPU hardware side-leg ({deadline}s budget) ...")
    t_begin = time.monotonic()
    r = _run_in_own_group([sys.executable, script], deadline)
    if r.killed:
        _log("TPU side-leg timed out (process group killed); omitting "
             "hardware fields")
        return None, True
    if r.returncode != 0:
        _log(f"TPU side-leg rc={r.returncode} stderr={r.stderr.strip()[-300:]!r}")
        return None, False
    legs = _json_records(r.stdout)
    stage = legs.get("dma_overlap/stage")
    take = legs.get("dma_overlap/async_take")
    sync = legs.get("dma_overlap/sync_take")
    ceiling = legs.get("dma_overlap/ceiling")
    if not (stage and take and sync):
        _log(f"TPU side-leg output incomplete ({sorted(legs)}); omitting")
        return None, False
    out = {
        "dma_overlap_ratio": stage["overlap_ratio"],
        "async_step_inflation": take["step_inflation"],
        "sync_take_mbps": sync["take_mbps"],
        "sync_take_state_mb": sync.get("state_mb"),
        "sync_take_bit_exact": sync["bit_exact"],
    }
    if ceiling is not None and ceiling.get("dtoh_ceiling_mbps") is not None:
        # Normalized view: absolute MB/s through a tunneled relay
        # measures the tunnel; achieved-%-of-(measured)-ceiling is the
        # design number. >100% is possible — the pipeline overlaps many
        # DtoH streams while the ceiling probe is one serial device_get.
        # .get throughout: a partial/older ceiling record degrades to
        # omitted fields, never a crash.
        out["ceiling_gbps"] = round(ceiling["dtoh_ceiling_mbps"] / 1e3, 4)
        out["host_memcpy_gbps"] = ceiling.get("host_memcpy_gbps")
        out["achieved_pct"] = sync.get("take_pct_of_ceiling")
        out["async_stage_pct_of_ceiling"] = stage.get("async_pct_of_ceiling")
    # Second side-leg: device-resident change detection (benchmarks/
    # device_dedup.py) — unchanged-resave speedup from skipping DtoH.
    # Optional: its absence never discards the DMA numbers above.
    script2 = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "device_dedup.py"
    )
    # Both side-legs share the announced budget: the second gets what the
    # first left over (min 60 s), never a fresh full deadline.
    remaining = max(60, int(deadline - (time.monotonic() - t_begin)))
    r2 = _run_in_own_group([sys.executable, script2], remaining)
    if r2.killed:
        _log("device-dedup side-leg timed out (process group killed)")
        return out, True
    if r2.returncode == 0:
        rec = _json_records(r2.stdout).get("device_dedup/unchanged_resave")
        if rec is not None:
            out["device_dedup_speedup"] = rec["speedup"]
    else:
        _log(f"device-dedup side-leg rc={r2.returncode}")
    _log(f"TPU hardware side-leg ok: {out}")
    return out, False


def _coop_restore_leg(timeout_s: float = 420.0):
    """Cooperative restore fan-out leg (benchmarks/coop_restore.py):
    1/2/4-process throttled-storage restores of replicated-heavy state,
    measuring aggregate restore GB/s and the storage-read amplification
    ratio (fleet payload bytes read / payload bytes — ~1.0 cooperative
    vs ~N direct; the script asserts the r09 criteria itself). Runs in
    its own process group with a hard timeout so a wedged world can
    never stall the headline metric; the parsed summary is persisted to
    BENCH_r09.json and embedded in the main record."""
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "coop_restore.py"
    )
    env_note = {"JAX_PLATFORMS": "cpu"}
    _log(f"running cooperative-restore leg ({timeout_s:.0f}s budget) ...")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    r = _run_in_own_group(
        [sys.executable, script, "64"], timeout=timeout_s
    )
    if r.killed or r.returncode != 0:
        _log(
            f"coop-restore leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("coop_restore/") and name != "coop_restore/summary"
    ]
    summary = records.get("coop_restore/summary")
    if summary is None:
        _log("coop-restore leg produced no summary; omitting")
        return None
    out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r09.json"
    )
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "cooperative_restore_fanout",
                "unit": "GB/s aggregate",
                "payload_mb": summary.get("payload_mb"),
                "throttle_mbps": summary.get("throttle_mbps"),
                "worlds": summary.get("worlds"),
                "legs": legs,
                "platform": "cpu",
                "env": env_note,
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(f"coop-restore leg ok: {summary['worlds']}; written to {out}")
    return summary["worlds"]


def _reshard_leg(timeout_s: float = 420.0):
    """Planned-reshard legs (ISSUE 12), persisted to BENCH_r11.json and
    embedded in the main record:

    - benchmarks/reshard_throughput.py: the world-2 tp2 -> world-4
      column cross-cut on throttled storage, RESHARD=never vs =always
      (the script asserts <= 1.3x planned vs ~4x direct amplification
      and a >= 1.5x aggregate speedup itself);
    - benchmarks/manifest_scale.py's plan-time leg: the minimal-movement
      plan over a ~50k-shard manifest under its own wall bound.

    Each runs in its own process group with a hard timeout; failures
    degrade to an absent key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running planned-reshard legs ({timeout_s:.0f}s budget) ...")
    deadline = time.monotonic() + timeout_s
    r = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "reshard_throughput.py")],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"reshard-throughput leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    summary = records.get("reshard_throughput/summary")
    if summary is None:
        _log("reshard-throughput leg produced no summary; omitting")
        return None
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("reshard_throughput/")
        and name != "reshard_throughput/summary"
    ]

    plan = None
    remaining = max(30.0, deadline - time.monotonic())
    r2 = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "manifest_scale.py")],
        timeout=remaining,
    )
    if not r2.killed and r2.returncode == 0:
        ms = _json_records(r2.stdout).get("manifest_scale")
        if ms is not None:
            plan = {
                "shard_leaves": ms.get("shard_leaves"),
                "planned_units": ms.get("reshard_planned_units"),
                "plan_s": ms.get("reshard_plan_s"),
            }
    if plan is None:
        _log(
            f"manifest-scale plan leg rc={r2.returncode} killed={r2.killed}; "
            "omitting plan numbers"
        )

    out = os.path.join(here, "BENCH_r11.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "planned_reshard",
                "unit": "storage-read amplification (x payload) / GB/s",
                "summary": summary,
                "legs": legs,
                "plan_scale": plan,
                "platform": "cpu",
                "env": {"JAX_PLATFORMS": "cpu"},
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"reshard leg ok: speedup {summary.get('speedup')}x, "
        f"amplification {summary.get('direct_amplification')}x -> "
        f"{summary.get('planned_amplification')}x; written to {out}"
    )
    compact = dict(summary)
    compact.pop("benchmark", None)
    if plan is not None:
        compact["plan_scale"] = plan
    return compact


def _journal_leg(timeout_s: float = 420.0):
    """Delta-journal RPO leg (ISSUE 14), persisted to BENCH_r12.json and
    embedded in the main record: benchmarks/journal_rpo.py measures the
    cost of one journal epoch (a small hot set over a mostly-frozen
    state, many small arrays) vs a full save on 50 MB/s-throttled
    storage, expresses both as recoverable-state intervals at a 1%
    sustained-overhead budget, and asserts the >= 10x RPO reduction
    itself. Runs in its own process group with a hard timeout; failures
    degrade to an absent key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running delta-journal RPO leg ({timeout_s:.0f}s budget) ...")
    r = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "journal_rpo.py")],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"journal RPO leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    summary = records.get("journal_rpo/summary")
    if summary is None:
        _log("journal RPO leg produced no summary; omitting")
        return None
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("journal_rpo/") and name != "journal_rpo/summary"
    ]
    out = os.path.join(here, "BENCH_r12.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "journal_rpo",
                "unit": "seconds of recoverable-state interval at 1% "
                "sustained checkpoint overhead / MiB/s append",
                "summary": summary,
                "legs": legs,
                "platform": "cpu",
                "env": {
                    "JAX_PLATFORMS": "cpu",
                    "TORCHSNAPSHOT_TPU_JOURNAL": "1",
                    "TORCHSNAPSHOT_TPU_NATIVE_IO": "never",
                },
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"journal leg ok: RPO {summary.get('rpo_full_save_s')}s -> "
        f"{summary.get('rpo_journal_s')}s "
        f"({summary.get('rpo_reduction_x')}x) at equal overhead, "
        f"append {summary.get('append_throughput_mib_s')} MiB/s; "
        f"written to {out}"
    )
    compact = dict(summary)
    compact.pop("benchmark", None)
    return compact


def _distrib_leg(timeout_s: float = 420.0):
    """Fleet-distribution leg (ISSUE 16), persisted to BENCH_r13.json
    and embedded in the main record: benchmarks/fleet_restore.py runs
    the emulated world-64 rollout on throttled storage — 64 independent
    replica restores with the seeding tier on vs the 64x direct baseline
    (the script asserts storage-read amplification <= 1.2x and scaling
    past the BENCH_r09 w4 cooperative restore itself), the concurrent
    chunk-wave fan-out depth measurement, and the journal-delta rolling
    update (asserts pushed bytes <= 1.5x committed epoch bytes). Runs in
    its own process group with a hard timeout; failures degrade to an
    absent key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running fleet-distribution leg ({timeout_s:.0f}s budget) ...")
    r = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "fleet_restore.py")],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"fleet-distribution leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    summary = records.get("fleet_restore/summary")
    if summary is None:
        _log("fleet-distribution leg produced no summary; omitting")
        return None
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("fleet_restore/") and name != "fleet_restore/summary"
    ]
    out = os.path.join(here, "BENCH_r13.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "fleet_distribution",
                "unit": "storage-read amplification (x payload) / GB/s "
                "aggregate / bytes per replica per rolling update",
                "summary": summary,
                "legs": legs,
                "platform": "cpu",
                "env": {
                    "JAX_PLATFORMS": "cpu",
                    "TORCHSNAPSHOT_TPU_SEED_RESTORE": "always",
                    "TORCHSNAPSHOT_TPU_JOURNAL": "1",
                },
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"fleet-distribution leg ok: amplification "
        f"{summary.get('direct_fleet_amplification')}x -> "
        f"{summary.get('seeded_amplification')}x at fleet "
        f"{summary.get('fleet')}, tree depth "
        f"{summary.get('max_tree_depth')}, push amplification "
        f"{summary.get('push_amplification')}x; written to {out}"
    )
    compact = dict(summary)
    compact.pop("benchmark", None)
    return compact


def _tenancy_leg(timeout_s: float = 420.0):
    """Multi-tenant leg (ISSUE 17), persisted to BENCH_r14.json and
    embedded in the main record. Two sub-drills: the million-entry
    columnar manifest plane (benchmarks/manifest_scale.py --columnar:
    build/encode/decode/plan walls over ~1M shard leaves, asserted
    < 60 s total) and the admission drill (benchmarks/
    tenant_admission.py: a priority-1 bulk save contending with a
    priority-4 restore on one throttled bucket, restore p50 asserted
    <= 2x solo). Runs in its own process group with a hard timeout;
    failures degrade to an absent key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running multi-tenant leg ({timeout_s:.0f}s budget) ...")
    r = _run_in_own_group(
        [
            sys.executable,
            os.path.join(here, "benchmarks", "manifest_scale.py"),
            "--columnar",
        ],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"columnar manifest leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    manifest_rec = _json_records(r.stdout).get("manifest_scale_columnar")
    if manifest_rec is None:
        _log("columnar manifest leg produced no record; omitting")
        return None
    r = _run_in_own_group(
        [
            sys.executable,
            os.path.join(here, "benchmarks", "tenant_admission.py"),
        ],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"admission drill rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    admission_summary = records.get("tenant_admission/summary")
    if admission_summary is None:
        _log("admission drill produced no summary; omitting")
        return None
    legs = [manifest_rec] + [
        rec
        for name, rec in records.items()
        if name.startswith("tenant_admission/")
        and name != "tenant_admission/summary"
    ]
    summary = {
        "manifest_entries": manifest_rec.get("entries"),
        "manifest_shard_leaves": manifest_rec.get("shard_leaves"),
        "manifest_total_s": manifest_rec.get("total_s"),
        "manifest_compaction_x": manifest_rec.get("compaction_x"),
        "admission_degradation_x": admission_summary.get("degradation_x"),
        "no_admission_degradation_x": admission_summary.get(
            "no_admission_degradation_x"
        ),
    }
    out = os.path.join(here, "BENCH_r14.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "tenancy",
                "unit": "seconds for 1M-leaf manifest round-trip / restore "
                "p50 degradation (x solo) under a contending save",
                "summary": summary,
                "legs": legs,
                "platform": "cpu",
                "env": {"JAX_PLATFORMS": "cpu"},
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"tenancy leg ok: {summary['manifest_shard_leaves']} shard leaves "
        f"in {summary['manifest_total_s']}s "
        f"({summary['manifest_compaction_x']}x smaller than JSON), "
        f"contended restore p50 {summary['admission_degradation_x']}x solo "
        f"(no admission: {summary['no_admission_degradation_x']}x); "
        f"written to {out}"
    )
    return summary


def _lazy_leg(timeout_s: float = 420.0):
    """Lazy page-in restore leg (ISSUE 18), persisted to BENCH_r15.json
    and embedded in the main record: benchmarks/lazy_restore.py measures
    time-to-first-inference on throttled storage — eager full-restore
    wall vs lazy restore() return with a ~4% hot set resident (the
    script asserts TTFI speedup >= 5x floor and total payload bytes
    <= 1.1x eager, bit-exact on every leaf), plus the demand-only
    fault-path drain. Runs in its own process group with a hard
    timeout; failures degrade to an absent key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running lazy-restore leg ({timeout_s:.0f}s budget) ...")
    r = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "lazy_restore.py")],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"lazy-restore leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    summary = records.get("lazy_restore/summary")
    if summary is None:
        _log("lazy-restore leg produced no summary; omitting")
        return None
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("lazy_restore/") and name != "lazy_restore/summary"
    ]
    out = os.path.join(here, "BENCH_r15.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "lazy_restore",
                "unit": "time-to-first-inference speedup (x eager wall) / "
                "payload-read amplification (x eager bytes)",
                "summary": summary,
                "legs": legs,
                "platform": "cpu",
                "env": {
                    "JAX_PLATFORMS": "cpu",
                    "TORCHSNAPSHOT_TPU_LAZY_RESTORE": "always",
                },
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"lazy-restore leg ok: TTFI {summary.get('ttfi_lazy_s')}s vs eager "
        f"{summary.get('ttfi_eager_s')}s "
        f"({summary.get('ttfi_speedup_x')}x) at hot fraction "
        f"{summary.get('hot_fraction')}, bytes "
        f"{summary.get('bytes_amplification_x')}x; written to {out}"
    )
    compact = dict(summary)
    compact.pop("benchmark", None)
    return compact


def _autotune_leg(timeout_s: float = 420.0):
    """Closed-loop autotune leg (ISSUE 19), persisted to BENCH_r16.json
    and embedded in the main record: benchmarks/autotune.py pits the
    self-driving IOGovernor against a hand-tuned static election on
    latency-bound storage — cold-start convergence (within 10% of the
    hand-tuned p50 inside 8 takes) and warm-start parity (first take of
    a fresh governor >= 0.9x hand-tuned, profiles loaded from the
    history journal). Runs in its own process group with a hard
    timeout; failures degrade to an absent key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running autotune leg ({timeout_s:.0f}s budget) ...")
    r = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "autotune.py")],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"autotune leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    summary = records.get("autotune/summary")
    if summary is None:
        _log("autotune leg produced no summary; omitting")
        return None
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("autotune/") and name != "autotune/summary"
    ]
    out = os.path.join(here, "BENCH_r16.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "autotune",
                "unit": "take throughput vs hand-tuned p50 (x) / "
                "takes to convergence",
                "summary": summary,
                "legs": legs,
                "platform": "cpu",
                "env": {
                    "JAX_PLATFORMS": "cpu",
                    "TORCHSNAPSHOT_TPU_AUTOTUNE": "fresh/auto per leg",
                },
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"autotune leg ok: heuristic "
        f"{summary.get('heuristic_vs_hand')}x hand-tuned, converged at "
        f"take {summary.get('cold_converged_take')} "
        f"(budget {summary.get('cold_budget_takes')}), warm first take "
        f"{summary.get('warm_first_vs_hand_p50')}x; written to {out}"
    )
    compact = dict(summary)
    compact.pop("benchmark", None)
    return compact


def _georep_leg(timeout_s: float = 420.0):
    """Geo-replication RPO leg (ISSUE 20), persisted to BENCH_r17.json
    and embedded in the main record: benchmarks/georep_rpo.py ships a
    base snapshot and per-epoch journal deltas over a 20 MB/s-throttled
    WAN, expresses the remote tier's recovery point at several journal
    cadences (cadence + measured fold time, vs re-shipping the base
    every cadence point), and gates the foreground cost of an armed
    shipper (<= 5% with a 50 ms floor on journal_step). Runs in its own
    process group with a hard timeout; failures degrade to an absent
    key, never a dead bench."""
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _log(f"running geo-replication RPO leg ({timeout_s:.0f}s budget) ...")
    r = _run_in_own_group(
        [sys.executable, os.path.join(here, "benchmarks", "georep_rpo.py")],
        timeout=timeout_s,
    )
    if r.killed or r.returncode != 0:
        _log(
            f"georep RPO leg rc={r.returncode} killed={r.killed} "
            f"stderr={r.stderr.strip()[-300:]!r}; omitting"
        )
        return None
    records = _json_records(r.stdout)
    summary = records.get("georep_rpo/summary")
    if summary is None:
        _log("georep RPO leg produced no summary; omitting")
        return None
    legs = [
        rec
        for name, rec in records.items()
        if name.startswith("georep_rpo/") and name != "georep_rpo/summary"
    ]
    out = os.path.join(here, "BENCH_r17.json")
    with open(out, "w") as f:
        json.dump(
            {
                "metric": "georep_rpo",
                "unit": "seconds of remote-tier recovery point vs "
                "journal cadence on a 20 MB/s WAN",
                "summary": summary,
                "legs": legs,
                "platform": "cpu",
                "env": {
                    "JAX_PLATFORMS": "cpu",
                    "TORCHSNAPSHOT_TPU_JOURNAL": "1",
                },
            },
            f,
            indent=1,
        )
        f.write("\n")
    _log(
        f"georep leg ok: epoch ship {summary.get('epoch_ship_s')}s vs "
        f"base ship {summary.get('base_ship_s')}s "
        f"({summary.get('ship_reduction_x')}x), foreground overhead "
        f"{summary.get('foreground_overhead_pct')}%; written to {out}"
    )
    compact = dict(summary)
    compact.pop("benchmark", None)
    return compact


def _native_io_leg(tmp: str, app_state, state, nbytes: int):
    """Side-by-side native-engine vs Python-path legs (ISSUE 9),
    persisted to BENCH_r10.json and embedded in the main record.

    Both save legs pin a 32 MB sub-chunk so the streamed write path (the
    surface the engine replaces) engages for every entry under BOTH
    modes — the comparison measures the engine, not the streaming
    election; both restore legs force streamed reads for the same
    reason. Trials are back-to-back best-of-N (this host's bimodal
    reclaim stalls only ever inflate walls). Returns the record dict, or
    None when the engine probe fails (the legs would measure nothing)."""
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict, native_io

    if native_io.engine_kind() is None:
        _log("native I/O leg skipped: engine probe failed")
        return None

    pinned = {
        "TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES": str(32 << 20),
        "TORCHSNAPSHOT_TPU_STREAM_READS": "always",
    }
    saved_env = {
        k: os.environ.get(k)
        for k in list(pinned) + ["TORCHSNAPSHOT_TPU_NATIVE_IO"]
    }
    legs: "dict[str, dict]" = {}
    try:
        os.environ.update(pinned)
        for mode in ("never", "always"):
            os.environ["TORCHSNAPSHOT_TPU_NATIVE_IO"] = mode
            root = f"{tmp}/native_{mode}"
            saves, restores = [], []
            Snapshot.take(f"{root}/warm", app_state)  # discarded warmup
            shutil.rmtree(f"{root}/warm", ignore_errors=True)
            for trial in range(4):
                t0 = time.perf_counter()
                Snapshot.take(f"{root}/s", app_state)
                saves.append(time.perf_counter() - t0)
                dst = {
                    "model": StateDict(
                        {k: jnp.zeros_like(v) for k, v in state.items()}
                    )
                }
                t0 = time.perf_counter()
                Snapshot(f"{root}/s").restore(dst)
                restores.append(time.perf_counter() - t0)
                if trial < 3:
                    shutil.rmtree(f"{root}/s", ignore_errors=True)
            shutil.rmtree(root, ignore_errors=True)
            legs[mode] = {
                "save_trials_s": [round(t, 3) for t in saves],
                "restore_trials_s": [round(t, 3) for t in restores],
                "save_gbps": round(nbytes / 1e9 / min(saves), 3),
                "save_p50_gbps": round(
                    nbytes / 1e9 / statistics.median(saves), 3
                ),
                "restore_gbps": round(nbytes / 1e9 / min(restores), 3),
                "restore_p50_gbps": round(
                    nbytes / 1e9 / statistics.median(restores), 3
                ),
            }
            _log(
                f"native leg [{mode}]: save best "
                f"{legs[mode]['save_gbps']:.2f} GB/s p50 "
                f"{legs[mode]['save_p50_gbps']:.2f} | restore best "
                f"{legs[mode]['restore_gbps']:.2f} p50 "
                f"{legs[mode]['restore_p50_gbps']:.2f}"
            )
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    from torchsnapshot_tpu import _native

    record = {
        "engine": native_io.engine_kind(),
        "queue_depth": native_io.queue_depth(),
        "slab_caps_seen": _native.slab_caps_seen(),
        "sub_chunk_bytes_pinned": 32 << 20,
        "python": legs["never"],
        "native": legs["always"],
        "native_vs_python_save": round(
            legs["always"]["save_p50_gbps"]
            / max(legs["never"]["save_p50_gbps"], 1e-9),
            3,
        ),
        "native_vs_python_restore": round(
            legs["always"]["restore_p50_gbps"]
            / max(legs["never"]["restore_p50_gbps"], 1e-9),
            3,
        ),
    }
    out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r10.json"
    )
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    _log(f"native I/O side-by-side written to {out}")
    return record


def build_state(total_bytes: int, n_arrays: int = 18):
    """n_arrays bf16 arrays totalling ~total_bytes, on device."""
    import jax
    import jax.numpy as jnp

    per = total_bytes // n_arrays
    n_elem = per // 2  # bf16
    side = int(n_elem**0.5)
    key = jax.random.PRNGKey(0)
    arrs = {}
    for i in range(n_arrays):
        key, sub = jax.random.split(key)
        arrs[f"param_{i}"] = jax.random.normal(sub, (side, side), jnp.bfloat16)
    jax.block_until_ready(arrs)
    return arrs


def main() -> None:
    platform, tpu_reachable = _probe_backend()
    # Hardware side-leg first, while the relay is known-good (it runs in
    # its own subprocess, so it composes with a cpu-backend main leg).
    tpu_hw, side_leg_killed = _tpu_hw_leg() if tpu_reachable else (None, False)
    if side_leg_killed and platform != "cpu":
        # The killed client may have wedged the relay; an in-process TPU
        # init has no timeout and could hang forever. A cpu number beats
        # no number.
        _log("side-leg kill may have wedged the relay; main leg falls back to cpu")
        platform = "cpu"
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    _log(f"initializing backend (requested platform={platform}) ...")
    t0 = time.perf_counter()
    devices = jax.devices()
    _log(
        f"backend up in {time.perf_counter() - t0:.1f}s: "
        f"platform={jax.default_backend()} devices={devices}"
    )

    from torchsnapshot_tpu import Snapshot, StateDict

    total = int(float(sys.argv[1]) * (1 << 30)) if len(sys.argv) > 1 else 2 << 30
    state = build_state(total)
    nbytes = sum(a.nbytes for a in state.values())
    app_state = {"model": StateDict(state)}
    _log(f"state built: {nbytes / 1e9:.2f} GB across {len(state)} arrays")

    # Self-calibrate BEFORE the timed window: a contaminated host (orphan
    # probe children, noisy neighbor, throttled memory) gets one cool-down
    # + re-check, and the verdict is recorded in the artifact either way —
    # a wedged-relay day can degrade the number but can no longer
    # masquerade as a code regression (VERDICT r5 item 1).
    calibration = _host_calibration()
    if calibration["contaminated"]:
        _log("host contaminated; cooling down 30s and re-checking")
        time.sleep(30)
        calibration = _host_calibration()

    # Write to tmpfs when available AND large enough (a snapshot is written
    # twice concurrently at peak: previous + current trial): the reference
    # baseline ran against FSx Lustre (a fast parallel FS); a slow container
    # disk would measure the disk, not the snapshot pipeline.
    base = None
    if os.path.isdir("/dev/shm"):
        if shutil.disk_usage("/dev/shm").free > int(nbytes * 2.5):
            base = "/dev/shm"
        else:
            _log("/dev/shm too small for the snapshot; using default tmpdir")
    tmp = tempfile.mkdtemp(prefix="tsnap_bench_", dir=base)
    try:
        # Warm-up at FULL size, untimed: on lazily-backed VMs the first
        # touch of never-used pages costs several x a normal fault — one
        # full pass warms the guest page pool so the timed trials measure
        # the pipeline, not the hypervisor (round 2 saw a 5.7x
        # run-to-run spread from this; with the warm-up p50 sits within
        # a few percent of best).
        Snapshot.take(f"{tmp}/warm", app_state)
        shutil.rmtree(f"{tmp}/warm", ignore_errors=True)
        _log("full-size warm-up snapshot done; starting timed saves")

        # 6 trials, not 4: on a 1-core VM the hypervisor occasionally
        # steals the core for seconds mid-trial; with 4 trials one such
        # outlier drags p50 below the pipeline's real rate, with 6 the
        # median holds (the raw trials stay in the JSON for audit).
        n_trials = int(os.environ.get("BENCH_TRIALS", "6"))
        # Per-trial purity guard: a ~64 MB memcpy immediately after each
        # trial measures whether the host was contended DURING the
        # window (the pre-window calibration can't see contention that
        # arrives later — exactly the r05 wedged-relay failure mode,
        # where neighbor load made pipeline trials measure the neighbor).
        # A trial whose probe runs at <50% of the calibrated memcpy rate
        # is discarded and retried (bounded); every discarded wall time
        # still lands in the JSON for audit.
        import numpy as _np

        probe_src = _np.empty(64 << 20, _np.uint8)
        probe_src[::4096] = 1
        probe_dst = _np.empty_like(probe_src)
        # Pre-fault the destination too: on this lazily-backed VM a
        # first-touch copy runs at a fraction of the calibrated rate and
        # would falsely flag trial 0 as contended.
        probe_dst[::4096] = 1

        def _memcpy_probe_gbps() -> float:
            t0 = time.perf_counter()
            _np.copyto(probe_dst, probe_src)
            return probe_src.nbytes / max(time.perf_counter() - t0, 1e-9) / 1e9

        import psutil as _psutil

        proc = _psutil.Process()

        save_times = []
        discarded_trials = []
        max_retries = int(os.environ.get("BENCH_TRIAL_RETRIES", "6"))
        retries = 0
        trial = 0
        while trial < n_trials:
            cpu0 = proc.cpu_times()
            t0 = time.perf_counter()
            Snapshot.take(f"{tmp}/snap", app_state)
            trial_dt = time.perf_counter() - t0
            cpu1 = proc.cpu_times()
            # The save is CPU-bound on this path (memcpy + CRC + tmpfs
            # writes): a clean trial's process CPU time ~= wall. When
            # the hypervisor/a neighbor steals the core mid-window, wall
            # inflates while our CPU time doesn't — the ratio is a
            # DURING-trial contention detector the post-trial probe
            # can't be (the thief may leave before the probe runs).
            cpu_ratio = (
                (cpu1.user - cpu0.user) + (cpu1.system - cpu0.system)
            ) / max(trial_dt, 1e-9)
            probe = _memcpy_probe_gbps()
            # The cpu/wall criterion only holds on tmpfs, where the save
            # is CPU-bound; on the disk-directory fallback trials block
            # in I/O wait and a low ratio is the storage medium, not a
            # noisy neighbor — flagging those would discard every clean
            # trial and mislabel the artifact's audit trail.
            contended = probe < 0.5 * calibration["memcpy_gbps"] or (
                base is not None and cpu_ratio < 0.6
            )
            _log(
                f"timed save {trial}: {trial_dt:.2f}s "
                f"({nbytes / 1e9 / trial_dt:.2f} GB/s), cpu/wall "
                f"{cpu_ratio:.2f}, post-trial memcpy {probe:.1f} GB/s"
                f"{' CONTENDED' if contended else ''}"
            )
            # Trials run BACK-TO-BACK deliberately: on this lazily-backed
            # VM, freed tmpfs pages that sit idle get reclaimed by the
            # host and the next trial refaults them at hypervisor speed
            # (measured 0.1 GB/s on all-fresh pages vs 2.5 GB/s reusing
            # just-freed ones). Sleeping between trials — the previous
            # rounds' approach — invited exactly that reclaim; the tight
            # loop reuses the pages the rmtree just freed.
            if contended and retries < max_retries:
                discarded_trials.append(round(trial_dt, 3))
                retries += 1
                shutil.rmtree(f"{tmp}/snap", ignore_errors=True)
                continue
            save_times.append(trial_dt)
            trial += 1
            if trial < n_trials:
                shutil.rmtree(f"{tmp}/snap", ignore_errors=True)
        del probe_src, probe_dst
        dt = min(save_times)
        p50 = statistics.median(save_times)

        # Telemetry leg: one-two takes with the telemetry bus enabled so
        # (a) the per-take summary JSON lands alongside the BENCH_*
        # artifacts — bench trajectory and traces now come from the SAME
        # instrumentation as production saves — and (b) the enabled-vs-
        # disabled overhead is measured and bounded (<3% best-vs-best;
        # the subsystem's contract is near-zero cost). Runs before the
        # restores so they read the final (telemetry-written) snapshot —
        # bit-identical payloads either way.
        from torchsnapshot_tpu import telemetry as _telemetry

        max_overhead = float(os.environ.get("BENCH_TELEMETRY_MAX_PCT", "3.0"))
        # Relative budget with a small absolute floor: persisting the
        # summary + trace costs a fixed few ms, which dominates any
        # percentage on debug-size invocations (~40 ms saves) while
        # vanishing at real sizes (measured +0.65% at 1 GiB).
        overhead_budget_s = max(max_overhead / 100.0 * dt, 0.05)
        tele_times = []
        _telemetry.set_enabled(True)
        try:
            # Up to 6 trials, stopping early once one lands within the
            # overhead budget: this host's lazily-backed VM throws
            # bimodal trials (documented above for the main leg — the
            # disabled trials show the same 2x spread), so a fixed
            # best-of-2 vs the main leg's best-of-6 would measure
            # sampling luck, not the subsystem.
            for tele_trial in range(6):
                shutil.rmtree(f"{tmp}/snap", ignore_errors=True)
                t0 = time.perf_counter()
                Snapshot.take(f"{tmp}/snap", app_state)
                tele_times.append(time.perf_counter() - t0)
                _log(
                    f"telemetry-enabled save {tele_trial}: "
                    f"{tele_times[-1]:.2f}s "
                    f"({nbytes / 1e9 / tele_times[-1]:.2f} GB/s)"
                )
                if tele_trial >= 1 and (min(tele_times) - dt) < overhead_budget_s:
                    break
        finally:
            _telemetry.set_enabled(False)
        tele_summary = _telemetry.last_summary()
        tele_fleet = _telemetry.last_fleet()
        telemetry_overhead_pct = round((min(tele_times) - dt) / dt * 100, 2)
        tele_out = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_TELEMETRY.json"
        )
        with open(tele_out, "w") as f:
            json.dump(
                {
                    "telemetry_trials_s": [round(t, 3) for t in tele_times],
                    "baseline_best_s": round(dt, 3),
                    "overhead_pct": telemetry_overhead_pct,
                    "summary": tele_summary,
                    "fleet": tele_fleet,
                },
                f,
                indent=1,
            )
        _log(
            f"telemetry leg: overhead {telemetry_overhead_pct:+.2f}% "
            f"(best-vs-best); summary written to {tele_out}"
        )
        if not calibration["contaminated"]:
            assert (min(tele_times) - dt) < overhead_budget_s, (
                f"telemetry-enabled save overhead {telemetry_overhead_pct:.2f}% "
                f">= {max_overhead}% budget (disabled best {dt:.3f}s vs "
                f"enabled best {min(tele_times):.3f}s)"
            )
        else:
            _log("host contaminated: telemetry overhead assert skipped")

        # Forensics leg: the main leg's saves ran with the hang watchdog
        # armed (the shipping default — telemetry/forensics.py). A few
        # watchdog-disabled saves bound its always-on cost the other way
        # around: overhead = main-leg best MINUS disabled best. Same
        # early-stop recipe as the telemetry leg (bimodal host).
        from torchsnapshot_tpu.telemetry import forensics as _forensics

        forensics_budget_s = max(0.01 * dt, 0.05)
        noforensics_times = []
        _forensics.set_enabled(False)
        try:
            for nf_trial in range(6):
                shutil.rmtree(f"{tmp}/snap", ignore_errors=True)
                t0 = time.perf_counter()
                Snapshot.take(f"{tmp}/snap", app_state)
                noforensics_times.append(time.perf_counter() - t0)
                _log(
                    f"forensics-disabled save {nf_trial}: "
                    f"{noforensics_times[-1]:.2f}s "
                    f"({nbytes / 1e9 / noforensics_times[-1]:.2f} GB/s)"
                )
                if nf_trial >= 1 and (dt - min(noforensics_times)) < forensics_budget_s:
                    break
        finally:
            _forensics.set_enabled(True)
        forensics_overhead_pct = round(
            (dt - min(noforensics_times)) / min(noforensics_times) * 100, 2
        )
        _log(
            f"forensics leg: overhead {forensics_overhead_pct:+.2f}% "
            "(enabled main-leg best vs disabled best)"
        )
        if not calibration["contaminated"]:
            assert (dt - min(noforensics_times)) < forensics_budget_s, (
                f"always-on hang-watchdog overhead {forensics_overhead_pct:.2f}% "
                f">= 1% budget (disabled best {min(noforensics_times):.3f}s vs "
                f"enabled best {dt:.3f}s, floor 50 ms)"
            )
        else:
            _log("host contaminated: forensics overhead assert skipped")

        # Timed restores into a device-resident destination (mmap read
        # path + zero-copy device_put).
        dst = {"model": StateDict({k: jnp.zeros_like(v) for k, v in state.items()})}
        restore_times = []
        for trial in range(2):
            t0 = time.perf_counter()
            Snapshot(f"{tmp}/snap").restore(dst)
            restore_times.append(time.perf_counter() - t0)
            _log(
                f"timed restore {trial}: {restore_times[-1]:.2f}s "
                f"({nbytes / 1e9 / restore_times[-1]:.2f} GB/s)"
            )
        import numpy as np

        a = np.asarray(jax.device_get(state["param_0"]))
        b = np.asarray(jax.device_get(dst["model"]["param_0"]))
        assert a.tobytes() == b.tobytes(), "restore not bit-exact"
        _log("restore round-trip verified bit-exact")

        # Native-engine side-by-side (BENCH_r10.json): never vs always
        # at a pinned sub-chunk so both modes stream every entry.
        native_leg = _native_io_leg(tmp, app_state, state, nbytes)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    gbps = (nbytes / 1e9) / dt  # decimal GB/s, same unit as the 18 GB/45 s baseline
    record = {
        "metric": "snapshot_save_throughput_1chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / REFERENCE_SAVE_GBPS, 2),
        "p50_gbps": round((nbytes / 1e9) / p50, 3),
        # Raw trial walls: makes best-vs-p50 divergence auditable when a
        # 1-core VM throws an outlier trial (page-cache effects).
        "save_trials_s": [round(t, 3) for t in save_times],
        "restore_gbps": round((nbytes / 1e9) / min(restore_times), 3),
        "platform": jax.default_backend(),
        "host_calibration": calibration,
        # Enabled-vs-disabled cost of the telemetry subsystem (full
        # per-take summary + trace in BENCH_TELEMETRY.json).
        "telemetry_overhead_pct": telemetry_overhead_pct,
        # Always-on hang-watchdog cost (telemetry/forensics.py): main-leg
        # best (watchdog armed, the default) vs watchdog-disabled best.
        "forensics_overhead_pct": forensics_overhead_pct,
    }
    if discarded_trials:
        # Trials where the post-trial memcpy probe showed the host was
        # contended mid-window (neighbor/hypervisor, not the pipeline).
        record["discarded_contended_trials_s"] = discarded_trials
    if tpu_hw is not None:
        record["tpu_hw"] = tpu_hw
    if native_leg is not None:
        record["native_io"] = native_leg
    # Cooperative restore fan-out side-leg (multi-process, own group +
    # timeout): failures degrade to an absent key, never a dead bench.
    coop = _coop_restore_leg()
    if coop is not None:
        record["coop_restore"] = coop
    # Planned-reshard side-leg (BENCH_r11.json): never vs always on the
    # tp2 -> tp4 cross-cut, plus the 50k-shard plan-time bound.
    reshard_leg = _reshard_leg()
    if reshard_leg is not None:
        record["reshard"] = reshard_leg
    # Delta-journal RPO side-leg (BENCH_r12.json): epoch append vs full
    # save on throttled storage — recoverable-state interval at equal
    # sustained overhead.
    journal_leg = _journal_leg()
    if journal_leg is not None:
        record["journal"] = journal_leg
    # Fleet-distribution side-leg (BENCH_r13.json): emulated world-64
    # seeded rollout vs the 64x direct baseline, fan-out depth, and the
    # journal-delta rolling update.
    distrib_leg = _distrib_leg()
    if distrib_leg is not None:
        record["fleet_distribution"] = distrib_leg
    # Multi-tenant side-leg (BENCH_r14.json): the 1M-leaf columnar
    # manifest plane and the priority-weighted admission drill.
    tenancy_leg = _tenancy_leg()
    if tenancy_leg is not None:
        record["tenancy"] = tenancy_leg
    # Lazy page-in side-leg (BENCH_r15.json): time-to-first-inference
    # with a hot-set-resident return vs the eager full-restore wall.
    lazy_leg = _lazy_leg()
    if lazy_leg is not None:
        record["lazy_restore"] = lazy_leg
    # Closed-loop autotune side-leg (BENCH_r16.json): cold-start
    # convergence vs a hand-tuned pin, and warm-start from persisted
    # learned profiles.
    autotune_leg = _autotune_leg()
    if autotune_leg is not None:
        record["autotune"] = autotune_leg
    # Geo-replication RPO side-leg (BENCH_r17.json): remote recovery
    # point vs journal cadence over a throttled WAN, and the armed-
    # shipper foreground gate.
    georep_leg = _georep_leg()
    if georep_leg is not None:
        record["georep"] = georep_leg
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
