"""Headline benchmark: Snapshot save throughput for device state.

Mirrors the reference's DDP benchmark (benchmarks/ddp/main.py: save a model
of N x 100MB params, report wall time). Reference baseline on comparable
1-worker hardware: 18 GB in ~45 s => 0.40 GB/s (benchmarks/ddp/README.md:15,
reproduced in BASELINE.md). We report save throughput in GB/s on one chip;
vs_baseline is the ratio against that 0.40 GB/s figure.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time

import jax
import jax.numpy as jnp

REFERENCE_SAVE_GBPS = 18.0 / 45.0  # benchmarks/ddp/README.md:15 (1 worker)


def build_state(total_bytes: int, n_arrays: int = 18):
    """n_arrays bf16 arrays totalling ~total_bytes, on device."""
    per = total_bytes // n_arrays
    n_elem = per // 2  # bf16
    side = int(n_elem**0.5)
    key = jax.random.PRNGKey(0)
    arrs = {}
    for i in range(n_arrays):
        key, sub = jax.random.split(key)
        arrs[f"param_{i}"] = jax.random.normal(sub, (side, side), jnp.bfloat16)
    jax.block_until_ready(arrs)
    return arrs


def main() -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    total = int(float(sys.argv[1]) * (1 << 30)) if len(sys.argv) > 1 else 2 << 30
    state = build_state(total)
    nbytes = sum(a.nbytes for a in state.values())
    app_state = {"model": StateDict(state)}

    tmp = tempfile.mkdtemp(prefix="tsnap_bench_")
    try:
        # Warm-up on a small state to amortize one-time costs out of the try.
        warm = {"model": StateDict({"w": jnp.ones((256, 256), jnp.bfloat16)})}
        Snapshot.take(f"{tmp}/warm", warm)

        t0 = time.perf_counter()
        Snapshot.take(f"{tmp}/snap", app_state)
        dt = time.perf_counter() - t0

        # Sanity: restore must round-trip (not timed into the headline).
        dst = {"model": StateDict({k: jnp.zeros_like(v) for k, v in state.items()})}
        Snapshot(f"{tmp}/snap").restore(dst)
        import numpy as np

        a = np.asarray(jax.device_get(state["param_0"]))
        b = np.asarray(jax.device_get(dst["model"]["param_0"]))
        assert a.tobytes() == b.tobytes(), "restore not bit-exact"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    gbps = (nbytes / 1e9) / dt  # decimal GB/s, same unit as the 18 GB/45 s baseline
    print(
        json.dumps(
            {
                "metric": "snapshot_save_throughput_1chip",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / REFERENCE_SAVE_GBPS, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
