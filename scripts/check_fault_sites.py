#!/usr/bin/env python
"""Lint: fault-injection sites are unique, registered, shim-only (thin wrapper).

The implementation moved into the ``tsalint`` static-analysis framework
(``torchsnapshot_tpu/analysis/plugins/legacy_fault_sites.py``, rule id
``fault-sites``) — run it standalone here, as ``python -m
torchsnapshot_tpu lint --rule fault-sites``, or as part of the full
``tsalint`` run. This wrapper keeps the historical entry point and
re-exports the names tier-1 tests exercise; output and exit codes are
bit-identical.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchsnapshot_tpu.analysis.plugins.legacy_fault_sites import (  # noqa: E402,F401
    ALLOWED_ATTRS,
    KNOWN_SITES,
    MIN_SITES,
    PACKAGE,
    PINNED_SITE_FILES,
    REPO,
    check_source,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
