#!/usr/bin/env python
"""Lint: the peer plane stays jax-free (thin wrapper).

The implementation moved into the ``tsalint`` static-analysis framework
(``torchsnapshot_tpu/analysis/plugins/legacy_peer_channel.py``, rule id
``peer-channel``) — run it standalone here, as ``python -m
torchsnapshot_tpu lint --rule peer-channel``, or as part of the full
``tsalint`` run. This wrapper keeps the historical entry point and
re-exports the names tier-1 tests exercise; output and exit codes are
bit-identical.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchsnapshot_tpu.analysis.plugins.legacy_peer_channel import (  # noqa: E402,F401
    PEER_PLANE_FILES,
    PKG,
    REPO,
    check_source,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
