#!/usr/bin/env python
"""Lint: the cooperative-restore peer plane must be device-free BY
CONSTRUCTION — no ``jax`` import, attribute chain, or device/collective
call anywhere in ``fanout.py`` or the ``dist_store.py`` transport.

Why a lint, not review: the peer channel runs on background restore
threads (async_restore's worker, receiver/handler threads, the commit
thread's restores), where a device collective deadlocks against the main
thread's XLA programs — the exact hazard the repo's snapshot.py:33
invariant exists to prevent. The streaming consumers that DO touch
devices (io_preparers) sit above the channel and run on the scheduler's
event loop; the channel itself moves bytes only. A well-meaning
"optimization" that slips a ``jax.device_put`` or a collective into the
forwarding path would pass every single-process test and hang a pod —
so opting the peer plane into jax must fail CI, not slip through review.

Checked per file (AST walk, so comments/strings never false-positive):
  - ``import jax`` / ``import jax.anything`` / ``from jax... import ...``
  - any attribute/call chain rooted at a name bound from jax

Run: ``python scripts/check_peer_channel.py`` — exits 0 when clean, 1
with a per-violation report otherwise. Enforced in tier-1 via
tests/test_fanout.py (test_peer_channel_lint).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "torchsnapshot_tpu")

# The peer plane: the fan-out protocol/session module and the transport
# sidecar it rides (dist_store also hosts the KV store — equally
# device-free by the same invariant).
PEER_PLANE_FILES = ("fanout.py", "dist_store.py")


def check_source(source: str, filename: str) -> list:
    """Return (line, message) violations for one file's source."""
    tree = ast.parse(source, filename=filename)
    violations = []
    jax_names = set()

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root == "jax":
                    violations.append(
                        (node.lineno, f"import {alias.name!r}")
                    )
                    jax_names.add(alias.asname or root)
        elif isinstance(node, ast.ImportFrom):
            root = (node.module or "").split(".")[0]
            if root == "jax":
                names = ", ".join(a.name for a in node.names)
                violations.append(
                    (node.lineno, f"from {node.module} import {names}")
                )
                for alias in node.names:
                    jax_names.add(alias.asname or alias.name)

    if jax_names:
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and node.id in jax_names:
                # Attribute chains and calls both root at a Name load.
                if isinstance(node.ctx, ast.Load):
                    violations.append(
                        (node.lineno, f"use of jax-bound name {node.id!r}")
                    )
    return sorted(set(violations))


def main() -> int:
    bad = 0
    for name in PEER_PLANE_FILES:
        path = os.path.join(PKG, name)
        with open(path, "r") as f:
            source = f.read()
        for lineno, msg in check_source(source, path):
            print(
                f"{os.path.relpath(path, REPO)}:{lineno}: jax on the peer "
                f"plane ({msg}) — the cooperative-restore byte channel must "
                "stay background-thread-safe by construction; move device "
                "work into a consumer above the channel",
                file=sys.stderr,
            )
            bad += 1
    if bad:
        return 1
    print(
        f"peer channel lint: clean ({len(PEER_PLANE_FILES)} file(s), "
        "no jax imports or calls)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
