#!/usr/bin/env python
"""Lint: flight-recorder event names AND histogram instrument names are
registered literals, and both registries are fully wired.

The flight recorder (torchsnapshot_tpu/telemetry/flightrec.py) is always
on: its event stream is an operator interface — the ``blackbox`` CLI
merges rank dumps by matching event names, runbooks grep for them, tests
assert on them. Three properties keep that interface trustworthy, in the
same lint culture as ``check_fault_sites.py``:

1. **Registered names only.** Every ``flightrec.record(...)`` call in
   the package must pass a STRING LITERAL present in
   ``telemetry.events.FLIGHT_EVENTS`` — a typo'd name would record
   events nothing can find.
2. **No dead registry rows.** Every registered name must be recorded at
   one or more call sites (unlike fault sites, multiplicity is fine:
   ``collective.enter`` fires from every collective verb); a registered-
   but-unwired name means a documented event that can never occur.
3. **Literal-first calls.** The event name must be the literal first
   argument — computed names are unlintable and ungreppable.

The latency-histogram instrument (``telemetry.histogram_observe``, ISSUE
8) gets the same treatment against ``taxonomy.HISTOGRAM_NAMES``: fleet
merges sum bucket-wise BY NAME and the /metrics exposition names
families by it, so a typo'd instrument would silently fork a family no
dashboard watches. Every ``histogram_observe(...)`` call in the package
must pass a registered literal first argument, and every registered name
must be observed somewhere.

Run: ``python scripts/check_event_taxonomy.py`` — exits 0 when clean, 1
with a per-violation report. Enforced in tier-1 via
tests/test_flightrec.py.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "torchsnapshot_tpu")

sys.path.insert(0, REPO)

from torchsnapshot_tpu.telemetry.taxonomy import (  # noqa: E402
    FLIGHT_EVENTS,
    HISTOGRAMS,
)

# Names a module may bind the flightrec module to. Calls are recognized
# as ``<alias>.record(...)`` or ``telemetry.flightrec.record(...)``.
_MODULE_NAME = "flightrec"

# Regression floor: the taxonomy shipped with this many events (ISSUE 7).
# Shrinking it means an operator-facing event class was silently dropped.
MIN_EVENTS = 15
# Same floor for histogram instruments (ISSUE 8).
MIN_HISTOGRAMS = 5


def _is_flightrec_record(fn: ast.AST, aliases: set) -> bool:
    """True for ``<alias>.record`` and ``<mod>.flightrec.record``."""
    if not (isinstance(fn, ast.Attribute) and fn.attr == "record"):
        return False
    val = fn.value
    if isinstance(val, ast.Name) and val.id in aliases:
        return True
    return isinstance(val, ast.Attribute) and val.attr == _MODULE_NAME


def _is_histogram_observe(fn: ast.AST) -> bool:
    """True for ``<anything>.histogram_observe`` and a bare
    ``histogram_observe`` name (``from ... import histogram_observe``)."""
    if isinstance(fn, ast.Attribute) and fn.attr == "histogram_observe":
        return True
    return isinstance(fn, ast.Name) and fn.id == "histogram_observe"


def check_source(
    source: str, filename: str
) -> Tuple[List[Tuple[int, str]], Dict[str, List[int]], Dict[str, List[int]]]:
    """Return (violations, {event_name: [lines]}, {hist_name: [lines]})
    for one file."""
    tree = ast.parse(source, filename=filename)
    violations: List[Tuple[int, str]] = []
    uses: Dict[str, List[int]] = {}
    hist_uses: Dict[str, List[int]] = {}
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[-1] == _MODULE_NAME:
                    aliases.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == _MODULE_NAME:
                    aliases.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_histogram_observe(node.func):
            if not node.args or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                violations.append(
                    (
                        node.lineno,
                        "histogram_observe(...) — the instrument name must "
                        "be a string literal",
                    )
                )
                continue
            name = node.args[0].value
            if name not in HISTOGRAMS:
                violations.append(
                    (
                        node.lineno,
                        f"histogram_observe({name!r}) — instrument not "
                        "registered in telemetry/taxonomy.py",
                    )
                )
                continue
            hist_uses.setdefault(name, []).append(node.lineno)
            continue
        if not _is_flightrec_record(node.func, aliases):
            continue
        if not node.args or not (
            isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            violations.append(
                (
                    node.lineno,
                    "flightrec.record(...) — the event name must be a "
                    "string literal",
                )
            )
            continue
        name = node.args[0].value
        if name not in FLIGHT_EVENTS:
            violations.append(
                (
                    node.lineno,
                    f"flightrec.record({name!r}) — event not registered in "
                    "telemetry/taxonomy.py",
                )
            )
            continue
        uses.setdefault(name, []).append(node.lineno)
    return violations, uses, hist_uses


def run(package_dir: str = PACKAGE) -> List[str]:
    failures: List[str] = []
    wired: Dict[str, List[str]] = {}
    hist_wired: Dict[str, List[str]] = {}
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fname), package_dir)
            if rel in (
                os.path.join("telemetry", "flightrec.py"),
                os.path.join("telemetry", "core.py"),
            ):
                continue  # the shims themselves
            path = os.path.join(dirpath, fname)
            with open(path, "r") as f:
                source = f.read()
            violations, uses, hist_uses = check_source(source, path)
            for lineno, what in violations:
                failures.append(f"{rel}:{lineno}: {what}")
            for name, lines in uses.items():
                for lineno in lines:
                    wired.setdefault(name, []).append(f"{rel}:{lineno}")
            for name, lines in hist_uses.items():
                for lineno in lines:
                    hist_wired.setdefault(name, []).append(f"{rel}:{lineno}")
    # flight.dump is emitted by the dump machinery itself (the header
    # record), not via record() — it is wired by construction.
    wired.setdefault("flight.dump", ["telemetry/flightrec.py:dump"])
    for name in sorted(FLIGHT_EVENTS - set(wired)):
        failures.append(
            f"event {name!r} is registered in telemetry/taxonomy.py but "
            "recorded nowhere — remove the registration or wire the event"
        )
    for name in sorted(HISTOGRAMS - set(hist_wired)):
        failures.append(
            f"histogram {name!r} is registered in telemetry/taxonomy.py but "
            "observed nowhere — remove the registration or wire the "
            "instrument"
        )
    if len(FLIGHT_EVENTS) < MIN_EVENTS:
        failures.append(
            f"event taxonomy shrank to {len(FLIGHT_EVENTS)} (< {MIN_EVENTS}): "
            "an operator-facing event class was dropped"
        )
    if len(HISTOGRAMS) < MIN_HISTOGRAMS:
        failures.append(
            f"histogram registry shrank to {len(HISTOGRAMS)} "
            f"(< {MIN_HISTOGRAMS}): an operator-facing latency family was "
            "dropped"
        )
    return failures


def main() -> int:
    failures = run()
    if failures:
        print("flight-recorder event taxonomy lint failures:", file=sys.stderr)
        for failure in sorted(failures):
            print(f"  {failure}", file=sys.stderr)
        return 1
    print(
        f"event-taxonomy lint: clean ({len(FLIGHT_EVENTS)} events, "
        f"{len(HISTOGRAMS)} histograms registered)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
