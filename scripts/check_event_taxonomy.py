#!/usr/bin/env python
"""Lint: flight-recorder events and histograms use the taxonomy (thin wrapper).

The implementation moved into the ``tsalint`` static-analysis framework
(``torchsnapshot_tpu/analysis/plugins/legacy_event_taxonomy.py``, rule
id ``event-taxonomy``) — run it standalone here, as ``python -m
torchsnapshot_tpu lint --rule event-taxonomy``, or as part of the full
``tsalint`` run. This wrapper keeps the historical entry point and
re-exports the names tier-1 tests exercise; output and exit codes are
bit-identical.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchsnapshot_tpu.analysis.plugins.legacy_event_taxonomy import (  # noqa: E402,F401
    FLIGHT_EVENTS,
    HISTOGRAMS,
    MIN_EVENTS,
    MIN_HISTOGRAMS,
    PACKAGE,
    REPO,
    check_source,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
