#!/usr/bin/env python
"""Run the FULL test suite in bounded tier groups and write TESTRUN.md.

The suite is large enough (45+ files, ~12k test LoC) that one
monolithic `pytest tests/` run is hard to audit and hard to bound on a
1-core host. This driver runs the marker tiers as separate pytest
invocations, each with its own hard timeout, and records an auditable
artifact — date, commit, per-group counts/durations, the slowest tests
— so "the whole suite is green" is a committed fact rather than a
builder's claim (reference seam: the reference CI publishes every run,
.github/workflows/unit_test.yaml:36-41).

Usage:  python scripts/run_full_suite.py [--out TESTRUN.md]
Exit status: 0 iff every group passed.
"""

from __future__ import annotations

import argparse
import datetime
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (name, pytest -m expression, per-group timeout seconds)
# Groups partition the suite exactly: every test matches one expression.
GROUPS = [
    ("fast", "not slow and not multiprocess and not hypothesis_fuzz", 900),
    ("multiprocess", "multiprocess and not slow", 1200),
    ("slow", "slow and not multiprocess", 1800),
    ("slow-multiprocess", "slow and multiprocess", 1200),
    ("fuzz", "hypothesis_fuzz and not slow and not multiprocess", 900),
]

_SUMMARY_RE = re.compile(
    r"(?:(\d+) failed)?(?:, )?(?:(\d+) passed)?(?:, )?(?:(\d+) skipped)?"
    r"(?:, )?(?:(\d+) deselected)?.* in ([\d.]+)s"
)
_DURATION_RE = re.compile(r"^([\d.]+)s\s+(call|setup|teardown)\s+(\S+)")


def run_group(name: str, marker: str, timeout: int):
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/",
        "-q",
        "-m",
        marker,
        "--durations=10",
        "-p",
        "no:cacheprovider",
    ]
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            cmd,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        out = proc.stdout + proc.stderr
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries undecoded bytes even under text=True.
        def _as_text(x):
            if x is None:
                return ""
            return x.decode(errors="replace") if isinstance(x, bytes) else x

        out = _as_text(e.stdout) + _as_text(e.stderr)
        rc = -1
    elapsed = time.monotonic() - t0

    counts = {"failed": 0, "passed": 0, "skipped": 0, "deselected": 0}
    for line in reversed(out.splitlines()):
        m = _SUMMARY_RE.search(line)
        if m and ("passed" in line or "failed" in line or "skipped" in line):
            counts["failed"] = int(m.group(1) or 0)
            counts["passed"] = int(m.group(2) or 0)
            counts["skipped"] = int(m.group(3) or 0)
            counts["deselected"] = int(m.group(4) or 0)
            break
    durations = []
    for line in out.splitlines():
        m = _DURATION_RE.match(line.strip())
        if m and m.group(2) == "call":
            durations.append((float(m.group(1)), m.group(3)))
    # rc==5 means "no tests collected" — fine for an empty group.
    ok = rc in (0, 5) and counts["failed"] == 0
    return {
        "name": name,
        "marker": marker,
        "ok": ok,
        "rc": rc,
        "elapsed": elapsed,
        "counts": counts,
        "durations": durations,
        "tail": "\n".join(out.splitlines()[-30:]) if not ok else "",
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "TESTRUN.md"))
    args = ap.parse_args()

    commit = subprocess.run(
        ["git", "rev-parse", "--short", "HEAD"],
        cwd=REPO,
        capture_output=True,
        text=True,
    ).stdout.strip()
    started = datetime.datetime.now(datetime.timezone.utc)

    results = []
    for name, marker, timeout in GROUPS:
        print(f"=== group {name!r} (-m {marker!r}, timeout {timeout}s)")
        r = run_group(name, marker, timeout)
        c = r["counts"]
        print(
            f"    {'OK' if r['ok'] else 'FAIL'}: {c['passed']} passed, "
            f"{c['failed']} failed, {c['skipped']} skipped "
            f"in {r['elapsed']:.0f}s"
        )
        results.append(r)

    total = {
        k: sum(r["counts"][k] for r in results)
        for k in ("passed", "failed", "skipped")
    }
    total_s = sum(r["elapsed"] for r in results)
    all_ok = all(r["ok"] for r in results)
    slowest = sorted(
        (d for r in results for d in r["durations"]), reverse=True
    )[:10]

    lines = [
        "# TESTRUN — full-suite run artifact",
        "",
        "Produced by `python scripts/run_full_suite.py` (tier groups with",
        "per-group hard timeouts; see the script for the exact matrix).",
        "",
        f"- date: {started.strftime('%Y-%m-%d %H:%M UTC')}",
        f"- commit: `{commit}`",
        f"- host: 1-core CI-class VM, CPU backend (8 virtual devices)",
        f"- result: **{'GREEN' if all_ok else 'FAILED'}** — "
        f"{total['passed']} passed, {total['failed']} failed, "
        f"{total['skipped']} skipped in {total_s/60:.1f} min",
        "",
        "| group | marker | passed | failed | skipped | time |",
        "|---|---|---|---|---|---|",
    ]
    for r in results:
        c = r["counts"]
        lines.append(
            f"| {r['name']} | `{r['marker']}` | {c['passed']} | "
            f"{c['failed']} | {c['skipped']} | {r['elapsed']:.0f}s |"
        )
    lines += ["", "Slowest tests (call phase):", ""]
    for secs, test in slowest:
        lines.append(f"- {secs:.1f}s `{test}`")
    for r in results:
        if not r["ok"]:
            lines += ["", f"## FAILURE tail: {r['name']}", "", "```",
                      r["tail"], "```"]
    lines.append("")

    with open(args.out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {args.out}: {'GREEN' if all_ok else 'FAILED'}")
    return 0 if all_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
