#!/usr/bin/env python
"""Lint: no ad-hoc ``time.monotonic()`` / ``time.perf_counter()`` timing
in ``torchsnapshot_tpu/`` outside the telemetry package.

The telemetry subsystem (torchsnapshot_tpu/telemetry/) is the ONE
measurement mechanism for the pipeline — spans, counters, rates, and the
blessed ``telemetry.monotonic`` clock. Before it existed, measurements
forked into private meters (scheduler throughput tables, governor EWMA
feeds, phase timers) that could silently disagree; this check keeps new
code from regrowing them. Wall-clock DEADLINE logic (store RPC timeouts,
the test launcher's subprocess deadline) is not measurement and stays on
raw ``time.monotonic`` via the explicit allowlist below.

Run: ``python scripts/check_timing_lint.py`` — exits 0 when clean,
1 with a per-violation report otherwise. Enforced in tier-1 via
tests/test_timing_lint.py.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "torchsnapshot_tpu")

# Paths (relative to the package) allowed to call time.monotonic/
# perf_counter directly. Deadline/timeout bookkeeping only — add a file
# here ONLY for wall-deadline logic, never for measurement (measurement
# belongs on the telemetry bus).
ALLOWLIST = {
    "dist_store.py",  # store RPC / barrier deadline arithmetic
    "test_utils.py",  # multi-process launcher subprocess deadline
}

_BANNED_ATTRS = {"monotonic", "perf_counter", "monotonic_ns", "perf_counter_ns"}


def _violations_in(path: str) -> list:
    with open(path, "r") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:  # pragma: no cover - package must parse
        return [(e.lineno or 0, f"syntax error: {e}")]
    out = []
    # Names bound by `from time import monotonic/perf_counter [as alias]`.
    from_time_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in _BANNED_ATTRS:
                    from_time_aliases.add(alias.asname or alias.name)
                    out.append(
                        (node.lineno, f"from time import {alias.name}")
                    )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _BANNED_ATTRS
            and isinstance(fn.value, ast.Name)
            and fn.value.id in ("time", "_time")
        ):
            out.append((node.lineno, f"{fn.value.id}.{fn.attr}()"))
        elif isinstance(fn, ast.Name) and fn.id in from_time_aliases:
            out.append((node.lineno, f"{fn.id}()"))
    return out


def main() -> int:
    failures = []
    for dirpath, dirnames, filenames in os.walk(PACKAGE):
        rel_dir = os.path.relpath(dirpath, PACKAGE)
        if rel_dir.split(os.sep)[0] == "telemetry":
            continue  # the one place the raw clock belongs
        for name in filenames:
            if not name.endswith(".py"):
                continue
            rel = os.path.normpath(os.path.join(rel_dir, name))
            if rel in ALLOWLIST:
                continue
            for lineno, what in _violations_in(os.path.join(dirpath, name)):
                failures.append((rel, lineno, what))
    if failures:
        print(
            "ad-hoc timing outside torchsnapshot_tpu/telemetry/ "
            "(use telemetry.span()/record_rate()/telemetry.monotonic, or "
            "add a DEADLINE-logic file to the allowlist in "
            "scripts/check_timing_lint.py):",
            file=sys.stderr,
        )
        for rel, lineno, what in sorted(failures):
            print(f"  torchsnapshot_tpu/{rel}:{lineno}: {what}", file=sys.stderr)
        return 1
    print("timing lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
