#!/usr/bin/env python
"""Lint: the telemetry package owns pipeline timing (thin wrapper).

The implementation moved into the ``tsalint`` static-analysis framework
(``torchsnapshot_tpu/analysis/plugins/legacy_timing.py``, rule id
``timing``) — run it standalone here, as ``python -m torchsnapshot_tpu
lint --rule timing``, or as part of the full ``tsalint`` run. This
wrapper keeps the historical entry point and re-exports the names
tier-1 tests exercise; output and exit codes are bit-identical.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchsnapshot_tpu.analysis.plugins.legacy_timing import (  # noqa: E402,F401
    ALLOWLIST,
    BENCH_DIR,
    BENCHMARK_ALLOWLIST,
    PACKAGE,
    REPO,
    TELEMETRY_COVERED,
    _BANNED_ATTRS,
    _violations_in,
    collect_failures,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
