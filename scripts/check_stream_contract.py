#!/usr/bin/env python
"""Lint: every storage plugin advertising ``supports_streaming_reads``
must be covered by the shared read-stream contract parametrization
(``CONTRACT_PLUGINS`` in tests/test_streaming_read.py).

The streaming contract is behavioral, not structural: a plugin whose
``read_stream`` drops, reorders, or duplicates a byte corrupts restored
state silently, and nothing in the type system catches it. The contract
tests (streamed bytes == buffered bytes, full + ranged, zero-length
short-circuit) are the enforcement — so opting a plugin in WITHOUT
registering it there must fail CI, not slip through review.

Run: ``python scripts/check_stream_contract.py`` — exits 0 when every
advertising plugin is registered, 1 with a per-plugin report otherwise.
Enforced in tier-1 via tests/test_streaming_read.py
(test_contract_coverage_lint).
"""

from __future__ import annotations

import importlib
import inspect
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TEST_FILE = os.path.join(REPO, "tests", "test_streaming_read.py")

# Every module under torchsnapshot_tpu/storage_plugins that can define a
# plugin class (the walk is explicit so a new module is added here — and
# thereby linted — rather than silently skipped).
PLUGIN_MODULES = ("fs", "s3", "gcs", "mirror", "retry")


def advertising_plugins() -> set:
    sys.path.insert(0, REPO)
    from torchsnapshot_tpu.io_types import StoragePlugin

    out = set()
    for name in PLUGIN_MODULES:
        mod = importlib.import_module(f"torchsnapshot_tpu.storage_plugins.{name}")
        for _, cls in inspect.getmembers(mod, inspect.isclass):
            if not issubclass(cls, StoragePlugin) or cls.__module__ != mod.__name__:
                continue
            # getattr_static sees a property (mirror's delegation) as
            # advertising too — composition still needs contract tests.
            flag = inspect.getattr_static(cls, "supports_streaming_reads", False)
            if flag is not False:
                out.add(cls.__name__)
    return out


def covered_plugins() -> set:
    with open(TEST_FILE, "r") as f:
        source = f.read()
    match = re.search(r"CONTRACT_PLUGINS\s*=\s*\{(.*?)\n\}", source, re.S)
    if match is None:
        return set()
    return set(re.findall(r'"(\w+)"\s*:', match.group(1)))


def main() -> int:
    advertised = advertising_plugins()
    covered = covered_plugins()
    missing = sorted(advertised - covered)
    if missing:
        print(
            "storage plugin(s) advertise supports_streaming_reads without "
            "read-stream contract coverage (register them in "
            "CONTRACT_PLUGINS, tests/test_streaming_read.py):",
            file=sys.stderr,
        )
        for name in missing:
            print(f"  {name}", file=sys.stderr)
        return 1
    print(
        f"stream contract lint: clean ({len(advertised)} advertising "
        f"plugin(s), all covered)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
