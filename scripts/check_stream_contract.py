#!/usr/bin/env python
"""Lint: streaming-read advertisers carry contract tests (thin wrapper).

The implementation moved into the ``tsalint`` static-analysis framework
(``torchsnapshot_tpu/analysis/plugins/legacy_stream_contract.py``, rule
id ``stream-contract``) — run it standalone here, as ``python -m
torchsnapshot_tpu lint --rule stream-contract``, or as part of the full
``tsalint`` run. This wrapper keeps the historical entry point and
re-exports the names tier-1 tests exercise; output and exit codes are
bit-identical.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchsnapshot_tpu.analysis.plugins.legacy_stream_contract import (  # noqa: E402,F401
    PLUGIN_MODULES,
    REPO,
    TEST_FILE,
    advertising_plugins,
    covered_plugins,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
