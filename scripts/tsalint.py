#!/usr/bin/env python
"""tsalint — the torchsnapshot_tpu static analyzer (standalone entry).

Equivalent to ``python -m torchsnapshot_tpu lint``; this script exists
so CI and pre-commit hooks can run the analyzer without importing the
package's heavy top level. See docs/source/static_analysis.rst for the
rule catalog and suppression syntax.

Exit codes: 0 clean, 1 findings (or suppression-hygiene failures),
2 usage/internal error.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchsnapshot_tpu.analysis.runner import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
