"""Pod-shape process topology: N processes x M local devices each.

A real TPU pod host is ONE process owning SEVERAL chips (a v5p host is
1 process x 4 chips inside a multi-host world). Everything else in the
suite tests either 1 process x 8 virtual devices (single-process GSPMD)
or N processes x 1 device (test_multiprocess_jax.py). These tests run the
missing shape: real ``jax.distributed`` worlds where every process holds
MULTIPLE local devices, meshes span the process boundary on one axis and
stay inside it on the other, and a process can own several shard boxes at
once. That is where writer election must balance within AND across
processes, where partially-replicated layouts put the same box in every
process, and where addressable/non-addressable mixes get interesting
(reference analogue: the multi-process harness of test_utils.py:166-205,
which exists for exactly this class of semantics).

Topologies: 2 procs x 4 devices ("v5p-host-like") and 4 procs x 2 devices.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import (
    _find_free_port,
    init_pod_world as _init_pod,
    run_with_subprocesses,
)

pytestmark = [pytest.mark.multiprocess]

SHAPE = (8, 8)


def _global_data() -> np.ndarray:
    return np.arange(64, dtype=np.float32).reshape(SHAPE)


def _pod_mesh(jax, n_procs: int, local: int, transpose: bool = False):
    """('proc', 'local') mesh: axis 0 crosses processes, axis 1 stays
    inside one. ``transpose`` builds the swapped (local, n_procs) mesh —
    a genuinely different layout whose boxes cut across the originals."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()).reshape(n_procs, local)
    if transpose:
        return Mesh(devs.reshape(local, n_procs), ("proc", "local"))
    return Mesh(devs, ("proc", "local"))


def _make_array(jax, mesh, spec):
    from jax.sharding import NamedSharding

    return jax.make_array_from_callback(
        SHAPE, NamedSharding(mesh, spec), lambda idx: _global_data()[idx]
    )


def _check_restored(arr) -> None:
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )


def _matrix_worker(rank, world_size, root, port, local):
    """The core save/restore matrix at pod shape, one world bring-up:

    a) fully-partitioned 2-D sharding (this process owns ``local`` boxes)
       -> take -> restore into the TRANSPOSED mesh layout (cross-layout
       reshard across the process boundary);
    b) partially-replicated P(None,'local'): every box is held by every
       process -> writer election must dedupe to ONE writer per box,
       balanced by hash across all processes;
    c) process-internal replication P('proc',None): each box is held by
       ``local`` devices of a single process -> that process writes it;
    d) replicated big host array: chunk-striped across ranks.
    """
    from jax.sharding import PartitionSpec as P

    jax = _init_pod(rank, world_size, port, local)
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.sharded import ShardedArrayIOPreparer

    mesh = _pod_mesh(jax, world_size, local)
    owned_counts = {}

    # --- a) fully partitioned: local x proc boxes, several per process
    full = _make_array(jax, mesh, P("proc", "local"))
    assert len(full.addressable_shards) == local
    if world_size > 1:
        assert not full.is_fully_addressable
    owned_counts["full"] = len(
        list(ShardedArrayIOPreparer._owned_pieces(full))
    )

    # --- b) every process holds every box (replicated over 'proc')
    repl_proc = _make_array(jax, mesh, P(None, "local"))
    owned_counts["repl_proc"] = len(
        list(ShardedArrayIOPreparer._owned_pieces(repl_proc))
    )

    # --- c) boxes replicated only WITHIN a process
    repl_local = _make_array(jax, mesh, P("proc", None))
    owned_counts["repl_local"] = len(
        list(ShardedArrayIOPreparer._owned_pieces(repl_local))
    )

    # --- d) replicated host array, chunk-striped across ranks
    from torchsnapshot_tpu.io_preparers import chunked

    old = chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES
    chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = 64  # 2 rows of 8 float32 per chunk
    try:
        app = {
            "m": StateDict(
                full=full,
                repl_proc=repl_proc,
                repl_local=repl_local,
                host=_global_data(),
                step=7,
            )
        }
        Snapshot.take(root, app, replicated=["m/host"])
    finally:
        chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = old

    # Restore into the TRANSPOSED mesh: every destination box cuts across
    # several saved boxes, so the overlap scatter runs across the process
    # boundary in both directions.
    mesh2 = _pod_mesh(jax, world_size, local, transpose=True)
    out = StateDict(
        full=_make_array(jax, mesh2, P("proc", "local")) * 0,
        repl_proc=_make_array(jax, mesh2, P("local", None)) * 0,
        repl_local=_make_array(jax, mesh2, P(None, "proc")) * 0,
        host=np.zeros(SHAPE, np.float32),
        step=-1,
    )
    Snapshot(root).restore({"m": out})
    assert out["step"] == 7
    np.testing.assert_array_equal(out["host"], _global_data())
    for key in ("full", "repl_proc", "repl_local"):
        _check_restored(out[key])
    return owned_counts


def _assert_matrix(results, world_size, local, root):
    # a) fully partitioned: every process wrote exactly its local boxes.
    assert all(r["full"] == local for r in results.values()), results
    # b) replicated over 'proc': the `local` unique boxes were written
    # exactly once IN TOTAL (dedup), spread by hash across processes.
    assert sum(r["repl_proc"] for r in results.values()) == local, results
    # c) replicated within a process: one writer per process-owned box.
    assert sum(r["repl_local"] for r in results.values()) == world_size
    assert all(r["repl_local"] <= 1 for r in results.values())

    # On-disk shard-file counts match the elected-writer totals.
    def files(tag):
        return [
            f
            for dp, _, fs in os.walk(root)
            for f in fs
            if f"m/{tag}" in os.path.join(dp, f)
        ]

    assert len(files("full")) == world_size * local
    assert len(files("repl_proc")) == local
    assert len(files("repl_local")) == world_size
    # d) the replicated host array was chunk-striped: more than one chunk
    # file exists, all under replicated/.
    host_files = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(root)
        for f in fs
        if "m/host" in os.path.join(dp, f)
    ]
    assert len(host_files) == 4, host_files  # 8 rows / 2 rows per chunk
    assert all(f"{os.sep}replicated{os.sep}" in p for p in host_files)


def test_pod_2x4_matrix(tmp_path) -> None:
    """2 processes x 4 local devices: the v5p-host shape."""
    port = _find_free_port()
    root = str(tmp_path / "snap")
    results = run_with_subprocesses(
        _matrix_worker, 2, root, port, 4, timeout=300.0
    )
    _assert_matrix(results, 2, 4, root)


def test_pod_4x2_matrix(tmp_path) -> None:
    """4 processes x 2 local devices: wider world, smaller hosts."""
    port = _find_free_port()
    root = str(tmp_path / "snap")
    results = run_with_subprocesses(
        _matrix_worker, 4, root, port, 2, timeout=300.0
    )
    _assert_matrix(results, 4, 2, root)


def _digest_worker(rank, world_size, base, inc, port, local):
    """Device digests at pod shape: the take-side DtoH skip and the
    restore-side read skip when a process owns SEVERAL boxes (the
    windowed multi-piece verification path of
    ShardedArrayIOPreparer._dst_already_matches)."""
    from jax.sharding import PartitionSpec as P

    jax = _init_pod(rank, world_size, port, local)
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    mesh = _pod_mesh(jax, world_size, local)
    arr = _make_array(jax, mesh, P("proc", "local"))
    assert len(arr.addressable_shards) == local  # several boxes per proc
    Snapshot.take(base, {"m": StateDict(emb=arr)}, device_digests=True)

    # Unchanged resave from fresh buffers: nothing stages anywhere.
    staged = []
    orig = ArrayBufferStager._stage_and_sum
    ArrayBufferStager._stage_and_sum = (
        lambda self, a: staged.append(1) or orig(self, a)
    )
    try:
        arr2 = _make_array(jax, mesh, P("proc", "local"))
        Snapshot.take(
            inc,
            {"m": StateDict(emb=arr2)},
            incremental_base=base,
            device_digests=True,
        )
    finally:
        ArrayBufferStager._stage_and_sum = orig
    assert staged == [], f"rank {rank} staged {staged}"

    # Same-layout restore into matching content: every process verifies
    # its OWN `local` pieces on device and consumes nothing.
    consumed = []
    orig_c = _ShardScatterConsumer._consume_sync
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed.append(1) or orig_c(self, buf)
    )
    try:
        dst = StateDict(emb=_make_array(jax, mesh, P("proc", "local")))
        Snapshot(base).restore({"m": dst}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
    assert consumed == [], f"rank {rank} consumed {consumed}"
    _check_restored(dst["emb"])
    return "ok"


def test_pod_2x4_device_digests(tmp_path) -> None:
    port = _find_free_port()
    results = run_with_subprocesses(
        _digest_worker,
        2,
        str(tmp_path / "base"),
        str(tmp_path / "inc"),
        port,
        4,
        timeout=300.0,
    )
    assert all(v == "ok" for v in results.values())


def _async_failure_worker(rank, world_size, snap, port, local):
    """async_take at pod shape with one process's storage I/O failing:
    every process's wait() must raise and nothing may commit."""
    from jax.sharding import PartitionSpec as P

    jax = _init_pod(rank, world_size, port, local)
    from torchsnapshot_tpu import Snapshot, StateDict

    if rank == 1:
        from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

        async def boom(self, write_io):
            raise RuntimeError("injected write failure on rank 1")

        FSStoragePlugin.write = boom

    mesh = _pod_mesh(jax, world_size, local)
    arr = _make_array(jax, mesh, P("proc", "local"))
    # The injected failure can surface at async_take time (a write fails
    # while staging drains) or from wait() (the barrier propagates the
    # peer's error) — both are correct abort paths.
    try:
        pending = Snapshot.async_take(snap, {"m": StateDict(emb=arr)})
        pending.wait()
    except RuntimeError as e:
        msg = str(e)
        assert "injected write failure" in msg or "peer rank" in msg, msg
        return "aborted"
    return "NOT-ABORTED"


def test_pod_2x4_async_take_peer_failure(tmp_path) -> None:
    port = _find_free_port()
    snap = str(tmp_path / "snap")
    results = run_with_subprocesses(
        _async_failure_worker, 2, snap, port, 4, timeout=300.0
    )
    assert all(v == "aborted" for v in results.values()), results
    assert not os.path.exists(os.path.join(snap, ".snapshot_metadata"))


def _digest_cross_layout_worker(rank, world_size, base, port, local):
    """Device-digest restore skips ACROSS A LAYOUT CHANGE in a real
    multi-process world: saved under P('proc','local') (block pieces),
    restored into P(('proc','local'), None) — rows sharded over BOTH
    axes, full width. No destination box contains a saved piece (the
    finer row split cuts every piece), but the union of each process's
    boxes covers the pieces it overlaps, so the assembly path
    (sharded._make_assembler) stitches + verifies on device and no
    reads are planned. A mutated destination must still re-read — on
    the rank whose region went stale; the other rank's local handle is
    unchanged and stays skipped (per-rank locality).

    The DISTRIBUTED verification pass (summed partial lanes; its own
    test below) would verify this layout first and short-circuit the
    local paths; it is disabled here so this test keeps pinning the
    rank-local assembly machinery, which remains the fallback for
    non-collective read paths and failed exchanges."""
    from jax.sharding import PartitionSpec as P

    jax = _init_pod(rank, world_size, port, local)
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    Snapshot._distributed_preverify = (
        lambda self, flattened, available, pg_wrapper: set()
    )

    mesh = _pod_mesh(jax, world_size, local)
    arr = _make_array(jax, mesh, P("proc", "local"))
    Snapshot.take(base, {"m": StateDict(emb=arr)}, device_digests=True)

    dst_spec = P(("proc", "local"), None)
    consumed = []
    assembled = []
    orig_c = _ShardScatterConsumer._consume_sync
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed.append(1) or orig_c(self, buf)
    )
    from torchsnapshot_tpu.io_preparers import sharded as sharded_mod

    orig_asm = sharded_mod._make_assembler
    sharded_mod._make_assembler = (
        lambda *a, **k: assembled.append(1) or orig_asm(*a, **k)
    )
    try:
        dst = StateDict(emb=_make_array(jax, mesh, dst_spec))
        Snapshot(base).restore({"m": dst}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
        sharded_mod._make_assembler = orig_asm
    assert consumed == [], f"rank {rank} consumed {consumed}"
    # Genuinely a different layout: the skip came from the ASSEMBLY path
    # (dest boxes are 2 rows x full width; pieces 4 rows x 4 cols, so no
    # containment was possible).
    assert assembled, f"rank {rank}: assembly path never used"
    _check_restored(dst["emb"])

    # A stale cell at [0,0] lives in rank 0's region under BOTH layouts:
    # rank 0 must re-read its overlapping piece(s); rank 1's handle is
    # unchanged and plans nothing.
    from jax.sharding import NamedSharding

    stale_host = _global_data()
    stale_host[0, 0] += 5.0
    stale = jax.make_array_from_callback(
        SHAPE,
        NamedSharding(mesh, dst_spec),
        lambda idx: stale_host[idx],
    )
    consumed2 = []
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed2.append(1) or orig_c(self, buf)
    )
    try:
        dst2 = StateDict(emb=stale)
        Snapshot(base).restore({"m": dst2}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
    if rank == 0:
        assert consumed2, "rank 0: stale destination planned no reads"
    else:
        assert consumed2 == [], f"rank {rank} re-read unchanged data"
    _check_restored(dst2["emb"])
    return "ok"


def test_pod_2x2_device_digest_cross_layout(tmp_path) -> None:
    """VERDICT r4 item 7: a 2-proc restore with a DIFFERENT sharding
    still skips reads when the destination already holds the content."""
    port = _find_free_port()
    results = run_with_subprocesses(
        _digest_cross_layout_worker,
        2,
        str(tmp_path / "base"),
        port,
        2,
        timeout=300.0,
    )
    assert all(v == "ok" for v in results.values())


def _digest_cross_process_worker(rank, world_size, base, port, local):
    """Distributed digest verification: the destination layout cuts every
    saved piece ACROSS PROCESS BOUNDARIES, so no process can verify any
    piece alone (containment and union assembly both impossible). The
    ranks exchange 16-byte partial fingerprint lanes over the
    coordination plane (snapshot._distributed_preverify) and skip every
    read with zero payload bytes moved. A single-cell mutation on ONE
    rank's region must fail the piece's summed lanes and re-read it on
    the ranks that hold its regions."""
    from jax.sharding import PartitionSpec as P

    jax = _init_pod(rank, world_size, port, local)
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    mesh = _pod_mesh(jax, world_size, local)
    # Saved: column pieces replicated over procs -> pieces span ALL rows.
    arr = _make_array(jax, mesh, P(None, "local"))
    Snapshot.take(base, {"m": StateDict(emb=arr)}, device_digests=True)

    # Destination: row boxes across procs, full width -> every saved
    # column piece intersects EVERY process's boxes; no box contains a
    # piece and no process's union covers one.
    dst_spec = P("proc", None)
    consumed = []
    orig_c = _ShardScatterConsumer._consume_sync
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed.append(1) or orig_c(self, buf)
    )
    try:
        dst = StateDict(emb=_make_array(jax, mesh, dst_spec))
        Snapshot(base).restore({"m": dst}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
    assert consumed == [], f"rank {rank} consumed {consumed}"
    _check_restored(dst["emb"])

    # Stale cell at [0, 0] (inside rank 0's region of the first column
    # piece): that piece's summed lanes mismatch, the whole entry's
    # verdict fails (verdicts are whole-entry, like every other skip
    # path — a partially-skipped scatter would leave unread regions of
    # the rebuild buffers uninitialized), and every rank re-reads the
    # pieces overlapping its boxes: both column pieces per rank here.
    from jax.sharding import NamedSharding

    stale_host = _global_data()
    stale_host[0, 0] += 9.0
    stale = jax.make_array_from_callback(
        SHAPE, NamedSharding(mesh, dst_spec), lambda idx: stale_host[idx]
    )
    consumed2 = []
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed2.append(1) or orig_c(self, buf)
    )
    try:
        dst2 = StateDict(emb=stale)
        Snapshot(base).restore({"m": dst2}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
    assert len(consumed2) == 2, f"rank {rank} consumed {len(consumed2)} pieces"
    _check_restored(dst2["emb"])

    # The corrected destination verifies again on the next reload: the
    # distributed pass plans zero reads (the serving hot-reload steady
    # state for pieces cut across processes).
    consumed3 = []
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed3.append(1) or orig_c(self, buf)
    )
    try:
        dst3 = StateDict(emb=dst2["emb"])
        Snapshot(base).restore({"m": dst3}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
    assert consumed3 == [], f"rank {rank} consumed {consumed3}"
    return "ok"


def test_pod_2x2_distributed_digest_verification(tmp_path) -> None:
    """Pieces cut across process boundaries verify via summed partial
    lanes — zero payload movement — instead of falling back to reads."""
    port = _find_free_port()
    results = run_with_subprocesses(
        _digest_cross_process_worker,
        2,
        str(tmp_path / "base"),
        port,
        2,
        timeout=300.0,
    )
    assert all(v == "ok" for v in results.values())


def test_pod_4x2_distributed_digest_verification(tmp_path) -> None:
    """Four contributors per piece: a wider world where every saved
    piece's verification sums partial lanes from ALL FOUR processes."""
    port = _find_free_port()
    results = run_with_subprocesses(
        _digest_cross_process_worker,
        4,
        str(tmp_path / "base"),
        port,
        2,
        timeout=300.0,
    )
    assert all(v == "ok" for v in results.values())
