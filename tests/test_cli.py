"""CLI: info/ls/cat/verify/migrate over real snapshots.

The reference has no CLI analogue; these commands wrap the manifest,
read_object, integrity, and interop layers, so the tests double as
integration coverage for those seams.
"""

import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.cli import main

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "reference_snapshot")


@pytest.fixture()
def snap_path(tmp_path):
    sd = StateDict(
        step=5,
        weights=np.arange(24, dtype=np.float32).reshape(4, 6),
        nested={"b": np.ones(3, dtype=np.int64)},
        note="hello",
    )
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": sd})
    return path


def test_info(snap_path, capsys):
    assert main(["info", snap_path]) == 0
    out = capsys.readouterr().out
    assert "world_size:  1" in out
    assert "array" in out and "primitive" in out
    assert "checksums:" in out


def test_ls_filters_and_sizes(snap_path, capsys):
    assert main(["ls", snap_path]) == 0
    out = capsys.readouterr().out
    assert "0/app/weights" in out and "float32[4, 6]" in out
    assert "96" in out  # 24 * 4 bytes
    # containers hidden by default, shown with --all
    assert "0/app/nested " not in out
    assert main(["ls", snap_path, "--all"]) == 0
    assert "dict" in capsys.readouterr().out


def test_cat_array_and_primitive(snap_path, capsys):
    assert main(["cat", snap_path, "0/app/weights"]) == 0
    out = capsys.readouterr().out
    assert "float32[4, 6]" in out
    assert main(["cat", snap_path, "0/app/note"]) == 0
    assert "hello" in capsys.readouterr().out


def test_verify_clean_and_corrupted(snap_path, capsys):
    assert main(["verify", snap_path]) == 0
    out = capsys.readouterr().out
    assert ", 0 failed" in out

    # Flip one byte of a payload: verify must fail with nonzero exit.
    target = None
    for root, _, files in os.walk(snap_path):
        for f in files:
            if f != ".snapshot_metadata" and "weights" in f:
                target = os.path.join(root, f)
    assert target is not None
    blob = bytearray(open(target, "rb").read())
    blob[0] ^= 0xFF
    open(target, "wb").write(bytes(blob))

    assert main(["verify", snap_path]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out


def test_missing_payload_fails_verify(snap_path, capsys):
    target = None
    for root, _, files in os.walk(snap_path):
        for f in files:
            if "weights" in f:
                target = os.path.join(root, f)
    os.remove(target)
    assert main(["verify", snap_path]) == 1
    assert "FAIL" in capsys.readouterr().out


def test_migrate_reference_fixture(tmp_path, capsys):
    dst = str(tmp_path / "native")
    assert main(["migrate", FIXTURE, dst]) == 0
    assert "migrated" in capsys.readouterr().out
    v = Snapshot(dst).read_object("0/app/weights")
    np.testing.assert_array_equal(
        np.asarray(v), np.arange(48, dtype=np.float32).reshape(6, 8)
    )
    # native snapshots refuse re-migration
    assert main(["migrate", dst, str(tmp_path / "x")]) == 1


def test_error_path_returns_2(tmp_path, capsys):
    assert main(["info", str(tmp_path / "nope")]) == 2
    assert "error:" in capsys.readouterr().err


def test_diff_reports_changed_added_removed(tmp_path, capsys):
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    Snapshot.take(
        a,
        {"app": StateDict(same=np.ones(4, np.float32),
                          changed=np.zeros(3, np.float32),
                          gone=7)},
        record_digests=True,
    )
    Snapshot.take(
        b,
        {"app": StateDict(same=np.ones(4, np.float32),
                          changed=np.full((3,), 5.0, np.float32),
                          added="new")},
        record_digests=True,
    )
    assert main(["diff", a, b]) == 1  # differences found
    out = capsys.readouterr().out
    assert "+ 0/app/added" in out
    assert "- 0/app/gone" in out
    assert "~ 0/app/changed" in out
    assert "1 added, 1 removed, 1 changed, 1 unchanged" in out

    # identical snapshots diff clean (exit 0)
    c = str(tmp_path / "c")
    Snapshot.take(c, {"app": StateDict(same=np.ones(4, np.float32))},
                  record_digests=True)
    d = str(tmp_path / "d")
    Snapshot.take(d, {"app": StateDict(same=np.ones(4, np.float32))},
                  record_digests=True)
    assert main(["diff", c, d]) == 0
    assert "0 changed, 1 unchanged" in capsys.readouterr().out


def test_diff_without_digests_uses_checksums(tmp_path, capsys):
    # checksums are on by default, so equality is still exact
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    Snapshot.take(a, {"app": StateDict(w=np.ones(4, np.float32))})
    Snapshot.take(b, {"app": StateDict(w=np.ones(4, np.float32))})
    assert main(["diff", a, b]) == 0
    assert "1 unchanged" in capsys.readouterr().out


def test_diff_across_evidence_tiers(tmp_path, capsys):
    """One side has digests, the other only checksums: the comparison
    degrades to the tier both sides share instead of calling identical
    content changed."""
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    Snapshot.take(
        a, {"app": StateDict(w=np.ones(4, np.float32), step=7)},
        record_digests=True,
    )
    Snapshot.take(b, {"app": StateDict(w=np.ones(4, np.float32), step=7)})
    assert main(["diff", a, b]) == 0
    out = capsys.readouterr().out
    # primitive equality counts as unchanged, not indeterminate
    assert "0 changed, 2 unchanged" in out and "indeterminate" not in out


def test_diff_indeterminate_without_any_evidence(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_CHECKSUM", "0")
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    Snapshot.take(a, {"app": StateDict(w=np.ones(4, np.float32))})
    Snapshot.take(b, {"app": StateDict(w=np.ones(4, np.float32))})
    assert main(["diff", a, b]) == 0  # no *proven* differences
    assert "1 indeterminate" in capsys.readouterr().out


def test_deps_graph_and_safe_to_delete(tmp_path, capsys):
    base = str(tmp_path / "step_0")
    inc = str(tmp_path / "step_1")
    solo = str(tmp_path / "solo")
    Snapshot.take(base, {"app": StateDict(w=np.ones(16, np.float32))},
                  record_digests=True)
    Snapshot.take(inc, {"app": StateDict(w=np.ones(16, np.float32))},
                  incremental_base=base)
    Snapshot.take(solo, {"app": StateDict(v=np.zeros(4, np.float32))})

    assert main(["deps", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "step_0 [REQUIRED by step_1]" in out
    assert "step_1 <- bases: step_0" in out
    assert "safe to delete" in out
    safe_line = [l for l in out.splitlines() if l.startswith("safe to delete")][0]
    assert "step_1" in safe_line and "solo" in safe_line
    assert "step_0" not in safe_line


def test_deps_with_relative_base_recorded(tmp_path, capsys, monkeypatch):
    """A base given as a RELATIVE path at take time must still be matched
    when deps runs from a different working directory — origins are
    canonicalized at record time, so a false 'safe to delete' (data loss)
    can't happen."""
    monkeypatch.chdir(tmp_path)
    Snapshot.take("step_0", {"app": StateDict(w=np.ones(8, np.float32))},
                  record_digests=True)
    Snapshot.take("step_1", {"app": StateDict(w=np.ones(8, np.float32))},
                  incremental_base="step_0")
    monkeypatch.chdir("/")
    assert main(["deps", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "step_0 [REQUIRED by step_1]" in out
    safe_line = [l for l in out.splitlines() if l.startswith("safe to delete")][0]
    assert "step_0" not in safe_line


def test_prune_keeps_required_bases(tmp_path, capsys):
    import time

    def take(name, base=None):
        p = str(tmp_path / name)
        Snapshot.take(p, {"app": StateDict(w=np.ones(16, np.float32))},
                      incremental_base=base, record_digests=True)
        time.sleep(0.02)  # distinct mtimes for retention ordering
        return p

    s0 = take("step_0")
    take("step_1", base=s0)
    take("step_2", base=s0)
    take("step_3")  # independent full snapshot, the newest

    # keep newest 2 (step_2, step_3); step_0 is required by step_2
    assert main(["prune", str(tmp_path), "--keep", "2"]) == 0
    out = capsys.readouterr().out
    assert "keep    step_2" in out and "keep    step_3" in out
    assert "keep    step_0  (base of a kept snapshot)" in out
    assert "delete  step_1" in out
    assert "dry run" in out
    assert (tmp_path / "step_1").exists()  # dry run deletes nothing

    assert main(["prune", str(tmp_path), "--keep", "2", "--yes"]) == 0
    capsys.readouterr()
    assert not (tmp_path / "step_1").exists()
    for name in ("step_0", "step_2", "step_3"):
        assert (tmp_path / name).exists()

    # the surviving incremental still restores through its kept base
    dst = StateDict(w=np.zeros(16, np.float32))
    Snapshot(str(tmp_path / "step_2")).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], np.ones(16, np.float32))

    # pruning again: nothing eligible
    assert main(["prune", str(tmp_path), "--keep", "3"]) == 0
    assert "nothing to prune" in capsys.readouterr().out


def test_prune_required_set_is_transitive(tmp_path, capsys):
    """A spared base's OWN bases must survive: s2 borrows X from s1, s1
    borrows Y from s0 — keeping only s2 must spare both s1 and s0, or the
    'kept' s1 (and s2's own restore of Y via s1? no — via s0 directly)
    would dangle."""
    import time

    def take(name, x, y, base=None):
        p = str(tmp_path / name)
        Snapshot.take(
            p,
            {"app": StateDict(x=np.full((8,), float(x), np.float32),
                              y=np.full((8,), float(y), np.float32))},
            incremental_base=base, record_digests=True,
        )
        time.sleep(0.02)
        return p

    s0 = take("s0", x=1, y=1)
    s1 = take("s1", x=2, y=1, base=s0)  # holds X, borrows Y from s0
    take("s2", x=2, y=2, base=s1)       # borrows X from s1, holds Y

    assert main(["prune", str(tmp_path), "--keep", "1", "--yes"]) == 0
    capsys.readouterr()
    for name in ("s0", "s1", "s2"):
        assert (tmp_path / name).exists(), name

    # everything still restores
    for name, (ex, ey) in (("s1", (2, 1)), ("s2", (2, 2))):
        dst = StateDict(x=np.zeros(8, np.float32), y=np.zeros(8, np.float32))
        Snapshot(str(tmp_path / name)).restore({"app": dst})
        np.testing.assert_array_equal(dst["x"], np.full((8,), float(ex), np.float32))
        np.testing.assert_array_equal(dst["y"], np.full((8,), float(ey), np.float32))


def test_prune_spares_bases_by_name_after_tree_move(tmp_path, capsys):
    """Origins record absolute realpaths at take time. If the checkpoint
    tree is moved (or scanned via a different mount path), those paths
    resolve to nothing — prune must fall back to basename matching
    instead of deleting the base of a kept incremental."""
    import shutil
    import time

    src = tmp_path / "ckpts"
    src.mkdir()
    Snapshot.take(str(src / "step_0"),
                  {"app": StateDict(w=np.ones(16, np.float32))},
                  record_digests=True)
    time.sleep(0.02)
    Snapshot.take(str(src / "step_1"),
                  {"app": StateDict(w=np.ones(16, np.float32))},
                  incremental_base=str(src / "step_0"))
    time.sleep(0.02)
    Snapshot.take(str(src / "step_2"), {"app": StateDict(w=np.ones(16, np.float32))})

    moved = tmp_path / "ckpts_moved"
    shutil.move(str(src), str(moved))

    # keep newest 2 (step_1, step_2): step_0 must be spared via basename
    assert main(["prune", str(moved), "--keep", "2", "--yes"]) == 0
    out = capsys.readouterr().out
    assert "keep    step_0  (base of a kept snapshot, matched by name)" in out
    assert (moved / "step_0").exists()


def test_prune_name_match_requires_payload_identity(tmp_path, capsys):
    """A same-named but UNRELATED snapshot must not satisfy the basename
    fallback: the true base was renamed (origins still record its old
    path), and an unrelated snapshot now occupies the old name. Sparing
    the impostor would also suppress the unresolved-base refusal while
    the real base is rmtree'd — the fallback must verify the candidate
    actually holds the referenced payload files."""
    import time

    Snapshot.take(str(tmp_path / "step_0"),
                  {"app": StateDict(w=np.ones(16, np.float32))},
                  record_digests=True)
    time.sleep(0.02)
    Snapshot.take(str(tmp_path / "step_1"),
                  {"app": StateDict(w=np.ones(16, np.float32))},
                  incremental_base=str(tmp_path / "step_0"))
    time.sleep(0.02)
    (tmp_path / "step_0").rename(tmp_path / "step_0_renamed")
    # unrelated snapshot under the base's old name — SAME model, same tree
    # shape and sizes, different values (the hard case: file-existence or
    # size checks would accept it); backdated so retention keeps
    # (step_1, step_2), not the impostor
    Snapshot.take(str(tmp_path / "step_0"),
                  {"app": StateDict(w=np.full(16, 7.0, np.float32))})
    import os as _os
    meta = tmp_path / "step_0" / ".snapshot_metadata"
    st = _os.stat(str(tmp_path / "step_0_renamed" / ".snapshot_metadata"))
    _os.utime(str(meta), (st.st_atime, st.st_mtime - 1))
    time.sleep(0.02)
    Snapshot.take(str(tmp_path / "step_2"), {"app": StateDict(w=np.ones(16, np.float32))})

    # keep newest 2 (step_0 impostor is older than step_1? ensure keep
    # covers step_1 and step_2): the impostor must NOT be spared by name,
    # the origin is unresolved, and --yes refuses.
    assert main(["prune", str(tmp_path), "--keep", "2", "--yes"]) == 2
    captured = capsys.readouterr()
    assert "refusing --yes" in captured.err
    assert "matched by name" not in captured.out
    assert (tmp_path / "step_0_renamed").exists()


def test_prune_refuses_yes_on_unresolved_bases(tmp_path, capsys):
    """A kept snapshot whose base resolves to nothing in the scanned
    directory (and matches no name) makes `prune --yes` refuse: prune
    cannot prove the doomed snapshots aren't that base under another
    name. `--ignore-missing-bases` overrides."""
    import time

    external = tmp_path / "elsewhere" / "base"
    Snapshot.take(str(external), {"app": StateDict(w=np.ones(16, np.float32))},
                  record_digests=True)
    scanned = tmp_path / "ckpts"
    Snapshot.take(str(scanned / "old"), {"app": StateDict(w=np.zeros(16, np.float32))})
    time.sleep(0.02)
    Snapshot.take(str(scanned / "new"), {"app": StateDict(w=np.ones(16, np.float32))},
                  incremental_base=str(external))

    # dry run: plan prints, loud warning on stderr, rc 0
    assert main(["prune", str(scanned), "--keep", "1"]) == 0
    captured = capsys.readouterr()
    assert "delete  old" in captured.out
    assert "resolve to no snapshot in this directory" in captured.err

    # --yes refuses; nothing deleted
    assert main(["prune", str(scanned), "--keep", "1", "--yes"]) == 2
    captured = capsys.readouterr()
    assert "refusing --yes" in captured.err
    assert (scanned / "old").exists()

    # explicit override deletes
    assert main(["prune", str(scanned), "--keep", "1", "--yes",
                 "--ignore-missing-bases"]) == 0
    capsys.readouterr()
    assert not (scanned / "old").exists()
    assert (scanned / "new").exists()


def test_prune_rejects_remote_and_bad_args(tmp_path, capsys):
    assert main(["prune", "gs://bucket/x", "--keep", "1"]) == 2
    Snapshot.take(str(tmp_path / "s"), {"app": StateDict(n=1)})
    assert main(["prune", str(tmp_path), "--keep", "0"]) == 2


def test_looks_native_handles_type_name_collisions():
    from torchsnapshot_tpu.cli import _looks_native

    # Tensor-free reference snapshot: only objects + containers. The
    # container/object type names collide with native ones; the torch_save
    # serializer is the discriminator.
    ref = {
        "0/app": {"type": "dict", "keys": ["o"]},
        "0/app/o": {"type": "object", "location": "0/app/o",
                    "serializer": "torch_save", "obj_type": "builtins.tuple",
                    "replicated": False},
    }
    assert not _looks_native(ref)
    ref_prim = {"0/app/x": {"type": "int", "serialized_value": "3",
                            "readable": None, "replicated": False}}
    assert not _looks_native(ref_prim)
    native = {
        "0/app": {"type": "dict", "keys": ["o"]},
        "0/app/o": {"type": "object", "location": "0/app/o",
                    "serializer": "pickle", "obj_type": "builtins.tuple",
                    "replicated": False},
    }
    assert _looks_native(native)


def test_info_dedups_replicated_payloads(tmp_path, capsys):
    """A replicated entry appears under every rank prefix but shares one
    payload on disk; info must count its bytes once, not world_size times."""
    import yaml as _yaml

    root = tmp_path / "snap"
    root.mkdir()
    arr_entry = {
        "type": "array",
        "location": "replicated/app/w",
        "serializer": "buffer_protocol",
        "dtype": "float32",
        "shape": [8],
        "replicated": True,
        "byte_range": None,
        "checksum": None,
    }
    meta = {
        "version": "0.1.0",
        "world_size": 2,
        "manifest": {"0/app/w": dict(arr_entry), "1/app/w": dict(arr_entry)},
    }
    (root / ".snapshot_metadata").write_text(_yaml.safe_dump(meta, sort_keys=False))
    assert main(["info", str(root)]) == 0
    out = capsys.readouterr().out
    assert "payload:     32B" in out  # 8 * 4 bytes, once
    assert "checksums:   0/1 payloads" in out


def test_plan_dry_run(tmp_path, capsys):
    """``plan`` reports the planner's byte accounting for a layout
    change from manifest geometry alone — here the row->column
    cross-cut where direct restore reads every shard on every rank."""
    jax = pytest.importorskip("jax")
    import json

    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))
    vals = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    arr = jax.make_array_from_callback(
        vals.shape, NamedSharding(mesh, P("x", None)), lambda i: vals[i]
    )
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(w=arr, step=3)})
    layout = str(tmp_path / "dst.json")
    with open(layout, "w") as f:
        json.dump(
            {
                "version": 1,
                "mesh": [["x", 4]],
                "rules": [{"pattern": "app/w$", "spec": [[], ["x"]]}],
            },
            f,
        )

    assert main(["plan", path, layout, "--world", "4"]) == 0
    out = capsys.readouterr().out
    assert "app/w" in out
    assert "4.0x reduction" in out

    assert main(["plan", path, layout, "--world", "4", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    totals = doc["totals"]
    assert totals["planned_units"] == totals["shards"] == len(jax.devices())
    assert (
        totals["direct_bytes_from_storage"]
        == 4 * totals["planned_bytes_from_storage"]
    )
    assert totals["planned_peer_bytes"] > 0

    # Sub-threshold worlds leave every shard on direct reads.
    assert main(
        ["plan", path, layout, "--world", "4", "--min-requesters", "9"]
    ) == 0
    assert "0/8 unit(s) claimed" in capsys.readouterr().out

    # An unreadable destination layout is exit 2, not a traceback.
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("{")
    assert main(["plan", path, bad, "--world", "4"]) == 2
