"""Multi-process snapshot semantics: replication, striping, elasticity
(reference: tests/test_ddp.py, tests/test_replication_glob.py,
tests/test_partition_replicated_paths.py)."""

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]


def _replicated_take_worker(rank: int, world_size: int, snap_path: str):
    from torchsnapshot_tpu import Snapshot, StateDict

    # identical ("replicated") params on every rank + per-rank state
    params = {
        "w1": np.arange(4096, dtype=np.float32).reshape(64, 64),
        "w2": np.ones((32, 32), dtype=np.float32) * 7,
    }
    app_state = {
        "model": StateDict(**params),
        "local": StateDict(rank_data=np.full((8,), rank, dtype=np.int32), step=rank),
    }
    snapshot = Snapshot.take(snap_path, app_state, replicated=["model/*"])
    manifest = snapshot.get_manifest()

    # every rank's manifest view carries the replicated entries
    assert f"{rank}/model/w1" in manifest
    entry = manifest[f"{rank}/model/w1"]
    assert entry.replicated
    return sorted(
        os.path.relpath(os.path.join(dp, f), snap_path)
        for dp, _, fs in os.walk(snap_path)
        for f in fs
    )


def _replicated_restore_worker(rank: int, world_size: int, snap_path: str):
    from torchsnapshot_tpu import Snapshot, StateDict

    snapshot = Snapshot(snap_path)
    dst = StateDict(
        w1=np.zeros((64, 64), dtype=np.float32),
        w2=np.zeros((32, 32), dtype=np.float32),
    )
    local_dst = StateDict(rank_data=np.zeros((8,), dtype=np.int32), step=-1)
    snapshot.restore({"model": dst, "local": local_dst})
    np.testing.assert_array_equal(
        dst["w1"], np.arange(4096, dtype=np.float32).reshape(64, 64)
    )
    np.testing.assert_array_equal(dst["w2"], np.ones((32, 32), dtype=np.float32) * 7)
    np.testing.assert_array_equal(
        local_dst["rank_data"], np.full((8,), rank, dtype=np.int32)
    )
    assert local_dst["step"] == rank
    return "ok"


@pytest.mark.parametrize("world_size", [2, 4])
def test_replicated_save_restore(tmp_path, world_size: int) -> None:
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(_replicated_take_worker, world_size, snap_path)

    # Replicated data written exactly once (under replicated/), striped:
    # every rank saw the same file set, and each replicated array appears once.
    file_sets = list(results.values())
    assert all(fs == file_sets[0] for fs in file_sets)
    files = file_sets[0]
    repl_files = [f for f in files if f.startswith("replicated/")]
    assert any("model/w1" in f for f in repl_files)
    assert any("model/w2" in f for f in repl_files)
    # per-rank entries present for every rank
    for r in range(world_size):
        assert any(f.startswith(f"{r}/local/rank_data") for f in files)

    results = run_with_subprocesses(
        _replicated_restore_worker, world_size, snap_path
    )
    assert all(v == "ok" for v in results.values())


def _elastic_take_worker(rank: int, world_size: int, snap_path: str):
    from torchsnapshot_tpu import Snapshot, StateDict

    app_state = {
        "model": StateDict(w=np.arange(100, dtype=np.float64)),
        "local": StateDict(step=rank * 10),
    }
    Snapshot.take(snap_path, app_state, replicated=["model/*"])
    return "ok"


def _elastic_restore_worker(rank: int, world_size: int, snap_path: str):
    from torchsnapshot_tpu import Snapshot, StateDict

    snapshot = Snapshot(snap_path)
    dst = StateDict(w=np.zeros(100, dtype=np.float64))
    snapshot.restore({"model": dst})
    np.testing.assert_array_equal(dst["w"], np.arange(100, dtype=np.float64))

    # per-rank entries only restorable by their original ranks
    local_dst = StateDict(step=-1)
    if rank < 2:
        snapshot.restore({"local": local_dst})
        assert local_dst["step"] == rank * 10
        return "restored-local"
    else:
        try:
            snapshot.restore({"local": local_dst})
            return "unexpected-success"
        except RuntimeError as e:
            assert "Unable to find entry" in str(e)
            return "got-elasticity-error"


def test_elasticity_world_size_change(tmp_path) -> None:
    """Save with world=2, restore with world=4: replicated entries restore
    everywhere; per-rank entries error helpfully on new ranks
    (reference: snapshot.py:112-155, 707-725)."""
    snap_path = str(tmp_path / "snap")
    run_with_subprocesses(_elastic_take_worker, 2, snap_path)
    results = run_with_subprocesses(_elastic_restore_worker, 4, snap_path)
    assert results[0] == "restored-local"
    assert results[1] == "restored-local"
    assert results[2] == "got-elasticity-error"
    assert results[3] == "got-elasticity-error"


def test_shrink_world_size(tmp_path) -> None:
    """Save with world=4, restore with world=1 (single process)."""
    snap_path = str(tmp_path / "snap")
    run_with_subprocesses(_elastic_take_worker, 4, snap_path)

    from torchsnapshot_tpu import Snapshot, StateDict

    snapshot = Snapshot(snap_path)
    dst = StateDict(w=np.zeros(100, dtype=np.float64))
    snapshot.restore({"model": dst})
    np.testing.assert_array_equal(dst["w"], np.arange(100, dtype=np.float64))
    # rank 0 can also restore its own per-rank entry
    local_dst = StateDict(step=-1)
    snapshot.restore({"local": local_dst})
    assert local_dst["step"] == 0


def _striping_worker(rank: int, world_size: int, snap_path: str):
    """Force small chunks so the replicated array stripes across ranks."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers import chunked

    old = chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES
    chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = 1024  # 4 rows of 64 floats
    try:
        arr = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
        snapshot = Snapshot.take(
            snap_path, {"model": StateDict(big=arr)}, replicated=["model/*"]
        )
    finally:
        chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = old
    entry = snapshot.get_manifest()[f"{rank}/model/big"]
    return [tuple(c.offsets) for c in entry.chunks]


def test_replicated_chunk_striping(tmp_path) -> None:
    """The chunk set is identical in every rank's manifest entry, while the
    bytes are written cooperatively (greedy striping — the manifest records
    all chunks, each rank writes a disjoint subset)."""
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(_striping_worker, 2, snap_path)
    assert results[0] == results[1]
    assert len(results[0]) == 16  # 64 rows / 4 rows-per-chunk

    # all chunk files exist exactly once under replicated/
    files = [
        f
        for dp, _, fs in os.walk(snap_path)
        for f in fs
        if "model/big" in os.path.join(dp, f)
    ]
    assert len(files) == 16


def _glob_mismatch_worker(rank: int, world_size: int, snap_path: str):
    """Ranks claim different globs -> only the verified intersection is
    replicated (reference: tests/test_replication_glob.py:104-113)."""
    from torchsnapshot_tpu import Snapshot, StateDict

    app_state = {
        "m": StateDict(
            a=np.ones(10, dtype=np.float32),
            b=np.ones(10, dtype=np.float32) * 2,
        )
    }
    globs = ["m/a", "m/b"] if rank == 0 else ["m/a"]
    snapshot = Snapshot.take(snap_path, app_state, replicated=globs)
    manifest = snapshot.get_manifest()
    return {
        "a_replicated": manifest[f"{rank}/m/a"].replicated,
        "b_replicated": manifest[f"{rank}/m/b"].replicated,
    }


def test_replication_glob_negotiation(tmp_path) -> None:
    results = run_with_subprocesses(_glob_mismatch_worker, 2, str(tmp_path / "s"))
    for r in results.values():
        assert r["a_replicated"] is True
        assert r["b_replicated"] is False


def _materialize_failure_worker(rank: int, world_size: int, snap_path: str):
    """Rank 1's state_dict() raises during take: every rank must abort (no
    deadlock on the per-key lockstep barriers, no metadata commit)."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME

    class ExplodingStateful:
        def state_dict(self):
            if rank == 1:
                raise RuntimeError("injected state_dict failure")
            return {"w": np.ones(8, dtype=np.float32)}

        def load_state_dict(self, sd):
            pass

    app_state = {
        "ok": StateDict(x=np.zeros(4, dtype=np.float32)),
        "boom": ExplodingStateful(),
    }
    try:
        Snapshot.take(snap_path, app_state)
        return "unexpected-success"
    except RuntimeError:
        assert not os.path.exists(os.path.join(snap_path, SNAPSHOT_METADATA_FNAME))
        return "aborted"


def test_state_dict_failure_aborts_all_ranks(tmp_path) -> None:
    results = run_with_subprocesses(
        _materialize_failure_worker, 2, str(tmp_path / "snap"), timeout=120.0
    )
    assert all(v == "aborted" for v in results.values())


def _sequential_snapshots_worker(rank: int, world_size: int, base_path: str):
    """50 sequential snapshots must not grow the KV store unboundedly
    (PGWrapper retire/GC protocol)."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    store = get_default_pg().store
    app_state = {
        "model": StateDict(w=np.ones((16, 16), dtype=np.float32)),
        "local": StateDict(step=rank),
    }
    counts = []
    for i in range(50):
        Snapshot.take(f"{base_path}/snap_{i}", app_state)
        counts.append(store.num_keys())
    assert counts[-1] < 60, f"store grew unbounded: tail={counts[-10:]}"
    return counts[-1]


def test_sequential_snapshots_store_bounded(tmp_path) -> None:
    results = run_with_subprocesses(
        _sequential_snapshots_worker, 2, str(tmp_path), timeout=300.0
    )
    assert all(v < 60 for v in results.values())
