"""Replicated coordination store: leased leader failover, idempotent
replay, epoch fencing, connect retries, and the store-status surface.

All in-process (threads): a leader ``_StoreServer``, standbys via
``host_standby``, and real TCP clients — short leases so a failover
completes in well under a second. The multi-process drills live in
tests/test_store_spof.py (no-replica bounded aborts) and
tests/test_chaos_matrix.py (store-host SIGKILL mid-take).
"""

from __future__ import annotations

import pickle
import socket
import threading
import time

import pytest

from torchsnapshot_tpu import faultinject, telemetry
from torchsnapshot_tpu.dist_store import (
    LinearBarrier,
    StoreConnectionLostError,
    TCPStore,
    _DeposedError,
    _recv_msg,
    _send_msg,
    host_standby,
    probe_store_status,
)

LEASE = 0.4


@pytest.fixture
def replicated():
    """(leader_store, standby_server, client): one standby joined, the
    client's replica cache primed."""
    leader = TCPStore(
        "127.0.0.1", is_server=True, timeout=15.0, lease_s=LEASE,
        expected_replicas=1,
    )
    standby = host_standby(leader.addr, lease_s=LEASE)
    client = TCPStore("127.0.0.1", leader.port, timeout=15.0)
    # Prime the replica cache (the rsv piggyback needs one response).
    client.set("__prime__", b"1")
    deadline = time.monotonic() + 5
    while not client.replica_addrs and time.monotonic() < deadline:
        client.check("__prime__")
        time.sleep(0.02)
    assert client.replica_addrs, "client never learned the replica set"
    yield leader, standby, client
    client.close()
    standby.close()
    leader.close()


def _kill_leader(leader: TCPStore) -> None:
    """SIGKILL-equivalent for an in-process leader: close every socket."""
    leader._server.close()


def _wait_promoted(standby, timeout=10.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if standby._role == "leader":
            return
        time.sleep(0.02)
    raise AssertionError("standby never assumed leadership")


# ----------------------------------------------------------- idempotency


def _raw_client(store: TCPStore) -> socket.socket:
    sock = socket.create_connection(("127.0.0.1", store.port), timeout=5.0)
    sock.settimeout(5.0)
    return sock


def _roundtrip(sock, req):
    _send_msg(sock, req)
    return _recv_msg(sock)


def test_duplicate_mutating_ops_apply_exactly_once():
    """Every mutating op replayed with the same (client_id, seq) — the
    post-failover replay shape — applies exactly once and answers the
    CACHED response."""
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    sock = _raw_client(store)
    try:
        # add: the op whose double-apply is visible arithmetically.
        r1 = _roundtrip(sock, {"op": "add", "key": "ctr", "amount": 5,
                               "cid": "c1", "cseq": 1})
        r2 = _roundtrip(sock, {"op": "add", "key": "ctr", "amount": 5,
                               "cid": "c1", "cseq": 1})
        assert r1["value"] == 5 and r2["value"] == 5
        assert store.get("ctr") == b"5"

        # set replay is a no-op (idempotent by value) but must still
        # answer from the cache, not re-apply over a later write.
        _roundtrip(sock, {"op": "set", "key": "k", "value": b"first",
                          "cid": "c1", "cseq": 2})
        store.set("k", b"second")  # a later op from another client
        r = _roundtrip(sock, {"op": "set", "key": "k", "value": b"first",
                              "cid": "c1", "cseq": 2})
        assert r["ok"]
        assert store.get("k") == b"second", "replay re-applied over a later write"

        # mset (multi_set)
        _roundtrip(sock, {"op": "mset", "items": {"m/1": b"a", "m/2": b"b"},
                          "cid": "c1", "cseq": 3})
        store.set("m/1", b"z")
        r = _roundtrip(sock, {"op": "mset", "items": {"m/1": b"a", "m/2": b"b"},
                              "cid": "c1", "cseq": 3})
        assert r["ok"] and store.get("m/1") == b"z"

        # delete: the first application returns True; the replay must
        # echo it (a fresh apply would return False — key already gone).
        r1 = _roundtrip(sock, {"op": "delete", "key": "m/2",
                               "cid": "c1", "cseq": 4})
        r2 = _roundtrip(sock, {"op": "delete", "key": "m/2",
                               "cid": "c1", "cseq": 4})
        assert r1["value"] is True and r2["value"] is True

        # delete_prefix: same cached-count contract.
        store.mset({"p/1": b"x", "p/2": b"y"})
        r1 = _roundtrip(sock, {"op": "delete_prefix", "prefix": "p/",
                               "cid": "c1", "cseq": 5})
        r2 = _roundtrip(sock, {"op": "delete_prefix", "prefix": "p/",
                               "cid": "c1", "cseq": 5})
        assert r1["value"] == 2 and r2["value"] == 2
    finally:
        sock.close()
        store.close()


@pytest.mark.parametrize(
    "op_fn,verify",
    [
        (lambda s: s.set("ik", b"v"), lambda s: s.get("ik") == b"v"),
        (lambda s: s.add("ictr", 3), lambda s: s.get("ictr") == b"3"),
        (lambda s: s.mset({"im/1": b"a"}), lambda s: s.get("im/1") == b"a"),
    ],
)
def test_injected_rpc_transient_is_retried_exactly_once(op_fn, verify):
    """An injected ``dist_store.rpc`` transient models a blip that failed
    one request: the client resends with the same (client_id, seq) and
    the op applies exactly once — the connection is NOT latched dead."""
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    try:
        faultinject.configure("dist_store.rpc@1=transient")
        op_fn(store)
        assert verify(store)
        store.set("still-alive", b"1")  # not latched dead
    finally:
        faultinject.disable()
        store.close()


def test_injected_rpc_transient_barrier_arrive_depart():
    """Barrier arrive + depart under an rpc blip: the arrive-side set and
    the depart write each survive one injected transient, the barrier
    completes, and the arrive keys show exactly one write per rank."""
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    errs = []

    def run(rank: int) -> None:
        try:
            s = store.clone()
            b = LinearBarrier("ibar", s, rank, 2)
            b.arrive(timeout=10.0)
            b.depart(timeout=10.0)
            s.close()
        except BaseException as e:  # noqa: B036
            errs.append((rank, e))

    try:
        # Probabilistic plan: each rpc independently blips 30% of the
        # time, seeded — every request retries through it idempotently.
        faultinject.configure("dist_store.rpc@p0.3=transient;seed=9")
        threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
    finally:
        faultinject.disable()
    assert store.get("ibar/arrive/0") == b"1"
    assert store.get("ibar/arrive/1") == b"1"
    assert store.get("ibar/depart") == b"1"
    store.close()


def test_exhausted_rpc_blips_propagate_without_latching():
    """A plan that blips every attempt exhausts the bounded resend budget
    and propagates the transient — but the connection stays usable once
    the plan clears (a blip is not a torn store)."""
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    try:
        faultinject.configure("dist_store.rpc@1+=transient")
        with pytest.raises(faultinject.InjectedTransientError):
            store.set("never", b"1")
        faultinject.disable()
        store.set("after", b"1")
        assert store.get("after") == b"1"
    finally:
        faultinject.disable()
        store.close()


# -------------------------------------------------------------- failover


def test_failover_mid_blocked_wait_any(replicated):
    """A client blocked in wait_any when the leader dies re-arms against
    the promoted replica and completes when the key appears there."""
    leader, standby, client = replicated
    got = {}

    def blocked():
        got["res"] = client.wait_any(["late-key"], timeout=60.0)

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.3)  # let the wait block server-side
    _kill_leader(leader)
    _wait_promoted(standby)
    writer = TCPStore("127.0.0.1", standby.port, timeout=10.0)
    writer.set("late-key", b"arrived")
    t.join(timeout=30)
    assert not t.is_alive(), "wait_any never re-armed after failover"
    assert got["res"] == ("late-key", b"arrived")
    assert client.failovers == 1
    writer.close()


def test_failover_preserves_data_dedup_and_blocking_ops(replicated):
    """The full client surface across a leader kill: reads see the
    replicated data, mutations keep flowing, exactly one failover is
    counted, and the telemetry counter matches."""
    leader, standby, client = replicated
    telemetry.set_enabled(True)
    telemetry.reset()
    try:
        client.set("pre", b"1")
        assert client.add("ctr", 7) == 7
        _kill_leader(leader)
        # Every op after the kill fails over transparently.
        assert client.get("pre", timeout=30.0) == b"1"
        assert client.add("ctr", 1) == 8
        assert client.check("__prime__")
        client.mset({"post/1": b"a"})
        assert client.delete("post/1") is True
        assert client.failovers == 1
        assert telemetry.counters().get("store_failovers") == 1
        st = client.status()
        assert st["role"] == "leader" and st["epoch"] == 2
    finally:
        telemetry.set_enabled(False)


def test_clone_fails_over_to_promoted_replica(replicated):
    """clone() (the async-commit thread's bootstrap) targets the dead
    leader first, then the replica set."""
    leader, standby, client = replicated
    _kill_leader(leader)
    _wait_promoted(standby)
    c2 = client.clone()
    c2.set("via-clone", b"1")
    assert c2.get("via-clone", timeout=5.0) == b"1"
    c2.close()


def test_no_replicas_latches_dead_fast():
    """The regression guard: with zero replicas the pre-replication
    behavior is exact — connection loss latches the client dead with the
    rank-0 diagnosis, in well under the failover budget."""
    leader = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    client = TCPStore("127.0.0.1", leader.port, timeout=10.0)
    client.set("warm", b"1")
    leader._server.close()
    t0 = time.monotonic()
    with pytest.raises(StoreConnectionLostError) as ei:
        client.get("warm", timeout=30.0)
    assert time.monotonic() - t0 < 8.0
    assert "rank 0" in str(ei.value)
    with pytest.raises(StoreConnectionLostError):
        client.set("more", b"1")  # latched: fails fast
    client.close()


def test_surviving_client_retracts_its_false_death_key(replicated):
    """Review regression: a client whose CONNECTION dropped but whose
    process survived (failover over a blip, leader still alive) must
    retract the death key the server flushed for it — otherwise every
    collective watches a sticky false death forever. A different rank's
    genuine death record in the same key is preserved (value-conditional
    delete)."""
    import socket as socket_mod

    leader, standby, client = replicated
    observer = TCPStore("127.0.0.1", leader.port, timeout=10.0)
    client.register_liveness("pgw/death", b"rank-3-died")
    # Tear the CONNECTION only (the process lives): the server's handler
    # flushes the death key.
    client._sock.shutdown(socket_mod.SHUT_RDWR)
    deadline = time.monotonic() + 10
    while not observer.check("pgw/death") and time.monotonic() < deadline:
        time.sleep(0.02)
    assert observer.check("pgw/death"), "server never flushed the death key"
    # The client's next op fails over (re-adopting the live leader) and
    # retracts its own false death.
    client.set("recovered", b"1")
    assert client.failovers == 1
    assert not observer.check("pgw/death"), "false death key not retracted"
    # A DIFFERENT rank's genuine death is not erased by the retraction:
    observer.set("pgw/death", b"rank-7-died")  # first-death-wins record
    client._sock.shutdown(socket_mod.SHUT_RDWR)
    client.set("recovered2", b"1")
    assert client.failovers == 2
    assert observer.get("pgw/death", timeout=5.0) == b"rank-7-died"
    observer.close()


def test_late_flush_of_superseded_connection_does_not_publish_death():
    """Review regression: when the same client has RE-registered its
    liveness over a newer connection (failover over a blip), a late
    drop of the OLD connection must not publish the death key — only
    the connection currently holding the registration may."""
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    old = _raw_client(store)
    new = _raw_client(store)
    try:
        for sock in (old, new):  # `new` supersedes `old` for (cidZ, key)
            assert _roundtrip(
                sock,
                {"op": "register_liveness", "key": "death/z",
                 "value": b"z-died", "cid": "cidZ"},
            )["ok"]
        old.close()  # late FIN of the superseded connection
        time.sleep(0.5)
        assert not store.check("death/z"), "superseded drop published death"
        new.close()  # the CURRENT registration dropping IS a death
        deadline = time.monotonic() + 10
        while not store.check("death/z") and time.monotonic() < deadline:
            time.sleep(0.02)
        assert store.get("death/z", timeout=5.0) == b"z-died"
    finally:
        store.close()


def test_lease_renewals_flow_and_counter(replicated):
    leader, standby, client = replicated
    telemetry.set_enabled(True)
    telemetry.reset()
    try:
        time.sleep(LEASE * 3)
        assert telemetry.counters().get("lease_renewals", 0) >= 1
        st = client.status()
        (rep,) = st["replicas"]
        assert rep["lease_age_s"] < LEASE * 2
        assert rep["lag"] == 0
    finally:
        telemetry.set_enabled(False)


# --------------------------------------------------------- epoch fencing


def test_stalled_leader_is_rejoined_not_deposed(replicated):
    """Review regression: a leader that stalls past one lease (GC pause,
    GIL-held checkpoint serialization) but recovers must be REJOINED by
    its standby — index-0 standbys previously assumed with zero probes,
    silently forking the tier."""
    leader, standby, client = replicated
    srv = leader._server
    # Simulate the stall: hold the server's data lock, which freezes
    # dispatch AND the lease loop's renewal snapshot (whois is served
    # lock-free, exactly like a real stalled-then-recovered process
    # whose kernel keeps answering).
    srv._cond.acquire()
    try:
        time.sleep(LEASE * 3)
    finally:
        srv._cond.release()
    deadline = time.monotonic() + 15
    rejoined = False
    while time.monotonic() < deadline:
        with srv._cond:
            active = [l for l in srv._replicas if not l.syncing]
        if (
            standby._role == "standby"
            and standby._epoch == 1
            and len(active) == 1
        ):
            rejoined = True
            break
        time.sleep(0.05)
    assert rejoined, (
        standby._role,
        standby._epoch,
        srv._role,
        srv._epoch,
    )
    assert srv._role == "leader" and srv._epoch == 1
    # The tier still works end to end, with no client failover needed.
    client.set("post-stall", b"1")
    assert client.get("post-stall", timeout=5.0) == b"1"
    assert client.failovers == 0


def test_client_dedup_table_is_bounded():
    """Review regression: the idempotency table evicts
    least-recently-writing clients past CLIENT_SEQ_CAP instead of
    leaking one entry per client forever."""
    from torchsnapshot_tpu.dist_store import CLIENT_SEQ_CAP

    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    sock = _raw_client(store)
    try:
        for i in range(CLIENT_SEQ_CAP + 50):
            r = _roundtrip(
                sock,
                {"op": "set", "key": "k", "value": b"v",
                 "cid": f"c{i}", "cseq": 1},
            )
            assert r["ok"]
        table = store._server._client_seqs
        assert len(table) == CLIENT_SEQ_CAP
        assert "c0" not in table  # oldest evicted
        assert f"c{CLIENT_SEQ_CAP + 49}" in table  # newest kept
    finally:
        sock.close()
        store.close()


def test_replica_rejects_stale_epoch_stream(replicated):
    """Epoch fencing at the protocol level: a replicate stamped with a
    lower epoch than the replica's is refused (``stale_epoch``), raises
    the deposition marker on the sender, and is NOT applied."""
    leader, standby, client = replicated
    link = leader._server._replicas[0]
    with pytest.raises(_DeposedError):
        link.send(
            {
                "op": "replicate",
                "epoch": 0,  # below the replica's epoch (1)
                "seq": 999,
                "req": {"op": "set", "key": "stale", "value": b"poison"},
            },
            timeout=5.0,
        )
    assert "stale" not in standby._data


def test_deposed_mid_replicate_write_is_not_acked(replicated):
    """Review regression: a leader that learns it was deposed DURING the
    synchronous replicate of a write must answer ``not_leader``, not
    ``ok`` — the write lives only on the dead lineage and the client
    must replay it against the promoted leader."""
    leader, standby, client = replicated
    # Simulate a promotion that happened elsewhere: the standby moves to
    # a higher epoch, so the leader's next replicate draws stale_epoch.
    with standby._cond:
        standby._epoch += 1
    sock = _raw_client(leader)
    try:
        resp = _roundtrip(
            sock,
            {"op": "set", "key": "doomed", "value": b"x", "cid": "cX", "cseq": 1},
        )
        assert resp.get("not_leader"), resp
        assert not resp.get("ok"), resp
        info = _roundtrip(sock, {"op": "whois"})
        assert info["role"] == "deposed"
    finally:
        sock.close()


def test_failover_budget_scales_with_probed_lease(replicated):
    """Review regression: the client's failover budget must follow the
    LARGEST lease any probed candidate reports (a server built with a
    long lease parameter keeps its standby in a fencing wait far past
    the env default)."""
    leader, standby, client = replicated
    assert client._failover_budget_s(0.0) == pytest.approx(
        max(4.0 * 5.0, 10.0)
    )
    assert client._failover_budget_s(30.0) == pytest.approx(120.0)
    # whois advertises the lease the budget learns from.
    from torchsnapshot_tpu.dist_store import _try_whois

    info = _try_whois(leader.addr)
    assert info["lease_s"] == pytest.approx(LEASE)


def test_rs_update_stale_epoch_deposes_leader(replicated):
    """Review regression: fencing evidence arriving on an rs_update
    answer (not just replicate/lease) must depose the old leader, not
    merely drop the fenced replica."""
    leader, standby, client = replicated
    with standby._cond:
        standby._epoch += 1
    leader._server._broadcast_rs_update()
    assert leader._server._role == "deposed"


def test_promoted_join_connection_not_tracked_as_client_conn(replicated):
    """Review regression: a replica-join connection's accept-time
    tracking entry is released once the link owns the socket (standbys
    blip and rejoin for months; each cycle must not leak a ref)."""
    leader, standby, client = replicated
    srv = leader._server
    (link,) = srv._replicas
    with srv._conns_lock:
        assert link.sock not in srv._conns


def test_deposed_leader_answers_not_leader(replicated):
    """A leader that received fencing evidence stops serving: clients get
    ``not_leader`` and fail over instead of writing into a dead epoch."""
    leader, standby, client = replicated
    with leader._server._cond:
        leader._server._depose_locked()
    # The standby's upstream link died with the deposition; it promotes.
    _wait_promoted(standby)
    client.set("after-depose", b"1")
    assert client.get("after-depose", timeout=10.0) == b"1"
    assert client.failovers == 1
    assert client.status()["epoch"] == 2


# ------------------------------------------------------- connect retries


def test_connect_retries_outwait_slow_server_start():
    """TCPStore's bounded, jittered connect-retry: a server that binds
    late is reached; retries=0 preserves the instant-refusal behavior."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    with pytest.raises(ConnectionRefusedError):
        TCPStore("127.0.0.1", port, connect_retries=0)

    started = {}

    def late_server():
        time.sleep(0.8)
        started["server"] = TCPStore("127.0.0.1", port, is_server=True)

    t = threading.Thread(target=late_server)
    t.start()
    try:
        client = TCPStore("127.0.0.1", port, connect_retries=6, timeout=10.0)
        client.set("late", b"ok")
        assert client.get("late") == b"ok"
        client.close()
    finally:
        t.join(timeout=10)
        if "server" in started:
            started["server"].close()


def test_connection_lost_error_role_parametrized():
    err = StoreConnectionLostError("1.2.3.4:5", "get", OSError("boom"))
    assert "rank 0, the snapshot leader" in str(err)
    err = StoreConnectionLostError(
        "1.2.3.4:5", "get", OSError("boom"),
        role="the store leader at epoch 3; failover exhausted",
    )
    assert "epoch 3" in str(err) and "rank 0" not in str(err)
    assert err.role.startswith("the store leader")


# ----------------------------------------------------------- bootstrap


def test_create_store_replica_bootstrap_threads():
    """create_store with replicas=1: the hosting side gates on the full
    replica set, the standby rank hosts it, and every client's failover
    cache is primed by the bootstrap."""
    from torchsnapshot_tpu.dist_store import create_store, REPLICAS_READY_KEY

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    results = {}

    def rank0():
        results[0] = create_store(0, addr, timeout=30.0, replicas=1,
                                  lease_s=LEASE)

    def rank1():
        results[1] = create_store(1, addr, timeout=30.0, replicas=1,
                                  lease_s=LEASE)

    threads = [threading.Thread(target=rank0), threading.Thread(target=rank1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert set(results) == {0, 1}
    s0, s1 = results[0], results[1]
    try:
        assert s0.check(REPLICAS_READY_KEY)
        assert s1._standby is not None, "rank 1 did not host the standby"
        # Both clients know the failover target after bootstrap.
        s0.set("x", b"1")
        assert s0.replica_addrs or s1.replica_addrs
    finally:
        s1.close()
        s0.close()


# ---------------------------------------------------------- store-status


def test_probe_store_status_and_cli(replicated, capsys):
    leader, standby, client = replicated
    info = probe_store_status(leader.addr)
    assert info["role"] == "leader" and info["epoch"] == 1
    (rep,) = info["replicas"]
    assert rep["addr"].endswith(str(standby.port))

    standby_info = probe_store_status(f"127.0.0.1:{standby.port}")
    assert standby_info["role"] == "standby"
    assert standby_info["leader"] == leader.addr

    from torchsnapshot_tpu.cli import main

    assert main(["store-status", leader.addr]) == 0
    out = capsys.readouterr().out
    assert "role=leader" in out and "replica[0]" in out

    assert main(["store-status", "--json", leader.addr]) == 0
    import json

    doc = json.loads(capsys.readouterr().out)
    assert doc["role"] == "leader" and doc["replicas"]

    assert main(["store-status", "127.0.0.1:1"]) == 2
    assert "no store node answering" in capsys.readouterr().err


def test_store_status_no_replicas_warns(capsys):
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    try:
        from torchsnapshot_tpu.cli import main

        assert main(["store-status", store.addr]) == 0
        assert "single point of failure" in capsys.readouterr().out
    finally:
        store.close()


def test_serve_op_site_counts_hits():
    """The server-side fault site: every dispatched client op counts one
    ``dist_store.serve_op`` hit — the hook the SIGKILL-the-store-host
    chaos schedules are pinned to."""
    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    try:
        faultinject.configure("dist_store.serve_op@999=delay:0")
        before = faultinject.hits().get("dist_store.serve_op", 0)
        store.set("a", b"1")
        store.get("a")
        store.check("a")
        after = faultinject.hits().get("dist_store.serve_op", 0)
        assert after - before == 3
    finally:
        faultinject.disable()
        store.close()
