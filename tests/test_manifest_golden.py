"""Golden-data manifest tests: pin the on-disk YAML format and the
elasticity rules against a hand-maintained fixture covering every entry
type (reference: tests/test_manifest.py:21-441, incl. the rank-42
larger-world case).

The YAML metadata is the snapshot commit point — its format is the
compatibility contract between releases. If a change breaks byte-exact
round-trip of the fixture, it breaks restores of existing snapshots:
regenerate the fixture ONLY for deliberate, versioned format changes.
"""

from __future__ import annotations

import os
from dataclasses import asdict

import pytest

from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
    get_available_entries,
    get_manifest_for_rank,
)

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "data", "golden_manifest.json")
LEGACY_YAML_PATH = os.path.join(
    os.path.dirname(__file__), "data", "golden_manifest.yaml"
)


@pytest.fixture()
def golden_text() -> str:
    with open(GOLDEN_PATH) as f:
        return f.read()


@pytest.fixture()
def metadata(golden_text: str) -> SnapshotMetadata:
    return SnapshotMetadata.from_yaml(golden_text)


def test_round_trip_is_byte_exact(golden_text, metadata) -> None:
    assert metadata.to_yaml() == golden_text


def test_legacy_yaml_golden_still_loads(metadata) -> None:
    """Snapshots written before the round-4 JSON switch carry YAML
    metadata; they must parse to exactly the same manifest."""
    with open(LEGACY_YAML_PATH) as f:
        legacy = SnapshotMetadata.from_yaml(f.read())
    assert asdict(legacy) == asdict(metadata)


def test_emission_is_readable_by_yaml_loaders() -> None:
    """Builds predating the JSON switch parse ``.snapshot_metadata`` with
    a YAML loader; JSON emission must stay within what it accepts."""
    import json

    import yaml

    with open(GOLDEN_PATH) as f:
        text = f.read()
    assert yaml.safe_load(text) == json.loads(text)


def test_all_entry_types_parse(metadata) -> None:
    m = metadata.manifest
    assert type(m["0/model"]).__name__ == "DictEntry"
    assert type(m["0/model/layers"]).__name__ == "ListEntry"
    assert type(m["0/counters"]).__name__ == "TupleEntry"
    assert type(m["0/extra"]).__name__ == "OrderedDictEntry"
    assert isinstance(m["0/model/weight"], ArrayEntry)
    assert isinstance(m["0/model/big"], ChunkedArrayEntry)
    assert isinstance(m["0/model/sharded_w"], ShardedArrayEntry)
    assert isinstance(m["0/extra/blob"], ObjectEntry)
    opt = m["0/model/opt"]
    assert (opt.module, opt.qualname) == ("optax", "ScaleByAdamState")
    assert opt.fields == ["count", "mu", "nu"]

    # field-level pins
    w = m["0/model/weight"]
    assert (w.dtype, w.shape, w.replicated) == ("bfloat16", [64, 64], True)
    assert w.checksum == "crc32c:deadbeef"
    buf = m["0/model/buf"]
    assert buf.byte_range == [128, 144]
    blob = m["0/extra/blob"]
    assert (blob.size, blob.obj_type) == (4096, "set")
    big = m["0/model/big"]
    assert [c.offsets for c in big.chunks] == [[0, 0], [512, 0]]


def test_primitive_values_restore_bit_exact(metadata) -> None:
    m = metadata.manifest
    assert m["0/counters/0"].get_value() == 7
    assert m["0/counters/1"].get_value() == 0.5
    assert m["0/counters/2"].get_value() == "step-name"
    assert m["0/counters/3"].get_value() is True
    assert m["0/counters/4"].get_value() == b"\x00\x01"
    assert m["0/counters/5"].get_value() is None


def test_availability_same_world(metadata) -> None:
    avail0 = get_available_entries(metadata.manifest, 0)
    avail1 = get_available_entries(metadata.manifest, 1)

    # per-rank entries go to their owner only
    assert avail0["rank_local"].location == "0/rank_local"
    assert avail1["rank_local"].location == "1/rank_local"

    # replicated entries go to everyone; a saver reads its own copy
    assert avail0["model/weight"].location == "replicated/model/weight"
    assert avail1["model/weight"].location == "replicated/model/weight"

    # rank 1 did not save model/buf (per-rank, not replicated) -> absent
    assert "model/buf" in avail0
    assert "model/buf" not in avail1

    # sharded entries merge all ranks' shards for everyone
    for avail in (avail0, avail1):
        merged = avail["model/sharded_w"]
        assert sorted(s.offsets for s in merged.shards) == [[0, 0], [64, 0]]

    # container entries are structural only
    assert "model" not in avail0
    assert "counters" not in avail0


def test_availability_larger_world_rank_beyond_savers(metadata) -> None:
    # Restoring with world size 43: rank 42 saved nothing.
    avail = get_available_entries(metadata.manifest, 42)
    # replicated + sharded available
    assert avail["model/weight"].location == "replicated/model/weight"
    assert isinstance(avail["model/big"], ChunkedArrayEntry)
    assert len(avail["model/sharded_w"].shards) == 2
    # primitives saved replicated=False belong to their rank
    assert "counters/0" not in avail
    # per-rank entries are NOT available
    assert "rank_local" not in avail
    assert "model/buf" not in avail


def test_manifest_for_rank_includes_rank0_containers_for_new_ranks(metadata) -> None:
    m42 = get_manifest_for_rank(metadata, 42)
    # container structure borrowed from rank 0 so inflate can rebuild
    assert type(m42["model"]).__name__ == "DictEntry"
    assert m42["model"].keys == ["weight", "buf", "opt", "layers"]


def test_asdict_field_order_is_stable(metadata) -> None:
    # Serialization order is part of the format: type first, then fields in
    # declaration order.
    d = asdict(metadata.manifest["0/model/weight"])
    assert list(d.keys()) == [
        "type",
        "location",
        "serializer",
        "dtype",
        "shape",
        "replicated",
        "byte_range",
        "checksum",
        "digest",
        "origin",
        "codec",
        "device_digest",
    ]
    d = asdict(metadata.manifest["0/extra/blob"])
    assert list(d.keys()) == [
        "type",
        "location",
        "serializer",
        "obj_type",
        "replicated",
        "checksum",
        "size",
        "digest",
        "origin",
        "codec",
    ]
    # The incremental-snapshot fields are serialization-suppressed while
    # None (SnapshotMetadata.to_yaml), so the YAML golden files above—and
    # every non-incremental snapshot's on-disk format—are unchanged.


class TestColumnarGolden:
    """ISSUE 17: the binary struct-of-arrays (TSCM) manifest plane must
    be BIT-equivalent to the JSON carrier on the golden fixtures —
    decode(encode(md)).to_yaml() reproduces the golden text exactly, so
    either format restores identical snapshots."""

    def test_encode_decode_reproduces_golden_text(
        self, golden_text, metadata
    ) -> None:
        from torchsnapshot_tpu import colmanifest

        raw = colmanifest.encode_metadata(metadata)
        assert raw[:4] == b"TSCM"
        assert colmanifest.decode_metadata(raw).to_yaml() == golden_text

    def test_legacy_yaml_to_columnar_equivalence(self, metadata) -> None:
        """Snapshots parsed from the pre-JSON YAML carrier survive a
        columnar round-trip with identical manifests."""
        from torchsnapshot_tpu import colmanifest

        with open(LEGACY_YAML_PATH) as f:
            legacy = SnapshotMetadata.from_yaml(f.read())
        rt = colmanifest.decode_metadata(colmanifest.encode_metadata(legacy))
        assert asdict(rt) == asdict(metadata)

    def test_diff_round_trip(self, metadata) -> None:
        """Manifest diffs (TSCD) applied to the base reproduce the new
        manifest exactly — the incremental manifest plane's contract."""
        import copy

        from torchsnapshot_tpu import colmanifest

        new = copy.deepcopy(metadata)
        # mutate: change one leaf, drop one entry, add one entry
        new.manifest["0/model/weight"].checksum = "crc32c:0badf00d"
        del new.manifest["0/extra/blob"]
        new.manifest["0/model/extra_w"] = ArrayEntry(
            location="0/model/extra_w",
            serializer="buffer_protocol",
            dtype="float32",
            shape=[4],
            replicated=False,
        )
        diff = colmanifest.encode_manifest_diff(metadata, new)
        assert diff[:4] == b"TSCD"
        applied = colmanifest.apply_manifest_diff(metadata, diff)
        assert asdict(applied) == asdict(new)
        assert applied.to_yaml() == new.to_yaml()
        # the diff is much smaller than a full re-encode
        assert len(diff) < len(colmanifest.encode_metadata(new))

    def test_snapshot_metadata_reader_sniffs_columnar(
        self, metadata, tmp_path
    ) -> None:
        """_read_metadata dispatches on the TSCM magic, so a columnar
        ``.snapshot_metadata`` restores through the normal path."""
        from torchsnapshot_tpu import colmanifest
        from torchsnapshot_tpu.snapshot import Snapshot

        (tmp_path / ".snapshot_metadata").write_bytes(
            colmanifest.encode_metadata(metadata)
        )
        got = Snapshot(str(tmp_path)).metadata
        assert asdict(got) == asdict(metadata)


def test_legacy_manifest_without_new_fields_parses() -> None:
    # Forward compatibility: manifests written before ObjectEntry.size was
    # introduced must keep loading.
    legacy = """\
version: 0.1.0
world_size: 1
manifest:
  0/obj:
    type: object
    location: 0/obj
    serializer: pickle
    obj_type: dict
    replicated: false
"""
    md = SnapshotMetadata.from_yaml(legacy)
    entry = md.manifest["0/obj"]
    assert isinstance(entry, ObjectEntry)
    assert entry.size is None and entry.checksum is None
