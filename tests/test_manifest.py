"""Manifest golden-data + elasticity tests (reference: tests/test_manifest.py:21-441)."""

import pytest

from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    DictEntry,
    ObjectEntry,
    OrderedDictEntry,
    PrimitiveEntry,
    Shard,
    ShardedArrayEntry,
    SnapshotMetadata,
    get_available_entries,
    get_manifest_for_rank,
    is_replicated,
)


def _array(location: str, replicated: bool = False) -> ArrayEntry:
    return ArrayEntry(
        location=location,
        serializer="buffer_protocol",
        dtype="float32",
        shape=[4, 4],
        replicated=replicated,
    )


def _shard(off, sz, location) -> Shard:
    return Shard(offsets=off, sizes=sz, array=_array(location))


@pytest.fixture
def global_manifest():
    return {
        "0/state/step": PrimitiveEntry.from_object(100, replicated=False),
        "1/state/step": PrimitiveEntry.from_object(100, replicated=False),
        "0/model/weight": _array("replicated/model/weight", replicated=True),
        "1/model/weight": _array("replicated/model/weight", replicated=True),
        "0/model/emb": ShardedArrayEntry(
            dtype="float32",
            shape=[8, 4],
            shards=[_shard([0, 0], [4, 4], "sharded/model/emb_0_0")],
        ),
        "1/model/emb": ShardedArrayEntry(
            dtype="float32",
            shape=[8, 4],
            shards=[_shard([4, 0], [4, 4], "sharded/model/emb_4_0")],
        ),
        "0/extra/local": _array("0/extra/local"),
        "0/obj": ObjectEntry(
            location="0/obj", serializer="pickle", obj_type="Foo", replicated=False
        ),
        "0": DictEntry(keys=["state", "model", "extra", "obj"]),
        "0/state": OrderedDictEntry(keys=["step"]),
        "1": DictEntry(keys=["state", "model"]),
        "1/state": OrderedDictEntry(keys=["step"]),
    }


def test_rank0_view(global_manifest) -> None:
    avail = get_available_entries(global_manifest, 0)
    assert avail["state/step"].get_value() == 100
    assert avail["model/weight"].replicated
    assert len(avail["model/emb"].shards) == 2  # merged across ranks
    assert "extra/local" in avail
    assert "obj" in avail
    # container entries excluded
    assert "state" not in avail


def test_rank1_view(global_manifest) -> None:
    avail = get_available_entries(global_manifest, 1)
    assert "extra/local" not in avail  # per-rank, owned by rank 0
    assert "obj" not in avail
    assert "state/step" in avail  # rank 1 saved its own
    assert len(avail["model/emb"].shards) == 2


def test_larger_world_rank42(global_manifest) -> None:
    # A rank beyond the saving world size sees replicated + sharded only.
    avail = get_available_entries(global_manifest, 42)
    assert set(avail) == {"model/weight", "model/emb"}


def test_yaml_roundtrip(global_manifest) -> None:
    md = SnapshotMetadata(version="0.1.0", world_size=2, manifest=global_manifest)
    restored = SnapshotMetadata.from_yaml(md.to_yaml())
    assert restored.version == "0.1.0"
    assert restored.world_size == 2
    assert set(restored.manifest) == set(global_manifest)
    emb = restored.manifest["0/model/emb"]
    assert isinstance(emb, ShardedArrayEntry)
    assert emb.shards[0].offsets == [0, 0]
    assert emb.shards[0].array.location == "sharded/model/emb_0_0"
    step = restored.manifest["0/state/step"]
    assert step.get_value() == 100
    assert isinstance(restored.manifest["0/state"], OrderedDictEntry)


def test_primitive_float_bit_exact() -> None:
    val = 0.1 + 0.2  # not representable exactly
    entry = PrimitiveEntry.from_object(val)
    rt = SnapshotMetadata(version="v", world_size=1, manifest={"0/x": entry})
    restored = SnapshotMetadata.from_yaml(rt.to_yaml())
    assert restored.manifest["0/x"].get_value() == val


def test_primitive_types() -> None:
    for val in [3, -1, True, False, "hello", b"\x00\xff", None, 2.5]:
        entry = PrimitiveEntry.from_object(val)
        assert entry.get_value() == val
        assert type(entry.get_value()) is type(val)


def test_chunked_entry_roundtrip() -> None:
    entry = ChunkedArrayEntry(
        dtype="bfloat16",
        shape=[100, 10],
        chunks=[
            _shard([0, 0], [50, 10], "replicated/x_0_0"),
            _shard([50, 0], [50, 10], "replicated/x_50_0"),
        ],
        replicated=True,
    )
    md = SnapshotMetadata(version="v", world_size=1, manifest={"0/x": entry})
    restored = SnapshotMetadata.from_yaml(md.to_yaml()).manifest["0/x"]
    assert restored.chunks[1].offsets == [50, 0]
    assert is_replicated(restored)


def test_get_manifest_for_rank_includes_containers(global_manifest) -> None:
    md = SnapshotMetadata(version="v", world_size=2, manifest=global_manifest)
    m0 = get_manifest_for_rank(md, 0)
    assert isinstance(m0[""], DictEntry)  # rank-root container present
    assert "state" in m0 and isinstance(m0["state"], OrderedDictEntry)
    # new rank falls back to rank 0's containers
    m42 = get_manifest_for_rank(md, 42)
    assert "state" in m42


def test_byte_range_persisted() -> None:
    e = _array("batched/abc")
    e.byte_range = [128, 256]
    md = SnapshotMetadata(version="v", world_size=1, manifest={"0/t": e})
    restored = SnapshotMetadata.from_yaml(md.to_yaml()).manifest["0/t"]
    assert restored.byte_range == [128, 256]
