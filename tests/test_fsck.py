"""The ``fsck`` subcommand: every corruption class it exists to detect,
one seeded instance each, plus the clean/cannot-check/repair contracts.

Classes (ISSUE 5 acceptance): truncated payload, flipped byte, missing
file, orphan temp dir, dangling incremental dep — each must exit nonzero
with the right finding class — plus corrupt metadata, partial commits,
stale fences, and the ``--repair`` quarantine being reversible and
convergent (a second fsck after repair is clean).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu import CorruptSnapshotError, Snapshot, StateDict
from torchsnapshot_tpu.cli import main as cli_main, run_fsck


def _take(path: str, scale: float = 1.0, record_digests: bool = False) -> dict:
    state = {
        "model": StateDict(
            w=np.arange(4096, dtype=np.float32) * scale,
            b=np.arange(256, dtype=np.float64) * scale,
        )
    }
    Snapshot.take(str(path), state, record_digests=record_digests)
    return state


def _payload(path, name: str) -> str:
    p = os.path.join(str(path), "0", "model", name)
    assert os.path.exists(p), p
    return p


def test_clean_snapshot_is_clean(tmp_path):
    _take(tmp_path / "snap")
    code, report = run_fsck(str(tmp_path / "snap"))
    assert code == 0
    assert report.clean
    assert report.payloads_ok == 2
    # Committed snapshots carry no fence (deleted at the commit point).
    assert not os.path.exists(tmp_path / "snap" / ".snapshot_fence")


def test_truncated_payload_detected(tmp_path):
    _take(tmp_path / "snap")
    with open(_payload(tmp_path / "snap", "w_0"), "r+b") as f:
        f.truncate(100)
    code, report = run_fsck(str(tmp_path / "snap"))
    assert code == 1
    assert "truncated-payload" in report.classes()


def test_flipped_byte_detected(tmp_path):
    _take(tmp_path / "snap")
    with open(_payload(tmp_path / "snap", "w_0"), "r+b") as f:
        f.seek(1234)
        byte = f.read(1)
        f.seek(1234)
        f.write(bytes([byte[0] ^ 0xFF]))
    code, report = run_fsck(str(tmp_path / "snap"))
    assert code == 1
    assert "checksum-mismatch" in report.classes()


def test_missing_payload_detected(tmp_path):
    _take(tmp_path / "snap")
    os.remove(_payload(tmp_path / "snap", "b_0"))
    code, report = run_fsck(str(tmp_path / "snap"))
    assert code == 1
    assert "missing-payload" in report.classes()


def test_orphan_temp_dir_detected_and_repaired(tmp_path):
    snap = tmp_path / "snap"
    _take(snap)
    os.makedirs(snap / "batched.tmp.4242")
    (snap / "batched.tmp.4242" / "slab").write_bytes(b"\x00" * 64)
    (snap / "stray_payload").write_bytes(b"\x00" * 8)
    code, report = run_fsck(str(snap))
    assert code == 1
    assert {"temp-file", "orphan"} <= report.classes()

    code, report = run_fsck(str(snap), repair=True)
    assert code == 0, report.findings
    assert len(report.repaired) == 2
    # Reversible: quarantined, not deleted.
    assert (snap / ".fsck_quarantine" / "batched.tmp.4242" / "slab").exists()
    assert (snap / ".fsck_quarantine" / "stray_payload").exists()
    # Convergent: a second fsck (quarantine dir ignored) is clean.
    code, report = run_fsck(str(snap))
    assert code == 0, report.findings


def test_repair_never_touches_corruption(tmp_path):
    snap = tmp_path / "snap"
    _take(snap)
    with open(_payload(snap, "w_0"), "r+b") as f:
        f.truncate(100)
    code, report = run_fsck(str(snap), repair=True)
    assert code == 1
    assert "truncated-payload" in report.classes()
    assert not report.repaired


def test_dangling_incremental_dep_detected(tmp_path):
    base = tmp_path / "base"
    state = _take(base, record_digests=True)
    Snapshot.take(
        str(tmp_path / "inc"),
        {
            "model": StateDict(
                w=np.asarray(state["model"]["w"]),
                b=np.asarray(state["model"]["b"]),
            )
        },
        incremental_base=str(base),
        record_digests=True,
    )
    # Baseline: intact chain is clean.
    code, report = run_fsck(str(tmp_path / "inc"))
    assert code == 0, report.findings

    os.remove(_payload(base, "w_0"))
    code, report = run_fsck(str(tmp_path / "inc"))
    assert code == 1
    assert "dangling-dep" in report.classes()

    # Base gone entirely: the dep findings name the base as unreadable.
    import shutil

    shutil.rmtree(base)
    code, report = run_fsck(str(tmp_path / "inc"))
    assert code == 1
    assert "dangling-dep" in report.classes()


def test_corrupt_metadata_detected(tmp_path):
    snap = tmp_path / "snap"
    _take(snap)
    meta = snap / ".snapshot_metadata"
    raw = meta.read_bytes()
    meta.write_bytes(raw[: len(raw) // 2])  # torn mid-write
    code, report = run_fsck(str(snap))
    assert code == 1
    assert "corrupt-metadata" in report.classes()
    with pytest.raises(CorruptSnapshotError) as exc_info:
        Snapshot(str(snap)).metadata
    assert str(snap) in str(exc_info.value)

    meta.write_bytes(b"")  # zero-byte commit residue
    code, report = run_fsck(str(snap))
    assert code == 1
    assert "corrupt-metadata" in report.classes()
    with pytest.raises(CorruptSnapshotError):
        Snapshot(str(snap)).metadata


def test_partial_commit_detected(tmp_path):
    partial = tmp_path / "partial"
    os.makedirs(partial / "0" / "model")
    (partial / "0" / "model" / "w_0").write_bytes(b"\x00" * 512)
    code, report = run_fsck(str(partial))
    assert code == 1
    assert "partial-commit" in report.classes()


def test_stale_fence_detected_and_repaired(tmp_path):
    snap = tmp_path / "snap"
    _take(snap)
    (snap / ".snapshot_fence").write_text('{"gen": "dead"}')
    code, report = run_fsck(str(snap))
    assert code == 1
    assert "stale-fence" in report.classes()
    code, report = run_fsck(str(snap), repair=True)
    assert code == 0, report.findings


def test_fsck_agrees_with_mirror_failover(tmp_path):
    """Restore-equivalence: a payload whose primary copy is lost but
    whose mirror copy is intact must fsck CLEAN (restore reads it fine
    via failover) — with explicit mirror options AND with none, because
    the snapshot's own recorded mirror_url is applied by default (a
    degraded-but-healthy deployment must not raise a false alarm). An
    explicit ``mirror_url=None`` audits the primary tier alone."""
    snap = tmp_path / "snap"
    opts = {"mirror_url": str(tmp_path / "mirror")}
    state = {
        "model": StateDict(
            w=np.arange(4096, dtype=np.float32),
            b=np.arange(256, dtype=np.float64),
        )
    }
    Snapshot.take(str(snap), state, storage_options=opts)
    os.remove(_payload(snap, "w_0"))

    code, report = run_fsck(str(snap), storage_options=opts)
    assert code == 0, report.findings
    # No options: the recorded meta.mirror_url kicks in (restore would
    # succeed through it, so fsck must be clean too).
    code, report = run_fsck(str(snap))
    assert code == 0, report.findings
    # Primary tier alone, by explicit caller word.
    code, report = run_fsck(str(snap), storage_options={"mirror_url": None})
    assert code == 1
    assert "missing-payload" in report.classes()


def test_cannot_check_exit_codes(tmp_path):
    assert run_fsck(str(tmp_path / "absent"))[0] == 2
    os.makedirs(tmp_path / "empty")
    assert run_fsck(str(tmp_path / "empty"))[0] == 2


def test_cli_entrypoint_exit_codes(tmp_path, capsys):
    snap = tmp_path / "snap"
    _take(snap)
    assert cli_main(["fsck", str(snap)]) == 0
    with open(_payload(snap, "w_0"), "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff\xff")
    assert cli_main(["fsck", str(snap)]) == 1
    out = capsys.readouterr().out
    assert "CHECKSUM-MISMATCH" in out
    assert cli_main(["fsck", str(tmp_path / "absent")]) == 2


def test_truncated_mmap_sized_range_is_eof_not_valueerror(tmp_path):
    """A byte-ranged read big enough for the fs plugin's mmap path
    (>= 1 MiB) whose range extends past a truncated file's EOF must
    surface as EOFError — the taxonomy the buffered path and mirror
    failover speak — never CPython mmap's ValueError (which bypassed
    failover and crashed fsck). Whole-file reads stat first, so only
    ranged reads — slab byte_ranges — could hit the leak."""
    import asyncio

    from torchsnapshot_tpu.io_types import ReadIO
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    full = (1 << 20) + 4096
    (tmp_path / "slab").write_bytes(b"\xab" * full)
    with open(tmp_path / "slab", "r+b") as f:
        f.truncate(full // 2)

    plugin = FSStoragePlugin(str(tmp_path))
    loop = asyncio.new_event_loop()
    try:
        with pytest.raises(EOFError):
            loop.run_until_complete(
                plugin.read(ReadIO(path="slab", byte_range=(0, full)))
            )
    finally:
        plugin.sync_close(loop)
        loop.close()


def test_truncated_primary_range_fails_over_to_mirror(tmp_path):
    """The production consequence of the EOF taxonomy: a truncated
    primary under an intact mirror must fail over (EOFError is a
    documented primary-read loss), bit-exact — on the mmap-sized ranged
    path, where the old ValueError bypassed _PRIMARY_READ_FAILURES."""
    import asyncio

    from torchsnapshot_tpu.io_types import ReadIO
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
    from torchsnapshot_tpu.storage_plugins.mirror import (
        MirroredStoragePlugin,
    )

    full = (1 << 20) + 4096
    payload = bytes(range(256)) * (full // 256)
    os.makedirs(tmp_path / "primary")
    os.makedirs(tmp_path / "mirror")
    (tmp_path / "primary" / "slab").write_bytes(payload)
    (tmp_path / "mirror" / "slab").write_bytes(payload)
    with open(tmp_path / "primary" / "slab", "r+b") as f:
        f.truncate(full // 2)

    loop = asyncio.new_event_loop()
    primary = FSStoragePlugin(str(tmp_path / "primary"))
    mirror = FSStoragePlugin(str(tmp_path / "mirror"))
    plugin = MirroredStoragePlugin(primary, mirror, ".snapshot_metadata")
    try:
        read_io = ReadIO(path="slab", byte_range=(0, full))
        loop.run_until_complete(plugin.read(read_io))
        assert bytes(read_io.buf) == payload
    finally:
        plugin.sync_close(loop)
        loop.close()


def test_cloud_style_notfound_is_a_finding_not_a_crash(tmp_path):
    """Backend-specific not-found types (botocore NoSuchKey, google-api
    NotFound) are matched by NAME — fsck must turn them into findings
    and keep scanning, never abort with a traceback."""
    from torchsnapshot_tpu.cli import (
        _classify_read_failure,
        _is_not_found_error,
    )

    class NoSuchKey(Exception):  # botocore's shape, by name
        pass

    class NotFound(Exception):  # google-api's shape, by name
        pass

    assert _is_not_found_error(NoSuchKey("missing"))
    assert _is_not_found_error(NotFound("missing"))
    assert not _is_not_found_error(RuntimeError("throttled"))
    assert _classify_read_failure(NoSuchKey("x"), None) == "missing-payload"
    assert _classify_read_failure(NoSuchKey("x"), "dangling-dep") == (
        "dangling-dep"
    )
    assert _classify_read_failure(EOFError("x"), None) == "truncated-payload"
    assert _classify_read_failure(RuntimeError("x"), None) == "io-error"


def test_metadata_transport_error_is_cannot_check(tmp_path, monkeypatch):
    """A transport/auth failure reading .snapshot_metadata (not a
    not-found) means fsck can conclude nothing: exit 2 with a diagnosis
    through the caller's echo, never a raw traceback."""
    snap = tmp_path / "snap"
    _take(snap)

    class ClientError(Exception):  # transport-shaped, NOT a not-found
        pass

    from torchsnapshot_tpu.snapshot import Snapshot as _Snap

    def _boom(self, storage, event_loop):
        raise ClientError("connection reset by peer")

    monkeypatch.setattr(_Snap, "_read_metadata", _boom)
    lines: list = []
    code, report = run_fsck(str(snap), echo=lines.append)
    assert code == 2
    assert not report.findings
    # The cannot-check diagnosis reaches programmatic echo consumers.
    assert any("ClientError" in ln for ln in lines)


def test_fsck_verifies_batched_slab_ranges(tmp_path, monkeypatch):
    """Slab-batched payloads share one location under different byte
    ranges; fsck must verify each range (and catch a flip inside one)."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    state = {
        "model": StateDict(
            **{f"p{i}": np.arange(64, dtype=np.float32) + i for i in range(6)}
        )
    }
    snap = tmp_path / "snap"
    Snapshot.take(str(snap), state)
    code, report = run_fsck(str(snap))
    assert code == 0, report.findings
    slabs = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(snap / "batched")
        for f in fs
    ]
    assert slabs, "batching should have produced a slab"
    with open(slabs[0], "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad")
    code, report = run_fsck(str(snap))
    assert code == 1
    assert "checksum-mismatch" in report.classes()


# ------------------------------------------------- journal artifact class


def _take_with_journal(tmp_path, monkeypatch, epochs: int = 2):
    """A committed snapshot carrying a journal chain of ``epochs`` epochs."""
    from torchsnapshot_tpu import CheckpointManager

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    state = StateDict(w=np.arange(512, dtype=np.float32), step=0)
    mgr.save(0, {"app": state})
    for epoch in range(1, epochs + 1):
        state["step"] = epoch
        assert mgr.journal_step(epoch, {"app": state})
    snap = os.path.join(str(tmp_path), sorted(
        n for n in os.listdir(tmp_path)
        if os.path.isdir(os.path.join(str(tmp_path), n))
    )[0])
    return snap, os.path.join(snap, ".journal")


def test_all_internal_artifact_classes_fsck_clean(tmp_path, monkeypatch):
    """The internal-artifact registry regression: a snapshot carrying
    EVERY registered artifact class — telemetry summary, critpath,
    flight-recorder dumps, a quarantine dir, and a journal chain — must
    fsck clean, and ``--repair`` must leave all of them in place."""
    snap, jdir = _take_with_journal(tmp_path, monkeypatch)
    os.makedirs(os.path.join(snap, ".flight"))
    with open(os.path.join(snap, ".flight", "rank_0.jsonl"), "w") as f:
        f.write("{}\n")
    os.makedirs(os.path.join(snap, ".fsck_quarantine"))
    with open(os.path.join(snap, ".fsck_quarantine", "old_orphan"), "w") as f:
        f.write("x")
    os.makedirs(os.path.join(snap, ".telemetry"))
    with open(os.path.join(snap, ".telemetry", "r0.json"), "w") as f:
        f.write("{}")
    for fname in (".snapshot_telemetry", ".snapshot_critpath"):
        with open(os.path.join(snap, fname), "w") as f:
            f.write("{}")

    code, report = run_fsck(snap)
    assert code == 0, report.findings

    before = sorted(
        os.path.relpath(os.path.join(dp, f), snap)
        for dp, _, fs in os.walk(snap)
        for f in fs
    )
    code, report = run_fsck(snap, repair=True)
    assert code == 0 and not report.repaired
    after = sorted(
        os.path.relpath(os.path.join(dp, f), snap)
        for dp, _, fs in os.walk(snap)
        for f in fs
    )
    assert after == before


def test_internal_artifact_registry_is_the_single_source(tmp_path):
    """Every registry row answers internal_artifact_class; unregistered
    paths do not."""
    from torchsnapshot_tpu.cli import (
        INTERNAL_ARTIFACTS,
        internal_artifact_class,
    )

    for art in INTERNAL_ARTIFACTS:
        for f in art.files:
            assert internal_artifact_class(f) == art.name
        for p in art.prefixes:
            assert internal_artifact_class(os.path.join(p, "x")) == art.name
    assert internal_artifact_class("0/model/w_0") is None
    assert internal_artifact_class("stray") is None
    names = [art.name for art in INTERNAL_ARTIFACTS]
    assert "journal" in names and len(names) == len(set(names))


def test_journal_torn_tail_detected_and_repaired(tmp_path, monkeypatch):
    snap, jdir = _take_with_journal(tmp_path, monkeypatch)
    seg = os.path.join(jdir, "rank_0.seg")
    committed = os.path.getsize(seg)
    with open(seg, "ab") as f:
        f.write(b"TSJR\x20\x00\x00\x00torn")

    code, report = run_fsck(snap)
    assert code == 1
    assert report.classes() == {"journal-torn-tail"}

    code, report = run_fsck(snap, repair=True)
    assert code == 0, report.findings
    assert os.path.getsize(seg) == committed  # truncated to committed offset
    # Reversible: the tail bytes are quarantined, not deleted.
    tail = os.path.join(snap, ".fsck_quarantine", ".journal", "rank_0.seg.tail")
    assert os.path.isfile(tail) and os.path.getsize(tail) == 12
    # Convergent, and the committed chain still replays.
    assert run_fsck(snap)[0] == 0
    from torchsnapshot_tpu import CheckpointManager

    dst = StateDict(w=np.zeros(512, np.float32), step=-1)
    CheckpointManager(str(tmp_path)).restore({"app": dst})
    assert dst["step"] == 2


def test_journal_orphan_epoch_detected_and_repaired(tmp_path, monkeypatch):
    snap, jdir = _take_with_journal(tmp_path, monkeypatch)
    os.remove(os.path.join(jdir, "epoch_000001.json"))  # epoch 2 past the gap
    code, report = run_fsck(snap)
    assert code == 1
    assert "journal-orphan-epoch" in report.classes()
    code, report = run_fsck(snap, repair=True)
    assert code == 0, report.findings
    assert run_fsck(snap)[0] == 0


def test_journal_corrupt_record_is_not_repairable(tmp_path, monkeypatch):
    snap, jdir = _take_with_journal(tmp_path, monkeypatch)
    seg = os.path.join(jdir, "rank_0.seg")
    with open(seg, "r+b") as f:
        f.seek(20)
        byte = f.read(1)
        f.seek(20)
        f.write(bytes([byte[0] ^ 0xFF]))
    code, report = run_fsck(snap, repair=True)
    assert code == 1
    assert "journal-corrupt-record" in report.classes()
    assert not report.repaired  # corruption is never quarantined away


def test_journal_stale_fence_detected_and_repaired(tmp_path, monkeypatch):
    snap, jdir = _take_with_journal(tmp_path, monkeypatch)
    with open(os.path.join(jdir, ".fence"), "w") as f:
        f.write('{"gen": "dead", "epoch": 3}')
    code, report = run_fsck(snap)
    assert code == 1
    assert "stale-fence" in report.classes()
    code, report = run_fsck(snap, repair=True)
    assert code == 0, report.findings
    assert run_fsck(snap)[0] == 0
