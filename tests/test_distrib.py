"""Fleet distribution tier (distrib.py): digest-keyed chunk cache
semantics, seed-registry registration/retraction, the ghost-key rule on
peer death, content-address verification on fetch, and exactly-once
journal-epoch apply under duplicated rolling-update pushes (ISSUE 16).

The contracts under test:

- The chunk cache never diverges from the registry in the direction of
  advertising bytes it cannot serve: TTL expiry and byte-cap eviction
  report the evicted digests so the session retracts their rows.
- A restore that aborts retracts exactly the registrations it made
  (a partially-restored replica must not advertise chunks it may throw
  away), while earlier restores' registrations survive.
- A holder whose process dies without deregistering becomes a ghost:
  its death-notice key is up, fetchers skip it and lazily delete its
  rows — never a hang.
- A fetched chunk failing its content address (a corrupting peer) is
  rejected like a CRC failure and the fetcher re-parents; with no clean
  parent left, the chunk degrades to a direct storage read.
- An epoch push is applied exactly once per (gen, epoch): duplicated
  pushes (lost cursor, blind retry, overlapping pushers) are dup-acked
  and dropped; a corrupt push is nacked before any state mutates.
"""

import time

import numpy as np
import pytest

from torchsnapshot_tpu import (
    CheckpointManager,
    Snapshot,
    StateDict,
    distrib,
    faultinject,
)
from torchsnapshot_tpu.dist_store import (
    SEED_DEAD_PREFIX,
    TCPStore,
    seed_holder_rows,
)
from torchsnapshot_tpu.fanout import content_address, content_unit_id


@pytest.fixture
def registry():
    """One in-process store server + a client factory; the seed-session
    global is reset around each test so sessions never leak across."""
    server = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    port = server.port

    def client() -> TCPStore:
        return TCPStore("127.0.0.1", port, is_server=False, timeout=10.0)

    distrib.configure_registry(client)
    try:
        yield client
    finally:
        distrib.reset_session()
        distrib.configure_registry(None)
        faultinject.disable()
        server.close()


# ------------------------------------------------------------- chunk cache


def test_chunk_cache_ttl_expiry():
    cache = distrib.ChunkCache(ttl_s=0.05, cap_bytes=1 << 20)
    cache.put("sha256:a", b"x" * 64)
    assert cache.get("sha256:a") == b"x" * 64
    time.sleep(0.08)
    assert cache.get("sha256:a") is None
    assert cache.nbytes == 0


def test_chunk_cache_cap_eviction_reports_digests():
    cache = distrib.ChunkCache(ttl_s=60.0, cap_bytes=100)
    assert cache.put("sha256:a", b"a" * 40) == []
    assert cache.put("sha256:b", b"b" * 40) == []
    # Inserting c exceeds the cap: the LRU chunk (a) must be reported so
    # the session can retract its registry row.
    assert cache.put("sha256:c", b"c" * 40) == ["sha256:a"]
    assert cache.get("sha256:a") is None
    assert cache.get("sha256:b") is not None


def test_chunk_cache_hit_refreshes_lru_order():
    cache = distrib.ChunkCache(ttl_s=60.0, cap_bytes=100)
    cache.put("sha256:a", b"a" * 40)
    cache.put("sha256:b", b"b" * 40)
    cache.get("sha256:a")  # touch: b is now least-recent
    assert cache.put("sha256:c", b"c" * 40) == ["sha256:b"]
    assert cache.get("sha256:a") is not None


def test_chunk_cache_oversized_chunk_never_cached():
    cache = distrib.ChunkCache(ttl_s=60.0, cap_bytes=100)
    cache.put("sha256:big", b"x" * 200)
    assert cache.get("sha256:big") is None
    assert cache.nbytes == 0


# -------------------------------------------------------- content addressing


def test_content_address_is_device_digest_namespace():
    d = content_address(b"some chunk bytes")
    assert d.startswith("sha256:") and len(d) == 7 + 64
    assert d == content_address(bytearray(b"some chunk bytes"))
    assert d != content_address(b"other chunk bytes")


def test_content_unit_id_scope_rules():
    uid = content_unit_id("/snaps/step_5", "replicated/0/model.w", (0, 100))
    assert uid is not None and uid.startswith("sha256:")
    # Snapshot identity is part of the key: byte-identical requests
    # against different snapshots must never collide in the catalog.
    other = content_unit_id("/snaps/step_6", "replicated/0/model.w", (0, 100))
    assert other != uid
    assert content_unit_id("/s", "sharded/0/emb.0", (0, 10)) is not None
    # Per-rank and slab payloads are never shareable; zero-length moves
    # nothing.
    assert content_unit_id("/s", "0/model.w", (0, 100)) is None
    assert content_unit_id("/s", "batched/slab_0", (0, 100)) is None
    assert content_unit_id("/s", "replicated/0/model.w", (5, 5)) is None


# ----------------------------------------------------- registry + fetching


def test_publish_lookup_fetch_roundtrip(registry):
    payload = b"replicated-bytes" * 500
    uid = content_unit_id("/snap", "replicated/0/w", (0, len(payload)))
    s1 = distrib.SeedSession(registry(), holder_id="h1")
    s2 = distrib.SeedSession(registry(), holder_id="h2")
    try:
        digest = s1.publish(uid, payload, depth=0)
        assert s1.lookup(uid) == (digest, len(payload))
        got = s2.fetch(uid, digest, len(payload))
        assert got == payload
        # The fetcher registered itself one level below its parent.
        rows = seed_holder_rows(s2.store, digest)
        assert rows["h1"]["depth"] == 0
        assert rows["h2"]["depth"] == 1
    finally:
        s1.close()
        s2.close()


def test_fetch_with_no_holder_raises_seed_unavailable(registry):
    s = distrib.SeedSession(registry(), holder_id="lone")
    try:
        assert s.lookup("sha256:" + "0" * 64) is None
        with pytest.raises(distrib.SeedUnavailable):
            s.fetch("unit", "sha256:" + "0" * 64, 10)
    finally:
        s.close()


def test_fetch_rejects_corrupt_chunk_and_reparents(registry):
    """A corrupting seeder is caught by the receiver's content-address
    re-hash (the distrib.seed_xfer fault site corrupts the payload as it
    leaves the FIRST serving peer); the fetcher re-parents to the next
    holder and still delivers verified bytes."""
    payload = b"seeded-chunk" * 1000
    uid = content_unit_id("/snap", "replicated/0/w", (0, len(payload)))
    s1 = distrib.SeedSession(registry(), holder_id="h1")
    s2 = distrib.SeedSession(registry(), holder_id="h2")
    s3 = distrib.SeedSession(registry(), holder_id="h3")
    try:
        digest = s1.publish(uid, payload, depth=0)
        s2.publish(uid, payload, depth=0)
        # h1 is elected parent first (same depth, lower registration
        # seq); its one serve is corrupted.
        faultinject.configure("distrib.seed_xfer@1=corrupt")
        got = s3.fetch(uid, digest, len(payload))
        assert got == payload
        assert content_address(got) == digest
    finally:
        faultinject.disable()
        s1.close()
        s2.close()
        s3.close()


def test_ghost_key_rule_on_holder_death(registry):
    """A holder that dies without deregistering (store connection drops
    → its liveness death notice publishes) is skipped by fetchers and
    its rows are lazily retracted — the PR 7 health-plane pattern."""
    payload = b"ghost-chunk" * 800
    uid = content_unit_id("/snap", "replicated/0/w", (0, len(payload)))
    s1 = distrib.SeedSession(registry(), holder_id="alive")
    s2 = distrib.SeedSession(registry(), holder_id="doomed")
    try:
        digest = s1.publish(uid, payload, depth=0)
        s2.publish(uid, payload, depth=0)
        # Simulate death: the store connection drops WITHOUT a
        # deregister, publishing the death-notice key; the listener
        # socket stays up, so only liveness distinguishes dead from slow.
        s2.store.close()
        deadline = time.monotonic() + 10.0
        probe = registry()
        try:
            while time.monotonic() < deadline:
                if probe.check(f"{SEED_DEAD_PREFIX}doomed"):
                    break
                time.sleep(0.05)
            assert probe.check(f"{SEED_DEAD_PREFIX}doomed")
        finally:
            probe.close()
        s3 = distrib.SeedSession(registry(), holder_id="fresh")
        try:
            got = s3.fetch(uid, digest, len(payload))
            assert got == payload
            rows = seed_holder_rows(s3.store, digest)
            assert "doomed" not in rows  # lazily retracted
            assert "alive" in rows and "fresh" in rows
        finally:
            s3.close()
    finally:
        s1.close()
        s2._listener.close()  # the store is already gone; just the socket


def test_eviction_retracts_registry_row(registry):
    """Cap eviction must retract the evicted digest's holder row — the
    registry never advertises bytes the cache can no longer serve."""
    s = distrib.SeedSession(registry(), holder_id="tiny")
    s.cache = distrib.ChunkCache(ttl_s=60.0, cap_bytes=100)
    try:
        uid_a = content_unit_id("/snap", "replicated/0/a", (0, 40))
        uid_b = content_unit_id("/snap", "replicated/0/b", (0, 40))
        uid_c = content_unit_id("/snap", "replicated/0/c", (0, 40))
        da = s.publish(uid_a, b"a" * 40, depth=0)
        s.publish(uid_b, b"b" * 40, depth=0)
        s.publish(uid_c, b"c" * 40, depth=0)  # evicts a
        assert seed_holder_rows(s.store, da) == {}
        assert s.cache.get(da) is None
    finally:
        s.close()


# ------------------------------------------- restore-path registration


class _BoomStateful:
    """state_dict works (take succeeds); load_state_dict raises (restore
    aborts after its payloads were read — and seeded)."""

    def __init__(self, arr):
        self.arr = arr

    def state_dict(self):
        return {"w": self.arr}

    def load_state_dict(self, sd):
        raise RuntimeError("injected load failure")


def test_restore_abort_retracts_this_restores_registrations(
    registry, tmp_path, monkeypatch
):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SEED_RESTORE", "always")
    arr = np.arange(1 << 14, dtype=np.float32)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": _BoomStateful(arr)}, replicated=["**"])
    with pytest.raises(RuntimeError, match="injected load failure"):
        Snapshot(path).restore({"app": _BoomStateful(arr.copy())})
    sess = distrib.session()
    assert sess is not None
    # Every row this (aborted) restore registered is gone again: a
    # partially-restored replica must not advertise chunks it may be
    # about to throw away.
    assert sess._registered == {}
    assert sess.cache.nbytes == 0


def test_seeded_restore_roundtrip_and_second_restore_hits_cache(
    registry, tmp_path, monkeypatch
):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SEED_RESTORE", "always")
    st = StateDict(w=np.arange(1 << 14, dtype=np.float32), step=7)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": st}, replicated=["**"])
    dst = StateDict(w=np.zeros(1 << 14, dtype=np.float32), step=0)
    Snapshot(path).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], st["w"])
    sess = distrib.session()
    assert sess is not None and len(sess._registered) > 0
    # The session persists past the restore: a second restore sources
    # its shareable chunks from the local cache, not storage.
    hits_before = sess.cache.nbytes
    dst2 = StateDict(w=np.zeros(1 << 14, dtype=np.float32), step=0)
    Snapshot(path).restore({"app": dst2})
    np.testing.assert_array_equal(dst2["w"], st["w"])
    assert sess.cache.nbytes == hits_before


def test_seed_restore_defaults_off(monkeypatch):
    """Unset, the seeding tier is one env check: maybe_wrap_restore
    returns the storage untouched and no session is created."""
    distrib.reset_session()
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_SEED_RESTORE", raising=False)
    sentinel = object()
    wrapped, tier = distrib.maybe_wrap_restore(sentinel, "/p", None)
    assert wrapped is sentinel and tier is None


def test_seed_restore_mode_parser(monkeypatch):
    assert distrib.seed_restore_mode() == "never"
    for raw, want in (
        ("always", "always"), ("1", "always"), ("force", "always"),
        ("auto", "auto"), ("governor", "auto"),
        ("never", "never"), ("0", "never"), ("junk", "never"),
    ):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_SEED_RESTORE", raw)
        assert distrib.seed_restore_mode() == want, raw


# ------------------------------------------------------- rolling updates


def _state(v: float) -> StateDict:
    return StateDict(
        w=np.arange(512, dtype=np.float32) + v,
        b=np.full((32,), v, np.float64),
        step=int(v),
    )


@pytest.fixture
def journaling(monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")


def test_exactly_once_epoch_apply_under_duplicated_push(
    registry, tmp_path, journaling
):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0.0)
    mgr.save(0, {"app": st})
    mgr.wait()
    replica = {"app": _state(0.0)}
    rx = distrib.UpdateReceiver(registry(), replica, base_step=0)
    try:
        st["w"] = st["w"] + 1.0
        st["step"] = 1
        assert mgr.journal_step(1, {"app": st})
        out = mgr.push_update()
        assert out == {"replicas": 1, "epochs": 1, "bytes": out["bytes"],
                       "nacks": 0}
        assert out["bytes"] > 0
        st["b"] = st["b"] + 2.0
        st["step"] = 2
        assert mgr.journal_step(2, {"app": st})
        assert mgr.push_update()["epochs"] == 1  # cursor: only the new epoch
        np.testing.assert_array_equal(replica["app"]["w"], st["w"])
        np.testing.assert_array_equal(replica["app"]["b"], st["b"])
        assert replica["app"]["step"] == 2
        assert rx.epochs_applied == 2
        # A lost cursor replays everything; the receiver dup-acks and
        # applies nothing twice.
        mgr._push_cursor.clear()
        replay = mgr.push_update()
        assert replay["epochs"] == 2 and replay["nacks"] == 0
        assert rx.epochs_applied == 2  # exactly once
    finally:
        rx.close()


def test_corrupt_epoch_push_is_nacked_before_apply(
    registry, tmp_path, journaling
):
    """A corrupted push frame (the distrib.epoch_push fault site) fails
    the receiver's record CRCs and is nacked; no state mutates. With the
    fault cleared, the push converges (the nacked epoch's cursor never
    advanced)."""
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0.0)
    mgr.save(0, {"app": st})
    mgr.wait()
    replica = {"app": _state(0.0)}
    rx = distrib.UpdateReceiver(registry(), replica, base_step=0)
    try:
        st["w"] = st["w"] + 5.0
        assert mgr.journal_step(1, {"app": st})
        faultinject.configure("distrib.epoch_push@1=corrupt")
        try:
            out = mgr.push_update()
        finally:
            faultinject.disable()
        assert out["nacks"] == 1 and out["epochs"] == 0
        np.testing.assert_array_equal(
            replica["app"]["w"], _state(0.0)["w"]
        )  # nothing applied
        out2 = mgr.push_update()
        assert out2["epochs"] == 1 and out2["nacks"] == 0
        np.testing.assert_array_equal(replica["app"]["w"], st["w"])
        assert rx.epochs_applied == 1
    finally:
        rx.close()


def test_push_update_without_receivers_is_empty(registry, tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0.0)
    mgr.save(0, {"app": st})
    mgr.wait()
    st["w"] = st["w"] + 1.0
    assert mgr.journal_step(1, {"app": st})
    assert mgr.push_update() == {
        "replicas": 0, "epochs": 0, "bytes": 0, "nacks": 0,
    }


def test_push_update_unarmed_journal_is_empty(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.push_update() == {
        "replicas": 0, "epochs": 0, "bytes": 0, "nacks": 0,
    }


def test_dead_receiver_is_skipped_by_death_notice(
    registry, tmp_path, journaling
):
    """A registered update receiver whose process died (ghost-key rule)
    is skipped entirely — the push neither hangs nor counts it."""
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0.0)
    mgr.save(0, {"app": st})
    mgr.wait()
    rx = distrib.UpdateReceiver(registry(), {"app": _state(0.0)}, base_step=0)
    rx.store.close()  # dies without deregistering → death notice
    deadline = time.monotonic() + 10.0
    probe = registry()
    try:
        while time.monotonic() < deadline:
            if probe.check(f"{SEED_DEAD_PREFIX}{rx.holder_id}"):
                break
            time.sleep(0.05)
        assert distrib.live_update_targets(probe, 0) == {}
    finally:
        probe.close()
        rx._listener.close()
    st["w"] = st["w"] + 1.0
    assert mgr.journal_step(1, {"app": st})
    assert mgr.push_update()["replicas"] == 0
