"""The flagship full-system elasticity drill (round-4 verdict item 7).

One story end to end, composing every production feature at once:
CheckpointManager (cadence + retention + resume) + incremental dedup +
zstd compression + mirrored two-tier storage, across REAL
``jax.distributed`` world-size changes:

1. world=8 trains steps 0-2, checkpointing each (step 1 and 2 chain
   incrementally against their predecessors), then the job "dies".
2. world=4 resumes from the latest committed step, verifies the restored
   state bit-exactly against the oracle, trains step 3, saves it
   (chained against the RESTORED step — manager.restore seeds the
   chain), and dies.
3. world=16 resumes from step 3, reading transparently through the
   incremental chain 3→2→1→0, and verifies bit-exactness again.

Afterwards the single-process checks: `cli verify` passes on the final
snapshot (checksums + chain closure), and each step's PER-STEP mirror
replica restores independently after the primary tier is destroyed —
total-primary-loss recovery.

Elasticity rules seam: /root/reference/torchsnapshot/snapshot.py:112-155
(world-size flexibility); this drill exercises them across three worlds
with genuinely non-addressable shards (one CPU device per process).
"""

from __future__ import annotations

import os
import shutil

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

pytestmark = [pytest.mark.multiprocess, pytest.mark.slow]

ROWS, COLS = 16, 8  # divisible by 8, 4, and 16 ranks


def _init_jax_dist(rank: int, world_size: int, port: int):
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    return jax


def _oracle(step: int) -> np.ndarray:
    # Value of the "weights" after `step` completed training steps.
    return np.arange(ROWS * COLS, dtype=np.float32).reshape(ROWS, COLS) + step


def _assert_local_shards_equal(arr, expected: np.ndarray) -> None:
    # device_get of a non-fully-addressable array is invalid; each process
    # verifies the shards it owns (together the worlds cover every row).
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), expected[shard.index])


def _make_sharded(jax, values: np.ndarray):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))
    return jax.make_array_from_callback(
        values.shape, NamedSharding(mesh, P("x", None)), lambda idx: values[idx]
    )


def _manager(root: str, mirror: str):
    from torchsnapshot_tpu import CheckpointManager
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    return CheckpointManager(
        root,
        incremental=True,
        compression="zstd:3",
        keep_every=1,  # archival: the drill inspects every step afterwards
        # step and the frozen backbone are identical on every rank and
        # must stay restorable on ranks beyond the saving world (per-rank
        # entries are owner-only under the elasticity rules).
        replicated=["train/step", "train/frozen"],
        storage_options={"mirror_url": mirror},
        pg=get_default_pg(),
    )


# Constant across steps AND worlds: every incremental save deduplicates it
# against the previous step, so the drill genuinely reads through the
# origin chain 3->2->1->0 at restore time.
def _frozen() -> np.ndarray:
    return np.linspace(0.0, 1.0, 4096, dtype=np.float32)


def _phase_a_worker(rank, world_size, root, mirror, port):
    """world=8: train steps 0..2, checkpoint each, die."""
    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import StateDict

    mgr = _manager(root, mirror)
    for step in range(3):
        w = _make_sharded(jax, _oracle(step))  # weights after `step` steps
        state = {"train": StateDict(w=w, step=step, frozen=_frozen())}
        assert mgr.save(step, state) is True
    return "ok"


def _phase_b_worker(rank, world_size, root, mirror, port):
    """world=4: resume latest, verify, train one step, save, die."""
    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import StateDict

    mgr = _manager(root, mirror)
    latest = mgr.latest_step()
    assert latest == 2, latest
    dst = {"train": StateDict(w=_make_sharded(jax, np.zeros((ROWS, COLS), np.float32)), step=-1, frozen=np.zeros(4096, np.float32))}
    assert mgr.restore(dst) == 2
    _assert_local_shards_equal(dst["train"]["w"], _oracle(2))
    assert dst["train"]["step"] == 2
    np.testing.assert_array_equal(dst["train"]["frozen"], _frozen())

    w = _make_sharded(jax, _oracle(3))  # step 3 of training
    state = {"train": StateDict(w=w, step=3, frozen=_frozen())}
    assert mgr.save(3, state) is True
    return "ok"


def _phase_c_worker(rank, world_size, root, mirror, port):
    """world=16: resume step 3 through the incremental chain, verify."""
    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import StateDict

    mgr = _manager(root, mirror)
    assert mgr.latest_step() == 3
    dst = {"train": StateDict(w=_make_sharded(jax, np.zeros((ROWS, COLS), np.float32)), step=-1, frozen=np.zeros(4096, np.float32))}
    assert mgr.restore(dst) == 3
    _assert_local_shards_equal(dst["train"]["w"], _oracle(3))
    assert dst["train"]["step"] == 3
    # frozen was never re-written after step 0: this read followed the
    # recorded origin to step 0's payload.
    np.testing.assert_array_equal(dst["train"]["frozen"], _frozen())
    # A re-save of the restored step must be skipped on EVERY rank.
    assert mgr.save(3, dst) is False
    return "ok"


def test_elasticity_drill_8_to_4_to_16(tmp_path) -> None:
    root = str(tmp_path / "primary")
    mirror = f"fs://{tmp_path}/mirror"

    for world, worker, timeout in (
        (8, _phase_a_worker, 420),
        (4, _phase_b_worker, 300),
        (16, _phase_c_worker, 600),
    ):
        port = _find_free_port()
        results = run_with_subprocesses(
            worker, world, root, mirror, port, timeout=timeout
        )
        assert all(v == "ok" for v in results.values()), (world, results)

    steps = sorted(os.listdir(root))
    assert steps == [f"step_{i:010d}" for i in range(4)]

    # Chain integrity: cli verify checks every checksum, reading dedup'd
    # payloads through their origin snapshots.
    from torchsnapshot_tpu.cli import main as cli_main

    assert cli_main(["verify", os.path.join(root, "step_0000000003")]) == 0

    # Incremental actually elided bytes: each step's manifest records
    # (transitive) origins for the unchanged frozen entry.
    assert cli_main(["deps", root]) == 0
    from torchsnapshot_tpu.cli import _entry_payloads
    from torchsnapshot_tpu.manifest import SnapshotMetadata

    for step in (1, 2, 3):
        with open(
            os.path.join(root, f"step_{step:010d}", ".snapshot_metadata")
        ) as f:
            meta = SnapshotMetadata.from_yaml(f.read())
        origins = {
            origin
            for e in meta.manifest.values()
            for _, _, _, _, origin in _entry_payloads(e)
            if origin
        }
        # Origins are TRANSITIVE: they name the snapshot physically
        # holding the bytes — frozen was only ever written at step 0.
        assert origins and all(
            o.endswith("step_0000000000") for o in origins
        ), (step, origins)

    # Total primary loss: every step's PER-STEP mirror replica restores
    # on its own (virtual mesh, single process).
    shutil.rmtree(root)
    import jax

    from torchsnapshot_tpu import Snapshot, StateDict

    for step in (0, 3):
        mdir = f"{tmp_path}/mirror/step_{step:010d}"
        assert os.path.isfile(os.path.join(mdir, ".snapshot_metadata")), mdir
        dst = {"train": StateDict(w=np.zeros((ROWS, COLS), np.float32), step=-1, frozen=np.zeros(4096, np.float32))}
        Snapshot(mdir).restore(dst)
        np.testing.assert_array_equal(dst["train"]["w"], _oracle(step))
        assert dst["train"]["step"] == step
        np.testing.assert_array_equal(dst["train"]["frozen"], _frozen())
