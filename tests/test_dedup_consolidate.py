"""``dedup.consolidate`` over snapshots carrying delta-journal epochs
(journal.py): compaction folds the final committed value of every
journaled leaf into the destination payloads, the destination carries no
journal, its integrity fields agree with the new bytes (fsck-clean), and
incremental origin chains keep resolving — including through a base's
mirror tier. Unfoldable journals raise instead of silently dropping
committed state.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict
from torchsnapshot_tpu.cli import run_fsck
from torchsnapshot_tpu.dedup import consolidate


@pytest.fixture
def journaling(monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")


def _journaled_base(root, epochs=2, **mgr_kwargs):
    """A committed base snapshot plus ``epochs`` journal epochs touching an
    array, a scalar, and a string. Returns (snapshot_path, live_state)."""
    mgr = CheckpointManager(str(root), save_interval_steps=100, **mgr_kwargs)
    st = StateDict(
        w=np.arange(1024, dtype=np.float32),
        b=np.full((32,), 0.0, np.float64),
        step=0,
        name="run-0",
    )
    mgr.save(0, {"app": st})
    for epoch in range(1, epochs + 1):
        st["w"] = np.arange(1024, dtype=np.float32) + epoch
        st["step"] = epoch
        st["name"] = f"run-{epoch}"
        assert mgr.journal_step(epoch, {"app": st})
    return mgr.path_for(0), st


def _restore(path):
    dst = StateDict(
        w=np.zeros(1024, np.float32),
        b=np.ones((32,), np.float64),
        step=-1,
        name="",
    )
    Snapshot(str(path)).restore({"app": dst})
    return dst


def test_consolidate_folds_journal_epochs(tmp_path, journaling):
    src, live = _journaled_base(tmp_path / "root", epochs=3)
    dst = str(tmp_path / "flat")
    consolidate(src, dst)

    # The destination is journal-free and self-contained...
    assert not os.path.isdir(os.path.join(dst, ".journal"))
    code, report = run_fsck(dst)
    assert code == 0, report.findings

    # ...and equals base + replay, bit-exact, across entry types:
    # chunked array, primitive scalar, primitive string.
    out = _restore(dst)
    np.testing.assert_array_equal(out["w"], live["w"])
    np.testing.assert_array_equal(out["b"], live["b"])
    assert out["step"] == live["step"] == 3
    assert out["name"] == live["name"] == "run-3"

    # The source (base + journal) restores to the same state.
    srcout = _restore(src)
    np.testing.assert_array_equal(srcout["w"], out["w"])
    assert srcout["step"] == out["step"]


def test_consolidate_without_journal_unchanged(tmp_path):
    """No journal present: consolidation behaves exactly as before."""
    src = str(tmp_path / "snap")
    Snapshot.take(src, {"app": StateDict(w=np.arange(64, dtype=np.float32))})
    dst = str(tmp_path / "flat")
    consolidate(src, dst)
    assert run_fsck(dst)[0] == 0
    out = StateDict(w=np.zeros(64, np.float32))
    Snapshot(dst).restore({"app": out})
    np.testing.assert_array_equal(out["w"], np.arange(64, dtype=np.float32))


def test_consolidate_incremental_chain_with_journal(tmp_path, journaling):
    """An incremental child whose payloads dedup against a base, PLUS a
    journal on the child: consolidation must both resolve the origin deps
    and fold the journal."""
    mgr = CheckpointManager(
        str(tmp_path / "root"), save_interval_steps=1, incremental=True
    )
    frozen = np.arange(4096, dtype=np.float32)
    st = StateDict(frozen=frozen, head=np.full((64,), 0.0, np.float32), step=0)
    mgr.save(0, {"app": st})
    st["head"] = np.full((64,), 1.0, np.float32)
    st["step"] = 1
    mgr.save(1, {"app": st})  # frozen dedups against step 0
    st["head"] = np.full((64,), 2.0, np.float32)
    st["step"] = 2
    assert mgr.journal_step(2, {"app": st})

    dst = str(tmp_path / "flat")
    consolidate(mgr.path_for(1), dst)
    assert run_fsck(dst)[0] == 0, "consolidated chain must be self-contained"

    import shutil

    shutil.rmtree(mgr.path_for(0))  # base gone: dst must not need it
    out = StateDict(
        frozen=np.zeros(4096, np.float32),
        head=np.zeros(64, np.float32),
        step=-1,
    )
    Snapshot(dst).restore({"app": out})
    np.testing.assert_array_equal(out["frozen"], frozen)
    np.testing.assert_array_equal(out["head"], np.full((64,), 2.0, np.float32))
    assert out["step"] == 2


def test_consolidate_reads_origin_through_mirror(tmp_path, journaling):
    """Origin-mirror-aware compaction: the base's primary payload is lost
    but its mirror is intact — consolidating a journaled child still
    succeeds (the same failover the restore path uses)."""
    base = str(tmp_path / "base")
    opts = {"mirror_url": str(tmp_path / "mirror")}
    frozen = np.arange(4096, dtype=np.float32)
    Snapshot.take(
        base,
        {"app": StateDict(frozen=frozen, head=np.zeros(8, np.float32))},
        storage_options=opts,
        record_digests=True,
    )
    inc = str(tmp_path / "inc")
    Snapshot.take(
        inc,
        {"app": StateDict(frozen=frozen, head=np.ones(8, np.float32))},
        incremental_base=base,
        record_digests=True,
    )
    # Journal an epoch on the incremental child.
    from torchsnapshot_tpu import journal

    st = StateDict(frozen=frozen, head=np.full((8,), 5.0, np.float32))
    j = journal.DeltaJournal(inc, base_step=0, rank=0)
    j.capture_baseline({"app": StateDict(frozen=frozen, head=np.ones(8, np.float32))})
    assert j.append_epoch({"app": st}) == 1

    # Lose the base's primary copy of a frozen payload.
    lost = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(os.path.join(base, "0"))
        for f in fs
        if "frozen" in f
    ]
    assert lost
    os.remove(lost[0])

    dst = str(tmp_path / "flat")
    consolidate(inc, dst)
    assert run_fsck(dst)[0] == 0
    out = StateDict(frozen=np.zeros(4096, np.float32), head=np.zeros(8, np.float32))
    Snapshot(dst).restore({"app": out})
    np.testing.assert_array_equal(out["frozen"], frozen)
    np.testing.assert_array_equal(out["head"], np.full((8,), 5.0, np.float32))


def test_consolidate_refuses_corrupt_journal(tmp_path, journaling):
    """A journal whose committed region fails CRC must abort consolidation
    with a diagnosis pointing at fsck — never silently drop the epochs."""
    src, _ = _journaled_base(tmp_path / "root")
    seg = os.path.join(src, ".journal", "rank_0.seg")
    with open(seg, "r+b") as f:
        f.seek(16)
        byte = f.read(1)
        f.seek(16)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(ValueError, match="fsck"):
        consolidate(src, str(tmp_path / "flat"))


def test_consolidate_refuses_new_leaf_in_journal(tmp_path, journaling):
    """A journaled key absent from the base manifest (state grew a leaf
    between base and epoch) cannot be folded — explicit refusal."""
    src, _ = _journaled_base(tmp_path / "root", epochs=1)
    from torchsnapshot_tpu import journal

    jdir = os.path.join(src, ".journal")
    committed = journal.committed_epochs(journal.read_epoch_metas(jdir))
    gen = committed[-1]["gen"]
    fields, payload = journal._serialize_leaf(123, "object")
    header = {"v": 1, "gen": gen, "epoch": 1, "key": "app/brand_new"}
    header.update(fields)
    seg = os.path.join(jdir, journal.segment_name(0))
    with open(seg, "ab") as f:
        f.write(journal.encode_record(header, payload))
    # Extend the committed offset over the forged record.
    import json

    meta_path = os.path.join(jdir, journal.epoch_meta_name(1))
    with open(meta_path) as f:
        meta = json.load(f)
    meta["offsets"]["0"] = os.path.getsize(seg)
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    with pytest.raises(ValueError, match="restore and retake"):
        consolidate(src, str(tmp_path / "flat"))


def test_consolidated_journal_snapshot_serves_as_base(tmp_path, journaling):
    """Chain-dep integrity after compaction: the consolidated snapshot's
    digests reflect the FOLDED content, so it works as a future
    incremental base without false dedup hits."""
    src, live = _journaled_base(
        tmp_path / "root", epochs=2, incremental=True
    )
    dst = str(tmp_path / "flat")
    consolidate(src, dst)

    nxt = str(tmp_path / "next")
    Snapshot.take(
        nxt,
        {
            "app": StateDict(
                w=np.asarray(live["w"]),  # unchanged vs folded dst
                b=np.asarray(live["b"]),
                step=live["step"],
                name=live["name"],
            )
        },
        incremental_base=dst,
        record_digests=True,
    )
    out = _restore(nxt)
    np.testing.assert_array_equal(out["w"], live["w"])
    assert out["step"] == live["step"]
    assert run_fsck(nxt)[0] == 0
