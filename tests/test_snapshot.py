"""End-to-end take/restore round trips (reference: tests/test_snapshot.py,
examples/simple_example.py). Round-trip equality is the universal oracle."""

import os
from collections import OrderedDict

import numpy as np
import pytest

from torchsnapshot_tpu import RNGState, Snapshot, StateDict
from torchsnapshot_tpu.test_utils import assert_state_dict_eq, check_state_dict_eq


def _jax():
    import jax

    return jax


def _make_model_state(seed: int = 0):
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(k, 3)
    params = {
        "dense1": {
            "kernel": jax.random.normal(k1, (16, 32), dtype=jnp.float32),
            "bias": jnp.zeros((32,), dtype=jnp.float32),
        },
        "dense2": {
            "kernel": jax.random.normal(k2, (32, 8), dtype=jnp.bfloat16),
            "bias": jnp.ones((8,), dtype=jnp.bfloat16),
        },
        "embedding": jax.random.normal(k3, (64, 16)),
    }
    return params


def test_take_restore_roundtrip(tmp_path) -> None:
    jax = _jax()
    params = _make_model_state(0)
    app_state = {"model": StateDict(params=params, step=17, lr=1e-3)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)

    # perturb
    perturbed = _make_model_state(1)
    dst = StateDict(params=perturbed, step=0, lr=0.5)
    snapshot.restore({"model": dst})

    assert dst["step"] == 17
    assert dst["lr"] == 1e-3
    assert_state_dict_eq(None, jax.tree.map(np.asarray, dst["params"]),
                         jax.tree.map(np.asarray, params))
    # restored arrays are jax.Arrays with the destination's sharding
    assert isinstance(dst["params"]["dense1"]["kernel"], jax.Array)
    assert dst["params"]["dense2"]["kernel"].dtype == params["dense2"]["kernel"].dtype


def test_optimizer_state_roundtrip(tmp_path) -> None:
    import jax
    import jax.numpy as jnp
    import optax

    params = _make_model_state(0)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    # advance one step so moments are non-trivial
    grads = jax.tree.map(jnp.ones_like, params)
    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)

    app_state = {
        "model": StateDict(params=params),
        "optim": StateDict(state=opt_state),
    }
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)

    fresh_state = opt.init(_make_model_state(2))
    dst_optim = StateDict(state=fresh_state)
    snapshot.restore({"optim": dst_optim})

    restored = dst_optim["state"]
    # the restored state must drive optax updates again
    opt.update(grads, restored, params)
    flat_a = jax.tree.leaves(restored)
    flat_b = jax.tree.leaves(opt_state)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_numpy_and_primitives(tmp_path) -> None:
    app_state = {
        "misc": StateDict(
            np_arr=np.arange(100, dtype=np.int64).reshape(10, 10),
            count=42,
            name="experiment-7",
            ratio=0.1 + 0.2,
            flag=True,
            blob=b"\x00\x01\xff",
            nothing=None,
            nested={"a": [1, 2, {"b": np.ones(3)}], "t": (4, 5)},
        )
    }
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    dst = StateDict(
        np_arr=np.zeros((10, 10), dtype=np.int64),
        count=0,
        name="",
        ratio=0.0,
        flag=False,
        blob=b"",
        nothing="something",
        nested={"a": [0, 0, {"b": np.zeros(3)}], "t": (0, 0)},
    )
    snapshot.restore({"misc": dst})
    assert dst["count"] == 42
    assert dst["name"] == "experiment-7"
    assert dst["ratio"] == 0.1 + 0.2
    assert dst["flag"] is True
    assert dst["blob"] == b"\x00\x01\xff"
    assert dst["nothing"] is None
    np.testing.assert_array_equal(dst["np_arr"], app_state["misc"]["np_arr"])
    assert dst["nested"]["t"] == (4, 5)
    np.testing.assert_array_equal(dst["nested"]["a"][2]["b"], np.ones(3))


class Custom:
    def __init__(self, x):
        self.x = x

    def __eq__(self, other):
        return isinstance(other, Custom) and self.x == other.x


def test_arbitrary_object_roundtrip(tmp_path) -> None:
    app_state = {"s": StateDict(obj=Custom([1, 2, 3]), d={"inner": Custom("y")})}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    dst = StateDict(obj=Custom(None), d={"inner": Custom(None)})
    snapshot.restore({"s": dst})
    assert dst["obj"] == Custom([1, 2, 3])
    assert dst["d"]["inner"] == Custom("y")


def test_rng_state_invariant(tmp_path) -> None:
    """Taking a snapshot must not perturb the RNG stream, and restoring must
    reproduce it (reference: tests/test_rng_state.py:26)."""
    np.random.seed(123)
    app_state = {"rng": RNGState()}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    expected = np.random.rand(4)  # stream after take == stream without take

    np.random.seed(999)  # diverge
    snapshot.restore({"rng": RNGState()})
    actual = np.random.rand(4)
    np.testing.assert_array_equal(actual, expected)


def test_metadata_and_manifest(tmp_path) -> None:
    app_state = {"m": StateDict(w=np.ones((4, 4), dtype=np.float32), step=3)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    manifest = snapshot.get_manifest()
    assert "0/m/w" in manifest
    assert "0/m/step" in manifest
    # a fresh handle reads metadata from storage
    reopened = Snapshot(str(tmp_path / "snap"))
    assert set(reopened.get_manifest()) == set(manifest)
    assert reopened.metadata.world_size == 1
    # commit point: metadata file exists
    assert (tmp_path / "snap" / ".snapshot_metadata").exists()


def test_read_object(tmp_path) -> None:
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    app_state = {"m": StateDict(w=arr, step=3, tag="hello")}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)

    assert snapshot.read_object("0/m/step") == 3
    assert snapshot.read_object("0/m/tag") == "hello"
    out = snapshot.read_object("0/m/w")
    np.testing.assert_array_equal(out, arr)
    # in-place destination
    dst = np.zeros((8, 8), dtype=np.float32)
    ret = snapshot.read_object("0/m/w", obj_out=dst)
    np.testing.assert_array_equal(dst, arr)
    # with a small memory budget (chunked byte-range reads)
    out2 = snapshot.read_object("0/m/w", memory_budget_bytes=64)
    np.testing.assert_array_equal(out2, arr)


def test_read_object_invalid_path(tmp_path) -> None:
    app_state = {"m": StateDict(x=1)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    with pytest.raises(RuntimeError, match="not a valid entry"):
        snapshot.read_object("0/m/nope")
    with pytest.raises(RuntimeError, match="RANK/logical/path"):
        snapshot.read_object("m")


def test_restore_missing_entry_error(tmp_path) -> None:
    app_state = {"m": StateDict(x=1)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    with pytest.raises(RuntimeError, match="Unable to find entry"):
        snapshot.restore({"m": StateDict(x=1, extra=np.ones(3))})


def test_non_stateful_rejected(tmp_path) -> None:
    with pytest.raises(TypeError, match="StateDict"):
        Snapshot.take(str(tmp_path / "snap"), {"raw": {"a": 1}})


def test_take_twice_same_path(tmp_path) -> None:
    app_state = {"m": StateDict(step=1)}
    Snapshot.take(str(tmp_path / "snap"), app_state)
    app_state["m"]["step"] = 2
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    dst = StateDict(step=0)
    snapshot.restore({"m": dst})
    assert dst["step"] == 2


def test_bf16_bit_exact(tmp_path) -> None:
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(0).standard_normal((33, 7)), dtype=jnp.bfloat16)
    app_state = {"m": StateDict(x=x)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
    dst = StateDict(x=jnp.zeros((33, 7), dtype=jnp.bfloat16))
    snapshot.restore({"m": dst})
    assert np.asarray(dst["x"]).tobytes() == np.asarray(x).tobytes()


def test_storage_layout(tmp_path) -> None:
    """Entries land under <rank>/ per the layout rule (io_preparer.py:792-798)."""
    app_state = {"m": StateDict(w=np.ones((4, 4), dtype=np.float32))}
    Snapshot.take(str(tmp_path / "snap"), app_state)
    files = {
        os.path.relpath(os.path.join(dp, f), tmp_path / "snap")
        for dp, _, fs in os.walk(tmp_path / "snap")
        for f in fs
    }
    assert ".snapshot_metadata" in files
    assert any(f.startswith("0/m/w") for f in files)


def test_phase_timer_logs(tmp_path, caplog) -> None:
    """take/restore emit a one-line phase-duration summary at INFO."""
    import logging

    app_state = {"m": StateDict(w=np.ones((16, 16), dtype=np.float32))}
    with caplog.at_level(logging.INFO, logger="torchsnapshot_tpu.snapshot"):
        snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)
        snapshot.restore({"m": StateDict(w=np.zeros((16, 16), dtype=np.float32))})
    take_lines = [r.message for r in caplog.records if "Snapshot.take" in r.message]
    restore_lines = [r.message for r in caplog.records if "Snapshot.restore" in r.message]
    assert take_lines and all(
        p in take_lines[0] for p in ("materialize=", "stage=", "io_drain=", "commit=")
    )
    assert restore_lines and "load=" in restore_lines[0]


def test_kitchen_sink_all_features(tmp_path, monkeypatch) -> None:
    """Everything on at once: batching, checksums+verification, sharded +
    replicated-jax + object + primitive entries, async_take, restore into a
    DIFFERENT sharding. Features must compose, not just pass alone."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_CHECKSUM", "1")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_VERIFY", "1")

    devs = np.array(jax.devices()[:8])
    mesh_row = Mesh(devs.reshape(8), ("x",))
    mesh_2d = Mesh(devs.reshape(4, 2), ("x", "y"))
    data = np.random.default_rng(0).standard_normal((16, 24)).astype(np.float32)
    sharded = jax.device_put(jnp.asarray(data), NamedSharding(mesh_row, P("x", None)))
    repl = jax.device_put(
        jnp.arange(64, dtype=jnp.float32), NamedSharding(mesh_row, P(None))
    )
    app_state = {
        "m": StateDict(
            emb=sharded,
            repl=repl,
            blob={"nested": [1, 2.5, "three"]},
            step=7,
            name="ckpt",
        )
    }
    pending = Snapshot.async_take(str(tmp_path / "snap"), app_state)
    snapshot = pending.wait()

    # Destination mirrors the saved structure (restore is into-structure, as
    # in the reference); a leaf where the snapshot has a container raises a
    # structure-mismatch error — asserted at the end.
    dst = StateDict(
        emb=jax.device_put(
            jnp.zeros((16, 24), jnp.float32), NamedSharding(mesh_2d, P("x", "y"))
        ),
        repl=jnp.zeros(64, jnp.float32),
        blob={"nested": [0, 0.0, ""]},
        step=0,
        name="",
    )
    snapshot.restore({"m": dst})
    np.testing.assert_array_equal(np.asarray(dst["emb"]), data)
    assert dst["emb"].sharding.is_equivalent_to(
        NamedSharding(mesh_2d, P("x", "y")), 2
    )
    np.testing.assert_array_equal(
        np.asarray(dst["repl"]), np.arange(64, dtype=np.float32)
    )
    assert dst["blob"] == {"nested": [1, 2.5, "three"]}
    assert dst["step"] == 7 and dst["name"] == "ckpt"

    bad = StateDict(blob=None)  # leaf where the snapshot saved a container
    with pytest.raises(RuntimeError, match="Structure mismatch"):
        snapshot.restore({"m": bad})


def test_auto_replication_detection(monkeypatch) -> None:
    """A fully-replicated multi-process jax.Array is auto-detected as
    replicated (the DDP-auto-detect analogue); sharded or single-process
    arrays are not."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.snapshot import _is_process_replicated_jax_array

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs.reshape(4), ("x",))
    repl = jax.device_put(jnp.ones(8), NamedSharding(mesh, P(None)))
    shard = jax.device_put(jnp.ones(8), NamedSharding(mesh, P("x")))

    # Single-process: never auto-replicated (each process is the world).
    assert not _is_process_replicated_jax_array(repl)
    # Simulate a 4-process world where the mesh spans all processes.
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(
        type(next(iter(repl.sharding.device_set))),
        "process_index",
        property(lambda d: d.id),
        raising=False,
    )
    assert _is_process_replicated_jax_array(repl)
    assert not _is_process_replicated_jax_array(shard)  # not fully replicated
    assert not _is_process_replicated_jax_array(np.ones(8))  # not a jax array
