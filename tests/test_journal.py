"""Delta journal (journal.py): sub-second RPO between full snapshots.

The contract under test (ISSUE 14): ``journal_step`` appends only the
leaves that changed since the last durable state, as fenced, CRC32C'd,
generation-stamped records; restore is base + bounded replay of the
committed epoch chain; a torn tail is truncated and never replayed; a
corrupt committed record rejects the whole journal and falls back to the
base snapshot (never a partial splice); the configured bounds convert a
journal step into a full save; preemption flushes the open journal
instead of taking a synchronous full emergency save.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, StateDict
from torchsnapshot_tpu import journal


@pytest.fixture
def journaling(monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")


def _state(v: float) -> StateDict:
    return StateDict(
        w=np.arange(512, dtype=np.float32) + v,
        b=np.full((32,), v, np.float64),
        step=int(v),
        name=f"run-{int(v)}",
    )


def _assert_state(dst: StateDict, v: float) -> None:
    np.testing.assert_array_equal(
        dst["w"], np.arange(512, dtype=np.float32) + v
    )
    np.testing.assert_array_equal(dst["b"], np.full((32,), v, np.float64))
    assert dst["step"] == int(v)
    assert dst["name"] == f"run-{int(v)}"


def _snap_dir(mgr: CheckpointManager, step: int) -> str:
    from torchsnapshot_tpu.storage_plugin import local_fs_root

    local = local_fs_root(mgr.path_for(step))
    assert local is not None
    return local


def _segment(mgr: CheckpointManager, step: int, rank: int = 0) -> str:
    return os.path.join(
        _snap_dir(mgr, step), journal.JOURNAL_DIRNAME, journal.segment_name(rank)
    )


def test_disabled_by_default(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(0, {"app": _state(0)})
    assert not mgr.journal_step(1, {"app": _state(1)})
    assert not os.path.exists(
        os.path.join(_snap_dir(mgr, 0), journal.JOURNAL_DIRNAME)
    )


def test_journal_step_needs_a_committed_base(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=5)
    # No base snapshot yet: nothing to journal against.
    assert not mgr.journal_step(0, {"app": _state(0)})


def test_roundtrip_replay_bit_exact(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    for v in (1, 2, 3):
        st["w"] = np.arange(512, dtype=np.float32) + v
        st["b"] = np.full((32,), float(v), np.float64)
        st["step"] = v
        st["name"] = f"run-{v}"
        assert mgr.journal_step(v, {"app": st})

    jdir = os.path.join(_snap_dir(mgr, 0), journal.JOURNAL_DIRNAME)
    metas = journal.read_epoch_metas(jdir)
    assert [m["epoch"] for m in journal.committed_epochs(metas)] == [1, 2, 3]
    # Fence never outlives a committed epoch.
    assert not os.path.exists(os.path.join(jdir, journal.FENCE_FNAME))

    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    _assert_state(dst, 3)


def test_only_dirty_leaves_are_appended(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["step"] = 1  # one scalar dirty; the arrays unchanged
    assert mgr.journal_step(1, {"app": st})
    records, err = journal.scan_segment(_segment(mgr, 0))
    assert err is None
    assert [h["key"] for h, _ in records] == ["app/step"]
    # An epoch with nothing dirty still commits (an explicit durability
    # point), just with zero records.
    assert mgr.journal_step(2, {"app": st})
    assert len(journal.read_epoch_metas(os.path.dirname(_segment(mgr, 0)))) == 2


def test_torn_tail_truncated_on_replay(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["step"] = 1
    assert mgr.journal_step(1, {"app": st})

    seg = _segment(mgr, 0)
    committed = os.path.getsize(seg)
    with open(seg, "ab") as f:  # writer died mid-append
        f.write(b"TSJR\x40\x00\x00\x00{\"v\": 1, \"gen\"")

    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    np.testing.assert_array_equal(dst["w"], np.arange(512, dtype=np.float32))
    assert dst["step"] == 1  # the committed epoch replayed
    assert os.path.getsize(seg) == committed  # tail truncated, records kept


def test_corrupt_committed_record_falls_back_to_base(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["w"] = st["w"] + 5
    st["step"] = 5
    assert mgr.journal_step(1, {"app": st})

    seg = _segment(mgr, 0)
    with open(seg, "r+b") as f:
        f.seek(os.path.getsize(seg) // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))

    # CRC rejects the record; the WHOLE journal is refused (bounded
    # fallback, never a partial splice) and the base restores intact.
    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    _assert_state(dst, 0)
    # The corrupt segment is left in place as fsck evidence.
    assert os.path.getsize(seg) > 0


def test_fenced_off_straggler_records_never_spliced(tmp_path, journaling):
    """A record inside the committed byte range whose generation matches
    no committed epoch (a resurrected straggler's write that slipped in
    before its fence check) is skipped on replay."""
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["step"] = 1
    assert mgr.journal_step(1, {"app": st})

    seg = _segment(mgr, 0)
    jdir = os.path.dirname(seg)
    fields, payload = journal._serialize_leaf(99, "object")
    header = {"v": 1, "gen": "deadbeef" * 4, "epoch": 2, "key": "app/step"}
    header.update(fields)
    stale = journal.encode_record(header, payload)
    with open(seg, "ab") as f:
        f.write(stale)
    # Forge the committed offset to cover the stale record.
    meta_path = os.path.join(jdir, journal.epoch_meta_name(1))
    with open(meta_path) as f:
        meta = json.load(f)
    meta["offsets"]["0"] = os.path.getsize(seg)
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    assert dst["step"] == 1  # the committed epoch applied; 99 never did


def test_epoch_gap_stops_replay(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    for v in (1, 2):
        st["step"] = v
        assert mgr.journal_step(v, {"app": st})
    os.remove(
        os.path.join(
            os.path.dirname(_segment(mgr, 0)), journal.epoch_meta_name(1)
        )
    )
    # Epoch 2 sits past a gap: nothing is committed, base restores.
    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    _assert_state(dst, 0)


def test_epoch_bytes_cap_forces_full_save(tmp_path, journaling, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL_EPOCH_BYTES", "64")
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["w"] = st["w"] + 1  # 2 KiB of dirty payload > the 64-byte cap
    assert mgr.journal_step(1, {"app": st})  # durable — via a full save
    assert mgr.latest_step() == 1
    # The new base re-armed a fresh journal; small deltas journal again.
    st["step"] = 2
    assert mgr.journal_step(2, {"app": st})
    assert mgr.latest_step() == 1  # no extra full save


def test_max_epochs_bounds_the_replay_chain(tmp_path, journaling, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL_MAX_EPOCHS", "2")
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    for v in (1, 2):
        st["step"] = v
        assert mgr.journal_step(v, {"app": st})
    assert mgr.latest_step() == 0
    st["step"] = 3
    assert mgr.journal_step(3, {"app": st})  # epoch 3 > cap: full save
    assert mgr.latest_step() == 3


def test_preemption_flushes_journal_not_full_save(tmp_path, journaling):
    from torchsnapshot_tpu.preemption import (
        PreemptionWatcher,
        simulate_preemption_now,
    )

    watcher = PreemptionWatcher()
    mgr = CheckpointManager(
        str(tmp_path), save_interval_steps=100, preemption=watcher
    )
    st = _state(0)
    mgr.save(0, {"app": st})
    st["step"] = 1
    assert mgr.journal_step(1, {"app": st})

    st["w"] = st["w"] + 7
    st["step"] = 2
    simulate_preemption_now()
    try:
        # Off-cadence save: the journal flush replaces the synchronous
        # full emergency save — no new snapshot directory appears.
        assert mgr.save(2, {"app": st}) is False
        assert watcher.consumed
        assert mgr.all_steps() == [0]
    finally:
        watcher.close()

    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    np.testing.assert_array_equal(
        dst["w"], np.arange(512, dtype=np.float32) + 7
    )
    assert dst["step"] == 2


def test_restore_rearms_and_continues_the_chain(tmp_path, journaling):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["step"] = 1
    assert mgr.journal_step(1, {"app": st})

    # A resumed run: restore re-arms the journal against the same base...
    mgr2 = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st2 = _state(-1)
    assert mgr2.restore({"app": st2}) == 0
    assert st2["step"] == 1
    st2["step"] = 2
    assert mgr2.journal_step(2, {"app": st2})  # ...and the chain continues

    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    assert dst["step"] == 2


def test_journal_flight_events(tmp_path, journaling):
    from torchsnapshot_tpu.telemetry import flightrec

    flightrec.reset()
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    st = _state(0)
    mgr.save(0, {"app": st})
    st["step"] = 1
    assert mgr.journal_step(1, {"app": st})
    events = {ev for _, _, ev, _ in flightrec.snapshot_ring()}
    assert {"journal.open", "journal.commit"} <= events

    dst = _state(-1)
    assert CheckpointManager(str(tmp_path)).restore({"app": dst}) == 0
    events = {ev for _, _, ev, _ in flightrec.snapshot_ring()}
    assert "journal.replay" in events


# ------------------------------------------------------- record framing unit


def test_record_framing_roundtrip_torn_and_corrupt():
    payload = memoryview(b"\x01\x02\x03\x04" * 8)
    rec = journal.encode_record(
        {
            "v": 1,
            "gen": "g",
            "epoch": 1,
            "key": "k",
            "kind": "object",
            "nbytes": len(payload),
        },
        payload,
    )
    header, out, off = journal._decode_one(memoryview(rec), 0)
    assert header["key"] == "k" and bytes(out) == bytes(payload)
    assert off == len(rec)

    for cut in (2, 10, len(rec) - 1):  # torn anywhere: EOFError, no splice
        with pytest.raises(EOFError):
            journal._decode_one(memoryview(rec[:cut]), 0)

    flipped = bytearray(rec)
    flipped[-6] ^= 0xFF  # payload byte under the trailer CRC
    with pytest.raises(ValueError):
        journal._decode_one(memoryview(bytes(flipped)), 0)


def test_committed_epochs_is_the_contiguous_prefix():
    metas = [{"epoch": 1}, {"epoch": 2}, {"epoch": 4}]
    assert [m["epoch"] for m in journal.committed_epochs(metas)] == [1, 2]
    assert journal.committed_epochs([{"epoch": 2}]) == []
