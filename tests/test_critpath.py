"""Critical-path attribution engine (telemetry/critpath.py) and the
``explain`` CLI.

Covers the ISSUE 8 acceptance criteria directly: a throttled
(storage-bound) take must be named storage-write-bound with the injected
bandwidth recovered within 25%, an unthrottled tmpfs take must name a
pipeline category instead (both via the `explain` exit code a bench can
assert), and a w2 take's fleet-merged histograms must equal the
bucket-wise sum of the rank histograms.
"""

import asyncio
import json
import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, telemetry
from torchsnapshot_tpu.cli import main
from torchsnapshot_tpu.telemetry import critpath
from torchsnapshot_tpu.test_utils import run_with_subprocesses


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.refresh_from_env()
    telemetry.set_enabled(False)
    telemetry.reset()
    yield
    telemetry.set_enabled(False)
    telemetry.reset()


# -------------------------------------------------------- interval math


def test_union_seconds_merges_overlaps():
    assert critpath._union_seconds([(0, 1), (0.5, 2), (3, 4)]) == pytest.approx(3.0)
    assert critpath._union_seconds([]) == 0.0
    # Clipping to a window.
    assert critpath._union_seconds([(0, 10)], lo=2, hi=5) == pytest.approx(3.0)


def test_subtract_intervals():
    out = critpath._subtract_intervals([(0, 10)], [(2, 3), (5, 7)])
    assert out == [(0, 2), (3, 5), (7, 10)]
    # Full cover -> nothing left; no cover -> identity.
    assert critpath._subtract_intervals([(1, 2)], [(0, 5)]) == []
    assert critpath._subtract_intervals([(1, 2)], []) == [(1, 2)]


# -------------------------------------------------- per-rank attribution


def _span(name, ts, dur, cat="pipeline", **args):
    ev = {"ph": "span", "name": name, "ts": ts, "dur": dur, "cat": cat}
    if args:
        ev["args"] = args
    return ev


def test_build_attribution_categories_and_idle():
    events = [
        _span("stage_hash", 0.0, 1.0),
        _span("storage_write", 1.0, 2.0),
        _span("storage_write", 2.0, 2.0),  # overlaps: union, not sum
    ]
    attr = critpath.build_attribution(events, wall_s=5.0, rank=3)
    assert attr["rank"] == 3
    assert attr["categories"]["hash"] == pytest.approx(1.0)
    assert attr["categories"]["storage_write"] == pytest.approx(3.0)
    assert attr["categories"]["sched_idle"] == pytest.approx(1.0)


def test_build_attribution_fused_residual():
    """A fused stream_write window covered 60% by staging spans must
    attribute only the residual 40% to storage — the whole-window
    mapping would call every streamed tmpfs save storage-bound."""
    events = [
        _span("stream_write", 0.0, 10.0),
        _span("sub_chunk_stage", 0.0, 3.0),
        _span("sub_chunk_stage", 4.0, 3.0),
    ]
    attr = critpath.build_attribution(events, wall_s=10.0)
    assert attr["categories"]["stage_copy"] == pytest.approx(6.0)
    assert attr["categories"]["storage_write"] == pytest.approx(4.0)


def test_build_attribution_segments_cut_at_collectives():
    events = [
        _span("stage_hash", 0.0, 2.0),
        _span(
            "collective_wait", 2.0, 1.0, cat="collective",
            ns="pgw/ns/7", cseq=1, kind="all_gather",
        ),
        _span("storage_write", 3.0, 4.0),
    ]
    attr = critpath.build_attribution(events, wall_s=7.0)
    segs = attr["segments"]
    assert [s["key"] for s in segs] == ["pgw/ns/7#1", "tail"]
    assert segs[0]["dur_s"] == pytest.approx(2.0)
    assert segs[0]["wait_s"] == pytest.approx(1.0)
    assert segs[0]["categories"]["hash"] == pytest.approx(2.0)
    assert segs[1]["categories"]["storage_write"] == pytest.approx(4.0)


def test_build_attribution_empty_events():
    attr = critpath.build_attribution([], wall_s=1.5)
    assert attr["wall_s"] == 1.5
    assert attr["categories"] == {"sched_idle": 1.5}
    assert attr["segments"] == []


# ------------------------------------------------------- fleet stitching


def _rank_attr(rank, wall, segs):
    return {
        "rank": rank,
        "wall_s": wall,
        "categories": {},
        "segments": [
            {
                "key": k,
                "kind": "all_gather",
                "dur_s": d,
                "wait_s": w,
                "categories": cats,
            }
            for (k, d, w, cats) in segs
        ],
    }


def test_merge_attributions_picks_gating_rank_per_segment():
    """Rank 1 gates segment A (peers waited on it); rank 0 gates B. The
    critical path must name each gating rank and sum ITS categories —
    the waiting rank's collective_wait never enters the fleet view."""
    a0 = _rank_attr(0, 10.0, [
        ("ns#1", 1.0, 4.0, {"stage_copy": 1.0}),
        ("ns#2", 5.0, 0.0, {"storage_write": 5.0}),
    ])
    a1 = _rank_attr(1, 10.0, [
        ("ns#1", 5.0, 0.0, {"storage_write": 5.0}),
        ("ns#2", 1.0, 4.0, {"decode": 1.0}),
    ])
    a0["categories"] = {"storage_write": 6.0}
    a1["categories"] = {"storage_write": 5.0}
    fleet = critpath.merge_attributions([a0, a1])
    path = fleet["critical_path"]
    assert [(s["key"], s["rank"]) for s in path] == [("ns#1", 1), ("ns#2", 0)]
    assert fleet["critical_wall_s"] == pytest.approx(10.0)
    assert fleet["categories"]["storage_write"] == pytest.approx(10.0)
    assert fleet["binding"]["category"] == "storage_write"
    assert fleet["binding"]["class"] == "storage"
    assert "collective_wait" not in fleet["categories"]


def test_merge_attributions_fallback_without_shared_segments():
    a0 = {"rank": 0, "wall_s": 2.0,
          "categories": {"stage_copy": 1.8}, "segments": []}
    fleet = critpath.merge_attributions([a0, None])
    assert fleet["reporting"] == 1
    assert fleet["binding"]["category"] == "stage_copy"
    assert fleet["binding"]["class"] == "pipeline"
    assert critpath.merge_attributions([None, None]) is None


def test_merge_attributions_rate_from_aggregate():
    a0 = {"rank": 0, "wall_s": 2.0,
          "categories": {"storage_write": 2.0}, "segments": []}
    fleet = critpath.merge_attributions(
        [a0], aggregate={"bytes_written": 4e9}
    )
    assert fleet["binding"]["gbps"] == pytest.approx(2.0)


def test_live_binding():
    assert critpath.live_binding([]) is None
    events = [
        _span("storage_write", 0.0, 3.0),
        _span("stage_hash", 0.0, 1.0),
    ]
    assert critpath.live_binding(events) == "storage_write"


def test_binding_exit_code_and_verdict_threshold():
    assert critpath.binding_exit_code(
        {"fleet": {"verdict": "storage-bound"}}
    ) == 1
    assert critpath.binding_exit_code(
        {"fleet": {"verdict": "pipeline-bound"}}
    ) == 0
    # A storage category that is merely the LARGEST slice (not the
    # majority of the critical path) stays pipeline-bound: a fast local
    # save's pwrite at 30% of wall must not read as "buy faster disks".
    minority = {
        "rank": 0, "wall_s": 10.0, "segments": [],
        "categories": {"storage_write": 3.0, "stage_copy": 2.0,
                       "sched_idle": 5.0},
    }
    fleet = critpath.merge_attributions([minority])
    assert fleet["binding"]["category"] == "sched_idle"
    assert fleet["verdict"] == "pipeline-bound"
    majority = {
        "rank": 0, "wall_s": 10.0, "segments": [],
        "categories": {"storage_write": 8.0, "stage_copy": 2.0},
    }
    fleet = critpath.merge_attributions([majority])
    assert fleet["verdict"] == "storage-bound"


# ----------------------------------------------------------- e2e verdicts


_PAYLOAD_ELEMS = 12_000_000  # 48 MB fp32


def _throttled_fs(bandwidth_bps: float):
    """An FSStoragePlugin whose writes share one rate gate — models a
    storage tier with a hard bandwidth ceiling (each write's TOTAL
    service time is nbytes/bandwidth, so the injected rate is exact).
    Buffered-only so the write path exercises the plain storage_write
    spans."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    class ThrottledFS(FSStoragePlugin):
        supports_streaming = False

        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._gate = asyncio.Lock()

        async def write(self, write_io):
            nbytes = memoryview(write_io.buf).nbytes
            async with self._gate:
                loop = asyncio.get_running_loop()
                t0 = loop.time()
                await super().write(write_io)
                await asyncio.sleep(
                    max(0.0, nbytes / bandwidth_bps - (loop.time() - t0))
                )

    return ThrottledFS


def test_throttled_take_is_storage_bound(tmp_path, monkeypatch, capsys):
    """Acceptance: on a bandwidth-throttled take, `explain` names
    storage write as the binding category, recovers the injected
    bandwidth within 25%, and exits 1 (storage-bound)."""
    bandwidth = 40e6  # 40 MB/s
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        _throttled_fs(bandwidth),
    )
    telemetry.set_enabled(True)
    snap = str(tmp_path / "snap")
    state = {
        "m": StateDict(
            w=np.random.default_rng(0)
            .standard_normal(_PAYLOAD_ELEMS)
            .astype(np.float32)
        )
    }
    Snapshot.take(snap, state)
    attr = telemetry.last_attribution()
    assert attr is not None
    binding = attr["binding"]
    assert binding["category"] == "storage_write"
    assert binding["class"] == "storage"
    assert binding["gbps"] == pytest.approx(bandwidth / 1e9, rel=0.25)
    # The persisted record drives the CLI to the same verdict.
    assert os.path.isfile(os.path.join(snap, critpath.ATTRIBUTION_FNAME))
    assert main(["explain", snap]) == 1
    out = capsys.readouterr().out
    assert "storage_write" in out
    assert "storage-write-bound" in out


def test_tmpfs_take_is_pipeline_bound(tmp_path, capsys):
    """Acceptance: an unthrottled local take whose pipeline does real
    host-side work (zlib staging — the deterministic stand-in for the
    DtoH/serialize/compress pipeline cost a TPU save pays) names a
    PIPELINE category and `explain` exits 0, the ROADMAP-claim
    assertion. Storage is tmpfs at memcpy speed, so any storage-bound
    verdict here would be an attribution bug, not a slow disk."""
    telemetry.set_enabled(True)
    snap = str(tmp_path / "snap")
    state = {
        "m": StateDict(
            w=np.random.default_rng(0)
            .standard_normal(_PAYLOAD_ELEMS)
            .astype(np.float32)
        )
    }
    Snapshot.take(snap, state, compression="zlib:1")
    attr = telemetry.last_attribution()
    assert attr is not None
    assert attr["verdict"] == "pipeline-bound"
    assert main(["explain", snap]) == 0
    assert "binding:" in capsys.readouterr().out


def test_explain_falls_back_to_telemetry_document(tmp_path, capsys):
    """Snapshots without .snapshot_critpath (rank-0 persist failure,
    older format) re-derive the verdict from the telemetry document's
    per-rank attribution blobs."""
    telemetry.set_enabled(True)
    snap = str(tmp_path / "snap")
    Snapshot.take(
        snap, {"m": StateDict(w=np.arange(100_000, dtype=np.float32))}
    )
    os.remove(os.path.join(snap, critpath.ATTRIBUTION_FNAME))
    code = main(["explain", snap])
    assert code in (0, 1)
    assert "binding:" in capsys.readouterr().out


def test_explain_missing_attribution_exits_2(tmp_path, capsys):
    # A committed snapshot taken with telemetry OFF has no attribution.
    snap = str(tmp_path / "snap")
    Snapshot.take(snap, {"m": StateDict(w=np.arange(10, dtype=np.float32))})
    assert main(["explain", snap]) == 2
    assert "no critical-path attribution" in capsys.readouterr().err


def test_explain_json_dump(tmp_path, capsys):
    telemetry.set_enabled(True)
    snap = str(tmp_path / "snap")
    Snapshot.take(
        snap, {"m": StateDict(w=np.arange(100_000, dtype=np.float32))}
    )
    main(["explain", snap, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert doc["fleet"]["binding"]["category"]


def test_governor_elections_ride_summary_and_critpath_doc(tmp_path):
    telemetry.set_enabled(True)
    snap = str(tmp_path / "snap")
    Snapshot.take(
        snap, {"m": StateDict(w=np.arange(100_000, dtype=np.float32))}
    )
    summary = telemetry.last_summary()
    sites = {row.get("site") for row in summary.get("governor") or []}
    assert "write" in sites
    doc = json.loads(
        open(os.path.join(snap, critpath.ATTRIBUTION_FNAME)).read()
    )
    assert any(r.get("site") == "write" for r in doc.get("governor") or [])


def test_fsck_exempts_critpath_record(tmp_path):
    from torchsnapshot_tpu.cli import run_fsck

    telemetry.set_enabled(True)
    snap = str(tmp_path / "snap")
    Snapshot.take(
        snap, {"m": StateDict(w=np.arange(10_000, dtype=np.float32))}
    )
    assert os.path.isfile(os.path.join(snap, critpath.ATTRIBUTION_FNAME))
    code, report = run_fsck(snap, echo=lambda *a, **k: None)
    assert code == 0, report.findings


# ---------------------------------------------------------- distributed


def _critpath_take_worker(rank: int, world_size: int, snap_path: str):
    import numpy as np  # noqa: F811

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry  # noqa: F811

    telemetry.set_enabled(True)
    state = {
        "local": StateDict(
            data=np.full((65_536,), rank, dtype=np.float32)
        ),
    }
    Snapshot.take(snap_path, state)
    summary = telemetry.last_summary()
    return {
        "histograms": summary.get("histograms") or {},
        "attribution": telemetry.last_attribution(),
    }


@pytest.mark.multiprocess
def test_w2_histograms_merge_bucketwise_and_critpath_stitches(tmp_path):
    """Acceptance: fleet-merged histograms sum bucket-wise across a w2
    take, and the persisted attribution stitched at least one shared
    collective segment."""
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(_critpath_take_worker, 2, snap_path)
    doc = json.loads(
        (tmp_path / "snap" / ".snapshot_telemetry").read_text()
    )
    fleet_hist = (doc["fleet"] or {}).get("histograms") or {}
    assert fleet_hist, "fleet view carries no histograms"
    # Bucket-wise: every (name, key) family in the fleet view equals the
    # element-wise sum of the per-rank contributions.
    for name, by_key in fleet_hist.items():
        for key, merged in by_key.items():
            per_rank = [
                (results[r]["histograms"].get(name) or {}).get(key)
                for r in results
            ]
            contributing = [h for h in per_rank if h]
            assert contributing, (name, key)
            assert merged["count"] == sum(h["count"] for h in contributing)
            width = max(len(h["counts"]) for h in contributing)
            summed = [0] * width
            for h in contributing:
                for i, n in enumerate(h["counts"]):
                    summed[i] += n
            assert merged["counts"] == summed, (name, key)
    # The stitched critical path exists and every segment names a rank.
    cp_doc = json.loads(
        (tmp_path / "snap" / ".snapshot_critpath").read_text()
    )
    fleet = cp_doc["fleet"]
    assert fleet["reporting"] == 2
    assert fleet["critical_path"], "no shared collective segments stitched"
    assert all(s["rank"] in (0, 1) for s in fleet["critical_path"])
    # Both ranks computed the same merged view from the gather.
    attrs = [results[r]["attribution"] for r in results]
    assert attrs[0] == attrs[1]
