"""Tests for the RSS profiler (reference test pattern: used as a budget
oracle in benchmarks; here we validate the sampling mechanics)."""

import time

import numpy as np

from torchsnapshot_tpu.rss_profiler import RSSProfiler, measure_rss_deltas


def test_samples_collected():
    deltas = []
    with measure_rss_deltas(deltas, interval_s=0.01):
        time.sleep(0.1)
    assert len(deltas) >= 2


def test_allocation_visible_in_peak():
    prof = RSSProfiler(interval_s=0.01)
    with prof:
        # 64 MB touch — comfortably above sampling noise.
        buf = np.ones(64 * 1024 * 1024, dtype=np.uint8)
        buf[::4096] += 1
        time.sleep(0.1)
    assert prof.peak_delta_bytes > 32 * 1024 * 1024
    del buf


def test_thread_stops_on_exit():
    prof = RSSProfiler(interval_s=0.01)
    with prof:
        time.sleep(0.03)
    n = len(prof.rss_deltas)
    time.sleep(0.05)
    assert len(prof.rss_deltas) == n
