"""Direct unit tests for telemetry/export.py edge cases — previously
covered only indirectly through e2e takes (ISSUE 8 satellite): empty
bus, a recorder abandoned mid-span, nested interleaved tasks, and the
OpenMetrics helpers the live exporter shares.
"""

import asyncio
import json

import pytest

from torchsnapshot_tpu import telemetry
from torchsnapshot_tpu.telemetry import export
from torchsnapshot_tpu.telemetry.core import HISTOGRAM_BOUNDS


@pytest.fixture(autouse=True)
def _clean_bus():
    telemetry.set_enabled(False)
    telemetry.reset()
    yield
    telemetry.set_enabled(False)
    telemetry.reset()


# ------------------------------------------------------------ chrome trace


def test_chrome_trace_empty_bus():
    """An empty bus must still export a loadable trace (metadata lane
    only) — the disabled-telemetry / brand-new-process case."""
    trace = export.chrome_trace([])
    assert trace["traceEvents"][0]["ph"] == "M"
    assert json.loads(export.chrome_trace_json([]))


def test_chrome_trace_abandoned_recorder_mid_span():
    """A recorder abandoned while a span is still OPEN (the abort path:
    the exception unwound through the span's body) must export whatever
    completed without the torn span, and the next op's begin must trim
    the abandoned events instead of letting them pin the buffer."""
    telemetry.set_enabled(True)
    recorder = telemetry.begin_op("take", rank=0)
    with telemetry.span("completed"):
        pass
    torn = telemetry.span("never-exits")
    torn.__enter__()  # deliberately not exited yet: abort unwound past it
    try:
        events = recorder.events()
        recorder.abandon()
        names = [e["name"] for e in events if e.get("ph") == "span"]
        assert names == ["completed"]  # the torn span never appended
        trace = export.chrome_trace(events, pid=7)
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert [s["name"] for s in spans] == ["completed"]
        assert all(s["ts"] >= 0 and s["dur"] >= 0 for s in spans)
        # The next op starts clean: the abandoned recorder no longer
        # pins the abandoned events in the live buffer.
        nxt = telemetry.begin_op("take", rank=0)
        assert nxt.events() == []
        nxt.abandon()
    finally:
        # Unwind the torn span so this test's context stack (a
        # contextvar shared with later tests on this thread) is clean.
        torn.__exit__(None, None, None)


def test_chrome_trace_nested_interleaved_tasks():
    """Spans opened by interleaved asyncio tasks export with their own
    parent chains — task A's child must never parent onto task B's open
    span even though they interleave on one thread."""
    telemetry.set_enabled(True)

    async def worker(tag):
        with telemetry.span(f"outer-{tag}"):
            await asyncio.sleep(0.01)
            with telemetry.span(f"inner-{tag}"):
                await asyncio.sleep(0.01)

    async def main():
        await asyncio.gather(worker("a"), worker("b"))

    asyncio.run(main())
    events = {e["name"]: e for e in telemetry.events() if e["ph"] == "span"}
    trace = export.chrome_trace(list(events.values()))
    by_name = {
        e["name"]: e for e in trace["traceEvents"] if e.get("ph") == "X"
    }
    for tag in ("a", "b"):
        assert (
            by_name[f"inner-{tag}"]["args"]["parent"]
            == events[f"outer-{tag}"]["id"]
        )
    # Monotonic, rebased timestamps.
    assert all(e["ts"] >= 0 for e in trace["traceEvents"] if "ts" in e)


def test_chrome_trace_counter_events():
    telemetry.set_enabled(True)
    telemetry.counter_add("bytes_written", 10)
    telemetry.counter_add("bytes_written", 5)
    trace = export.chrome_trace()
    tracks = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert [t["args"]["bytes_written"] for t in tracks] == [10, 15]


# --------------------------------------------------------------- summaries


def test_render_summary_document_minimal():
    """Documents from foreign/older producers may omit nearly
    everything; rendering must not crash on missing fields."""
    out = export.render_summary_document({"op": "take"})
    assert "op:          take" in out
    out = export.render_summary_document(
        {"op": "take", "world_size": 1, "ranks": [None], "fleet": None}
    )
    assert "world_size" in out


def test_render_summary_document_histograms():
    doc = {
        "op": "take",
        "world_size": 1,
        "ranks": [],
        "fleet": {
            "wall_s_max": 1.0,
            "slowest_rank": 0,
            "skew_s": 0.0,
            "aggregate": {},
            "histograms": {
                "write.entry_s": {
                    "FSStoragePlugin": {
                        "counts": [0] * 14 + [3] + [0] * 14,
                        "count": 3,
                        "sum": 0.03,
                    }
                }
            },
        },
    }
    out = export.render_summary_document(doc)
    assert "latency histograms" in out
    assert "write.entry_s[FSStoragePlugin]: n=3" in out


def test_fmt_bytes():
    assert export.fmt_bytes(None) == "?"
    assert export.fmt_bytes(0) == "0B"
    assert export.fmt_bytes(1536) == "1.5KiB"
    assert export.fmt_bytes(3 * 1024**4) == "3.0TiB"


# ------------------------------------------------------------- openmetrics


def test_om_family_name_sanitizes():
    assert (
        export.om_family_name("write.sub_chunk_s")
        == "torchsnapshot_tpu_write_sub_chunk_s"
    )
    assert "-" not in export.om_family_name("a-b c.d")


def test_om_histogram_lines_cumulative_and_inf():
    hist = {"": {"counts": [1, 2] + [0] * 27, "count": 3, "sum": 0.5}}
    lines = export.om_histogram_lines("collective.wait_s", hist)
    assert lines[0] == "# TYPE torchsnapshot_tpu_collective_wait_s histogram"
    buckets = [ln for ln in lines if "_bucket" in ln]
    # Cumulative over the fixed ladder + the +Inf slot == count.
    assert len(buckets) == len(HISTOGRAM_BOUNDS) + 1
    assert buckets[0].endswith(" 1")
    assert buckets[1].endswith(" 3")
    assert buckets[-1] == (
        'torchsnapshot_tpu_collective_wait_s_bucket{le="+Inf"} 3'
    )
    assert any(
        ln == "torchsnapshot_tpu_collective_wait_s_count 3" for ln in lines
    )


def test_render_openmetrics_includes_fleet_histograms():
    doc = {
        "op": "take",
        "world_size": 1,
        "ranks": [
            {
                "op": "take",
                "rank": 0,
                "wall_s": 1.0,
                "counters": {"bytes_written": 10},
                "histograms": {
                    "write.entry_s": {
                        "FS": {"counts": [5] + [0] * 28, "count": 5,
                               "sum": 0.001}
                    }
                },
            }
        ],
    }
    from torchsnapshot_tpu.telemetry.aggregate import merge_summaries

    doc["fleet"] = merge_summaries(doc["ranks"])
    out = export.render_openmetrics(doc)
    assert "torchsnapshot_tpu_write_entry_s_bucket" in out
    assert out.endswith("# EOF\n")
    try:
        from prometheus_client.openmetrics import parser
    except ImportError:
        return
    families = {
        f.name: f for f in parser.text_string_to_metric_families(out)
    }
    assert families["torchsnapshot_tpu_write_entry_s"].type == "histogram"
