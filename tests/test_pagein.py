"""Lazy page-in restore (ISSUE 18): serve before the last byte lands.

In-process: default-off semantics, hot-set grammar, futures resolving
bit-exact under concurrent demand faults, learned first-touch replay as
prefetch order, admission interaction, abort leaving partial state
unreferencable. Chaos drills: SIGKILL mid-page-in leaves every committed
snapshot restorable and fsck-clean; a corrupt background read is
CRC-rejected and the leaf re-read direct, bit-exact. Multiprocess (w2):
env skew (one rank lazy, one not) degrades to eager everywhere via the
one election gather; both-ranks-lazy serves the hot set before the last
byte.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, faultinject, pagein
from torchsnapshot_tpu.cli import run_fsck
from torchsnapshot_tpu.layout import Rule
from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

LEAVES = ("emb", "w1", "w2", "w3")


def _state(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "model": StateDict(
            emb=rng.standard_normal((64, 32)).astype(np.float32),
            w1=rng.standard_normal((48, 16)).astype(np.float32),
            w2=rng.standard_normal((40, 20)).astype(np.float32),
            w3=rng.standard_normal((30, 30)).astype(np.float32),
            step=np.array([seed], dtype=np.int64),
        )
    }


def _zeros_like(state: dict) -> dict:
    return {
        "model": StateDict(
            **{
                k: np.zeros_like(np.asarray(v))
                for k, v in state["model"].items()
            }
        )
    }


def _value(leaf):
    """A restored leaf's value: under lazy restore a deferred leaf is a
    LeafFuture proxy; result() demand-faults and returns the value."""
    if isinstance(leaf, pagein.LeafFuture):
        return leaf.result(timeout=120)
    return leaf


def _equal(restored: dict, expected: dict) -> bool:
    return all(
        np.array_equal(
            np.asarray(_value(restored["model"][k])),
            np.asarray(expected["model"][k]),
        )
        for k in expected["model"]
    )


# ------------------------------------------------------------ default off


def test_default_off_returns_none(tmp_path):
    """No env: restore is eager (one env check), returns None, and a
    hot= declaration alone does not engage lazy mode."""
    assert os.environ.get("TORCHSNAPSHOT_TPU_LAZY_RESTORE") is None
    state = _state(0)
    Snapshot.take(str(tmp_path / "snap"), state)
    dst = _zeros_like(state)
    sess = Snapshot(str(tmp_path / "snap")).restore(dst, hot=["model/emb"])
    assert sess is None
    assert _equal(dst, state)
    assert not any(
        isinstance(v, pagein.LeafFuture) for v in dst["model"].values()
    )


def test_auto_without_hot_or_learned_stays_eager(tmp_path, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "auto")
    state = _state(1)
    Snapshot.take(str(tmp_path / "snap"), state)
    dst = _zeros_like(state)
    assert Snapshot(str(tmp_path / "snap")).restore(dst) is None
    assert _equal(dst, state)


# ------------------------------------------------------- hot-set grammar


def test_hot_set_rule_matching(monkeypatch):
    """hot= accepts regex strings and layout.Rule objects (re.search,
    first match wins); env patterns append; duplicates collapse."""
    rules = pagein.compile_hot_set(
        ["model/emb", Rule.of(r"^model/w1$", ()), "model/emb"],
        include_env=False,
    )
    assert [r.pattern for r in rules] == ["model/emb", r"^model/w1$"]
    hs = pagein.HotSet(rules)
    assert hs.matches("model/emb")
    assert hs.matches("model/emb_table")  # re.search, unanchored
    assert hs.matches("model/w1")
    assert not hs.matches("model/w10")  # anchored rule
    assert not hs.matches("model/w2")

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_HOT_SET", "model/w2;model/emb")
    rules = pagein.compile_hot_set(["model/emb"])
    assert [r.pattern for r in rules] == ["model/emb", "model/w2"]

    # The vote signature keys engagement: same rules, same token;
    # different rules, different token (ranks must defer identically).
    a = pagein.HotSet(pagein.compile_hot_set(["x"], include_env=False))
    b = pagein.HotSet(pagein.compile_hot_set(["x"], include_env=False))
    c = pagein.HotSet(pagein.compile_hot_set(["y"], include_env=False))
    assert a.signature() == b.signature() != c.signature()
    assert pagein.vote_token(True, a) == f"lazy:{a.signature()}"
    assert pagein.vote_token(False, a) == ""

    with pytest.raises(Exception):
        pagein.compile_hot_set(["[invalid"], include_env=False)


# ------------------------------------------- futures under concurrent faults


def test_futures_bitexact_under_concurrent_faults(tmp_path, monkeypatch):
    """Threads demand-faulting deferred leaves while the background
    prefetch walks the same units: every future resolves bit-exact,
    residency reaches 1.0, and nothing is torn."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "always")
    state = _state(2)
    Snapshot.take(str(tmp_path / "snap"), state)
    dst = _zeros_like(state)
    sess = Snapshot(str(tmp_path / "snap")).restore(dst, hot=["model/emb"])
    assert sess is not None
    assert np.array_equal(dst["model"]["emb"], state["model"]["emb"])
    assert sess.resident_fraction() < 1.0

    errors = []

    def hammer(path):
        try:
            sess.leaf(path).result(timeout=120)
        except BaseException as e:  # noqa: B036
            errors.append((path, e))

    threads = [
        threading.Thread(target=hammer, args=(f"model/{name}",))
        for name in ("w1", "w2", "w3")
        for _ in range(3)  # several threads per leaf: racing faults
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    sess.wait(timeout=120)
    assert _equal(dst, state)
    assert sess.resident_fraction() == 1.0
    assert sess.pending_paths() == []


# -------------------------------------------------- learned-order replay


def test_prefetch_order_replay(tmp_path, monkeypatch):
    """First-touch order recorded by one lazy restore replays as the
    next restore's prefetch order (via the history journal), and auto
    mode engages on the learned order alone."""
    snap = str(tmp_path / "snap")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "always")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PAGEIN_PREFETCH", "0")
    state = _state(3)
    Snapshot.take(snap, state)
    dst = _zeros_like(state)
    sess = Snapshot(snap).restore(dst, hot=["model/emb"])
    assert sess is not None
    touch_order = ["model/w3", "model/step", "model/w1", "model/w2"]
    for path in touch_order:
        sess.fault(path, timeout=120)
    sess.wait(timeout=120)
    assert _equal(dst, state)

    assert pagein.learned_order(snap) == touch_order

    # Second restore: auto + no hot rules — the learned order alone
    # engages lazy mode and leads the prefetch order.
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "auto")
    dst2 = _zeros_like(state)
    sess2 = Snapshot(snap).restore(dst2)
    assert sess2 is not None
    assert sess2.prefetch_order()[:4] == touch_order
    sess2.wait(timeout=120)
    assert _equal(dst2, state)


# ------------------------------------------------- admission interaction


def test_admission_share_interaction(tmp_path, monkeypatch):
    """With a tenant ambient and admission on, the page-in engine arms
    its own admission session (the restore's was disarmed at return)
    and still drains bit-exact."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "always")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_TENANT", "acme")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ADMISSION", "1")
    state = _state(4)
    Snapshot.take(str(tmp_path / "snap"), state)
    dst = _zeros_like(state)
    sess = Snapshot(str(tmp_path / "snap")).restore(dst, hot=["model/emb"])
    assert sess is not None
    sess.fault("model/w1", timeout=120)
    sess.wait(timeout=120)
    assert _equal(dst, state)


# ------------------------------------------------------------------ abort


def test_abort_leaves_partial_state_unreferencable(tmp_path, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "always")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PAGEIN_PREFETCH", "0")
    state = _state(5)
    Snapshot.take(str(tmp_path / "snap"), state)
    dst = _zeros_like(state)
    sess = Snapshot(str(tmp_path / "snap")).restore(dst, hot=["model/emb"])
    assert sess is not None
    pending = sess.pending_paths()
    assert pending
    sess.abort()
    for path in pending:
        with pytest.raises(pagein.PageInAborted):
            sess.leaf(path).result(timeout=5)
    # The hot set stays valid — it was resident before the abort.
    assert np.array_equal(dst["model"]["emb"], state["model"]["emb"])


# ------------------------------------------------------------ chaos drills

_KILL_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TORCHSNAPSHOT_TPU_LAZY_RESTORE"] = "always"
import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict, faultinject

root = sys.argv[1]

def state(seed):
    rng = np.random.default_rng(seed)
    return {"model": StateDict(
        emb=rng.standard_normal((64, 32)).astype(np.float32),
        w1=rng.standard_normal((48, 16)).astype(np.float32),
        w2=rng.standard_normal((40, 20)).astype(np.float32),
        w3=rng.standard_normal((30, 30)).astype(np.float32),
        step=np.array([seed], dtype=np.int64),
    )}

Snapshot.take(os.path.join(root, "prev"), state(0))
Snapshot.take(os.path.join(root, "cur"), state(1))
dst = {"model": StateDict(**{
    k: np.zeros_like(np.asarray(v)) for k, v in state(1)["model"].items()
})}
faultinject.configure("pagein.prefetch@1=kill")
sess = Snapshot(os.path.join(root, "cur")).restore(dst, hot=["model/emb"])
assert sess is not None
sess.wait(timeout=120)  # the first background batch SIGKILLs us here
print("SURVIVED")  # only reachable if the plan never fired
"""


def test_chaos_sigkill_mid_pagein(tmp_path):
    """SIGKILL while pages are in flight: restores never write into the
    snapshot, so every committed snapshot stays restorable and
    fsck-clean — the serving replica died, nothing else happened."""
    r = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD, str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=150,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    assert "SURVIVED" not in r.stdout
    state0 = _state(0)
    dst = _zeros_like(state0)
    assert Snapshot(str(tmp_path / "prev")).restore(dst) is None
    assert _equal(dst, state0)
    assert run_fsck(str(tmp_path / "prev"))[0] == 0
    assert run_fsck(str(tmp_path / "cur"))[0] == 0


def test_chaos_corrupt_background_read_degrades_direct(tmp_path, monkeypatch):
    """A corrupted background fault read is CRC-rejected; the engine
    re-reads the leaf with a blocking direct read — the accessor gets
    the bit-exact value, never a torn or stale one."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_CHECKSUM", "1")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_VERIFY", "1")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", "never")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_LAZY_RESTORE", "always")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PAGEIN_PREFETCH", "0")
    state = _state(6)
    Snapshot.take(str(tmp_path / "snap"), state)
    dst = _zeros_like(state)
    sess = Snapshot(str(tmp_path / "snap")).restore(dst, hot=["model/emb"])
    assert sess is not None
    try:
        # Armed AFTER restore returned: with prefetch off, the next
        # fs.read is the engine's background read for the fault below.
        faultinject.configure("fs.read@1=corrupt;seed=5")
        v = sess.leaf("model/w1").result(timeout=120)
        assert np.array_equal(np.asarray(v), state["model"]["w1"])
        # The corrupt read fired AND a clean re-read followed it.
        assert faultinject.hits().get("fs.read", 0) >= 2
    finally:
        faultinject.disable()
    sess.wait(timeout=120)
    assert _equal(dst, state)
    assert run_fsck(str(tmp_path / "snap"))[0] == 0


# --------------------------------------------------------- multiprocess


def _init_jax_dist(rank: int, world_size: int, port: int):
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    return jax


def _skew_worker(rank, world_size, root, port):
    """Env skew: rank 0 votes always, rank 1 never. The unanimity check
    on the (one) election gather fails; every rank restores eagerly —
    no session, no futures, no hang, bit-exact."""
    os.environ["TORCHSNAPSHOT_TPU_LAZY_RESTORE"] = (
        "always" if rank == 0 else "never"
    )
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    _init_jax_dist(rank, world_size, port)
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.pagein import LeafFuture

    rng = np.random.default_rng(10 + rank)
    state = {
        "model": StateDict(
            w=rng.standard_normal((64, 32)).astype(np.float32),
            b=rng.standard_normal(100).astype(np.float64),
        )
    }
    Snapshot.take(root, state)
    dst = {
        "model": StateDict(
            w=np.zeros((64, 32), np.float32), b=np.zeros(100, np.float64)
        )
    }
    sess = Snapshot(root).restore(dst)
    assert all(
        not isinstance(v, LeafFuture) for v in dst["model"].values()
    )
    return {
        "session": sess is not None,
        "bitexact": all(
            np.array_equal(np.asarray(dst["model"][k]), state["model"][k])
            for k in state["model"]
        ),
    }


@pytest.mark.multiprocess
def test_env_skew_degrades_to_eager_everywhere(tmp_path):
    results = run_with_subprocesses(
        _skew_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        timeout=180.0,
    )
    for rank, r in results.items():
        assert r["session"] is False, (rank, results)
        assert r["bitexact"], (rank, results)


def _ttfi_worker(rank, world_size, root, port):
    """Both ranks lazy with the same env hot set: restore returns with
    the hot leaf servable while deferred bytes are still unread (first
    inference before the last byte), then drains bit-exact."""
    os.environ["TORCHSNAPSHOT_TPU_LAZY_RESTORE"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_HOT_SET"] = "model/emb"
    # Demand-only paging makes "bytes still unread at return" exact.
    os.environ["TORCHSNAPSHOT_TPU_PAGEIN_PREFETCH"] = "0"
    _init_jax_dist(rank, world_size, port)
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    rng = np.random.default_rng(20 + rank)
    state = {
        "model": StateDict(
            emb=rng.standard_normal((64, 32)).astype(np.float32),
            w1=rng.standard_normal((48, 16)).astype(np.float32),
            w2=rng.standard_normal((40, 20)).astype(np.float32),
        )
    }
    Snapshot.take(root, state)
    dst = {
        "model": StateDict(
            emb=np.zeros((64, 32), np.float32),
            w1=np.zeros((48, 16), np.float32),
            w2=np.zeros((40, 20), np.float32),
        )
    }
    sess = Snapshot(root).restore(dst)
    assert sess is not None
    # First inference is servable NOW: the hot leaf is bit-exact while
    # the tail has not been read.
    hot_exact = np.array_equal(dst["model"]["emb"], state["model"]["emb"])
    resident_at_return = sess.resident_fraction()
    sess.wait(timeout=120)
    from torchsnapshot_tpu.pagein import LeafFuture

    def value(leaf):
        return leaf.result(timeout=120) if isinstance(leaf, LeafFuture) else leaf

    tail_exact = all(
        np.array_equal(np.asarray(value(dst["model"][k])), state["model"][k])
        for k in ("w1", "w2")
    )
    return {
        "hot_exact": hot_exact,
        "resident_at_return": resident_at_return,
        "tail_exact": tail_exact,
        "final_resident": sess.resident_fraction(),
    }


@pytest.mark.multiprocess
def test_w2_first_inference_before_last_byte(tmp_path):
    results = run_with_subprocesses(
        _ttfi_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        timeout=180.0,
    )
    for rank, r in results.items():
        assert r["hot_exact"], (rank, results)
        assert r["resident_at_return"] < 1.0, (rank, results)
        assert r["tail_exact"], (rank, results)
        assert r["final_resident"] == 1.0, (rank, results)
