"""``blackbox`` CLI: merged cross-rank timelines from flight dumps.

Covers the CLI surface (exit codes, rendering, --json) over hand-built
dumps, and the store-failover chaos drill: SIGKILL the store leader
mid-take at w2 with one replica (the PR 6 headline schedule) — the take
commits through transparent failover, each rank spools its flight ring,
and ``blackbox`` names the adopted epoch per rank.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.cli import main
from torchsnapshot_tpu.telemetry import flightrec


def _write_dump(root, rank, records):
    d = os.path.join(root, ".flight")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"rank_{rank}.jsonl"), "w") as f:
        f.write(json.dumps({"seq": 0, "t": 0.0, "ev": "flight.dump",
                            "rank": rank, "reason": "test"}) + "\n")
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_blackbox_no_dumps_exits_2(tmp_path, capsys):
    assert main(["blackbox", str(tmp_path)]) == 2
    assert "no flight dumps" in capsys.readouterr().err


def test_blackbox_renders_stale_commit_with_generation(tmp_path, capsys):
    """A refused fenced commit is a finding that names the rank, both
    generations, and exits 1."""
    _write_dump(tmp_path, 0, [
        {"seq": 1, "t": 1.0, "ev": "fence.plant", "gen": "aaaa1111"},
        {"seq": 2, "t": 2.0, "ev": "commit.decision", "gen": "aaaa1111",
         "found": "bbbb2222", "ok": False},
        {"seq": 3, "t": 2.1, "ev": "op.abort", "op": "take",
         "error": "StaleCommitError(...)", "gen": "aaaa1111"},
    ])
    assert main(["blackbox", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "STALE-COMMIT" in out
    assert "rank 0" in out
    assert "aaaa1111" in out and "bbbb2222" in out


def test_blackbox_renders_store_failover_with_epoch(tmp_path, capsys):
    _write_dump(tmp_path, 1, [
        {"seq": 1, "t": 1.0, "ev": "store.failover", "epoch": 3,
         "leader": "127.0.0.1:4242", "cause": "ConnectionResetError()"},
    ])
    assert main(["blackbox", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "STORE-FAILOVER" in out
    assert "rank 1" in out
    assert "epoch 3" in out
    assert "127.0.0.1:4242" in out


def test_blackbox_json_mode(tmp_path, capsys):
    _write_dump(tmp_path, 0, [
        {"seq": 1, "t": 1.0, "ev": "op.begin", "op": "take"},
    ])
    assert main(["blackbox", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ranks"] == [0]
    assert doc["events"][0]["ev"] == "op.begin"
    assert doc["findings"] == []


def test_blackbox_clean_dump_exits_0(tmp_path, capsys):
    """A committed take's forced dump (operator `flightrec.dump`) has no
    findings: exit 0, timeline still rendered."""
    flightrec.set_enabled(True)
    flightrec.reset()
    state = {"model": StateDict(w=np.arange(10_000, dtype=np.float32))}
    cur = str(tmp_path / "cur")
    Snapshot.take(cur, state)
    flightrec.dump(cur, 0, "operator request")
    assert main(["blackbox", cur]) == 0
    out = capsys.readouterr().out
    assert "op.begin" in out
    assert "commit.decision" in out


# ----------------------------------------------- store-failover drill


STORE_KILL_PLAN = "dist_store.serve_op@14=kill;seed=601"


def _failover_worker(rank: int, world_size: int, root: str):
    from torchsnapshot_tpu.pg_wrapper import get_default_pg
    from torchsnapshot_tpu.telemetry import flightrec as fr

    fr.set_enabled(True)
    fr.reset()
    rng = np.random.default_rng(100 + rank)
    state = {"model": StateDict(w=rng.standard_normal(20_000).astype(np.float32))}
    path = os.path.join(root, "cur")
    Snapshot.take(path, state)
    # The take survived the leader kill via transparent failover — spool
    # the ring anyway (the operator's "what just happened" request; the
    # same dump an abort would have forced).
    fr.dump(path, rank, "post-drill audit")
    return {"failovers": get_default_pg().store.failovers}


@pytest.mark.multiprocess
def test_blackbox_names_store_failover_epoch_after_leader_kill(tmp_path, capsys):
    """The PR 6 headline schedule through the observability plane:
    SIGKILL the store leader at the 14th served op (w2, one replica);
    the take commits through failover, and blackbox's merged timeline
    names each rank's adopted epoch."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _failover_worker,
        2,
        str(tmp_path),
        timeout=180.0,
        store_replicas=1,
        store_lease_s=0.5,
        external_store=True,
        store_host_plan=STORE_KILL_PLAN,
    )
    for rank, out in results.items():
        assert out["failovers"] == 1, (rank, out)
    cur = str(tmp_path / "cur")
    assert os.path.exists(os.path.join(cur, ".snapshot_metadata"))
    rc = main(["blackbox", cur, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1  # the failover IS a finding
    failovers = [f for f in doc["findings"] if f["class"] == "store-failover"]
    # Both ranks adopted the promoted leader, at the SAME (higher) epoch.
    assert {f["rank"] for f in failovers} == {0, 1}, failovers
    epochs = {f["epoch"] for f in failovers}
    assert len(epochs) == 1 and min(epochs) >= 1, failovers
    rc = main(["blackbox", cur, "-v"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "STORE-FAILOVER" in out
    assert "rank 0 adopted leader" in out
    assert "rank 1 adopted leader" in out
    assert f"epoch {epochs.pop()}" in out
