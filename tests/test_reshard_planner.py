"""Device-free property tests for the minimal-movement reshard planner
(ISSUE 12). The plan is a pure function of (manifest entry, destination
boxes, world size); these tests drive randomized src x dst GSPMD layouts
through the planner and simulate the whole data movement in numpy:

- every destination byte is covered EXACTLY once (no hole, no double
  write) whichever mix of planned peer bundles and direct reads serves
  it, and the reassembled values are bit-exact;
- the plan is deterministic: identical across "ranks" (independent
  contexts), across repeat runs, and under permuted input dict order;
- owners are always requesters, and sub-threshold shards stay unclaimed.
"""

from __future__ import annotations

import numpy as np
import pytest

from torchsnapshot_tpu import reshard
from torchsnapshot_tpu.io_preparers.sharded import _overlap
from torchsnapshot_tpu.layout import LayoutSpec
from torchsnapshot_tpu.manifest import ArrayEntry, Shard, ShardedArrayEntry
from torchsnapshot_tpu.reshard import (
    PlannedUnit,
    ReshardContext,
    plan_entry_transfers,
    plan_summary,
)


def _entry_from_boxes(shape, boxes, dtype="float32", itemsize=4):
    """One saved shard per distinct source box (the save path's
    owner-only dedup), locations in box order."""
    shards = []
    for i, box in enumerate(sorted(set(boxes))):
        offsets = [lo for lo, _ in box]
        sizes = [hi - lo for lo, hi in box]
        shards.append(
            Shard(
                offsets=offsets,
                sizes=sizes,
                array=ArrayEntry(
                    location=f"sharded/model.w_{i}",
                    serializer="numpy",
                    dtype=dtype,
                    shape=sizes,
                    replicated=False,
                ),
            )
        )
    return ShardedArrayEntry(dtype=dtype, shape=list(shape), shards=shards)


def _src_boxes(layout, shape, spec):
    return [b for boxes in layout.boxes_by_rank(shape, spec, 1).values() for b in boxes]


def _simulate(entry, boxes_by_rank, world_size, global_arr, min_requesters=2):
    """Run the full planned+direct movement in numpy and return, per
    rank, {box: (reassembled array, write-count array)}."""
    ctxs = {
        r: ReshardContext(None, r, world_size, min_requesters=min_requesters)
        for r in range(world_size)
    }
    roles = {
        r: ctxs[r].plan_entry(entry, boxes_by_rank) or {}
        for r in range(world_size)
    }
    units = {
        u.shard_index: u
        for u in plan_entry_transfers(entry, boxes_by_rank, min_requesters)
    }

    out = {}
    for rank in range(world_size):
        out[rank] = {
            box: (
                np.zeros([hi - lo for lo, hi in box], global_arr.dtype),
                np.zeros([hi - lo for lo, hi in box], np.int32),
            )
            for box in boxes_by_rank[rank]
        }

    for i, shard in enumerate(entry.shards):
        lo = tuple(shard.offsets)
        stored = global_arr[
            tuple(slice(o, o + s) for o, s in zip(shard.offsets, shard.sizes))
        ]
        unit = units.get(i)
        for rank in range(world_size):
            role = roles[rank].get(i)
            if isinstance(role, reshard.RecvUnit):
                # Wire simulation: the owner serializes this receiver's
                # bundle from ITS role (src slices in sorted-box order);
                # the receiver scatters from ITS role's dst regions.
                owner_role = roles[role.owner][i]
                assert isinstance(owner_role, reshard.OwnerUnit)
                bundle = next(
                    (srcs for sub, _key, srcs in owner_role.bundles if sub == rank)
                )
                payload = b"".join(
                    np.ascontiguousarray(stored[src]).tobytes() for src in bundle
                )
                pos = 0
                for box, dst_slices, shape in role.regions:
                    n = global_arr.itemsize * int(np.prod(shape, dtype=np.int64))
                    region = np.frombuffer(
                        payload[pos : pos + n], global_arr.dtype
                    ).reshape(shape)
                    buf, count = out[rank][box]
                    buf[dst_slices] = region
                    count[dst_slices] += 1
                    pos += n
                assert pos == len(payload), "trailing bundle bytes"
            else:
                # Owner local scatter, or an unclaimed shard's direct
                # read: the existing overlap-scatter path.
                if unit is not None and (
                    role is None and rank in unit.requesters
                ):
                    raise AssertionError(
                        f"rank {rank} requests claimed shard {i} but got no role"
                    )
                for box in boxes_by_rank[rank]:
                    ov = _overlap(shard.offsets, shard.sizes, box)
                    if ov is None:
                        continue
                    if unit is not None and unit.owner != rank:
                        continue  # non-owner requesters go via the wire
                    src_slices, dst_slices = ov
                    buf, count = out[rank][box]
                    buf[dst_slices] = stored[src_slices]
                    count[dst_slices] += 1
    return out, roles, units


_LAYOUT_CASES = [
    # (shape, mesh_src, spec_src, mesh_dst, spec_dst, world_dst)
    ((16, 8), [("x", 2)], [("x",)], [("x", 4)], [(), ("x",)], 4),  # tp2->tp4 cross-cut
    ((16, 8), [("x", 4)], [(), ("x",)], [("x", 2)], [("x",)], 2),  # reverse
    ((24, 12), [("x", 2), ("y", 2)], [("x",), ("y",)],
     [("x", 4), ("y", 2)], [("y",), ("x",)], 8),  # 2D -> transposed 2D
    ((24, 12), [("x", 4)], [("x",)], [("x", 2), ("y", 2)],
     [("x", "y"), ()], 4),  # same dim, finer tiling
    ((32,), [("x", 2)], [("x",)], [("x", 8)], [("x",)], 8),  # 1D refine
    ((16, 8), [("x", 2)], [("x",)], [("x", 4)], [], 4),  # -> replicated
]


@pytest.mark.parametrize("case", _LAYOUT_CASES)
def test_every_destination_byte_covered_exactly_once(case) -> None:
    shape, mesh_src, spec_src, mesh_dst, spec_dst, world = case
    src = LayoutSpec(mesh_src)
    dst = LayoutSpec(mesh_dst)
    entry = _entry_from_boxes(shape, _src_boxes(src, shape, spec_src))
    boxes_by_rank = dst.boxes_by_rank(shape, spec_dst, world)
    rng = np.random.default_rng(7)
    global_arr = rng.standard_normal(shape).astype(np.float32)

    out, _roles, units = _simulate(entry, boxes_by_rank, world, global_arr)
    for rank, per_box in out.items():
        for box, (buf, count) in per_box.items():
            expected = global_arr[tuple(slice(lo, hi) for lo, hi in box)]
            assert (count == 1).all(), (
                f"rank {rank} box {box}: coverage {count.min()}..{count.max()}"
            )
            np.testing.assert_array_equal(buf, expected)
    # Cross-cut cases actually exercise the wire.
    if spec_dst and units:
        assert any(len(u.requesters) > 1 for u in units.values())


def test_randomized_layout_pairs() -> None:
    """Fuzz src x dst over random meshes/specs; the exactly-once +
    bit-exact invariant must hold for every pair."""
    rng = np.random.default_rng(1234)
    shape = (24, 16)
    dims = ["x", "y"]
    for trial in range(30):
        sizes = [int(rng.choice([1, 2, 4])) for _ in dims]
        mesh = [(d, s) for d, s in zip(dims, sizes)]

        def rand_spec(r=rng):
            # Valid GSPMD specs only: a mesh axis appears at most once
            # across the whole spec (the compiler rejects reuse).
            pairs = [
                ((), ()), (("x",), ()), ((), ("x",)), (("y",), ()),
                ((), ("y",)), (("x",), ("y",)), (("y",), ("x",)),
                (("x", "y"), ()), ((), ("x", "y")), (("y", "x"), ()),
            ]
            return list(pairs[r.integers(len(pairs))])

        src = LayoutSpec(mesh)
        dst = LayoutSpec(mesh)
        spec_src, spec_dst = rand_spec(), rand_spec()
        world = int(rng.choice([1, 2, 4]))
        if src.n_devices % world:
            world = 1
        try:
            entry = _entry_from_boxes(shape, _src_boxes(src, shape, spec_src))
            boxes_by_rank = dst.boxes_by_rank(shape, spec_dst, world)
        except ValueError:
            continue  # untileable combination; the compiler rejected it
        global_arr = rng.standard_normal(shape).astype(np.float32)
        out, _roles, _units = _simulate(entry, boxes_by_rank, world, global_arr)
        for rank, per_box in out.items():
            for box, (buf, count) in per_box.items():
                assert (count == 1).all(), (trial, rank, box)
                np.testing.assert_array_equal(
                    buf, global_arr[tuple(slice(lo, hi) for lo, hi in box)]
                )


def test_plan_is_deterministic_across_ranks_and_order() -> None:
    src = LayoutSpec([("x", 2)])
    dst = LayoutSpec([("x", 4)])
    shape = (16, 8)
    entry = _entry_from_boxes(shape, _src_boxes(src, shape, [("x",)]))
    boxes = dst.boxes_by_rank(shape, [(), ("x",)], 4)

    baseline = plan_entry_transfers(entry, boxes)
    assert baseline == plan_entry_transfers(entry, boxes)  # repeatable
    # Dict insertion order must not matter (no set/dict-order iteration).
    reversed_boxes = {r: boxes[r] for r in sorted(boxes, reverse=True)}
    assert plan_entry_transfers(entry, reversed_boxes) == baseline
    # Per-rank role projections agree with the shared plan: every
    # receiver's (key, owner) has a matching owner-side bundle.
    ctxs = {r: ReshardContext(None, r, 4) for r in range(4)}
    roles = {r: ctxs[r].plan_entry(entry, boxes) or {} for r in range(4)}
    for rank, per_shard in roles.items():
        for i, role in per_shard.items():
            if isinstance(role, reshard.RecvUnit):
                owner_role = roles[role.owner][i]
                keys = [key for _sub, key, _src in owner_role.bundles]
                assert role.key in keys, (rank, i)


def test_owner_is_always_a_requester_and_balanced() -> None:
    src = LayoutSpec([("x", 4)])
    dst = LayoutSpec([("x", 4)])
    shape = (32, 8)
    entry = _entry_from_boxes(shape, _src_boxes(src, shape, [("x",)]))
    boxes = dst.boxes_by_rank(shape, [(), ("x",)], 4)
    units = plan_entry_transfers(entry, boxes)
    assert len(units) == 4
    for u in units:
        assert u.owner in u.requesters
        assert u.requesters == tuple(sorted(u.requesters))
    # 4 equal units over 4 mutually-eligible ranks: one owner each.
    assert sorted(u.owner for u in units) == [0, 1, 2, 3]


def test_min_requesters_threshold() -> None:
    # Identical src/dst layouts: each shard wanted by exactly one rank —
    # nothing to dedup, the planner claims nothing, and no context
    # fabricates roles.
    layout = LayoutSpec([("x", 2)])
    shape = (16, 8)
    entry = _entry_from_boxes(shape, _src_boxes(layout, shape, [("x",)]))
    boxes = layout.boxes_by_rank(shape, [("x",)], 2)
    assert plan_entry_transfers(entry, boxes) == []
    assert ReshardContext(None, 0, 2).plan_entry(entry, boxes) is None
    # Raising the threshold un-claims shards a lower one would claim.
    dst = LayoutSpec([("x", 4)])
    boxes4 = dst.boxes_by_rank(shape, [(), ("x",)], 4)
    assert len(plan_entry_transfers(entry, boxes4, min_requesters=2)) == 2
    assert plan_entry_transfers(entry, boxes4, min_requesters=5) == []


def test_plan_summary_accounting() -> None:
    # w2 rows -> w4 cols over (16, 8) fp32: 2 shards of 256 B, each
    # wanted by all 4 ranks. Direct: 2*4*256 = 2048. Planned: one owner
    # read per shard = 512. Peer: 3 non-owners x (8x2 fp32 = 64 B) per
    # shard = 384.
    src = LayoutSpec([("x", 2)])
    dst = LayoutSpec([("x", 4)])
    shape = (16, 8)
    entry = _entry_from_boxes(shape, _src_boxes(src, shape, [("x",)]))
    boxes = dst.boxes_by_rank(shape, [(), ("x",)], 4)
    summary = plan_summary(entry, boxes)
    assert summary == {
        "shards": 2,
        "planned_units": 2,
        "direct_bytes_from_storage": 2048,
        "planned_bytes_from_storage": 512,
        "planned_peer_bytes": 384,
    }
    assert (
        summary["direct_bytes_from_storage"]
        >= 3 * summary["planned_bytes_from_storage"]
    )
    # Unclaimed plans read exactly what the direct path reads.
    same = LayoutSpec([("x", 2)])
    boxes_same = same.boxes_by_rank(shape, [("x",)], 2)
    s2 = plan_summary(entry, boxes_same)
    assert s2["planned_units"] == 0
    assert s2["planned_bytes_from_storage"] == s2["direct_bytes_from_storage"]


def test_planned_unit_fields() -> None:
    u = PlannedUnit(shard_index=3, owner=1, requesters=(0, 1, 2), nbytes=128)
    assert u.owner in u.requesters
    with pytest.raises(Exception):
        u.owner = 2  # frozen


def test_plan_scales_to_50k_shards_bounded() -> None:
    """A slice of the 50k-shard cardinality the benchmarks pin (the
    full-size wall bound lives in benchmarks/manifest_scale.py's plan
    leg): ~3.4k shards across 210 entries, planned into a 32-way
    destination, bounded here so a planner complexity regression fails
    tier-1 and not just the bench."""
    import importlib.util
    import os
    import time

    path = os.path.join(
        os.path.dirname(__file__), "..", "benchmarks", "manifest_scale.py"
    )
    spec_obj = importlib.util.spec_from_file_location("manifest_scale", path)
    manifest_scale = importlib.util.module_from_spec(spec_obj)
    spec_obj.loader.exec_module(manifest_scale)

    manifest = manifest_scale.build_manifest(n_params=70, n_ranks=16)
    entries = [e for e in manifest.values() if isinstance(e, ShardedArrayEntry)]
    dst = LayoutSpec([("x", 32)])
    t0 = time.monotonic()
    total_units = 0
    for entry in entries:
        boxes = dst.boxes_by_rank(entry.shape, [(), ("x",)], 32)
        total_units += len(plan_entry_transfers(entry, boxes))
    elapsed = time.monotonic() - t0
    assert total_units > 0
    assert elapsed < 30.0, f"{len(entries)} entries took {elapsed:.1f}s"
