"""SSM (associative-scan) sequence mixing correctness.

Oracle: a per-step Python recurrence. Covers the scan vs the naive
recurrence, chunked scan with carried state (the resumable-training
invariant), sequence-parallel scan vs single-device, and gradient flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchsnapshot_tpu.ops.ssm import (
    init_ssm_params,
    ssm_mix,
    ssm_mix_sharded,
    ssm_scan,
)

B, S, D, N = 2, 16, 8, 4


def naive_scan(a, b, h0=None):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    h = np.zeros_like(b)
    prev = np.zeros(b[:, 0].shape) if h0 is None else np.asarray(h0, np.float64)
    for t in range(a.shape[1]):
        prev = a[:, t] * prev + b[:, t]
        h[:, t] = prev
    return h


def test_scan_matches_naive_recurrence() -> None:
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(0.1, 0.99, (B, S, D, N)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((B, S, D, N)), jnp.float32)
    h = ssm_scan(a, b)
    np.testing.assert_allclose(np.asarray(h), naive_scan(a, b), atol=1e-4)


def test_scan_with_initial_state() -> None:
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.uniform(0.1, 0.99, (B, S, D, N)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((B, S, D, N)), jnp.float32)
    h0 = jnp.asarray(rng.standard_normal((B, D, N)), jnp.float32)
    h = ssm_scan(a, b, h0)
    np.testing.assert_allclose(np.asarray(h), naive_scan(a, b, h0), atol=1e-4)


@pytest.mark.slow
def test_chunked_scan_resumes_exactly() -> None:
    """Scanning two halves with the carried state == scanning the whole —
    the invariant that makes the final state a checkpointable cursor."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((B, S, D)), jnp.float32)
    params = init_ssm_params(jax.random.PRNGKey(0), D, N)
    y_full, h_full = ssm_mix(params, x)
    y1, h1 = ssm_mix(params, x[:, : S // 2])
    y2, h2 = ssm_mix(params, x[:, S // 2 :], h0=h1)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate([y1, y2], axis=1)),
        np.asarray(y_full),
        atol=1e-5,
    )
    np.testing.assert_allclose(np.asarray(h2), np.asarray(h_full), atol=1e-5)


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_sharded_scan_matches_single_device(ring: int) -> None:
    mesh = Mesh(np.array(jax.devices()[:ring]).reshape(ring), ("seq",))
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((B, S, D)), jnp.float32)
    params = init_ssm_params(jax.random.PRNGKey(1), D, N)
    y_ref, h_ref = ssm_mix(params, x)
    y, h = jax.jit(lambda p, x: ssm_mix_sharded(p, x, mesh))(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref), atol=1e-5)


def test_sharded_scan_with_initial_state() -> None:
    """Sequence-parallel resume: h0 in, global final state out — identical
    to the single-device chunked run."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((B, S, D)), jnp.float32)
    params = init_ssm_params(jax.random.PRNGKey(6), D, N)
    _, h_mid = ssm_mix(params, x[:, : S // 2])
    y_ref, h_ref = ssm_mix(params, x[:, S // 2 :], h0=h_mid)
    y, h = jax.jit(lambda p, x, h0: ssm_mix_sharded(p, x, mesh, h0=h0))(
        params, x[:, S // 2 :], h_mid
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref), atol=1e-5)


@pytest.mark.slow
def test_sharded_ssm_gradients_flow() -> None:
    """The sequence-parallel path must be trainable (reverse-mode through
    the cross-chunk carry fold)."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    params = init_ssm_params(jax.random.PRNGKey(7), D, N)
    x = jax.random.normal(jax.random.PRNGKey(8), (B, S, D))

    def loss(params):
        y, _ = ssm_mix_sharded(params, x, mesh)
        return jnp.sum(y**2)

    grads = jax.jit(jax.grad(loss))(params)
    ref = jax.grad(lambda p: jnp.sum(ssm_mix(p, x)[0] ** 2))(params)
    for g, r in zip(
        jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref)
    ):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=1e-5, atol=1e-4
        )


def test_sharded_state_dtype_matches_single_device() -> None:
    """The carried state is f32 on BOTH paths — bf16 runs must not lose
    state mantissa at chunk boundaries only when sequence-sharded."""
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("seq",))
    params = init_ssm_params(jax.random.PRNGKey(9), D, N)
    x = jax.random.normal(jax.random.PRNGKey(10), (B, S, D), jnp.bfloat16)
    _, h_single = ssm_mix(params, x)
    _, h_sharded = jax.jit(lambda p, x: ssm_mix_sharded(p, x, mesh))(params, x)
    assert h_single.dtype == jnp.float32
    assert h_sharded.dtype == jnp.float32


def test_ssm_gradients_flow() -> None:
    params = init_ssm_params(jax.random.PRNGKey(2), D, N)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, S, D))

    def loss(params):
        y, _ = ssm_mix(params, x)
        return jnp.sum(y**2)

    grads = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
        assert np.abs(arr).sum() > 0


@pytest.mark.slow
def test_ssm_state_snapshot_roundtrip(tmp_path) -> None:
    """The recurrent state is a checkpointable cursor: snapshot mid-sequence,
    restore, resume — identical to the uninterrupted run."""
    from torchsnapshot_tpu import Snapshot, StateDict

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((B, S, D)), jnp.float32)
    params = init_ssm_params(jax.random.PRNGKey(4), D, N)
    y_full, _ = ssm_mix(params, x)

    _, h_mid = ssm_mix(params, x[:, : S // 2])
    Snapshot.take(
        str(tmp_path / "s"),
        {"cursor": StateDict(h=h_mid, params=params)},
    )
    dst = StateDict(
        h=jnp.zeros_like(h_mid),
        params=jax.tree_util.tree_map(jnp.zeros_like, params),
    )
    Snapshot(str(tmp_path / "s")).restore({"cursor": dst})
    y2, _ = ssm_mix(dst["params"], x[:, S // 2 :], h0=dst["h"])
    np.testing.assert_allclose(
        np.asarray(y2), np.asarray(y_full[:, S // 2 :]), atol=1e-5
    )


@pytest.mark.slow
def test_ssm_lm_trains_and_checkpoints(tmp_path) -> None:
    """The SSM LM trains on a dp x sp x tp mesh, checkpoints, restores onto
    the same mesh, and resumes — the model-family end-to-end loop."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import ssm_lm
    from torchsnapshot_tpu.models.transformer import make_optimizer

    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model"))
    cfg = ssm_lm.SSMConfig(
        vocab_size=64, d_model=16, d_state=4, n_layers=2, d_ff=32
    )
    tx = make_optimizer()
    state = ssm_lm.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    step = jax.jit(ssm_lm.make_train_step(cfg, tx, mesh=mesh))
    batch = {
        "tokens": jnp.zeros((4, 16), jnp.int32),
        "targets": jnp.zeros((4, 16), jnp.int32),
    }
    batch = jax.device_put(batch, NamedSharding(mesh, P("data", "seq")))
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))

    Snapshot.take(str(tmp_path / "s"), {"train": StateDict(state=state)})
    dst = {
        "train": StateDict(
            state=ssm_lm.init_state(jax.random.PRNGKey(9), cfg, tx, mesh=mesh)
        )
    }
    Snapshot(str(tmp_path / "s")).restore(dst)
    for a, b in zip(
        jax.tree_util.tree_leaves(state),
        jax.tree_util.tree_leaves(dst["train"]["state"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state2, loss2 = step(dst["train"]["state"], batch)
    assert int(state2["step"]) == 2 and np.isfinite(float(loss2))


@pytest.mark.slow
def test_ssm_lm_sharded_forward_matches_unsharded() -> None:
    from torchsnapshot_tpu.models import ssm_lm

    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model"))
    cfg = ssm_lm.SSMConfig(vocab_size=64, d_model=16, d_state=4, n_layers=2, d_ff=32)
    params = ssm_lm.init_params(jax.random.PRNGKey(1), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)
    ref = ssm_lm.forward(params, tokens, cfg)
    from jax.sharding import NamedSharding, PartitionSpec as P

    st = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))
    out = jax.jit(lambda p, t: ssm_lm.forward(p, t, cfg, mesh=mesh))(params, st)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
