"""Preemption-aware emergency checkpointing (preemption.py).

The hard property is collective consistency: cloud preemption SIGTERMs a
SUBSET of hosts, yet every rank must make the same save-now decision or
the collective take hangs. Single-process tests use SIGUSR1 (so pytest
itself never sees a SIGTERM); the multiprocess drill sends a real
SIGTERM to ONE rank of a 2-process ``jax.distributed`` world and both
ranks must commit the same emergency snapshot.
"""

from __future__ import annotations

import os
import signal

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import PreemptionWatcher, Snapshot, StateDict
from torchsnapshot_tpu.manager import CheckpointManager


@pytest.fixture
def watcher():
    w = PreemptionWatcher(signals=(signal.SIGUSR1,))
    yield w
    w.close()


def _fire() -> None:
    os.kill(os.getpid(), signal.SIGUSR1)


def test_flag_and_should_save(watcher):
    assert not watcher.preempted
    assert not watcher.should_save()
    _fire()
    assert watcher.preempted
    assert watcher.should_save()
    # Not consumed until a save handles it.
    assert not watcher.consumed
    watcher.consume()
    assert watcher.consumed


def test_previous_handler_chained():
    hits = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: hits.append(s))
    try:
        w = PreemptionWatcher(signals=(signal.SIGUSR1,))
        try:
            _fire()
            assert w.preempted
            assert hits == [signal.SIGUSR1]  # the old handler still ran
        finally:
            w.close()
        # close() restored the previous handler.
        _fire()
        assert hits == [signal.SIGUSR1, signal.SIGUSR1]
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_manager_emergency_save_off_cadence(tmp_path, watcher):
    w = jnp.arange(256, dtype=jnp.float32)
    mgr = CheckpointManager(
        str(tmp_path / "ckpts"), save_interval_steps=100, preemption=watcher
    )
    state = {"m": StateDict(w=w)}
    assert not mgr.save(1, state)  # not due, no preemption
    _fire()
    assert mgr.save(2, state)  # off-cadence emergency save
    assert watcher.consumed
    assert mgr.all_steps() == [2]
    # Grace-window loop continues: no re-save every step.
    assert not mgr.save(3, state)
    dst = {"m": StateDict(w=jnp.zeros_like(w))}
    Snapshot(mgr.path_for(2)).restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


def test_emergency_save_is_synchronous(tmp_path, watcher):
    """async_save managers still commit emergency snapshots before save()
    returns — the process is about to die."""
    w = jnp.arange(256, dtype=jnp.float32)
    mgr = CheckpointManager(
        str(tmp_path / "ckpts"),
        save_interval_steps=100,
        async_save=True,
        preemption=watcher,
    )
    _fire()
    assert mgr.save(5, {"m": StateDict(w=w)})
    # Committed synchronously: no pending handle, metadata on disk.
    assert mgr._pending is None
    assert mgr.all_steps() == [5]


def test_simulate_helper_uses_sigterm():
    from torchsnapshot_tpu import simulate_preemption_now

    w = PreemptionWatcher()  # default: SIGTERM
    try:
        simulate_preemption_now()
        assert w.preempted
    finally:
        w.close()


def _preemption_drill_worker(rank: int, world_size: int, root: str):
    """Rank 0 alone receives SIGTERM; the collective decision must bring
    BOTH ranks into the same emergency save."""
    from torchsnapshot_tpu import PreemptionWatcher, StateDict
    from torchsnapshot_tpu.manager import CheckpointManager

    watcher = PreemptionWatcher()  # SIGTERM
    try:
        mgr = CheckpointManager(
            root, save_interval_steps=1000, preemption=watcher
        )
        state = {
            "model": StateDict(w=np.arange(64, dtype=np.float32)),
            "local": StateDict(r=np.full((4,), rank, dtype=np.int32)),
        }
        saved_at = None
        last_step = None
        for step in range(1, 100):
            last_step = step
            if rank == 0 and step == 4:
                os.kill(os.getpid(), signal.SIGTERM)
            if mgr.save(step, state):
                saved_at = step
            if watcher.consumed:  # the documented recipe: set on EVERY
                break             # rank, so all exit the loop together
        assert saved_at == 4, saved_at
        assert last_step == 4, last_step  # both ranks broke immediately
        assert watcher.consumed
        assert not mgr._pending  # synchronous commit
        return saved_at
    finally:
        watcher.close()


@pytest.mark.multiprocess
def test_multiprocess_preemption_drill(tmp_path):
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _preemption_drill_worker, 2, str(tmp_path / "ckpts")
    )
    assert set(results.values()) == {4}
    # The emergency snapshot is complete and restorable.
    dst = {
        "model": StateDict(w=jnp.zeros(64, jnp.float32)),
        "local": StateDict(r=np.zeros((4,), np.int32)),
    }
    Snapshot(str(tmp_path / "ckpts" / "step_0000000004")).restore(dst)
    np.testing.assert_array_equal(
        np.asarray(dst["model"]["w"]), np.arange(64, dtype=np.float32)
    )


def test_emergency_at_already_committed_step_consumes(tmp_path, watcher):
    """Resume recipe: the loop re-runs the restored step; a preemption
    there finds the step already committed — the existing snapshot IS the
    resume point, and the watcher must still be consumed so the loop's
    consumed-break fires."""
    w = jnp.arange(64, dtype=jnp.float32)
    state = {"m": StateDict(w=w)}
    mgr = CheckpointManager(str(tmp_path / "ckpts"), preemption=watcher)
    assert mgr.save(3, state)
    mgr2 = CheckpointManager(str(tmp_path / "ckpts"), preemption=watcher)
    assert mgr2.restore(state) == 3
    _fire()
    assert not mgr2.save(3, state)  # nothing re-saved ...
    assert watcher.consumed  # ... but the preemption is handled
    assert mgr2.all_steps() == [3]


def test_explicit_none_pg_is_authoritative(tmp_path):
    """An explicit pg (even None) to should_save never falls back to the
    watcher's constructor group — the manager's group always wins."""

    class FakeSubgroupPG:
        # A watcher constructed over some subgroup object; if should_save
        # fell back to it, PGWrapper would choke on this non-pg — the
        # test passes only because the explicit pg=None wins.
        pass

    w = PreemptionWatcher(pg=FakeSubgroupPG(), signals=(signal.SIGUSR1,))
    try:
        _fire()
        assert w.should_save(pg=None) is True  # default group: world 1
    finally:
        w.close()


def test_handler_does_not_log(watcher, caplog):
    """The handler itself must not touch logging (stream reentrancy at
    eviction time); the record is emitted lazily from should_save."""
    import logging

    with caplog.at_level(logging.WARNING, logger="torchsnapshot_tpu.preemption"):
        _fire()
        assert caplog.records == []  # nothing logged inside the handler
        assert watcher.should_save()
    assert any("flagged for emergency" in r.message for r in caplog.records)
