"""Embedding-model checkpointing tests (reference analogue:
tests/gpu_tests/test_torchrec.py — row-wise sharded tables round-trip and
reshard across layouts)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.models import embedding as E
from torchsnapshot_tpu.parallel import make_mesh

CFG = E.EmbeddingConfig(n_tables=3, rows_per_table=64, dim=8, mlp_hidden=(16,))


def _batch(key, n=16):
    kd, ks, kl = jax.random.split(key, 3)
    return {
        "dense": jax.random.normal(kd, (n, CFG.n_dense_features)),
        "sparse_ids": jax.random.randint(ks, (n, CFG.n_tables), 0, CFG.rows_per_table),
        "labels": jax.random.bernoulli(kl, 0.5, (n,)).astype(jnp.float32),
    }


def test_train_step_runs():
    tx = optax.adagrad(1e-2)
    mesh = make_mesh(devices=jax.devices())
    state = E.init_state(jax.random.PRNGKey(0), CFG, tx, mesh=mesh)
    step = jax.jit(E.make_train_step(CFG, tx, mesh=mesh))
    state2, loss = step(state, _batch(jax.random.PRNGKey(1)))
    assert np.isfinite(float(loss))
    assert int(state2["step"]) == 1


def test_rowwise_sharded_roundtrip(tmp_path):
    tx = optax.adagrad(1e-2)
    mesh = make_mesh(devices=jax.devices())
    state = E.init_state(jax.random.PRNGKey(0), CFG, tx, mesh=mesh)
    # advance one step so adagrad accumulators are non-trivial
    step = jax.jit(E.make_train_step(CFG, tx, mesh=mesh))
    state, _ = step(state, _batch(jax.random.PRNGKey(1)))

    Snapshot.take(str(tmp_path / "snap"), {"train": StateDict(**state)})

    fresh = E.init_state(jax.random.PRNGKey(9), CFG, tx, mesh=mesh)
    dst = {"train": StateDict(**fresh)}
    Snapshot(str(tmp_path / "snap")).restore(dst)

    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(dst["train"].data)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
    # restored table keeps the row-wise sharding of the destination
    t0 = dst["train"]["params"]["tables"]["table_0"]
    assert t0.sharding.spec == E.param_specs(CFG)["tables"]["table_0"]


def test_reshard_rowwise_to_replicated(tmp_path):
    """Row-wise saved tables restore into a replicated destination (the
    cross-layout matrix case rw -> replicated)."""
    tx = optax.adagrad(1e-2)
    mesh = make_mesh(devices=jax.devices())
    state = E.init_state(jax.random.PRNGKey(0), CFG, tx, mesh=mesh)
    Snapshot.take(str(tmp_path / "snap"), {"train": StateDict(**state)})

    plain = E.init_state(jax.random.PRNGKey(9), CFG, tx, mesh=None)
    dst = {"train": StateDict(**plain)}
    Snapshot(str(tmp_path / "snap")).restore(dst)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state["params"]["tables"]["table_1"])),
        np.asarray(jax.device_get(dst["train"]["params"]["tables"]["table_1"])),
    )
