"""Ring / blockwise attention correctness on a virtual 8-device CPU mesh.

Oracle: dense O(S^2) attention. Ring attention over a 'seq' mesh axis and
flash-style blockwise attention must match it to float tolerance, forward
and backward (the reference's round-trip-equality pattern, SURVEY.md §4.1,
applied to ops instead of snapshots).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu.ops import (
    blockwise_attention,
    dense_attention,
    ring_attention_sharded,
    ulysses_attention_sharded,
    zigzag_ring_attention_sharded,
)

B, S, H, D = 2, 32, 4, 8


def make_qkv(seed: int = 0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_size", [8, 16, 32])
def test_blockwise_matches_dense(causal: bool, block_size: int) -> None:
    q, k, v = make_qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=block_size, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("mesh_shape", [{"seq": 4}, {"data": 2, "seq": 4}])
def test_ring_matches_dense(causal: bool, mesh_shape) -> None:
    devices = np.array(jax.devices()[: np.prod(list(mesh_shape.values()))])
    mesh = Mesh(devices.reshape(tuple(mesh_shape.values())), tuple(mesh_shape))
    q, k, v = make_qkv(seed=1)
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("mesh_shape", [{"seq": 2}, {"seq": 4}, {"data": 2, "seq": 4}])
def test_zigzag_ring_matches_dense(mesh_shape) -> None:
    """Causally load-balanced ring == dense oracle (zigzag layout applied
    and inverted by the wrapper)."""
    devices = np.array(jax.devices()[: np.prod(list(mesh_shape.values()))])
    mesh = Mesh(devices.reshape(tuple(mesh_shape.values())), tuple(mesh_shape))
    q, k, v = make_qkv(seed=7)
    ref = dense_attention(q, k, v, causal=True)
    out = zigzag_ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_zigzag_ring_composes_with_head_sharding() -> None:
    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    q, k, v = make_qkv(seed=8)
    ref = dense_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: zigzag_ring_attention_sharded(q, k, v, mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_zigzag_ring_gradients_match_dense() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    q, k, v = make_qkv(seed=9)

    def loss_z(q, k, v):
        return jnp.sum(zigzag_ring_attention_sharded(q, k, v, mesh) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_z = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for gz, gd in zip(g_z, g_d):
        np.testing.assert_allclose(np.asarray(gz), np.asarray(gd), atol=1e-4)


def test_zigzag_layout_roundtrip() -> None:
    from torchsnapshot_tpu.ops.ring_attention import zigzag_layout_indices

    idx = np.asarray(zigzag_layout_indices(32, 4))
    assert sorted(idx.tolist()) == list(range(32))
    # device i's shard (8 positions) = chunks i and 2n-1-i (chunk=4)
    for i in range(4):
        shard = idx[i * 8 : (i + 1) * 8]
        lo, hi = shard[:4], shard[4:]
        assert lo.tolist() == list(range(i * 4, (i + 1) * 4))
        c = 2 * 4 - 1 - i
        assert hi.tolist() == list(range(c * 4, (c + 1) * 4))


def test_zigzag_indivisible_raises() -> None:
    from torchsnapshot_tpu.ops.ring_attention import zigzag_layout_indices

    with pytest.raises(ValueError, match="divisible"):
        zigzag_layout_indices(36, 4)


def test_ring_composes_with_head_sharding() -> None:
    """cp x tp: heads sharded over 'model' inside the ring shard_map."""
    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    q, k, v = make_qkv(seed=2)
    ref = dense_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_ring_gradients_match_dense() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    q, k, v = make_qkv(seed=3)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=1e-4)


@pytest.mark.slow
def test_ring_transformer_forward_matches_dense() -> None:
    """Full model: ring/cp sharded forward == single-device dense forward."""
    from torchsnapshot_tpu.models import transformer as T

    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    base = dict(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=S, dtype=jnp.float32,
    )
    cfg_dense = T.TransformerConfig(**base)
    cfg_ring = T.TransformerConfig(**base, attn_impl="ring")
    params = T.init_params(jax.random.PRNGKey(0), cfg_dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, S), 0, 128)

    ref = T.forward(params, tokens, cfg_dense)
    sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))
    out = jax.jit(lambda p, t: T.forward(p, t, cfg_ring, mesh=mesh))(
        params, sharded_tokens
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_zigzag_transformer_forward_matches_dense() -> None:
    """Full model with attn_impl='zigzag' == single-device dense forward."""
    from torchsnapshot_tpu.models import transformer as T

    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    base = dict(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=S, dtype=jnp.float32,
    )
    cfg_dense = T.TransformerConfig(**base)
    cfg_zz = T.TransformerConfig(**base, attn_impl="zigzag")
    params = T.init_params(jax.random.PRNGKey(0), cfg_dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, S), 0, 128)

    ref = T.forward(params, tokens, cfg_dense)
    sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))
    out = jax.jit(lambda p, t: T.forward(p, t, cfg_zz, mesh=mesh))(
        params, sharded_tokens
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("mesh_shape", [{"seq": 4}, {"data": 2, "seq": 2}])
def test_ulysses_matches_dense(causal: bool, mesh_shape) -> None:
    devices = np.array(jax.devices()[: np.prod(list(mesh_shape.values()))])
    mesh = Mesh(devices.reshape(tuple(mesh_shape.values())), tuple(mesh_shape))
    q, k, v = make_qkv(seed=4)
    ref = dense_attention(q, k, v, causal=causal)
    out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_composes_with_head_sharding() -> None:
    """cp x tp: the all_to_all further splits the tp-local head group."""
    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    q, k, v = make_qkv(seed=5)
    ref = dense_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_ulysses_gradients_match_dense() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    q, k, v = make_qkv(seed=6)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for gu, gd in zip(g_u, g_d):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gd), atol=1e-4)


def test_ulysses_head_starved_raises() -> None:
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
    q, k, v = make_qkv(seed=7)  # H=4 < 8-way seq axis
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, k, v, mesh, causal=True)


def test_ulysses_transformer_forward_matches_dense() -> None:
    from torchsnapshot_tpu.models import transformer as T

    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    base = dict(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=S, dtype=jnp.float32,
    )
    cfg_dense = T.TransformerConfig(**base)
    cfg_u = T.TransformerConfig(**base, attn_impl="ulysses")
    params = T.init_params(jax.random.PRNGKey(0), cfg_dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, S), 0, 128)

    ref = T.forward(params, tokens, cfg_dense)
    sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))
    out = jax.jit(lambda p, t: T.forward(p, t, cfg_u, mesh=mesh))(
        params, sharded_tokens
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


@pytest.mark.slow
def test_ring_train_step_runs_and_checkpoints(tmp_path) -> None:
    """The cp-sharded training state round-trips through Snapshot."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import transformer as T

    mesh = Mesh(
        np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model")
    )
    cfg = T.TransformerConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, dtype=jnp.float32, attn_impl="ring",
    )
    tx = T.make_optimizer()
    state = T.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    step = jax.jit(T.make_train_step(cfg, tx, mesh=mesh))
    batch = {
        "tokens": jnp.zeros((4, 16), jnp.int32),
        "targets": jnp.zeros((4, 16), jnp.int32),
    }
    batch = jax.device_put(batch, NamedSharding(mesh, P("data", "seq")))
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))

    app_state = {"train": StateDict(state=state)}
    Snapshot.take(str(tmp_path / "snap"), app_state)
    restored_tmpl = T.init_state(jax.random.PRNGKey(7), cfg, tx, mesh=mesh)
    dst = {"train": StateDict(state=restored_tmpl)}
    Snapshot(str(tmp_path / "snap")).restore(dst)
    orig = jax.tree_util.tree_leaves(state)
    got = jax.tree_util.tree_leaves(dst["train"]["state"])
    for a, b in zip(orig, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Resume training from the restored state: restored leaves come back
    # committed to their destination shardings, and the jitted step must
    # accept the mix (regression: uncommitted scalars in init_state made
    # restored state un-resumable).
    state2, loss2 = step(dst["train"]["state"], batch)
    assert np.isfinite(float(loss2))
    assert int(state2["step"]) == 2


# ------------------------------------------------------------- ring-flash

@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("mesh_shape", [{"seq": 2}, {"seq": 4}, {"data": 2, "seq": 4}])
def test_ring_flash_matches_dense(causal: bool, mesh_shape) -> None:
    """Ring attention with the Pallas flash inner kernel (interpret mode
    on CPU) == dense oracle, forward."""
    from torchsnapshot_tpu.ops import ring_flash_attention_sharded

    devices = np.array(jax.devices()[: np.prod(list(mesh_shape.values()))])
    mesh = Mesh(devices.reshape(tuple(mesh_shape.values())), tuple(mesh_shape))
    q, k, v = make_qkv(seed=11)
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_flash_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow
def test_ring_flash_gradients_match_dense(causal: bool) -> None:
    """The custom VJP (per-hop flash backward with global lse, rotating
    dK/dV accumulators) == autodiff through the dense oracle."""
    from torchsnapshot_tpu.ops import ring_flash_attention_sharded

    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices.reshape(4), ("seq",))
    q, k, v = make_qkv(seed=13)
    g = jax.random.normal(jax.random.PRNGKey(99), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) * g)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_flash_attention_sharded(q, k, v, mesh, causal=causal) * g
        )

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    ring_grads = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(ring_grads, ref_grads, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, err_msg=f"d{name}"
        )


def test_ring_flash_composes_with_tp_axis() -> None:
    """Heads sharded over 'model' while sequence rings over 'seq'."""
    from torchsnapshot_tpu.ops import ring_flash_attention_sharded

    devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devices, ("data", "seq", "model"))
    q, k, v = make_qkv(seed=17)
    ref = dense_attention(q, k, v, causal=True)
    out = ring_flash_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("mesh_shape", [{"seq": 2}, {"seq": 4}, {"data": 2, "seq": 4}])
def test_zigzag_flash_matches_dense(mesh_shape) -> None:
    """Load-balanced zigzag ring with flash inner kernels == dense oracle."""
    from torchsnapshot_tpu.ops import zigzag_ring_flash_attention_sharded

    devices = np.array(jax.devices()[: np.prod(list(mesh_shape.values()))])
    mesh = Mesh(devices.reshape(tuple(mesh_shape.values())), tuple(mesh_shape))
    q, k, v = make_qkv(seed=21)
    ref = dense_attention(q, k, v, causal=True)
    out = zigzag_ring_flash_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
def test_zigzag_flash_gradients_match_dense() -> None:
    from torchsnapshot_tpu.ops import zigzag_ring_flash_attention_sharded

    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices.reshape(4), ("seq",))
    q, k, v = make_qkv(seed=23)
    g = jax.random.normal(jax.random.PRNGKey(5), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) * g)

    def loss_zz(q, k, v):
        return jnp.sum(zigzag_ring_flash_attention_sharded(q, k, v, mesh) * g)

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    zz_grads = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(zz_grads, ref_grads, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, err_msg=f"d{name}"
        )


@pytest.mark.slow
def test_zigzag_flash_in_layout() -> None:
    """in_layout=True (training loops keep activations zigzag end-to-end)
    equals the permute-in/permute-out path."""
    from torchsnapshot_tpu.ops import zigzag_ring_flash_attention_sharded
    from torchsnapshot_tpu.ops.ring_attention import zigzag_layout_indices

    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices.reshape(4), ("seq",))
    q, k, v = make_qkv(seed=29)
    ref = zigzag_ring_flash_attention_sharded(q, k, v, mesh)

    idx = zigzag_layout_indices(S, 4)
    inv = jnp.argsort(idx)
    qz, kz, vz = (jnp.take(x, idx, axis=1) for x in (q, k, v))
    out = zigzag_ring_flash_attention_sharded(qz, kz, vz, mesh, in_layout=True)
    np.testing.assert_allclose(
        np.asarray(jnp.take(out, inv, axis=1)), np.asarray(ref), atol=1e-6
    )
