"""Incremental (deduplicated) snapshots — beyond-reference capability.

take(incremental_base=...) skips storage writes for payloads whose content
digest matches the base snapshot's; restore reads those payloads from the
base. See torchsnapshot_tpu/dedup.py.
"""

import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.manifest import ChunkedArrayEntry, ObjectEntry


def _state(frozen_val=1.0, trainable_val=2.0, obj=frozenset({"a", 1})):
    return StateDict(
        frozen=np.full((64, 8), frozen_val, np.float32),
        trainable=np.full((16, 4), trainable_val, np.float32),
        meta=obj,
        step=7,
    )


def _payload_files(root):
    out = set()
    for r, _, files in os.walk(root):
        for f in files:
            if f != ".snapshot_metadata":
                out.add(os.path.relpath(os.path.join(r, f), root))
    return out


def test_base_records_digests(tmp_path):
    base = str(tmp_path / "base")
    Snapshot.take(base, {"app": _state()}, record_digests=True)
    meta = Snapshot(base).metadata
    entry = meta.manifest["0/app/frozen"]
    assert isinstance(entry, ChunkedArrayEntry)
    for chunk in entry.chunks:
        assert chunk.array.digest is not None
        assert chunk.array.digest.startswith("sha256:")
        assert chunk.array.origin is None
    obj_entry = meta.manifest["0/app/meta"]
    assert isinstance(obj_entry, ObjectEntry) and obj_entry.digest is not None


def test_incremental_skips_unchanged_and_restores(tmp_path):
    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": _state()}, record_digests=True)
    # trainable changed; frozen + meta unchanged
    Snapshot.take(
        inc,
        {"app": _state(trainable_val=9.0)},
        incremental_base=base,
    )

    files = _payload_files(inc)
    assert not any("frozen" in f for f in files), files  # deduped
    assert not any("meta" in f for f in files), files
    assert any("trainable" in f for f in files), files  # rewritten

    meta = Snapshot(inc).metadata
    frozen = meta.manifest["0/app/frozen"]
    for chunk in frozen.chunks:
        assert chunk.array.origin == base
    trainable = meta.manifest["0/app/trainable"]
    for chunk in trainable.chunks:
        assert chunk.array.origin is None

    dst = _state(frozen_val=0.0, trainable_val=0.0, obj=None)
    Snapshot(inc).restore({"app": dst})
    np.testing.assert_array_equal(dst["frozen"], np.full((64, 8), 1.0, np.float32))
    np.testing.assert_array_equal(dst["trainable"], np.full((16, 4), 9.0, np.float32))
    assert dst["meta"] == frozenset({"a", 1})
    assert dst["step"] == 7


def test_chained_incrementals_resolve_origin_transitively(tmp_path):
    a, b, c = (str(tmp_path / n) for n in "abc")
    Snapshot.take(a, {"app": _state()}, record_digests=True)
    Snapshot.take(b, {"app": _state(trainable_val=5.0)}, incremental_base=a)
    Snapshot.take(c, {"app": _state(trainable_val=6.0)}, incremental_base=b)

    meta = Snapshot(c).metadata
    # frozen was written once, in A; C points straight at A (not at B)
    for chunk in meta.manifest["0/app/frozen"].chunks:
        assert chunk.array.origin == a
    # trainable changed at every link: written locally in C
    for chunk in meta.manifest["0/app/trainable"].chunks:
        assert chunk.array.origin is None

    dst = _state(0.0, 0.0, None)
    Snapshot(c).restore({"app": dst})
    np.testing.assert_array_equal(dst["frozen"], np.full((64, 8), 1.0, np.float32))
    np.testing.assert_array_equal(dst["trainable"], np.full((16, 4), 6.0, np.float32))


def test_async_take_incremental(tmp_path):
    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": _state()}, record_digests=True)
    pending = Snapshot.async_take(
        inc, {"app": _state(trainable_val=3.5)}, incremental_base=base
    )
    pending.wait()
    assert not any("frozen" in f for f in _payload_files(inc))
    dst = _state(0.0, 0.0, None)
    Snapshot(inc).restore({"app": dst})
    np.testing.assert_array_equal(dst["frozen"], np.full((64, 8), 1.0, np.float32))
    np.testing.assert_array_equal(dst["trainable"], np.full((16, 4), 3.5, np.float32))


def test_read_object_follows_origin(tmp_path):
    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": _state()}, record_digests=True)
    Snapshot.take(inc, {"app": _state(trainable_val=4.0)}, incremental_base=base)
    v = Snapshot(inc).read_object("0/app/frozen")
    np.testing.assert_array_equal(np.asarray(v), np.full((64, 8), 1.0, np.float32))


def test_missing_base_raises_actionable_error(tmp_path):
    import shutil

    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": _state()}, record_digests=True)
    Snapshot.take(inc, {"app": _state(trainable_val=8.0)}, incremental_base=base)
    shutil.rmtree(base)
    dst = _state(0.0, 0.0, None)
    with pytest.raises((RuntimeError, FileNotFoundError)):
        Snapshot(inc).restore({"app": dst})


def test_base_without_digests_rewrites_everything(tmp_path, caplog):
    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": _state()})  # no record_digests
    Snapshot.take(inc, {"app": _state()}, incremental_base=base)
    # nothing to dedup against: every payload written locally
    assert any("frozen" in f for f in _payload_files(inc))
    dst = _state(0.0, 0.0, None)
    Snapshot(inc).restore({"app": dst})
    np.testing.assert_array_equal(dst["frozen"], np.full((64, 8), 1.0, np.float32))


def test_cli_info_and_verify_on_incremental(tmp_path, capsys):
    from torchsnapshot_tpu.cli import main

    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": _state()}, record_digests=True)
    Snapshot.take(inc, {"app": _state(trainable_val=2.5)}, incremental_base=base)

    assert main(["info", inc]) == 0
    out = capsys.readouterr().out
    assert "external:" in out and base in out

    assert main(["verify", inc]) == 0
    out = capsys.readouterr().out
    assert ", 0 failed" in out

    # corrupt the payload in the BASE; verifying the incremental must fail
    target = None
    for r, _, files in os.walk(base):
        for f in files:
            if "frozen" in f:
                target = os.path.join(r, f)
    blob = bytearray(open(target, "rb").read())
    blob[0] ^= 0xFF
    open(target, "wb").write(bytes(blob))
    assert main(["verify", inc]) == 1
    assert "FAIL" in capsys.readouterr().out


def test_sharded_array_dedup(tmp_path):
    """GSPMD-sharded arrays dedup per shard: sharded/... locations are
    rank- and writer-independent, so an unchanged sharded param is skipped
    even though a hash-elected writer stages it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    sharding = NamedSharding(mesh, P("dp", "tp"))

    def make(frozen_val, trainable_val):
        return StateDict(
            emb=jax.device_put(
                jnp.full((8, 4), frozen_val, jnp.float32), sharding
            ),
            head=jax.device_put(
                jnp.full((8, 4), trainable_val, jnp.float32), sharding
            ),
        )

    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    Snapshot.take(base, {"app": make(1.0, 2.0)}, record_digests=True)
    Snapshot.take(inc, {"app": make(1.0, 9.0)}, incremental_base=base)

    files = _payload_files(inc)
    assert not any("emb" in f for f in files), files
    assert any("head" in f for f in files), files

    dst = make(0.0, 0.0)
    Snapshot(inc).restore({"app": dst})
    np.testing.assert_array_equal(
        np.asarray(dst["emb"]), np.full((8, 4), 1.0, np.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(dst["head"]), np.full((8, 4), 9.0, np.float32)
    )


def _multiproc_incremental_worker(rank, world_size, base_path, inc_path):
    from torchsnapshot_tpu import Snapshot, StateDict

    def make(trainable_val):
        return {
            "model": StateDict(
                frozen=np.arange(2048, dtype=np.float32).reshape(64, 32),
                head=np.full((16,), trainable_val, np.float32),
            ),
            "local": StateDict(rank_data=np.full((4,), rank, np.int32)),
        }

    Snapshot.take(
        base_path, make(1.0), replicated=["model/*"], record_digests=True
    )
    Snapshot.take(
        inc_path, make(2.0), replicated=["model/*"], incremental_base=base_path
    )

    meta = Snapshot(inc_path).metadata
    # EVERY rank's copy of the replicated deduped entry must carry origin —
    # each rank restores its own copy (regression: origin was only set on
    # the writing rank before _propagate_checksums learned about it).
    for r in range(world_size):
        for chunk in meta.manifest[f"{r}/model/frozen"].chunks:
            assert chunk.array.origin == base_path, (r, chunk.array)

    dst = make(0.0)
    dst["model"]["frozen"][:] = 0
    Snapshot(inc_path).restore(dst)
    np.testing.assert_array_equal(
        dst["model"]["frozen"], np.arange(2048, dtype=np.float32).reshape(64, 32)
    )
    np.testing.assert_array_equal(dst["model"]["head"], np.full((16,), 2.0, np.float32))
    np.testing.assert_array_equal(dst["local"]["rank_data"], np.full((4,), rank, np.int32))
    return "ok"


@pytest.mark.multiprocess
def test_multiprocess_replicated_incremental(tmp_path):
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _multiproc_incremental_worker,
        2,
        str(tmp_path / "base"),
        str(tmp_path / "inc"),
    )
    assert all(v == "ok" for v in results.values())
    # the deduplicated replicated payload must not exist in the incremental
    inc_files = _payload_files(tmp_path / "inc")
    assert not any("frozen" in f for f in inc_files), inc_files
    assert any("head" in f for f in inc_files)


def test_consolidate_detaches_from_bases(tmp_path, capsys):
    import shutil

    from torchsnapshot_tpu.cli import main
    from torchsnapshot_tpu.manifest import ChunkedArrayEntry as _CAE

    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    flat = str(tmp_path / "flat")
    Snapshot.take(a, {"app": _state()}, record_digests=True)
    Snapshot.take(b, {"app": _state(trainable_val=5.0)}, incremental_base=a)

    assert main(["consolidate", b, flat]) == 0
    assert "payloads copied" in capsys.readouterr().out

    # self-contained: verify passes, info shows no external deps
    assert main(["verify", flat]) == 0
    capsys.readouterr()
    assert main(["info", flat]) == 0
    assert "external:" not in capsys.readouterr().out

    # bases gone -> consolidated snapshot still restores; digests survive
    shutil.rmtree(a)
    shutil.rmtree(b)
    dst = _state(0.0, 0.0, None)
    Snapshot(flat).restore({"app": dst})
    np.testing.assert_array_equal(dst["frozen"], np.full((64, 8), 1.0, np.float32))
    np.testing.assert_array_equal(dst["trainable"], np.full((16, 4), 5.0, np.float32))

    meta = Snapshot(flat).metadata
    entry = meta.manifest["0/app/frozen"]
    assert isinstance(entry, _CAE)
    for chunk in entry.chunks:
        assert chunk.array.origin is None
        assert chunk.array.digest is not None  # still usable as a base

    # ...and it can indeed serve as a new incremental base
    nxt = str(tmp_path / "next")
    Snapshot.take(nxt, {"app": _state(trainable_val=6.0)}, incremental_base=flat)
    assert not any("frozen" in f for f in _payload_files(nxt))


def test_non_incremental_format_unchanged(tmp_path):
    """Snapshots taken without digest recording must not carry the new
    fields in their YAML (on-disk format stability)."""
    p = str(tmp_path / "plain")
    Snapshot.take(p, {"app": _state()})
    raw = open(os.path.join(p, ".snapshot_metadata")).read()
    assert "digest" not in raw and "origin" not in raw


def test_capstone_sharded_incremental_mirror_reshard(tmp_path, capsys):
    """Cross-feature integration: GSPMD-sharded train state, incremental
    async save with a mirror tier, primary loss, restore from the
    incremental's MIRROR onto a DIFFERENT mesh layout (elastic reshard),
    with origin payloads read from the base snapshot."""
    import shutil

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.cli import main as cli_main

    devices = np.array(jax.devices()[:4])
    mesh_a = Mesh(devices.reshape(2, 2), ("dp", "tp"))
    shard_a = NamedSharding(mesh_a, P("dp", "tp"))

    def make(head_val, sharding):
        return {
            "model": StateDict(
                emb=jax.device_put(
                    jnp.arange(256, dtype=jnp.float32).reshape(16, 16), sharding
                ),
                head=jax.device_put(
                    jnp.full((16, 16), head_val, jnp.float32), sharding
                ),
            ),
            "progress": StateDict(step=int(head_val)),
        }

    s0 = str(tmp_path / "s0")
    s0_m = str(tmp_path / "s0_mirror")
    s1 = str(tmp_path / "s1")
    s1_m = str(tmp_path / "s1_mirror")

    Snapshot.take(s0, make(1.0, shard_a),
                  storage_options={"mirror_url": s0_m}, record_digests=True)
    pending = Snapshot.async_take(
        s1, make(2.0, shard_a),
        storage_options={"mirror_url": s1_m}, incremental_base=s0,
    )
    pending.wait()

    # emb unchanged: not rewritten in either tier of s1
    for root in (s1, s1_m):
        files = _payload_files(root)
        assert not any("emb" in f for f in files), (root, files)
        assert any("head" in f for f in files), (root, files)

    # machine dies: s1's primary tier is gone; restore from its mirror
    # onto a DIFFERENT layout (1x4 mesh) — elastic resharding
    shutil.rmtree(s1)
    mesh_b = Mesh(devices.reshape(1, 4), ("dp", "tp"))
    shard_b = NamedSharding(mesh_b, P(None, "tp"))
    dst = make(0.0, shard_b)
    Snapshot(s1_m).restore(dst)

    np.testing.assert_array_equal(
        np.asarray(dst["model"]["emb"]),
        np.arange(256, dtype=np.float32).reshape(16, 16),
    )
    np.testing.assert_array_equal(
        np.asarray(dst["model"]["head"]), np.full((16, 16), 2.0, np.float32)
    )
    assert dst["model"]["emb"].sharding.is_equivalent_to(shard_b, 2)
    assert dst["progress"]["step"] == 2

    # integrity verifies across tiers and origins
    assert cli_main(["verify", s1_m]) == 0
    assert ", 0 failed" in capsys.readouterr().out
