"""MoE FFN (expert parallelism) correctness.

Oracle for routing: a per-token numpy reimplementation of top-2
capacity-bounded dispatch. Model-level: the MoE transformer trains,
checkpoints with expert weights sharded over the mesh, restores, resumes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu.ops.moe import init_moe_params, moe_ffn


def reference_moe_no_drops(params, x):
    """Per-token numpy top-2 MoE assuming ample capacity (no drops): each
    token's output is g1*FFN_e1(x) + g2*FFN_e2(x) with renormalized gates."""
    x = np.asarray(jnp.asarray(x, jnp.float32))
    router = np.asarray(params["router"], np.float32)
    w_in = np.asarray(params["w_in"], np.float32)
    w_out = np.asarray(params["w_out"], np.float32)

    logits = x @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)

    out = np.zeros_like(x)
    gelu = lambda z: np.asarray(jax.nn.gelu(jnp.asarray(z)))
    for t in range(x.shape[0]):
        e1 = int(np.argmax(probs[t]))
        p = probs[t].copy()
        p[e1] = -1
        e2 = int(np.argmax(p))
        g1, g2 = probs[t, e1], probs[t, e2]
        s = g1 + g2 + 1e-9
        out[t] = (g1 / s) * (gelu(x[t] @ w_in[e1]) @ w_out[e1]) + (g2 / s) * (
            gelu(x[t] @ w_in[e2]) @ w_out[e2]
        )
    return out


@pytest.mark.slow
def test_moe_shapes_and_finiteness() -> None:
    params = init_moe_params(jax.random.PRNGKey(0), 16, 32, 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux = moe_ffn(params, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    assert float(aux) > 0


@pytest.mark.slow
def test_moe_matches_reference_routing() -> None:
    params = init_moe_params(jax.random.PRNGKey(2), 8, 16, 2)
    x = jax.random.normal(jax.random.PRNGKey(3), (12, 8))
    y, _ = moe_ffn(params, x, capacity_factor=8.0)  # ample capacity, no drops
    ref = reference_moe_no_drops(params, x)
    np.testing.assert_allclose(np.asarray(y), ref, atol=2e-3)


def test_moe_capacity_drops_bounded() -> None:
    """With tiny capacity most tokens drop; outputs must stay finite and
    dropped tokens produce exactly zero."""
    params = init_moe_params(jax.random.PRNGKey(4), 8, 16, 2)
    x = jax.random.normal(jax.random.PRNGKey(5), (64, 8))
    y, _ = moe_ffn(params, x, capacity_factor=0.05)
    y = np.asarray(y)
    assert np.isfinite(y).all()
    zero_rows = (np.abs(y).sum(-1) == 0).sum()
    assert zero_rows > 0  # some tokens overflowed and were dropped


@pytest.mark.parametrize("dispatch", ["einsum", "sort"])
@pytest.mark.slow
def test_moe_gradients_flow(dispatch: str) -> None:
    params = init_moe_params(jax.random.PRNGKey(6), 8, 16, 2)
    x = jax.random.normal(jax.random.PRNGKey(7), (16, 8))

    def loss(params):
        y, aux = moe_ffn(params, x, dispatch=dispatch)
        return jnp.sum(y**2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
        assert np.abs(arr).sum() > 0  # every param receives gradient


@pytest.mark.parametrize("capacity_factor", [8.0, 1.25, 0.25])
def test_moe_sort_dispatch_matches_einsum(capacity_factor: float) -> None:
    """The two dispatch strategies must route identically — including which
    tokens drop under tight capacity (same slot-major priority order)."""
    params = init_moe_params(jax.random.PRNGKey(8), 16, 32, 4)
    x = jax.random.normal(jax.random.PRNGKey(9), (96, 16))
    y_e, aux_e = moe_ffn(params, x, capacity_factor=capacity_factor, dispatch="einsum")
    y_s, aux_s = moe_ffn(params, x, capacity_factor=capacity_factor, dispatch="sort")
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e), atol=1e-5)
    np.testing.assert_allclose(float(aux_s), float(aux_e), atol=1e-6)


@pytest.mark.slow
def test_moe_sort_dispatch_gradients_match_einsum() -> None:
    params = init_moe_params(jax.random.PRNGKey(10), 8, 16, 4)
    x = jax.random.normal(jax.random.PRNGKey(11), (32, 8))

    def loss(params, dispatch):
        y, aux = moe_ffn(params, x, dispatch=dispatch)
        return jnp.sum(y**2) + 0.01 * aux

    g_e = jax.grad(lambda p: loss(p, "einsum"))(params)
    g_s = jax.grad(lambda p: loss(p, "sort"))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_e), jax.tree_util.tree_leaves(g_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_moe_sharded_all_to_all_matches_unsharded() -> None:
    """Explicit-EP (shard_map + lax.all_to_all) output matches the GSPMD
    single-call path when capacity is ample (per-device vs global capacity
    accounting only differs when tokens drop)."""
    from torchsnapshot_tpu.ops import moe_ffn_sharded

    n_dev = 4
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(n_dev), ("model",))
    params = init_moe_params(jax.random.PRNGKey(12), 16, 32, 8)
    x = jax.random.normal(jax.random.PRNGKey(13), (64, 16))
    x_sharded = jax.device_put(x, NamedSharding(mesh, P("model", None)))
    params_sharded = jax.device_put(
        params,
        {
            "router": NamedSharding(mesh, P(None, None)),
            "w_in": NamedSharding(mesh, P("model", None, None)),
            "w_out": NamedSharding(mesh, P("model", None, None)),
        },
    )
    y, aux = jax.jit(
        lambda p, x: moe_ffn_sharded(p, x, mesh, capacity_factor=8.0)
    )(params_sharded, x_sharded)
    y_ref, aux_ref = moe_ffn(params, x, capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), atol=1e-6)


def test_moe_sharded_gradients_flow() -> None:
    from torchsnapshot_tpu.ops import moe_ffn_sharded

    n_dev = 2
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(n_dev), ("model",))
    params = init_moe_params(jax.random.PRNGKey(14), 8, 16, 4)
    x = jax.random.normal(jax.random.PRNGKey(15), (16, 8))

    def loss(params):
        y, aux = moe_ffn_sharded(params, x, mesh)
        return jnp.sum(y**2) + 0.01 * aux

    grads = jax.jit(jax.grad(loss))(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
        assert np.abs(arr).sum() > 0


@pytest.mark.slow
def test_moe_transformer_trains_and_checkpoints(tmp_path) -> None:
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import transformer as T

    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("data", "seq", "model"))
    cfg = T.TransformerConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, dtype=jnp.float32, attn_impl="ring", n_experts=2,
    )
    tx = T.make_optimizer()
    state = T.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    # expert-stacked weights are sharded over 'model'
    w_in_sharding = state["params"]["layers"]["moe_w_in"].sharding
    assert "model" in w_in_sharding.spec

    step = jax.jit(T.make_train_step(cfg, tx, mesh=mesh))
    batch = {
        "tokens": jnp.zeros((4, 16), jnp.int32),
        "targets": jnp.zeros((4, 16), jnp.int32),
    }
    batch = jax.device_put(batch, NamedSharding(mesh, P("data", "seq")))
    state, loss = step(state, batch)
    assert np.isfinite(float(loss))

    Snapshot.take(str(tmp_path / "s"), {"train": StateDict(state=state)})
    dst = {"train": StateDict(state=T.init_state(jax.random.PRNGKey(9), cfg, tx, mesh=mesh))}
    Snapshot(str(tmp_path / "s")).restore(dst)
    for a, b in zip(
        jax.tree_util.tree_leaves(state),
        jax.tree_util.tree_leaves(dst["train"]["state"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state2, loss2 = step(dst["train"]["state"], batch)
    assert int(state2["step"]) == 2 and np.isfinite(float(loss2))


def test_dense_transformer_unchanged() -> None:
    """n_experts=0 keeps the original dense-FFN param tree."""
    from torchsnapshot_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, dtype=jnp.float32,
    )
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    assert "ff_in" in params["layers"] and "moe_router" not in params["layers"]
    logits = T.forward(params, jnp.zeros((2, 16), jnp.int32), cfg)
    assert logits.shape == (2, 16, 64)
