"""Telemetry subsystem: span correctness, disabled-mode cost, exporters,
cross-rank aggregation, and the end-to-end take -> stats flow.

Covers the correctness contracts docs/source/telemetry.rst promises:
span nesting/parenting invariants, disabled mode being a true no-op,
Chrome-trace output loading as valid JSON with consistent ts/dur, and
the fleet merge handling a skewed slow rank.
"""

import asyncio
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, telemetry
from torchsnapshot_tpu.test_utils import run_with_subprocesses


@pytest.fixture(autouse=True)
def _clean_bus():
    """Each test starts with an empty, disabled bus and leaves it so
    (refresh re-resolves the cached event cap after monkeypatched env)."""
    telemetry.refresh_from_env()
    telemetry.set_enabled(False)
    telemetry.reset()
    yield
    telemetry.refresh_from_env()
    telemetry.set_enabled(False)
    telemetry.reset()


# ------------------------------------------------------------------- spans


def test_span_nesting_and_parenting():
    telemetry.set_enabled(True)
    with telemetry.span("outer"):
        with telemetry.span("mid"):
            with telemetry.span("inner"):
                pass
        with telemetry.span("sibling"):
            pass
    events = {e["name"]: e for e in telemetry.events() if e["ph"] == "span"}
    assert set(events) == {"outer", "mid", "inner", "sibling"}
    assert events["outer"]["parent"] is None
    assert events["mid"]["parent"] == events["outer"]["id"]
    assert events["inner"]["parent"] == events["mid"]["id"]
    assert events["sibling"]["parent"] == events["outer"]["id"]
    # Temporal containment: child windows sit inside the parent's.
    for child, parent in (("mid", "outer"), ("inner", "mid"), ("sibling", "outer")):
        c, p = events[child], events[parent]
        assert c["ts"] >= p["ts"]
        assert c["ts"] + c["dur"] <= p["ts"] + p["dur"] + 1e-9


def test_span_parenting_isolated_across_interleaved_tasks():
    """Two coroutines interleaving spans on ONE event-loop thread must not
    corrupt each other's parent stacks (contextvars isolation)."""
    telemetry.set_enabled(True)

    async def worker(name):
        with telemetry.span(f"root_{name}"):
            await asyncio.sleep(0.01)
            with telemetry.span(f"child_{name}"):
                await asyncio.sleep(0.01)

    async def main():
        await asyncio.gather(worker("a"), worker("b"))

    asyncio.run(main())
    events = {e["name"]: e for e in telemetry.events() if e["ph"] == "span"}
    assert events["child_a"]["parent"] == events["root_a"]["id"]
    assert events["child_b"]["parent"] == events["root_b"]["id"]
    assert events["root_a"]["parent"] is None
    assert events["root_b"]["parent"] is None


def test_span_set_args():
    telemetry.set_enabled(True)
    with telemetry.span("s", bytes=1) as sp:
        sp.set(bytes=42, extra="x")
    (ev,) = [e for e in telemetry.events() if e["ph"] == "span"]
    assert ev["args"] == {"bytes": 42, "extra": "x"}


# ----------------------------------------------------------- disabled mode


def test_disabled_mode_is_noop():
    assert not telemetry.enabled()
    # Hot path returns THE shared singleton: no per-call allocation
    # beyond the flag check.
    s1 = telemetry.span("a", bytes=123)
    s2 = telemetry.span("b")
    assert s1 is s2
    with s1:
        pass
    telemetry.event("x", k=1)
    telemetry.counter_add("c", 5)
    telemetry.gauge_set("g", 7)
    telemetry.histogram_observe("write.entry_s", 0.1)
    assert telemetry.events() == []
    assert telemetry.counters() == {}
    assert telemetry.gauges() == {}
    assert telemetry.histograms() == {}
    # An op bracketing a fully-disabled window summarizes to None.
    rec = telemetry.begin_op("take", rank=0)
    assert rec.finish() is None


def test_disabled_rates_still_feed_governor():
    """Adaptive tuning must keep working with telemetry off: rate
    observations bypass the enabled gate on their way to the governor."""
    from torchsnapshot_tpu.scheduler import io_governor

    telemetry.record_rate("write", "LintTestPlugin", 10_000_000, 0.01)
    assert io_governor().write_bps("LintTestPlugin") == pytest.approx(1e9)
    assert telemetry.events() == []  # but nothing was recorded


# -------------------------------------------------------------- histograms


def test_histogram_log2_bucketing():
    """Observations land in the smallest power-of-two upper bound >=
    the value; sub-1µs values collapse into bucket 0 and huge values
    into the +Inf overflow slot."""
    from torchsnapshot_tpu.telemetry.core import HISTOGRAM_BOUNDS

    telemetry.set_enabled(True)
    telemetry.histogram_observe("write.entry_s", 0.0)        # floor
    telemetry.histogram_observe("write.entry_s", 1e-9)       # floor
    telemetry.histogram_observe("write.entry_s", 0.05)       # le=0.0625
    telemetry.histogram_observe("write.entry_s", 0.0625)     # le=0.0625 (==)
    telemetry.histogram_observe("write.entry_s", 0.07)       # le=0.125
    telemetry.histogram_observe("write.entry_s", 1e9)        # +Inf overflow
    hist = telemetry.histograms()["write.entry_s"][""]
    counts = hist["counts"]
    assert hist["count"] == 6
    assert counts[0] == 2
    assert counts[HISTOGRAM_BOUNDS.index(0.0625)] == 2
    assert counts[HISTOGRAM_BOUNDS.index(0.125)] == 1
    assert counts[len(HISTOGRAM_BOUNDS)] == 1  # the overflow slot
    assert hist["sum"] == pytest.approx(0.0625 + 0.05 + 0.07 + 1e9)


def test_histogram_keys_are_separate_series():
    telemetry.set_enabled(True)
    telemetry.histogram_observe("storage.op_s", 0.01, key="S3.put")
    telemetry.histogram_observe("storage.op_s", 0.02, key="S3.get_range")
    by_key = telemetry.histograms()["storage.op_s"]
    assert set(by_key) == {"S3.put", "S3.get_range"}
    assert by_key["S3.put"]["count"] == 1


def test_histogram_quantile_approximation():
    telemetry.set_enabled(True)
    for _ in range(9):
        telemetry.histogram_observe("write.entry_s", 0.01)
    telemetry.histogram_observe("write.entry_s", 1.5)
    hist = telemetry.histograms()["write.entry_s"][""]
    # p50 lands in 0.01's bucket (le=0.015625); p99 in the tail's.
    assert telemetry.histogram_quantile(hist, 0.5) == pytest.approx(0.015625)
    assert telemetry.histogram_quantile(hist, 0.99) == pytest.approx(2.0)
    assert telemetry.histogram_quantile({"count": 0, "counts": []}, 0.5) is None


def test_op_recorder_histogram_deltas():
    """A summary reports only the histograms observed DURING the op —
    the previous op's tail must not leak in — while the process-level
    view keeps accumulating."""
    telemetry.set_enabled(True)
    telemetry.histogram_observe("write.entry_s", 0.01, key="FS")
    rec = telemetry.begin_op("take", rank=0)
    telemetry.histogram_observe("write.entry_s", 0.02, key="FS")
    telemetry.histogram_observe("read.entry_s", 0.03, key="FS")
    summary = rec.finish()
    hist = summary["histograms"]
    assert hist["write.entry_s"]["FS"]["count"] == 1  # not 2
    assert hist["read.entry_s"]["FS"]["count"] == 1
    assert telemetry.histograms()["write.entry_s"]["FS"]["count"] == 2
    # An op with no observations elides the key entirely.
    rec = telemetry.begin_op("take", rank=0)
    assert "histograms" not in rec.finish()


def test_histogram_thread_safety_no_lost_updates():
    import threading

    telemetry.set_enabled(True)
    n, threads = 2000, 8

    def pound():
        for i in range(n):
            telemetry.histogram_observe(
                "collective.wait_s", 1e-6 * (i % 7 + 1), key="barrier"
            )

    ts = [threading.Thread(target=pound) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    hist = telemetry.histograms()["collective.wait_s"]["barrier"]
    assert hist["count"] == n * threads
    assert sum(hist["counts"]) == n * threads


# ------------------------------------------------------------ counters/ops


def test_counters_and_op_recorder_deltas():
    telemetry.set_enabled(True)
    telemetry.counter_add("bytes_written", 100)
    rec = telemetry.begin_op("take", rank=3)
    telemetry.counter_add("bytes_written", 50)
    telemetry.counter_add("retry_attempts", 2)
    with telemetry.span("stage"):
        pass
    summary = rec.finish(extra={"phases": {"plan": 0.1}})
    # Deltas, not absolutes: the 100 pre-op bytes are excluded.
    assert summary["counters"] == {"bytes_written": 50, "retry_attempts": 2}
    assert summary["rank"] == 3
    assert summary["op"] == "take"
    assert summary["spans"]["stage"]["count"] == 1
    assert summary["phases"] == {"plan": 0.1}
    assert telemetry.last_summary() is summary


def test_event_buffer_trimmed_between_ops(monkeypatch):
    """A long-lived process saving every N steps must never fill the
    event cap and go dark: each begin_op trims events no live recorder
    can still export."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_TELEMETRY_MAX_EVENTS", "10")
    telemetry.refresh_from_env()  # the cap is cached, not read per append
    telemetry.set_enabled(True)
    for op_i in range(5):
        rec = telemetry.begin_op("take", rank=0)
        for _ in range(8):
            with telemetry.span("stage"):
                pass
        summary = rec.finish()
        # Every op keeps full span coverage — op 5 as much as op 1.
        assert summary["spans"]["stage"]["count"] == 8, f"op {op_i} went dark"
        assert summary["dropped_events"] == 0


def test_per_op_trace_counters_rebased():
    """Take #2's exported counter track must read 0 -> bytes-this-op,
    not carry take #1's cumulative total."""
    telemetry.set_enabled(True)
    rec1 = telemetry.begin_op("take")
    telemetry.counter_add("bytes_written", 1000)
    rec1.finish()
    rec2 = telemetry.begin_op("take")
    telemetry.counter_add("bytes_written", 500)
    rec2.finish()
    vals = [
        e["value"]
        for e in rec2.events()
        if e["ph"] == "counter" and e["name"] == "bytes_written"
    ]
    assert vals == [500]


def test_per_op_gauges_and_dropped_are_op_scoped(monkeypatch):
    telemetry.set_enabled(True)
    rec1 = telemetry.begin_op("take")
    telemetry.gauge_set("write_inflight_io", 9)
    s1 = rec1.finish()
    assert s1["gauges"] == {"write_inflight_io": 9}
    # A later restore sets no gauges: it must not inherit the take's.
    rec2 = telemetry.begin_op("restore")
    s2 = rec2.finish()
    assert s2["gauges"] == {}
    assert s2["dropped_events"] == 0


def test_finished_op_exports_survive_next_ops_trim():
    """Async commits export AFTER finish(): a new op beginning in that
    window trims the live buffer, so the export must be served from the
    finished recorder's own capture."""
    telemetry.set_enabled(True)
    rec1 = telemetry.begin_op("take")
    with telemetry.span("stage"):
        pass
    summary = rec1.finish()
    telemetry.begin_op("take")  # trims everything rec1 referenced
    evs = rec1.events()
    assert [e["name"] for e in evs if e["ph"] == "span"] == ["stage"]
    assert summary["spans"]["stage"]["count"] == 1


def test_annotate_next_op_lands_in_summary():
    telemetry.set_enabled(True)
    telemetry.annotate_next_op(step=1000, mode="async")
    rec = telemetry.begin_op("take")
    summary = rec.finish()
    assert summary["annotations"] == {"step": 1000, "mode": "async"}
    # Consumed: the following op carries none.
    assert telemetry.begin_op("take").finish().get("annotations") is None


def test_manager_save_annotates_take_summary(tmp_path):
    from torchsnapshot_tpu import CheckpointManager

    telemetry.set_enabled(True)
    mgr = CheckpointManager(str(tmp_path / "ckpts"), save_interval_steps=1)
    mgr.save(0, {"app": StateDict(w=np.ones(256, np.float32))})
    summary = telemetry.last_summary()
    assert summary["annotations"]["step"] == 0
    assert summary["annotations"]["mode"] == "sync"


# ------------------------------------------------------------ chrome trace


def test_chrome_trace_valid_and_consistent():
    telemetry.set_enabled(True)
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
    telemetry.counter_add("bytes_written", 10)
    telemetry.event("phase:commit", cat="phase")
    blob = telemetry.chrome_trace_json(pid=7)
    doc = json.loads(blob)  # valid JSON
    events = doc["traceEvents"]
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(xs) == {"outer", "inner"}
    for e in events:
        if "ts" in e:
            assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
            assert e["pid"] == 7
    # Monotonic consistency: the child's [ts, ts+dur] window sits inside
    # the parent's in exported (µs) time too.
    assert xs["inner"]["ts"] >= xs["outer"]["ts"]
    assert (
        xs["inner"]["ts"] + xs["inner"]["dur"]
        <= xs["outer"]["ts"] + xs["outer"]["dur"]
    )
    assert any(e["ph"] == "C" and e["name"] == "bytes_written" for e in events)
    assert any(e["ph"] == "i" and e["name"] == "phase:commit" for e in events)


def test_chrome_trace_file_roundtrip(tmp_path):
    telemetry.set_enabled(True)
    with telemetry.span("s"):
        pass
    path = str(tmp_path / "trace.json")
    telemetry.write_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    assert any(e.get("name") == "s" for e in doc["traceEvents"])


# ----------------------------------------------------------- fleet merge


def _mk_summary(rank, wall_s, written=0, read=0, deduped=0, retries=0):
    counters = {}
    if written:
        counters["bytes_written"] = written
    if read:
        counters["bytes_read"] = read
    if deduped:
        counters["bytes_deduped"] = deduped
    if retries:
        counters["retry_attempts"] = retries
    return {
        "op": "take",
        "rank": rank,
        "wall_s": wall_s,
        "spans": {},
        "counters": counters,
    }


def test_merge_with_skewed_slow_rank():
    summaries = [
        _mk_summary(0, 1.0, written=100),
        _mk_summary(1, 9.0, written=300, retries=4),  # the straggler
        _mk_summary(2, 1.5, written=200, deduped=50),
    ]
    fleet = telemetry.merge_summaries(summaries)
    assert fleet["slowest_rank"] == 1
    assert fleet["fastest_rank"] == 0
    assert fleet["wall_s_max"] == 9.0
    assert fleet["skew_s"] == pytest.approx(8.0)
    agg = fleet["aggregate"]
    # Aggregate write bytes are exactly the per-rank sum.
    assert agg["bytes_written"] == 600
    assert agg["bytes_deduped"] == 50
    assert agg["retry_attempts"] == 4
    # Fleet bandwidth is bytes over the CRITICAL PATH (slowest rank).
    assert agg["write_gbps"] == pytest.approx(600 / 9.0 / 1e9, rel=1e-3)


def test_merge_handles_disabled_ranks_and_all_none():
    fleet = telemetry.merge_summaries([None, _mk_summary(1, 2.0, written=10), None])
    assert fleet["reporting"] == 1
    assert fleet["world_size"] == 3
    assert fleet["slowest_rank"] == 1
    assert telemetry.merge_summaries([None, None]) is None


# ------------------------------------------------- end-to-end single rank


def test_take_persists_summary_and_trace(tmp_path):
    telemetry.set_enabled(True)
    w = np.arange(32768, dtype=np.float32)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(w=w, step=7)})
    doc = json.loads((tmp_path / "snap" / ".snapshot_telemetry").read_text())
    assert doc["op"] == "take"
    assert doc["world_size"] == 1
    summary = doc["ranks"][0]
    assert summary["counters"]["bytes_written"] == w.nbytes
    assert doc["fleet"]["aggregate"]["bytes_written"] == w.nbytes
    assert "phases" in summary and "commit" in summary["phases"]
    trace = json.loads(
        (tmp_path / "snap" / ".telemetry" / "rank_0.trace.json").read_text()
    )
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "stage" in names and "storage_write" in names
    # Programmatic scraping surface.
    assert telemetry.last_summary()["op"] == "take"
    assert telemetry.last_fleet()["aggregate"]["bytes_written"] == w.nbytes


def test_restore_merges_fleet_without_writing(tmp_path):
    path = str(tmp_path / "snap")
    w = np.arange(4096, dtype=np.float32)
    Snapshot.take(path, {"app": StateDict(w=w)})  # telemetry off: no residue
    assert not (tmp_path / "snap" / ".snapshot_telemetry").exists()
    telemetry.set_enabled(True)
    dst = StateDict(w=np.zeros_like(w))
    before = set(os.listdir(path))
    Snapshot(path).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], w)
    assert set(os.listdir(path)) == before  # restores never write
    fleet = telemetry.last_fleet()
    assert fleet["op"] == "restore"
    assert fleet["aggregate"]["bytes_read"] == w.nbytes


def test_disabled_take_leaves_zero_residue(tmp_path):
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(w=np.ones(64, np.float32))})
    assert sorted(os.listdir(path)) == [".snapshot_metadata", "0"]


def test_stats_cli_on_fresh_snapshot(tmp_path):
    """Tier-1 smoke: `python -m torchsnapshot_tpu stats <snapshot>` on a
    snapshot taken moments earlier with telemetry enabled."""
    path = str(tmp_path / "snap")
    env = dict(os.environ, TORCHSNAPSHOT_TPU_TELEMETRY="1", JAX_PLATFORMS="cpu")
    take = (
        "import numpy as np\n"
        "from torchsnapshot_tpu import Snapshot, StateDict\n"
        f"Snapshot.take({path!r}, "
        "{'app': StateDict(w=np.arange(8192, dtype=np.float32))})\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", take], env=env, capture_output=True, text=True,
        timeout=180,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    r = subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu", "stats", path],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "bytes_written" in r.stdout
    assert "fleet wall" in r.stdout
    r = subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu", "stats", path, "--json"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0
    assert json.loads(r.stdout)["op"] == "take"


# ------------------------------------------------------------- retry leg


def test_retry_strategy_emits_events_and_enriches_exception():
    from torchsnapshot_tpu.storage_plugins.retry import CollectiveRetryStrategy

    telemetry.set_enabled(True)
    clock = [0.0]
    sleeps = []

    async def fake_sleep(s):
        sleeps.append(s)

    strategy = CollectiveRetryStrategy(
        stall_timeout_s=10.0, clock=lambda: clock[0], sleep=fake_sleep
    )

    async def scenario():
        err = ConnectionError("reset by peer")
        slept = 0.0
        # Two retries while the fleet is healthy...
        slept += await strategy.backoff_or_raise(
            err, 0, op_started_at=clock[0], op="put", backoff_slept_s=slept
        )
        slept += await strategy.backoff_or_raise(
            err, 1, op_started_at=clock[0], op="put", backoff_slept_s=slept
        )
        # ...then the shared deadline lapses with no progress anywhere.
        clock[0] = 100.0
        with pytest.raises(ConnectionError) as ei:
            await strategy.backoff_or_raise(
                err, 2, op_started_at=clock[0], op="put", backoff_slept_s=slept
            )
        return ei.value, slept

    exc, slept = asyncio.run(scenario())
    # The final exception carries the attempt history (satellite: the
    # fleet-deadline path used to discard it).
    assert exc.retry_attempts == 3
    assert exc.retry_error_kind == "connection"
    assert exc.retry_backoff_slept_s == pytest.approx(slept, abs=0.01)
    assert exc.retry_fleet_attempts == 2
    assert len(sleeps) == 2
    if sys.version_info >= (3, 11):
        assert any("gave up after 3 attempt" in n for n in exc.__notes__)
    events = [e for e in telemetry.events() if e["cat"] == "retry"]
    kinds = [e["name"] for e in events]
    assert kinds.count("storage_retry") == 2
    assert kinds.count("storage_retry_exhausted") == 1
    assert all(e["args"]["kind"] == "connection" for e in events)
    assert telemetry.counters()["retry_attempts"] == 2


def test_classify_error_kinds():
    from torchsnapshot_tpu.storage_plugins.retry import classify_error

    assert classify_error(ConnectionError("x")) == "connection"
    assert classify_error(TimeoutError("x")) == "timeout"
    assert classify_error(ValueError("x")) == "other"

    class TooManyRequests(Exception):
        pass

    class ServiceUnavailable(Exception):
        pass

    class ReadTimeoutError(Exception):
        pass

    assert classify_error(TooManyRequests()) == "throttle"
    assert classify_error(ServiceUnavailable()) == "server"
    assert classify_error(ReadTimeoutError()) == "timeout"

    class ClientError(Exception):
        def __init__(self, code=None, err=None):
            self.response = {
                "ResponseMetadata": {"HTTPStatusCode": code},
                "Error": {"Code": err},
            }

    assert classify_error(ClientError(code=429)) == "throttle"
    assert classify_error(ClientError(code=503)) == "server"
    assert classify_error(ClientError(err="SlowDown")) == "throttle"


# ---------------------------------------------------------- distributed


def _telemetry_take_worker(rank: int, world_size: int, snap_path: str):
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    telemetry.set_enabled(True)
    per_rank = np.full((4096,), rank, dtype=np.float32)  # 16 KiB each
    shared = np.arange(8192, dtype=np.float32)  # 32 KiB, striped
    app_state = {
        "local": StateDict(data=per_rank),
        "model": StateDict(w=shared),
    }
    Snapshot.take(snap_path, app_state, replicated=["model/*"])
    summary = telemetry.last_summary()
    fleet = telemetry.last_fleet()
    return {
        "bytes_written": summary["counters"].get("bytes_written", 0),
        "fleet": fleet,
    }


@pytest.mark.multiprocess
def test_distributed_take_fleet_view_and_artifacts(tmp_path):
    """Acceptance: a multi-process telemetry-enabled take produces a
    per-rank Chrome trace that parses, a persisted summary readable via
    ``stats``, and a fleet view whose aggregate write bytes equal the sum
    of per-rank bytes."""
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(_telemetry_take_worker, 2, snap_path)
    per_rank_bytes = {r: results[r]["bytes_written"] for r in results}
    total = sum(per_rank_bytes.values())
    assert total > 0
    # Every rank computed the identical fleet view from the gather.
    for r in results:
        fleet = results[r]["fleet"]
        assert fleet["world_size"] == 2
        assert fleet["reporting"] == 2
        assert fleet["aggregate"]["bytes_written"] == total
        assert fleet["slowest_rank"] in (0, 1)
        assert fleet["skew_s"] >= 0
    # Persisted artifacts: summary document + one trace per rank.
    doc = json.loads((tmp_path / "snap" / ".snapshot_telemetry").read_text())
    assert doc["world_size"] == 2
    assert doc["fleet"]["aggregate"]["bytes_written"] == total
    assert [s["rank"] for s in doc["ranks"]] == [0, 1]
    for rank in (0, 1):
        trace = json.loads(
            (tmp_path / "snap" / ".telemetry" / f"rank_{rank}.trace.json")
            .read_text()
        )
        assert trace["traceEvents"], f"rank {rank} trace is empty"
        assert all(e["ts"] >= 0 for e in trace["traceEvents"] if "ts" in e)
    # And the stats CLI renders it.
    r = subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu", "stats", snap_path],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "slowest rank" in r.stdout


def _telemetry_skew_worker(rank: int, world_size: int, snap_path: str):
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    telemetry.set_enabled(True)
    if rank == 1:
        # A deliberately slow rank: peers wait for it at the commit
        # barrier, but ITS wall stays shortest-path while rank 0's
        # stretches — the merge must still single out a slowest rank and
        # a positive skew consistently on every rank.
        import time as _t

        _t.sleep(0.4)
    Snapshot.take(
        snap_path, {"local": StateDict(x=np.ones(1024, np.float32) * rank)}
    )
    return telemetry.last_fleet()


@pytest.mark.multiprocess
def test_distributed_skewed_rank_merge(tmp_path):
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(_telemetry_skew_worker, 2, snap_path)
    fleets = [results[r] for r in sorted(results)]
    assert fleets[0] == fleets[1]  # identical gathered view everywhere
    assert fleets[0]["skew_s"] >= 0.0
    assert fleets[0]["wall_s_max"] >= fleets[0]["wall_s_min"]
