"""Write batching composed with the other storage features.

The suite-wide conftest pins batching OFF (layout-dependent tests); every
test here opts back in. Reference matrix pattern:
tests/test_batcher.py:188-192 in the reference exercises batching across
dtypes — here the axis is FEATURES: incremental, mirror, async fault
injection, resharding.
"""

import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin


def _small_state(v=1.0, n_small=24):
    # many small arrays => the batcher packs them into slabs
    return StateDict(
        big=np.arange(100_000, dtype=np.float32) * v,
        **{
            f"s{i}": np.full((32,), v * i, np.float32)
            for i in range(n_small)
        },
    )


def _zero_state(n_small=24):
    return StateDict(
        big=np.zeros(100_000, np.float32),
        **{f"s{i}": np.zeros((32,), np.float32) for i in range(n_small)},
    )


def _assert_equal(dst, src, n_small=24):
    np.testing.assert_array_equal(dst["big"], src["big"])
    for i in range(n_small):
        np.testing.assert_array_equal(dst[f"s{i}"], src[f"s{i}"])


def _slab_files(root):
    return [
        os.path.join(r, f)
        for r, _, fs in os.walk(root)
        for f in fs
        if "batched" in os.path.join(r, f)
    ]


def test_batching_with_incremental_warns_and_stays_correct(
    tmp_path, monkeypatch, caplog
):
    """Batched (slab) payloads opt out of dedup by design — the library
    says so loudly — but the COMBINATION must stay correct: everything
    restores, and digests that were recorded still serve non-batched
    payloads."""
    import logging

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    base, inc = str(tmp_path / "b"), str(tmp_path / "i")
    state = _small_state()
    # replicated entries keep deterministic per-payload locations (never
    # batched), so 'big' is the one payload that CAN dedup here
    with caplog.at_level(logging.WARNING):
        Snapshot.take(base, {"app": state}, record_digests=True,
                      replicated=["app/big"])
    assert any("batched" in r.message.lower() for r in caplog.records)
    assert _slab_files(base), "setup must actually produce slabs"

    caplog.clear()
    with caplog.at_level(logging.WARNING):
        Snapshot.take(inc, {"app": state}, incremental_base=base,
                      replicated=["app/big"])
    assert any("batch" in r.message.lower() for r in caplog.records)

    # the replicated (non-batched) payload deduplicates; slabs rewrite
    from torchsnapshot_tpu.cli import _entry_payloads

    meta = Snapshot(inc).metadata
    origins = [
        origin
        for e in meta.manifest.values()
        for _, _, _, _, origin in _entry_payloads(e)
    ]
    assert any(o is not None for o in origins), "big payload should dedup"

    dst = _zero_state()
    Snapshot(inc).restore({"app": dst})
    _assert_equal(dst, state)


def test_unbatched_base_batched_incremental(tmp_path, monkeypatch):
    """Base saved without batching, incremental with it: slab locations
    can never match the base's per-payload locations, so slabs rewrite;
    restore must be correct either way."""
    base, inc = str(tmp_path / "b"), str(tmp_path / "i")
    state = _small_state()
    Snapshot.take(base, {"app": state}, record_digests=True)

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    Snapshot.take(inc, {"app": state}, incremental_base=base)
    dst = _zero_state()
    Snapshot(inc).restore({"app": dst})
    _assert_equal(dst, state)


def test_batching_with_mirror_both_tiers(tmp_path, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    primary, mirror = str(tmp_path / "fast"), str(tmp_path / "durable")
    state = _small_state(2.0)
    Snapshot.take(primary, {"app": state},
                  storage_options={"mirror_url": mirror})
    assert _slab_files(primary) and _slab_files(mirror)
    for root in (primary, mirror):
        dst = _zero_state()
        Snapshot(root).restore({"app": dst})
        _assert_equal(dst, state)

    # mirror read fallback with a slab: delete a PRIMARY slab, restore
    # through the mirrored options
    for slab in _slab_files(primary):
        os.remove(slab)
    dst = _zero_state()
    Snapshot(primary, storage_options={"mirror_url": mirror}).restore(
        {"app": dst}
    )
    _assert_equal(dst, state)


class _FailSlabPlugin(FSStoragePlugin):
    """Fails exactly the slab writes — the batched path's fault lane."""

    async def write(self, write_io) -> None:
        if "batched" in write_io.path:
            raise RuntimeError("injected slab write failure")
        await super().write(write_io)


def test_batching_async_fault_leaves_no_committed_metadata(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        _FailSlabPlugin,
    )
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"app": _small_state()}
    )
    with pytest.raises(RuntimeError, match="injected slab write failure"):
        pending.wait()
    assert not (tmp_path / "snap" / SNAPSHOT_METADATA_FNAME).exists()


def test_batching_async_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    state = _small_state(3.0)
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"app": state})
    snap = pending.wait()
    dst = _zero_state()
    snap.restore({"app": dst})
    _assert_equal(dst, state)


def test_batching_sharded_reshard_roundtrip(tmp_path, monkeypatch):
    """Sharded sub-entries are batchable; restoring into a different
    layout reads slab ranges for shard overlap regions."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.parallel import make_mesh

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs >=4 devices")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    mesh = make_mesh({"data": 4, "model": 1}, devices=devices[:4])
    arr = jnp.arange(32 * 16, dtype=jnp.float32).reshape(32, 16)
    sharded = jax.device_put(arr, NamedSharding(mesh, P("data", None)))
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": StateDict(x=sharded)})

    mesh2 = make_mesh({"data": 2, "model": 2}, devices=devices[:4])
    dst = jax.device_put(
        jnp.zeros_like(arr), NamedSharding(mesh2, P("data", "model"))
    )
    out = StateDict(x=dst)
    Snapshot(root).restore({"app": out})
    np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(arr))
