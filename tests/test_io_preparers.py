"""Plan-level preparer tests that bypass scheduler and storage entirely:
ReadReqs are fulfilled directly from WriteReqs' staged buffers in memory
(reference pattern: tests/test_tensor_io_preparer.py:33-56). Also the
reference's chunked-read edge cases — strided/offset/non-contiguous
destination views and prime-sized arrays (tests/test_tensor_io_preparer.py:
158-181) — and greedy-partition determinism
(tests/test_partition_replicated_paths.py)."""

import asyncio
from typing import Dict, List

import numpy as np
import pytest

from torchsnapshot_tpu.io_preparers.array import ArrayIOPreparer
from torchsnapshot_tpu.io_types import ReadReq, WriteReq
from torchsnapshot_tpu.snapshot import _partition_write_units


def _fulfill(write_reqs: List[WriteReq], read_reqs: List[ReadReq]) -> None:
    """Serve byte-range reads straight from staged write buffers."""

    async def run() -> None:
        staged: Dict[str, bytes] = {}
        for wr in write_reqs:
            buf = await wr.buffer_stager.stage_buffer(None)
            staged[wr.path] = bytes(buf)
        for rr in read_reqs:
            blob = staged[rr.path]
            if rr.byte_range is not None:
                lo, hi = rr.byte_range
                blob = blob[lo:hi]
            await rr.buffer_consumer.consume_buffer(blob, None)

    asyncio.run(run())


@pytest.mark.parametrize("shape", [(13,), (7, 11), (1,), (0,), (5, 3, 2)])
def test_write_read_plan_roundtrip(shape) -> None:
    src = np.random.default_rng(0).standard_normal(shape).astype(np.float32)
    entry, write_reqs = ArrayIOPreparer.prepare_write("loc", src)
    dst = np.zeros(shape, dtype=np.float32)
    read_reqs = ArrayIOPreparer.prepare_read(entry, dst_view=dst)
    _fulfill(write_reqs, read_reqs)
    np.testing.assert_array_equal(dst, src)


@pytest.mark.parametrize("limit", [1, 7, 64, 10**9])
def test_chunked_read_prime_sized(limit) -> None:
    """Prime-sized array under assorted buffer limits — uneven final chunk."""
    src = np.arange(97, dtype=np.int64)
    entry, write_reqs = ArrayIOPreparer.prepare_write("loc", src)
    dst = np.zeros(97, dtype=np.int64)
    read_reqs = ArrayIOPreparer.prepare_read(
        entry, dst_view=dst, buffer_size_limit_bytes=limit
    )
    if limit < src.nbytes:
        assert len(read_reqs) > 1
    _fulfill(write_reqs, read_reqs)
    np.testing.assert_array_equal(dst, src)


def test_chunked_read_into_strided_view() -> None:
    """reshape(-1) of a strided view is a copy — fills must still land in the
    underlying destination (reference: tests/test_tensor_io_preparer.py:158-181)."""
    src = np.random.default_rng(1).standard_normal((16, 8)).astype(np.float32)
    entry, write_reqs = ArrayIOPreparer.prepare_write("loc", src)

    backing = np.zeros((16, 16), dtype=np.float32)
    dst = backing[:, ::2]  # non-contiguous column-strided view
    assert not dst.flags["C_CONTIGUOUS"]
    read_reqs = ArrayIOPreparer.prepare_read(
        entry, dst_view=dst, buffer_size_limit_bytes=64
    )
    _fulfill(write_reqs, read_reqs)
    np.testing.assert_array_equal(backing[:, ::2], src)
    # untouched lanes stay zero
    np.testing.assert_array_equal(backing[:, 1::2], np.zeros((16, 8), np.float32))


def test_chunked_read_into_offset_view() -> None:
    src = np.arange(24, dtype=np.float32).reshape(4, 6)
    entry, write_reqs = ArrayIOPreparer.prepare_write("loc", src)
    backing = np.full((8, 6), -1, dtype=np.float32)
    dst = backing[2:6, :]  # offset (but contiguous) view
    read_reqs = ArrayIOPreparer.prepare_read(
        entry, dst_view=dst, buffer_size_limit_bytes=32
    )
    _fulfill(write_reqs, read_reqs)
    np.testing.assert_array_equal(backing[2:6, :], src)
    assert (backing[:2] == -1).all() and (backing[6:] == -1).all()


def test_unchunked_read_into_transposed_view() -> None:
    src = np.random.default_rng(2).standard_normal((6, 4)).astype(np.float64)
    entry, write_reqs = ArrayIOPreparer.prepare_write("loc", src)
    backing = np.zeros((4, 6), dtype=np.float64)
    dst = backing.T
    read_reqs = ArrayIOPreparer.prepare_read(entry, dst_view=dst)
    _fulfill(write_reqs, read_reqs)
    np.testing.assert_array_equal(backing.T, src)


def test_chunked_entry_read_into_strided_view() -> None:
    """Budgeted ChunkedArrayEntry restore into a non-contiguous dst: per-chunk
    sub-views of a strided dst can themselves be contiguous, which routed
    writes directly into dst while the outer assembler's scratch copy-back
    then clobbered them. All writes must go through the assembler."""
    from torchsnapshot_tpu.io_preparers.chunked import ChunkedArrayIOPreparer

    src = np.random.default_rng(4).standard_normal((8, 6)).astype(np.float32)
    chunks = [([0, 0], [4, 6]), ([4, 0], [4, 6])]
    entry, write_reqs = ChunkedArrayIOPreparer.prepare_write("loc", src, chunks)

    backing = np.zeros((16, 6), dtype=np.float32)
    dst = backing[::2, :]  # row-strided, non-contiguous
    assert not dst.flags["C_CONTIGUOUS"]
    fired = []
    read_reqs = ChunkedArrayIOPreparer.prepare_read(
        entry,
        dst_view=dst,
        callback=lambda a: fired.append(a),
        buffer_size_limit_bytes=48,
    )
    _fulfill(write_reqs, read_reqs)
    assert fired, "completion callback did not fire"
    np.testing.assert_array_equal(backing[::2, :], src)
    np.testing.assert_array_equal(backing[1::2, :], np.zeros((8, 6), np.float32))


# ------------------------------------------------------- partition planning


def _partition_all_ranks(flattened, replicated, world_size):
    plans = [
        _partition_write_units(flattened, replicated, rank, world_size)
        for rank in range(world_size)
    ]
    return plans


def test_partition_deterministic_and_disjoint() -> None:
    rng = np.random.default_rng(3)
    flattened = {
        f"model/p{i}": rng.standard_normal((sz,)).astype(np.float32)
        for i, sz in enumerate([100, 5000, 17, 40000, 2, 900])
    }
    flattened["obj"] = {"arbitrary": "object"}
    replicated = set(flattened)
    world_size = 4
    plans = _partition_all_ranks(flattened, replicated, world_size)

    # Every chunk/object assigned exactly once across ranks.
    chunk_owners = []
    obj_owners = []
    for rank, (chunks, objs) in enumerate(plans):
        for lp, lst in chunks.items():
            for c in lst:
                chunk_owners.append((lp, tuple(c[0]), tuple(c[1]), rank))
        for lp in objs:
            obj_owners.append((lp, rank))
    keys = [(lp, o, s) for lp, o, s, _ in chunk_owners]
    assert len(keys) == len(set(keys)), "chunk assigned to multiple ranks"
    assert len(obj_owners) == len({lp for lp, _ in obj_owners})

    # Re-running yields the identical plan (determinism).
    again = _partition_all_ranks(flattened, replicated, world_size)
    for (c1, o1), (c2, o2) in zip(plans, again):
        assert {k: [tuple(map(tuple, c)) for c in v] for k, v in c1.items()} == {
            k: [tuple(map(tuple, c)) for c in v] for k, v in c2.items()
        }
        assert o1 == o2


def test_partition_balances_load() -> None:
    flattened = {
        f"p{i}": np.zeros(1000, dtype=np.float32) for i in range(16)
    }
    replicated = set(flattened)
    plans = _partition_all_ranks(flattened, replicated, 4)
    per_rank = [
        sum(len(v) for v in chunks.values()) for chunks, _ in plans
    ]
    assert sum(per_rank) == 16
    assert max(per_rank) - min(per_rank) <= 1


def test_partition_non_replicated_stays_local() -> None:
    flattened = {"mine": np.zeros(10, dtype=np.float32)}
    chunks, objs = _partition_write_units(flattened, set(), rank=2, world_size=4)
    assert "mine" in chunks and len(chunks["mine"]) == 1
    assert objs == set()


# ------------------------------------------------------------ object costs


def test_object_staging_cost_is_serialized_size() -> None:
    import pickle

    from torchsnapshot_tpu.io_preparers.object import ObjectIOPreparer

    # A nested dict whose sys.getsizeof is tiny but whose pickle is ~8 MB:
    # the cost model must see the real size (round-1 budget hole). Distinct
    # bytes objects — pickle memoizes repeated references.
    obj = {"level1": {"level2": [bytes([i]) * (1 << 20) for i in range(8)]}}
    entry, write_reqs = ObjectIOPreparer.prepare_write("0/obj", obj)
    cost = write_reqs[0].buffer_stager.get_staging_cost_bytes()
    actual = len(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
    assert cost == actual
    assert cost > 8 * (1 << 20)


def test_object_entry_records_size_and_consumer_uses_it() -> None:
    import asyncio

    from torchsnapshot_tpu.io_preparers.object import ObjectIOPreparer
    from torchsnapshot_tpu.manifest import entry_from_dict
    from dataclasses import asdict

    obj = list(range(100_000))
    entry, write_reqs = ObjectIOPreparer.prepare_write("0/obj", obj)
    buf = asyncio.new_event_loop().run_until_complete(
        write_reqs[0].buffer_stager.stage_buffer()
    )
    assert entry.size == len(buf)

    # size survives the manifest round trip and drives the consuming cost
    entry2 = entry_from_dict(asdict(entry))
    read_reqs, consumer = ObjectIOPreparer.prepare_read(entry2)
    assert consumer.get_consuming_cost_bytes() == 2 * len(buf)


def test_large_objects_stage_within_budget(tmp_path) -> None:
    """8 x 32 MB-pickle objects under a 64 MB budget: the scheduler must
    pipeline staging, not materialize all pickles at once (peak RSS stays
    near the budget, nowhere near the 256 MB sum)."""
    import os

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.rss_profiler import RSSProfiler

    objs = {f"o{i}": [bytes([i]) * (1 << 25)] for i in range(8)}  # list => object path
    app_state = {"blob": StateDict(objs)}
    os.environ["TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES"] = str(64 << 20)
    try:
        with RSSProfiler(interval_s=0.01) as prof:
            Snapshot.take(str(tmp_path / "snap"), app_state)
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES"]
    # Budget 64 MB; one over-budget item may be admitted via the starvation
    # escape, and buffers linger while writes drain — allow 3x headroom.
    # Without the real cost model, peak delta lands at the full 256 MB sum.
    assert prof.peak_delta_bytes < 192 << 20, (
        f"peak RSS delta {prof.peak_delta_bytes >> 20} MB exceeds bound"
    )


def test_host_consumers_get_writable_arrays_from_immutable_buffers() -> None:
    """Remote plugins (S3/GCS) hand back immutable ``bytes``. Host-facing
    consumers (read_state_dict, host callbacks) must still deliver
    WRITABLE arrays — a zero-copy frombuffer view over bytes is read-only
    and breaks in-place user code. Device-materialize consumers opt out:
    device_put never needs a writable source."""
    import asyncio

    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer
    from torchsnapshot_tpu.manifest import ArrayEntry
    from torchsnapshot_tpu.serialization import Serializer

    entry = ArrayEntry(
        location="0/app/w",
        serializer=Serializer.BUFFER_PROTOCOL.value,
        dtype="float32",
        shape=[8],
        replicated=False,
    )
    payload = np.arange(8, dtype=np.float32).tobytes()  # immutable

    got = {}
    consumer = ArrayBufferConsumer(entry, callback=lambda a: got.update(arr=a))
    asyncio.run(consumer.consume_buffer(payload))
    assert got["arr"].flags["WRITEABLE"]
    got["arr"][0] = 99.0  # must not raise
    np.testing.assert_array_equal(got["arr"][1:], np.arange(1, 8, dtype=np.float32))

    # opt-out path: zero-copy read-only view is acceptable for device_put
    got2 = {}
    consumer2 = ArrayBufferConsumer(
        entry, callback=lambda a: got2.update(arr=a), ensure_writable=False
    )
    asyncio.run(consumer2.consume_buffer(payload))
    np.testing.assert_array_equal(got2["arr"], np.arange(8, dtype=np.float32))
