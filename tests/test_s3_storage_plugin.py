"""S3 plugin logic tests against an in-memory fake client.

The reference gates its S3 tests on a real bucket + env var
(tests/test_s3_storage_plugin.py:29-86: write/read/delete + ranged read);
that covers AWS's SDK more than the plugin. These tests target OUR logic —
zero-copy streaming, rewind-on-retry, transient classification, ranged
GETs, and the shared collective retry strategy — with fakes, so they run
unconditionally (test strategy: SURVEY.md §4.4 fault injection via
plugin-level fakes).
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.storage_plugins.retry import CollectiveRetryStrategy
from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin


class FakeBody:
    def __init__(self, data: bytes):
        self._data = data

    def read(self) -> bytes:
        return self._data


class FakeS3Client:
    """Implements the three client calls the plugin makes, with optional
    transient failures injected before each operation."""

    def __init__(self, fail_times: int = 0):
        self.store: dict = {}
        self._fail_times = fail_times
        self.put_attempts = 0
        self.get_ranges: list = []

    def _maybe_fail(self):
        if self._fail_times > 0:
            self._fail_times -= 1
            raise ConnectionError("fake transient")

    def put_object(self, Bucket, Key, Body):
        self.put_attempts += 1
        # Consume the stream BEFORE failing, so a retry without rewind
        # would upload a short/corrupt body.
        data = Body.read()
        self._maybe_fail()
        self.store[(Bucket, Key)] = bytes(data)

    def get_object(self, Bucket, Key, Range=None):
        self._maybe_fail()
        data = self.store[(Bucket, Key)]
        if Range is not None:
            assert Range.startswith("bytes=")
            lo, _, hi = Range[len("bytes=") :].partition("-")
            self.get_ranges.append((int(lo), int(hi)))
            data = data[int(lo) : int(hi) + 1]  # HTTP ranges are inclusive
        return {"Body": FakeBody(data)}

    def delete_object(self, Bucket, Key):
        self._maybe_fail()
        del self.store[(Bucket, Key)]


def make_plugin(client: FakeS3Client, **options) -> S3StoragePlugin:
    return S3StoragePlugin(
        "fake-bucket/prefix", storage_options={"client": client, **options}
    )


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_write_read_delete_round_trip() -> None:
    client = FakeS3Client()
    plugin = make_plugin(client)
    payload = bytes(range(256)) * 100

    run(plugin.write(WriteIO(path="0/model/w", buf=memoryview(payload))))
    assert client.store[("fake-bucket", "prefix/0/model/w")] == payload

    read_io = ReadIO(path="0/model/w")
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload

    run(plugin.delete("0/model/w"))
    assert not client.store


def test_ranged_read() -> None:
    client = FakeS3Client()
    plugin = make_plugin(client)
    payload = bytes(range(256)) * 4
    run(plugin.write(WriteIO(path="f", buf=memoryview(payload))))

    read_io = ReadIO(path="f", byte_range=(100, 300))
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload[100:300]
    assert client.get_ranges == [(100, 299)]  # inclusive HTTP range header


def test_upload_retries_with_rewind() -> None:
    client = FakeS3Client(fail_times=2)
    strategy = CollectiveRetryStrategy(sleep=lambda s: asyncio.sleep(0))
    plugin = make_plugin(client, retry_strategy=strategy)
    payload = b"x" * 10_000

    run(plugin.write(WriteIO(path="w", buf=memoryview(payload))))
    assert client.put_attempts == 3  # 2 transient failures + success
    # A missing rewind would have stored a short body on the final attempt.
    assert client.store[("fake-bucket", "prefix/w")] == payload


def test_nontransient_error_propagates() -> None:
    client = FakeS3Client()
    plugin = make_plugin(client)
    with pytest.raises(KeyError):
        run(plugin.read(ReadIO(path="missing")))


def test_stalled_fleet_fails_together() -> None:
    t = [0.0]

    def clock():
        return t[0]

    async def sleep(s):
        t[0] += s

    client = FakeS3Client(fail_times=1000)
    strategy = CollectiveRetryStrategy(
        stall_timeout_s=10.0, clock=clock, sleep=sleep
    )
    plugin = make_plugin(client, retry_strategy=strategy)
    with pytest.raises(ConnectionError):
        run(plugin.write(WriteIO(path="w", buf=memoryview(b"data"))))


def test_short_ranged_read_raises() -> None:
    class TruncatingClient(FakeS3Client):
        def get_object(self, Bucket, Key, Range=None):
            resp = super().get_object(Bucket, Key, Range)
            return {"Body": FakeBody(resp["Body"].read()[:-5])}

    client = TruncatingClient()
    plugin = make_plugin(client)
    run(plugin.write(WriteIO(path="f", buf=memoryview(b"a" * 100))))
    with pytest.raises(IOError, match="short read"):
        run(plugin.read(ReadIO(path="f", byte_range=(0, 50))))


def test_end_to_end_snapshot_via_fake_s3(monkeypatch) -> None:
    """Full Snapshot.take/restore through the s3:// URL scheme."""
    from torchsnapshot_tpu import Snapshot, StateDict

    client = FakeS3Client()
    state = {
        "w": np.arange(1024, dtype=np.float32).reshape(32, 32),
        "step": 7,
    }
    app_state = {"model": StateDict(**state)}
    Snapshot.take(
        "s3://fake-bucket/ckpt", app_state, storage_options={"client": client}
    )

    dst = StateDict(w=np.zeros((32, 32), np.float32), step=-1)
    Snapshot(
        "s3://fake-bucket/ckpt", storage_options={"client": client}
    ).restore({"model": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])
    assert dst["step"] == 7
