"""S3 plugin logic tests against an in-memory fake client.

The reference gates its S3 tests on a real bucket + env var
(tests/test_s3_storage_plugin.py:29-86: write/read/delete + ranged read);
that covers AWS's SDK more than the plugin. These tests target OUR logic —
zero-copy streaming, rewind-on-retry, transient classification, ranged
GETs, and the shared collective retry strategy — with fakes, so they run
unconditionally (test strategy: SURVEY.md §4.4 fault injection via
plugin-level fakes).
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.storage_plugins.retry import CollectiveRetryStrategy
from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin


class FakeBody:
    def __init__(self, data: bytes):
        self._data = data

    def read(self) -> bytes:
        return self._data


class FakeS3Client:
    """Implements the three client calls the plugin makes, with optional
    transient failures injected before each operation."""

    def __init__(self, fail_times: int = 0):
        self.store: dict = {}
        self._fail_times = fail_times
        self.put_attempts = 0
        self.get_ranges: list = []

    def _maybe_fail(self):
        if self._fail_times > 0:
            self._fail_times -= 1
            raise ConnectionError("fake transient")

    def put_object(self, Bucket, Key, Body):
        self.put_attempts += 1
        # Consume the stream BEFORE failing, so a retry without rewind
        # would upload a short/corrupt body.
        data = Body.read()
        self._maybe_fail()
        self.store[(Bucket, Key)] = bytes(data)

    def get_object(self, Bucket, Key, Range=None):
        self._maybe_fail()
        data = self.store[(Bucket, Key)]
        if Range is not None:
            assert Range.startswith("bytes=")
            lo, _, hi = Range[len("bytes=") :].partition("-")
            self.get_ranges.append((int(lo), int(hi)))
            data = data[int(lo) : int(hi) + 1]  # HTTP ranges are inclusive
        return {"Body": FakeBody(data)}

    def delete_object(self, Bucket, Key):
        self._maybe_fail()
        del self.store[(Bucket, Key)]


def make_plugin(client: FakeS3Client, **options) -> S3StoragePlugin:
    return S3StoragePlugin(
        "fake-bucket/prefix", storage_options={"client": client, **options}
    )


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_write_read_delete_round_trip() -> None:
    client = FakeS3Client()
    plugin = make_plugin(client)
    payload = bytes(range(256)) * 100

    run(plugin.write(WriteIO(path="0/model/w", buf=memoryview(payload))))
    assert client.store[("fake-bucket", "prefix/0/model/w")] == payload

    read_io = ReadIO(path="0/model/w")
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload

    run(plugin.delete("0/model/w"))
    assert not client.store


def test_ranged_read() -> None:
    client = FakeS3Client()
    plugin = make_plugin(client)
    payload = bytes(range(256)) * 4
    run(plugin.write(WriteIO(path="f", buf=memoryview(payload))))

    read_io = ReadIO(path="f", byte_range=(100, 300))
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload[100:300]
    assert client.get_ranges == [(100, 299)]  # inclusive HTTP range header


def test_upload_retries_with_rewind() -> None:
    client = FakeS3Client(fail_times=2)
    strategy = CollectiveRetryStrategy(sleep=lambda s: asyncio.sleep(0))
    plugin = make_plugin(client, retry_strategy=strategy)
    payload = b"x" * 10_000

    run(plugin.write(WriteIO(path="w", buf=memoryview(payload))))
    assert client.put_attempts == 3  # 2 transient failures + success
    # A missing rewind would have stored a short body on the final attempt.
    assert client.store[("fake-bucket", "prefix/w")] == payload


def test_nontransient_error_propagates() -> None:
    client = FakeS3Client()
    plugin = make_plugin(client)
    with pytest.raises(KeyError):
        run(plugin.read(ReadIO(path="missing")))


def test_stalled_fleet_fails_together() -> None:
    t = [0.0]

    def clock():
        return t[0]

    async def sleep(s):
        t[0] += s

    client = FakeS3Client(fail_times=1000)
    strategy = CollectiveRetryStrategy(
        stall_timeout_s=10.0, clock=clock, sleep=sleep
    )
    plugin = make_plugin(client, retry_strategy=strategy)
    with pytest.raises(ConnectionError):
        run(plugin.write(WriteIO(path="w", buf=memoryview(b"data"))))


def test_short_ranged_read_raises() -> None:
    class TruncatingClient(FakeS3Client):
        def get_object(self, Bucket, Key, Range=None):
            resp = super().get_object(Bucket, Key, Range)
            return {"Body": FakeBody(resp["Body"].read()[:-5])}

    client = TruncatingClient()
    plugin = make_plugin(client)
    run(plugin.write(WriteIO(path="f", buf=memoryview(b"a" * 100))))
    with pytest.raises(IOError, match="short read"):
        run(plugin.read(ReadIO(path="f", byte_range=(0, 50))))


def test_end_to_end_snapshot_via_fake_s3(monkeypatch) -> None:
    """Full Snapshot.take/restore through the s3:// URL scheme."""
    from torchsnapshot_tpu import Snapshot, StateDict

    client = FakeS3Client()
    state = {
        "w": np.arange(1024, dtype=np.float32).reshape(32, 32),
        "step": 7,
    }
    app_state = {"model": StateDict(**state)}
    Snapshot.take(
        "s3://fake-bucket/ckpt", app_state, storage_options={"client": client}
    )

    dst = StateDict(w=np.zeros((32, 32), np.float32), step=-1)
    Snapshot(
        "s3://fake-bucket/ckpt", storage_options={"client": client}
    ).restore({"model": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])
    assert dst["step"] == 7


class FakeMultipartS3Client(FakeS3Client):
    """Adds the four multipart calls; parts assemble on complete."""

    def __init__(self, fail_times: int = 0, fail_part_numbers=()):
        super().__init__(fail_times)
        self.uploads: dict = {}
        self.aborted: list = []
        self._fail_part_numbers = set(fail_part_numbers)
        self.part_attempts = 0

    def create_multipart_upload(self, Bucket, Key):
        self._maybe_fail()
        uid = f"upload-{len(self.uploads)}"
        self.uploads[uid] = {}
        return {"UploadId": uid}

    def upload_part(self, Bucket, Key, UploadId, PartNumber, Body):
        self.part_attempts += 1
        data = Body.read()
        if PartNumber in self._fail_part_numbers:
            self._fail_part_numbers.discard(PartNumber)
            raise ConnectionError("fake transient part failure")
        self._maybe_fail()
        self.uploads[UploadId][PartNumber] = bytes(data)
        return {"ETag": f"etag-{PartNumber}"}

    def complete_multipart_upload(self, Bucket, Key, UploadId, MultipartUpload):
        self._maybe_fail()
        parts = MultipartUpload["Parts"]
        assert [p["PartNumber"] for p in parts] == sorted(
            p["PartNumber"] for p in parts
        )
        assembled = b"".join(
            self.uploads[UploadId][p["PartNumber"]] for p in parts
        )
        self.store[(Bucket, Key)] = assembled
        del self.uploads[UploadId]

    def abort_multipart_upload(self, Bucket, Key, UploadId):
        self.aborted.append(UploadId)
        self.uploads.pop(UploadId, None)


def test_multipart_upload_round_trip(monkeypatch) -> None:
    """Payloads past the threshold upload in parts and read back intact."""
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    monkeypatch.setattr(s3mod, "MULTIPART_PART_BYTES", 1024)
    client = FakeMultipartS3Client()
    plugin = make_plugin(client, multipart_threshold=2048)
    data = np.random.default_rng(0).integers(0, 255, 5000, np.uint8).tobytes()
    run(plugin.write(WriteIO(path="big.obj", buf=memoryview(data))))
    assert client.store[("fake-bucket", "prefix/big.obj")] == data
    assert client.part_attempts == 5  # ceil(5000/1024)
    assert not client.uploads  # completed, nothing in flight

    read_io = ReadIO(path="big.obj")
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == data


def test_multipart_part_retries_with_fresh_stream(monkeypatch) -> None:
    """A transient part failure retries that part; the part's stream is
    re-created so the retry uploads the full part, not a consumed one."""
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    monkeypatch.setattr(s3mod, "MULTIPART_PART_BYTES", 1024)
    client = FakeMultipartS3Client(fail_part_numbers=[2])
    plugin = make_plugin(
        client,
        multipart_threshold=2048,
        retry_strategy=CollectiveRetryStrategy(base_backoff_s=0.01),
    )
    data = bytes(range(256)) * 12  # 3072 bytes -> 3 parts
    run(plugin.write(WriteIO(path="retry.obj", buf=memoryview(data))))
    assert client.store[("fake-bucket", "prefix/retry.obj")] == data
    assert client.part_attempts == 4  # 3 parts + 1 retried


def test_multipart_aborts_on_nontransient_failure(monkeypatch) -> None:
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    monkeypatch.setattr(s3mod, "MULTIPART_PART_BYTES", 1024)

    class PoisonClient(FakeMultipartS3Client):
        def upload_part(self, Bucket, Key, UploadId, PartNumber, Body):
            if PartNumber == 2:
                raise ValueError("permanent")
            return super().upload_part(Bucket, Key, UploadId, PartNumber, Body)

    client = PoisonClient()
    plugin = make_plugin(client, multipart_threshold=2048)
    data = b"z" * 3000
    with pytest.raises(ValueError, match="permanent"):
        run(plugin.write(WriteIO(path="bad.obj", buf=memoryview(data))))
    assert client.aborted  # server-side cleanup requested
    assert ("fake-bucket", "prefix/bad.obj") not in client.store


def test_transfers_run_on_dedicated_cloud_pool() -> None:
    """Cloud I/O must ride the bounded tsnap-cloud-io pool, not the
    default loop executor."""
    import threading

    seen = []

    class RecordingClient(FakeS3Client):
        def put_object(self, Bucket, Key, Body):
            seen.append(threading.current_thread().name)
            return super().put_object(Bucket, Key, Body)

    plugin = make_plugin(RecordingClient())
    run(plugin.write(WriteIO(path="t.obj", buf=memoryview(b"x" * 64))))
    assert seen and all(n.startswith("tsnap-cloud-io") for n in seen)


def test_multipart_complete_commit_then_lost_response(monkeypatch) -> None:
    """CompleteMultipartUpload is not idempotent: if the server commits
    but the response is lost, the retry must detect the committed object
    (head_object) instead of failing on the dead upload id."""
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    monkeypatch.setattr(s3mod, "MULTIPART_PART_BYTES", 1024)

    class CommitThenDropClient(FakeMultipartS3Client):
        def __init__(self):
            super().__init__()
            self.completes = 0

        def complete_multipart_upload(self, Bucket, Key, UploadId, MultipartUpload):
            self.completes += 1
            super().complete_multipart_upload(Bucket, Key, UploadId, MultipartUpload)
            if self.completes == 1:
                # Server committed; response never reached the client.
                raise ConnectionError("response lost")

        def head_object(self, Bucket, Key):
            if (Bucket, Key) not in self.store:
                raise KeyError(Key)
            return {"ContentLength": len(self.store[(Bucket, Key)])}

    client = CommitThenDropClient()
    plugin = make_plugin(
        client,
        multipart_threshold=2048,
        retry_strategy=CollectiveRetryStrategy(base_backoff_s=0.01),
    )
    data = b"q" * 3000
    run(plugin.write(WriteIO(path="lost.obj", buf=memoryview(data))))
    assert client.store[("fake-bucket", "prefix/lost.obj")] == data
    assert client.completes == 1  # the retry resolved via head_object


def test_large_ranged_read_splits_into_concurrent_chunks(monkeypatch) -> None:
    """Ranged GETs past the chunk size fetch concurrently and reassemble
    bit-exactly; short chunk responses raise instead of zero-filling."""
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    monkeypatch.setattr(s3mod, "RANGED_READ_CHUNK_BYTES", 1024)
    client = FakeS3Client()
    plugin = make_plugin(client)
    data = np.random.default_rng(1).integers(0, 255, 10_000, np.uint8).tobytes()
    run(plugin.write(WriteIO(path="r.obj", buf=memoryview(data))))

    read_io = ReadIO(path="r.obj", byte_range=(500, 9_500))
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == data[500:9_500]
    # ceil(9000/1024) = 9 chunk GETs hit the client
    assert len(client.get_ranges) == 9

    class TruncatingClient(FakeS3Client):
        def get_object(self, Bucket, Key, Range=None):
            resp = super().get_object(Bucket, Key, Range)
            return {"Body": FakeBody(resp["Body"].read()[:-1])}

    t_client = TruncatingClient()
    t_client.store = dict(client.store)
    t_plugin = make_plugin(t_client)
    with pytest.raises(IOError, match="short read"):
        run(t_plugin.read(ReadIO(path="r.obj", byte_range=(0, 8_000))))


def test_cloud_pool_sustains_concurrent_transfers() -> None:
    """32 latency-bound transfers through the dedicated pool must overlap
    ~16-wide (the pool size), not serialize: wall ~ ceil(32/16) x op
    latency, far under 32 x latency."""
    import asyncio
    import time

    class SlowClient(FakeS3Client):
        def put_object(self, Bucket, Key, Body):
            data = Body.read()
            time.sleep(0.1)  # network latency stand-in (GIL released)
            self.store[(Bucket, Key)] = bytes(data)

    plugin = make_plugin(SlowClient())

    async def run_all():
        await asyncio.gather(
            *(
                plugin.write(WriteIO(path=f"o{i}", buf=memoryview(b"x" * 128)))
                for i in range(32)
            )
        )

    t0 = time.perf_counter()
    run(run_all())
    wall = time.perf_counter() - t0
    # Serial would be 3.2 s; 16-way pool gives ~0.2 s. Allow generous
    # headroom for a loaded 1-core host.
    assert wall < 1.2, f"transfers serialized: {wall:.2f}s for 32 x 0.1s ops"
