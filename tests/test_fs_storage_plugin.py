"""FS storage plugin tests (reference: tests/test_fs_storage_plugin.py:26)."""

import asyncio
import os

import pytest

from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_write_read_delete(tmp_path, loop) -> None:
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(4096)

    loop.run_until_complete(plugin.write(WriteIO(path="a/b/c.bin", buf=payload)))
    assert (tmp_path / "a" / "b" / "c.bin").read_bytes() == payload

    read_io = ReadIO(path="a/b/c.bin")
    loop.run_until_complete(plugin.read(read_io))
    assert bytes(read_io.buf) == payload

    ranged = ReadIO(path="a/b/c.bin", byte_range=(100, 200))
    loop.run_until_complete(plugin.read(ranged))
    assert bytes(ranged.buf) == payload[100:200]

    loop.run_until_complete(plugin.delete("a/b/c.bin"))
    assert not (tmp_path / "a" / "b" / "c.bin").exists()
    loop.run_until_complete(plugin.close())


def test_memoryview_write(tmp_path, loop) -> None:
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = bytearray(b"hello world" * 100)
    loop.run_until_complete(
        plugin.write(WriteIO(path="mv.bin", buf=memoryview(payload)))
    )
    read_io = ReadIO(path="mv.bin")
    loop.run_until_complete(plugin.read(read_io))
    assert bytes(read_io.buf) == bytes(payload)


def test_url_resolution(tmp_path) -> None:
    for url in [str(tmp_path), f"fs://{tmp_path}"]:
        plugin = url_to_storage_plugin(url)
        assert isinstance(plugin, FSStoragePlugin)
        assert plugin.root == str(tmp_path)


def test_unknown_protocol_raises() -> None:
    with pytest.raises(RuntimeError, match="Failed to resolve storage plugin"):
        url_to_storage_plugin("bogus://bucket/path")


def test_write_is_atomic_no_tmp_litter(tmp_path, loop) -> None:
    """Writes land via temp+rename: after a snapshot no .tmp files remain,
    and an interrupted write leaves neither a truncated destination nor a
    stray temp file."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    p = str(tmp_path / "snap")
    Snapshot.take(p, {"app": StateDict(w=np.ones(64, np.float32))})
    leftovers = [
        f for _, _, files in os.walk(p) for f in files if ".tmp." in f
    ]
    assert leftovers == []

    plugin = FSStoragePlugin(str(tmp_path))

    class Boom:
        def __bytes__(self):
            raise RuntimeError("boom")

    with pytest.raises(Exception):
        loop.run_until_complete(plugin.write(WriteIO(path="dst", buf=Boom())))
    assert not (tmp_path / "dst").exists()
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


def test_write_fsync_env(tmp_path, loop, monkeypatch) -> None:
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_FSYNC", "1")
    plugin = FSStoragePlugin(str(tmp_path))
    assert plugin._fsync
    loop.run_until_complete(plugin.write(WriteIO(path="f", buf=b"abc")))
    assert (tmp_path / "f").read_bytes() == b"abc"
