"""Direct unit tests for the thread-pool aiofiles shim (_aio.py).

The shim is the local-FS plugin's fallback when aiofiles is absent
(hermetic containers), so its surface must behave exactly like the real
thing: async open as a context manager, write/read/readinto/seek/flush/
fileno, os.replace/os.remove, exception propagation, and clean behavior
around event-loop teardown.
"""

import asyncio
import os

import pytest

from torchsnapshot_tpu import _aio


def test_write_then_read_roundtrip(tmp_path):
    path = str(tmp_path / "f.bin")
    payload = os.urandom(1 << 16)

    async def main():
        async with _aio.open(path, "wb") as f:
            n = await f.write(payload)
            await f.flush()
            assert n == len(payload)
            assert isinstance(f.fileno(), int)
        async with _aio.open(path, "rb") as f:
            return await f.read()

    assert asyncio.run(main()) == payload


def test_readinto_and_seek(tmp_path):
    path = str(tmp_path / "f.bin")
    payload = bytes(range(256)) * 16

    async def main():
        async with _aio.open(path, "wb") as f:
            await f.write(payload)
        async with _aio.open(path, "rb") as f:
            pos = await f.seek(100)
            assert pos == 100
            buf = bytearray(32)
            got = await f.readinto(memoryview(buf))
            assert got == 32
            return bytes(buf)

    assert asyncio.run(main()) == payload[100:132]


def test_concurrent_writes_and_reads(tmp_path):
    """Many files written concurrently through the shared executor, then
    read back concurrently — no interleaving corruption, no lost writes."""
    n_files = 16
    payloads = {i: bytes([i]) * (4096 + i) for i in range(n_files)}

    async def write_one(i):
        async with _aio.open(str(tmp_path / f"f{i}"), "wb") as f:
            await f.write(payloads[i])

    async def read_one(i):
        async with _aio.open(str(tmp_path / f"f{i}"), "rb") as f:
            return i, await f.read()

    async def main():
        await asyncio.gather(*(write_one(i) for i in range(n_files)))
        results = await asyncio.gather(*(read_one(i) for i in range(n_files)))
        return dict(results)

    assert asyncio.run(main()) == payloads


def test_exception_propagation(tmp_path):
    async def read_missing():
        async with _aio.open(str(tmp_path / "nope"), "rb") as f:
            await f.read()

    with pytest.raises(FileNotFoundError):
        asyncio.run(read_missing())

    async def write_into_missing_dir():
        async with _aio.open(str(tmp_path / "no" / "dir" / "f"), "wb") as f:
            await f.write(b"x")

    with pytest.raises(FileNotFoundError):
        asyncio.run(write_into_missing_dir())

    async def bad_mode_op():
        # Writing to a read-mode handle: the underlying io error must
        # surface through the executor hop, not vanish.
        p = str(tmp_path / "ro")
        with open(p, "wb") as f:
            f.write(b"x")
        async with _aio.open(p, "rb") as f:
            await f.write(b"y")

    # io.UnsupportedOperation subclasses both OSError and ValueError.
    with pytest.raises((OSError, ValueError)):
        asyncio.run(bad_mode_op())


def test_aio_os_replace_and_remove(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")

    async def main():
        async with _aio.open(src, "wb") as f:
            await f.write(b"payload")
        await _aio.os.replace(src, dst)
        assert not os.path.exists(src)
        with open(dst, "rb") as f:
            assert f.read() == b"payload"
        await _aio.os.remove(dst)
        assert not os.path.exists(dst)
        with pytest.raises(FileNotFoundError):
            await _aio.os.remove(dst)

    asyncio.run(main())


def test_context_exit_closes_file_even_on_error(tmp_path):
    path = str(tmp_path / "f")
    holder = {}

    async def main():
        try:
            async with _aio.open(path, "wb") as f:
                holder["f"] = f
                await f.write(b"x")
                raise RuntimeError("boom")
        except RuntimeError:
            pass

    asyncio.run(main())
    # The underlying file object must be closed by __aexit__ despite the
    # in-body exception (fd leak otherwise).
    assert holder["f"]._f.closed


def test_executor_shutdown_on_loop_close(tmp_path):
    """asyncio.run closes the loop AND shuts down its default executor;
    the shim must not cache anything loop-bound — a fresh loop after a
    closed one keeps working, and ops on the CLOSED loop fail cleanly."""
    path = str(tmp_path / "f")

    async def write(data):
        async with _aio.open(path, "wb") as f:
            await f.write(data)

    # Loop 1: use and close (asyncio.run shuts down the default executor).
    asyncio.run(write(b"first"))
    # Loop 2: the shim rebinds to the running loop's executor each call.
    asyncio.run(write(b"second"))
    with open(path, "rb") as f:
        assert f.read() == b"second"
    # Driving the coroutine on a closed loop raises, not hangs.
    loop = asyncio.new_event_loop()
    loop.close()
    coro = write(b"third")
    with pytest.raises(RuntimeError):
        loop.run_until_complete(coro)
    coro.close()  # never started; close it so no un-awaited warning


def test_fs_plugin_uses_shim_surface(tmp_path):
    """The exact subset fs.py consumes exists and composes: write via the
    plugin code path with the shim forced in place of aiofiles."""
    import torchsnapshot_tpu.storage_plugins.fs as fs_mod
    from torchsnapshot_tpu.io_types import ReadIO, WriteIO

    orig = fs_mod.aiofiles
    fs_mod.aiofiles = _aio
    try:
        plugin = fs_mod.FSStoragePlugin(str(tmp_path / "root"))

        async def main():
            await plugin.write(WriteIO(path="a/b.bin", buf=b"shimmed"))
            read_io = ReadIO(path="a/b.bin")
            await plugin.read(read_io)
            return bytes(read_io.buf)

        assert asyncio.run(main()) == b"shimmed"
    finally:
        fs_mod.aiofiles = orig
