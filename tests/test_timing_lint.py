"""Tier-1 enforcement of the ad-hoc-timing lint (scripts/check_timing_lint.py):
the telemetry package owns pipeline timing; raw time.monotonic()/
perf_counter() measurement anywhere else in torchsnapshot_tpu/ fails CI.
"""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "check_timing_lint.py")


def _load_lint():
    spec = importlib.util.spec_from_file_location("check_timing_lint", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_package_is_clean():
    r = subprocess.run(
        [sys.executable, SCRIPT], capture_output=True, text=True, timeout=120
    )
    assert r.returncode == 0, r.stderr


def test_lint_detects_violations(tmp_path):
    lint = _load_lint()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "import time as _time\n"
        "from time import perf_counter\n"
        "from time import monotonic as mono\n"
        "t0 = time.monotonic()\n"
        "t1 = _time.perf_counter()\n"
        "t2 = perf_counter()\n"
        "t3 = mono()\n"
    )
    found = lint._violations_in(str(bad))
    # Two from-imports + four call sites.
    assert len(found) == 6
    whats = {w for _, w in found}
    assert "time.monotonic()" in whats
    assert "_time.perf_counter()" in whats
    assert "perf_counter()" in whats
    assert "mono()" in whats


def test_benchmark_allowlist_covers_wall_clock_benchmarks():
    """benchmarks/ is linted too: raw-clock benchmarks must be
    registered deliberately, and the registry must not list files that
    no longer exist (stale entries would mask a future rename)."""
    lint = _load_lint()
    assert "stream_overlap.py" in lint.BENCHMARK_ALLOWLIST
    assert "restore_overlap.py" in lint.BENCHMARK_ALLOWLIST
    for name in lint.BENCHMARK_ALLOWLIST:
        assert os.path.exists(os.path.join(lint.BENCH_DIR, name)), name


def test_lint_ignores_deadline_allowlist_and_telemetry():
    lint = _load_lint()
    assert "dist_store.py" in lint.ALLOWLIST
    # The telemetry package itself is exempt by construction: the walk
    # skips it; its own clock IS time.monotonic.
    tele = os.path.join(REPO, "torchsnapshot_tpu", "telemetry", "core.py")
    assert os.path.exists(tele)
