"""Dtype-matrix round-trip tests for the serialization codecs.

Mirrors the reference's parametrized dtype coverage
(tests/test_tensor_io_preparer.py:104-107) extended to ml_dtypes.
"""

import numpy as np
import pytest

from torchsnapshot_tpu.serialization import (
    SUPPORTED_DTYPE_STRINGS,
    array_as_memoryview,
    array_from_buffer,
    array_size_bytes,
    dtype_to_string,
    object_as_bytes,
    object_from_bytes,
    string_to_dtype,
)


def _rand_array(dtype_str: str, shape=(7, 5)) -> np.ndarray:
    dtype = string_to_dtype(dtype_str)
    rng = np.random.default_rng(0)
    if dtype_str == "bool":
        return rng.integers(0, 2, size=shape).astype(bool)
    if dtype_str.startswith(("int", "uint")):
        hi = 2 if dtype_str.endswith("2") else (8 if dtype_str.endswith("4") else 100)
        return rng.integers(0, hi, size=shape).astype(dtype)
    if dtype_str.startswith("complex"):
        return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(dtype)
    return rng.standard_normal(shape).astype(dtype)


@pytest.mark.parametrize("dtype_str", sorted(SUPPORTED_DTYPE_STRINGS))
def test_roundtrip_all_dtypes(dtype_str: str) -> None:
    arr = _rand_array(dtype_str)
    mv = array_as_memoryview(arr)
    assert len(mv) == array_size_bytes(arr.shape, dtype_str)
    out = array_from_buffer(bytes(mv), dtype_str, arr.shape)
    assert out.dtype == string_to_dtype(dtype_str)
    assert out.shape == arr.shape
    # Bitwise equality: the strongest round-trip guarantee, and robust to
    # dtypes whose values can't be compared (e8m0 NaN etc.).
    assert bytes(array_as_memoryview(out)) == bytes(mv)


def test_memoryview_is_zero_copy() -> None:
    arr = np.arange(16, dtype=np.float32).reshape(4, 4)
    mv = array_as_memoryview(arr)
    arr[0, 0] = 42.0
    assert np.frombuffer(mv, dtype=np.float32)[0] == 42.0


def test_non_contiguous_input() -> None:
    arr = np.arange(24, dtype=np.int32).reshape(4, 6)[:, ::2]
    mv = array_as_memoryview(arr)
    out = array_from_buffer(bytes(mv), "int32", arr.shape)
    np.testing.assert_array_equal(out, arr)


def test_scalar_shape() -> None:
    arr = np.float64(3.5)
    mv = array_as_memoryview(np.asarray(arr))
    out = array_from_buffer(bytes(mv), "float64", ())
    assert out == arr


def test_dtype_string_stability() -> None:
    # On-disk format: these names must never change meaning.
    for name in ["float32", "bfloat16", "int8", "bool", "float8_e4m3fn"]:
        if name in SUPPORTED_DTYPE_STRINGS:
            assert dtype_to_string(string_to_dtype(name)) == name


def test_unknown_dtype_string_raises() -> None:
    with pytest.raises(ValueError, match="Unknown dtype"):
        string_to_dtype("float1337")


def test_object_roundtrip() -> None:
    obj = {"a": [1, 2, (3, "x")], "b": {4, 5}}
    assert object_from_bytes(object_as_bytes(obj)) == obj


def test_jax_array_to_numpy_roundtrip() -> None:
    import jax
    import jax.numpy as jnp

    x = jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4)
    host = np.asarray(jax.device_get(x))
    mv = array_as_memoryview(host)
    out = array_from_buffer(bytes(mv), "bfloat16", (3, 4))
    np.testing.assert_array_equal(out, host)
