"""Stall forensics (telemetry/forensics.py): the always-on hang
watchdog, stack sampling/classification, self- and remote-triggered
dumps, and the blackbox WEDGE/frames merge.

The end-to-end hang drill (delay-injected w2 take -> stalled rank
self-dumps -> watch --dump round trip) lives in test_watch_cli.py next
to the health-plane drill it extends.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.telemetry import flightrec, forensics, health


@pytest.fixture(autouse=True)
def _forensics_clean():
    """Every test starts enabled with empty registries and leaves the
    module the way the shipping default has it."""
    forensics.set_enabled(True)
    forensics._reset_registries_for_tests()
    health.clear()
    yield
    forensics.set_enabled(True)
    forensics._reset_registries_for_tests()
    health.clear()


# ----------------------------------------------------------- env gating


def test_enabled_by_default_and_env_gate(monkeypatch):
    monkeypatch.delenv(forensics.FORENSICS_ENV_VAR, raising=False)
    assert forensics.refresh_from_env() is True
    for off in ("0", "off", "false", "no", "never"):
        monkeypatch.setenv(forensics.FORENSICS_ENV_VAR, off)
        assert forensics.refresh_from_env() is False
    monkeypatch.setenv(forensics.FORENSICS_ENV_VAR, "1")
    assert forensics.refresh_from_env() is True


def test_knob_accessors_parse_and_floor(monkeypatch):
    monkeypatch.setenv(forensics.SAMPLE_ENV_VAR, "0.001")
    assert forensics.sample_cadence_s() == 0.05  # floored
    monkeypatch.setenv(forensics.SAMPLE_ENV_VAR, "junk")
    assert forensics.sample_cadence_s() == 0.5  # default on parse failure
    monkeypatch.setenv(forensics.DEADLINE_FRAC_ENV_VAR, "0.25")
    assert forensics.deadline_fraction() == 0.25
    monkeypatch.setenv(forensics.STALL_ENV_VAR, "2.5")
    assert forensics.stall_window_s() == 2.5


# ------------------------------------------- classification and sampling


def _pkg(rel):
    return os.path.join(os.sep + "x", "torchsnapshot_tpu", rel)


def test_classify_frames_maps_modules_to_critpath_lanes():
    cases = [
        ("pg_wrapper.py", "collective_wait"),
        ("native_io.py", "native_io"),
        (os.path.join("io_preparers", "array.py"), "stage_copy"),
        ("integrity.py", "hash"),
        ("compression.py", "decode"),
        ("partial_reader.py", "storage_read"),
        ("fanout.py", "peer_transfer"),
    ]
    for rel, want in cases:
        cat, frame = forensics.classify_frames([(_pkg(rel), "f", 10)])
        assert cat == want, rel
        assert frame.endswith(":f:10")


def test_classify_frames_storage_plugin_read_write_split():
    wr = forensics.classify_frames(
        [(_pkg(os.path.join("storage_plugins", "fs.py")), "write", 99)]
    )
    rd = forensics.classify_frames(
        [(_pkg(os.path.join("storage_plugins", "fs.py")), "read", 120)]
    )
    assert wr[0] == "storage_write"
    assert rd[0] == "storage_read"


def test_classify_frames_skips_observer_modules():
    """faultinject and telemetry frames never take the blame: a delay
    injected at fs.write attributes to the fs.py frame above it."""
    frames = [
        (_pkg("snapshot.py"), "take", 1),
        (_pkg(os.path.join("storage_plugins", "fs.py")), "write", 99),
        (_pkg("faultinject.py"), "_delay", 50),
    ]
    cat, frame = forensics.classify_frames(frames)
    assert cat == "storage_write"
    assert "fs.py:write:99" in frame


def test_classify_frames_non_package_is_idle():
    assert forensics.classify_frames([("/usr/lib/python3/ast.py", "x", 1)]) == (
        None,
        None,
    )


def test_sample_stacks_covers_every_thread():
    ev = threading.Event()
    t = threading.Thread(target=ev.wait, name="parked", daemon=True)
    t.start()
    try:
        threads = forensics.sample_stacks()
        names = {rec["name"] for rec in threads}
        assert "parked" in names
        assert any(rec["name"] == "MainThread" for rec in threads)
        for rec in threads:
            assert set(rec) >= {"name", "daemon", "idle", "category",
                                "leaf", "frames"}
            assert len(rec["frames"]) <= forensics.MAX_FRAMES
    finally:
        ev.set()
        t.join()


def test_fold_into_counts_and_evicts():
    profile = {}
    threads = [{"name": "T", "frames": ["a.py:f:1", "b.py:g:2"]}]
    forensics.fold_into(profile, threads)
    forensics.fold_into(profile, threads)
    (key, count), = profile.items()
    assert count == 2
    assert key == "T;a.py:f:1;b.py:g:2"


def test_pick_wedge_prefers_trigger_category():
    threads = [
        {"name": "A", "idle": True, "category": None, "leaf": None},
        {"name": "B", "idle": False, "category": "stage_copy", "leaf": "x"},
        {"name": "C", "idle": False, "category": "storage_write", "leaf": "y"},
    ]
    assert forensics.pick_wedge(threads)["name"] == "B"  # first non-idle
    assert forensics.pick_wedge(threads, prefer="storage")["name"] == "C"
    assert forensics.pick_wedge(
        threads, prefer="collective_wait")["name"] == "B"  # fall through
    assert forensics.pick_wedge([threads[0]]) is None


# ----------------------------------------------------- trigger registries


def test_collective_registry_and_overdue_fraction():
    forensics.collective_begin("barrier", "ns", 1, 10.0)
    now = forensics.monotonic()
    assert forensics.collectives_overdue(now + 1.0, 0.5) == []
    over = forensics.collectives_overdue(now + 6.0, 0.5)
    assert len(over) == 1 and over[0]["kind"] == "barrier"
    forensics.collective_end("ns", 1)
    assert forensics.collectives_overdue(now + 60.0, 0.5) == []


def test_collective_without_deadline_never_triggers():
    forensics.collective_begin("barrier", "ns", 2, None)
    assert forensics.collectives_overdue(
        forensics.monotonic() + 9e6, 0.5) == []


def test_storage_op_feeds_p99_ring():
    for _ in range(forensics._MIN_P99_SAMPLES):
        with forensics.storage_op("storage_write", path="p"):
            pass
    assert forensics._p99("storage_write") is not None
    assert forensics._p99("storage_read") is None  # no samples yet


def test_storage_overdue_uses_no_history_floor():
    release = threading.Event()

    def slow():
        with forensics.storage_op("storage_write", path="/p"):
            release.wait(5.0)

    t = threading.Thread(target=slow, daemon=True)
    t.start()
    try:
        time.sleep(0.05)
        now = forensics.monotonic()
        # Below the 30 s no-history floor: quiet.
        assert forensics.storage_overdue(now) == []
        # Past it: overdue, naming the kind and path.
        over = forensics.storage_overdue(
            now + forensics.NO_HISTORY_FLOOR_S + 1.0)
        assert len(over) == 1
        assert over[0]["kind"] == "storage_write"
        assert over[0]["path"] == "/p"
    finally:
        release.set()
        t.join()
    # Completed op leaves the in-flight table.
    assert forensics.storage_overdue(forensics.monotonic() + 9e6) == []


def test_disabled_guards_are_no_ops():
    forensics.set_enabled(False)
    forensics.collective_begin("barrier", "ns", 3, 1.0)
    with forensics.storage_op("storage_write"):
        pass
    assert forensics.collectives_overdue(
        forensics.monotonic() + 9e6, 0.5) == []
    assert forensics._p99("storage_write") is None


# ------------------------------------------------------- dumps and loads


def test_dump_and_load_roundtrip(tmp_path):
    p = forensics.dump_stacks(str(tmp_path), 3, "test reason",
                              trigger="remote")
    assert p is not None and p.endswith("rank_3.stacks.jsonl")
    # Append, not overwrite: the WEDGE rule needs consecutive dumps.
    forensics.dump_stacks(str(tmp_path), 3, "again", trigger="remote")
    loaded = forensics.load_stack_dumps(str(tmp_path))
    assert list(loaded) == [3] and len(loaded[3]) == 2
    rec = loaded[3][0]
    assert rec["reason"] == "test reason"
    assert rec["trigger"] == "remote"
    assert rec["threads"]


def test_dump_disabled_returns_none(tmp_path):
    forensics.set_enabled(False)
    assert forensics.dump_stacks(str(tmp_path), 0, "r") is None
    assert forensics.load_stack_dumps(str(tmp_path)) == {}


def test_flight_ring_dump_also_dumps_stacks(tmp_path):
    """The on-abort pairing: every flight-ring dump brings the stacks
    with it (the hook lives inside flightrec.dump, so every abort path
    inherits it)."""
    flightrec.record("take.begin", path=str(tmp_path))
    out = flightrec.dump(str(tmp_path), 0, "test abort")
    assert out is not None
    stacks = forensics.load_stack_dumps(str(tmp_path))
    assert 0 in stacks
    assert stacks[0][-1]["trigger"] == "abort"
    # And the ring loader does not choke on the stacks file next door.
    rings = flightrec.load_dumps(str(tmp_path))
    assert 0 in rings


def test_stacks_file_survives_fsck_clean_and_repair(tmp_path):
    """A snapshot whose .flight/ holds both ring and stack dumps fscks
    clean — forensic artifacts are internal, not orphans — and --repair
    leaves them in place."""
    from torchsnapshot_tpu.cli import run_fsck

    snap = tmp_path / "snap"
    Snapshot.take(str(snap), {"model": StateDict(
        a=np.arange(64, dtype=np.float32))})
    flightrec.record("take.begin", path=str(snap))
    assert flightrec.dump(str(snap), 0, "post-commit dump") is not None
    assert forensics.dump_stacks(str(snap), 0, "manual") is not None
    code, report = run_fsck(str(snap))
    assert code == 0, report
    code, report = run_fsck(str(snap), repair=True)
    assert code == 0, report
    assert os.path.exists(snap / ".flight" / "rank_0.stacks.jsonl")
    assert os.path.exists(snap / ".flight" / "rank_0.jsonl")


# --------------------------------------------------- watchdog lifecycle


def test_arm_returns_none_when_disabled():
    forensics.set_enabled(False)

    class PG:
        def get_rank(self):
            return 0

        def get_world_size(self):
            return 1

    assert forensics.arm(PG(), "take", "/tmp/x") is None


def test_take_arms_and_disarms_watchdog(tmp_path):
    """A plain take starts exactly one watchdog thread and its finally
    stops it (no 'tsnap-forensics' thread outlives the op)."""
    Snapshot.take(str(tmp_path / "s"), {"model": StateDict(
        a=np.arange(256, dtype=np.float32))})
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if not [t for t in threading.enumerate()
                if t.name == "tsnap-forensics"]:
            break
        time.sleep(0.02)
    assert not [t for t in threading.enumerate()
                if t.name == "tsnap-forensics"]


def test_watchdog_frozen_progress_self_dump(tmp_path, monkeypatch):
    monkeypatch.setenv(forensics.STALL_ENV_VAR, "0.2")
    health.update(op="take", phase="write", written_bytes=5)
    wd = forensics.Watchdog(0, "take", str(tmp_path), cadence_s=0.05)
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            loaded = forensics.load_stack_dumps(str(tmp_path))
            if loaded.get(0):
                break
            time.sleep(0.05)
        loaded = forensics.load_stack_dumps(str(tmp_path))
        assert loaded.get(0), "watchdog never self-dumped"
        rec = loaded[0][0]
        assert rec["trigger"] == "frozen-progress"
        assert "frozen" in rec["reason"]
    finally:
        wd.stop()


def test_watchdog_collective_deadline_self_dump(tmp_path):
    forensics.collective_begin("barrier", "ckpt", 9, 0.2)
    wd = forensics.Watchdog(1, "take", str(tmp_path), cadence_s=0.05)
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if forensics.load_stack_dumps(str(tmp_path)).get(1):
                break
            time.sleep(0.05)
        recs = forensics.load_stack_dumps(str(tmp_path)).get(1)
        assert recs, "watchdog never fired on the overdue collective"
        assert recs[0]["trigger"] == "collective-deadline"
        assert "barrier #9" in recs[0]["reason"]
    finally:
        wd.stop()
        forensics.collective_end("ckpt", 9)


def test_remote_dump_request_roundtrip(tmp_path):
    """watch --dump protocol over a real local store: request key in,
    stacks on disk + summary under forensic_out/, retraction on stop."""
    from torchsnapshot_tpu.dist_store import TCPStore

    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    wd = None
    try:
        wd = forensics.Watchdog(
            1, "take", str(tmp_path), store=store, cadence_s=0.05)
        wd.start()
        store.set(f"{forensics.FORENSIC_REQ_PREFIX}1", b"1")
        out_key = f"{forensics.FORENSIC_OUT_PREFIX}1"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if store.check(out_key):
                break
            time.sleep(0.05)
        assert store.check(out_key), "watchdog never answered the request"
        payload = json.loads(store.get(out_key).decode("utf-8"))
        assert payload["rank"] == 1
        assert payload["trigger"] == "remote"
        # The request key was consumed; the stacks landed on disk.
        assert not store.check(f"{forensics.FORENSIC_REQ_PREFIX}1")
        assert forensics.load_stack_dumps(str(tmp_path)).get(1)
        wd.stop()
        wd = None
        assert not store.check(out_key)  # retracted on the way out
    finally:
        if wd is not None:
            wd.stop()
        store.close()


# ------------------------------------------------------ blackbox merging


def _rec(leaf, category, thread="pipeline"):
    return {
        "threads": [
            {"name": thread, "idle": False, "leaf": leaf,
             "category": category, "daemon": True, "frames": [leaf]},
            {"name": "MainThread", "idle": True, "leaf": None,
             "category": None, "daemon": False, "frames": []},
        ],
        "wedge": {"thread": thread, "frame": leaf, "category": category},
    }


def test_derive_wedge_findings_needs_consecutive_identical_leaves():
    same = [_rec("fs.py:write:99", "storage_write")] * 2
    moving = [_rec("a.py:f:1", "stage_copy"), _rec("b.py:g:2", "hash")]
    found = forensics.derive_wedge_findings({0: same, 1: moving})
    assert len(found) == 1
    f = found[0]
    assert (f["class"], f["rank"], f["dumps"]) == ("wedge", 0, 2)
    assert f["frame"] == "fs.py:write:99"
    assert f["category"] == "storage_write"
    # A single dump is a snapshot, not a wedge.
    assert forensics.derive_wedge_findings(
        {2: [_rec("x.py:f:1", "hash")]}) == []


def test_latest_wedge_renders_category_and_frame():
    stacks = {1: [_rec("fs.py:write:99", "storage_write")]}
    assert forensics.latest_wedge(stacks, 1) == (
        "storage_write @ fs.py:write:99")
    assert forensics.latest_wedge(stacks, 7) is None


def test_merge_stack_findings_annotates_desertion_and_appends_wedge():
    merged = {
        "findings": [{
            "class": "desertion", "kind": "barrier", "ns": "ckpt",
            "cseq": 4, "entered": [1], "never_arrived": [0],
            "stuck": [1], "errored": [], "errors": {},
        }],
    }
    stacks = {1: [_rec("pg_wrapper.py:_wait:310", "collective_wait")] * 2}
    forensics.merge_stack_findings(merged, stacks)
    assert merged["stack_ranks"] == [1]
    assert merged["stack_dumps"] == {1: 2}
    desertion = merged["findings"][0]
    assert desertion["frames"][1] == (
        "collective_wait @ pg_wrapper.py:_wait:310")
    wedges = [f for f in merged["findings"] if f["class"] == "wedge"]
    assert len(wedges) == 1 and wedges[0]["rank"] == 1
    rendered = flightrec.render_timeline(merged)
    assert "WEDGE" in rendered
    assert "pg_wrapper.py:_wait:310" in rendered
    assert "executing: r1 collective_wait" in rendered


def test_blackbox_cli_reads_stacks_only_wreck(tmp_path, capsys):
    """A hang that never aborted leaves stack dumps and no ring dumps;
    blackbox still reads the wreck and exits 1 on the WEDGE finding."""
    from torchsnapshot_tpu.cli import main as cli_main

    flight = tmp_path / ".flight"
    flight.mkdir()
    rec = _rec("fs.py:write:99", "storage_write")
    rec.update(rank=1, seq=1, t=0.0, reason="r", trigger="storage-p99")
    with open(flight / "rank_1.stacks.jsonl", "w") as f:
        f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(rec) + "\n")
    code = cli_main(["blackbox", str(tmp_path)])
    out = capsys.readouterr().out
    assert code == 1
    assert "WEDGE" in out
    assert "fs.py:write:99" in out


def test_blackbox_cli_exit_2_only_when_nothing_at_all(tmp_path, capsys):
    from torchsnapshot_tpu.cli import main as cli_main

    assert cli_main(["blackbox", str(tmp_path)]) == 2
    assert "stack dumps" in capsys.readouterr().err


# -------------------------------------------------------- watch rendering


def test_render_fleet_shows_wedged_frame_inline():
    fleet = {
        0: {"op": "take", "phase": "write", "seq": 3, "wall_s": 2.0},
        1: {"op": "take", "phase": "write", "seq": 2, "wall_s": 2.1},
    }
    out = health.render_fleet(
        fleet, {0: 0.1, 1: 9.0}, stall_s=5.0,
        wedged={1: "storage_write @ fs.py:write:99"},
    )
    stalled_row = [ln for ln in out.splitlines() if "STALLED" in ln][0]
    assert "wedged storage_write @ fs.py:write:99" in stalled_row
    clean_row = [ln for ln in out.splitlines()
                 if ln.lstrip().startswith("0")][0]
    assert "wedged" not in clean_row


def test_native_degrade_event_registered():
    from torchsnapshot_tpu.telemetry import taxonomy

    assert "native.degrade" in taxonomy.EVENTS
    assert "forensic.dump" in taxonomy.EVENTS
