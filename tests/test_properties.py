"""Property-based tests (hypothesis) for the pure layers.

The reference pins these behaviors with hand-picked cases; hypothesis
additionally sweeps the input space: flatten/inflate inversion over
arbitrary nested containers and hostile keys, serialization round-trips
across the whole dtype table, zigzag layout permutation validity, and an
end-to-end snapshot round-trip fuzz over generated app states.
"""

from __future__ import annotations

import string

import numpy as np
import pytest
from hypothesis import HealthCheck, given, settings, strategies as st

from torchsnapshot_tpu.flatten import flatten, inflate
from torchsnapshot_tpu.serialization import (
    SUPPORTED_DTYPE_STRINGS,
    array_as_memoryview,
    array_from_buffer,
    string_to_dtype,
)

pytestmark = [pytest.mark.hypothesis_fuzz]

# Keys exercise the escaping path: slashes, percents, spaces, unicode.
_KEY_ALPHABET = string.ascii_letters + string.digits + "/%._- é"
_keys = st.text(alphabet=_KEY_ALPHABET, min_size=1, max_size=12)
_leaves = st.one_of(
    st.integers(min_value=-(2**31), max_value=2**31),
    st.floats(allow_nan=False, allow_infinity=False, width=32),
    st.booleans(),
    st.text(max_size=8),
    st.binary(max_size=8),
)


def _containers(children):
    return st.one_of(
        st.dictionaries(_keys, children, max_size=4),
        st.lists(children, max_size=4),
        st.tuples(children, children),
    )


_nested = st.recursive(_leaves, _containers, max_leaves=12)


@given(obj=st.dictionaries(_keys, _nested, min_size=1, max_size=4))
@settings(max_examples=60, deadline=None)
def test_flatten_inflate_roundtrip(obj) -> None:
    manifest, flattened = flatten(obj, prefix="app")
    # every logical path is rank-prefix-safe: exactly the escaped key joins
    for path in flattened:
        assert path.startswith("app/")
    restored = inflate(manifest, flattened, prefix="app")
    assert restored == obj


@given(
    dtype_str=st.sampled_from(sorted(SUPPORTED_DTYPE_STRINGS)),
    shape=st.lists(st.integers(min_value=0, max_value=5), max_size=3),
    seed=st.integers(min_value=0, max_value=2**16),
)
@settings(max_examples=80, deadline=None)
def test_serialization_roundtrip(dtype_str, shape, seed) -> None:
    """Random bit patterns survive serialize -> deserialize for every dtype
    in the table (bit-exact, incl. bf16/fp8/int4 and size-0 arrays)."""
    dtype = string_to_dtype(dtype_str)
    shape = tuple(shape)
    n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    raw = np.random.default_rng(seed).integers(0, 255, n, dtype=np.uint8)
    arr = raw.view(dtype).reshape(shape)
    buf = bytes(array_as_memoryview(arr))
    back = array_from_buffer(buf, dtype_str, shape)
    assert back.shape == shape
    assert back.dtype == dtype
    assert bytes(array_as_memoryview(back)) == buf == raw.tobytes()


@given(
    ring=st.integers(min_value=1, max_value=8),
    chunk=st.integers(min_value=1, max_value=8),
)
@settings(max_examples=40, deadline=None)
def test_zigzag_layout_is_permutation(ring, chunk) -> None:
    from torchsnapshot_tpu.ops.ring_attention import zigzag_layout_indices

    seq = 2 * ring * chunk
    idx = np.asarray(zigzag_layout_indices(seq, ring))
    assert sorted(idx.tolist()) == list(range(seq))
    # self-inverse composition: take(take(x, idx), argsort(idx)) == x
    inv = np.argsort(idx)
    assert (idx[inv] == np.arange(seq)).all()


_app_leaves = st.one_of(
    st.integers(min_value=-(2**31), max_value=2**31),
    st.floats(allow_nan=False, allow_infinity=False, width=32),
    st.text(max_size=6),
    st.sampled_from(["f32", "i64", "bf16"]).flatmap(
        lambda k: st.integers(min_value=0, max_value=2**16).map(
            lambda seed: _rand_array(k, seed)
        )
    ),
)


def _rand_array(kind: str, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    if kind == "f32":
        return rng.standard_normal((3, 5)).astype(np.float32)
    if kind == "i64":
        return rng.integers(-1000, 1000, size=(7,), dtype=np.int64)
    import ml_dtypes

    return rng.standard_normal((4, 4)).astype(ml_dtypes.bfloat16)


def _zeroed_copy(obj):
    """Same structure, arrays zeroed, scalars reset — a restore target."""
    if isinstance(obj, np.ndarray):
        return np.zeros_like(obj)
    if isinstance(obj, dict):
        return {k: _zeroed_copy(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_zeroed_copy(v) for v in obj)
    if isinstance(obj, list):
        return [_zeroed_copy(v) for v in obj]
    return type(obj)()  # int/float/str/bytes/bool zero value


@given(
    state=st.dictionaries(
        _keys, st.recursive(_app_leaves, _containers, max_leaves=6),
        min_size=1, max_size=3,
    )
)
@settings(
    max_examples=15,
    deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)
def test_snapshot_roundtrip_fuzz(state, tmp_path_factory) -> None:
    """End-to-end: any generated app state must round-trip bit-exactly
    through take -> restore into a structurally equal zeroed target."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.test_utils import tree_eq

    tmp = tmp_path_factory.mktemp("fuzz")
    Snapshot.take(str(tmp / "s"), {"m": StateDict(s=state)})
    dst = StateDict(s=_zeroed_copy(state))
    Snapshot(str(tmp / "s")).restore({"m": dst})
    ok, msg = tree_eq(dst["s"], state)
    assert ok, msg


# ---------------------------------------------------------------- incremental

_inc_array_names = ["a", "b", "c", "d", "e"]


@given(
    mutations=st.lists(
        st.sets(st.sampled_from(_inc_array_names)), min_size=1, max_size=4
    ),
    data=st.data(),
)
@settings(
    max_examples=20,
    deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)
def test_incremental_chain_random_mutations(tmp_path_factory, mutations, data):
    """Fuzz an incremental chain: each link mutates a random subset of
    arrays. Every link must (a) physically store exactly the mutated
    payloads, (b) reference everything else in an ancestor, and (c)
    restore bit-exactly to its oracle state."""
    import os

    from torchsnapshot_tpu import Snapshot, StateDict

    root = tmp_path_factory.mktemp("inc_chain")
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))

    state = {
        name: rng.standard_normal((16, 4)).astype(np.float32)
        for name in _inc_array_names
    }
    oracles = []
    paths = []

    prev = None
    for i, mutated in enumerate([set(_inc_array_names)] + list(mutations)):
        for name in mutated:
            state[name] = state[name] + rng.standard_normal((16, 4)).astype(
                np.float32
            )
        path = str(root / f"link_{i}")
        Snapshot.take(
            path,
            {"app": StateDict(**{k: v.copy() for k, v in state.items()})},
            incremental_base=prev,
            record_digests=True,
        )
        oracles.append({k: v.copy() for k, v in state.items()})
        paths.append(path)
        prev = path

        written = {
            f
            for r, _, fs in os.walk(path)
            for f in fs
            if f != ".snapshot_metadata"
        }
        for name in _inc_array_names:
            has_file = any(f.startswith(f"{name}_") for f in written)
            assert has_file == (name in mutated or i == 0), (
                i, name, mutated, written,
            )

    for path, oracle in zip(paths, oracles):
        dst = StateDict(
            **{k: np.zeros((16, 4), np.float32) for k in _inc_array_names}
        )
        Snapshot(path).restore({"app": dst})
        for name in _inc_array_names:
            np.testing.assert_array_equal(dst[name], oracle[name])


@given(
    codec=st.sampled_from(["zstd:1", "zstd:3", "zlib:1", "zlib:6"]),
    dtype_str=st.sampled_from(sorted(SUPPORTED_DTYPE_STRINGS)),
    n=st.integers(min_value=0, max_value=9000),
    seed=st.integers(min_value=0, max_value=2**16),
    compressible=st.booleans(),
)
@settings(max_examples=80, deadline=None)
def test_compression_codec_roundtrip_fuzz(
    codec, dtype_str, n, seed, compressible
) -> None:
    """compress -> decompress is bit-exact for arbitrary payloads of every
    supported dtype, both entropy regimes, both codecs, incl. size 0 —
    and the expected_size cross-check accepts exactly the true size."""
    from torchsnapshot_tpu.compression import compress, decompress

    dtype = string_to_dtype(dtype_str)
    nbytes = n * dtype.itemsize
    rng = np.random.default_rng(seed)
    if compressible:
        raw = np.zeros(nbytes, np.uint8)
        if nbytes:
            raw[:: max(1, nbytes // 17)] = rng.integers(0, 255)
    else:
        raw = rng.integers(0, 255, nbytes, dtype=np.uint8)
    payload = raw.tobytes()
    packed = compress(codec, payload)
    back = bytes(decompress(codec, packed, expected_size=nbytes))
    assert back == payload


# --------------------------------------------------------------------------
# Manifest fast-path fuzz: the hand-rolled entry<->dict converters
# (round 4: _entry_to_dict / _array_entry_from_dict, added for 70B-scale
# emit/parse speed) must agree with the dataclass ground truth for every
# combination of optional fields.

_opt_str = st.none() | st.text(alphabet=_KEY_ALPHABET, min_size=1, max_size=16)


@st.composite
def _array_entries(draw):
    from torchsnapshot_tpu.manifest import ArrayEntry

    byte_range = draw(
        st.none()
        | st.tuples(
            st.integers(0, 1 << 40), st.integers(0, 1 << 30)
        ).map(lambda t: [t[0], t[0] + t[1]])
    )
    return ArrayEntry(
        location=draw(st.text(alphabet=_KEY_ALPHABET, min_size=1, max_size=24)),
        serializer="buffer_protocol",
        dtype=draw(st.sampled_from(sorted(SUPPORTED_DTYPE_STRINGS))),
        shape=draw(st.lists(st.integers(0, 1 << 20), max_size=4)),
        replicated=draw(st.booleans()),
        byte_range=byte_range,
        checksum=draw(_opt_str),
        digest=draw(_opt_str),
        origin=draw(_opt_str),
        codec=draw(st.none() | st.sampled_from(["zstd:3", "zlib:6"])),
    )


@st.composite
def _entries(draw):
    from torchsnapshot_tpu.manifest import (
        ChunkedArrayEntry,
        ObjectEntry,
        PrimitiveEntry,
        Shard,
        ShardedArrayEntry,
    )

    kind = draw(st.sampled_from(["array", "sharded", "chunked", "object", "prim"]))
    if kind == "array":
        return draw(_array_entries())
    if kind in ("sharded", "chunked"):
        shards = [
            Shard(
                offsets=draw(st.lists(st.integers(0, 1 << 20), min_size=2, max_size=2)),
                sizes=draw(st.lists(st.integers(0, 1 << 20), min_size=2, max_size=2)),
                array=draw(_array_entries()),
            )
            for _ in range(draw(st.integers(1, 3)))
        ]
        if kind == "sharded":
            return ShardedArrayEntry(dtype="bfloat16", shape=[8, 8], shards=shards)
        return ChunkedArrayEntry(
            dtype="bfloat16", shape=[8, 8], chunks=shards,
            replicated=draw(st.booleans()),
        )
    if kind == "object":
        return ObjectEntry(
            location=draw(st.text(alphabet=_KEY_ALPHABET, min_size=1, max_size=24)),
            serializer="pickle",
            obj_type="dict",
            replicated=draw(st.booleans()),
            checksum=draw(_opt_str),
            size=draw(st.none() | st.integers(0, 1 << 40)),
            digest=draw(_opt_str),
            origin=draw(_opt_str),
            codec=draw(st.none() | st.sampled_from(["zstd:3"])),
        )
    return PrimitiveEntry(
        ptype="str",
        readable=draw(st.text(alphabet=_KEY_ALPHABET, max_size=16)),
        replicated=draw(st.booleans()),
    )


@given(
    entries=st.dictionaries(
        st.text(alphabet=_KEY_ALPHABET, min_size=1, max_size=20),
        _entries(),
        min_size=1,
        max_size=6,
    ),
    mirror=st.none() | st.just("fs:///mirror"),
)
@settings(max_examples=60, deadline=None)
def test_manifest_fast_paths_match_dataclass_truth(entries, mirror) -> None:
    from dataclasses import asdict

    from torchsnapshot_tpu.manifest import SnapshotMetadata

    md = SnapshotMetadata(
        version="fuzz", world_size=4, manifest=entries, mirror_url=mirror
    )
    text = md.to_yaml()
    back = SnapshotMetadata.from_yaml(text)
    # Semantic equality via the dataclass ground truth.
    assert asdict(back) == asdict(md)
    # Emission is deterministic and round-trip stable.
    assert back.to_yaml() == text


@given(
    world=st.integers(2, 8),
    ops=st.lists(
        st.tuples(
            st.sampled_from(["bcast", "gather", "scatter", "barrier"]),
            st.integers(0, 40_000),  # payload size: straddles the 16 KB
            st.integers(0, 2**16),   # compression threshold
        ),
        min_size=1,
        max_size=6,
    ),
)
@settings(max_examples=25, deadline=None)
def test_collective_sequences_fuzz(world, ops) -> None:
    """Arbitrary op sequences over thread-ranks against a real store
    server: every rank sees identical, correct results regardless of
    payload size (raw vs compressed wire format) and op interleaving."""
    import threading

    from torchsnapshot_tpu.dist_store import TCPStore
    from torchsnapshot_tpu.pg_wrapper import PGWrapper, ProcessGroup

    server = TCPStore("127.0.0.1", None, is_server=True)
    errors = []

    def payload(size, seed, rank):
        # Deterministic, rank-tagged, compressible-ish payload.
        return {"rank": rank, "blob": (str(seed) * 50)[: size // 8], "n": size}

    def runner(rank):
        store = server.clone() if rank else server
        pg = ProcessGroup(store, rank, world)
        w = PGWrapper(pg, namespace="fuzz/collectives")
        try:
            for i, (op, size, seed) in enumerate(ops):
                if op == "bcast":
                    got = w.broadcast_object(
                        payload(size, seed, 0) if rank == 0 else None
                    )
                    assert got == payload(size, seed, 0), (i, op)
                elif op == "gather":
                    got = w.all_gather_object(payload(size, seed, rank))
                    assert got == [payload(size, seed, r) for r in range(world)]
                elif op == "scatter":
                    objs = (
                        [payload(size, seed, r) for r in range(world)]
                        if rank == 0
                        else None
                    )
                    got = w.scatter_object(objs)
                    assert got == payload(size, seed, rank), (i, op)
                else:
                    w.barrier()
        except BaseException as e:  # noqa: B036
            errors.append((rank, e))
        finally:
            if rank:
                store.close()

    threads = [
        threading.Thread(target=runner, args=(r,), daemon=True)
        for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    server.close()
    assert not errors, errors[0]


# --------------------------------------------------- device fingerprints


@settings(max_examples=40, deadline=None)
@given(
    dtype_str=st.sampled_from(
        ["float32", "bfloat16", "float16", "int32", "int8", "uint8", "bool"]
    ),
    shape=st.lists(st.integers(0, 9), min_size=0, max_size=3).map(tuple),
    seed=st.integers(0, 2**31 - 1),
    data=st.data(),
)
def test_device_fingerprint_properties(dtype_str, shape, seed, data) -> None:
    """Content-determined, copy-invariant, and bit-flip sensitive across
    the dtype table (device_digest.py's trust model reduced to testable
    properties)."""
    import jax.numpy as jnp

    from torchsnapshot_tpu.device_digest import PREFIX, device_fingerprint

    rng = np.random.default_rng(seed)
    np_dtype = string_to_dtype(dtype_str)
    if dtype_str == "bool":
        host = rng.integers(0, 2, size=shape).astype(np_dtype)
    elif np.issubdtype(np_dtype, np.integer):
        info = np.iinfo(np_dtype)
        host = rng.integers(info.min, info.max, size=shape, endpoint=True).astype(
            np_dtype
        )
    else:
        host = rng.standard_normal(size=shape).astype(np_dtype)

    x = jnp.asarray(host)
    fp = device_fingerprint(x)
    assert fp is not None and fp.startswith(PREFIX + ":")
    # Copy invariance: a distinct buffer with equal content hashes equal.
    assert device_fingerprint(jnp.asarray(host.copy())) == fp

    if host.size == 0 or dtype_str == "bool":
        return
    # Single-bit sensitivity at a random element: flip the lowest bit of
    # the element's raw representation (always changes the byte stream).
    flat = host.reshape(-1).copy()
    idx = data.draw(st.integers(0, flat.size - 1))
    raw = flat.view(f"u{flat.dtype.itemsize}")
    raw[idx] ^= 1
    mutated = raw.view(np_dtype).reshape(shape)
    assert device_fingerprint(jnp.asarray(mutated)) != fp


@settings(max_examples=40, deadline=None)
@given(
    sizes=st.lists(st.integers(min_value=1, max_value=512), min_size=1, max_size=12),
    window=st.integers(min_value=1, max_value=6),
    window_bytes=st.integers(min_value=1, max_value=4096),
    bad_at=st.one_of(st.none(), st.integers(min_value=0, max_value=11)),
)
def test_fingerprints_match_equals_naive_oracle(
    sizes, window, window_bytes, bad_at
) -> None:
    """Windowed/byte-budgeted verification must return exactly what the
    naive compare-every-fingerprint oracle returns, for any window
    geometry, slice-size mix (incl. slices far over the byte budget),
    and mismatch position — and every thunk runs at most once."""
    import jax.numpy as jnp

    from torchsnapshot_tpu.device_digest import (
        device_fingerprint,
        fingerprints_match,
    )

    arrs = [
        jnp.arange(n, dtype=jnp.float32) + 3.0 * i
        for i, n in enumerate(sizes)
    ]
    fps = [device_fingerprint(a) for a in arrs]
    expected = list(fps)
    if bad_at is not None and bad_at < len(expected):
        expected[bad_at] = "xxh4x32:" + "0" * 32
    oracle = all(f == e for f, e in zip(fps, expected))

    calls = []
    items = [
        (a.nbytes, lambda i=i, a=a: (calls.append(i), a)[1], e)
        for i, (a, e) in enumerate(zip(arrs, expected))
    ]
    got = fingerprints_match(items, window=window, window_bytes=window_bytes)
    assert got == oracle
    assert len(calls) == len(set(calls)), "a slice thunk ran twice"
    if got:
        assert calls == list(range(len(arrs)))  # everything verified
