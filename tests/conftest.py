"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports.

Mirrors the reference's strategy of testing distributed semantics without a
cluster (test_utils.py:166-205): sharding/resharding tests run on 8 virtual
CPU devices; multi-process semantics are tested with real subprocesses.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
