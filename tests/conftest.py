"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed semantics without a
cluster (test_utils.py:166-205): sharding/resharding tests run on 8 virtual
CPU devices; multi-process semantics are tested with real subprocesses.

NOTE: the ambient environment may have already imported jax (via
sitecustomize) with JAX_PLATFORMS pointed at real TPU hardware, so setting
the env var here is too late — use jax.config, which takes effect at first
backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses we spawn
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Telemetry is read ONCE at package import: pin it off before any test
# module imports torchsnapshot_tpu so an ambient TORCHSNAPSHOT_TPU_TELEMETRY=1
# can't scatter .snapshot_telemetry/.telemetry artifacts through tests
# that assert exact snapshot directory layouts. Telemetry tests opt back
# in with telemetry.set_enabled(True).
os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "0"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _default_write_batching_off(monkeypatch):
    """Most tests depend on the default per-payload file layout (payload
    names, deterministic dedup locations, corrupt-one-file helpers) —
    slab batching changes all of that by design. Pin it off suite-wide so
    an ambient TORCHSNAPSHOT_TPU_ENABLE_BATCHING=1 can't change test
    semantics; batching tests opt back in with monkeypatch.setenv (their
    in-test setenv runs after this autouse fixture)."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "0")


@pytest.fixture(autouse=True)
def _default_autotune_off(monkeypatch):
    """Integration tests assert deterministic election outcomes (chunk
    layouts, binding verdicts, exact file counts) — a live perturbation
    trial changes those by design, and the process-global governor would
    carry learned profiles ACROSS tests. Pin the tuner off suite-wide;
    autotune tests opt back in with monkeypatch.delenv/setenv (their
    in-test patch runs after this autouse fixture)."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_AUTOTUNE", "never")
