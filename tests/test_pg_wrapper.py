"""Object-collective tests over real subprocesses
(reference pattern: tests/test_ddp.py:56-59 — N workers, store rendezvous)."""

import pytest

from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import run_with_subprocesses


def _collectives_worker(rank: int, world_size: int):
    pg = PGWrapper()
    assert pg.get_rank() == rank
    assert pg.get_world_size() == world_size

    # broadcast
    value = pg.broadcast_object(f"from-rank-{rank}", src=0)
    assert value == "from-rank-0"

    # all_gather
    gathered = pg.all_gather_object({"rank": rank, "data": [rank] * 3})
    assert [g["rank"] for g in gathered] == list(range(world_size))

    # scatter
    objs = [f"item-{r}" for r in range(world_size)] if rank == 1 else None
    mine = pg.scatter_object(objs, src=1)
    assert mine == f"item-{rank}"

    # barrier + second wrapper (namespace isolation)
    pg.barrier()
    pg2 = PGWrapper()
    gathered2 = pg2.all_gather_object(rank * 10)
    assert gathered2 == [r * 10 for r in range(world_size)]
    return "ok"


@pytest.mark.parametrize("world_size", [2, 4])
def test_collectives(world_size: int) -> None:
    results = run_with_subprocesses(_collectives_worker, world_size)
    assert all(v == "ok" for v in results.values())


def test_single_process_trivial_collectives() -> None:
    # No default pg initialized in this process -> single-process semantics.
    w = PGWrapper(pg=None)
    assert w.get_rank() == 0
    assert w.get_world_size() == 1
    assert w.all_gather_object("x") == ["x"]
    assert w.broadcast_object("y") == "y"
    assert w.scatter_object(["z"]) == "z"
    w.barrier()  # no-op
