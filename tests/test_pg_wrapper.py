"""Object-collective tests over real subprocesses
(reference pattern: tests/test_ddp.py:56-59 — N workers, store rendezvous)."""

import pytest

from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]


def _collectives_worker(rank: int, world_size: int):
    pg = PGWrapper()
    assert pg.get_rank() == rank
    assert pg.get_world_size() == world_size

    # broadcast
    value = pg.broadcast_object(f"from-rank-{rank}", src=0)
    assert value == "from-rank-0"

    # all_gather
    gathered = pg.all_gather_object({"rank": rank, "data": [rank] * 3})
    assert [g["rank"] for g in gathered] == list(range(world_size))

    # scatter
    objs = [f"item-{r}" for r in range(world_size)] if rank == 1 else None
    mine = pg.scatter_object(objs, src=1)
    assert mine == f"item-{rank}"

    # barrier + second wrapper (namespace isolation)
    pg.barrier()
    pg2 = PGWrapper()
    gathered2 = pg2.all_gather_object(rank * 10)
    assert gathered2 == [r * 10 for r in range(world_size)]
    return "ok"


@pytest.mark.parametrize("world_size", [2, 4])
def test_collectives(world_size: int) -> None:
    results = run_with_subprocesses(_collectives_worker, world_size)
    assert all(v == "ok" for v in results.values())


def _extra_wrapper_worker(rank: int, world_size: int):
    # One rank constructs extra wrappers (e.g. on an exception path) that
    # never perform collectives. The lazy namespace handshake means they
    # consume nothing, so peers stay in sync.
    pg = PGWrapper()
    if rank == 1:
        _unused_a = PGWrapper()  # noqa: F841
        _unused_b = PGWrapper()  # noqa: F841
    assert pg.broadcast_object(rank, src=0) == 0
    pg2 = PGWrapper()
    assert pg2.all_gather_object(rank) == list(range(world_size))
    pg.barrier()
    return "ok"


def test_extra_wrapper_does_not_desync() -> None:
    results = run_with_subprocesses(_extra_wrapper_worker, 2)
    assert all(v == "ok" for v in results.values())


def _error_channel_worker(rank: int, world_size: int):
    pg = PGWrapper()
    pg.barrier()  # establish the namespace on every rank
    if rank == 0:
        pg.report_error(ValueError("boom"))
        return "reported"
    try:
        # Rank 0 never broadcasts; without the error channel this would
        # block for the full store timeout.
        pg.broadcast_object(None, src=0)
    except RuntimeError as e:
        assert isinstance(e.__cause__, ValueError)
        return "raised"
    raise AssertionError("collective did not observe the peer error")


def test_error_channel_unblocks_collectives() -> None:
    results = run_with_subprocesses(_error_channel_worker, 2)
    assert results[0] == "reported"
    assert results[1] == "raised"


def _store_hygiene_worker(rank: int, world_size: int, n_ops: int):
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    store = get_default_pg().store
    key_counts = []
    for _ in range(n_ops):
        pg = PGWrapper()
        pg.broadcast_object({"plan": list(range(8))}, src=0)
        pg.all_gather_object({"rank": rank})
        pg.barrier()
        pg.retire()
        key_counts.append(store.num_keys())
    # Retired namespaces are GCed at later handshakes: the store must not
    # grow linearly with the number of operations.
    assert key_counts[-1] < 40, f"store grew unbounded: {key_counts}"
    return key_counts[-1]


def test_store_keys_bounded_over_many_operations() -> None:
    results = run_with_subprocesses(_store_hygiene_worker, 2, 50)
    assert all(v < 40 for v in results.values())


def test_single_process_trivial_collectives() -> None:
    # No default pg initialized in this process -> single-process semantics.
    w = PGWrapper(pg=None)
    assert w.get_rank() == 0
    assert w.get_world_size() == 1
    assert w.all_gather_object("x") == ["x"]
    assert w.broadcast_object("y") == "y"
    assert w.scatter_object(["z"]) == "z"
    w.barrier()  # no-op


def _large_payload_worker(rank: int, world_size: int):
    # A manifest-sized, highly-compressible payload: exercises the
    # compressed (\x01) wire format through every collective.
    payload = {"rank": rank, "entries": [f"layer/{i}/weight" for i in range(20000)]}
    pg = PGWrapper()
    got = pg.broadcast_object(payload if rank == 0 else None, src=0)
    assert got["rank"] == 0 and len(got["entries"]) == 20000
    gathered = pg.all_gather_object(payload)
    assert [g["rank"] for g in gathered] == list(range(world_size))
    assert all(len(g["entries"]) == 20000 for g in gathered)
    return "ok"


def test_large_payload_collectives_compress() -> None:
    from torchsnapshot_tpu.pg_wrapper import _dumps, _loads

    big = {"entries": [f"layer/{i}/weight" for i in range(20000)]}
    wire = _dumps(big)
    assert wire[:1] == b"\x01"  # compressed marker
    assert _loads(wire) == big
    import pickle

    assert len(wire) < len(pickle.dumps(big)) // 3
    results = run_with_subprocesses(_large_payload_worker, 2)
    assert all(v == "ok" for v in results.values())


def _gather_error_worker(rank: int, world_size: int):
    pg = PGWrapper()
    pg.barrier()  # establish the namespace on every rank
    if rank == 0:
        pg.report_error(ValueError("gather-boom"))
        return "reported"
    try:
        # Rank 0 never contributes; the collect-based gather must observe
        # the error channel instead of blocking for the store timeout.
        pg.all_gather_object(rank)
    except RuntimeError as e:
        assert isinstance(e.__cause__, ValueError)
        return "raised"
    raise AssertionError("all_gather did not observe the peer error")


def test_error_channel_unblocks_all_gather() -> None:
    results = run_with_subprocesses(_gather_error_worker, 2)
    assert results[0] == "reported"
    assert results[1] == "raised"


def _peer_death_worker(rank, world, store_addr, q):
    import os
    import time

    from torchsnapshot_tpu.dist_store import create_store
    from torchsnapshot_tpu.pg_wrapper import PGWrapper, init_process_group

    store = create_store(rank=rank, addr=store_addr)
    init_process_group(store=store, rank=rank, world_size=world)
    pg = PGWrapper()
    pg.barrier()  # everyone alive and registered
    if rank == 2:
        os._exit(1)  # dies WITHOUT deregistering — a real crash
    t0 = time.monotonic()
    try:
        pg.all_gather_object(rank)  # rank 2 never contributes
        q.put((rank, "no-error", None))
    except RuntimeError as e:
        assert "died" in str(e), e
        q.put((rank, "death-detected", time.monotonic() - t0))
    # Exit handshake: rank 0 hosts the store server — it must outlive
    # rank 1's observation of the death key, or rank 1 sees a closed
    # connection instead of the death error.
    store.set(f"exit/{rank}", b"1")
    if rank == 0:
        store.get("exit/1", timeout=60.0)


def test_peer_death_unblocks_collectives_fast() -> None:
    """A rank dying mid-collective must surface to peers in seconds (the
    server publishes the death channel when its connection drops), not
    after the 1800 s store timeout."""
    import multiprocessing as mp

    from torchsnapshot_tpu.test_utils import _find_free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    addr = f"127.0.0.1:{_find_free_port()}"
    procs = [
        ctx.Process(target=_peer_death_worker, args=(r, 3, addr, q), daemon=True)
        for r in range(3)
    ]
    for p in procs:
        p.start()
    results = {}
    for _ in range(2):  # rank 2 never reports
        rank, status, elapsed = q.get(timeout=120)
        results[rank] = (status, elapsed)
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    assert set(results) == {0, 1}, results
    for rank, (status, elapsed) in results.items():
        assert status == "death-detected", results
        assert elapsed < 30, f"rank {rank} took {elapsed:.1f}s to observe the death"


def _take_death_worker(rank, world, store_addr, snap_path, q):
    import os
    import time

    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.dist_store import create_store
    from torchsnapshot_tpu.pg_wrapper import init_process_group

    store = create_store(rank=rank, addr=store_addr)
    init_process_group(store=store, rank=rank, world_size=world)

    class DieOnRank2(StateDict):
        def state_dict(self):
            if rank == 2:
                os._exit(1)  # crash INSIDE take's materialization phase
            return super().state_dict()

    app = {"m": DieOnRank2(w=np.ones(1024, np.float32), r=rank)}
    t0 = time.monotonic()
    try:
        Snapshot.take(snap_path, app)
        q.put((rank, "no-error", None))
    except RuntimeError as e:
        q.put((rank, "death-detected", time.monotonic() - t0))
    # Exit handshake (rank 0 hosts the store; see _peer_death_worker).
    store.set(f"exit/{rank}", b"1")
    if rank == 0:
        store.get("exit/1", timeout=60.0)


def test_rank_crash_inside_take_unblocks_peers(tmp_path) -> None:
    """A rank crashing inside Snapshot.take (mid-materialization) must
    abort the take on every surviving rank within seconds — and commit
    nothing."""
    import multiprocessing as mp
    import os

    from torchsnapshot_tpu.test_utils import _find_free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    addr = f"127.0.0.1:{_find_free_port()}"
    snap_path = str(tmp_path / "snap")
    procs = [
        ctx.Process(
            target=_take_death_worker, args=(r, 3, addr, snap_path, q), daemon=True
        )
        for r in range(3)
    ]
    for p in procs:
        p.start()
    results = {}
    for _ in range(2):  # rank 2 never reports
        rank, status, elapsed = q.get(timeout=180)
        results[rank] = (status, elapsed)
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    assert set(results) == {0, 1}, results
    for rank, (status, elapsed) in results.items():
        assert status == "death-detected", results
        assert elapsed < 60, f"rank {rank} took {elapsed:.1f}s"
    # No commit anywhere.
    assert not os.path.exists(os.path.join(snap_path, ".snapshot_metadata"))
