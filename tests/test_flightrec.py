"""Flight recorder (telemetry/flightrec.py): ring semantics, abort
dumps, the cross-rank merge, and the event-taxonomy lint.

The headline drill is the PR 5 commit-barrier desertion schedule at
world size 2: one rank's drain-phase fault deserts its peer at the
commit barrier; both ranks must leave ``.flight/rank_<r>.jsonl`` dumps,
and the merged blackbox timeline must name the failing rank, the
desertion, and the commit generation — the "who deserted whom" question
answered from the wreck alone.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, faultinject
from torchsnapshot_tpu.cli import run_fsck
from torchsnapshot_tpu.telemetry import flightrec
from torchsnapshot_tpu.telemetry.taxonomy import FLIGHT_EVENTS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TAXONOMY_SCRIPT = os.path.join(REPO, "scripts", "check_event_taxonomy.py")


@pytest.fixture(autouse=True)
def _fresh_ring():
    flightrec.set_enabled(True)
    flightrec.reset()
    yield
    flightrec.set_enabled(True)
    flightrec.reset()


# ------------------------------------------------------------------ ring


def test_ring_is_bounded_and_ordered(monkeypatch):
    monkeypatch.setenv(flightrec.RING_ENV_VAR, "32")
    flightrec.refresh_from_env()
    try:
        for i in range(100):
            flightrec.record("progress", op="take", done=i)
        ring = flightrec.snapshot_ring()
        assert len(ring) == 32
        seqs = [r[0] for r in ring]
        assert seqs == sorted(seqs)
        assert seqs[-1] == 100  # newest survives; oldest dropped
        assert flightrec.recorded_total() == 100
    finally:
        monkeypatch.delenv(flightrec.RING_ENV_VAR)
        flightrec.refresh_from_env()


def test_disabled_records_nothing():
    flightrec.set_enabled(False)
    flightrec.record("phase", name="stage", op="take")
    assert flightrec.snapshot_ring() == []
    assert flightrec.dump(None, 0, "disabled") is None


def test_enabled_by_default_env_gate(monkeypatch):
    monkeypatch.setenv(flightrec.FLIGHTREC_ENV_VAR, "")
    assert flightrec.refresh_from_env() is True  # always-on default
    monkeypatch.setenv(flightrec.FLIGHTREC_ENV_VAR, "0")
    assert flightrec.refresh_from_env() is False
    monkeypatch.setenv(flightrec.FLIGHTREC_ENV_VAR, "1")
    assert flightrec.refresh_from_env() is True


# ----------------------------------------------------------------- dumps


def test_abort_dump_written_next_to_snapshot(tmp_path):
    """A faulted single-process take leaves a parseable dump with the
    op lifecycle, the fault trip, and the abort — and the dump residue
    never confuses fsck's orphan scan on a committed snapshot."""
    state = {"model": StateDict(w=np.arange(50_000, dtype=np.float32))}
    cur = str(tmp_path / "cur")
    faultinject.configure("fs.write@1=permanent")
    try:
        with pytest.raises(OSError):
            Snapshot.take(cur, state)
    finally:
        faultinject.disable()
    dump_file = os.path.join(cur, ".flight", "rank_0.jsonl")
    assert os.path.isfile(dump_file)
    events = [json.loads(line) for line in open(dump_file)]
    names = [e["ev"] for e in events]
    assert names[0] == "flight.dump"
    assert "op.begin" in names
    assert "fault.trip" in names
    assert "op.abort" in names
    assert all(e["ev"] in FLIGHT_EVENTS for e in events)
    # A later successful take into a fresh dir with a restore-abort dump
    # inside stays fsck-clean (.flight is internal residue, not orphans).
    good = str(tmp_path / "good")
    Snapshot.take(good, state)
    flightrec.dump(good, 0, "manual")
    code, report = run_fsck(good, echo=lambda *a, **k: None)
    assert code == 0, report.findings


def test_dump_skips_remote_paths_without_spool(monkeypatch):
    monkeypatch.delenv(flightrec.DUMP_DIR_ENV_VAR, raising=False)
    flightrec.record("phase", name="x", op="take")
    assert flightrec.dump("s3://bucket/snap", 0, "abort") is None


def test_dump_spool_dir_env(tmp_path, monkeypatch):
    monkeypatch.setenv(flightrec.DUMP_DIR_ENV_VAR, str(tmp_path))
    flightrec.record("phase", name="x", op="take")
    out = flightrec.dump("s3://bucket/snap", 3, "abort")
    assert out == str(tmp_path / ".flight" / "rank_3.jsonl")
    assert os.path.isfile(out)


# ------------------------------------------------------- merge machinery


def _mk_dump(tmp_path, rank, records):
    d = tmp_path / ".flight"
    d.mkdir(exist_ok=True)
    with open(d / f"rank_{rank}.jsonl", "w") as f:
        f.write(json.dumps({"seq": 0, "t": 0.0, "ev": "flight.dump",
                            "rank": rank, "reason": "test"}) + "\n")
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_merge_aligns_clocks_on_shared_collective(tmp_path):
    """Rank clocks with wildly different epochs align on the shared
    (ns, cseq) anchor; the deserter is named from the causal keys."""
    ns = "pgw/ns/1-abc"
    _mk_dump(tmp_path, 0, [
        {"seq": 1, "t": 1000.0, "ev": "collective.enter", "kind": "barrier",
         "ns": ns, "cseq": 1},
        {"seq": 2, "t": 1000.1, "ev": "collective.exit", "kind": "barrier",
         "ns": ns, "cseq": 1, "ok": True},
        {"seq": 3, "t": 1005.0, "ev": "collective.enter", "kind": "barrier",
         "ns": ns, "cseq": 2},
        {"seq": 4, "t": 1012.0, "ev": "collective.exit", "kind": "barrier",
         "ns": ns, "cseq": 2, "ok": False, "error": "TimeoutError('8s')"},
        {"seq": 5, "t": 1012.1, "ev": "op.abort", "op": "take",
         "error": "RuntimeError('peer died')"},
    ])
    _mk_dump(tmp_path, 1, [
        {"seq": 1, "t": 50.0, "ev": "collective.enter", "kind": "barrier",
         "ns": ns, "cseq": 1},
        {"seq": 2, "t": 50.1, "ev": "collective.exit", "kind": "barrier",
         "ns": ns, "cseq": 1, "ok": True},
        # rank 1 never reaches barrier #2: it is the deserter
        {"seq": 3, "t": 50.2, "ev": "op.abort", "op": "take",
         "error": "InjectedTransientError('boom')"},
    ])
    merged = flightrec.merge_timeline(flightrec.load_dumps(str(tmp_path)))
    assert merged["aligned"] is True
    desertions = [f for f in merged["findings"] if f["class"] in
                  ("desertion", "collective-error")]
    assert desertions, merged["findings"]
    d = desertions[0]
    assert d["cseq"] == 2
    assert d["never_arrived"] == [1]
    assert d["errored"] == [0]
    text = flightrec.render_timeline(merged)
    assert "DESERTION" in text
    assert "rank(s) 1 never arrived" in text
    assert "InjectedTransientError" in text


def test_merge_tolerates_torn_lines_and_single_rank(tmp_path):
    d = tmp_path / ".flight"
    d.mkdir()
    with open(d / "rank_0.jsonl", "w") as f:
        f.write(json.dumps({"seq": 1, "t": 1.0, "ev": "op.begin",
                            "op": "take"}) + "\n")
        f.write('{"seq": 2, "t": 1.5, "ev": "pha')  # torn mid-write
    merged = flightrec.merge_timeline(flightrec.load_dumps(str(tmp_path)))
    assert merged["ranks"] == [0]
    assert len(merged["events"]) == 1


# ------------------------------------------------- w2 desertion drill


def _desertion_worker(rank: int, world_size: int, root: str):
    from torchsnapshot_tpu import faultinject as fi
    from torchsnapshot_tpu.telemetry import flightrec as fr

    fr.set_enabled(True)
    fr.reset()
    rng = np.random.default_rng(1000 * rank)
    state = {"model": StateDict(w=rng.standard_normal(8_000).astype(np.float32))}
    if rank == 0:
        # The PR 5 drain-phase desertion schedule: the delay parks rank
        # 0's write past the manifest gather, the transient fires inside
        # its post-gather sync_complete — deserting rank 1 at the commit
        # barrier (bounded by the wrapper error channel).
        fi.configure("fs.write@2=delay:0.3;fs.write@2=transient")
    err = None
    try:
        Snapshot.take(os.path.join(root, "cur"), state)
    except BaseException as e:  # noqa: B036
        err = repr(e)
    finally:
        fi.disable()
    return {"err": err}


@pytest.mark.multiprocess
def test_w2_desertion_drill_dumps_and_blackbox_names_the_deserter(tmp_path):
    """The acceptance drill: the commit-barrier desertion schedule at w2
    ends with BOTH ranks' .flight dumps on disk, and the merged timeline
    names the failing rank, the desertion, and the commit generation."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _desertion_worker, 2, str(tmp_path), timeout=180.0
    )
    for rank, out in results.items():
        assert out["err"] is not None, rank
    cur = str(tmp_path / "cur")
    for rank in (0, 1):
        assert os.path.isfile(
            os.path.join(cur, ".flight", f"rank_{rank}.jsonl")
        ), f"rank {rank} left no flight dump"
    dumps = flightrec.load_dumps(cur)
    merged = flightrec.merge_timeline(dumps)
    text = flightrec.render_timeline(merged, verbose=True)
    # The failing rank (0, the injected one) is named in an abort finding
    # with the injected error class.
    aborts = [f for f in merged["findings"] if f["class"] == "abort"]
    assert any(
        f["rank"] == 0 and "InjectedTransientError" in str(f["error"])
        for f in aborts
    ), aborts
    # The desertion itself: a collective some ranks never finished.
    assert "DESERTION" in text or any(
        f["class"] in ("desertion", "collective-error")
        for f in merged["findings"]
    ), text
    # The commit generation is in the timeline (rank 0 planted the fence).
    assert "gen=" in text
    # The fault trip that caused it all is named with its site.
    assert "fs.write" in text


# ----------------------------------------------------------- preemption


def test_sigterm_records_event_and_optionally_dumps(tmp_path, monkeypatch):
    """The preemption watcher records ``preempt.signal`` from the handler
    (a single GIL-atomic append — handler-safe) and, with
    TORCHSNAPSHOT_TPU_FLIGHTREC_SIGTERM=1, spools the ring to the
    FLIGHTREC_DIR on the next normal-control-flow call."""
    from torchsnapshot_tpu.preemption import (
        PreemptionWatcher,
        simulate_preemption_now,
    )

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_FLIGHTREC_SIGTERM", "1")
    monkeypatch.setenv(flightrec.DUMP_DIR_ENV_VAR, str(tmp_path))
    watcher = PreemptionWatcher()
    try:
        simulate_preemption_now()
        assert watcher.preempted
        names = [r[2] for r in flightrec.snapshot_ring()]
        assert "preempt.signal" in names
        assert watcher.should_save() is True  # triggers the deferred dump
        dumped = tmp_path / ".flight" / "rank_0.jsonl"
        assert dumped.is_file()
        recs = [json.loads(line) for line in open(dumped)]
        assert recs[0]["reason"] == "sigterm"
        assert any(r["ev"] == "preempt.signal" for r in recs)
    finally:
        watcher.close()


# ------------------------------------------------------------- taxonomy


def test_taxonomy_lint_is_clean():
    r = subprocess.run(
        [sys.executable, TAXONOMY_SCRIPT], capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr


def test_taxonomy_lint_detects_unregistered_and_computed_names(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "check_event_taxonomy", TAXONOMY_SCRIPT
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    violations, uses, hist_uses = lint.check_source(
        "from .telemetry import flightrec\n"
        "flightrec.record('not.an.event', a=1)\n"
        "flightrec.record(name_var, a=1)\n"
        "flightrec.record('phase', name='x')\n",
        "bad.py",
    )
    whats = "\n".join(w for _, w in violations)
    assert "not registered" in whats
    assert "string literal" in whats
    assert uses == {"phase": [4]}
    assert hist_uses == {}


def test_taxonomy_lint_covers_histogram_instruments():
    """ISSUE 8 satellite: histogram instrument names are pinned the
    same way flight events are — literal-first, registered-only, and
    every registered family wired somewhere."""
    spec = importlib.util.spec_from_file_location(
        "check_event_taxonomy", TAXONOMY_SCRIPT
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    violations, _uses, hist_uses = lint.check_source(
        "from . import telemetry\n"
        "telemetry.histogram_observe('no.such_hist', 0.1)\n"
        "telemetry.histogram_observe(computed_name, 0.1)\n"
        "telemetry.histogram_observe('write.entry_s', 0.1, key='FS')\n",
        "bad.py",
    )
    whats = "\n".join(w for _, w in violations)
    assert "no.such_hist" in whats
    assert "string literal" in whats
    assert hist_uses == {"write.entry_s": [4]}
    # The registry floor is enforced.
    assert lint.MIN_HISTOGRAMS >= 5


def test_taxonomy_registry_matches_module():
    assert "collective.enter" in FLIGHT_EVENTS
    assert "store.failover" in FLIGHT_EVENTS
    assert "governor.elect" in FLIGHT_EVENTS
    assert len(FLIGHT_EVENTS) >= 15
    from torchsnapshot_tpu.telemetry.taxonomy import HISTOGRAMS

    assert "write.sub_chunk_s" in HISTOGRAMS
    assert "collective.wait_s" in HISTOGRAMS
    assert len(HISTOGRAMS) >= 5


def test_timing_lint_covers_flightrec():
    """Satellite: the ad-hoc-timing lint walks telemetry/flightrec.py
    (a clock consumer) even though the telemetry package owns the raw
    clock."""
    spec = importlib.util.spec_from_file_location(
        "check_timing_lint",
        os.path.join(REPO, "scripts", "check_timing_lint.py"),
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    assert "flightrec.py" in lint.TELEMETRY_COVERED
    # ISSUE 8 satellite: the new clock consumers are covered too.
    assert "critpath.py" in lint.TELEMETRY_COVERED
    assert "promexp.py" in lint.TELEMETRY_COVERED
