"""True multi-process jax.Array snapshot round-trip.

Everything else in the suite simulates multi-host with a virtual
8-device single-process mesh. This test runs the REAL path: two
processes under ``jax.distributed.initialize`` (CPU backend) share a
global 2-device mesh, each owning one NON-addressable-elsewhere shard.
Take must elect exactly one writer per shard across processes; restore
must fill each process's addressable shards, including into a different
sharding layout (resharding across the process boundary).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]

SHAPE = (4, 8)


def _init_jax_dist(rank: int, world_size: int, port: int):
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    # The pytest conftest forces 8 virtual devices per process; here each
    # process must own exactly ONE device so shards are genuinely
    # non-addressable across processes.
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    return jax


def _global_data() -> np.ndarray:
    return np.arange(32, dtype=np.float32).reshape(SHAPE)


def _make_global_array(jax, spec):
    import jax.numpy as jnp  # noqa: F401
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("x",))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        SHAPE, sharding, lambda idx: _global_data()[idx]
    )


def _take_restore_worker(rank: int, world_size: int, snap_path: str, port: int):
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict

    arr = _make_global_array(jax, P("x", None))  # row-sharded across procs
    assert len(arr.addressable_shards) == 1  # truly multi-host
    app = {"m": StateDict(emb=arr, step=rank)}
    Snapshot.take(snap_path, app)

    # Restore into a DIFFERENT layout: column-sharded across processes.
    dst = _make_global_array(jax, P(None, "x")) * 0
    out = StateDict(emb=dst, step=-1)
    Snapshot(snap_path).restore({"m": out})
    restored = out["emb"]
    assert out["step"] == rank
    # Each process checks its own addressable shard against the source.
    for shard in restored.addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )
    return [s.index for s in restored.addressable_shards]


def test_multiprocess_sharded_roundtrip(tmp_path) -> None:
    port = _find_free_port()
    results = run_with_subprocesses(
        _take_restore_worker, 2, str(tmp_path / "snap"), port, timeout=180.0
    )
    # Both processes restored, each owning a DISTINCT column shard.
    assert len(results) == 2
    assert len({str(v) for v in results.values()}) == 2

    # Exactly one writer per saved shard: two row shards on disk.
    shard_files = [
        f
        for dp, _, fs in os.walk(tmp_path / "snap")
        for f in fs
        if "m/emb" in os.path.join(dp, f)
    ]
    assert len(shard_files) == 2, shard_files


def _replicated_worker(rank: int, world_size: int, snap_path: str, port: int):
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict

    # Fully-replicated over a multi-process device set: auto-detected as
    # replicated (no glob needed), written once.
    arr = _make_global_array(jax, P(None, None))
    app = {"m": StateDict(w=arr)}
    snapshot = Snapshot.take(snap_path, app)
    entry = snapshot.get_manifest()[f"{rank}/m/w"]
    assert entry.replicated

    dst = _make_global_array(jax, P(None, None)) * 0
    out = StateDict(w=dst)
    Snapshot(snap_path).restore({"m": out})
    for shard in out["w"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )
    return "ok"


def test_multiprocess_auto_replication(tmp_path) -> None:
    port = _find_free_port()
    results = run_with_subprocesses(
        _replicated_worker, 2, str(tmp_path / "snap"), port, timeout=180.0
    )
    assert all(v == "ok" for v in results.values())
    # Replicated data written once, under replicated/.
    repl_files = [
        os.path.relpath(os.path.join(dp, f), tmp_path / "snap")
        for dp, _, fs in os.walk(tmp_path / "snap")
        for f in fs
        if f != ".snapshot_metadata"
    ]
    assert all(p.startswith("replicated/") for p in repl_files), repl_files


def _full_flow_worker(rank, world_size, base_path, inc_path, mirror_base,
                      mirror_inc, port):
    """The production flow end to end under REAL jax.distributed:
    sync take (digests + mirror) -> train step -> async_take incremental
    (+ mirror) -> restore the incremental into a different layout."""
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict

    frozen = _make_global_array(jax, P("x", None))  # row-sharded, unchanged
    head = np.full((4,), 1.0, np.float32)  # replicated host state, trains
    app = {"m": StateDict(frozen=frozen, head=head, step=0)}
    Snapshot.take(
        base_path, app, record_digests=True,
        replicated=["m/head"],
        storage_options={"mirror_url": mirror_base},
    )

    head2 = head + 1.0  # the training step: only the head moves
    app2 = {"m": StateDict(frozen=frozen, head=head2, step=1)}
    pending = Snapshot.async_take(
        inc_path, app2, incremental_base=base_path,
        replicated=["m/head"],
        storage_options={"mirror_url": mirror_inc},
    )
    pending.wait()

    # Restore the incremental into a DIFFERENT layout (col-sharded).
    dst = _make_global_array(jax, P(None, "x")) * 0
    out = StateDict(frozen=dst, head=np.zeros((4,), np.float32), step=-1)
    Snapshot(inc_path).restore({"m": out})
    assert out["step"] == 1
    np.testing.assert_array_equal(out["head"], head2)
    for shard in out["frozen"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )
    return "ok"


def test_multiprocess_4proc_async_incremental_mirror(tmp_path) -> None:
    """VERDICT r2 item 7: 4 real processes, async_take + incremental +
    mirrored storage together under jax.distributed."""
    port = _find_free_port()
    base, inc = str(tmp_path / "base"), str(tmp_path / "inc")
    mb, mi = str(tmp_path / "mirror_base"), str(tmp_path / "mirror_inc")
    results = run_with_subprocesses(
        _full_flow_worker, 4, base, inc, mb, mi, port, timeout=360.0
    )
    assert all(v == "ok" for v in results.values())

    # Dedup across processes: the unchanged sharded payloads must NOT be
    # rewritten in the incremental (4 shard files in base, none in inc).
    def shard_files(root):
        return [
            f
            for dp, _, fs in os.walk(root)
            for f in fs
            if "m/frozen" in os.path.join(dp, f)
        ]

    assert len(shard_files(base)) == 4
    assert len(shard_files(inc)) == 0, shard_files(inc)

    # Both mirror tiers are committed, complete snapshots; the inc's
    # mirror records the base's mirror for disaster recovery.
    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.dedup import canonical_base_url

    for tier in (mb, mi):
        assert os.path.isfile(os.path.join(tier, ".snapshot_metadata")), tier
    meta = Snapshot(mi).metadata
    assert meta.origin_mirrors
    assert meta.origin_mirrors.get(canonical_base_url(base)) == canonical_base_url(mb)


def _staging_failure_worker(rank, world_size, snap_path, port):
    """Rank 2's staging fails; EVERY rank must abort (the error rides the
    manifest gather) and no metadata may be committed."""
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict

    if rank == 2:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager

        def boom(self, arr):
            raise RuntimeError("injected staging failure on rank 2")

        ArrayBufferStager._stage_and_sum = boom

    arr = _make_global_array(jax, P("x", None))
    try:
        Snapshot.take(snap_path, {"m": StateDict(emb=arr)})
    except RuntimeError as e:
        msg = str(e)
        assert "injected staging failure" in msg or "peer rank" in msg, msg
        return "aborted"
    return "NOT-ABORTED"


def test_multiprocess_4proc_staging_failure_aborts_all_ranks(tmp_path) -> None:
    port = _find_free_port()
    snap = str(tmp_path / "snap")
    results = run_with_subprocesses(
        _staging_failure_worker, 4, snap, port, timeout=360.0
    )
    assert all(v == "aborted" for v in results.values()), results
    assert not os.path.exists(os.path.join(snap, ".snapshot_metadata"))


def _device_digest_worker(rank, world_size, base_path, inc_path, port):
    """Device digests across a REAL 2-process world: the take-side DtoH
    skip and the restore-side read skip both exercise the
    NON-fully-addressable code paths (per-shard containment in
    ShardedArrayIOPreparer._dst_already_matches)."""
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    arr = _make_global_array(jax, P("x", None))
    assert not arr.is_fully_addressable
    Snapshot.take(base_path, {"m": StateDict(emb=arr)}, device_digests=True)

    # Unchanged resave: nothing stages on either process.
    staged = []
    orig = ArrayBufferStager._stage_and_sum
    ArrayBufferStager._stage_and_sum = lambda self, a: staged.append(1) or orig(
        self, a
    )
    try:
        arr2 = _make_global_array(jax, P("x", None))  # fresh buffers
        Snapshot.take(
            inc_path,
            {"m": StateDict(emb=arr2)},
            incremental_base=base_path,
            device_digests=True,
        )
    finally:
        ArrayBufferStager._stage_and_sum = orig
    assert staged == [], f"rank {rank} staged {staged}"

    # Restore into a destination already holding the content: the
    # multi-process containment path verifies each locally-owned piece
    # and consumes nothing.
    consumed = []
    orig_c = _ShardScatterConsumer._consume_sync
    _ShardScatterConsumer._consume_sync = (
        lambda self, buf: consumed.append(1) or orig_c(self, buf)
    )
    try:
        dst = StateDict(emb=_make_global_array(jax, P("x", None)))
        Snapshot(base_path).restore({"m": dst}, device_digests=True)
    finally:
        _ShardScatterConsumer._consume_sync = orig_c
    assert consumed == [], f"rank {rank} consumed {consumed}"
    for shard in dst["emb"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )
    return rank


def test_multiprocess_device_digests(tmp_path) -> None:
    port = _find_free_port()
    results = run_with_subprocesses(
        _device_digest_worker,
        2,
        str(tmp_path / "base"),
        str(tmp_path / "inc"),
        port,
        timeout=180.0,
    )
    assert sorted(results.values()) == [0, 1]
