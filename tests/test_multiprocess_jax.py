"""True multi-process jax.Array snapshot round-trip.

Everything else in the suite simulates multi-host with a virtual
8-device single-process mesh. This test runs the REAL path: two
processes under ``jax.distributed.initialize`` (CPU backend) share a
global 2-device mesh, each owning one NON-addressable-elsewhere shard.
Take must elect exactly one writer per shard across processes; restore
must fill each process's addressable shards, including into a different
sharding layout (resharding across the process boundary).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

SHAPE = (4, 8)


def _init_jax_dist(rank: int, world_size: int, port: int):
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    # The pytest conftest forces 8 virtual devices per process; here each
    # process must own exactly ONE device so shards are genuinely
    # non-addressable across processes.
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    return jax


def _global_data() -> np.ndarray:
    return np.arange(32, dtype=np.float32).reshape(SHAPE)


def _make_global_array(jax, spec):
    import jax.numpy as jnp  # noqa: F401
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("x",))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        SHAPE, sharding, lambda idx: _global_data()[idx]
    )


def _take_restore_worker(rank: int, world_size: int, snap_path: str, port: int):
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict

    arr = _make_global_array(jax, P("x", None))  # row-sharded across procs
    assert len(arr.addressable_shards) == 1  # truly multi-host
    app = {"m": StateDict(emb=arr, step=rank)}
    Snapshot.take(snap_path, app)

    # Restore into a DIFFERENT layout: column-sharded across processes.
    dst = _make_global_array(jax, P(None, "x")) * 0
    out = StateDict(emb=dst, step=-1)
    Snapshot(snap_path).restore({"m": out})
    restored = out["emb"]
    assert out["step"] == rank
    # Each process checks its own addressable shard against the source.
    for shard in restored.addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )
    return [s.index for s in restored.addressable_shards]


def test_multiprocess_sharded_roundtrip(tmp_path) -> None:
    port = _find_free_port()
    results = run_with_subprocesses(
        _take_restore_worker, 2, str(tmp_path / "snap"), port, timeout=180.0
    )
    # Both processes restored, each owning a DISTINCT column shard.
    assert len(results) == 2
    assert len({str(v) for v in results.values()}) == 2

    # Exactly one writer per saved shard: two row shards on disk.
    shard_files = [
        f
        for dp, _, fs in os.walk(tmp_path / "snap")
        for f in fs
        if "m/emb" in os.path.join(dp, f)
    ]
    assert len(shard_files) == 2, shard_files


def _replicated_worker(rank: int, world_size: int, snap_path: str, port: int):
    from jax.sharding import PartitionSpec as P

    jax = _init_jax_dist(rank, world_size, port)
    from torchsnapshot_tpu import Snapshot, StateDict

    # Fully-replicated over a multi-process device set: auto-detected as
    # replicated (no glob needed), written once.
    arr = _make_global_array(jax, P(None, None))
    app = {"m": StateDict(w=arr)}
    snapshot = Snapshot.take(snap_path, app)
    entry = snapshot.get_manifest()[f"{rank}/m/w"]
    assert entry.replicated

    dst = _make_global_array(jax, P(None, None)) * 0
    out = StateDict(w=dst)
    Snapshot(snap_path).restore({"m": out})
    for shard in out["w"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _global_data()[shard.index]
        )
    return "ok"


def test_multiprocess_auto_replication(tmp_path) -> None:
    port = _find_free_port()
    results = run_with_subprocesses(
        _replicated_worker, 2, str(tmp_path / "snap"), port, timeout=180.0
    )
    assert all(v == "ok" for v in results.values())
    # Replicated data written once, under replicated/.
    repl_files = [
        os.path.relpath(os.path.join(dp, f), tmp_path / "snap")
        for dp, _, fs in os.walk(tmp_path / "snap")
        for f in fs
        if f != ".snapshot_metadata"
    ]
    assert all(p.startswith("replicated/") for p in repl_files), repl_files
