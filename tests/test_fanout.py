"""Cooperative restore fan-out: unit coverage (single process).

Four seams, mirrored from the design (fanout.py):

- **Partitioner extraction**: ``greedy_size_balanced`` must be
  bit-identical to the historical inline loop in
  ``_partition_write_units`` for the same input — the save side's
  striping is a compatibility contract (existing snapshots' chunk
  ownership), so the extraction may not move a single byte.
- **Unit keys**: only rank-identical locations (``replicated/``,
  ``sharded/``) form cooperative units; per-rank, slab, and zero-length
  requests never do; the origin (incremental chains) is part of the key.
- **Peer transport + session**: frames round-trip, owner→receiver
  forwarding delivers bit-exact payloads to the scheduler's consumers
  (two real sessions over loopback in one process), restarts discard
  pre-restart bytes wholesale, aborts/timeouts degrade the entry to a
  direct storage read.
- **The device-free lint**: scripts/check_peer_channel.py is clean on
  the real tree and actually catches a planted jax call.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from torchsnapshot_tpu.fanout import (
    CoopKeyPlan,
    CoopRestoreSession,
    PeerTransferError,
    coop_restore_mode,
    greedy_size_balanced,
    unit_key,
)
from torchsnapshot_tpu.dist_store import (
    PeerListener,
    peer_connect,
    recv_peer_frame,
    send_peer_frame,
)
from torchsnapshot_tpu.io_types import ReadReq, WriteIO
from torchsnapshot_tpu.manifest import ArrayEntry
from torchsnapshot_tpu.scheduler import execute_read_reqs
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

SUB = 64 << 10


# ------------------------------------------------------------- partitioner


def _historical_partition(pool_sizes, world_size):
    """The pre-extraction inline loop from _partition_write_units,
    verbatim — the compatibility oracle."""
    loads = [0] * world_size
    owners = []
    for nbytes in pool_sizes:
        target = min(range(world_size), key=lambda r: (loads[r], r))
        loads[target] += nbytes
        owners.append(target)
    return owners


@pytest.mark.parametrize("world_size", [1, 2, 3, 7])
def test_greedy_partition_bit_identical_to_save_side(world_size) -> None:
    rng = np.random.default_rng(world_size)
    for trial in range(20):
        n = int(rng.integers(0, 40))
        sizes = sorted(
            (int(s) for s in rng.integers(1, 1 << 20, size=n)), reverse=True
        )
        assert greedy_size_balanced(sizes, world_size) == _historical_partition(
            sizes, world_size
        )


def test_greedy_partition_respects_candidates() -> None:
    sizes = [100, 90, 80, 70]
    candidates = [[1, 2], [0], [2], [1, 2]]
    owners = greedy_size_balanced(sizes, 3, candidates)
    for owner, allowed in zip(owners, candidates):
        assert owner in allowed
    # Within the allowed sets, loads balance greedily and ties go low:
    # unit 3 (70) goes to rank 2 (load 80) over rank 1 (load 100).
    assert owners == [1, 0, 2, 2]


def test_greedy_partition_balances() -> None:
    sizes = sorted([5, 5, 5, 5, 20], reverse=True)
    owners = greedy_size_balanced(sizes, 2)
    loads = [0, 0]
    for s, o in zip(sizes, owners):
        loads[o] += s
    assert abs(loads[0] - loads[1]) <= 10


# --------------------------------------------------------------- unit keys


def _req(path, byte_range=None, origin=None, nbytes=1024):
    entry = ArrayEntry(
        location=path,
        serializer="buffer_protocol",
        dtype="uint8",
        shape=[nbytes],
        replicated=True,
    )
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    return ReadReq(
        path=path,
        buffer_consumer=ArrayBufferConsumer(entry, callback=lambda a: None),
        byte_range=byte_range,
        origin=origin,
    )


def test_unit_key_scopes_to_shared_locations() -> None:
    assert unit_key(_req("replicated/model/w")) is not None
    assert unit_key(_req("sharded/model/w", byte_range=(0, 10))) is not None
    assert unit_key(_req("0/model/w")) is None  # per-rank
    assert unit_key(_req("batched/abc123")) is None  # slab
    assert unit_key(_req("replicated/x", byte_range=(5, 5))) is None  # empty
    # The origin (incremental chains) distinguishes otherwise-equal keys.
    a = unit_key(_req("replicated/x"))
    b = unit_key(_req("replicated/x", origin="/base/snap"))
    assert a != b
    # Byte ranges distinguish too (post-reshard overlap reads).
    c = unit_key(_req("sharded/x", byte_range=(0, 10)))
    d = unit_key(_req("sharded/x", byte_range=(10, 20)))
    assert c != d


def test_coop_mode_parser(monkeypatch) -> None:
    for raw, want in [
        ("never", "never"),
        ("0", "never"),
        ("always", "always"),
        ("1", "always"),
        ("auto", "auto"),
        ("", "auto"),
        ("bogus", "auto"),
    ]:
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_COOP_RESTORE", raw)
        assert coop_restore_mode() == want


def test_governor_coop_gate() -> None:
    from torchsnapshot_tpu.scheduler import IOGovernor

    gov = IOGovernor()
    # No evidence: direct reads stay.
    assert not gov.should_coop_restore("FSStoragePlugin")
    gov.record_read("FSStoragePlugin", 1 << 30, 0.1)  # ~10 GB/s: memcpy-speed
    assert not gov.should_coop_restore("FSStoragePlugin")
    gov2 = IOGovernor()
    gov2.record_read("S3StoragePlugin", 1 << 26, 1.0)  # ~64 MB/s: throttled
    assert gov2.should_coop_restore("S3StoragePlugin")


# ---------------------------------------------------------- raw transport


def test_peer_frame_roundtrip() -> None:
    got = []
    done = asyncio.Event() if False else None  # noqa: F841

    import threading

    received = threading.Event()

    def handler(conn):
        try:
            while True:
                header, payload = recv_peer_frame(conn)
                got.append((header, bytes(payload) if payload is not None else None))
                if header.get("op") == "bye":
                    received.set()
                    return
        except (ConnectionError, OSError, EOFError):
            received.set()

    listener = PeerListener()
    listener.start(handler)
    try:
        sock = peer_connect(f"127.0.0.1:{listener.port}")
        payload = os.urandom(257_123)
        send_peer_frame(sock, {"op": "hello", "rank": 3})
        send_peer_frame(
            sock, {"op": "chunk", "key": "k", "gen": 1, "seq": 0}, payload
        )
        send_peer_frame(sock, {"op": "bye"})
        assert received.wait(10.0)
        sock.close()
    finally:
        listener.close()
    assert got[0] == ({"op": "hello", "rank": 3}, None)
    assert got[1][0]["op"] == "chunk" and got[1][1] == payload
    assert got[2][0]["op"] == "bye"


# ------------------------------------------------- session pair, one process


def _session_pair(loop0, loop1):
    l0, l1 = PeerListener(), PeerListener()
    addrs = [f"127.0.0.1:{l0.port}", f"127.0.0.1:{l1.port}"]
    s0 = CoopRestoreSession(0, addrs, l0, loop0)
    s1 = CoopRestoreSession(1, addrs, l1, loop1)
    s0._connect_peers()
    s1._connect_peers()
    return s0, s1


def _entry_for(arr, location):
    from torchsnapshot_tpu.integrity import compute_checksum
    from torchsnapshot_tpu.serialization import dtype_to_string

    entry = ArrayEntry(
        location=location,
        serializer="buffer_protocol",
        dtype=dtype_to_string(arr.dtype),
        shape=list(arr.shape),
        replicated=True,
    )
    entry.checksum = compute_checksum(arr.tobytes())
    return entry


def _write(loop, plugin, path, payload) -> None:
    loop.run_until_complete(plugin.write(WriteIO(path=path, buf=payload)))


@pytest.fixture
def loops():
    loop0, loop1 = asyncio.new_event_loop(), asyncio.new_event_loop()
    yield loop0, loop1
    loop0.close()
    loop1.close()


def test_owner_forwards_receiver_consumes_bit_exact(tmp_path, loops, monkeypatch):
    """The core data path: the owner reads from storage (streamed, small
    sub-chunks) and forwards; the receiver's storage directory is EMPTY,
    so its bit-exact result proves every byte came over the peer
    channel — and its own chained CRC verified them."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(SUB))
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", "always")
    loop0, loop1 = loops
    arr = np.frombuffer(os.urandom(400_000), np.uint8).copy()
    owner_fs = FSStoragePlugin(str(tmp_path / "full"))
    empty_fs = FSStoragePlugin(str(tmp_path / "empty"))
    _write(loop0, owner_fs, "replicated/x", arr.tobytes())

    s0, s1 = _session_pair(loop0, loop1)
    try:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

        entry = _entry_for(arr, "replicated/x")
        out0, out1 = [], []
        req0 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out0.append),
        )
        req1 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out1.append),
        )
        key = unit_key(req0)
        plan0 = CoopKeyPlan(s0, {key: [1]}, {})
        plan1 = CoopKeyPlan(s1, {}, {key: 0})

        # Owner executes first: frames buffer in the receiver's staged
        # inboxes (unbounded, routed on handler threads) until its loop
        # consumes them — the cross-rank skew the design absorbs.
        loop0.run_until_complete(
            execute_read_reqs([req0], owner_fs, 1 << 30, 0, coop=plan0)
        )
        loop1.run_until_complete(
            execute_read_reqs([req1], empty_fs, 1 << 30, 1, coop=plan1)
        )
        assert out0 and out0[0].tobytes() == arr.tobytes()
        assert out1 and out1[0].tobytes() == arr.tobytes()
    finally:
        s0.close()
        s1.close()


def test_owner_restart_never_splices_on_peer_path(tmp_path, loops, monkeypatch):
    """Mirror-failover under cooperation: the owner's primary dies after
    one streamed chunk, the entry restarts buffered off the replica, and
    the RECEIVER commits only post-restart (generation-2) bytes — the
    never-splice invariant extended over the peer channel."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(SUB))
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", "always")
    from torchsnapshot_tpu.io_types import ReadStream
    from torchsnapshot_tpu.storage_plugins.mirror import MirroredStoragePlugin

    loop0, loop1 = loops
    arr = np.frombuffer(os.urandom(400_000), np.uint8).copy()

    class FlakyPrimary(FSStoragePlugin):
        async def read_stream(self, read_io, sub_chunk_bytes):
            inner = await super().read_stream(read_io, sub_chunk_bytes)

            async def chunks():
                it = inner.chunks
                yield await it.__anext__()
                await it.aclose()
                raise OSError("injected primary mid-stream death")

            return ReadStream(
                path=inner.path, nbytes=inner.nbytes, chunks=chunks()
            )

        async def read(self, read_io):
            raise OSError("injected primary read death")

    for d in ("p", "m"):
        _write(
            loop0, FSStoragePlugin(str(tmp_path / d)), "replicated/x", arr.tobytes()
        )
    owner_storage = MirroredStoragePlugin(
        FlakyPrimary(str(tmp_path / "p")),
        FSStoragePlugin(str(tmp_path / "m")),
        ".snapshot_metadata",
    )
    empty_fs = FSStoragePlugin(str(tmp_path / "empty"))

    s0, s1 = _session_pair(loop0, loop1)
    try:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

        entry = _entry_for(arr, "replicated/x")
        out0, out1 = [], []
        req0 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out0.append),
        )
        req1 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out1.append),
        )
        key = unit_key(req0)
        plan0 = CoopKeyPlan(s0, {key: [1]}, {})
        plan1 = CoopKeyPlan(s1, {}, {key: 0})
        loop0.run_until_complete(
            execute_read_reqs([req0], owner_storage, 1 << 30, 0, coop=plan0)
        )
        loop1.run_until_complete(
            execute_read_reqs([req1], empty_fs, 1 << 30, 1, coop=plan1)
        )
        assert out0 and out0[0].tobytes() == arr.tobytes()
        assert out1 and out1[0].tobytes() == arr.tobytes()
    finally:
        s0.close()
        s1.close()


def test_owner_abort_degrades_receiver_to_direct_read(tmp_path, loops, monkeypatch):
    """abort_incomplete (the owner never read the unit) must push the
    receiver onto a direct storage read promptly — not the timeout."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(SUB))
    loop0, loop1 = loops
    arr = np.frombuffer(os.urandom(200_000), np.uint8).copy()
    fs = FSStoragePlugin(str(tmp_path / "real"))
    _write(loop1, fs, "replicated/x", arr.tobytes())

    s0, s1 = _session_pair(loop0, loop1)
    try:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

        entry = _entry_for(arr, "replicated/x")
        out1 = []
        req1 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out1.append),
        )
        key = unit_key(req1)
        plan0 = CoopKeyPlan(s0, {key: [1]}, {})
        plan1 = CoopKeyPlan(s1, {}, {key: 0})
        plan0.abort_incomplete()  # the owner gives up before reading
        loop1.run_until_complete(
            execute_read_reqs([req1], fs, 1 << 30, 1, coop=plan1)
        )
        assert out1 and out1[0].tobytes() == arr.tobytes()
    finally:
        s0.close()
        s1.close()


def test_receiver_timeout_degrades_to_direct_read(tmp_path, loops, monkeypatch):
    """A silent (alive but never-sending) owner must cost the receiver
    one coop timeout, then a direct read — never a hang."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_COOP_TIMEOUT", "1")
    loop0, loop1 = loops
    arr = np.frombuffer(os.urandom(100_000), np.uint8).copy()
    fs = FSStoragePlugin(str(tmp_path / "real"))
    _write(loop1, fs, "replicated/x", arr.tobytes())

    s0, s1 = _session_pair(loop0, loop1)
    try:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

        entry = _entry_for(arr, "replicated/x")
        out1 = []
        req1 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out1.append),
        )
        key = unit_key(req1)
        plan1 = CoopKeyPlan(s1, {}, {key: 0})
        loop1.run_until_complete(
            execute_read_reqs([req1], fs, 1 << 30, 1, coop=plan1)
        )
        assert out1 and out1[0].tobytes() == arr.tobytes()
    finally:
        s0.close()
        s1.close()


def test_owner_death_poisons_pending_units(tmp_path, loops, monkeypatch):
    """An unclean connection drop from the owner aborts its pending
    units immediately (fail-fast, not the timeout) and the receiver
    direct-reads."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_COOP_TIMEOUT", "30")
    loop0, loop1 = loops
    arr = np.frombuffer(os.urandom(100_000), np.uint8).copy()
    fs = FSStoragePlugin(str(tmp_path / "real"))
    _write(loop1, fs, "replicated/x", arr.tobytes())

    s0, s1 = _session_pair(loop0, loop1)
    try:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

        entry = _entry_for(arr, "replicated/x")
        out1 = []
        req1 = ReadReq(
            path="replicated/x",
            buffer_consumer=ArrayBufferConsumer(entry, callback=out1.append),
        )
        key = unit_key(req1)
        plan1 = CoopKeyPlan(s1, {}, {key: 0})
        # Send one chunk then die UNCLEANLY (no bye): simulates the
        # owner crashing mid-entry.
        sock, lock = s0._out[1]
        with lock:
            send_peer_frame(
                sock,
                {"op": "chunk", "key": key, "gen": 1, "seq": 0},
                arr.tobytes()[:1000],
            )
            sock.close()
        import time

        t0 = time.perf_counter()
        loop1.run_until_complete(
            execute_read_reqs([req1], fs, 1 << 30, 1, coop=plan1)
        )
        # Fail-fast: well under the 30 s timeout.
        assert time.perf_counter() - t0 < 10.0
        assert out1 and out1[0].tobytes() == arr.tobytes()
    finally:
        s0.close()
        s1.close()


def test_world_size_1_never_offers() -> None:
    class _PG:
        def get_world_size(self):
            return 1

    offer = CoopRestoreSession.local_offer("FSStoragePlugin", _PG())
    assert offer.addr is None
    assert offer.engage([None], 0, None) is None


# ------------------------------------------------------------------- lint


def test_peer_channel_lint() -> None:
    """Tier-1 wiring for scripts/check_peer_channel.py: the real peer
    plane must be jax-free."""
    result = subprocess.run(
        [sys.executable, os.path.join("scripts", "check_peer_channel.py")],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr


def test_peer_channel_lint_catches_jax() -> None:
    import check_peer_channel as lint

    bad = "import jax\n\ndef f(x):\n    return jax.device_put(x)\n"
    violations = lint.check_source(bad, "<synthetic>")
    assert len(violations) >= 2
    aliased = "import jax.numpy as jnp\n\ndef f(x):\n    return jnp.sum(x)\n"
    assert lint.check_source(aliased, "<synthetic>")
    from_import = "from jax import device_put\n\ndef f(x):\n    return device_put(x)\n"
    assert lint.check_source(from_import, "<synthetic>")
    clean = "import numpy as np\n\ndef f(x):\n    return np.sum(x)\n"
    assert lint.check_source(clean, "<synthetic>") == []
