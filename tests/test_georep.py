"""Cross-region disaster recovery (georep.py): async geo-replication
via journal-epoch shipping with a durable cursor.

The contract under test (ISSUE 20): a rank-0 background shipper
replicates committed full snapshots and committed journal epochs to a
remote tier; the remote is a REAL snapshot + journal tree, so disaster
restore is the ordinary restore path folding base + committed epochs
bit-exact; a durable cursor makes shipping resume exactly-once across
shipper death; three fences (record CRCs, offset continuity, generation
chaining) mean a deposed or resurrected shipper can never splice a torn
tail or a stale generation over newer remote state; fsck understands
the cursor on both tiers and repairs a stale one.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np
import pytest

from torchsnapshot_tpu import (
    CheckpointManager,
    Snapshot,
    StateDict,
    georep,
    journal,
    telemetry,
)
from torchsnapshot_tpu.cli import main as cli_main, run_fsck
from torchsnapshot_tpu.journal import DeltaJournal


@pytest.fixture
def replicated(tmp_path, monkeypatch):
    """A primary root + armed remote root, fast shipper cadence."""
    remote = str(tmp_path / "remote")
    os.makedirs(remote)
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_GEOREP", remote)
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_GEOREP_INTERVAL_S", "0.05")
    telemetry.set_enabled(True)
    yield str(tmp_path / "primary"), remote
    telemetry.reset()
    telemetry.set_enabled(False)


def _state(v: float) -> StateDict:
    return StateDict(
        w=np.arange(512, dtype=np.float32) + v,
        b=np.full((32,), v, np.float64),
        step=int(v),
    )


def _assert_state(dst: StateDict, v: float) -> None:
    np.testing.assert_array_equal(
        dst["w"], np.arange(512, dtype=np.float32) + v
    )
    np.testing.assert_array_equal(dst["b"], np.full((32,), v, np.float64))
    assert dst["step"] == int(v)


def _journaled_step(root: str, epochs: int = 2):
    """A committed base + ``epochs`` committed journal epochs, built
    below the manager so tests can drive the shipper directly. Returns
    the live DeltaJournal so tests can CONTINUE the chain (a fresh
    DeltaJournal restarts epoch numbering — that is the deposed-writer
    scenario, not a continuation)."""
    step_dir = os.path.join(root, "step_0000000001")
    state = {"app": _state(0)}
    Snapshot.take(step_dir, state)
    j = DeltaJournal(step_dir, base_step=1, rank=0)
    j.capture_baseline(state)
    for e in range(1, epochs + 1):
        state["app"]["w"][: 16 * e] = float(100 + e)
        state["app"]["step"] = e
        assert j.append_epoch(state) > 0
    return step_dir, state, j


def _remote_segment(remote_step: str, rank: int = 0) -> str:
    return os.path.join(
        remote_step, journal.JOURNAL_DIRNAME, journal.segment_name(rank)
    )


# ------------------------------------------------------- headline drill


def test_region_loss_restores_remote_bit_exact(replicated, monkeypatch):
    """Primary region lost: the remote tier restores base + every
    committed epoch bit-exact through the ORDINARY restore path."""
    root, remote = replicated
    mgr = CheckpointManager(root, save_interval_steps=100)
    assert mgr._georep is not None  # armed by the env
    st = _state(0)
    mgr.save(0, {"app": st})
    for v in (1, 2, 3):
        st["w"] = np.arange(512, dtype=np.float32) + v
        st["b"] = np.full((32,), float(v), np.float64)
        st["step"] = v
        assert mgr.journal_step(v, {"app": st})
    assert mgr._georep.drain(timeout=30.0), mgr._georep.last_error
    mgr.close()

    shutil.rmtree(root)  # the disaster
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_GEOREP")
    before = telemetry.counters().get("dr_replica_restores", 0)
    dst = _state(-1)
    assert CheckpointManager(remote).restore({"app": dst}) == 0
    _assert_state(dst, 3)
    # Restore provenance: the replica restore is counted + logged.
    assert telemetry.counters().get("dr_replica_restores", 0) == before + 1


def test_remote_is_never_ahead_mid_epoch(replicated):
    """Only COMMITTED state ships: with the shipper drained, the remote
    journal chain equals the local committed chain exactly (a torn or
    open local tail never travels)."""
    root, remote = replicated
    del remote
    step_dir, _, _j = _journaled_step(root, epochs=3)
    remote_root = os.environ["TORCHSNAPSHOT_TPU_GEOREP"]
    rep = georep.GeoReplicator(remote_root, interval=0.05)
    try:
        rep.enqueue(step_dir, 1)
        assert rep.drain(timeout=30.0), rep.last_error
    finally:
        rep.close(0)
    local = journal.committed_epochs(
        journal.read_epoch_metas(
            os.path.join(step_dir, journal.JOURNAL_DIRNAME)
        )
    )
    remote_step = os.path.join(remote_root, "step_0000000001")
    shipped = journal.committed_epochs(
        journal.read_epoch_metas(
            os.path.join(remote_step, journal.JOURNAL_DIRNAME)
        )
    )
    assert [m["epoch"] for m in shipped] == [m["epoch"] for m in local]
    assert [m["gen"] for m in shipped] == [m["gen"] for m in local]


# --------------------------------------------------- cursor exactly-once


def test_cursor_resumes_shipping_mid_stream(replicated, monkeypatch):
    """A restarted shipper resumes from the durable cursor: only the
    epochs past it cross the WAN, appended (not rewritten) onto the
    remote segment."""
    root, remote = replicated
    step_dir, state, j = _journaled_step(root, epochs=1)
    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)  # the shipper dies

    remote_step = os.path.join(remote, "step_0000000001")
    seg_after_e1 = os.path.getsize(_remote_segment(remote_step))

    state["app"]["w"][:8] = -5.0  # epoch 2 continues the chain
    assert j.append_epoch(state) > 0

    appended = []
    orig = georep._RemoteTier.append

    def counting_append(self, rel, existing, region, _orig=orig):
        appended.append((rel, len(existing), len(region)))
        _orig(self, rel, existing, region)

    monkeypatch.setattr(georep._RemoteTier, "append", counting_append)
    rep2 = georep.GeoReplicator(remote, interval=0.05)
    try:
        rep2.enqueue(step_dir, 1)
        assert rep2.drain(timeout=30.0), rep2.last_error
    finally:
        rep2.close(0)
    # Exactly one extension, from exactly the epoch-1 committed offset.
    assert [(n, e) for n, e, _ in appended] == [
        (os.path.join(journal.JOURNAL_DIRNAME, journal.segment_name(0)),
         seg_after_e1)
    ]
    cur = georep.read_cursor(remote_step)
    assert cur is not None and cur["epoch"] == 2


def test_death_between_remote_commit_and_cursor_is_exactly_once(
    replicated, monkeypatch
):
    """Shipper died after committing epoch k remotely but before the
    cursor write: the resurrected shipper probes the remote metadata,
    advances the cursor, and never re-applies a byte."""
    root, remote = replicated
    step_dir, _, _j = _journaled_step(root, epochs=2)
    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)

    remote_step = os.path.join(remote, "step_0000000001")
    cur = georep.read_cursor(remote_step)
    assert cur["epoch"] == 2
    metas = journal.committed_epochs(
        journal.read_epoch_metas(
            os.path.join(remote_step, journal.JOURNAL_DIRNAME)
        )
    )
    # Rewind the cursor to simulate the crash window.
    with open(os.path.join(remote_step, georep.CURSOR_FNAME), "w") as f:
        json.dump({**cur, "epoch": 1, "gen": metas[0]["gen"]}, f)

    def no_writes(self, rel, *a, **k):
        raise AssertionError(f"remote write during advance-only: {rel}")

    monkeypatch.setattr(georep._RemoteTier, "append", no_writes)
    rep2 = georep.GeoReplicator(remote, interval=0.05)
    try:
        rep2.enqueue(step_dir, 1)
        assert rep2.drain(timeout=30.0), rep2.last_error
    finally:
        rep2.close(0)
    assert georep.read_cursor(remote_step)["epoch"] == 2


# ------------------------------------------------------------ the fences


def test_diverged_generation_is_refused(replicated):
    """A remote chain carrying a different generation for epoch k-1
    refuses epoch k before any byte moves (the deposed-shipper fence)."""
    root, remote = replicated
    step_dir, state, j = _journaled_step(root, epochs=1)
    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)

    remote_step = os.path.join(remote, "step_0000000001")
    jdir = os.path.join(remote_step, journal.JOURNAL_DIRNAME)
    meta_path = os.path.join(jdir, journal.epoch_meta_name(1))
    with open(meta_path) as f:
        meta = json.load(f)
    meta["gen"] = "0" * 32  # the remote chain now belongs to someone else
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    # Cursor agrees with the tampered chain (a resurrected shipper
    # whose local journal diverged from what the remote holds).
    cur = georep.read_cursor(remote_step)
    with open(os.path.join(remote_step, georep.CURSOR_FNAME), "w") as f:
        json.dump({**cur, "gen": "0" * 32}, f)

    state["app"]["w"][:4] = 7.0  # epoch 2 continues the LOCAL chain
    assert j.append_epoch(state) > 0

    seg = _remote_segment(remote_step)
    before_bytes = open(seg, "rb").read()
    refusals0 = telemetry.counters().get("georep_splice_refusals", 0)
    rep2 = georep.GeoReplicator(remote, interval=0.05)
    try:
        rep2.enqueue(step_dir, 1)
        assert not rep2.drain(timeout=1.0)  # refused, stays pending
        assert "generation" in (rep2.last_error or "")
    finally:
        rep2.close(0)
    assert telemetry.counters().get("georep_splice_refusals", 0) > refusals0
    assert open(seg, "rb").read() == before_bytes  # not a byte moved


def test_offset_discontinuity_is_refused(replicated):
    """A remote segment that is not exactly at the epoch's start offset
    refuses the splice (never overwrite, never leave a gap)."""
    root, remote = replicated
    step_dir, _, _j = _journaled_step(root, epochs=2)
    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)

    remote_step = os.path.join(remote, "step_0000000001")
    seg = _remote_segment(remote_step)
    blob = open(seg, "rb").read()
    # Truncate the remote segment INTO a committed region (off any
    # epoch boundary) and erase the cursor + remote metas: the re-ship
    # must refuse to extend a segment at no committed offset.
    with open(seg, "wb") as f:
        f.write(blob[: len(blob) - 3])
    os.remove(os.path.join(remote_step, georep.CURSOR_FNAME))
    for n in os.listdir(os.path.join(remote_step, journal.JOURNAL_DIRNAME)):
        if journal._EPOCH_META_RE.match(n):
            os.remove(
                os.path.join(remote_step, journal.JOURNAL_DIRNAME, n)
            )

    rep2 = georep.GeoReplicator(remote, interval=0.05)
    try:
        rep2.enqueue(step_dir, 1)
        assert not rep2.drain(timeout=1.0)
        assert "extend" in (rep2.last_error or "") or "segment" in (
            rep2.last_error or ""
        )
    finally:
        rep2.close(0)


# ------------------------------------------------------- status + fsck


def test_status_and_cli(replicated, capsys):
    root, remote = replicated
    step_dir, _, _j = _journaled_step(root, epochs=2)

    # Nothing shipped yet: the full backlog is visible.
    st = georep.status(root, remote_root=remote)
    assert st["enabled"] and st["step"] == 1
    assert not st["base_replicated"]
    assert st["backlog_epochs"] == 1 + 2  # base + both epochs
    assert cli_main(["georep-status", root]) == 1  # behind
    capsys.readouterr()  # drop the human rendering

    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)

    st = georep.status(root, remote_root=remote)
    assert st["base_replicated"]
    assert st["applied_epoch"] == 2 == st["local_epochs"]
    assert st["applied_gen"] == st["local_gen"]
    assert st["backlog_epochs"] == 0
    assert cli_main(["georep-status", root, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["backlog_epochs"] == 0
    # Unconfigured root: cannot-check.
    os.environ.pop("TORCHSNAPSHOT_TPU_GEOREP")
    assert cli_main(["georep-status", root]) == 2


def test_fsck_clean_on_both_tiers(replicated):
    """The regression the satellite pins: a replicated snapshot fscks
    clean on BOTH tiers — cursor and ship temps are known artifacts,
    and the shipped journal chain passes the journal checks."""
    root, remote = replicated
    step_dir, _, _j = _journaled_step(root, epochs=2)
    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)
    for tier_dir in (step_dir, os.path.join(remote, "step_0000000001")):
        code, report = run_fsck(tier_dir)
        assert code == 0, (tier_dir, report.findings)


def test_fsck_repairs_stale_cursor(replicated):
    root, remote = replicated
    step_dir, _, _j = _journaled_step(root, epochs=1)
    rep = georep.GeoReplicator(remote, interval=0.05)
    rep.enqueue(step_dir, 1)
    assert rep.drain(timeout=30.0), rep.last_error
    rep.close(0)

    remote_step = os.path.join(remote, "step_0000000001")
    cur = georep.read_cursor(remote_step)
    with open(os.path.join(remote_step, georep.CURSOR_FNAME), "w") as f:
        json.dump({**cur, "epoch": 99}, f)  # claims epochs that never shipped
    code, report = run_fsck(remote_step)
    assert code == 1
    assert report.classes() == {"georep-stale-cursor"}
    code, report = run_fsck(remote_step, repair=True)
    assert code == 0, report.findings
    assert ("georep-stale-cursor", georep.CURSOR_FNAME) in report.repaired
    # Convergent: a second pass is clean, and the shipper re-derives.
    code, _ = run_fsck(remote_step)
    assert code == 0
    rep2 = georep.GeoReplicator(remote, interval=0.05)
    try:
        rep2.enqueue(step_dir, 1)
        assert rep2.drain(timeout=30.0), rep2.last_error
    finally:
        rep2.close(0)
    assert georep.read_cursor(remote_step)["epoch"] == 1


# ------------------------------------------------- foreground isolation


def test_disabled_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_GEOREP", raising=False)
    mgr = CheckpointManager(str(tmp_path))
    assert mgr._georep is None
    mgr.save(0, {"app": _state(0)})
    mgr.close()


def test_backlog_is_bounded_drop_oldest(replicated, monkeypatch):
    """A dead remote tier means a BOUNDED backlog: oldest pending steps
    drop (a newer committed base supersedes them), counted loudly."""
    root, remote = replicated
    del root, remote
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_GEOREP_BACKLOG", "2")
    rep = georep.GeoReplicator("/nonexistent/remote", interval=3600.0)
    try:
        for step in range(5):
            rep.enqueue(f"/primary/step_{step:010d}", step)
        assert len(rep._pending) == 2
        assert sorted(rep._pending) == [3, 4]  # newest survive
        assert rep.dropped_steps == 3
        assert rep.lag_s() >= 0.0
    finally:
        rep.close(0)


def test_enqueue_coalesces_keeping_oldest_timestamp(replicated):
    root, remote = replicated
    del root, remote
    rep = georep.GeoReplicator("/nonexistent/remote", interval=3600.0)
    try:
        rep.enqueue("/primary/step_0000000001", 1)
        _, ts0 = rep._pending[1]
        rep.enqueue("/primary/step_0000000001", 1)  # another epoch commit
        assert rep._pending[1][1] == ts0  # lag measures the OLDEST state
        assert len(rep._pending) == 1
    finally:
        rep.close(0)


def test_preemption_consume_drains_the_shipper(replicated):
    """The grace window: consume() runs the registered bounded drain so
    the final flushed epoch reaches the remote tier before teardown."""
    from torchsnapshot_tpu.preemption import PreemptionWatcher

    watcher = PreemptionWatcher.__new__(PreemptionWatcher)
    watcher._consume_hooks = []
    watcher._consumed = False
    watcher._pending = []
    drained = []
    watcher.add_consume_hook(lambda: drained.append(True))
    watcher.add_consume_hook(lambda: (_ for _ in ()).throw(RuntimeError()))
    watcher._log_pending = lambda: None
    watcher.consume()
    assert drained == [True] and watcher.consumed  # isolated + fired
