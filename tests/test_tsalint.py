"""Tier-1 enforcement + unit tests for the ``tsalint`` static-analysis
framework (torchsnapshot_tpu/analysis/).

Four layers, mirroring ISSUE 11's acceptance bars:

1. **The package is clean** — the full analyzer exits 0 on the shipped
   tree (this is the CI gate; the dedicated workflow job runs the same
   entry point).
2. **Seeded negatives** — each new pass catches a synthetic fixture of
   the bug class it exists for: a lock-order inversion, a blocking call
   under a lock, a blocking finalizer, a leaked fd on an early return,
   an unregistered / unauditable env read. Exactly one finding each,
   with the right rule id.
3. **Suppression hygiene** — in-file allows (incl. multi-line comment
   blocks) suppress and are verified; stale allows, missing reasons,
   and stale/malformed baseline entries all fail the run.
4. **Legacy bit-identity** — the five ``scripts/check_*.py`` wrappers
   re-export the SAME function objects the plugins run, and a wrapper's
   stdout/exit code matches the plugin invoked directly.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TSALINT = os.path.join(REPO, "scripts", "tsalint.py")

from torchsnapshot_tpu.analysis import (  # noqa: E402
    EXIT_CLEAN,
    EXIT_ERROR,
    EXIT_FINDINGS,
    Project,
    run_lint,
)
from torchsnapshot_tpu.analysis import runner, suppress  # noqa: E402
from torchsnapshot_tpu.analysis.plugins import (  # noqa: E402
    PLUGINS,
    legacy_event_taxonomy,
    legacy_fault_sites,
    legacy_peer_channel,
    legacy_stream_contract,
    legacy_timing,
)


def _project(tmp_path, files):
    """Build a Project over a synthetic package tree."""
    for sub, source in files.items():
        path = tmp_path / sub
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return Project(package_dir=str(tmp_path), rel_prefix="pkg")


def _lint(tmp_path, files, rules):
    """run_lint over a synthetic tree with no baseline in play."""
    return run_lint(
        rules=rules,
        project=_project(tmp_path, files),
        baseline_file=str(tmp_path / "_no_baseline.json"),
    )


# ------------------------------------------------------- the shipped tree


def test_package_scan_clean():
    """The full analyzer is clean on the shipped tree: every true
    positive is fixed or carries an in-file justification, and the
    baseline holds zero entries."""
    r = subprocess.run(
        [sys.executable, TSALINT],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_module_entrypoint_json():
    r = subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu", "lint", "--json",
         "--rule", "timing", "--rule", "peer-channel"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["exit_code"] == 0
    assert doc["findings"] == []
    assert sorted(doc["rules"]) == ["peer-channel", "timing"]


def test_unknown_rule_is_usage_error(capsys):
    assert run_lint(rules=["no-such-rule"]).exit_code == EXIT_ERROR
    assert runner.main(["--rule", "no-such-rule"]) == EXIT_ERROR
    capsys.readouterr()


def test_list_rules_covers_every_plugin(capsys):
    assert runner.main(["--list-rules"]) == EXIT_CLEAN
    out = capsys.readouterr().out
    for mod in PLUGINS.values():
        for rule in mod.RULES:
            assert rule in out


# -------------------------------------------------------- seeded negatives


def test_lock_order_inversion_seeded(tmp_path):
    """A link-lock-then-cond acquisition in dist_store.py runs against
    the documented _cond -> lock order: exactly one finding."""
    report = _lint(tmp_path, {
        "dist_store.py": """\
            class S:
                def bad(self, link):
                    with link.lock:
                        with self._cond:
                            pass
            """,
    }, rules=["lock-order"])
    assert [f.rule for f in report.unsuppressed] == ["lock-order"]
    assert report.unsuppressed[0].file == "pkg/dist_store.py"
    assert report.exit_code == EXIT_FINDINGS


def test_lock_order_generic_inversion_seeded(tmp_path):
    """Without a documented order, a two-way inversion is reported once
    per direction."""
    report = _lint(tmp_path, {
        "mod.py": """\
            class S:
                def ab(self):
                    with self.a_lock:
                        with self.b_lock:
                            pass

                def ba(self):
                    with self.b_lock:
                        with self.a_lock:
                            pass
            """,
    }, rules=["lock-order"])
    assert [f.rule for f in report.unsuppressed] == ["lock-order"] * 2


def test_lock_blocking_seeded(tmp_path):
    report = _lint(tmp_path, {
        "mod.py": """\
            import time

            def f(lk):
                with lk:
                    time.sleep(1.0)
            """,
    }, rules=["lock-blocking"])
    assert [f.rule for f in report.unsuppressed] == ["lock-blocking"]
    assert "time.sleep" in report.unsuppressed[0].message


def test_lock_blocking_one_level_descent(tmp_path):
    """The pass sees a blocking call one package-local call below the
    lock (the wrapper-function idiom the repo actually uses)."""
    report = _lint(tmp_path, {
        "mod.py": """\
            import time

            def _wait():
                time.sleep(1.0)

            def f(lk):
                with lk:
                    _wait()
            """,
    }, rules=["lock-blocking"])
    assert [f.rule for f in report.unsuppressed] == ["lock-blocking"]
    assert "_wait" in report.unsuppressed[0].message


def test_restricted_context_blocking_finalizer_seeded(tmp_path):
    report = _lint(tmp_path, {
        "pool.py": """\
            import threading
            import weakref

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    weakref.finalize(self, self._cleanup)

                def _cleanup(self):
                    with self._lock:
                        pass
            """,
    }, rules=["restricted-context"])
    assert [f.rule for f in report.unsuppressed] == ["restricted-context"]
    assert "finalizer" in report.unsuppressed[0].message


def test_resource_lifecycle_early_return_leak_seeded(tmp_path):
    report = _lint(tmp_path, {
        "io.py": """\
            import os

            def read_header(path, probe):
                fd = os.open(path, os.O_RDONLY)
                if probe:
                    return None
                data = os.read(fd, 16)
                os.close(fd)
                return data
            """,
    }, rules=["resource-lifecycle"])
    assert [f.rule for f in report.unsuppressed] == ["resource-lifecycle"]
    assert "os.open" in report.unsuppressed[0].message


def test_resource_lifecycle_try_finally_is_clean(tmp_path):
    report = _lint(tmp_path, {
        "io.py": """\
            import os

            def read_header(path, probe):
                fd = os.open(path, os.O_RDONLY)
                try:
                    if probe:
                        return None
                    return os.read(fd, 16)
                finally:
                    os.close(fd)
            """,
    }, rules=["resource-lifecycle"])
    assert report.unsuppressed == []
    assert report.exit_code == EXIT_CLEAN


def test_env_unregistered_seeded(tmp_path):
    report = _lint(tmp_path, {
        "knobs.py": """\
            import os

            def knob():
                return os.environ.get("TORCHSNAPSHOT_TPU_NOT_A_KNOB", "0")
            """,
    }, rules=["env-unregistered"])
    assert [f.rule for f in report.unsuppressed] == ["env-unregistered"]
    assert "TORCHSNAPSHOT_TPU_NOT_A_KNOB" in report.unsuppressed[0].message


def test_env_dynamic_seeded(tmp_path):
    report = _lint(tmp_path, {
        "knobs.py": """\
            import os

            class Cfg:
                def get(self, name):
                    return os.environ.get(name)
            """,
    }, rules=["env-dynamic"])
    assert [f.rule for f in report.unsuppressed] == ["env-dynamic"]


def test_env_registered_read_is_clean(tmp_path):
    report = _lint(tmp_path, {
        "knobs.py": """\
            import os

            def knob():
                return os.environ.get("TORCHSNAPSHOT_TPU_TELEMETRY", "0")
            """,
    }, rules=["env-unregistered", "env-dynamic"])
    assert report.unsuppressed == []


# ---------------------------------------------------- suppression hygiene


_BLOCKING_FIXTURE = """\
    import time

    def f(lk):
        with lk:
            time.sleep(1.0)
    """


def test_inline_allow_suppresses(tmp_path):
    report = _lint(tmp_path, {
        "mod.py": """\
            import time

            def f(lk):
                with lk:
                    # tsalint: allow[lock-blocking] fixture: deliberate hold
                    time.sleep(1.0)
            """,
    }, rules=["lock-blocking"])
    assert report.unsuppressed == []
    assert report.hygiene == []
    assert len(report.suppressed) == 1
    assert report.exit_code == EXIT_CLEAN


def test_inline_allow_comment_block_slides(tmp_path):
    """A justification spread over a comment block still covers the
    first code line below it."""
    report = _lint(tmp_path, {
        "mod.py": """\
            import time

            def f(lk):
                with lk:
                    # tsalint: allow[lock-blocking] a long justification
                    # that continues onto a second comment line
                    time.sleep(1.0)
            """,
    }, rules=["lock-blocking"])
    assert report.unsuppressed == []
    assert report.hygiene == []
    assert len(report.suppressed) == 1


def test_stale_allow_fails_the_run(tmp_path):
    report = _lint(tmp_path, {
        "mod.py": """\
            # tsalint: allow[lock-blocking] nothing blocks here anymore
            X = 1
            """,
    }, rules=["lock-blocking"])
    assert [f.rule for f in report.hygiene] == ["stale-suppression"]
    assert report.exit_code == EXIT_FINDINGS


def test_allow_without_reason_fails_the_run(tmp_path):
    report = _lint(tmp_path, {
        "mod.py": """\
            import time

            def f(lk):
                with lk:
                    # tsalint: allow[lock-blocking]
                    time.sleep(1.0)
            """,
    }, rules=["lock-blocking"])
    assert any(f.rule == "suppression-syntax" for f in report.hygiene)
    assert report.exit_code == EXIT_FINDINGS


def test_allow_in_docstring_is_not_a_suppression(tmp_path):
    """Only real COMMENT tokens register — prose that mentions the
    syntax (like suppress.py's own docstring) must not."""
    report = _lint(tmp_path, {
        "mod.py": '''\
            """Docs: write '# tsalint: allow[lock-blocking] reason' above."""

            import time

            def f(lk):
                with lk:
                    time.sleep(1.0)
            ''',
    }, rules=["lock-blocking"])
    assert [f.rule for f in report.unsuppressed] == ["lock-blocking"]
    assert report.hygiene == []  # the docstring is neither stale nor bad


def test_baseline_suppresses_and_goes_stale(tmp_path):
    files = {"mod.py": _BLOCKING_FIXTURE}
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"suppressions": [
        {"rule": "lock-blocking", "file": "pkg/mod.py",
         "reason": "adopted with the analyzer"},
    ]}))
    report = run_lint(
        rules=["lock-blocking"], project=_project(tmp_path, files),
        baseline_file=str(base),
    )
    assert report.unsuppressed == []
    assert len(report.suppressed) == 1
    assert report.exit_code == EXIT_CLEAN

    # an entry matching nothing fails the run: the baseline only shrinks
    base.write_text(json.dumps({"suppressions": [
        {"rule": "lock-blocking", "file": "pkg/mod.py",
         "reason": "adopted with the analyzer"},
        {"rule": "lock-blocking", "file": "pkg/gone.py",
         "reason": "file was deleted"},
    ]}))
    report = run_lint(
        rules=["lock-blocking"], project=_project(tmp_path, files),
        baseline_file=str(base),
    )
    assert [f.rule for f in report.hygiene] == ["stale-suppression"]
    assert report.exit_code == EXIT_FINDINGS


def test_baseline_entry_requires_reason(tmp_path):
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"suppressions": [
        {"rule": "lock-blocking", "file": "pkg/mod.py"},
    ]}))
    report = run_lint(
        rules=["lock-blocking"],
        project=_project(tmp_path, {"mod.py": _BLOCKING_FIXTURE}),
        baseline_file=str(base),
    )
    assert any(f.rule == "suppression-syntax" for f in report.hygiene)
    # the finding itself is NOT covered by the malformed entry
    assert [f.rule for f in report.unsuppressed] == ["lock-blocking"]


def test_baseline_env_override(monkeypatch, tmp_path):
    override = tmp_path / "elsewhere.json"
    monkeypatch.setenv(suppress.BASELINE_ENV_VAR, str(override))
    assert suppress.baseline_path() == str(override)
    monkeypatch.delenv(suppress.BASELINE_ENV_VAR)
    assert suppress.baseline_path() == suppress.DEFAULT_BASELINE


def test_shipped_baseline_is_empty():
    with open(os.path.join(REPO, ".tsalint_baseline.json")) as f:
        doc = json.load(f)
    assert doc["suppressions"] == []


# ------------------------------------------------------ legacy bit-identity


def test_legacy_wrappers_reexport_the_plugin_objects():
    """The scripts/check_*.py wrappers and the tsalint plugins are the
    SAME objects — identical results by construction."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_event_taxonomy
        import check_fault_sites
        import check_peer_channel
        import check_stream_contract
        import check_timing_lint
    finally:
        sys.path.pop(0)
    assert check_timing_lint._violations_in is legacy_timing._violations_in
    assert check_timing_lint.collect_failures is legacy_timing.collect_failures
    assert check_timing_lint.ALLOWLIST is legacy_timing.ALLOWLIST
    assert check_fault_sites.check_source is legacy_fault_sites.check_source
    assert check_fault_sites.run is legacy_fault_sites.run
    assert check_fault_sites.MIN_SITES == legacy_fault_sites.MIN_SITES
    assert check_peer_channel.check_source is legacy_peer_channel.check_source
    assert (check_stream_contract.advertising_plugins
            is legacy_stream_contract.advertising_plugins)
    assert (check_event_taxonomy.check_source
            is legacy_event_taxonomy.check_source)
    assert check_event_taxonomy.run is legacy_event_taxonomy.run


@pytest.mark.parametrize("script,plugin_mod", [
    ("check_timing_lint.py",
     "torchsnapshot_tpu.analysis.plugins.legacy_timing"),
    ("check_fault_sites.py",
     "torchsnapshot_tpu.analysis.plugins.legacy_fault_sites"),
])
def test_legacy_wrapper_output_bit_identical(script, plugin_mod):
    """A wrapper's stdout and exit code match the plugin's own main()."""
    wrapper = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", script)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    direct = subprocess.run(
        [sys.executable, "-c",
         f"import sys; from {plugin_mod} import main; sys.exit(main())"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert wrapper.returncode == direct.returncode
    assert wrapper.stdout == direct.stdout
