"""Flatten/inflate round-trip tests (reference pattern: tests/test_flatten.py)."""

from collections import OrderedDict, namedtuple

import numpy as np
import pytest

from torchsnapshot_tpu.flatten import flatten, inflate
from torchsnapshot_tpu.manifest import DictEntry, ListEntry, NamedTupleEntry

Point = namedtuple("Point", ["x", "y"])


def test_roundtrip_nested() -> None:
    obj = {
        "model": OrderedDict(
            [("w", np.ones((2, 2))), ("b", np.zeros(3))],
        ),
        "step": 7,
        "history": [1.0, 2.0, {"nested": "deep"}],
        "coords": (1, 2, 3),
    }
    manifest, flattened = flatten(obj, prefix="app")
    out = inflate(manifest, flattened, prefix="app")
    assert out["step"] == 7
    assert isinstance(out["model"], OrderedDict)
    np.testing.assert_array_equal(out["model"]["w"], obj["model"]["w"])
    assert out["history"][2] == {"nested": "deep"}
    assert out["coords"] == (1, 2, 3)
    assert isinstance(out["coords"], tuple)


def test_namedtuple_roundtrip() -> None:
    obj = {"pt": Point(x=np.ones(2), y=3)}
    manifest, flattened = flatten(obj, prefix="s")
    entry = manifest["s/pt"]
    assert isinstance(entry, NamedTupleEntry)
    assert entry.fields == ["x", "y"]
    out = inflate(manifest, flattened, prefix="s")
    assert isinstance(out["pt"], Point)
    assert out["pt"].y == 3


def test_optax_state_flattens() -> None:
    import jax.numpy as jnp
    import optax

    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros(3)}
    opt = optax.adam(1e-3)
    state = opt.init(params)
    manifest, flattened = flatten({"opt": state}, prefix="0")
    out = inflate(manifest, flattened, prefix="0")
    # The reconstructed state must work as an optax state again.
    import jax

    grads = jax.tree.map(jnp.ones_like, params)
    opt.update(grads, out["opt"], params)


def test_key_escaping() -> None:
    obj = {"a/b": 1, "a%2Fb": 2, "c": {"d/e%": 3}}
    manifest, flattened = flatten(obj, prefix="r")
    assert len(flattened) == 3
    out = inflate(manifest, flattened, prefix="r")
    assert out == obj


def test_int_keys_preserved() -> None:
    obj = {0: "a", 1: "b", "k": {2: "c"}}
    manifest, flattened = flatten(obj, prefix="")
    out = inflate(manifest, flattened, prefix="")
    assert out == obj
    assert set(out.keys()) == {0, 1, "k"}


def test_colliding_keys_rejected() -> None:
    with pytest.raises(RuntimeError, match="collide"):
        flatten({1: "a", "1": "b"}, prefix="")


def test_unsupported_key_type_rejected() -> None:
    with pytest.raises(RuntimeError, match="unsupported key type"):
        flatten({(1, 2): "a"}, prefix="")


def test_empty_containers() -> None:
    obj = {"empty_list": [], "empty_dict": {}, "t": ()}
    manifest, flattened = flatten(obj, prefix="p")
    assert flattened == {}
    out = inflate(manifest, flattened, prefix="p")
    assert out == obj


def test_leaf_at_root() -> None:
    manifest, flattened = flatten(42, prefix="x")
    assert manifest == {}
    assert flattened == {"x": 42}
    assert inflate(manifest, flattened, prefix="x") == 42


def test_manifest_entries_are_expected_types() -> None:
    manifest, _ = flatten({"l": [1], "d": {"k": 2}}, prefix="0")
    assert isinstance(manifest["0"], DictEntry)
    assert isinstance(manifest["0/l"], ListEntry)
    assert manifest["0/d"].keys == ["k"]
