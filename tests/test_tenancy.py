"""Multi-tenant checkpoint service (ISSUE 17): namespaces, quota-aware
retention, cross-tenant dedup, admission control.

The isolation contract under test: two CheckpointManagers with different
tenants sharing ONE bucket root and ONE coordination store must be fully
isolated — disjoint storage trees (``tenants/<id>/...``), disjoint
``tsnap/t/<id>/...`` store keyspaces — while the deliberately-global
planes (tenant registry, admission table, payload pool) arbitrate across
them. Quota raises BEFORE payload I/O; the pool stores identical base
payloads once with per-tenant refcounts; a SIGKILLed tenant never
corrupts its neighbor.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from torchsnapshot_tpu import StateDict, telemetry
from torchsnapshot_tpu.manager import CheckpointManager
from torchsnapshot_tpu.tenancy import (
    NamespacedStore,
    Tenant,
    activated,
    current_tenant,
    maybe_scope_store,
    pool,
    quota,
    registry,
    scope_key,
    tenant_root,
)
from torchsnapshot_tpu.tenancy.admission import AdmissionSession, maybe_arm
from torchsnapshot_tpu.tenancy.quota import (
    QuotaExceededError,
    QuotaUnenforceableError,
)


def _state(n: int = 1024, mult: float = 1.0) -> dict:
    return {"model": StateDict(w=np.arange(n, dtype=np.float32) * mult)}


def _steps(root: str, tid: str) -> list:
    d = os.path.join(root, "tenants", tid)
    if not os.path.isdir(d):
        return []
    return sorted(x for x in os.listdir(d) if x.startswith("step_"))


class FakeStore:
    """Dict-backed store honoring the verbs registry/scoping rely on."""

    def __init__(self, data=None):
        self.data = {} if data is None else data

    def set(self, key, value):
        self.data[key] = bytes(value)

    def get(self, key):
        return self.data[key]

    def add(self, key, amount):
        cur = int(self.data.get(key, b"0")) + amount
        self.data[key] = str(cur).encode()
        return cur

    def check(self, key):
        return key in self.data

    def delete(self, key):
        return self.data.pop(key, None)

    def collect(self, prefix, count, timeout=None, **kw):
        items = {k: v for k, v in self.data.items() if k.startswith(prefix)}
        return len(items), items

    def clone(self):
        return FakeStore(self.data)


# ------------------------------------------------------------- Tenant


class TestTenant:
    def test_default_root_prefix(self):
        t = Tenant(id="alpha")
        assert t.root_prefix == "tenants/alpha"
        assert tenant_root("/data/ckpt", t) == "/data/ckpt/tenants/alpha"

    @pytest.mark.parametrize(
        "bad", ["", "a/b", "../x", ".hidden", "-lead", "x" * 65]
    )
    def test_bad_ids_rejected(self, bad):
        with pytest.raises(ValueError):
            Tenant(id=bad)

    def test_escaping_root_prefix_rejected(self):
        with pytest.raises(ValueError):
            Tenant(id="a", root_prefix="../outside")
        with pytest.raises(ValueError):
            Tenant(id="a", root_prefix="/abs")
        with pytest.raises(ValueError):
            Tenant(id="a", root_prefix="x/../../y")

    def test_quota_and_priority_validated(self):
        with pytest.raises(ValueError):
            Tenant(id="a", quota_bytes=0)
        with pytest.raises(ValueError):
            Tenant(id="a", priority=0)

    def test_env_tenant(self, monkeypatch):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_TENANT", "envt")
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_QUOTA_BYTES", "12345")
        t = current_tenant()
        assert t is not None and t.id == "envt" and t.quota_bytes == 12345

    def test_activation_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_TENANT", "envt")
        with activated(Tenant(id="explicit")):
            assert current_tenant().id == "explicit"
        assert current_tenant().id == "envt"


# ---------------------------------------------------------- key scoping


class TestStoreScoping:
    def test_scope_key(self):
        assert scope_key("tsnap/health/0", "a") == "tsnap/t/a/health/0"
        assert scope_key("other/key", "a") == "other/key"

    def test_namespaced_store_verbs(self):
        raw = FakeStore()
        ns = NamespacedStore(raw, "alpha")
        ns.set("tsnap/health/0", b"beat")
        assert "tsnap/t/alpha/health/0" in raw.data
        assert ns.get("tsnap/health/0") == b"beat"
        assert ns.check("tsnap/health/0")
        assert ns.add("tsnap/seq", 2) == 2
        ns.delete("tsnap/health/0")
        assert not ns.check("tsnap/health/0")

    def test_collect_translates_back(self):
        raw = FakeStore()
        ns = NamespacedStore(raw, "alpha")
        ns.set("tsnap/health/0", b"x")
        ns.set("tsnap/health/1", b"y")
        NamespacedStore(raw, "beta").set("tsnap/health/0", b"z")
        n, items = ns.collect("tsnap/health/", 0)
        assert n == 2
        # callers slice key[len(prefix):] — they must see UNSCOPED keys
        assert sorted(items) == ["tsnap/health/0", "tsnap/health/1"]

    def test_maybe_scope_store(self):
        raw = FakeStore()
        assert maybe_scope_store(raw) is raw  # no tenant -> untouched
        with activated(Tenant(id="a")):
            ns = maybe_scope_store(raw)
            assert isinstance(ns, NamespacedStore)
            assert maybe_scope_store(ns) is ns  # never double-wraps

    def test_clone_preserves_namespace(self):
        ns = NamespacedStore(FakeStore(), "a").clone()
        assert isinstance(ns, NamespacedStore)

    def test_heartbeat_keys_tenant_scoped(self):
        from torchsnapshot_tpu.telemetry.health import HeartbeatPublisher

        raw = FakeStore()
        with activated(Tenant(id="alpha")):
            pub = HeartbeatPublisher(raw, rank=0, op="take", path="/x")
        assert pub.prefix == "tsnap/t/alpha/health/"


# ------------------------------------------------------------ registry


class TestRegistry:
    def test_register_lookup_live(self):
        store = FakeStore()
        registry.register(store, Tenant(id="a", quota_bytes=9, priority=3))
        row = registry.lookup(store, "a")
        assert row["quota_bytes"] == 9 and row["priority"] == 3
        assert "a" in registry.live_tenants(store)

    def test_ghost_key_death_rule(self):
        store = FakeStore()
        registry.register(store, Tenant(id="a"))
        registry.deregister(store, "a")
        # row survives for post-mortem reads; liveness is gone
        assert registry.lookup(store, "a") is not None
        assert "a" not in registry.live_tenants(store)
        # re-registration resurrects (clears the ghost)
        registry.register(store, Tenant(id="a"))
        assert "a" in registry.live_tenants(store)

    def test_manager_registers_and_close_deregisters(self, tmp_path):
        from torchsnapshot_tpu import distrib

        store = FakeStore()
        distrib.configure_registry(lambda: store)
        try:
            m = CheckpointManager(
                str(tmp_path), tenant=Tenant(id="alpha"), keep_last=2
            )
            m.save(0, _state())
            assert "alpha" in registry.live_tenants(store)
            m.close()
            assert "alpha" not in registry.live_tenants(store)
        finally:
            distrib.configure_registry(None)


# ----------------------------------------------- two-tenant isolation


class TestTwoTenantIsolation:
    def test_interleaved_ops_fully_isolated(self, tmp_path):
        """Interleaved saves/restores/retention across two tenants on
        one bucket: disjoint trees, independent retention, both always
        restorable, fsck-clean."""
        from torchsnapshot_tpu.cli import run_fsck

        root = str(tmp_path)
        ma = CheckpointManager(root, tenant=Tenant(id="alpha"), keep_last=2)
        mb = CheckpointManager(root, tenant=Tenant(id="beta"), keep_last=1)
        ma.save(0, _state(mult=1.0))
        mb.save(0, _state(mult=2.0))
        ma.save(1, _state(mult=1.5))
        mb.save(1, _state(mult=2.5))
        ma.save(2, _state(mult=1.75))  # alpha retention evicts step 0

        # retention ran per-tenant: alpha keeps 2, beta keeps 1
        assert _steps(root, "alpha") == ["step_0000000001", "step_0000000002"]
        assert _steps(root, "beta") == ["step_0000000001"]

        got_a = _state()
        ma.restore(got_a)
        np.testing.assert_array_equal(
            got_a["model"]["w"], np.arange(1024, dtype=np.float32) * 1.75
        )
        got_b = _state()
        mb.restore(got_b)
        np.testing.assert_array_equal(
            got_b["model"]["w"], np.arange(1024, dtype=np.float32) * 2.5
        )

        # every committed step fscks clean
        for tid in ("alpha", "beta"):
            for step in _steps(root, tid):
                code, report = run_fsck(
                    os.path.join(root, "tenants", tid, step)
                )
                assert code == 0, report.findings

        # storage-tree audit: nothing outside the tenant trees and the
        # shared pool
        for name in os.listdir(root):
            assert name in ("tenants", pool.POOL_DIRNAME), name
        assert sorted(os.listdir(os.path.join(root, "tenants"))) == [
            "alpha",
            "beta",
        ]

    def test_store_keyspace_disjoint(self):
        """The same ``tsnap/`` key written under two activations lands in
        two disjoint namespaces — and reads back per-tenant."""
        raw = FakeStore()
        with activated(Tenant(id="alpha")):
            maybe_scope_store(raw).set("tsnap/journal/seed", b"a-seed")
        with activated(Tenant(id="beta")):
            maybe_scope_store(raw).set("tsnap/journal/seed", b"b-seed")
        keys = sorted(raw.data)
        assert keys == [
            "tsnap/t/alpha/journal/seed",
            "tsnap/t/beta/journal/seed",
        ]
        with activated(Tenant(id="alpha")):
            assert maybe_scope_store(raw).get("tsnap/journal/seed") == b"a-seed"

    def test_same_step_numbers_do_not_collide(self, tmp_path):
        root = str(tmp_path)
        ma = CheckpointManager(root, tenant=Tenant(id="alpha"))
        mb = CheckpointManager(root, tenant=Tenant(id="beta"))
        ma.save(7, _state(mult=1.0))
        mb.save(7, _state(mult=9.0))
        got = _state()
        ma.restore(got)
        np.testing.assert_array_equal(
            got["model"]["w"], np.arange(1024, dtype=np.float32)
        )


# --------------------------------------------------------------- quota


class TestQuota:
    def test_eviction_makes_room(self, tmp_path):
        t = Tenant(id="q", quota_bytes=12_000)  # ~2.5 steps of ~4.4 KiB
        m = CheckpointManager(str(tmp_path), tenant=t, keep_last=10)
        for s in range(4):
            m.save(s, _state())
        # the gate runs BEFORE each save's payload I/O: at save 3 the
        # three committed steps exceeded the budget, so the oldest was
        # evicted first; newest always survive
        steps = _steps(str(tmp_path), "q")
        assert steps == [
            "step_0000000001",
            "step_0000000002",
            "step_0000000003",
        ]
        # pre-I/O usage (committed minus the step just written) fit
        step3 = os.path.join(str(tmp_path), "tenants", "q", steps[-1])
        step3_bytes = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _, fs in os.walk(step3)
            for f in fs
        )
        used = quota.committed_bytes(
            os.path.join(str(tmp_path), "tenants", "q")
        )
        assert used - step3_bytes <= 12_000

    def test_raises_before_payload_io(self, tmp_path):
        t = Tenant(id="q2", quota_bytes=100)
        m = CheckpointManager(str(tmp_path), tenant=t, keep_last=10)
        m.save(0, _state())  # empty dir: gate passes at 0 used bytes
        with pytest.raises(QuotaExceededError) as ei:
            m.save(1, _state())
        assert ei.value.tenant_id == "q2"
        # no torn partial: step_1's directory was never created
        assert _steps(str(tmp_path), "q2") == ["step_0000000000"]

    def test_remote_root_quota_unenforceable(self):
        t = Tenant(id="r", quota_bytes=1000)
        m = CheckpointManager("s3://bucket/ckpt", tenant=t)
        with pytest.raises(QuotaUnenforceableError):
            quota.ensure_capacity(m)

    def test_remote_retention_skip_is_loud(self, caplog):
        import logging

        m = CheckpointManager("s3://bucket/ckpt", keep_last=2)
        telemetry.set_enabled(True)
        try:
            before = telemetry.counters().get("retention_skipped", 0)
            with caplog.at_level(logging.WARNING):
                m._apply_retention()
                m._apply_retention()
            after = telemetry.counters().get("retention_skipped", 0)
        finally:
            telemetry.set_enabled(False)
        assert after == before + 2  # counter every skip...
        warnings = [
            r for r in caplog.records if "retention skipped" in r.getMessage()
        ]
        assert len(warnings) == 1  # ...but ONE warning per manager

    def test_committed_bytes_ignores_partials(self, tmp_path):
        d = tmp_path / "t"
        (d / "step_0000000000").mkdir(parents=True)
        (d / "step_0000000000" / ".snapshot_metadata").write_bytes(b"{}")
        (d / "step_0000000000" / "payload").write_bytes(b"x" * 100)
        (d / "step_0000000001").mkdir()  # partial: no metadata
        (d / "step_0000000001" / "payload").write_bytes(b"x" * 900)
        counted = quota.committed_bytes(str(d))
        assert 100 <= counted < 1000


# ----------------------------------------------------- cross-tenant pool


class TestPool:
    def test_identical_bases_stored_once(self, tmp_path):
        """Byte accounting: two tenants' identical base payloads share
        ONE pool slot; each tenant's swept step drops to metadata-size."""
        root = str(tmp_path)
        w = np.arange(4096, dtype=np.float32)
        ma = CheckpointManager(
            root, tenant=Tenant(id="alpha"), keep_last=5, incremental=True
        )
        mb = CheckpointManager(
            root, tenant=Tenant(id="beta"), keep_last=5, incremental=True
        )
        ma.save(0, {"model": StateDict(w=w)})
        mb.save(0, {"model": StateDict(w=w)})
        assert pool.pool_bytes(root) == w.nbytes
        po_dir = os.path.join(pool.pool_root(root), "po")
        assert len(os.listdir(po_dir)) == 1  # stored exactly once
        # refcounts: one marker per (tenant, step)
        refs_dir = os.path.join(pool.pool_root(root), "refs")
        (digest_dir,) = os.listdir(refs_dir)
        assert sorted(os.listdir(os.path.join(refs_dir, digest_dir))) == [
            "alpha__step_0000000000",
            "beta__step_0000000000",
        ]
        # the swept step dirs hold no payload bytes anymore
        for tid in ("alpha", "beta"):
            d = os.path.join(root, "tenants", tid, "step_0000000000")
            on_disk = sum(
                os.path.getsize(os.path.join(dp, f))
                for dp, _, fs in os.walk(d)
                for f in fs
            )
            assert on_disk < w.nbytes / 4

    def test_restore_and_incremental_after_sweep(self, tmp_path):
        root = str(tmp_path)
        w = np.arange(4096, dtype=np.float32)
        ma = CheckpointManager(
            root, tenant=Tenant(id="alpha"), keep_last=5, incremental=True
        )
        ma.save(0, {"model": StateDict(w=w)})
        got = {"model": StateDict(w=np.zeros_like(w))}
        ma.restore(got)
        np.testing.assert_array_equal(got["model"]["w"], w)
        # a second save still dedups against the POOLED base (digest
        # fallback in dedup.py): no second full payload anywhere
        ma.save(1, {"model": StateDict(w=w)})
        d1 = os.path.join(root, "tenants", "alpha", "step_0000000001")
        on_disk = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _, fs in os.walk(d1)
            for f in fs
        )
        assert on_disk < w.nbytes / 4
        got = {"model": StateDict(w=np.zeros_like(w))}
        ma.restore(got)
        np.testing.assert_array_equal(got["model"]["w"], w)

    def test_refcounted_reclaim(self, tmp_path):
        """The pooled payload survives while ANY tenant references it
        and is unlinked exactly at refcount zero — proven by bytes."""
        root = str(tmp_path)
        w0 = np.arange(4096, dtype=np.float32)
        w1 = w0 * 3
        ma = CheckpointManager(
            root, tenant=Tenant(id="alpha"), keep_last=1, incremental=True
        )
        mb = CheckpointManager(
            root, tenant=Tenant(id="beta"), keep_last=1, incremental=True
        )
        ma.save(0, {"model": StateDict(w=w0)})
        mb.save(0, {"model": StateDict(w=w0)})
        assert pool.pool_bytes(root) == w0.nbytes
        ma.save(1, {"model": StateDict(w=w1)})  # alpha evicts step 0
        # w0 retained (beta still refs) + w1 pooled
        assert pool.pool_bytes(root) == w0.nbytes + w1.nbytes
        mb.save(1, {"model": StateDict(w=w1)})  # beta evicts step 0
        # w0's last ref released -> reclaimed; w1 shared by both
        assert pool.pool_bytes(root) == w1.nbytes
        for m, want in ((ma, w1), (mb, w1)):
            got = {"model": StateDict(w=np.zeros_like(want))}
            m.restore(got)
            np.testing.assert_array_equal(got["model"]["w"], want)

    def test_retention_does_not_freeze_on_pool_origins(self, tmp_path):
        """plan_retention must not flag pool origins unresolved (the
        pool is refcounted, not a snapshot)."""
        from torchsnapshot_tpu.retention import plan_retention

        root = str(tmp_path)
        w = np.arange(4096, dtype=np.float32)
        ma = CheckpointManager(
            root, tenant=Tenant(id="alpha"), keep_last=5, incremental=True
        )
        ma.save(0, {"model": StateDict(w=w)})
        ma.save(1, {"model": StateDict(w=w * 2)})
        plan = plan_retention(
            os.path.join(root, "tenants", "alpha"), 1
        )
        assert not plan.unresolved
        assert plan.doomed == ["step_0000000000"]


# ----------------------------------------------------------- admission


class TestAdmission:
    def test_no_tenant_is_none(self):
        assert maybe_arm("take") is None

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_ADMISSION", "0")
        assert maybe_arm("take", tenant=Tenant(id="a")) is None

    def test_share_is_priority_weighted(self):
        a = AdmissionSession(Tenant(id="a", priority=1), "take").start()
        b = AdmissionSession(Tenant(id="b", priority=4), "restore").start()
        try:
            assert a.share() == pytest.approx(0.2)
            assert b.share() == pytest.approx(0.8)
            assert a.scale_concurrency(10) == 2
            assert b.scale_concurrency(10) == 8
            assert a.scale_concurrency(1) == 1  # never starved to zero
        finally:
            a.stop()
            b.stop()

    def test_solo_share_is_full(self):
        a = AdmissionSession(Tenant(id="a", priority=1), "take").start()
        try:
            assert a.share() == 1.0
            assert a.scale_concurrency(10) == 10
        finally:
            a.stop()

    def test_stop_is_idempotent_and_rebalances(self):
        a = AdmissionSession(Tenant(id="a", priority=1), "take").start()
        b = AdmissionSession(Tenant(id="b", priority=1), "take").start()
        assert a.share() == pytest.approx(0.5)
        b.stop()
        b.stop()
        assert a.share() == 1.0
        a.stop()

    def test_admit_paces_against_measured_rate(self):
        """With a measured rate and a competing tenant, a large request
        clears the token bucket only after a proportional pause."""
        import asyncio

        from torchsnapshot_tpu.scheduler import io_governor

        a = AdmissionSession(Tenant(id="a", priority=1), "take").start()
        b = AdmissionSession(Tenant(id="b", priority=1), "take").start()
        telemetry.record_rate("write", "PaceTestPlugin", 100_000_000, 1.0)
        try:
            assert io_governor().write_bps("PaceTestPlugin")
            t0 = time.perf_counter()
            asyncio.run(a.admit(60_000_000, "write", "PaceTestPlugin"))
            wall = time.perf_counter() - t0
            # share 0.5 -> 50 MB/s allowed; 60 MB less the 0.5 s burst
            # (25 MB) paces ~0.7 s
            assert 0.3 < wall < 3.0
            assert a.paused_s > 0
        finally:
            a.stop()
            b.stop()

    def test_admit_free_when_solo(self):
        import asyncio

        a = AdmissionSession(Tenant(id="a", priority=1), "take").start()
        try:
            t0 = time.perf_counter()
            asyncio.run(a.admit(1 << 30, "write", "PaceTestPlugin"))
            assert time.perf_counter() - t0 < 0.1
        finally:
            a.stop()

    def test_admission_rows_on_store(self):
        store = FakeStore()
        a = AdmissionSession(
            Tenant(id="a", priority=2), "take", store=store
        ).start()
        rows = [k for k in store.data if k.startswith("tsnap/adm/a/")]
        assert len(rows) == 1
        a.stop()
        assert not [k for k in store.data if k.startswith("tsnap/adm/")]


# ------------------------------------------------------- SIGKILL drill


_KILLED_SAVER = r"""
import asyncio, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import StateDict
from torchsnapshot_tpu.manager import CheckpointManager
from torchsnapshot_tpu.tenancy import Tenant
from torchsnapshot_tpu.storage_plugins import fs as fs_mod

root, gate = sys.argv[1], sys.argv[2]

orig_write = fs_mod.FSStoragePlugin.write

async def gated_write(self, write_io):
    if not write_io.path.endswith((".snapshot_metadata", ".snapshot_fence")):
        await orig_write(self, write_io)
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    await orig_write(self, write_io)

fs_mod.FSStoragePlugin.write = gated_write

m = CheckpointManager(root, tenant=Tenant(id="alpha"), keep_last=3)
m.save(1, {"model": StateDict(w=np.arange(4096, dtype=np.float32))})
"""


class TestSigkillIsolation:
    def test_killed_tenant_does_not_affect_neighbor(self, tmp_path):
        """Tenant alpha's rank is SIGKILLed mid-save: beta's restore on
        the same bucket is unaffected, and alpha's partial is detectable
        (uncommitted — no metadata) and GC'd by alpha's next save."""
        root = str(tmp_path)
        w_b = np.arange(4096, dtype=np.float32) * 7
        mb = CheckpointManager(root, tenant=Tenant(id="beta"), keep_last=3)
        mb.save(0, {"model": StateDict(w=w_b)})

        gate = os.path.join(root, "gate")
        err_path = gate + ".stderr"
        with open(err_path, "wb") as err:
            proc = subprocess.Popen(
                [sys.executable, "-c", _KILLED_SAVER, root, gate],
                stdout=subprocess.DEVNULL,
                stderr=err,
            )
            deadline = time.monotonic() + 120
            while not os.path.exists(gate):
                if proc.poll() is not None:
                    with open(err_path) as f:
                        raise AssertionError(
                            "saver exited before the gate:\n" + f.read()
                        )
                if time.monotonic() > deadline:
                    proc.kill()
                    raise AssertionError("saver never reached the gate")
                time.sleep(0.01)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL

        # beta restores, oblivious
        got = {"model": StateDict(w=np.zeros_like(w_b))}
        mb.restore(got)
        np.testing.assert_array_equal(got["model"]["w"], w_b)

        # alpha's partial is detectable: step dir exists, uncommitted
        partial = os.path.join(root, "tenants", "alpha", "step_0000000001")
        assert os.path.isdir(partial)
        assert not os.path.exists(
            os.path.join(partial, ".snapshot_metadata")
        )
        from torchsnapshot_tpu.cli import run_fsck

        code, _report = run_fsck(partial)
        assert code != 0  # fsck refuses to call a torn partial clean

        # alpha's next manager GCs the rubble and saves cleanly
        ma = CheckpointManager(root, tenant=Tenant(id="alpha"), keep_last=3)
        w_a = np.arange(4096, dtype=np.float32) * 2
        ma.save(1, {"model": StateDict(w=w_a)})
        got = {"model": StateDict(w=np.zeros_like(w_a))}
        ma.restore(got)
        np.testing.assert_array_equal(got["model"]["w"], w_a)
        code, report = run_fsck(partial)
        assert code == 0, report.findings
        # beta remains untouched throughout
        got = {"model": StateDict(w=np.zeros_like(w_b))}
        mb.restore(got)
        np.testing.assert_array_equal(got["model"]["w"], w_b)


# ------------------------------------------------- quota retention unit


class TestPlanQuotaRetention:
    def _mk_step(self, d, name, nbytes):
        sd = os.path.join(d, name)
        os.makedirs(sd, exist_ok=True)
        with open(os.path.join(sd, "payload"), "wb") as f:
            f.write(b"x" * nbytes)
        import json

        with open(os.path.join(sd, ".snapshot_metadata"), "w") as f:
            f.write(
                json.dumps(
                    {"version": "0.1.0", "world_size": 1, "manifest": {}}
                )
                + "\n"
            )

    def test_drops_oldest_until_under_budget(self, tmp_path):
        d = str(tmp_path)
        for i in range(4):
            self._mk_step(d, f"step_{i:010d}", 1000)
            time.sleep(0.01)  # distinct mtimes: retention orders by them
        plan = quota.plan_quota_retention(
            d, keep=lambda names: set(names), byte_budget=2500
        )
        assert plan.doomed == ["step_0000000000", "step_0000000001"]

    def test_newest_always_survives(self, tmp_path):
        d = str(tmp_path)
        for i in range(2):
            self._mk_step(d, f"step_{i:010d}", 1000)
            time.sleep(0.01)
        plan = quota.plan_quota_retention(
            d, keep=lambda names: set(names), byte_budget=1
        )
        assert "step_0000000001" not in plan.doomed

    def test_droppable_filter_respected(self, tmp_path):
        d = str(tmp_path)
        for i in range(3):
            self._mk_step(d, f"step_{i:010d}", 1000)
            time.sleep(0.01)
        self._mk_step(d, "foreign_dir", 1000)
        plan = quota.plan_quota_retention(
            d,
            keep=lambda names: set(names),
            byte_budget=100,
            droppable=CheckpointManager._step_like,
        )
        assert "foreign_dir" not in plan.doomed
