"""``watch`` CLI + the live health plane (telemetry/health.py).

The acceptance drill: watch a live throttled w2 take end to end from a
separate process — per-rank phase/bytes render in flight, an
injected-delay straggler is flagged STALLED before any timeout fires,
and the watcher rides out a store-leader SIGKILL mid-take (the PR 6
failover schedule) without dying.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from torchsnapshot_tpu import StateDict
from torchsnapshot_tpu.telemetry import health


# ------------------------------------------------------------ unit layer


def test_tracker_flags_frozen_progress_not_frozen_seq():
    tracker = health.FleetTracker(stall_s=0.2)
    rec = {"seq": 1, "op": "take", "phase": "stage", "written_bytes": 100}
    tracker.observe({0: dict(rec)})
    time.sleep(0.25)
    # seq advances (the publisher is alive) but progress is frozen.
    rec["seq"] = 7
    ages = tracker.observe({0: dict(rec)})
    assert tracker.stalled(ages)[0] is True
    # Progress moves: the stall clears.
    rec["written_bytes"] = 200
    ages = tracker.observe({0: dict(rec)})
    assert tracker.stalled(ages)[0] is False


def test_tracker_drops_vanished_ranks():
    tracker = health.FleetTracker(stall_s=10.0)
    tracker.observe({0: {"seq": 1}, 1: {"seq": 1}})
    ages = tracker.observe({0: {"seq": 2}})
    assert set(ages) == {0}


def test_render_fleet_shows_phase_bytes_and_stall():
    fleet = {
        0: {"op": "take", "phase": "stage", "staged_bytes": 1 << 20,
            "written_bytes": 1 << 19, "seq": 3, "wall_s": 2.0},
        1: {"op": "take", "phase": "begin", "seq": 2, "wall_s": 2.5},
    }
    out = health.render_fleet(fleet, {0: 0.1, 1: 9.0}, stall_s=5.0)
    assert "stage" in out
    assert "1.0MiB" in out
    assert "STALLED" in out
    assert "stalled rank(s): 1" in out
    assert "skew" in out


def test_render_fleet_empty():
    assert "no in-flight" in health.render_fleet({}, {}, 5.0)


def test_render_fleet_shows_binding_resource():
    """ISSUE 8 satellite: a heartbeat carrying the binding-resource
    hint renders it, so a STALLED row says WHAT the rank is stuck on;
    ranks without one show a placeholder."""
    fleet = {
        0: {"op": "take", "phase": "stage", "written_bytes": 1 << 20,
            "seq": 3, "wall_s": 2.0, "binding": "storage_write"},
        1: {"op": "take", "phase": "begin", "seq": 2, "wall_s": 2.1},
    }
    out = health.render_fleet(fleet, {0: 9.0, 1: 0.1}, stall_s=5.0)
    assert "bound on" in out  # the column header
    assert "storage_write" in out
    stalled_row = [ln for ln in out.splitlines() if "STALLED" in ln][0]
    assert "storage_write" in stalled_row


def test_publisher_noop_without_store():
    class _PG:
        pg = None

        def get_world_size(self):
            return 1

        def get_rank(self):
            return 0

    assert health.maybe_start(_PG(), "take", "/tmp/x") is None


def test_heartbeat_cadence_env(monkeypatch):
    monkeypatch.setenv(health.HEARTBEAT_ENV_VAR, "2.5")
    assert health.heartbeat_cadence_s() == 2.5
    monkeypatch.setenv(health.HEARTBEAT_ENV_VAR, "junk")
    assert health.heartbeat_cadence_s() == 1.0
    monkeypatch.delenv(health.HEARTBEAT_ENV_VAR)


def test_publish_and_read_roundtrip_single_store():
    """Publisher -> store -> read_fleet over a real local KV server."""
    from torchsnapshot_tpu.dist_store import TCPStore

    store = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    try:
        health.clear()
        health.update(phase="stage", written_bytes=123, step=7)
        pub = health.HeartbeatPublisher(
            store, rank=0, op="take", path="/tmp/s", cadence_s=0.05
        ).start()
        time.sleep(0.2)
        fleet = health.read_fleet(store)
        assert 0 in fleet
        rec = fleet[0]
        assert rec["op"] == "take"
        assert rec["phase"] == "stage"
        assert rec["written_bytes"] == 123
        assert rec["step"] == 7
        assert rec["seq"] >= 2
        pub.stop()
        assert health.read_fleet(store) == {}  # key retracted on stop
    finally:
        store.close()
        health.clear()


# ------------------------------------------------- live w2 watch drill


STORE_KILL_PLAN = "dist_store.serve_op@60=kill;seed=601"


def _throttled_take_worker(rank: int, world_size: int, root: str):
    from torchsnapshot_tpu import Snapshot, faultinject as fi
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    os.environ["TORCHSNAPSHOT_TPU_HEARTBEAT_S"] = "0.1"
    os.environ["TORCHSNAPSHOT_TPU_PROGRESS_S"] = "0.15"
    # Stall forensics tuned to drill speed: sample fast, call a 0.4 s
    # frozen fingerprint a stall (the injected delay holds it for 1 s).
    os.environ["TORCHSNAPSHOT_TPU_FORENSICS_SAMPLE_S"] = "0.1"
    os.environ["TORCHSNAPSHOT_TPU_FORENSICS_STALL_S"] = "0.4"
    store = get_default_pg().store
    if rank == 0:
        # Publish the coordination-store address for the out-of-band
        # watcher (the launcher allocates the port internally).
        with open(os.path.join(root, "store_addr.txt"), "w") as f:
            f.write(store.bootstrap_addr)
    # Let the watcher connect before the take begins (it must learn the
    # replica set from live responses to survive the leader kill).
    time.sleep(0.7)
    rng = np.random.default_rng(100 + rank)
    state = {
        "model": StateDict(
            **{f"p{i}": rng.standard_normal(50_000).astype(np.float32)
               for i in range(4)}
        )
    }
    if rank == 1:
        # The straggler: every fs write stalls 1 s — comfortably past
        # the watcher's 0.5 s stall threshold even under suite load.
        fi.configure("fs.write@1+=delay:1.0")
    try:
        Snapshot.take(os.path.join(root, "cur"), state)
    finally:
        fi.disable()
    return {"failovers": store.failovers}


@pytest.mark.multiprocess
def test_watch_observes_live_take_flags_straggler_and_survives_failover(
    tmp_path,
):
    """watch renders a LIVE throttled w2 take: per-rank phase/bytes
    frames, the delay-injected rank 1 flagged STALLED, and the frames
    keep coming across a store-leader SIGKILL mid-take (one replica
    promotes; the watcher fails over like any client)."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = {}
    errors = []

    def drill():
        try:
            results.update(run_with_subprocesses(
                _throttled_take_worker,
                2,
                str(tmp_path),
                timeout=180.0,
                store_replicas=1,
                store_lease_s=0.5,
                external_store=True,
                store_host_plan=STORE_KILL_PLAN,
            ))
        except BaseException as e:  # noqa: B036
            errors.append(e)

    t = threading.Thread(target=drill)
    t.start()
    try:
        addr_file = os.path.join(str(tmp_path), "store_addr.txt")
        deadline = time.monotonic() + 60
        while not os.path.exists(addr_file):
            assert time.monotonic() < deadline, "store addr never published"
            assert t.is_alive() or not errors, errors
            time.sleep(0.05)
        addr = open(addr_file).read().strip()
        watch = subprocess.run(
            [
                sys.executable, "-m", "torchsnapshot_tpu", "watch", addr,
                "--interval", "0.15", "--stall", "0.5", "--ticks", "80",
                "--dump", "1",
            ],
            capture_output=True,
            text=True,
            timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    finally:
        t.join(timeout=120)
    assert not errors, errors
    assert watch.returncode == 0, watch.stderr[-2000:]
    out = watch.stdout
    # End-to-end: the take committed, each rank failed over exactly once
    # (the leader kill really happened mid-take).
    assert os.path.exists(tmp_path / "cur" / ".snapshot_metadata")
    for rank, res in results.items():
        assert res["failovers"] == 1, (rank, results)
    # Live per-rank rows: both ranks rendered with the take's op/phase.
    assert "take" in out
    frames = out.split("--- watch")
    rank_frames = [
        fr for fr in frames
        if "\n   0  take" in fr and "\n   1  take" in fr
    ]
    assert rank_frames, out[-3000:]
    # Bytes rendered for at least one in-flight frame (fmt_bytes units).
    assert any(("KiB" in fr or "MiB" in fr) for fr in rank_frames), out[-3000:]
    # The injected-delay straggler was flagged STALLED on its own row.
    # (Rank 0 may legitimately flag too — it freezes at the manifest
    # gather waiting for the crawling rank 1; the drill's requirement is
    # that the straggler is flagged, not that it is flagged alone.)
    def rank1_stalled(fr: str) -> bool:
        return any(
            line.lstrip().startswith("1 ") and "STALLED" in line
            for line in fr.splitlines()
        )

    assert any(rank1_stalled(fr) for fr in frames), out[-4000:]
    # Survival across the leader kill (which provably happened mid-take:
    # failovers==1 on every rank): either the watcher's own client
    # adopted the promoted leader (its store logs say so), or a degraded
    # unreachable frame was followed by a later successful one. (After
    # the JOB exits, the whole tier legitimately goes down — trailing
    # unreachable frames are the truthful render, not a failure.)
    adopted = "adopted leader" in watch.stderr
    success_idx = [
        i for i, fr in enumerate(frames)
        if "take" in fr or "no in-flight operation" in fr
    ]
    unreachable_idx = [
        i for i, fr in enumerate(frames) if "store unreachable" in fr
    ]
    recovered = bool(
        unreachable_idx
        and success_idx
        and max(success_idx) > min(unreachable_idx)
    )
    assert adopted or recovered or not unreachable_idx or (
        min(unreachable_idx) > max(success_idx)
    ), watch.stderr[-2000:]

    # --- ISSUE 13: stall forensics rode the same drill ---------------
    from torchsnapshot_tpu.telemetry import forensics

    # The stalled rank self-dumped its stacks (frozen-progress trigger:
    # the 1 s injected delay holds the fingerprint past the 0.4 s
    # window), and at least one dump catches a thread executing under
    # the injected site's category — the delay is wired at fs.write, so
    # the honest attribution is storage_write (faultinject's own frames
    # are observer-excluded).
    stacks = forensics.load_stack_dumps(str(tmp_path / "cur"))
    assert stacks.get(1), "stalled rank 1 never self-dumped its stacks"
    assert any(
        rec.get("trigger") in ("frozen-progress", "remote")
        for rec in stacks[1]
    ), [r.get("trigger") for r in stacks[1]]
    assert any(
        t.get("category") == "storage_write" and "fs.py" in (t.get("leaf") or "")
        for rec in stacks[1]
        for t in rec.get("threads", [])
    ), [t.get("leaf") for rec in stacks[1] for t in rec.get("threads", [])]
    # The remote request (--dump 1) round-tripped: the watchdog answered
    # on the store (surviving the leader kill like every client) and the
    # watcher rendered the wedged frame inline on rank 1's row.
    wedged_rows = [
        line for fr in frames for line in fr.splitlines()
        if line.lstrip().startswith("1 ") and "wedged" in line
    ]
    assert wedged_rows, out[-4000:]
    # Blackbox reads the stacks-only wreck (the take COMMITTED — no ring
    # dumps) and names the wedge: consecutive same-frame dumps earn a
    # WEDGE finding, exit 1.
    blackbox = subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu", "blackbox",
         str(tmp_path / "cur")],
        capture_output=True,
        text=True,
        timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert blackbox.returncode == 1, (blackbox.stdout, blackbox.stderr)
    assert "WEDGE" in blackbox.stdout, blackbox.stdout
    assert "storage_write" in blackbox.stdout, blackbox.stdout
