"""Mirrored (two-tier) storage: fast primary + background durable mirror.

No reference analogue. Fault injection mirrors the style of
tests/test_async_take.py (plugin subclassing).
"""

import asyncio
import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.io_types import WriteIO
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.storage_plugins.mirror import MirroredStoragePlugin


def _state(v=1.0):
    return StateDict(
        w=np.full((64, 32), v, np.float32),
        nested={"b": np.full((16,), v, np.float32)},
        step=int(v),
    )


def _opts(mirror_dir, **extra):
    return {"mirror_url": str(mirror_dir), **extra}


def test_take_commits_both_tiers(tmp_path):
    primary, mirror = tmp_path / "fast", tmp_path / "durable"
    Snapshot.take(str(primary), {"app": _state(3.0)},
                  storage_options=_opts(mirror))

    # both tiers are complete, independently restorable snapshots
    for root in (primary, mirror):
        dst = _state(0.0)
        Snapshot(str(root)).restore({"app": dst})
        np.testing.assert_array_equal(dst["w"], np.full((64, 32), 3.0, np.float32))
        assert (root / SNAPSHOT_METADATA_FNAME).exists()


def test_read_falls_back_to_mirror(tmp_path):
    primary, mirror = tmp_path / "fast", tmp_path / "durable"
    Snapshot.take(str(primary), {"app": _state(2.0)},
                  storage_options=_opts(mirror))

    # local disk loses a payload; restore through the mirrored options
    victims = [
        os.path.join(r, f)
        for r, _, fs in os.walk(primary)
        for f in fs
        if "w" in f and f != SNAPSHOT_METADATA_FNAME
    ]
    assert victims
    for v in victims:
        os.remove(v)
    dst = _state(0.0)
    Snapshot(str(primary), storage_options=_opts(mirror)).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], np.full((64, 32), 2.0, np.float32))


class _FaultyMirror(FSStoragePlugin):
    async def write(self, write_io: WriteIO) -> None:
        if write_io.path != SNAPSHOT_METADATA_FNAME:
            raise RuntimeError("mirror down")
        await super().write(write_io)


def test_mirror_failure_keeps_primary_and_never_commits_mirror(tmp_path):
    primary, mirror = tmp_path / "fast", tmp_path / "durable"
    plugin = MirroredStoragePlugin(
        primary=FSStoragePlugin(str(primary)),
        mirror=_FaultyMirror(str(mirror)),
        metadata_filename=SNAPSHOT_METADATA_FNAME,
    )
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(plugin.write(WriteIO(path="0/app/w", buf=b"abcd")))
        loop.run_until_complete(
            plugin.write(WriteIO(path=SNAPSHOT_METADATA_FNAME, buf=b"meta"))
        )
        with pytest.raises(RuntimeError, match="mirror write"):
            loop.run_until_complete(plugin.close())
    finally:
        loop.close()
    # primary complete; mirror has NO metadata (uncommitted => invisible)
    assert (primary / "0/app/w").read_bytes() == b"abcd"
    assert (primary / SNAPSHOT_METADATA_FNAME).read_bytes() == b"meta"
    assert not (mirror / SNAPSHOT_METADATA_FNAME).exists()


def test_strict_mirror_failure_raises_from_sync_take(tmp_path):
    """End-to-end through the public API: a strict-mode mirror failure is
    raised at storage close, and synchronous ``Snapshot.take`` must
    PROPAGATE it — a caller relying on ``mirror_strict=True`` (the
    default) may delete primary tiers believing the durable mirror
    landed. Regression: the close-error guard in take()'s finally block
    read ``sys.exc_info()`` inside the except handler, where it is the
    just-caught close exception, so the raise never fired."""
    primary = tmp_path / "fast"
    bad_mirror = tmp_path / "durable"
    bad_mirror.write_bytes(b"not a directory")  # every mirror write fails

    with pytest.raises(RuntimeError, match="mirror write"):
        Snapshot.take(str(primary), {"app": _state(7.0)},
                      storage_options=_opts(bad_mirror))

    # the primary tier committed before close — it remains restorable
    dst = _state(0.0)
    Snapshot(str(primary)).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], np.full((64, 32), 7.0, np.float32))

    # non-strict: same failure is demoted to a warning; take succeeds
    primary2 = tmp_path / "fast2"
    Snapshot.take(str(primary2), {"app": _state(8.0)},
                  storage_options=_opts(bad_mirror, mirror_strict=False))

    # checkpoint-on-error pattern: take() called from INSIDE an except
    # handler. The close-error guard must not mistake the caller's
    # ambient exception for an in-flight take failure and swallow the
    # strict-mirror error.
    try:
        raise ValueError("ambient caller exception")
    except ValueError:
        with pytest.raises(RuntimeError, match="mirror write"):
            Snapshot.take(str(tmp_path / "fast3"), {"app": _state(9.0)},
                          storage_options=_opts(bad_mirror))


def test_mirror_failure_nonstrict_warns_only(tmp_path):
    primary, mirror = tmp_path / "fast", tmp_path / "durable"
    plugin = MirroredStoragePlugin(
        primary=FSStoragePlugin(str(primary)),
        mirror=_FaultyMirror(str(mirror)),
        metadata_filename=SNAPSHOT_METADATA_FNAME,
        strict=False,
    )
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(plugin.write(WriteIO(path="x", buf=b"1")))
        loop.run_until_complete(plugin.close())  # no raise
    finally:
        loop.close()


class _SlowMirror(FSStoragePlugin):
    """Records the peak number of concurrently retained mirror buffers."""

    def __init__(self, root, delay_s=0.02):
        super().__init__(root)
        self.delay_s = delay_s

    async def write(self, write_io: WriteIO) -> None:
        await asyncio.sleep(self.delay_s)
        await super().write(write_io)


def test_backlog_backpressure_bounds_retained_bytes(tmp_path):
    primary, mirror = tmp_path / "fast", tmp_path / "durable"
    plugin = MirroredStoragePlugin(
        primary=FSStoragePlugin(str(primary)),
        mirror=_SlowMirror(str(mirror)),
        metadata_filename=SNAPSHOT_METADATA_FNAME,
        backlog_bytes=3000,  # three 1 KB payloads in flight at most
    )
    peak = 0

    async def run():
        nonlocal peak

        async def one(i):
            nonlocal peak
            await plugin.write(WriteIO(path=f"p{i}", buf=b"x" * 1000))
            peak = max(peak, plugin._backlog_bytes)

        await asyncio.gather(*(one(i) for i in range(12)))
        await plugin.close()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(run())
    finally:
        loop.close()
    assert peak <= 3000
    for i in range(12):
        assert (mirror / f"p{i}").read_bytes() == b"x" * 1000


def test_async_take_with_mirror(tmp_path):
    primary, mirror = tmp_path / "fast", tmp_path / "durable"
    pending = Snapshot.async_take(
        str(primary), {"app": _state(5.0)}, storage_options=_opts(mirror)
    )
    pending.wait()
    # by wait() time BOTH tiers are committed
    for root in (primary, mirror):
        dst = _state(0.0)
        Snapshot(str(root)).restore({"app": dst})
        np.testing.assert_array_equal(dst["w"], np.full((64, 32), 5.0, np.float32))


def test_incremental_take_with_mirror_strips_mirror_for_base(tmp_path):
    """Mirror options name THIS snapshot's mirror; base/origin reads must
    not be wrapped with it (a wrong fallback root). The combination
    incremental + mirror works end to end and the mirror tier of the
    incremental is itself restorable (its entries reference the base)."""
    base_p, base_m = str(tmp_path / "b_fast"), str(tmp_path / "b_durable")
    inc_p, inc_m = str(tmp_path / "i_fast"), str(tmp_path / "i_durable")
    Snapshot.take(base_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": base_m},
                  record_digests=True)
    Snapshot.take(inc_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": inc_m},
                  incremental_base=base_p)

    # frozen payloads not rewritten in either tier of the incremental
    for root in (inc_p, inc_m):
        payload_files = [
            f for r, _, fs in os.walk(root) for f in fs
            if f != SNAPSHOT_METADATA_FNAME
        ]
        assert not any("w" in f for f in payload_files), (root, payload_files)

    # restore from the incremental's mirror tier (base primary intact)
    dst = _state(0.0)
    Snapshot(inc_m).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], np.full((64, 32), 1.0, np.float32))


def test_incremental_mirror_survives_total_primary_loss(tmp_path):
    """Machine-loss disaster recovery for an incremental chain: every
    snapshot records its mirror in metadata and propagates origin->mirror
    mappings, so restoring from an incremental's MIRROR falls back to the
    base's MIRROR for deduplicated payloads after BOTH primaries are
    gone."""
    import shutil

    base_p, base_m = str(tmp_path / "b_fast"), str(tmp_path / "b_durable")
    inc_p, inc_m = str(tmp_path / "i_fast"), str(tmp_path / "i_durable")
    Snapshot.take(base_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": base_m}, record_digests=True)
    Snapshot.take(inc_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": inc_m},
                  incremental_base=base_p)

    meta = Snapshot(inc_m).metadata
    assert meta.origin_mirrors, "origin->mirror mapping must be recorded"
    from torchsnapshot_tpu.dedup import canonical_base_url

    assert meta.origin_mirrors.get(canonical_base_url(base_p)) == canonical_base_url(base_m)

    # the machine dies: both fast tiers are gone
    shutil.rmtree(base_p)
    shutil.rmtree(inc_p)

    dst = _state(0.0)
    Snapshot(inc_m).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], np.full((64, 32), 1.0, np.float32))
    np.testing.assert_array_equal(dst["nested"]["b"], np.full((16,), 1.0, np.float32))


def test_chained_origin_mirrors_propagate(tmp_path):
    """A -> B -> C chain, all mirrored: C's metadata carries A's mirror
    mapping (payloads written once in A are referenced directly), so C's
    mirror restores after every primary is gone."""
    import shutil

    paths = {}
    for name in "abc":
        paths[name] = (str(tmp_path / f"{name}_fast"), str(tmp_path / f"{name}_dur"))

    def chain_state(head_val):
        # frozen backbone identical across the chain; head trains
        return StateDict(
            frozen=np.arange(512, dtype=np.float32).reshape(32, 16),
            head=np.full((8,), float(head_val), np.float32),
            step=int(head_val),
        )

    Snapshot.take(paths["a"][0], {"app": chain_state(1)},
                  storage_options={"mirror_url": paths["a"][1]},
                  record_digests=True)
    Snapshot.take(paths["b"][0], {"app": chain_state(2)},
                  storage_options={"mirror_url": paths["b"][1]},
                  incremental_base=paths["a"][0])
    Snapshot.take(paths["c"][0], {"app": chain_state(3)},
                  storage_options={"mirror_url": paths["c"][1]},
                  incremental_base=paths["b"][0])

    from torchsnapshot_tpu.dedup import canonical_base_url

    meta_c = Snapshot(paths["c"][1]).metadata
    assert canonical_base_url(paths["a"][0]) in (meta_c.origin_mirrors or {})

    for name in "abc":
        shutil.rmtree(paths[name][0])

    dst = StateDict(
        frozen=np.zeros((32, 16), np.float32),
        head=np.zeros((8,), np.float32),
        step=0,
    )
    Snapshot(paths["c"][1]).restore({"app": dst})
    # frozen was written once, in A — read from A's MIRROR; head from C's
    np.testing.assert_array_equal(
        dst["frozen"], np.arange(512, dtype=np.float32).reshape(32, 16)
    )
    np.testing.assert_array_equal(dst["head"], np.full((8,), 3.0, np.float32))
    assert dst["step"] == 3


def _mirror_worker(rank, world_size, primary_dir, mirror_dir):
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    state = {
        "model": StateDict(w=np.arange(128, dtype=np.float32)),
        "local": StateDict(r=np.full((4,), rank, np.int32)),
    }
    Snapshot.take(
        primary_dir, state, replicated=["model/*"],
        storage_options={"mirror_url": mirror_dir},
    )
    return "ok"


@pytest.mark.multiprocess
def test_multiprocess_mirror_commit_is_complete(tmp_path):
    """Every rank's payload mirrors drain before the commit barrier, so
    the mirror metadata never publishes a mirror missing a rank's data."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    primary, mirror = str(tmp_path / "fast"), str(tmp_path / "durable")
    results = run_with_subprocesses(_mirror_worker, 2, primary, mirror)
    assert all(v == "ok" for v in results.values())

    # the mirror restores completely for both ranks' views
    for rank in range(2):
        dst = {
            "model": StateDict(w=np.zeros(128, np.float32)),
            "local": StateDict(r=np.zeros((4,), np.int32)),
        }
        import subprocess
        import sys

        code = f"""
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.manifest import get_available_entries
meta = Snapshot({mirror!r}).metadata
avail = get_available_entries(meta.manifest, {rank})
assert "model/w" in avail and "local/r" in avail
state = Snapshot({mirror!r}).read_state_dict(rank={rank})
np.testing.assert_array_equal(state["model"]["w"], np.arange(128, dtype=np.float32))
np.testing.assert_array_equal(state["local"]["r"], np.full((4,), {rank}, np.int32))
print("MIRROR-RANK-OK")
"""
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=300
        )
        assert r.returncode == 0, r.stderr
        assert "MIRROR-RANK-OK" in r.stdout


def test_consolidate_after_primary_loss(tmp_path):
    """Consolidation reads origin payloads through the recorded mirrors,
    so an incremental chain can be flattened into a standalone snapshot
    even after every primary tier is gone."""
    import shutil

    from torchsnapshot_tpu.dedup import consolidate

    base_p, base_m = str(tmp_path / "b_fast"), str(tmp_path / "b_dur")
    inc_p, inc_m = str(tmp_path / "i_fast"), str(tmp_path / "i_dur")
    Snapshot.take(base_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": base_m}, record_digests=True)
    Snapshot.take(inc_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": inc_m},
                  incremental_base=base_p)
    shutil.rmtree(base_p)
    shutil.rmtree(inc_p)

    flat = str(tmp_path / "flat")
    consolidate(inc_m, flat)
    meta = Snapshot(flat).metadata
    assert meta.origin_mirrors is None and meta.mirror_url is None

    shutil.rmtree(base_m)  # standalone: no tier of the chain needed
    dst = _state(0.0)
    Snapshot(flat).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], np.full((64, 32), 1.0, np.float32))


def test_cli_verify_and_info_follow_origin_mirrors(tmp_path, capsys):
    import shutil

    from torchsnapshot_tpu.cli import main

    base_p, base_m = str(tmp_path / "b_fast"), str(tmp_path / "b_dur")
    inc_p, inc_m = str(tmp_path / "i_fast"), str(tmp_path / "i_dur")
    Snapshot.take(base_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": base_m}, record_digests=True)
    Snapshot.take(inc_p, {"app": _state(1.0)},
                  storage_options={"mirror_url": inc_m},
                  incremental_base=base_p)

    assert main(["info", inc_m]) == 0
    out = capsys.readouterr().out
    assert "restore\nsurvives" in out.replace("\n             ", "\n") or \
        "survives loss" in out

    # after total primary loss, verify still passes via the origin mirrors
    shutil.rmtree(base_p)
    shutil.rmtree(inc_p)
    assert main(["verify", inc_m]) == 0
    assert ", 0 failed" in capsys.readouterr().out
