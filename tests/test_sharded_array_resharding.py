"""Resharding matrix tests on an 8-device virtual CPU mesh
(reference: tests/test_sharded_tensor_resharding.py:76-108 and
tests/gpu_tests/test_torchrec.py:170-241).

save-spec x restore-spec: every pair must round-trip bit-exactly, including
mesh-shape changes, partial replication subgroups, and sharded->plain-array
restores.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.io_preparers.sharded import ShardedArrayIOPreparer
from torchsnapshot_tpu.io_preparers.prepare import is_sharded_jax_array

SHAPE = (16, 24)


def _mesh_and_spec(kind: str):
    devs = np.array(jax.devices()[:8])
    if kind == "1d_row":
        return Mesh(devs.reshape(8), ("x",)), P("x", None)
    if kind == "1d_col":
        return Mesh(devs.reshape(8), ("x",)), P(None, "x")
    if kind == "2d":
        return Mesh(devs.reshape(4, 2), ("x", "y")), P("x", "y")
    if kind == "2d_flip":
        return Mesh(devs.reshape(2, 4), ("x", "y")), P("y", "x")
    if kind == "partial_repl":
        # sharded over x, replicated over y — shard duplication across devices
        return Mesh(devs.reshape(4, 2), ("x", "y")), P("x", None)
    if kind == "combined":
        return Mesh(devs.reshape(4, 2), ("x", "y")), P(("x", "y"), None)
    raise ValueError(kind)


def _make_sharded(kind: str, seed: int = 0):
    mesh, spec = _mesh_and_spec(kind)
    data = np.random.default_rng(seed).standard_normal(SHAPE).astype(np.float32)
    sharding = NamedSharding(mesh, spec)
    return jax.device_put(jnp.asarray(data), sharding), data


SPECS = ["1d_row", "1d_col", "2d", "2d_flip", "partial_repl", "combined"]


@pytest.mark.parametrize("src_kind", SPECS)
@pytest.mark.parametrize("dst_kind", SPECS)
def test_resharding_matrix(tmp_path, src_kind, dst_kind) -> None:
    arr, data = _make_sharded(src_kind, seed=0)
    assert is_sharded_jax_array(arr)
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(emb=arr)})

    dst_arr, _ = _make_sharded(dst_kind, seed=1)
    dst = StateDict(emb=dst_arr)
    snapshot.restore({"m": dst})
    restored = dst["emb"]
    assert isinstance(restored, jax.Array)
    assert restored.sharding.is_equivalent_to(dst_arr.sharding, len(SHAPE))
    np.testing.assert_array_equal(np.asarray(restored), data)


@pytest.mark.parametrize("src_kind", ["1d_row", "2d", "partial_repl"])
def test_sharded_to_plain_restore(tmp_path, src_kind) -> None:
    """ShardedArray -> numpy destination (reference: io_preparer.py:330-342)."""
    arr, data = _make_sharded(src_kind, seed=0)
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(emb=arr)})
    dst = StateDict(emb=np.zeros(SHAPE, dtype=np.float32))
    snapshot.restore({"m": dst})
    np.testing.assert_array_equal(dst["emb"], data)


@pytest.mark.parametrize("src_kind", ["1d_row", "2d"])
def test_read_object_sharded_gather(tmp_path, src_kind) -> None:
    """read_object gathers a sharded entry into a full array
    (reference: tests/test_read_object.py:132-140)."""
    arr, data = _make_sharded(src_kind, seed=0)
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(emb=arr)})
    out = snapshot.read_object("0/m/emb")
    np.testing.assert_array_equal(out, data)


def test_plain_to_sharded_restore(tmp_path) -> None:
    """Replicated/plain-saved array restored into a sharded destination."""
    data = np.random.default_rng(0).standard_normal(SHAPE).astype(np.float32)
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=data)})
    dst_arr, _ = _make_sharded("2d", seed=1)
    dst = StateDict(w=dst_arr)
    snapshot.restore({"m": dst})
    restored = dst["w"]
    assert restored.sharding.is_equivalent_to(dst_arr.sharding, len(SHAPE))
    np.testing.assert_array_equal(np.asarray(restored), data)


def test_shard_dedup_with_replication_subgroup(tmp_path) -> None:
    """With P('x', None) on a (4,2) mesh each shard is held by 2 devices —
    exactly 4 unique shards must be written, not 8 (SURVEY §7 hard-parts:
    dedupe writers)."""
    arr, _ = _make_sharded("partial_repl", seed=0)
    entry, write_reqs = ShardedArrayIOPreparer.prepare_write("sharded/m/emb", arr)
    assert len(write_reqs) == 4
    assert len(entry.shards) == 4
    offsets = sorted(tuple(s.offsets) for s in entry.shards)
    assert offsets == [(0, 0), (4, 0), (8, 0), (12, 0)]


def test_shard_subdivision(tmp_path) -> None:
    """Shards above the max size are subdivided along the largest dim
    (reference white-box pattern: tests/gpu_tests/test_torchrec.py:202-212)."""
    arr, data = _make_sharded("1d_row", seed=0)
    old = ShardedArrayIOPreparer.max_shard_size_bytes
    ShardedArrayIOPreparer.max_shard_size_bytes = 100  # < 2*24*4 bytes per shard
    try:
        snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(emb=arr)})
        entry = snapshot.get_manifest()["0/m/emb"]
        assert len(entry.shards) > 8
        for shard in entry.shards:
            nbytes = int(np.prod(shard.sizes)) * 4
            assert nbytes <= 100 or min(shard.sizes) == 1
        dst_arr, _ = _make_sharded("2d", seed=1)
        dst = StateDict(emb=dst_arr)
        snapshot.restore({"m": dst})
        np.testing.assert_array_equal(np.asarray(dst["emb"]), data)
    finally:
        ShardedArrayIOPreparer.max_shard_size_bytes = old


def test_mesh_shape_change(tmp_path) -> None:
    """Save on an 8-way 1-D mesh, restore on a (2,4) mesh with transposed
    axis assignment — simulates moving a checkpoint between pod slices."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs.reshape(8), ("x",))
    data = np.random.default_rng(0).standard_normal((16, 12)).astype(np.float32)
    arr = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", None)))
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=arr)})
    mesh2 = Mesh(devs.reshape(2, 4), ("a", "b"))
    dst_arr = jax.device_put(
        jnp.zeros((16, 12)), NamedSharding(mesh2, P("b", "a"))
    )
    dst = StateDict(w=dst_arr)
    snapshot.restore({"m": dst})
    np.testing.assert_array_equal(np.asarray(dst["w"]), data)


def test_overlap_math_uneven_boxes() -> None:
    """The overlap computation supports arbitrary (incl. uneven/unaligned)
    shard boxes, beyond what jax shardings can currently express."""
    from torchsnapshot_tpu.io_preparers.sharded import _overlap, _subdivide

    # saved shard rows [5, 13) x cols [0, 5); dest box rows [0, 8) x cols [2, 5)
    ov = _overlap([5, 0], [8, 5], ((0, 8), (2, 5)))
    assert ov is not None
    src, dst = ov
    assert src == (slice(0, 3), slice(2, 5))
    assert dst == (slice(5, 8), slice(0, 3))
    # disjoint
    assert _overlap([8, 0], [5, 5], ((0, 8), (0, 5))) is None
    # subdivision along the largest dim, uneven tail
    pieces = _subdivide([4, 0], [13, 5], itemsize=4, max_bytes=5 * 4 * 4)
    assert all(sz[0] <= 4 for _, sz in pieces)
    assert sum(sz[0] for _, sz in pieces) == 13
    assert pieces[0][0] == [4, 0] and pieces[-1][0][0] + pieces[-1][1][0] == 17


def test_bf16_sharded_roundtrip(tmp_path) -> None:
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs.reshape(8), ("x",))
    data = np.random.default_rng(0).standard_normal((32, 8)).astype(jnp.bfloat16)
    arr = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", None)))
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=arr)})
    dst = StateDict(w=jax.device_put(jnp.zeros((32, 8), dtype=jnp.bfloat16),
                                     NamedSharding(mesh, P(None, "x"))))
    snapshot.restore({"m": dst})
    assert np.asarray(dst["w"]).tobytes() == np.asarray(data).tobytes()


@pytest.mark.parametrize("src_kind,dst_kind", [("1d_row", "2d"), ("2d_flip", "1d_col")])
def test_async_take_reshards(tmp_path, src_kind, dst_kind) -> None:
    """async_take of sharded arrays + restore into a different sharding —
    the async path must compose with resharding like the sync path."""
    arr, data = _make_sharded(src_kind, seed=3)
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"m": StateDict(emb=arr)})
    snapshot = pending.wait()
    dst_arr, _ = _make_sharded(dst_kind, seed=4)
    dst = StateDict(emb=dst_arr)
    snapshot.restore({"m": dst})
    np.testing.assert_array_equal(np.asarray(dst["emb"]), data)


def test_writer_election_balances_across_holders():
    """_stable_owner's hash election must spread boxes roughly evenly
    across holder processes (the docstring's 'load-spreading' claim):
    with B boxes and H holders each holder should own ~B/H, never 0 and
    never a dominating share. Also deterministic across call order."""
    from torchsnapshot_tpu.io_preparers.sharded import _stable_owner

    holders = [0, 1, 2, 3]
    boxes = [
        ((r * 7, r * 7 + 7), (c * 13, c * 13 + 13))
        for r in range(32)
        for c in range(16)
    ]  # 512 distinct boxes
    counts = {h: 0 for h in holders}
    for box in boxes:
        owner = _stable_owner(box, holders)
        assert owner == _stable_owner(box, list(reversed(holders)))  # det.
        counts[owner] += 1
    expected = len(boxes) / len(holders)  # 128
    for h, n in counts.items():
        assert 0.6 * expected <= n <= 1.4 * expected, counts
