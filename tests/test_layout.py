"""Partition-rule layout compiler (ISSUE 12): rule matching, spec
compilation, serialization, and the DEVICE-FREE box geometry pinned
against jax's real ``NamedSharding.devices_indices_map`` — the planner
and the ``tstpu plan`` dry-run trust ``LayoutSpec.boxes_for`` to
reproduce exactly what jax will do at restore time."""

from __future__ import annotations

import numpy as np
import pytest

from torchsnapshot_tpu.layout import (
    LAYOUT_FORMAT_VERSION,
    LayoutSpec,
    Rule,
    resolve_layout,
)


def _spec2():
    return LayoutSpec(
        [("x", 2), ("y", 4)],
        [
            Rule.of(r"attention/(wq|wk|wv)/kernel$", [None, "y"], dtype="bfloat16"),
            Rule.of(r"attention/.*", ["y", None]),
            Rule.of(r"mlp/w_in", [None, ("x", "y")]),
            Rule.of(r"bias$", [None]),
        ],
    )


# ---------------------------------------------------------------- matching


def test_first_matching_rule_wins() -> None:
    spec = _spec2()
    # 'attention/wq/kernel' matches rule 0 AND rule 1; rule 0 wins.
    rule = spec.match("model/attention/wq/kernel")
    assert rule is not None and rule.dtype == "bfloat16"
    assert spec.spec_for("model/attention/wq/kernel", 2) == ((), ("y",))
    # 'attention/out' only matches the catch-all attention rule.
    assert spec.spec_for("model/attention/out", 2) == (("y",), ())
    # re.search semantics: the pattern may match anywhere in the path.
    assert spec.match("deep/nested/mlp/w_in/kernel") is not None


def test_unmatched_path_is_replicated() -> None:
    spec = _spec2()
    assert spec.match("model/step") is None
    assert spec.spec_for("model/step", 0) == ()
    assert spec.spec_for("model/embedding", 3) == ((), (), ())
    assert spec.dtype_for("model/step") is None


def test_spec_padding_and_overlong() -> None:
    spec = _spec2()
    # Shorter spec pads with replicated dims.
    assert spec.spec_for("model/attention/out", 4) == (("y",), (), (), ())
    # Longer spec with only-replicated extras truncates silently...
    assert spec.spec_for("model/bias", 0) == ()
    # ...but dropping a PARTITIONED dim is an error.
    with pytest.raises(ValueError, match="spec dims"):
        spec.spec_for("model/attention/out", 0)


def test_match_partition_rules_idiom() -> None:
    spec = _spec2()
    compiled = spec.match_partition_rules(
        {"a/attention/wq/kernel": 2, "a/mlp/w_in": 2, "a/step": 0}
    )
    assert compiled == {
        "a/attention/wq/kernel": ((), ("y",)),
        "a/mlp/w_in": ((), ("x", "y")),
        "a/step": (),
    }


def test_dtype_policy() -> None:
    spec = _spec2()
    assert spec.dtype_for("m/attention/wq/kernel") == "bfloat16"
    assert spec.dtype_for("m/attention/out") is None


# ------------------------------------------------------------- validation


def test_mesh_validation() -> None:
    with pytest.raises(ValueError, match="at least one"):
        LayoutSpec([])
    with pytest.raises(ValueError, match="size 0"):
        LayoutSpec([("x", 0)])
    with pytest.raises(ValueError, match="duplicate"):
        LayoutSpec([("x", 2), ("x", 4)])
    with pytest.raises(ValueError, match="unknown mesh axis"):
        LayoutSpec([("x", 2)], [Rule.of("w", ["z"])])


def test_dict_round_trip() -> None:
    spec = _spec2()
    d = spec.to_dict()
    assert d["version"] == LAYOUT_FORMAT_VERSION
    back = LayoutSpec.from_dict(d)
    assert back.mesh_axes == spec.mesh_axes
    assert back.rules == spec.rules
    assert back.to_dict() == d
    # dtype is omitted when unset, kept when set.
    assert "dtype" not in d["rules"][1]
    assert d["rules"][0]["dtype"] == "bfloat16"


def test_version_gate() -> None:
    d = _spec2().to_dict()
    d["version"] = LAYOUT_FORMAT_VERSION + 1
    with pytest.raises(ValueError, match="newer"):
        LayoutSpec.from_dict(d)


def test_resolve_layout() -> None:
    assert resolve_layout(None) is None
    spec = _spec2()
    assert resolve_layout(spec) == spec.to_dict()
    assert resolve_layout(spec.to_dict()) == spec.to_dict()
    with pytest.raises(TypeError, match="LayoutSpec or dict"):
        resolve_layout(42)
    # Malformed dicts fail eagerly (at take time, not a later plan).
    with pytest.raises(ValueError, match="unknown mesh axis"):
        resolve_layout(
            {"version": 1, "mesh": [["x", 2]],
             "rules": [{"pattern": "w", "spec": [["nope"]]}]}
        )


# -------------------------------------------------- device-free geometry


def test_boxes_replicated_spec() -> None:
    spec = _spec2()
    boxes = spec.boxes_for((6, 5), ())
    assert len(boxes) == 8
    assert all(b == ((0, 6), (0, 5)) for b in boxes)


def test_boxes_single_axis_rows() -> None:
    spec = LayoutSpec([("x", 4)])
    boxes = spec.boxes_for((8, 3), [("x",)])
    assert boxes == [
        ((0, 2), (0, 3)),
        ((2, 4), (0, 3)),
        ((4, 6), (0, 3)),
        ((6, 8), (0, 3)),
    ]


def test_boxes_ceil_division_tail() -> None:
    # 10 rows over 4 shards: ceil(10/4)=3 -> 3,3,3,1 (jax's tiling).
    spec = LayoutSpec([("x", 4)])
    boxes = spec.boxes_for((10, 2), [("x",)])
    assert [b[0] for b in boxes] == [(0, 3), (3, 6), (6, 9), (9, 10)]


def test_boxes_empty_shard_rejected() -> None:
    # 4 rows over 8 shards would leave empty shards.
    spec = LayoutSpec([("x", 8)])
    with pytest.raises(ValueError, match="non-empty"):
        spec.boxes_for((4, 4), [("x",)])


def test_boxes_by_rank_dedups_replicas() -> None:
    # Dim 0 split over x only: the 4 y-devices per x-coord hold the SAME
    # box, so each rank's distinct-box list collapses.
    spec = _spec2()  # x=2, y=4 -> 8 devices
    by_rank = spec.boxes_by_rank((8, 4), [("x",), ()], world_size=2)
    assert by_rank == {0: [((0, 4), (0, 4))], 1: [((4, 8), (0, 4))]}
    # world=8 (1 device/rank): same boxes, one per rank.
    by_rank8 = spec.boxes_by_rank((8, 4), [("x",), ()], world_size=8)
    assert all(len(v) == 1 for v in by_rank8.values())
    assert by_rank8[0] == by_rank8[3] == [((0, 4), (0, 4))]
    assert by_rank8[4] == by_rank8[7] == [((4, 8), (0, 4))]


def test_rank_of_device_requires_divisibility() -> None:
    spec = _spec2()
    assert [spec.rank_of_device(d, 2) for d in range(8)] == [0] * 4 + [1] * 4
    with pytest.raises(ValueError, match="do not divide"):
        spec.rank_of_device(0, 3)


# ------------------------------------------------ pinned against real jax
#
# conftest.py forces 8 host CPU devices, so the jax helpers run
# in-process; every spec below must produce byte-identical geometry from
# the device-free compiler and from jax's devices_indices_map.

_JAX_CASES = [
    ((16, 8), [("x",), ()]),
    ((16, 8), [(), ("y",)]),
    ((16, 8), [("x", "y"), ()]),
    ((16, 8), [("y",), ("x",)]),
    ((12, 8), [("y",), ()]),  # non-power-of-two rows
    # (uneven dims are exercised device-free in
    # test_boxes_ceil_division_tail: this jax build's
    # devices_indices_map rejects non-dividing shapes outright)
    ((16, 8), []),  # fully replicated
    ((12, 6, 4), [("y",), (), ("x",)]),
]


def _normalize_indices(idx, shape):
    out = []
    for sl, dim in zip(idx, shape):
        lo = 0 if sl.start is None else sl.start
        hi = dim if sl.stop is None else sl.stop
        out.append((lo, hi))
    return tuple(out)


@pytest.mark.parametrize("shape,spec", _JAX_CASES)
def test_boxes_match_jax_named_sharding(shape, spec) -> None:
    jax = pytest.importorskip("jax")
    layout = _spec2()
    order = {dev: i for i, dev in enumerate(jax.devices())}
    mesh = layout.build_mesh()
    sharding = layout.named_sharding(spec, mesh=mesh)
    jax_boxes = {
        order[dev]: _normalize_indices(idx, shape)
        for dev, idx in sharding.devices_indices_map(tuple(shape)).items()
    }
    ours = layout.boxes_for(shape, spec)
    assert jax_boxes == {d: ours[d] for d in range(len(ours))}


def test_shardings_for_whole_tree() -> None:
    jax = pytest.importorskip("jax")  # noqa: F841
    layout = _spec2()
    mesh = layout.build_mesh()
    shardings = layout.shardings_for(
        {"m/attention/out": 2, "m/step": 0}, mesh=mesh
    )
    assert set(shardings) == {"m/attention/out", "m/step"}
    # The sharding geometry agrees with the compiled spec's boxes.
    got = {
        dev: _normalize_indices(idx, (16, 8))
        for dev, idx in shardings["m/attention/out"]
        .devices_indices_map((16, 8))
        .items()
    }
    order = {dev: i for i, dev in enumerate(jax.devices())}
    ours = layout.boxes_for((16, 8), layout.spec_for("m/attention/out", 2))
    assert {order[d]: b for d, b in got.items()} == dict(enumerate(ours))


# ----------------------------------------------- recorded in the snapshot


def test_take_records_layout_in_metadata(tmp_path) -> None:
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.manifest import SnapshotMetadata

    spec = LayoutSpec([("x", 2)], [Rule.of("w", ["x"])])
    state = {"model": StateDict(w=np.arange(32, dtype=np.float32))}
    Snapshot.take(str(tmp_path / "snap"), state, layout=spec)
    with open(str(tmp_path / "snap" / ".snapshot_metadata")) as f:
        metadata = SnapshotMetadata.from_yaml(f.read())
    assert metadata.layout == spec.to_dict()
    # Round trip: the recorded dict rebuilds the rule set.
    back = LayoutSpec.from_dict(metadata.layout)
    assert back.rules == spec.rules

    # No layout -> no key in the metadata at all.
    Snapshot.take(str(tmp_path / "plain"), state)
    with open(str(tmp_path / "plain" / ".snapshot_metadata")) as f:
        raw = f.read()
    assert "layout" not in raw
    assert SnapshotMetadata.from_yaml(raw).layout is None


def test_take_rejects_malformed_layout(tmp_path) -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    state = {"model": StateDict(w=np.arange(8, dtype=np.float32))}
    with pytest.raises((TypeError, ValueError)):
        Snapshot.take(str(tmp_path / "bad"), state, layout="tp4")
    # The failed take must not have committed anything.
    import os

    assert not os.path.exists(str(tmp_path / "bad" / ".snapshot_metadata"))
