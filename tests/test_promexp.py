"""Live OpenMetrics exporter (telemetry/promexp.py): exposition
validity, the env-gated lifecycle, and the ISSUE 8 acceptance — a live
/metrics scrape DURING an in-flight take parses clean under
prometheus_client's strict OpenMetrics parser and includes at least one
histogram family.
"""

import asyncio
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, telemetry
from torchsnapshot_tpu.telemetry import promexp


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(promexp.METRICS_PORT_ENV_VAR, raising=False)
    telemetry.set_enabled(False)
    telemetry.reset()
    telemetry.health.clear()
    yield
    promexp.stop_exporter()
    telemetry.set_enabled(False)
    telemetry.reset()
    telemetry.health.clear()


def _parse_openmetrics(text):
    """Families via prometheus_client's strict parser, or None when the
    package is absent (the regex-free authoritative check)."""
    try:
        from prometheus_client.openmetrics import parser
    except ImportError:
        return None
    return list(parser.text_string_to_metric_families(text))


# -------------------------------------------------------------- rendering


def test_render_live_empty_is_valid():
    out = promexp.render_live()
    assert out.endswith("# EOF\n")
    families = _parse_openmetrics(out)
    if families is not None:
        assert families == []


def test_render_live_counters_gauges_histograms_heartbeat():
    telemetry.set_enabled(True)
    telemetry.counter_add("bytes_written", 123)
    telemetry.gauge_set("budget_free_bytes", 7.5)
    telemetry.histogram_observe("write.entry_s", 0.02, key="FSStoragePlugin")
    telemetry.health.update(
        op="take", phase="stage", written_bytes=64, binding="storage_write"
    )
    out = promexp.render_live(rank=3)
    assert 'torchsnapshot_tpu_bytes_written_total{rank="3"} 123' in out
    assert 'torchsnapshot_tpu_budget_free_bytes{rank="3"} 7.5' in out
    assert "torchsnapshot_tpu_write_entry_s_bucket" in out
    assert 'key="FSStoragePlugin"' in out
    assert 'phase="stage"' in out
    assert 'binding="storage_write"' in out
    assert 'torchsnapshot_tpu_heartbeat_written_bytes{rank="3"} 64' in out
    families = _parse_openmetrics(out)
    if families is not None:
        by_name = {f.name: f for f in families}
        assert by_name["torchsnapshot_tpu_write_entry_s"].type == "histogram"
        assert by_name["torchsnapshot_tpu_bytes_written"].type == "counter"


# --------------------------------------------------------------- lifecycle


def test_maybe_start_requires_env():
    assert promexp.maybe_start() is None
    assert promexp.active_exporter() is None


def test_maybe_start_bad_port_value(monkeypatch):
    monkeypatch.setenv(promexp.METRICS_PORT_ENV_VAR, "not-a-port")
    assert promexp.maybe_start() is None


def test_exporter_serves_and_is_idempotent(monkeypatch):
    monkeypatch.setenv(promexp.METRICS_PORT_ENV_VAR, "0")  # ephemeral
    exporter = promexp.maybe_start(rank=0)
    assert exporter is not None
    assert exporter.port > 0
    again = promexp.maybe_start(rank=0)
    assert again is exporter  # one exporter per process
    telemetry.set_enabled(True)
    telemetry.counter_add("bytes_written", 1)
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{exporter.port}/metrics", timeout=10
    ).read().decode("utf-8")
    assert "torchsnapshot_tpu_bytes_written_total" in body
    assert body.endswith("# EOF\n")
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/nope", timeout=10
        )


# ------------------------------------------------------------- acceptance


class _SlowFS:
    """Factory: an fs plugin whose payload writes pause, keeping the
    take in flight long enough to scrape mid-save."""

    @staticmethod
    def build(delay_s: float):
        from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

        class SlowFS(FSStoragePlugin):
            supports_streaming = False

            async def write(self, write_io):
                if not write_io.path.startswith(".snapshot"):
                    await asyncio.sleep(delay_s)
                await super().write(write_io)

        return SlowFS


def test_live_scrape_during_in_flight_take(tmp_path, monkeypatch):
    """Acceptance: a /metrics scrape while a take is IN FLIGHT parses
    clean under prometheus_client's parser (when importable) and
    includes at least one histogram family."""
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        _SlowFS.build(0.25),
    )
    monkeypatch.setenv(promexp.METRICS_PORT_ENV_VAR, "0")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PROGRESS_S", "0.05")
    telemetry.set_enabled(True)
    state = {
        "m": StateDict(
            **{
                f"p{i}": np.random.default_rng(i)
                .standard_normal(300_000)
                .astype(np.float32)
                for i in range(4)
            }
        )
    }
    snap = str(tmp_path / "snap")
    failures = []

    def run_take():
        try:
            Snapshot.take(snap, state)
        except BaseException as e:  # noqa: BLE001
            failures.append(e)

    taker = threading.Thread(target=run_take)
    taker.start()
    try:
        # Wait for the op itself to arm the exporter (promexp.maybe_start
        # runs at op begin), then scrape while writes are still pausing.
        deadline = 100
        exporter = None
        while exporter is None and deadline > 0:
            exporter = promexp.active_exporter()
            deadline -= 1
            threading.Event().wait(0.05)
        assert exporter is not None, "take never armed the exporter"
        body = None
        for _ in range(60):
            if not taker.is_alive():
                break
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics", timeout=10
            ).read().decode("utf-8")
            if "_bucket{" in text:
                body = text
                break
            threading.Event().wait(0.05)
        assert body is not None, "no in-flight scrape carried a histogram"
    finally:
        taker.join(timeout=120)
    assert not failures, failures
    assert os.path.isfile(os.path.join(snap, ".snapshot_metadata"))
    assert "# EOF" in body
    families = _parse_openmetrics(body)
    if families is not None:
        assert any(f.type == "histogram" for f in families)
