"""TCP KV store + LinearBarrier tests (reference: tests/test_dist_store.py)."""

import threading
import time

import pytest

from torchsnapshot_tpu.dist_store import LinearBarrier, TCPStore, create_store


@pytest.fixture
def store():
    s = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    yield s
    s.close()


def test_set_get(store) -> None:
    store.set("k", b"v")
    assert store.get("k") == b"v"
    assert store.check("k")
    assert not store.check("nope")


def test_blocking_get(store) -> None:
    def setter():
        time.sleep(0.2)
        store2 = store.clone()
        store2.set("later", b"done")
        store2.close()

    t = threading.Thread(target=setter)
    t.start()
    assert store.get("later", timeout=5.0) == b"done"
    t.join()


def test_get_timeout(store) -> None:
    with pytest.raises(TimeoutError):
        store.get("never", timeout=0.3)


def test_add(store) -> None:
    assert store.add("ctr", 1) == 1
    assert store.add("ctr", 5) == 6
    assert store.get("ctr") == b"6"


def test_wait_any(store) -> None:
    store.set("b", b"2")
    key, value = store.wait_any(["a", "b"], timeout=2.0)
    assert key == "b" and value == b"2"


def test_delete_and_prefix(store) -> None:
    store.set("p/1", b"x")
    store.set("p/2", b"y")
    store.set("q/1", b"z")
    assert store.delete("p/1")
    assert not store.delete("p/1")
    assert store.delete_prefix("p/") == 1
    assert store.check("q/1")


def test_multiple_clients(store) -> None:
    clients = [store.clone() for _ in range(4)]
    for i, c in enumerate(clients):
        c.set(f"client/{i}", str(i).encode())
    for i, c in enumerate(clients):
        assert c.get(f"client/{(i + 1) % 4}") == str((i + 1) % 4).encode()
    for c in clients:
        c.close()


def test_linear_barrier_two_threads(store) -> None:
    """Barrier with leader action between phases, driven from threads
    (the async-commit usage pattern)."""
    events = []
    lock = threading.Lock()

    def run(rank: int) -> None:
        s = store.clone()
        b = LinearBarrier("bar1", s, rank, 2)
        b.arrive(timeout=10.0)
        if rank == 0:
            with lock:
                events.append("leader-action")
        b.depart(timeout=10.0)
        with lock:
            events.append(f"departed-{rank}")
        s.close()

    threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert events[0] == "leader-action"
    assert set(events[1:]) == {"departed-0", "departed-1"}


def test_linear_barrier_error_propagation(store) -> None:
    """A rank's reported error must surface on peers instead of committing
    (reference: dist_store.py:177-193)."""
    results = {}

    def leader() -> None:
        s = store.clone()
        b = LinearBarrier("bar2", s, 0, 2)
        try:
            b.arrive(timeout=10.0)
            results[0] = "committed"
        except RuntimeError as e:
            results[0] = f"error: {e.__cause__}"
        s.close()

    def failing_peer() -> None:
        s = store.clone()
        b = LinearBarrier("bar2", s, 1, 2)
        b.report_error(ValueError("injected failure"))
        s.close()

    threads = [threading.Thread(target=leader), threading.Thread(target=failing_peer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert "injected failure" in results[0]


def test_create_store_rendezvous() -> None:
    server = create_store(rank=0)
    client = create_store(rank=1, addr=server.addr)
    client.set("hello", b"world")
    assert server.get("hello") == b"world"
    client.close()
    server.close()


def test_mset_and_collect(store) -> None:
    store.mset({f"batch/{i}": str(i).encode() for i in range(5)})
    stopped, items = store.collect("batch/", 5, timeout=5.0)
    assert stopped is None
    assert items == {f"batch/{i}": str(i).encode() for i in range(5)}


def test_collect_blocks_until_count(store) -> None:
    import threading
    import time

    def fill():
        for i in range(3):
            time.sleep(0.05)
            store.clone().set(f"slow/{i}", b"x")

    t = threading.Thread(target=fill)
    t.start()
    stopped, items = store.collect("slow/", 3, timeout=10.0)
    t.join()
    assert stopped is None and len(items) == 3


def test_collect_stop_key_short_circuits(store) -> None:
    store.set("err/0", b"boom")
    # only 1 of 99 keys present; the stop key returns immediately
    stopped, items = store.collect("never/", 99, stop_keys=["err/0"], timeout=5.0)
    assert stopped == "err/0"
    assert items["err/0"] == b"boom"


def test_collect_timeout(store) -> None:
    import pytest

    with pytest.raises(TimeoutError):
        store.collect("absent/", 2, timeout=0.2)


def test_liveness_publishes_on_connection_drop(store) -> None:
    """A liveness-registered connection that drops without deregistering
    publishes its death payload; a clean deregister does not."""
    import time

    from torchsnapshot_tpu.dist_store import TCPStore

    dirty = store.clone()
    dirty.register_liveness("death/dirty", b"rank-x-died")
    clean = store.clone()
    clean.register_liveness("death/clean", b"rank-y-died")
    clean.deregister_liveness("death/clean")
    dirty.close()
    clean.close()
    deadline = time.monotonic() + 10
    while not store.check("death/dirty") and time.monotonic() < deadline:
        time.sleep(0.05)
    assert store.get("death/dirty", timeout=5.0) == b"rank-x-died"
    assert not store.check("death/clean")


def test_liveness_does_not_overwrite_existing_key(store) -> None:
    """First death wins: a second dropped connection must not clobber an
    already-published death/error payload."""
    import time

    c1 = store.clone()
    c1.register_liveness("death/one", b"first")
    store.set("death/one", b"already-there")
    c1.close()
    time.sleep(0.3)
    assert store.get("death/one", timeout=5.0) == b"already-there"
