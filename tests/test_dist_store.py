"""TCP KV store + LinearBarrier tests (reference: tests/test_dist_store.py)."""

import threading
import time

import pytest

from torchsnapshot_tpu.dist_store import LinearBarrier, TCPStore, create_store


@pytest.fixture
def store():
    s = TCPStore("127.0.0.1", is_server=True, timeout=10.0)
    yield s
    s.close()


def test_set_get(store) -> None:
    store.set("k", b"v")
    assert store.get("k") == b"v"
    assert store.check("k")
    assert not store.check("nope")


def test_blocking_get(store) -> None:
    def setter():
        time.sleep(0.2)
        store2 = store.clone()
        store2.set("later", b"done")
        store2.close()

    t = threading.Thread(target=setter)
    t.start()
    assert store.get("later", timeout=5.0) == b"done"
    t.join()


def test_get_timeout(store) -> None:
    with pytest.raises(TimeoutError):
        store.get("never", timeout=0.3)


def test_add(store) -> None:
    assert store.add("ctr", 1) == 1
    assert store.add("ctr", 5) == 6
    assert store.get("ctr") == b"6"


def test_wait_any(store) -> None:
    store.set("b", b"2")
    key, value = store.wait_any(["a", "b"], timeout=2.0)
    assert key == "b" and value == b"2"


def test_delete_and_prefix(store) -> None:
    store.set("p/1", b"x")
    store.set("p/2", b"y")
    store.set("q/1", b"z")
    assert store.delete("p/1")
    assert not store.delete("p/1")
    assert store.delete_prefix("p/") == 1
    assert store.check("q/1")


def test_multiple_clients(store) -> None:
    clients = [store.clone() for _ in range(4)]
    for i, c in enumerate(clients):
        c.set(f"client/{i}", str(i).encode())
    for i, c in enumerate(clients):
        assert c.get(f"client/{(i + 1) % 4}") == str((i + 1) % 4).encode()
    for c in clients:
        c.close()


def test_linear_barrier_two_threads(store) -> None:
    """Barrier with leader action between phases, driven from threads
    (the async-commit usage pattern)."""
    events = []
    lock = threading.Lock()

    def run(rank: int) -> None:
        s = store.clone()
        b = LinearBarrier("bar1", s, rank, 2)
        b.arrive(timeout=10.0)
        if rank == 0:
            with lock:
                events.append("leader-action")
        b.depart(timeout=10.0)
        with lock:
            events.append(f"departed-{rank}")
        s.close()

    threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert events[0] == "leader-action"
    assert set(events[1:]) == {"departed-0", "departed-1"}


def test_linear_barrier_error_propagation(store) -> None:
    """A rank's reported error must surface on peers instead of committing
    (reference: dist_store.py:177-193)."""
    results = {}

    def leader() -> None:
        s = store.clone()
        b = LinearBarrier("bar2", s, 0, 2)
        try:
            b.arrive(timeout=10.0)
            results[0] = "committed"
        except RuntimeError as e:
            results[0] = f"error: {e.__cause__}"
        s.close()

    def failing_peer() -> None:
        s = store.clone()
        b = LinearBarrier("bar2", s, 1, 2)
        b.report_error(ValueError("injected failure"))
        s.close()

    threads = [threading.Thread(target=leader), threading.Thread(target=failing_peer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert "injected failure" in results[0]


def test_create_store_rendezvous() -> None:
    server = create_store(rank=0)
    client = create_store(rank=1, addr=server.addr)
    client.set("hello", b"world")
    assert server.get("hello") == b"world"
    client.close()
    server.close()


def test_mset_and_collect(store) -> None:
    store.mset({f"batch/{i}": str(i).encode() for i in range(5)})
    stopped, items = store.collect("batch/", 5, timeout=5.0)
    assert stopped is None
    assert items == {f"batch/{i}": str(i).encode() for i in range(5)}


def test_collect_blocks_until_count(store) -> None:
    import threading
    import time

    def fill():
        for i in range(3):
            time.sleep(0.05)
            store.clone().set(f"slow/{i}", b"x")

    t = threading.Thread(target=fill)
    t.start()
    stopped, items = store.collect("slow/", 3, timeout=10.0)
    t.join()
    assert stopped is None and len(items) == 3


def test_collect_stop_key_short_circuits(store) -> None:
    store.set("err/0", b"boom")
    # only 1 of 99 keys present; the stop key returns immediately
    stopped, items = store.collect("never/", 99, stop_keys=["err/0"], timeout=5.0)
    assert stopped == "err/0"
    assert items["err/0"] == b"boom"


def test_collect_timeout(store) -> None:
    import pytest

    with pytest.raises(TimeoutError):
        store.collect("absent/", 2, timeout=0.2)


def test_liveness_publishes_on_connection_drop(store) -> None:
    """A liveness-registered connection that drops without deregistering
    publishes its death payload; a clean deregister does not."""
    import time

    from torchsnapshot_tpu.dist_store import TCPStore

    dirty = store.clone()
    dirty.register_liveness("death/dirty", b"rank-x-died")
    clean = store.clone()
    clean.register_liveness("death/clean", b"rank-y-died")
    clean.deregister_liveness("death/clean")
    dirty.close()
    clean.close()
    deadline = time.monotonic() + 10
    while not store.check("death/dirty") and time.monotonic() < deadline:
        time.sleep(0.05)
    assert store.get("death/dirty", timeout=5.0) == b"rank-x-died"
    assert not store.check("death/clean")


def test_liveness_does_not_overwrite_existing_key(store) -> None:
    """First death wins: a second dropped connection must not clobber an
    already-published death/error payload."""
    import time

    c1 = store.clone()
    c1.register_liveness("death/one", b"first")
    store.set("death/one", b"already-there")
    c1.close()
    time.sleep(0.3)
    assert store.get("death/one", timeout=5.0) == b"already-there"


# ------------------------------------------------ store-server SPOF story


def _host_store_and_block(port_q):
    """Subprocess: host a store server, report its port, then sleep until
    killed (the server thread keeps serving)."""
    s = TCPStore("127.0.0.1", is_server=True, timeout=60.0)
    port_q.put(s.port)
    time.sleep(600)


def test_server_death_fails_blocked_clients_fast() -> None:
    """When the store-HOSTING process dies, a client blocked in a
    long-timeout get raises within seconds — naming the store host — not
    after the 1800 s barrier timeout (the SPOF the reference's
    rank-0-hosted TCPStore shares, dist_store.py:53-88)."""
    import multiprocessing as mp

    from torchsnapshot_tpu.dist_store import StoreConnectionLostError

    ctx = mp.get_context("spawn")
    port_q = ctx.Queue()
    server_proc = ctx.Process(target=_host_store_and_block, args=(port_q,))
    server_proc.start()
    try:
        port = port_q.get(timeout=30)
        client = TCPStore("127.0.0.1", port)
        client.set("warm", b"1")  # the connection works

        failed_at = {}

        def blocked_get():
            t0 = time.monotonic()
            try:
                client.get("never-set", timeout=120.0)
            except StoreConnectionLostError as e:
                failed_at["elapsed"] = time.monotonic() - t0
                failed_at["msg"] = str(e)

        t = threading.Thread(target=blocked_get)
        t.start()
        time.sleep(0.5)  # let the get block server-side
        server_proc.kill()
        t.join(timeout=30)
        assert not t.is_alive(), "blocked get did not fail after server death"
        assert failed_at["elapsed"] < 10.0, failed_at
        assert f"127.0.0.1:{port}" in failed_at["msg"]
        assert "rank 0" in failed_at["msg"]

        # Subsequent ops fail fast instead of re-blocking.
        t0 = time.monotonic()
        with pytest.raises(StoreConnectionLostError):
            client.set("more", b"1")
        assert time.monotonic() - t0 < 1.0
        # A clone (the async-commit thread's path) also fails by name —
        # including against the loopback ephemeral SELF-CONNECT trap
        # (connecting to the dead server's freed ephemeral port can
        # TCP-simultaneous-open onto itself and "succeed"; TCPStore
        # detects and refuses it).
        with pytest.raises(StoreConnectionLostError):
            client.clone()
    finally:
        if server_proc.is_alive():
            server_proc.kill()
        server_proc.join(timeout=10)


def test_unresponsive_server_hits_response_deadline(monkeypatch) -> None:
    """A wedged server (host alive, process stuck): detected at CONNECT
    time by the probe round-trip, and mid-session by the per-request
    response deadline — never an infinite hang."""
    import socket as socket_mod

    from torchsnapshot_tpu import dist_store
    from torchsnapshot_tpu.dist_store import (
        StoreConnectionLostError,
        _recv_msg,
        _send_msg,
    )

    monkeypatch.setattr(dist_store, "STORE_RPC_TIMEOUT_S", 1.0)
    monkeypatch.setattr(dist_store, "RPC_GRACE_S", 1.0)
    monkeypatch.setattr(dist_store, "CONNECT_TIMEOUT_S", 2.0)

    # --- never responds at all: the connect-time probe rejects it.
    lsock = socket_mod.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    port = lsock.getsockname()[1]
    t = threading.Thread(target=lambda: lsock.accept(), daemon=True)
    t.start()
    try:
        t0 = time.monotonic()
        with pytest.raises(OSError):
            TCPStore("127.0.0.1", port)
        assert time.monotonic() - t0 < 10.0
    finally:
        lsock.close()

    # --- wedges AFTER the handshake: the response deadline converts the
    # hang into StoreConnectionLostError, bounded by the op's own
    # timeout + grace (blocking ops) or the quick-op RPC deadline.
    lsock2 = socket_mod.socket()
    lsock2.bind(("127.0.0.1", 0))
    lsock2.listen(8)
    port2 = lsock2.getsockname()[1]

    def answer_probe_then_wedge():
        while True:
            try:
                conn, _ = lsock2.accept()
            except OSError:
                return
            _recv_msg(conn)  # the probe
            _send_msg(conn, {"ok": True, "value": False})
            # ...then go silent forever (but keep the socket open).

    t2 = threading.Thread(target=answer_probe_then_wedge, daemon=True)
    t2.start()
    try:
        client = TCPStore("127.0.0.1", port2)
        t0 = time.monotonic()
        with pytest.raises(StoreConnectionLostError):
            client.set("k", b"v")  # quick op: STORE_RPC_TIMEOUT_S bound
        assert time.monotonic() - t0 < 5.0

        client2 = TCPStore("127.0.0.1", port2)
        t0 = time.monotonic()
        with pytest.raises(StoreConnectionLostError):
            client2.get("k", timeout=1.0)  # op timeout + grace bound
        assert time.monotonic() - t0 < 5.0
    finally:
        lsock2.close()


def test_op_timeout_is_not_connection_loss(store) -> None:
    """A server-side op timeout (key never appears) stays a TimeoutError
    and the connection REMAINS usable — only server silence/death maps
    to StoreConnectionLostError."""
    with pytest.raises(TimeoutError):
        store.get("never-set", timeout=0.3)
    store.set("after", b"1")  # connection still fine
    assert store.get("after") == b"1"


def test_non_store_service_on_port_is_refused() -> None:
    """A port occupied by something that ANSWERS but is not a store
    (e.g. a service that grabbed the dead store's freed port): the
    connect-time probe must refuse it — whether the reply is non-pickle
    garbage or a pickled non-response — and must not leak the socket."""
    import socket as socket_mod

    def garbage_server(payload: bytes):
        lsock = socket_mod.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)

        def serve():
            while True:
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                try:
                    conn.recv(4096)  # swallow the probe
                    conn.sendall(payload)
                    conn.close()
                except OSError:
                    pass

        threading.Thread(target=serve, daemon=True).start()
        return lsock

    import struct

    # Length-prefixed non-pickle bytes: explodes inside unpickling.
    garbage = struct.pack(">Q", 8) + b"not-pkl!"
    # A pickled object that is not a response dict.
    import pickle

    notdict = pickle.dumps(["hello"])
    framed_notdict = struct.pack(">Q", len(notdict)) + notdict

    for payload in (garbage, framed_notdict, b"HTTP/1.1 400\r\n\r\n"[:8]):
        lsock = garbage_server(payload)
        try:
            with pytest.raises(OSError):
                TCPStore("127.0.0.1", lsock.getsockname()[1])
        finally:
            lsock.close()
