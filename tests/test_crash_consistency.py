"""SIGKILL crash-consistency: a writer killed mid-save commits nothing.

The commit protocol's crash-safety claim (snapshot.py: ``.snapshot_metadata``
is written only after every payload write completes; fs.py: every file lands
via temp+rename, so no path ever holds a partial write) has real fault tests
for *process-visible* failures (exceptions, peer aborts) but none for the
failure those mechanisms exist for: the process dying with no chance to run
``finally`` blocks. These tests SIGKILL a real writer subprocess at two
surgically-chosen points and verify every recovery surface:

- the partial directory has payloads but no ``.snapshot_metadata``;
- ``Snapshot(path).restore`` refuses it with a clean error;
- ``CheckpointManager`` resume discovery skips it and the previous committed
  step restores bit-exact;
- the ``verify`` CLI reports it as an error (exit 2) instead of crashing;
- a kill *during the metadata write itself* (after the temp file is fully
  written, before the rename) still leaves the snapshot uncommitted — the
  atomic-rename commit point.

The reference relies on the same metadata-last design
(/root/reference/torchsnapshot/snapshot.py:234-252 writes metadata after the
pending I/O work completes) but ships no kill test; this is the crash drill
for it.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict
from torchsnapshot_tpu.cli import main as cli_main

# The child stalls inside the fs plugin at a chosen point, touches a gate
# file so the parent knows the stall point was reached, then sleeps until
# SIGKILLed. Payload values are deterministic (arange) so the parent can
# verify the surviving step without shipping arrays across processes.
_CHILD = r"""
import asyncio, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.storage_plugins import fs as fs_mod

root, gate, stall_at = sys.argv[1], sys.argv[2], sys.argv[3]

orig_write = fs_mod.FSStoragePlugin.write
n_payload_writes = 0
first_payload_durable = asyncio.Event()

async def gated_write(self, write_io):
    global n_payload_writes
    is_meta = write_io.path.endswith(".snapshot_metadata")
    # The commit fence (.snapshot_fence) is a control file, not a payload:
    # it must neither stall nor count toward the payload-write numbering.
    is_internal = is_meta or write_io.path.endswith(".snapshot_fence")
    if stall_at == "payload" and not is_internal:
        # Let the first payload land fully, then stall the second forever:
        # the take is killed with SOME payloads durable and no metadata.
        # The writes run concurrently, so the stalling task must WAIT for
        # the first write's temp+rename to complete before signalling the
        # parent — otherwise the kill can land before anything is durable.
        n_payload_writes += 1
        if n_payload_writes == 1:
            await orig_write(self, write_io)
            first_payload_durable.set()
            return
        await first_payload_durable.wait()
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    if stall_at == "metadata" and is_meta:
        # Write the metadata TEMP file completely, then stall before the
        # rename: a kill here is a crash at the exact commit point.
        path = os.path.join(self.root, write_io.path)
        await self._ensure_parent(path)
        with open(path + ".tmp.crashtest", "wb") as f:
            f.write(bytes(write_io.buf))
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    await orig_write(self, write_io)

fs_mod.FSStoragePlugin.write = gated_write

state = {
    "model": StateDict(
        w=np.arange(64_000, dtype=np.float32),
        b=np.arange(8_000, dtype=np.float64),
    )
}
Snapshot.take(os.path.join(root, f"step_{1:010d}"), state)
"""


def _take_step0(root: str) -> dict:
    state = {
        "model": StateDict(
            w=np.arange(64_000, dtype=np.float32) * 2.0,
            b=np.arange(8_000, dtype=np.float64) * 3.0,
        )
    }
    Snapshot.take(os.path.join(root, f"step_{0:010d}"), state)
    return state


def _spawn_writer_until_gate(child_src: str, argv: list, gate: str):
    """Spawn a writer child and block until it touches ``gate``.

    stderr goes to a file, not a PIPE: nobody drains a pipe while the
    parent polls for the gate, and a chatty child (XLA init warnings)
    would block on a full pipe before ever reaching the stall point.
    Returns (proc, err_path); the caller decides when to SIGKILL.
    """
    err_path = gate + ".stderr"
    with open(err_path, "wb") as err:
        proc = subprocess.Popen(
            [sys.executable, "-c", child_src, *argv],
            stdout=subprocess.DEVNULL,
            stderr=err,
        )
        deadline = time.monotonic() + 120
        while not os.path.exists(gate):
            if proc.poll() is not None:
                with open(err_path) as f:
                    raise AssertionError(
                        "writer exited before reaching the gate:\n" + f.read()
                    )
            if time.monotonic() > deadline:
                proc.kill()
                raise AssertionError("writer never reached the gate")
            time.sleep(0.01)
    return proc, err_path


def _sigkill(proc, err_path: str, allow_clean_exit: bool = False) -> None:
    """SIGKILL the writer: no atexit, no finally, no cleanup. A child that
    DIED ON ITS OWN before the kill is a real writer failure, not a crash
    simulation — surface its stderr instead of letting it masquerade as
    the uncommitted outcome (unless the caller expects completion)."""
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)
    ok = (0,) if allow_clean_exit else ()
    if proc.returncode != -signal.SIGKILL and proc.returncode not in ok:
        with open(err_path) as f:
            raise AssertionError(
                f"writer exited on its own (rc={proc.returncode}) before "
                "the kill — a genuine failure, not a simulated crash:\n"
                + f.read()
            )


def _kill_mid_save(root: str, gate: str, stall_at: str) -> None:
    proc, err_path = _spawn_writer_until_gate(
        _CHILD, [root, gate, stall_at], gate
    )
    _sigkill(proc, err_path)


def _assert_uncommitted_and_recoverable(root: str, step0_state: dict) -> None:
    partial = os.path.join(root, f"step_{1:010d}")
    assert os.path.isdir(partial), "the kill should leave the partial dir"
    assert not os.path.exists(
        os.path.join(partial, ".snapshot_metadata")
    ), "a killed writer must never leave a committed metadata file"

    # Restore refuses the partial snapshot with a clean error, not garbage.
    dst = {"model": StateDict(w=np.zeros(1, np.float32))}
    with pytest.raises((FileNotFoundError, RuntimeError, ValueError)):
        Snapshot(path=partial).restore(dst)

    # verify CLI: clean error exit, no traceback.
    assert cli_main(["verify", partial]) == 2

    # Resume discovery skips the partial step and the prior step is intact.
    mgr = CheckpointManager(root)
    assert mgr.all_steps() == [0]
    assert mgr.latest_step() == 0
    dst = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=mgr.path_for(0)).restore(dst)
    np.testing.assert_array_equal(dst["model"]["w"], step0_state["model"]["w"])
    np.testing.assert_array_equal(dst["model"]["b"], step0_state["model"]["b"])


def test_sigkill_mid_payload_write_commits_nothing(tmp_path) -> None:
    root = str(tmp_path)
    step0 = _take_step0(root)
    _kill_mid_save(root, str(tmp_path / "gate"), "payload")

    partial = os.path.join(root, f"step_{1:010d}")
    payloads = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(partial)
        for f in fs
        if not f.startswith(".") and ".tmp." not in f
    ]
    assert payloads, "the first payload should have landed before the kill"
    _assert_uncommitted_and_recoverable(root, step0)


def test_sigkill_during_metadata_write_commits_nothing(tmp_path) -> None:
    """Crash at the exact commit point: the metadata temp file is fully
    written but never renamed — the snapshot must still read as
    uncommitted (this is what temp+rename atomicity buys)."""
    root = str(tmp_path)
    step0 = _take_step0(root)
    _kill_mid_save(root, str(tmp_path / "gate"), "metadata")

    partial = os.path.join(root, f"step_{1:010d}")
    tmp_files = [f for f in os.listdir(partial) if ".tmp." in f]
    assert tmp_files, "the metadata temp file should exist (crash pre-rename)"
    _assert_uncommitted_and_recoverable(root, step0)


# ------------------------------------------- deterministic (faultinject)

# Surgical kill points without monkeypatched stalls: the injector's kill
# action SIGKILLs the child at an exact site hit, so async_take and the
# mirror tier get the same crash drills the sync fs path has — chosen
# deterministically, not by timing.
_CHILD_FAULT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict, faultinject

root, plan, mode = sys.argv[1], sys.argv[2], sys.argv[3]
state = {
    "model": StateDict(
        w=np.arange(64_000, dtype=np.float32),
        b=np.arange(8_000, dtype=np.float64),
    )
}
faultinject.configure(plan)
path = os.path.join(root, f"step_{1:010d}")
if mode == "async":
    Snapshot.async_take(path, state).wait()
elif mode == "mirror":
    Snapshot.take(
        path,
        state,
        storage_options={
            "mirror_url": os.path.join(root, "mirror_tier", f"step_{1:010d}")
        },
    )
else:
    Snapshot.take(path, state)
print("SURVIVED")
"""


def _run_fault_child(root: str, plan: str, mode: str) -> None:
    err_path = os.path.join(root, "child.stderr")
    with open(err_path, "wb") as err:
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD_FAULT, root, plan, mode],
            stdout=subprocess.DEVNULL,
            stderr=err,
        )
    proc.wait(timeout=150)
    if proc.returncode != -signal.SIGKILL:
        with open(err_path) as f:
            raise AssertionError(
                f"child exited rc={proc.returncode}, expected SIGKILL from "
                "the fault plan:\n" + f.read()
            )


def test_sigkill_async_take_at_commit_point(tmp_path) -> None:
    """async_take's background commit thread killed exactly at the
    metadata commit site: the early-returned handle's promise ('wait()
    either returns a committed snapshot or raises') can never be met, so
    what must hold is the on-disk protocol — nothing committed, previous
    step intact."""
    root = str(tmp_path)
    step0 = _take_step0(root)
    _run_fault_child(root, "commit.metadata@1=kill", "async")
    _assert_uncommitted_and_recoverable(root, step0)


def test_sigkill_async_take_mid_payload(tmp_path) -> None:
    """async_take killed during a payload write (hit 1 is the commit
    fence; hit 2 the first payload temp-file write)."""
    root = str(tmp_path)
    step0 = _take_step0(root)
    _run_fault_child(root, "fs.write@2=kill", "async")
    _assert_uncommitted_and_recoverable(root, step0)


def test_sigkill_mirror_metadata_commit_leaves_mirror_uncommitted(
    tmp_path,
) -> None:
    """Mirror-tier crash drill: killed at the MIRROR's deferred metadata
    commit — the LAST buffered write of a mirrored take. Hit arithmetic:
    the fence and both payloads each write twice (primary + mirror
    replication) = 6, primary metadata = 7, mirror metadata = 8. The
    primary tier must be fully committed and bit-exact; the mirror must
    hold payloads but read as uncommitted — metadata-last holds
    independently per tier."""
    root = str(tmp_path)
    step0 = _take_step0(root)
    _run_fault_child(root, "fs.write@8=kill", "mirror")

    step1 = os.path.join(root, f"step_{1:010d}")
    assert os.path.exists(os.path.join(step1, ".snapshot_metadata"))
    assert cli_main(["verify", step1]) == 0
    dst = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=step1).restore(dst)
    np.testing.assert_array_equal(
        dst["model"]["w"], np.arange(64_000, dtype=np.float32)
    )

    mirror = os.path.join(root, "mirror_tier", f"step_{1:010d}")
    assert os.path.isdir(mirror), "mirror payloads should have replicated"
    assert not os.path.exists(
        os.path.join(mirror, ".snapshot_metadata")
    ), "a killed mirror commit must leave the mirror uncommitted"
    payloads = [
        f
        for dp, _, fs in os.walk(mirror)
        for f in fs
        if not f.startswith(".") and ".tmp." not in f
    ]
    assert payloads, "mirror payload replication ran before the kill"
    # step0 untouched throughout.
    dst0 = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=os.path.join(root, f"step_{0:010d}")).restore(dst0)
    np.testing.assert_array_equal(dst0["model"]["w"], step0["model"]["w"])


# ----------------------------------------------------------- randomized

# Unlike _CHILD, no stall point: the child takes a real ~96 MB snapshot at
# full speed and touches the gate right before Snapshot.take so the parent
# can sample a kill time anywhere in (or past) the take window. ``mode``
# extends the drill across the take surfaces: sync, async_take (the
# background commit thread is what dies), and the mirrored two-tier path.
_CHILD_FREE = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict

root, gate, mode = sys.argv[1], sys.argv[2], sys.argv[3]
state = {
    "model": StateDict(
        **{f"p{i}": np.full(3_000_000, i, dtype=np.float32) for i in range(8)}
    )
}
path = os.path.join(root, f"step_{1:010d}")
with open(gate, "w") as f:
    f.write("taking")
if mode == "async":
    Snapshot.async_take(path, state).wait()
elif mode == "mirror":
    Snapshot.take(
        path,
        state,
        storage_options={
            "mirror_url": os.path.join(root, "mirror_tier", f"step_{1:010d}")
        },
    )
else:
    Snapshot.take(path, state)
"""


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["sync", "async", "mirror"])
def test_random_kill_points_commit_or_recover(tmp_path, mode) -> None:
    """Kill the writer at RANDOM points instead of surgical ones: whatever
    the timing, the outcome must be binary — either the snapshot committed
    (verify passes, every value restores exactly) or it did not (recovery
    surfaces all refuse it and step_0 is intact). Any third outcome —
    committed-but-corrupt, partially-restorable — is the bug class this
    drill exists to catch. Seeded RNG, printed per-iteration, for replay.

    Two iterations are deterministic so BOTH outcomes always occur: an
    immediate kill (uncommitted) and a kill only after the metadata file
    appears (committed — a crash just after the commit point must leave a
    fully valid snapshot). The random delays between them are calibrated
    against one unkilled take timed on this host under current load."""
    import random
    import shutil

    rng = random.Random(0xC0FFEE)
    root = str(tmp_path)
    step0 = _take_step0(root)
    partial = os.path.join(root, f"step_{1:010d}")
    outcomes = {"committed": 0, "uncommitted": 0}

    # Calibrate: one unkilled take, timed from the gate to the metadata
    # file appearing, so random kill points span THIS host's take window.
    gate = str(tmp_path / "gate_cal")
    proc, err_path = _spawn_writer_until_gate(
        _CHILD_FREE, [root, gate, mode], gate
    )
    t0 = time.monotonic()
    meta = os.path.join(partial, ".snapshot_metadata")
    while not os.path.exists(meta):
        assert time.monotonic() - t0 < 120, "calibration take never finished"
        assert proc.poll() is None or proc.returncode == 0
        time.sleep(0.01)
    t_take = time.monotonic() - t0
    proc.wait(timeout=30)
    assert proc.returncode == 0
    print(f"calibration: take window {t_take:.3f}s")

    for it in range(6):
        shutil.rmtree(partial, ignore_errors=True)
        gate = str(tmp_path / f"gate_{it}")
        if it == 0:
            delay = 0.0  # guaranteed early kill -> uncommitted
        elif it == 1:
            delay = None  # kill right AFTER the commit point -> committed
        else:
            delay = rng.uniform(0.0, 1.2) * t_take
        # A fresh mirror tier per iteration: a committed outcome must
        # come from THIS run's replication, not a previous iteration's.
        if mode == "mirror":
            shutil.rmtree(
                os.path.join(root, "mirror_tier"), ignore_errors=True
            )
        proc, err_path = _spawn_writer_until_gate(
            _CHILD_FREE, [root, gate, mode], gate
        )
        if delay is None:
            t0 = time.monotonic()
            while not os.path.exists(os.path.join(partial, ".snapshot_metadata")):
                assert time.monotonic() - t0 < 120
                time.sleep(0.005)
        else:
            time.sleep(delay)
        # A take that outran a long delay exits cleanly first — that is the
        # committed outcome, not a writer failure.
        _sigkill(proc, err_path, allow_clean_exit=True)

        committed = os.path.exists(os.path.join(partial, ".snapshot_metadata"))
        label = "post-commit" if delay is None else f"{delay:.3f}s"
        print(f"iter {it}: delay={label} -> "
              f"{'committed' if committed else 'uncommitted'}")
        if committed:
            outcomes["committed"] += 1
            # Fully valid: checksums verify and every leaf restores exactly.
            assert cli_main(["verify", partial]) == 0
            dst = {
                "model": StateDict(
                    **{
                        f"p{i}": np.zeros(3_000_000, np.float32)
                        for i in range(8)
                    }
                )
            }
            Snapshot(path=partial).restore(dst)
            for i in range(8):
                np.testing.assert_array_equal(
                    dst["model"][f"p{i}"],
                    np.full(3_000_000, i, dtype=np.float32),
                )
        else:
            outcomes["uncommitted"] += 1
            dst = {"model": StateDict(w=np.zeros(1, np.float32))}
            with pytest.raises((FileNotFoundError, RuntimeError, ValueError)):
                Snapshot(path=partial).restore(dst)
            mgr = CheckpointManager(root)
            assert mgr.all_steps() == [0]

    # step_0 survived every kill, bit-exact.
    dst = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=os.path.join(root, f"step_{0:010d}")).restore(dst)
    np.testing.assert_array_equal(dst["model"]["w"], step0["model"]["w"])
    np.testing.assert_array_equal(dst["model"]["b"], step0["model"]["b"])
    print(f"outcomes: {outcomes}")
    # The deterministic iterations guarantee both branches really ran.
    assert outcomes["committed"] >= 1 and outcomes["uncommitted"] >= 1


# ----------------------------------------------- resurrected stragglers


def test_async_take_plants_fence_before_returning(tmp_path) -> None:
    """The fenced-GC safety argument requires the fence to exist by the
    time async_take RETURNS: a fence planted later (by the background
    commit thread) would be self-satisfying — a straggler reclaimed by
    GC could resume, re-plant its own token, pass its own commit check,
    and splice stale metadata over a newer snapshot."""
    from torchsnapshot_tpu import faultinject

    faultinject.disable()
    snap = tmp_path / "snap"
    state = {"model": StateDict(w=np.arange(4096, dtype=np.float32))}
    pending = Snapshot.async_take(str(snap), state)
    planted_on_return = os.path.exists(snap / ".snapshot_fence")
    pending.wait()
    assert planted_on_return, (
        "async_take returned without planting the commit fence"
    )
    # Committed: fence deleted at the commit point.
    assert os.path.exists(snap / ".snapshot_metadata")
    assert not os.path.exists(snap / ".snapshot_fence")


def test_straggler_with_reclaimed_fence_cannot_commit(tmp_path) -> None:
    """End-to-end straggler drill: the fence is removed (a fenced GC
    reclaiming the partial) while the async commit thread is still
    draining payload I/O — the commit must abort with StaleCommitError
    and write no metadata, never re-plant and splice."""
    from torchsnapshot_tpu import faultinject
    from torchsnapshot_tpu.snapshot import StaleCommitError

    snap = tmp_path / "snap"
    state = {
        "model": StateDict(
            w=np.arange(4096, dtype=np.float32),
            b=np.arange(256, dtype=np.float64),
        )
    }
    # Every storage write sleeps 300 ms: async_take returns at
    # staging-complete while payload writes are still in flight, giving
    # the parent a deterministic window to play the GC before the
    # commit thread's drain finishes.
    faultinject.configure("fs.write@1+=delay:0.3")
    try:
        pending = Snapshot.async_take(str(snap), state)
        fence = snap / ".snapshot_fence"
        assert os.path.exists(fence)
        os.remove(fence)  # the fenced GC reclaiming this take
        with pytest.raises(StaleCommitError):
            pending.wait()
    finally:
        faultinject.disable()
    assert not os.path.exists(snap / ".snapshot_metadata")
    # The straggler must not have re-planted its fence either.
    assert not os.path.exists(snap / ".snapshot_fence")


def test_commit_check_does_not_replant_missing_fence(tmp_path) -> None:
    """Unit form of the straggler drill: _write_snapshot_metadata with a
    generation whose fence is gone raises StaleCommitError and leaves
    the directory untouched (no metadata, no fence)."""
    import asyncio

    from torchsnapshot_tpu.snapshot import (
        Snapshot as Snap,
        SnapshotMetadata,
        StaleCommitError,
    )
    from torchsnapshot_tpu.storage_plugin import (
        url_to_storage_plugin_in_event_loop,
    )
    from torchsnapshot_tpu.version import __version__

    meta = SnapshotMetadata(version=__version__, world_size=1, manifest={})
    meta._commit_gen = "deadbeef"
    meta._commit_path = str(tmp_path)
    loop = asyncio.new_event_loop()
    storage = url_to_storage_plugin_in_event_loop(str(tmp_path), loop, None)
    try:
        with pytest.raises(StaleCommitError):
            Snap._write_snapshot_metadata(meta, storage, loop)
    finally:
        storage.sync_close(loop)
        loop.close()
    assert not os.path.exists(tmp_path / ".snapshot_metadata")
    assert not os.path.exists(tmp_path / ".snapshot_fence")


def _fence_fault_worker(rank: int, world_size: int, root: str) -> str:
    """Rank 0's very first storage write is the commit fence; injecting a
    permanent fault there must abort EVERY rank fast (the failure rides
    the manifest gather), not desert the peers until the barrier
    timeout."""
    from torchsnapshot_tpu import faultinject

    if rank == 0:
        faultinject.configure("fs.write@1=permanent")
    state = {
        "model": StateDict(w=np.arange(2048, dtype=np.float32) + rank)
    }
    t0 = time.monotonic()
    try:
        Snapshot.take(os.path.join(root, "snap"), state)
        return "committed"  # must not happen
    except Exception:
        elapsed = time.monotonic() - t0
        assert elapsed < 60, f"abort took {elapsed:.0f}s — peers deserted"
        return "aborted"
    finally:
        faultinject.disable()


def test_fence_write_failure_aborts_all_ranks_fast(tmp_path) -> None:
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _fence_fault_worker, 2, str(tmp_path)
    )
    assert all(v == "aborted" for v in results.values()), results
    assert not os.path.exists(tmp_path / "snap" / ".snapshot_metadata")
