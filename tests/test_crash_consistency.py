"""SIGKILL crash-consistency: a writer killed mid-save commits nothing.

The commit protocol's crash-safety claim (snapshot.py: ``.snapshot_metadata``
is written only after every payload write completes; fs.py: every file lands
via temp+rename, so no path ever holds a partial write) has real fault tests
for *process-visible* failures (exceptions, peer aborts) but none for the
failure those mechanisms exist for: the process dying with no chance to run
``finally`` blocks. These tests SIGKILL a real writer subprocess at two
surgically-chosen points and verify every recovery surface:

- the partial directory has payloads but no ``.snapshot_metadata``;
- ``Snapshot(path).restore`` refuses it with a clean error;
- ``CheckpointManager`` resume discovery skips it and the previous committed
  step restores bit-exact;
- the ``verify`` CLI reports it as an error (exit 2) instead of crashing;
- a kill *during the metadata write itself* (after the temp file is fully
  written, before the rename) still leaves the snapshot uncommitted — the
  atomic-rename commit point.

The reference relies on the same metadata-last design
(/root/reference/torchsnapshot/snapshot.py:234-252 writes metadata after the
pending I/O work completes) but ships no kill test; this is the crash drill
for it.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict
from torchsnapshot_tpu.cli import main as cli_main

# The child stalls inside the fs plugin at a chosen point, touches a gate
# file so the parent knows the stall point was reached, then sleeps until
# SIGKILLed. Payload values are deterministic (arange) so the parent can
# verify the surviving step without shipping arrays across processes.
_CHILD = r"""
import asyncio, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.storage_plugins import fs as fs_mod

root, gate, stall_at = sys.argv[1], sys.argv[2], sys.argv[3]

orig_write = fs_mod.FSStoragePlugin.write
n_payload_writes = 0
first_payload_durable = asyncio.Event()

async def gated_write(self, write_io):
    global n_payload_writes
    is_meta = write_io.path.endswith(".snapshot_metadata")
    if stall_at == "payload" and not is_meta:
        # Let the first payload land fully, then stall the second forever:
        # the take is killed with SOME payloads durable and no metadata.
        # The writes run concurrently, so the stalling task must WAIT for
        # the first write's temp+rename to complete before signalling the
        # parent — otherwise the kill can land before anything is durable.
        n_payload_writes += 1
        if n_payload_writes == 1:
            await orig_write(self, write_io)
            first_payload_durable.set()
            return
        await first_payload_durable.wait()
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    if stall_at == "metadata" and is_meta:
        # Write the metadata TEMP file completely, then stall before the
        # rename: a kill here is a crash at the exact commit point.
        path = os.path.join(self.root, write_io.path)
        await self._ensure_parent(path)
        with open(path + ".tmp.crashtest", "wb") as f:
            f.write(bytes(write_io.buf))
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    await orig_write(self, write_io)

fs_mod.FSStoragePlugin.write = gated_write

state = {
    "model": StateDict(
        w=np.arange(64_000, dtype=np.float32),
        b=np.arange(8_000, dtype=np.float64),
    )
}
Snapshot.take(os.path.join(root, f"step_{1:010d}"), state)
"""


def _take_step0(root: str) -> dict:
    state = {
        "model": StateDict(
            w=np.arange(64_000, dtype=np.float32) * 2.0,
            b=np.arange(8_000, dtype=np.float64) * 3.0,
        )
    }
    Snapshot.take(os.path.join(root, f"step_{0:010d}"), state)
    return state


def _kill_mid_save(root: str, gate: str, stall_at: str) -> None:
    # stderr goes to a file, not a PIPE: nobody drains a pipe while the
    # parent polls for the gate, and a chatty child (XLA init warnings)
    # would block on a full pipe before ever reaching the stall point.
    err_path = gate + ".stderr"
    with open(err_path, "wb") as err:
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD, root, gate, stall_at],
            stdout=subprocess.DEVNULL,
            stderr=err,
        )
        deadline = time.monotonic() + 120
        while not os.path.exists(gate):
            if proc.poll() is not None:
                with open(err_path) as f:
                    raise AssertionError(
                        "writer exited before reaching the stall point:\n"
                        + f.read()
                    )
            if time.monotonic() > deadline:
                proc.kill()
                raise AssertionError("writer never reached the stall point")
            time.sleep(0.05)
    os.kill(proc.pid, signal.SIGKILL)  # no atexit, no finally, no cleanup
    proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL


def _assert_uncommitted_and_recoverable(root: str, step0_state: dict) -> None:
    partial = os.path.join(root, f"step_{1:010d}")
    assert os.path.isdir(partial), "the kill should leave the partial dir"
    assert not os.path.exists(
        os.path.join(partial, ".snapshot_metadata")
    ), "a killed writer must never leave a committed metadata file"

    # Restore refuses the partial snapshot with a clean error, not garbage.
    dst = {"model": StateDict(w=np.zeros(1, np.float32))}
    with pytest.raises((FileNotFoundError, RuntimeError, ValueError)):
        Snapshot(path=partial).restore(dst)

    # verify CLI: clean error exit, no traceback.
    assert cli_main(["verify", partial]) == 2

    # Resume discovery skips the partial step and the prior step is intact.
    mgr = CheckpointManager(root)
    assert mgr.all_steps() == [0]
    assert mgr.latest_step() == 0
    dst = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=mgr.path_for(0)).restore(dst)
    np.testing.assert_array_equal(dst["model"]["w"], step0_state["model"]["w"])
    np.testing.assert_array_equal(dst["model"]["b"], step0_state["model"]["b"])


def test_sigkill_mid_payload_write_commits_nothing(tmp_path) -> None:
    root = str(tmp_path)
    step0 = _take_step0(root)
    _kill_mid_save(root, str(tmp_path / "gate"), "payload")

    partial = os.path.join(root, f"step_{1:010d}")
    payloads = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(partial)
        for f in fs
        if not f.startswith(".") and ".tmp." not in f
    ]
    assert payloads, "the first payload should have landed before the kill"
    _assert_uncommitted_and_recoverable(root, step0)


def test_sigkill_during_metadata_write_commits_nothing(tmp_path) -> None:
    """Crash at the exact commit point: the metadata temp file is fully
    written but never renamed — the snapshot must still read as
    uncommitted (this is what temp+rename atomicity buys)."""
    root = str(tmp_path)
    step0 = _take_step0(root)
    _kill_mid_save(root, str(tmp_path / "gate"), "metadata")

    partial = os.path.join(root, f"step_{1:010d}")
    tmp_files = [f for f in os.listdir(partial) if ".tmp." in f]
    assert tmp_files, "the metadata temp file should exist (crash pre-rename)"
    _assert_uncommitted_and_recoverable(root, step0)
