"""SIGKILL crash-consistency: a writer killed mid-save commits nothing.

The commit protocol's crash-safety claim (snapshot.py: ``.snapshot_metadata``
is written only after every payload write completes; fs.py: every file lands
via temp+rename, so no path ever holds a partial write) has real fault tests
for *process-visible* failures (exceptions, peer aborts) but none for the
failure those mechanisms exist for: the process dying with no chance to run
``finally`` blocks. These tests SIGKILL a real writer subprocess at two
surgically-chosen points and verify every recovery surface:

- the partial directory has payloads but no ``.snapshot_metadata``;
- ``Snapshot(path).restore`` refuses it with a clean error;
- ``CheckpointManager`` resume discovery skips it and the previous committed
  step restores bit-exact;
- the ``verify`` CLI reports it as an error (exit 2) instead of crashing;
- a kill *during the metadata write itself* (after the temp file is fully
  written, before the rename) still leaves the snapshot uncommitted — the
  atomic-rename commit point.

The reference relies on the same metadata-last design
(/root/reference/torchsnapshot/snapshot.py:234-252 writes metadata after the
pending I/O work completes) but ships no kill test; this is the crash drill
for it.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict
from torchsnapshot_tpu.cli import main as cli_main

# The child stalls inside the fs plugin at a chosen point, touches a gate
# file so the parent knows the stall point was reached, then sleeps until
# SIGKILLed. Payload values are deterministic (arange) so the parent can
# verify the surviving step without shipping arrays across processes.
_CHILD = r"""
import asyncio, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.storage_plugins import fs as fs_mod

root, gate, stall_at = sys.argv[1], sys.argv[2], sys.argv[3]

orig_write = fs_mod.FSStoragePlugin.write
n_payload_writes = 0
first_payload_durable = asyncio.Event()

async def gated_write(self, write_io):
    global n_payload_writes
    is_meta = write_io.path.endswith(".snapshot_metadata")
    if stall_at == "payload" and not is_meta:
        # Let the first payload land fully, then stall the second forever:
        # the take is killed with SOME payloads durable and no metadata.
        # The writes run concurrently, so the stalling task must WAIT for
        # the first write's temp+rename to complete before signalling the
        # parent — otherwise the kill can land before anything is durable.
        n_payload_writes += 1
        if n_payload_writes == 1:
            await orig_write(self, write_io)
            first_payload_durable.set()
            return
        await first_payload_durable.wait()
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    if stall_at == "metadata" and is_meta:
        # Write the metadata TEMP file completely, then stall before the
        # rename: a kill here is a crash at the exact commit point.
        path = os.path.join(self.root, write_io.path)
        await self._ensure_parent(path)
        with open(path + ".tmp.crashtest", "wb") as f:
            f.write(bytes(write_io.buf))
        with open(gate, "w") as f:
            f.write("stalled")
        await asyncio.sleep(600)
    await orig_write(self, write_io)

fs_mod.FSStoragePlugin.write = gated_write

state = {
    "model": StateDict(
        w=np.arange(64_000, dtype=np.float32),
        b=np.arange(8_000, dtype=np.float64),
    )
}
Snapshot.take(os.path.join(root, f"step_{1:010d}"), state)
"""


def _take_step0(root: str) -> dict:
    state = {
        "model": StateDict(
            w=np.arange(64_000, dtype=np.float32) * 2.0,
            b=np.arange(8_000, dtype=np.float64) * 3.0,
        )
    }
    Snapshot.take(os.path.join(root, f"step_{0:010d}"), state)
    return state


def _spawn_writer_until_gate(child_src: str, argv: list, gate: str):
    """Spawn a writer child and block until it touches ``gate``.

    stderr goes to a file, not a PIPE: nobody drains a pipe while the
    parent polls for the gate, and a chatty child (XLA init warnings)
    would block on a full pipe before ever reaching the stall point.
    Returns (proc, err_path); the caller decides when to SIGKILL.
    """
    err_path = gate + ".stderr"
    with open(err_path, "wb") as err:
        proc = subprocess.Popen(
            [sys.executable, "-c", child_src, *argv],
            stdout=subprocess.DEVNULL,
            stderr=err,
        )
        deadline = time.monotonic() + 120
        while not os.path.exists(gate):
            if proc.poll() is not None:
                with open(err_path) as f:
                    raise AssertionError(
                        "writer exited before reaching the gate:\n" + f.read()
                    )
            if time.monotonic() > deadline:
                proc.kill()
                raise AssertionError("writer never reached the gate")
            time.sleep(0.01)
    return proc, err_path


def _sigkill(proc, err_path: str, allow_clean_exit: bool = False) -> None:
    """SIGKILL the writer: no atexit, no finally, no cleanup. A child that
    DIED ON ITS OWN before the kill is a real writer failure, not a crash
    simulation — surface its stderr instead of letting it masquerade as
    the uncommitted outcome (unless the caller expects completion)."""
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)
    ok = (0,) if allow_clean_exit else ()
    if proc.returncode != -signal.SIGKILL and proc.returncode not in ok:
        with open(err_path) as f:
            raise AssertionError(
                f"writer exited on its own (rc={proc.returncode}) before "
                "the kill — a genuine failure, not a simulated crash:\n"
                + f.read()
            )


def _kill_mid_save(root: str, gate: str, stall_at: str) -> None:
    proc, err_path = _spawn_writer_until_gate(
        _CHILD, [root, gate, stall_at], gate
    )
    _sigkill(proc, err_path)


def _assert_uncommitted_and_recoverable(root: str, step0_state: dict) -> None:
    partial = os.path.join(root, f"step_{1:010d}")
    assert os.path.isdir(partial), "the kill should leave the partial dir"
    assert not os.path.exists(
        os.path.join(partial, ".snapshot_metadata")
    ), "a killed writer must never leave a committed metadata file"

    # Restore refuses the partial snapshot with a clean error, not garbage.
    dst = {"model": StateDict(w=np.zeros(1, np.float32))}
    with pytest.raises((FileNotFoundError, RuntimeError, ValueError)):
        Snapshot(path=partial).restore(dst)

    # verify CLI: clean error exit, no traceback.
    assert cli_main(["verify", partial]) == 2

    # Resume discovery skips the partial step and the prior step is intact.
    mgr = CheckpointManager(root)
    assert mgr.all_steps() == [0]
    assert mgr.latest_step() == 0
    dst = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=mgr.path_for(0)).restore(dst)
    np.testing.assert_array_equal(dst["model"]["w"], step0_state["model"]["w"])
    np.testing.assert_array_equal(dst["model"]["b"], step0_state["model"]["b"])


def test_sigkill_mid_payload_write_commits_nothing(tmp_path) -> None:
    root = str(tmp_path)
    step0 = _take_step0(root)
    _kill_mid_save(root, str(tmp_path / "gate"), "payload")

    partial = os.path.join(root, f"step_{1:010d}")
    payloads = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(partial)
        for f in fs
        if not f.startswith(".") and ".tmp." not in f
    ]
    assert payloads, "the first payload should have landed before the kill"
    _assert_uncommitted_and_recoverable(root, step0)


def test_sigkill_during_metadata_write_commits_nothing(tmp_path) -> None:
    """Crash at the exact commit point: the metadata temp file is fully
    written but never renamed — the snapshot must still read as
    uncommitted (this is what temp+rename atomicity buys)."""
    root = str(tmp_path)
    step0 = _take_step0(root)
    _kill_mid_save(root, str(tmp_path / "gate"), "metadata")

    partial = os.path.join(root, f"step_{1:010d}")
    tmp_files = [f for f in os.listdir(partial) if ".tmp." in f]
    assert tmp_files, "the metadata temp file should exist (crash pre-rename)"
    _assert_uncommitted_and_recoverable(root, step0)


# ----------------------------------------------------------- randomized

# Unlike _CHILD, no stall point: the child takes a real ~96 MB snapshot at
# full speed and touches the gate right before Snapshot.take so the parent
# can sample a kill time anywhere in (or past) the take window.
_CHILD_FREE = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict

root, gate = sys.argv[1], sys.argv[2]
state = {
    "model": StateDict(
        **{f"p{i}": np.full(3_000_000, i, dtype=np.float32) for i in range(8)}
    )
}
with open(gate, "w") as f:
    f.write("taking")
Snapshot.take(os.path.join(root, f"step_{1:010d}"), state)
"""


@pytest.mark.slow
def test_random_kill_points_commit_or_recover(tmp_path) -> None:
    """Kill the writer at RANDOM points instead of surgical ones: whatever
    the timing, the outcome must be binary — either the snapshot committed
    (verify passes, every value restores exactly) or it did not (recovery
    surfaces all refuse it and step_0 is intact). Any third outcome —
    committed-but-corrupt, partially-restorable — is the bug class this
    drill exists to catch. Seeded RNG, printed per-iteration, for replay.

    Two iterations are deterministic so BOTH outcomes always occur: an
    immediate kill (uncommitted) and a kill only after the metadata file
    appears (committed — a crash just after the commit point must leave a
    fully valid snapshot). The random delays between them are calibrated
    against one unkilled take timed on this host under current load."""
    import random
    import shutil

    rng = random.Random(0xC0FFEE)
    root = str(tmp_path)
    step0 = _take_step0(root)
    partial = os.path.join(root, f"step_{1:010d}")
    outcomes = {"committed": 0, "uncommitted": 0}

    # Calibrate: one unkilled take, timed from the gate to the metadata
    # file appearing, so random kill points span THIS host's take window.
    gate = str(tmp_path / "gate_cal")
    proc, err_path = _spawn_writer_until_gate(_CHILD_FREE, [root, gate], gate)
    t0 = time.monotonic()
    meta = os.path.join(partial, ".snapshot_metadata")
    while not os.path.exists(meta):
        assert time.monotonic() - t0 < 120, "calibration take never finished"
        assert proc.poll() is None or proc.returncode == 0
        time.sleep(0.01)
    t_take = time.monotonic() - t0
    proc.wait(timeout=30)
    assert proc.returncode == 0
    print(f"calibration: take window {t_take:.3f}s")

    for it in range(6):
        shutil.rmtree(partial, ignore_errors=True)
        gate = str(tmp_path / f"gate_{it}")
        if it == 0:
            delay = 0.0  # guaranteed early kill -> uncommitted
        elif it == 1:
            delay = None  # kill right AFTER the commit point -> committed
        else:
            delay = rng.uniform(0.0, 1.2) * t_take
        proc, err_path = _spawn_writer_until_gate(
            _CHILD_FREE, [root, gate], gate
        )
        if delay is None:
            t0 = time.monotonic()
            while not os.path.exists(os.path.join(partial, ".snapshot_metadata")):
                assert time.monotonic() - t0 < 120
                time.sleep(0.005)
        else:
            time.sleep(delay)
        # A take that outran a long delay exits cleanly first — that is the
        # committed outcome, not a writer failure.
        _sigkill(proc, err_path, allow_clean_exit=True)

        committed = os.path.exists(os.path.join(partial, ".snapshot_metadata"))
        label = "post-commit" if delay is None else f"{delay:.3f}s"
        print(f"iter {it}: delay={label} -> "
              f"{'committed' if committed else 'uncommitted'}")
        if committed:
            outcomes["committed"] += 1
            # Fully valid: checksums verify and every leaf restores exactly.
            assert cli_main(["verify", partial]) == 0
            dst = {
                "model": StateDict(
                    **{
                        f"p{i}": np.zeros(3_000_000, np.float32)
                        for i in range(8)
                    }
                )
            }
            Snapshot(path=partial).restore(dst)
            for i in range(8):
                np.testing.assert_array_equal(
                    dst["model"][f"p{i}"],
                    np.full(3_000_000, i, dtype=np.float32),
                )
        else:
            outcomes["uncommitted"] += 1
            dst = {"model": StateDict(w=np.zeros(1, np.float32))}
            with pytest.raises((FileNotFoundError, RuntimeError, ValueError)):
                Snapshot(path=partial).restore(dst)
            mgr = CheckpointManager(root)
            assert mgr.all_steps() == [0]

    # step_0 survived every kill, bit-exact.
    dst = {
        "model": StateDict(
            w=np.zeros(64_000, np.float32), b=np.zeros(8_000, np.float64)
        )
    }
    Snapshot(path=os.path.join(root, f"step_{0:010d}")).restore(dst)
    np.testing.assert_array_equal(dst["model"]["w"], step0["model"]["w"])
    np.testing.assert_array_equal(dst["model"]["b"], step0["model"]["b"])
    print(f"outcomes: {outcomes}")
    # The deterministic iterations guarantee both branches really ran.
    assert outcomes["committed"] >= 1 and outcomes["uncommitted"] >= 1
