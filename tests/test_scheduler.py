"""Scheduler pipeline tests: budget compliance, starvation escape, pipelining.

Reference patterns: plan-level tests with in-memory storage
(tests/test_batcher.py:268-281 style) + white-box budget assertions.
"""

import asyncio
from typing import Dict, Optional

import pytest

from torchsnapshot_tpu.io_types import (
    BufferConsumer,
    BufferStager,
    ReadIO,
    ReadReq,
    StoragePlugin,
    WriteIO,
    WriteReq,
)
from torchsnapshot_tpu.scheduler import (
    execute_write_reqs,
    execute_read_reqs,
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
    sync_execute_write_reqs,
)


class InMemoryStoragePlugin(StoragePlugin):
    def __init__(self, delay: float = 0.0) -> None:
        self.storage: Dict[str, bytes] = {}
        self.delay = delay

    async def write(self, write_io: WriteIO) -> None:
        await asyncio.sleep(self.delay)
        self.storage[write_io.path] = bytes(write_io.buf)

    async def read(self, read_io: ReadIO) -> None:
        await asyncio.sleep(self.delay)
        data = self.storage[read_io.path]
        if read_io.byte_range is not None:
            lo, hi = read_io.byte_range
            data = data[lo:hi]
        read_io.buf = bytearray(data)

    async def delete(self, path: str) -> None:
        del self.storage[path]

    async def close(self) -> None:
        pass


class TrackingStager(BufferStager):
    """Stager instrumented to observe peak concurrent staging cost."""

    live_bytes = 0
    peak_bytes = 0

    def __init__(self, payload: bytes, delay: float = 0.005) -> None:
        self.payload = payload
        self.delay = delay

    async def stage_buffer(self, executor=None):
        cls = TrackingStager
        cls.live_bytes += len(self.payload)
        cls.peak_bytes = max(cls.peak_bytes, cls.live_bytes)
        await asyncio.sleep(self.delay)
        # NOTE: live_bytes decremented when I/O completes (the scheduler holds
        # the buffer until written) — handled by the storage wrapper below.
        return self.payload

    def get_staging_cost_bytes(self) -> int:
        return len(self.payload)


class ReleasingStoragePlugin(InMemoryStoragePlugin):
    async def write(self, write_io: WriteIO) -> None:
        await super().write(write_io)
        TrackingStager.live_bytes -= len(write_io.buf)


class SimpleConsumer(BufferConsumer):
    def __init__(self, sink: Dict[str, bytes], key: str, cost: int) -> None:
        self.sink = sink
        self.key = key
        self.cost = cost

    async def consume_buffer(self, buf, executor=None) -> None:
        self.sink[self.key] = bytes(buf)

    def get_consuming_cost_bytes(self) -> int:
        return self.cost


def _make_write_reqs(n: int, size: int):
    return [
        WriteReq(path=f"obj_{i}", buffer_stager=TrackingStager(bytes([i % 256]) * size))
        for i in range(n)
    ]


def _reset_tracking():
    TrackingStager.live_bytes = 0
    TrackingStager.peak_bytes = 0


def test_write_all_completed() -> None:
    _reset_tracking()
    loop = asyncio.new_event_loop()
    storage = InMemoryStoragePlugin()
    reqs = _make_write_reqs(20, 100)
    sync_execute_write_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    loop.close()
    assert len(storage.storage) == 20
    assert storage.storage["obj_3"] == bytes([3]) * 100


def test_budget_respected() -> None:
    _reset_tracking()
    loop = asyncio.new_event_loop()
    storage = ReleasingStoragePlugin(delay=0.002)
    reqs = _make_write_reqs(16, 1000)
    sync_execute_write_reqs(reqs, storage, 3000, rank=0, event_loop=loop)
    loop.close()
    assert len(storage.storage) == 16
    assert TrackingStager.peak_bytes <= 3000


def test_oversized_request_does_not_deadlock() -> None:
    _reset_tracking()
    loop = asyncio.new_event_loop()
    storage = InMemoryStoragePlugin()
    reqs = _make_write_reqs(3, 5000)  # each bigger than budget
    sync_execute_write_reqs(reqs, storage, 1000, rank=0, event_loop=loop)
    loop.close()
    assert len(storage.storage) == 3


def test_pending_io_work_defers_storage_io() -> None:
    """The returned PendingIOWork is the staging-complete consistency point."""
    _reset_tracking()
    loop = asyncio.new_event_loop()
    storage = InMemoryStoragePlugin(delay=0.05)
    reqs = _make_write_reqs(4, 10)
    pending = loop.run_until_complete(
        execute_write_reqs(reqs, storage, 10**9, rank=0)
    )
    # Staging is done for every request, but slow storage I/O may not be.
    staged = [r.buffer_stager for r in reqs]
    assert all(s.payload is not None for s in staged)
    pending.sync_complete(loop)
    loop.close()
    assert len(storage.storage) == 4


def test_read_pipeline() -> None:
    loop = asyncio.new_event_loop()
    storage = InMemoryStoragePlugin()
    storage.storage = {f"k{i}": bytes([i]) * 50 for i in range(10)}
    sink: Dict[str, bytes] = {}
    reqs = [
        ReadReq(path=f"k{i}", buffer_consumer=SimpleConsumer(sink, f"k{i}", 50))
        for i in range(10)
    ]
    sync_execute_read_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    loop.close()
    assert sink == storage.storage


def test_read_with_byte_range() -> None:
    loop = asyncio.new_event_loop()
    storage = InMemoryStoragePlugin()
    storage.storage = {"blob": bytes(range(100))}
    sink: Dict[str, bytes] = {}
    reqs = [
        ReadReq(
            path="blob",
            buffer_consumer=SimpleConsumer(sink, "mid", 30),
            byte_range=(10, 40),
        )
    ]
    sync_execute_read_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    loop.close()
    assert sink["mid"] == bytes(range(10, 40))


def test_read_oversized_budget_escape() -> None:
    loop = asyncio.new_event_loop()
    storage = InMemoryStoragePlugin()
    storage.storage = {"big": b"x" * 10000}
    sink: Dict[str, bytes] = {}
    reqs = [ReadReq(path="big", buffer_consumer=SimpleConsumer(sink, "big", 10000))]
    sync_execute_read_reqs(reqs, storage, 100, rank=0, event_loop=loop)
    loop.close()
    assert sink["big"] == b"x" * 10000


def test_memory_budget_env_override(monkeypatch) -> None:
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES", "12345")
    assert get_process_memory_budget_bytes() == 12345


def test_memory_budget_default_capped() -> None:
    budget = get_process_memory_budget_bytes()
    assert 0 < budget <= 32 * 1024**3


def test_write_error_propagates() -> None:
    class FaultyStorage(InMemoryStoragePlugin):
        async def write(self, write_io: WriteIO) -> None:
            raise RuntimeError("injected storage failure")

    loop = asyncio.new_event_loop()
    reqs = _make_write_reqs(2, 10)
    with pytest.raises(RuntimeError, match="injected storage failure"):
        sync_execute_write_reqs(reqs, FaultyStorage(), 10**9, rank=0, event_loop=loop)
    loop.close()


def test_progress_reporter_logs_pipeline_table(caplog) -> None:
    """The reporter emits stage counts / bytes / budget / RSS
    (reference: _WriteReporter, scheduler.py:96-175)."""
    import logging

    import torchsnapshot_tpu.scheduler as sched

    budget = sched._MemoryBudget(1 << 30)
    budget.acquire(1 << 29)
    reporter = sched._ProgressReporter("write", rank=0, total=8, budget=budget)
    reporter.inflight_staging = 2
    reporter.staged_count = 3
    reporter.staged_bytes = 3 << 20
    reporter.inflight_io = 1
    reporter.completed_count = 2
    reporter.completed_bytes = 2 << 20
    with caplog.at_level(logging.INFO, logger="torchsnapshot_tpu.scheduler"):
        reporter.log_table()
    assert caplog.records, "no progress table logged"
    line = caplog.records[-1].message
    for token in (
        "8 total",
        "2 staging",
        "3 staged",
        "1 in io",
        "2 written",
        "budget free",
        "rss delta",
    ):
        assert token in line, f"missing {token!r} in {line!r}"


def test_write_pipeline_wires_progress_reporter(tmp_path) -> None:
    """execute_write_reqs attaches a periodic reporter that survives into
    the PendingIOWork drain phase."""
    import asyncio

    import numpy as np

    import torchsnapshot_tpu.scheduler as sched
    from torchsnapshot_tpu.io_preparers.array import ArrayIOPreparer
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    reqs = []
    for i in range(3):
        _, wreqs = ArrayIOPreparer.prepare_write(f"0/p{i}", np.ones((64, 64)))
        reqs.extend(wreqs)
    loop = asyncio.new_event_loop()
    storage = FSStoragePlugin(str(tmp_path))
    pending = loop.run_until_complete(
        sched.execute_write_reqs(reqs, storage, 1 << 30, rank=0)
    )
    reporter = pending._reporter
    assert reporter is not None
    assert reporter.staged_count == 3
    pending.sync_complete(loop)
    assert reporter.completed_count == 3
    assert reporter.completed_bytes == 3 * 64 * 64 * 8
    loop.run_until_complete(storage.close())
    loop.close()
