"""The deterministic fault-injection subsystem (faultinject.py) and its
site lint (scripts/check_fault_sites.py).

Covers the plan grammar (triggers, actions, seeding, rejection of
malformed specs), the exact-hit determinism fault schedules rely on, the
exception taxonomy (transient == retryable ConnectionError; permanent ==
OSError), the disabled-is-a-no-op contract the hot paths depend on, a
SIGKILL plan in a real subprocess, end-to-end abort through a real take,
and the lint that keeps every site unique/registered/shim-only.
"""

from __future__ import annotations

import importlib.util
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, faultinject
from torchsnapshot_tpu.faultinject import (
    FaultPlan,
    InjectedFault,
    InjectedPermanentError,
    InjectedTransientError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_fault_sites.py")


@pytest.fixture(autouse=True)
def _clean_injector():
    faultinject.disable()
    yield
    faultinject.disable()


# ------------------------------------------------------------- grammar


@pytest.mark.parametrize(
    "spec",
    [
        "garbage",
        "fs.write=transient",            # no trigger
        "fs.write@=transient",           # empty trigger
        "no.such.site@1=transient",      # unregistered site
        "fs.write@1=explode",            # unknown action
        "fs.write@0=transient",          # hits are 1-based
        "fs.write@p1.5=transient",       # probability outside [0, 1]
        "fs.write@x=transient",          # malformed trigger
        "fs.write@1=delay:abc",          # non-numeric arg
        "seed=1",                        # no rules at all
        "fs.write@1=transient;seed=zz",  # malformed seed
        "",                              # FaultPlan("") directly
    ],
)
def test_malformed_plans_rejected(spec):
    with pytest.raises(ValueError):
        FaultPlan(spec)


def test_configure_and_disable_roundtrip():
    assert not faultinject.active()
    faultinject.configure("fs.write@1=transient")
    assert faultinject.active()
    assert faultinject.active_spec() == "fs.write@1=transient"
    faultinject.disable()
    assert not faultinject.active()
    assert faultinject.hits() == {}


# ------------------------------------------------------- trigger logic


def test_exact_nth_hit_fires_once():
    faultinject.configure("fs.write@3=transient")
    faultinject.site("fs.write")
    faultinject.site("fs.write")
    with pytest.raises(InjectedTransientError):
        faultinject.site("fs.write")
    faultinject.site("fs.write")  # hit 4: no fault
    assert faultinject.hits() == {"fs.write": 4}


def test_open_ended_trigger_fires_from_nth_on():
    faultinject.configure("fs.write@2+=permanent")
    faultinject.site("fs.write")
    for _ in range(3):
        with pytest.raises(InjectedPermanentError):
            faultinject.site("fs.write")


def test_sites_count_independently():
    faultinject.configure("fs.write@2=transient")
    faultinject.site("fs.read")
    faultinject.site("fs.read")
    faultinject.site("fs.write")  # hit 1 of fs.write: no fault
    assert faultinject.hits() == {"fs.read": 2, "fs.write": 1}


def test_probabilistic_trigger_is_seed_deterministic():
    def pattern(seed):
        faultinject.configure(f"fs.write@p0.5=transient;seed={seed}")
        fired = []
        for _ in range(64):
            try:
                faultinject.site("fs.write")
                fired.append(False)
            except InjectedTransientError:
                fired.append(True)
        return fired

    a, b = pattern(7), pattern(7)
    assert a == b, "same seed must replay the same schedule"
    assert any(a) and not all(a)
    assert pattern(8) != a, "a different seed should differ (p=0.5, n=64)"


def test_configure_resets_counters_and_rng():
    faultinject.configure("fs.write@1=transient")
    with pytest.raises(InjectedTransientError):
        faultinject.site("fs.write")
    faultinject.configure("fs.write@1=transient")
    with pytest.raises(InjectedTransientError):
        faultinject.site("fs.write")


# ------------------------------------------------------------- actions


def test_exception_taxonomy():
    faultinject.configure("fs.write@1=transient;fs.read@1=permanent")
    with pytest.raises(ConnectionError) as ti:
        faultinject.site("fs.write")
    assert isinstance(ti.value, InjectedFault)
    with pytest.raises(OSError) as pi:
        faultinject.site("fs.read")
    assert isinstance(pi.value, InjectedFault)
    # permanent must NOT look transient to the retry machinery.
    from torchsnapshot_tpu.storage_plugins.retry import is_transient_error

    assert is_transient_error(ti.value)
    assert not is_transient_error(pi.value)


def test_corrupt_flips_exactly_one_byte_deterministically():
    payload = bytes(range(256)) * 4
    faultinject.configure("fs.write@1=corrupt;seed=5")
    out1 = bytes(faultinject.mutate("fs.write", payload))
    faultinject.configure("fs.write@1=corrupt;seed=5")
    out2 = bytes(faultinject.mutate("fs.write", payload))
    assert out1 == out2, "corrupt offset must be seed-deterministic"
    assert len(out1) == len(payload)
    diffs = [i for i, (a, b) in enumerate(zip(payload, out1)) if a != b]
    assert len(diffs) == 1


def test_corrupt_offset_argument_respected():
    faultinject.configure("fs.write@1=corrupt:3")
    out = bytes(faultinject.mutate("fs.write", b"\x00" * 16))
    assert out[3] == 0xFF and sum(out) == 0xFF


def test_truncate_keeps_fraction():
    faultinject.configure("fs.write@1=truncate:0.25")
    out = faultinject.mutate("fs.write", b"x" * 100)
    assert memoryview(out).nbytes == 25


def test_truncate_default_is_half():
    faultinject.configure("fs.write@1=truncate")
    assert memoryview(faultinject.mutate("fs.write", b"x" * 10)).nbytes == 5


def test_delay_returns_buffer_unchanged():
    faultinject.configure("fs.write@1=delay:0")
    buf = b"abc"
    assert bytes(faultinject.mutate("fs.write", buf)) == b"abc"


def test_data_actions_are_noop_at_control_sites():
    faultinject.configure("dist_store.rpc@1=corrupt")
    faultinject.site("dist_store.rpc")  # must not raise


def test_combined_rules_mutate_then_raise():
    faultinject.configure(
        "fs.write@1=truncate:0.5;fs.write@1=transient"
    )
    with pytest.raises(InjectedTransientError):
        faultinject.mutate("fs.write", b"x" * 8)


# ------------------------------------------------- disabled hot path


def test_disabled_shim_is_identity():
    assert faultinject.site("fs.write") is None
    buf = bytearray(b"payload")
    assert faultinject.mutate("fs.write", buf) is buf
    assert faultinject.hits() == {}


def test_refresh_from_env(monkeypatch):
    monkeypatch.setenv(
        faultinject.FAULT_PLAN_ENV_VAR, "fs.write@1=transient"
    )
    faultinject.refresh_from_env()
    assert faultinject.active()
    monkeypatch.delenv(faultinject.FAULT_PLAN_ENV_VAR)
    faultinject.refresh_from_env()
    assert not faultinject.active()


# ------------------------------------------------------- end to end


def test_staging_fault_aborts_take_without_commit(tmp_path):
    state = {"m": StateDict(w=np.arange(2048, dtype=np.float32))}
    path = str(tmp_path / "snap")
    faultinject.configure("scheduler.stage@1=permanent")
    with pytest.raises(Exception):
        Snapshot.take(path, state)
    faultinject.disable()
    assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))
    # The same path commits cleanly once the plan is gone.
    Snapshot.take(path, state)
    dst = {"m": StateDict(w=np.zeros(2048, np.float32))}
    Snapshot(path).restore(dst)
    np.testing.assert_array_equal(dst["m"]["w"], state["m"]["w"])


def test_transient_storage_fault_is_retried_by_s3(tmp_path):
    """An injected transient at the retry-wrapped s3 boundary is absorbed
    by the collective retry strategy — the take commits anyway."""
    from tests.test_s3_storage_plugin import FakeS3Client
    from torchsnapshot_tpu.storage_plugins.retry import (
        CollectiveRetryStrategy,
    )

    async def _nosleep(_s):
        return None

    client = FakeS3Client()
    opts = {
        "client": client,
        "retry_strategy": CollectiveRetryStrategy(sleep=_nosleep),
    }
    state = {"m": StateDict(w=np.arange(512, dtype=np.float32))}
    faultinject.configure("s3.put@1=transient")
    Snapshot.take("s3://bucket/chaos", state, storage_options=opts)
    faultinject.disable()
    dst = {"m": StateDict(w=np.zeros(512, np.float32))}
    Snapshot("s3://bucket/chaos", storage_options=opts).restore(dst)
    np.testing.assert_array_equal(dst["m"]["w"], state["m"]["w"])


def test_transient_read_fault_is_retried_by_gcs(tmp_path, monkeypatch):
    """An injected transient at gcs.get is absorbed by the retry
    machinery — the site sits INSIDE the retried closure (like s3.get),
    so the drill exercises the real retry path instead of escaping
    after a successful fetch."""
    from tests.test_gcs_storage_plugin import FakeBucket
    from torchsnapshot_tpu.storage_plugins import gcs as gcs_mod
    from torchsnapshot_tpu.storage_plugins.retry import (
        CollectiveRetryStrategy,
    )

    async def _nosleep(_s):
        return None

    bucket = FakeBucket()
    monkeypatch.setattr(
        gcs_mod.GCSStoragePlugin,
        "_make_bucket",
        staticmethod(lambda name, options: bucket),
    )
    opts = {"retry_strategy": CollectiveRetryStrategy(sleep=_nosleep)}
    state = {"m": StateDict(w=np.arange(512, dtype=np.float32))}
    Snapshot.take("gs://bkt/chaos", state, storage_options=opts)
    faultinject.configure("gcs.get@1=transient")
    dst = {"m": StateDict(w=np.zeros(512, np.float32))}
    Snapshot("gs://bkt/chaos", storage_options=opts).restore(dst)
    faultinject.disable()
    np.testing.assert_array_equal(dst["m"]["w"], state["m"]["w"])


def test_kill_plan_sigkills_subprocess(tmp_path):
    """A kill action takes the process down with SIGKILL — no atexit, no
    finally — exactly at the targeted site hit."""
    child = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "from torchsnapshot_tpu import Snapshot, StateDict\n"
        "state = {'m': StateDict(w=np.arange(2048, dtype=np.float32))}\n"
        f"Snapshot.take({str(tmp_path / 'snap')!r}, state)\n"
        "print('UNREACHABLE')\n"
    )
    env = dict(os.environ)
    env["TORCHSNAPSHOT_TPU_FAULT_PLAN"] = "commit.metadata@1=kill"
    r = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    assert r.returncode == -signal.SIGKILL, r.stderr
    assert "UNREACHABLE" not in r.stdout
    # Killed at the commit point: fence present, metadata absent.
    assert not os.path.exists(tmp_path / "snap" / ".snapshot_metadata")
    assert os.path.exists(tmp_path / "snap" / ".snapshot_fence")


# ------------------------------------------------------------- lint


def _load_lint():
    spec = importlib.util.spec_from_file_location("check_fault_sites", LINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fault_site_lint_package_clean():
    r = subprocess.run(
        [sys.executable, LINT], capture_output=True, text=True, timeout=120
    )
    assert r.returncode == 0, r.stderr


def test_fault_site_lint_detects_violations():
    lint = _load_lint()
    violations, uses = lint.check_source(
        "from . import faultinject\n"
        "from .faultinject import site\n"           # bypasses the shim
        "faultinject.site('no.such.site')\n"        # unregistered
        "faultinject.site(some_variable)\n"         # non-literal
        "faultinject.configure('fs.write@1=kill')\n"  # past the shim
        "faultinject.mutate('fs.write', b'x')\n"    # the one clean call
        "",
        "<test>",
    )
    whats = "\n".join(w for _, w in violations)
    assert "from ...faultinject import" in whats
    assert "no.such.site" in whats
    assert "string literal" in whats
    assert "faultinject.configure" in whats
    assert uses == {"fs.write": [6]}


def test_fault_site_lint_rejects_duplicate_and_dead_sites(tmp_path):
    lint = _load_lint()
    # Two call sites for one name -> non-deterministic schedules; and the
    # synthetic package wires almost nothing, so every other registered
    # site must be reported as dead.
    (tmp_path / "a.py").write_text(
        "from . import faultinject\nfaultinject.site('fs.write')\n"
    )
    (tmp_path / "b.py").write_text(
        "from . import faultinject\nfaultinject.site('fs.write')\n"
    )
    failures = "\n".join(lint.run(package_dir=str(tmp_path)))
    assert "2 call sites" in failures
    assert "wired nowhere" in failures


def test_every_registered_site_has_a_kind():
    assert set(faultinject.SITES.values()) <= {"control", "data"}
    assert faultinject.KNOWN_SITES == frozenset(faultinject.SITES)


def test_malformed_env_plan_does_not_break_import(tmp_path):
    """A typo'd TORCHSNAPSHOT_TPU_FAULT_PLAN must not make the package
    unimportable (the fsck/verify CLIs import it too) — import warns
    loudly and runs uninjected; configure() still raises."""
    import subprocess
    import sys

    child = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from torchsnapshot_tpu import faultinject\n"
        "assert not faultinject.active()\n"
        "print('IMPORT_OK')\n"
    )
    env = dict(os.environ)
    env["TORCHSNAPSHOT_TPU_FAULT_PLAN"] = "fs.write@0=kill"  # 1-based: invalid
    r = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    assert r.returncode == 0 and "IMPORT_OK" in r.stdout, r.stderr[-800:]
    assert "ignoring malformed" in r.stderr
    # Deliberate configuration still fails fast.
    with pytest.raises(ValueError, match="1-based"):
        faultinject.configure("fs.write@0=kill")
