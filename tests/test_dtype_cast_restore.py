"""Restore into a destination of a DIFFERENT dtype casts to the destination.

The destination app state is the spec — shape, sharding, and dtype. Restoring
a bf16 checkpoint into fp32 params (or vice versa: a precision-recipe change
mid-training-run) must produce arrays with the DESTINATION's dtype, mirroring
the reference's ``dst.copy_(src)`` semantics (reference io_preparer.py:426-427
— torch's copy_ casts into the pre-built tensor), so a jitted train step keeps
its compiled dtype. Divergence: only ``same_kind`` casts are allowed — a
float->int restore raises instead of silently truncating.

Covers every destination shape the preparers dispatch on: plain jax, numpy
in-place, chunked entries, sharded entries into jax (same mesh, resharded,
and dense) and into numpy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict


def _take(tmp_path, **leaves) -> str:
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": StateDict(**leaves)})
    return path


def _restore(path, **leaves):
    dst = {"m": StateDict(**leaves)}
    Snapshot(path=path).restore(dst)
    return dst["m"]


def test_plain_jax_bf16_checkpoint_into_fp32_params(tmp_path):
    src = jnp.arange(256, dtype=jnp.bfloat16)
    path = _take(tmp_path, w=src)
    out = _restore(path, w=jnp.zeros(256, jnp.float32))["w"]
    assert out.dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(256, dtype=np.float32)
    )


def test_plain_jax_fp32_checkpoint_into_bf16_params(tmp_path):
    # Small integers are exact in bf16, so equality is well-defined.
    src = jnp.arange(256, dtype=jnp.float32)
    path = _take(tmp_path, w=src)
    out = _restore(path, w=jnp.zeros(256, jnp.bfloat16))["w"]
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(256, dtype="float32").astype("bfloat16")
    )


def test_numpy_inplace_cast(tmp_path):
    src = np.arange(128, dtype="bfloat16")
    path = _take(tmp_path, w=src)
    dst = np.zeros(128, np.float32)
    Snapshot(path=path).restore({"m": StateDict(w=dst)})
    np.testing.assert_array_equal(dst, np.arange(128, dtype=np.float32))


def test_float_to_int_restore_refused(tmp_path):
    path = _take(tmp_path, w=jnp.arange(16, dtype=jnp.float32))
    with pytest.raises(RuntimeError, match="cannot be cast"):
        _restore(path, w=jnp.zeros(16, jnp.int32))


def test_chunked_entry_cast(tmp_path):
    from torchsnapshot_tpu.io_preparers import chunked

    old = chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES
    chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = 1024
    try:
        src = jnp.arange(4 * 256, dtype=jnp.float32).reshape(4, 256)
        path = _take(tmp_path, w=src)
        out = _restore(path, w=jnp.zeros((4, 256), jnp.bfloat16))["w"]
    finally:
        chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = old
    assert out.dtype == jnp.bfloat16
    # bf16 rounds large arange values; compare against the exact cast.
    np.testing.assert_array_equal(
        np.asarray(out),
        np.arange(4 * 256, dtype="float32").reshape(4, 256).astype("bfloat16"),
    )


def test_chunked_into_numpy_cast(tmp_path):
    """Multi-chunk entry into a mismatched-dtype numpy destination (the
    chunked assembler's fill-region cast path)."""
    from torchsnapshot_tpu.io_preparers import chunked

    old = chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES
    chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = 1024
    try:
        src = np.arange(4 * 256, dtype=np.float32).reshape(4, 256)
        path = _take(tmp_path, w=src)
        dst = np.zeros((4, 256), dtype="bfloat16")
        Snapshot(path=path).restore({"m": StateDict(w=dst)})
    finally:
        chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = old
    np.testing.assert_array_equal(dst, src.astype("bfloat16"))


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("x", "y"))


def test_sharded_cast_same_mesh(tmp_path):
    mesh = _mesh()
    data = np.arange(32 * 16, dtype="bfloat16").reshape(32, 16)
    src = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", "y")))
    path = _take(tmp_path, w=src)
    dst = jax.device_put(
        jnp.zeros((32, 16), jnp.float32), NamedSharding(mesh, P("x", "y"))
    )
    out = _restore(path, w=dst)["w"]
    assert out.dtype == jnp.float32
    assert out.sharding == dst.sharding
    np.testing.assert_array_equal(
        np.asarray(out), data.astype(np.float32)
    )


def test_sharded_cast_with_reshard(tmp_path):
    """Dtype cast composes with a sharding-layout change on restore."""
    mesh = _mesh()
    data = np.arange(32 * 16, dtype="float32").reshape(32, 16)
    src = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", None)))
    path = _take(tmp_path, w=src)
    dst = jax.device_put(
        jnp.zeros((32, 16), jnp.bfloat16), NamedSharding(mesh, P(None, "y"))
    )
    out = _restore(path, w=dst)["w"]
    assert out.dtype == jnp.bfloat16
    assert out.sharding == dst.sharding
    np.testing.assert_array_equal(np.asarray(out), data.astype("bfloat16"))


def test_sharded_to_dense_cast(tmp_path):
    mesh = _mesh()
    data = np.arange(32 * 16, dtype="bfloat16").reshape(32, 16)
    src = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", "y")))
    path = _take(tmp_path, w=src)
    out = _restore(path, w=jnp.zeros((32, 16), jnp.float32))["w"]
    assert out.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(out), data.astype(np.float32))


def test_sharded_to_numpy_cast(tmp_path):
    mesh = _mesh()
    data = np.arange(32 * 16, dtype="bfloat16").reshape(32, 16)
    src = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", "y")))
    path = _take(tmp_path, w=src)
    dst = np.zeros((32, 16), np.float32)
    Snapshot(path=path).restore({"m": StateDict(w=dst)})
    np.testing.assert_array_equal(dst, data.astype(np.float32))


def test_sharded_float_to_int_refused(tmp_path):
    mesh = _mesh()
    src = jax.device_put(
        jnp.arange(32, dtype=jnp.float32), NamedSharding(mesh, P("x"))
    )
    path = _take(tmp_path, w=src)
    dst = jax.device_put(
        jnp.zeros(32, jnp.int32), NamedSharding(mesh, P("x"))
    )
    with pytest.raises(RuntimeError, match="cannot be cast"):
        _restore(path, w=dst)


def test_matching_dtype_unaffected(tmp_path):
    """The no-cast fast path stays byte-exact (no same_kind detour)."""
    src = jnp.arange(256, dtype=jnp.bfloat16)
    path = _take(tmp_path, w=src)
    out = _restore(path, w=jnp.zeros(256, jnp.bfloat16))["w"]
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out), np.asarray(src))
