"""Closed-loop autotune (autotune.py + the IOGovernor election sites):
rate smoothing, gate hysteresis across every ``should_*`` knee, the
perturb/score/revert controller under noisy verdicts, profile
persistence, and the unattributed-verdict skip path."""

from __future__ import annotations

import random

import pytest

from torchsnapshot_tpu import telemetry
from torchsnapshot_tpu.autotune import AutoTuner, profile_key
from torchsnapshot_tpu.scheduler import (
    _DEFAULT_SUB_CHUNK_BYTES,
    _IO_CONCURRENCY_CAP,
    _KNEE_MARGIN,
    _NATIVE_FALLBACK_MARGIN,
    _PREVERIFY_READ_MARGIN,
    _STREAM_READ_LATENCY_BPS,
    IOGovernor,
)
from torchsnapshot_tpu.telemetry import history

MB = 1 << 20

_ELECTION_ENV = (
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES",
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_MIN_BYTES",
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_MAX_BYTES",
    "TORCHSNAPSHOT_TPU_IO_CONCURRENCY",
    "TORCHSNAPSHOT_TPU_PREVERIFY",
    "TORCHSNAPSHOT_TPU_AUTOTUNE",
)


@pytest.fixture
def clean_env(monkeypatch):
    """Elections see no ambient overrides; individual tests opt knobs
    back in with monkeypatch.setenv."""
    for var in _ELECTION_ENV:
        monkeypatch.delenv(var, raising=False)
    return monkeypatch


@pytest.fixture
def heuristic_env(clean_env):
    """Pure measured-rate heuristics: autotune off isolates the gate
    logic from the tuner plane (and proves ``never`` keeps it intact)."""
    clean_env.setenv("TORCHSNAPSHOT_TPU_AUTOTUNE", "never")
    return clean_env


def _set_read(gov, plugin, bps):
    with gov._lock:
        gov._read_bps[plugin] = bps


def _set_write(gov, plugin, bps):
    with gov._lock:
        gov._write_bps[plugin] = bps


# ------------------------------------------------------- rate smoothing


def test_ewma_first_sample_is_taken_verbatim(heuristic_env):
    gov = IOGovernor()
    gov.record_write("fs", 1 << 30, 1.0)
    assert gov.write_bps("fs") == pytest.approx(1 << 30)
    gov.record_read("fs", 1 << 30, 2.0)
    assert gov.read_bps("fs") == pytest.approx((1 << 30) / 2.0)
    gov.record_hash(1 << 30, 4.0)
    assert gov.hash_bps() == pytest.approx((1 << 30) / 4.0)


def test_ewma_alpha_half_smoothing(heuristic_env):
    gov = IOGovernor()
    gov.record_write("fs", 1 << 30, 1.0)  # 1 GiB/s
    gov.record_write("fs", 1 << 30, 0.25)  # 4 GiB/s sample
    # prev + 0.5 * (sample - prev) = 2.5 GiB/s
    assert gov.write_bps("fs") == pytest.approx(2.5 * (1 << 30))
    # One anomalous sample moves the rate halfway at most.
    gov.record_write("fs", 1 << 30, 100.0)
    assert gov.write_bps("fs") > 1.25 * (1 << 30)


def test_ewma_rejects_degenerate_samples(heuristic_env):
    gov = IOGovernor()
    gov.record_write("fs", 0, 1.0)
    gov.record_write("fs", 1 << 20, 0.0)
    gov.record_read("fs", -1, 1.0)
    assert gov.write_bps("fs") is None
    assert gov.read_bps("fs") is None


def test_rates_are_per_plugin(heuristic_env):
    gov = IOGovernor()
    gov.record_write("fs", 1 << 30, 1.0)
    gov.record_write("gcs", 1 << 27, 1.0)
    assert gov.write_bps("fs") == pytest.approx(1 << 30)
    assert gov.write_bps("gcs") == pytest.approx(1 << 27)
    assert gov.write_bps() == pytest.approx(1 << 30)  # best-known


# ------------------------------------------- gate hysteresis at the knee


def test_preverify_gate_crosses_knee_both_ways_without_flip_flop(
    heuristic_env,
):
    gov = IOGovernor()
    # No evidence: verify (the zero-byte path).
    assert gov.should_preverify("fs") is True
    gov.record_hash(1 << 30, 1.0 * (1 << 30) / 1e9)  # hash at 1 GB/s
    knee = 1e9 * _PREVERIFY_READ_MARGIN  # 1.25 GB/s crossover

    _set_read(gov, "fs", 2.0e9)  # reads clearly cheaper than hashing
    assert gov.should_preverify("fs") is False
    # Jitter back inside the dead band: no flip.
    _set_read(gov, "fs", knee * (1.0 - _KNEE_MARGIN / 2))
    assert gov.should_preverify("fs") is False
    # Clearly below the band: verify again.
    _set_read(gov, "fs", knee * (1.0 - 2 * _KNEE_MARGIN))
    assert gov.should_preverify("fs") is True
    # Jitter above the knee but inside the band: still no flip.
    _set_read(gov, "fs", knee * (1.0 + _KNEE_MARGIN / 2))
    assert gov.should_preverify("fs") is True
    # Clearly above: skip the verify pass.
    _set_read(gov, "fs", knee * (1.0 + 2 * _KNEE_MARGIN))
    assert gov.should_preverify("fs") is False


def test_preverify_env_overrides_beat_measurement(clean_env):
    gov = IOGovernor()
    gov.record_hash(1 << 30, 1.0)
    _set_read(gov, "fs", 100e9)  # measurement says skip
    clean_env.setenv("TORCHSNAPSHOT_TPU_PREVERIFY", "always")
    assert gov.should_preverify("fs") is True
    clean_env.setenv("TORCHSNAPSHOT_TPU_PREVERIFY", "never")
    assert gov.should_preverify("fs") is False


def test_native_write_gate_optimistic_then_deposed_then_recovers(
    heuristic_env,
):
    gov = IOGovernor()
    # Unmeasured: optimistic (queued SQEs are never worse than pwrite).
    assert gov.should_native_io("fs", op="write") is True
    _set_write(gov, "fs", 1.0e9)
    assert gov.should_native_io("fs", op="write") is True  # native unmeasured
    _set_write(gov, "fs.native", _NATIVE_FALLBACK_MARGIN * 1.0e9 - 1e6)
    assert gov.should_native_io("fs", op="write") is False  # clearly below
    _set_write(gov, "fs.native", 0.9e9)
    assert gov.should_native_io("fs", op="write") is True  # recovers


def test_native_read_gate_engages_only_on_latency_bound_storage(
    heuristic_env,
):
    gov = IOGovernor()
    # No measured base rate: no evidence, Python path.
    assert gov.should_native_io("fs", op="read") is False
    knee = _STREAM_READ_LATENCY_BPS
    _set_read(gov, "fs.native", 10e9)  # engine itself looks great
    _set_read(gov, "fs", 2 * knee)  # memcpy-speed local reads
    assert gov.should_native_io("fs", op="read") is False
    _set_read(gov, "fs", 0.5 * knee)  # latency-bound storage
    assert gov.should_native_io("fs", op="read") is True
    # Band: hovering just above the knee must not flip it off...
    _set_read(gov, "fs", knee * (1.0 + _KNEE_MARGIN / 2))
    assert gov.should_native_io("fs", op="read") is True
    # ...but clearly crossing it must.
    _set_read(gov, "fs", knee * (1.0 + 2 * _KNEE_MARGIN))
    assert gov.should_native_io("fs", op="read") is False
    # And just below the knee stays off until clearly below the band.
    _set_read(gov, "fs", knee * (1.0 - _KNEE_MARGIN / 2))
    assert gov.should_native_io("fs", op="read") is False
    _set_read(gov, "fs", knee * (1.0 - 2 * _KNEE_MARGIN))
    assert gov.should_native_io("fs", op="read") is True


def test_native_read_gate_deposes_slow_engine_even_when_latency_bound(
    heuristic_env,
):
    gov = IOGovernor()
    base = 0.5 * _STREAM_READ_LATENCY_BPS
    _set_read(gov, "fs", base)
    assert gov.should_native_io("fs", op="read") is True  # engine unmeasured
    _set_read(gov, "fs.native", _NATIVE_FALLBACK_MARGIN * base - 1e6)
    assert gov.should_native_io("fs", op="read") is False
    _set_read(gov, "fs.native", _NATIVE_FALLBACK_MARGIN * base + 1e6)
    assert gov.should_native_io("fs", op="read") is True


@pytest.mark.parametrize(
    "gate", ["should_coop_restore", "should_planned_reshard", "should_seed_restore"]
)
def test_latency_knee_gates_cross_both_ways_without_flip_flop(
    heuristic_env, gate
):
    gov = IOGovernor()
    decide = getattr(gov, gate)
    # No recorded read rate: no evidence, the status quo stays.
    assert decide("fs") is False
    knee = _STREAM_READ_LATENCY_BPS
    _set_read(gov, "fs", 0.5 * knee)
    assert decide("fs") is True  # storage-bandwidth-bound: fan out
    _set_read(gov, "fs", knee * (1.0 + _KNEE_MARGIN / 2))
    assert decide("fs") is True  # inside the dead band: no flip
    _set_read(gov, "fs", knee * (1.0 + 2 * _KNEE_MARGIN))
    assert decide("fs") is False  # clearly memcpy-speed: direct reads
    _set_read(gov, "fs", knee * (1.0 - _KNEE_MARGIN / 2))
    assert decide("fs") is False  # inside the band from below: no flip
    _set_read(gov, "fs", knee * (1.0 - 2 * _KNEE_MARGIN))
    assert decide("fs") is True


def test_knee_gate_bands_are_independent_per_gate_and_plugin(
    heuristic_env,
):
    gov = IOGovernor()
    knee = _STREAM_READ_LATENCY_BPS
    _set_read(gov, "fs", 0.5 * knee)
    assert gov.should_coop_restore("fs") is True
    # A different plugin at the same rate decides from scratch — and a
    # different gate on the same plugin keeps its own dead band.
    _set_read(gov, "gcs", 2 * knee)
    assert gov.should_coop_restore("gcs") is False
    _set_read(gov, "fs", knee * (1.0 + _KNEE_MARGIN / 2))
    assert gov.should_coop_restore("fs") is True  # banded (prior decision)
    # seed_restore has no prior decision for fs: first call compares the
    # raw knee, so the same rate decides False.
    assert gov.should_seed_restore("fs") is False


# ------------------------------------------------- election precedence


def _profile_records(settings, plugin="fs", world=1, binding="storage_write"):
    return [
        {
            "type": "profile",
            "plugin": plugin,
            "world_size": world,
            "binding": binding,
            "settings": settings,
            "score_gbps": 1.0,
            "takes": 5,
            "op": "write",
        }
    ]


def test_sub_chunk_env_pin_beats_learned_profile(clean_env):
    gov = IOGovernor()
    gov._tuner.load(_profile_records({"sub_chunk.write": 32 * MB}))
    assert gov.sub_chunk_bytes("fs", op="write") == 32 * MB  # profile
    clean_env.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(12345))
    assert gov.sub_chunk_bytes("fs", op="write") == 12345  # env wins


def test_sub_chunk_learned_profile_beats_heuristic(clean_env):
    gov = IOGovernor()
    _set_write(gov, "fs", 2e9)  # heuristic would size ~100 MB windows
    gov._tuner.load(_profile_records({"sub_chunk.write": 16 * MB}))
    assert gov.sub_chunk_bytes("fs", op="write") == 16 * MB
    # never: the learned profile is ignored, heuristics return.
    clean_env.setenv("TORCHSNAPSHOT_TPU_AUTOTUNE", "never")
    assert gov.sub_chunk_bytes("fs", op="write") == int(2e9 * 0.05) // MB * MB


def test_sub_chunk_learned_value_clamped_into_env_bounds(clean_env):
    gov = IOGovernor()
    gov._tuner.load(_profile_records({"sub_chunk.write": 1 * MB}))
    # Default floor is 8 MB: a profile learned under other bounds clamps.
    assert gov.sub_chunk_bytes("fs", op="write") == 8 * MB
    clean_env.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_MIN_BYTES", str(MB))
    assert gov.sub_chunk_bytes("fs", op="write") == 1 * MB


def test_sub_chunk_heuristic_defaults_without_measurement(heuristic_env):
    gov = IOGovernor()
    assert gov.sub_chunk_bytes("fs", op="write") == _DEFAULT_SUB_CHUNK_BYTES


def test_io_concurrency_precedence_env_profile_heuristic(clean_env):
    gov = IOGovernor()
    gov._tuner.load(_profile_records({"io_concurrency.write": 64}))
    # Learned values respect the designed-for cap...
    assert gov.io_concurrency("write", "fs") == _IO_CONCURRENCY_CAP
    # ...an explicit env pin may exceed it.
    clean_env.setenv("TORCHSNAPSHOT_TPU_IO_CONCURRENCY", "64")
    assert gov.io_concurrency("write", "fs") == 64


def test_io_concurrency_heuristic_rates(heuristic_env):
    gov = IOGovernor()
    default = gov.io_concurrency("write", "fs")
    assert 1 <= default <= 16
    _set_write(gov, "fs", 5e7)  # latency-bound network storage
    assert gov.io_concurrency("write", "fs") == 16
    _set_write(gov, "fs", 5e9)  # bandwidth-bound local storage
    assert gov.io_concurrency("write", "fs") <= default


# --------------------------------------- perturb / score / revert loop


DIMS = {
    "sub_chunk.write": {
        "value": 64 * MB,
        "kind": "geom",
        "lo": 8 * MB,
        "hi": 256 * MB,
        "quantum": MB,
    }
}


def test_tuner_arms_only_against_a_fresh_scored_incumbent():
    tuner = AutoTuner()
    # Cold: no binding verdict yet, nothing to experiment against.
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is None
    r = tuner.observe("write", "fs", "storage_write", 1.0)
    assert r["verdict"] == "scored"
    trial = tuner.maybe_arm("write", "fs", dict(DIMS))
    assert trial is not None and trial["dim"] == "sub_chunk.write"
    assert trial["value"] == 128 * MB  # geometric step, initial climb up
    # Exactly one perturbation process-wide.
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is None


def test_tuner_kept_adopts_and_chains():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    tuner.maybe_arm("write", "fs", dict(DIMS))
    r = tuner.observe("write", "fs", "storage_write", 1.2)  # beats +5% band
    assert r["verdict"] == "kept"
    assert r["settings"]["sub_chunk.write"] == 128 * MB
    assert r["score"] == pytest.approx(1.1)  # alpha-0.5 fold
    # A keep is itself a measurement at the adopted settings: the next
    # trial arms immediately (fast climb out of a bad region).
    trial = tuner.maybe_arm(
        "write", "fs", {"sub_chunk.write": dict(DIMS["sub_chunk.write"], value=128 * MB)}
    )
    assert trial is not None and trial["value"] == 256 * MB


def test_tuner_reverted_keeps_incumbent_flips_direction_and_rebaselines():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    tuner.maybe_arm("write", "fs", dict(DIMS))  # trial 128 MB
    r = tuner.observe("write", "fs", "storage_write", 0.5)  # clearly worse
    assert r["verdict"] == "reverted"
    assert "sub_chunk.write" not in r["settings"]  # incumbent stays
    assert r["score"] == pytest.approx(1.0)  # degraded rate NOT folded in
    # A/B pacing: no new trial until a clean take re-baselines the score.
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is None
    r = tuner.observe("write", "fs", "storage_write", 1.0)
    assert r["verdict"] == "scored"
    trial = tuner.maybe_arm("write", "fs", dict(DIMS))
    assert trial is not None and trial["value"] == 32 * MB  # direction flipped


def test_tuner_neutral_refreshes_score_without_moving_settings():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    tuner.maybe_arm("write", "fs", dict(DIMS))
    r = tuner.observe("write", "fs", "storage_write", 1.02)  # inside ±5%
    assert r["verdict"] == "neutral"
    assert "sub_chunk.write" not in r["settings"]
    assert r["score"] == pytest.approx(1.01)  # rate still folds in


def test_tuner_arm_false_never_unlocks_trials():
    tuner = AutoTuner()
    # A pipeline-bound verdict scores but does not open the experiment:
    # perturbing storage knobs cannot improve an op staging is gating.
    tuner.observe("write", "fs", "stage_copy", 1.0, arm=False)
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is None
    # A storage-bound verdict unlocks it.
    tuner.observe("write", "fs", "stage_copy", 1.0, arm=True)
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is not None


def test_tuner_kept_with_arm_false_does_not_chain():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    tuner.maybe_arm("write", "fs", dict(DIMS))
    r = tuner.observe("write", "fs", "storage_write", 1.5, arm=False)
    assert r["verdict"] == "kept"
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is None


def test_tuner_aborts_when_binding_flips_under_the_experiment():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    tuner.maybe_arm("write", "fs", dict(DIMS))
    # The verdict scores a different profile than the trial perturbed.
    r = tuner.observe("write", "fs", "collective_wait", 5.0)
    assert r["verdict"] == "aborted"
    assert "sub_chunk.write" not in tuner.profiles()[r["key"]]["settings"]
    old = profile_key("fs", 1, "storage_write")
    assert tuner.profiles()[old]["settings"] == {}


def test_tuner_explicit_abort_discards_the_trial():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    assert tuner.maybe_arm("write", "fs", dict(DIMS)) is not None
    assert tuner.abort_trial("write", "fs") is True
    assert tuner.active_trial() is None
    assert tuner.abort_trial("write", "fs") is False


def test_tuner_pin_mode_refreshes_binding_but_never_learns():
    tuner = AutoTuner()
    r = tuner.observe("write", "fs", "storage_write", 1.0, learn=False)
    assert r["verdict"] == "pinned"
    assert tuner.profiles() == {}
    # The binding memory still lets profile keys resolve.
    assert tuner.key_for("fs", "write") == profile_key("fs", 1, "storage_write")


def test_tuner_converges_to_the_optimum_under_noisy_verdicts():
    """Deterministic end-to-end climb: a synthetic landscape peaking at
    64 MB, multiplicative noise inside the hysteresis band. The climber
    must reach the peak and then hold it — reverted/neutral trials only,
    no flip-flop."""
    landscape = {8: 0.25, 16: 0.5, 32: 0.8, 64: 1.0, 128: 0.7, 256: 0.65}
    rng = random.Random(0)
    tuner = AutoTuner()
    key = profile_key("fs", 1, "storage_write")

    def current_setting():
        state = tuner.profiles().get(key, {"settings": {}})
        return state["settings"].get("sub_chunk.write", 8 * MB)

    def measure(nbytes):
        noise = 1.0 + rng.uniform(-0.03, 0.03)
        return landscape[nbytes // MB] * noise

    verdicts = []
    tuner.observe("write", "fs", "storage_write", measure(8 * MB))
    for _ in range(30):
        setting = current_setting()
        dims = {"sub_chunk.write": dict(DIMS["sub_chunk.write"], value=setting)}
        trial = tuner.maybe_arm("write", "fs", dims)
        effective = trial["value"] if trial is not None else setting
        r = tuner.observe("write", "fs", "storage_write", measure(effective))
        verdicts.append(r["verdict"])

    assert current_setting() == 64 * MB
    score = tuner.profiles()[key]["score_gbps"]
    assert score == pytest.approx(1.0, rel=0.1)
    # Converged: the tail probes both directions, rejects both, and the
    # incumbent never moves again.
    tail = verdicts[-8:]
    assert "kept" in verdicts
    assert all(v in ("reverted", "neutral", "scored") for v in tail)


def test_tuner_toggle_dimension_flips_the_engine_choice():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    dims = {"native.write": {"value": True, "kind": "toggle"}}
    trial = tuner.maybe_arm("write", "fs", dims)
    assert trial is not None and trial["value"] is False
    r = tuner.observe("write", "fs", "storage_write", 1.2)
    assert r["verdict"] == "kept"
    assert r["settings"]["native.write"] is False


def test_tuner_round_robin_cycles_dimensions():
    tuner = AutoTuner()
    tuner.observe("write", "fs", "storage_write", 1.0)
    dims = dict(
        DIMS,
        **{"io_concurrency.write": {"value": 8, "kind": "geom", "lo": 1, "hi": 32, "quantum": 1}},
    )
    first = tuner.maybe_arm("write", "fs", dims)
    tuner.observe("write", "fs", "storage_write", 1.0)  # neutral
    tuner.observe("write", "fs", "storage_write", 1.0)  # re-baseline
    second = tuner.maybe_arm("write", "fs", dims)
    assert {first["dim"], second["dim"]} == {
        "sub_chunk.write",
        "io_concurrency.write",
    }


# ------------------------------------------------- profile persistence


def test_profile_record_roundtrip_through_the_history_journal(tmp_path):
    tuner = AutoTuner()
    tuner.note_world(4)
    tuner.observe("write", "fs", "storage_write", 1.0)
    tuner.maybe_arm("write", "fs", dict(DIMS))
    tuner.observe("write", "fs", "storage_write", 1.3)  # kept: 128 MB
    key = profile_key("fs", 4, "storage_write")
    record = tuner.profile_record(key)
    assert record is not None and record["type"] == "profile"
    # No wall_s: the trend/regression reader must never see profiles.
    assert "wall_s" not in record
    record["op"] = "write"
    assert history.append_record(str(tmp_path), record)
    history.append_record(
        str(tmp_path),
        {"ts": 1.0, "op": "take", "snapshot": "s", "wall_s": 2.0},
    )

    assert [r["wall_s"] for r in history.load_history(str(tmp_path))] == [2.0]
    profiles = history.load_profiles(str(tmp_path))
    assert len(profiles) == 1

    warm = AutoTuner()
    warm.note_world(4)
    assert warm.load(profiles) == 1
    # The binding memory was re-seeded: the first op of the new process
    # resolves the learned value before any verdict is observed.
    assert warm.resolve("sub_chunk.write", "fs", "write") == (
        128 * MB,
        "profile",
    )
    assert warm.profiles()[key]["score_gbps"] == pytest.approx(1.15)


def test_profile_load_last_record_per_key_wins():
    tuner = AutoTuner()
    records = _profile_records({"sub_chunk.write": 16 * MB}) + _profile_records(
        {"sub_chunk.write": 32 * MB}
    )
    assert tuner.load(records) == 2
    assert tuner.resolve("sub_chunk.write", "fs", "write") == (32 * MB, "profile")


def test_profile_load_skips_malformed_records():
    tuner = AutoTuner()
    assert (
        tuner.load(
            [
                {"type": "profile", "plugin": "fs"},  # no binding
                {"type": "profile", "binding": "storage_write"},  # no plugin
                {"type": "profile", "plugin": "fs", "binding": None},
                {"type": "take", "wall_s": 1.0},
                "garbage",
            ]
        )
        == 0
    )
    assert tuner.profiles() == {}


def test_governor_warm_start_loads_once_per_root(clean_env, tmp_path):
    source = AutoTuner()
    source.observe("write", "fs", "storage_write", 1.0)
    source.maybe_arm("write", "fs", dict(DIMS))
    source.observe("write", "fs", "storage_write", 1.3)
    record = source.profile_record(profile_key("fs", 1, "storage_write"))
    record["op"] = "write"
    history.append_record(str(tmp_path), record)

    gov = IOGovernor()
    assert gov.load_profiles(str(tmp_path)) == 1
    assert gov.load_profiles(str(tmp_path)) == 0  # once per root
    assert gov.sub_chunk_bytes("fs", op="write") == 128 * MB
    # fresh mode relearns from scratch: stored profiles are ignored.
    clean_env.setenv("TORCHSNAPSHOT_TPU_AUTOTUNE", "fresh")
    fresh = IOGovernor()
    assert fresh.load_profiles(str(tmp_path)) == 0
    assert fresh.sub_chunk_bytes("fs", op="write") == _DEFAULT_SUB_CHUNK_BYTES


# --------------------------------------------- verdict feedback (gov)


@pytest.fixture
def live_telemetry():
    telemetry.set_enabled(True)
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.set_enabled(False)


def test_observe_verdict_skips_unattributed_ops(clean_env, live_telemetry):
    gov = IOGovernor()
    gov.observe_verdict("take", "fs", 1, attribution=None)
    gov.observe_verdict("take", "fs", 1, attribution={"binding": {}})
    gov.observe_verdict(
        "take", "fs", 1, attribution={"binding": {"category": "storage_write"}}
    )  # category but no rate: still no evidence
    assert telemetry.counters().get("profile_skips") == 3
    # Nothing learned: a None binding never became a profile key.
    assert gov.profiles() == {}


def test_observe_verdict_learns_and_persists_on_rank_zero(
    clean_env, live_telemetry, tmp_path
):
    gov = IOGovernor()
    gov.observe_verdict(
        "take",
        "fs",
        2,
        attribution={"binding": {"category": "storage_write", "gbps": 1.0}},
        root=str(tmp_path),
        rank=0,
    )
    key = profile_key("fs", 2, "storage_write")
    assert gov.profiles()[key]["score_gbps"] == pytest.approx(1.0)
    assert len(history.load_profiles(str(tmp_path))) == 1
    # Non-zero ranks learn in memory but never write the journal.
    gov.observe_verdict(
        "take",
        "fs",
        2,
        attribution={"binding": {"category": "storage_write", "gbps": 1.0}},
        root=str(tmp_path),
        rank=1,
    )
    assert len(history.load_profiles(str(tmp_path))) == 1


def test_observe_verdict_scores_by_aggregate_wall_rate(clean_env):
    """The binding window's busy rate is a fused-span residual; the
    score must track the operator's clock (bytes over the op wall)."""
    gov = IOGovernor()
    gov.observe_verdict(
        "take",
        "fs",
        1,
        attribution={"binding": {"category": "storage_write", "gbps": 9.0}},
        aggregate={"write_gbps": 2.0},
    )
    key = profile_key("fs", 1, "storage_write")
    assert gov.profiles()[key]["score_gbps"] == pytest.approx(2.0)


def test_observe_verdict_arms_only_storage_bound_categories(clean_env):
    gov = IOGovernor()
    gov.observe_verdict(
        "take",
        "fs",
        1,
        attribution={"binding": {"category": "stage_copy", "gbps": 1.0}},
    )
    assert gov._tuner._states[profile_key("fs", 1, "stage_copy")].fresh is False
    gov.observe_verdict(
        "take",
        "fs",
        1,
        attribution={"binding": {"category": "storage_write", "gbps": 1.0}},
    )
    assert gov._tuner._states[profile_key("fs", 1, "storage_write")].fresh is True


def test_real_take_learns_a_profile_and_explain_renders_it(
    clean_env, live_telemetry, tmp_path, capsys
):
    """End-to-end: a committed take under ``auto`` persists a profile
    record into the root's history journal, and ``explain --profiles``
    renders the decision trail from it."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.cli import main
    from torchsnapshot_tpu.scheduler import reset_io_governor

    reset_io_governor()
    state = {"model": StateDict(w=np.arange(200_000, dtype=np.float32))}
    Snapshot.take(str(tmp_path / "step_0000000001"), state)
    Snapshot.take(str(tmp_path / "step_0000000002"), state)
    records = history.load_profiles(str(tmp_path))
    assert records, "a committed take under auto must persist a profile"
    assert all(r["type"] == "profile" for r in records)
    assert all(r["binding"] for r in records)
    # The trend reader must not see them.
    assert all("wall_s" in r for r in history.load_history(str(tmp_path)))

    assert main(["explain", "--profiles", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "learned profiles" in out
    key = records[-1]
    assert f"{key['plugin']}|w{key['world_size']}|{key['binding']}" in out
    reset_io_governor()


def test_explain_profiles_errors_cleanly_without_a_journal(
    clean_env, tmp_path, capsys
):
    from torchsnapshot_tpu.cli import main

    assert main(["explain", "--profiles", str(tmp_path / "nowhere")]) == 2
    assert "no learned profiles" in capsys.readouterr().err


def test_observe_verdict_never_mode_is_one_env_check(clean_env):
    clean_env.setenv("TORCHSNAPSHOT_TPU_AUTOTUNE", "never")
    gov = IOGovernor()
    gov.observe_verdict(
        "take",
        "fs",
        1,
        attribution={"binding": {"category": "storage_write", "gbps": 1.0}},
    )
    assert gov.profiles() == {}
